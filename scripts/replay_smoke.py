"""Fast catch-up smoke for CI tier-1 (crypto-free, ~8 toy blocks).

Drives the ISSUE 18 catch-up path end to end in seconds with no
``cryptography`` and no device: a toy JSON validator (the
tests/test_resident.py wire form) through the REAL ``ReplayDriver`` /
``CommitPipeline`` / ``KVLedger`` / snapshot stack —

1. stage a dependent 8-block chain into a source ledger via the
   replay driver (checkpoint journal armed);
2. export a Fabric-shaped snapshot at the mid-chain boundary, then
   RESUME the driver for the tail (exercising the skip-below-height
   path a restarted replay takes);
3. bootstrap a joining ledger from the snapshot and replay the
   suffix with ``replay_into`` (``resumed_from`` must equal the
   snapshot height);
4. replay a second ledger from genesis as the oracle, and pin the
   byte-identity triangle: source ≡ full-replay ≡ snapshot-join on
   state digest, commit hash and height.

Exit 0 with a JSON summary on success; any divergence raises.

Usage: python scripts/replay_smoke.py
"""

import json
import os
import shutil
import sys
import tempfile
from dataclasses import dataclass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger import snapshot as snaplib
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer.replay import ReplayDriver, replay_into

N_BLOCKS = 8
N_TX = 6
SNAP_AT = 4  # snapshot boundary: blocks [0, 4) in, [4, 8) replayed


@dataclass
class _Ptx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class _Pend:
    block: object
    txs: list
    raw: list
    overlay: object
    extra: object
    hd_bytes: bytes | None = None  # the ledger takes None: re-serialize

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ToyValidator:
    """Crypto-free pipeline validator (the test_resident.py host-oracle
    shape): JSON txs {"id", "reads": {k: [b, t] | None}, "writes":
    {k: v}, "deletes": [k]}, MVCC against the ledger state with the
    in-flight overlay honored — the chain below has cross-block reads
    inside the depth window, so replay correctness depends on it."""

    VALID, DUP, MVCC = 0, 2, 11

    def __init__(self, state):
        self.state = state

    def preprocess(self, block):
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        txs = [_Ptx(t["id"], i) for i, t in enumerate(raw)]
        return _Pend(block, txs, raw, overlay, extra_txids)

    def _version(self, pr, over):
        if pr in over:
            return over[pr]
        vv = self.state.get_state(*pr)
        return None if vv is None else tuple(vv.version)

    def validate_finish(self, pend):
        over = {}
        if pend.overlay is not None:
            for pr, vv in pend.overlay.updates.items():
                over[pr] = None if vv.value is None else tuple(vv.version)
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for ptx, t in zip(pend.txs, pend.raw):
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            ok = all(
                self._version(("cc", k), over)
                == (None if want is None else tuple(want))
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put("cc", k, val.encode(), (num, ptx.idx))
            for k in t.get("deletes", ()):
                batch.delete("cc", k, (num, ptx.idx))
        return bytes(codes), batch, []


def build_chain(n_blocks=N_BLOCKS, n_tx=N_TX):
    """Dependent stream: a hot key every block re-reads, k→k+1 fresh
    reads that cross the pipeline window, one stale lane per block
    (→ MVCC reject, so tx_filters are non-trivial) and deletes."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"t{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if i == 0:
                t["reads"] = {"hot": [0, 0] if n else None}
                if n == 0:
                    t["writes"]["hot"] = "h"
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [n - 1, 1]}
            if n > 1 and i == 4:
                t["reads"] = {f"k{n-2}_4": [0, 0]}  # stale → MVCC
            if n > 0 and i == 5:
                t["deletes"] = [f"k{n-1}_5"]
                t["reads"] = {f"k{n-1}_5": [n - 1, 5]}
            txs.append(t)
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def drive(ledger, blocks, ckpt, start=None):
    """One ReplayDriver pass feeding ``ledger`` from an in-memory
    iterator (the driver takes any decoded-Block iterable)."""
    v = ToyValidator(ledger.state)

    def commit_fn(res):
        ledger.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)

    drv = ReplayDriver(v, commit_fn, depth=2, checkpoint=ckpt,
                       checkpoint_every=2)
    return drv.run(iter(blocks), start=start)


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="replaysmoke")
    try:
        blocks = build_chain()

        # 1. stage the source in two driver passes around the snapshot
        src = KVLedger(os.path.join(tmp, "src"), state_db=MemVersionedDB())
        ckpt = os.path.join(tmp, "src_ckpt.json")
        s1 = drive(src, blocks[:SNAP_AT], ckpt)
        assert src.height == SNAP_AT and s1["blocks"] == SNAP_AT, s1

        snap_dir = os.path.join(tmp, "snap")
        meta = snaplib.generate_snapshot(src, snap_dir, channel_id="smoke")
        assert meta["height"] == SNAP_AT, meta

        # 2. resume: hand the driver the FULL chain + committed height —
        # the below-start skip must land exactly on block SNAP_AT
        s2 = drive(src, blocks, ckpt, start=src.height)
        assert src.height == N_BLOCKS and s2["blocks"] == N_BLOCKS - SNAP_AT, s2
        with open(ckpt) as f:
            assert json.load(f)["height"] == N_BLOCKS

        # 3. snapshot join: import + replay the suffix off the source store
        join, jmeta = snaplib.create_from_snapshot(
            os.path.join(tmp, "snap"), os.path.join(tmp, "join"),
            state_db=MemVersionedDB(),
        )
        assert jmeta["height"] == SNAP_AT
        js = replay_into(join, ToyValidator(join.state), src.blocks,
                         depth=2,
                         checkpoint=os.path.join(tmp, "join_ckpt.json"))
        assert js["resumed_from"] == SNAP_AT, js
        assert js["blocks"] == N_BLOCKS - SNAP_AT, js

        # 4. oracle: full replay from genesis, then the identity triangle
        full = KVLedger(os.path.join(tmp, "full"), state_db=MemVersionedDB())
        fs = replay_into(full, ToyValidator(full.state), src.blocks, depth=2)
        assert fs["resumed_from"] == 0 and fs["blocks"] == N_BLOCKS, fs

        digests = {name: lg.state_digest()
                   for name, lg in (("src", src), ("join", join),
                                    ("full", full))}
        assert len(set(digests.values())) == 1, f"state diverged: {digests}"
        hashes = {n: lg.commit_hash.hex()
                  for n, lg in (("src", src), ("join", join), ("full", full))}
        assert len(set(hashes.values())) == 1, f"commit chain diverged: {hashes}"
        assert src.height == join.height == full.height == N_BLOCKS

        print(json.dumps({
            "ok": True,
            "height": src.height,
            "state_digest": digests["src"][:16],
            "commit_hash": hashes["src"][:16],
            "stage": {"blocks_per_s": s1["blocks_per_s"]},
            "resume": {"resumed": s2["blocks"]},
            "snapshot_join": {"replayed": js["blocks"],
                              "resumed_from": js["resumed_from"]},
        }))
        for lg in (src, join, full):
            lg.close()
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())

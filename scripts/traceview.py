#!/usr/bin/env python3
"""Text waterfall for block-commit traces — Perfetto for containers
with no browser.

Input (auto-detected):
  * Chrome trace-event JSON written by ``Tracer.export_chrome`` /
    ``FABTPU_BENCH_TRACE=trace.json`` ({"traceEvents": [...]}), or
  * a ``/trace`` endpoint dump (``curl :PORT/trace > dump.json`` —
    either the index payload or a single ``?block=N`` tree).

Usage:
  python scripts/traceview.py trace.json [--block N] [--width 48]

Per block, prints one line per span: an ASCII bar positioned on the
block's [0, total] time axis, the span name (indented by tree depth
where the dump carries the tree), start/duration in ms, and the
thread/worker that ran it — the overlap question ("did prefetch(k+1)
run while commit(k) fsynced?") is answered by bars on different
thread rows sharing a time range across consecutive blocks.

The launch ledger's device-lane child spans (observe/ledger.py) ride
``device:<lane>`` rows with distinct bar glyphs — ``%`` for
``dev:compile``, ``~`` for ``dev:queue``, ``=`` for ``dev:execute`` —
so a cold-compile stall is visually distinct from kernel execute.

Merged MULTI-PROCESS dumps (a peer tree with the sidecar's stitched
request subtree, or a Chrome export with several process_name rows)
render with per-process labels — ``[sidecar:fabtpu-sidecar-dev_0]``
vs ``[MainThread]`` — and a ``~ clock offset`` annotation under each
stitched subtree stating the estimated remote-clock offset and the
round-trip bound on its error, so a browserless host can read the
cross-process waterfall AND how far to trust its alignment.
"""

from __future__ import annotations

import argparse
import json
import sys


#: bar glyphs for the launch ledger's device-lane spans: a compile
#: stall must read differently from queue wait and execute at a glance
_DEV_BARS = {"dev:compile": "%", "dev:queue": "~", "dev:execute": "="}


def _bar(start: float, dur: float, total: float, width: int,
         char: str = "#") -> str:
    """[start, start+dur) rendered on a width-char axis of [0, total)."""
    if total <= 0:
        return " " * width
    lo = int(start / total * width)
    hi = int((start + dur) / total * width)
    lo = max(0, min(lo, width - 1))
    hi = max(lo + 1, min(hi, width))
    return " " * lo + char * (hi - lo) + " " * (width - hi)


def _line(depth: int, name: str, start: float, dur: float, total: float,
          thread: str, width: int) -> str:
    label = "  " * depth + name
    return "  %s %-28s %8.2f +%8.2f ms  [%s]" % (
        _bar(start, dur, total, width, _DEV_BARS.get(name, "#")),
        label[:28], start, dur, thread,
    )


# -- /trace dump form (span trees) ------------------------------------------


def render_tree(block: dict, width: int = 48) -> str:
    """One /trace block tree → waterfall text."""
    total = float(block.get("dur_ms", 0.0))
    attrs = block.get("attrs", {})
    extra = "".join(
        f" {k}={v}" for k, v in sorted(attrs.items()) if k != "block"
    )
    out = ["block %s  total %.2f ms%s" % (block.get("block"), total, extra)]

    def walk(span: dict, depth: int) -> None:
        row = span.get("thread", "?")
        if span.get("proc"):
            row = f"{span['proc']}:{row}"
        out.append(_line(depth, span.get("name", "?"),
                         float(span.get("start_ms", 0.0)),
                         float(span.get("dur_ms", 0.0)),
                         total, row, width))
        off = (span.get("attrs") or {}).get("clock_offset_ms")
        if off is not None:
            out.append("  %s ~ clock offset %.3f ms (rtt %.3f ms)" % (
                " " * width, float(off),
                float((span.get("attrs") or {}).get("rtt_ms", 0.0)),
            ))
        for ev in span.get("events", ()):
            out.append("  %s ! %s" % (
                " " * width,
                ev.get("name", "?") + " @ %.2f ms" % ev.get("at_ms", 0.0),
            ))
        for c in span.get("children", ()):
            walk(c, depth + 1)

    walk(block, 0)
    return "\n".join(out)


def render_trace_dump(data: dict, width: int = 48,
                      block: int | None = None) -> str:
    if "name" in data and "block" in data:  # a single ?block=N tree
        return render_tree(data, width)
    trees = {b.get("block"): b for b in data.get("recent_blocks", ())}
    for b in data.get("slow_blocks", ()):
        trees.setdefault(b.get("block"), b)
    if block is not None:
        if block not in trees:
            return (f"block {block} not in dump (have: "
                    f"{sorted(k for k in trees if k is not None)})")
        return render_tree(trees[block], width)
    return "\n\n".join(
        render_tree(trees[k], width) for k in sorted(trees)
    ) or "no block trees in dump"


# -- Chrome trace-event form ------------------------------------------------


def render_chrome(data: dict, width: int = 48,
                  block: int | None = None) -> str:
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    # thread rows are keyed (pid, tid) — tids repeat across processes
    # in a multi-process export; process_name rows label the pids
    procs = {
        e.get("pid", 0): e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    threads = {
        (e.get("pid", 0), e["tid"]): e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    by_block: dict[int, list] = {}
    for e in events:
        if e.get("ph") not in ("X", "i"):
            continue
        b = e.get("args", {}).get("block")
        if b is None:
            continue
        by_block.setdefault(int(b), []).append(e)
    if block is not None:
        by_block = {block: by_block.get(block, [])}
    out = []
    for b in sorted(by_block):
        evs = sorted(by_block[b], key=lambda e: e["ts"])
        roots = [e for e in evs if e.get("name") == "block"]
        if not roots:
            continue
        base, total = roots[0]["ts"], roots[0].get("dur", 0.0) / 1000.0
        lines = ["block %d  total %.2f ms" % (b, total)]
        for e in evs:
            pid = e.get("pid", 0)
            thread = threads.get((pid, e.get("tid")),
                                 str(e.get("tid")))
            proc = procs.get(pid, "")
            if proc and proc != "local":
                thread = f"{proc}:{thread}"
            start = (e["ts"] - base) / 1000.0
            if e["ph"] == "i":
                lines.append("  %s ! %s @ %.2f ms" % (
                    " " * width, e.get("name", "?"), start,
                ))
                continue
            lines.append(_line(0, e.get("name", "?"), start,
                               e.get("dur", 0.0) / 1000.0, total, thread,
                               width))
            off = e.get("args", {}).get("clock_offset_ms")
            if off is not None:
                lines.append(
                    "  %s ~ clock offset %.3f ms (rtt %.3f ms)" % (
                        " " * width, float(off),
                        float(e.get("args", {}).get("rtt_ms", 0.0)),
                    )
                )
        out.append("\n".join(lines))
    return "\n\n".join(out) or "no block events in trace"


def render(data, width: int = 48, block: int | None = None) -> str:
    if isinstance(data, dict) and "traceEvents" in data:
        return render_chrome(data, width, block)
    if isinstance(data, list):
        return render_chrome({"traceEvents": data}, width, block)
    return render_trace_dump(data, width, block)


# -- pipeline overlap coverage ----------------------------------------------


def render_coverage(data, window: int = 2) -> str:
    """Per-block device_wait coverage by neighbor-block host stages
    (observe/overlap.py) — the deep-pipelining acceptance number as a
    text table, from either input form."""
    import os
    import sys as _sys

    _sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from fabric_tpu.observe import overlap

    if isinstance(data, dict) and "traceEvents" in data:
        cov = overlap.coverage_from_spans(
            overlap.spans_from_chrome(data["traceEvents"]), window=window
        )
    elif isinstance(data, list):
        cov = overlap.coverage_from_spans(
            overlap.spans_from_chrome(data), window=window
        )
    else:
        cov = overlap.coverage_from_trace_dump(data, window=window)
        if cov is None:
            return ("no t0_s anchors in dump — re-capture from a "
                    "/trace endpoint that emits them")
    lines = [
        "pipeline overlap coverage (window ±%d): mean %s  p50 %s  "
        "min %s over %d block(s)" % (
            cov["window"], cov["mean"], cov["p50"], cov["min"],
            cov["blocks_measured"],
        )
    ]
    for b in cov["per_block"]:
        lines.append(
            "  block %-6s device_wait %8.2f ms  covered %8.2f ms  "
            "(%.1f%%)" % (
                b["block"], b["device_wait_ms"], b["covered_ms"],
                b["coverage"] * 100.0,
            )
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="chrome trace JSON or /trace dump")
    ap.add_argument("--block", type=int, default=None,
                    help="render one block only")
    ap.add_argument("--width", type=int, default=48,
                    help="waterfall bar width (chars)")
    ap.add_argument("--coverage", action="store_true",
                    help="print the pipeline overlap-coverage table "
                         "(device_wait hidden by neighbor host stages) "
                         "instead of the waterfall")
    ap.add_argument("--window", type=int, default=2,
                    help="coverage neighbor window in blocks "
                         "(depth−1; default 2 = depth-3)")
    args = ap.parse_args(argv)
    with open(args.path) as f:
        data = json.load(f)
    if args.coverage:
        print(render_coverage(data, window=args.window))
    else:
        print(render(data, width=args.width, block=args.block))
    return 0


if __name__ == "__main__":
    sys.exit(main())

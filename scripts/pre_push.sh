#!/usr/bin/env bash
# Pre-push lint gate: analyze only the files this push changes, emit
# SARIF for code-scanning upload, fail the push on any finding.
#
# Install as a git hook (runs on every `git push` from then on):
#
#   ln -sf ../../scripts/pre_push.sh .git/hooks/pre-push
#
# Or run it directly before pushing.  The diff base is the upstream
# of the current branch when one exists, else HEAD (covers the
# uncommitted + unpushed work either way); project-wide rules
# (FT017/FT018 provenance closure) still scan the full tree, so a
# changed module that breaks an UNCHANGED one is caught.
#
# SARIF lands in .git/pre-push.sarif (ignored by git); the human
# findings print on stderr via a second, cheap, cache-warm pass only
# when the SARIF pass fails.
set -u

cd "$(dirname "$0")/.."

base="HEAD"
if git rev-parse --abbrev-ref --symbolic-full-name '@{upstream}' \
        >/dev/null 2>&1; then
    base="@{upstream}"
fi

out=".git/pre-push.sarif"
if python scripts/lint.py --changed "$base" --sarif > "$out"; then
    exit 0
fi
echo "pre-push lint found problems (SARIF: $out):" >&2
python scripts/lint.py --changed "$base" >&2
exit 1

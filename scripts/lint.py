#!/usr/bin/env python
"""Repo lint entrypoint: runs the fabric_tpu static-analysis battery.

Equivalent to ``python -m fabric_tpu.analysis fabric_tpu/`` — kept as
a script so CI configs and operators have a stable path that survives
package renames.  Extra arguments pass through (``--json``,
``--rule FT004``, paths...).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

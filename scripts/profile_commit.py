"""Phase breakdown of the north-star block-commit path (dev tool)."""
import sys, time
import numpy as np

sys.path.insert(0, ".")
import bench


def main(n_tx=1000):
    blocks, fresh_state, fresh_validator, mgr, prov, CC, _ninv = bench._build_commit_network(n_tx)
    blk = blocks[0]
    state = fresh_state()
    v = fresh_validator(state)
    v.warmup()

    # piecewise timings of validator.validate
    from fabric_tpu.ops import p256
    for rep in range(3):
        t0 = time.perf_counter()
        txs, items, _rwp = v._parse(blk)
        t1 = time.perf_counter()
        sig_valid = np.asarray(p256.verify_host(items), bool)
        t2 = time.perf_counter()
        flt, batch, hist = v.validate(blk)
        t3 = time.perf_counter()
        print(f"rep{rep}: parse={t1-t0:.3f}s verify={t2-t1:.3f}s full_validate={t3-t2:.3f}s n_items={len(items)}")

    import cProfile, pstats
    pr = cProfile.Profile()
    pr.enable()
    v.validate(blk)
    pr.disable()
    st = pstats.Stats(pr)
    st.sort_stats("cumulative").print_stats(25)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1000)

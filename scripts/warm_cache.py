"""Persistent-compile-cache warm-up driven by the launch ledger.

A restarted peer (or a fresh snapshot-join peer about to replay its
chain suffix) pays a cold XLA compile for every kernel shape its
traffic touches — the launch ledger (observe/ledger.py) records those
as ``cache: "miss"`` rows with multi-second ``compile_ms``.  This tool
closes the loop: feed it a ledger report (the ``/launches`` operations
endpoint, or a ``BENCH_*.json`` line's ``extras.device_ledger``), and
it re-dispatches every compile-missed verify/sign shape with dummy
lanes AFTER arming the repo's persistent compile cache
(utils/xla_env.enable_compile_cache → ``.jax_cache``), so the next
process to hit those shapes loads the compiled program from disk
instead of tracing it on the serving path.

Only the standalone crypto kernels are reconstructable from a
(kernel, lanes) row alone:

* ``verify`` (ops/p256v3): one genuinely valid (e, r, s, qx, qy)
  tuple — produced by the host signer, no ``cryptography`` needed —
  replicated ``lanes`` times; the bucket/chunk padding reproduces the
  recorded structural shape.
* ``sign`` (ops/p256sign): the fixed-base comb ladder over ``lanes``
  dummy digests.

``stage2`` and ``resident_scatter`` rows are skipped with a note:
their shapes embed live validator state (read-set layout, resident
table geometry) that a report row does not carry — the first real
block recompiles those, and the verify/sign warms already cover the
dominant compile cost (see the ledger's per-kernel compile_ms).

The chunk / recode / mesh knobs are NOT in ledger rows either; pass
the serving configuration via flags (mirroring FABTPU_BENCH_RECODE /
FABTPU_BENCH_VERIFY_CHUNK) so the warmed structural keys match.

Usage:
    python scripts/warm_cache.py LAUNCHES.json [--chunk N] [--recode]
    python scripts/warm_cache.py BENCH_r06_block_commit.json
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: kernels whose structural shape a report row fully determines
RECONSTRUCTABLE = ("verify", "sign")


def load_report(path: str) -> dict:
    """Accept a raw ledger report, a ``/launches`` body, or a full
    bench JSON line (``extras.device_ledger``)."""
    with open(path) as f:
        doc = json.load(f)
    if "extras" in doc and isinstance(doc["extras"], dict):
        led = doc["extras"].get("device_ledger")
        if led is None:
            raise SystemExit(f"{path}: no extras.device_ledger section")
        return led
    return doc


def miss_shapes(report: dict) -> tuple[dict, list]:
    """(kernel → sorted lane counts that compile-missed, skipped
    kernel notes).  Reads the raw ``recent`` rows — the per-kernel
    stats aggregate away the lane counts the re-dispatch needs."""
    shapes: dict[str, set] = {}
    for row in report.get("recent", ()):
        if row.get("cache") != "miss":
            continue
        shapes.setdefault(row["kernel"], set()).add(int(row["lanes"]))
    skipped = [
        {"kernel": k, "lanes": sorted(v),
         "note": "shape depends on live validator state; first real "
                 "block recompiles it"}
        for k, v in shapes.items() if k not in RECONSTRUCTABLE
    ]
    # aggregated fallback: a kernel with recorded misses whose raw
    # rows already rotated out of the ring — report it rather than
    # silently claiming full coverage
    for k, st in report.get("kernels", {}).items():
        if st.get("cache_misses") and k not in shapes:
            skipped.append({"kernel": k, "lanes": [],
                            "note": "misses recorded but raw rows "
                                    "rotated out of the ring; rerun "
                                    "with a larger rows= report"})
    return {k: sorted(v) for k, v in shapes.items()
            if k in RECONSTRUCTABLE}, skipped


def warm_verify(lanes: int, chunk: int, recode: bool) -> None:
    from fabric_tpu.ops import p256sign, p256v3

    key = 0xC0FFEE + 1  # any scalar in [1, n-1]
    e = 0x5EED
    r, s = p256sign.sign_host([e], key)[0]
    qx, qy = p256sign._pub_of(key)
    items = [(e, r, s, qx, qy)] * lanes
    ok = p256v3.verify_launch(items, chunk=chunk or None,
                              recode_device=recode)()
    assert all(ok), "warm-up verify rejected a valid signature"


def warm_sign(lanes: int, chunk: int) -> None:
    from fabric_tpu.ops import p256sign

    sigs = p256sign.sign_launch([0x5EED] * lanes, 0xC0FFEE + 1,
                                chunk=chunk or None).fetch()
    assert len(sigs) == lanes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("report", help="/launches JSON, ledger report, or "
                                   "bench JSON with extras.device_ledger")
    ap.add_argument("--chunk", type=int, default=0,
                    help="verify microbatch size of the serving config "
                         "(FABTPU_BENCH_VERIFY_CHUNK); 0 = monolithic")
    ap.add_argument("--sign-chunk", type=int, default=0,
                    help="sign microbatch size; 0 = monolithic")
    ap.add_argument("--recode", action="store_true",
                    help="serving config ships limbs + recodes windows "
                         "on device (FABTPU_BENCH_RECODE=1)")
    args = ap.parse_args(argv)

    # arm the persistent cache BEFORE any kernel builds — this is the
    # entire point: the warm dispatches below populate .jax_cache
    from fabric_tpu.utils.xla_env import enable_compile_cache

    armed = enable_compile_cache()
    shapes, skipped = miss_shapes(load_report(args.report))

    warmed, failed = [], []
    for kernel, lane_counts in sorted(shapes.items()):
        for lanes in lane_counts:
            try:
                if kernel == "verify":
                    warm_verify(lanes, args.chunk, args.recode)
                else:
                    warm_sign(lanes, args.sign_chunk)
                warmed.append({"kernel": kernel, "lanes": lanes})
            except Exception as e:  # keep warming the rest
                failed.append({"kernel": kernel, "lanes": lanes,
                               "error": str(e)})
    print(json.dumps({
        "cache_armed": armed,
        "warmed": warmed,
        "skipped": skipped,
        "failed": failed,
    }, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

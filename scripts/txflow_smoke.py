"""Transaction-flow journal smoke for CI tier-1 (crypto-free, seconds).

Re-asserts the journal's acceptance geometry against the REAL commit
stack — not unit mocks — with no ``cryptography`` and no device:

1. arm the module-global journal (a private registry) and stamp
   gateway-shaped ``endorse/submit/broadcast`` milestones for every tx
   of a toy dependent chain;
2. push the chain through the REAL ``CommitPipeline`` (inclusion +
   verdict stamped in ``_run_commit``) into the REAL serial
   ``KVLedger`` (``applied`` stamped after state apply), with a stale
   read lane so verdicts are non-trivial;
3. pin the invariants on every completed flow: all milestones present
   and monotonic, stages telescope (sum(stages) == e2e to rounding),
   outcomes split VALID / MVCC, and the ``/txflow``-shaped
   ``report()`` carries per-stage percentiles;
4. disarm and prove the hooks go back to structural no-ops.

Exit 0 with a JSON summary on success; any violated invariant raises.

Usage: python scripts/txflow_smoke.py
"""

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.observe import txflow
from fabric_tpu.ops_metrics import Registry
from fabric_tpu.peer.pipeline import CommitPipeline

N_BLOCKS = 6
N_TX = 8

MILESTONE_ORDER = ["endorse_begin", "endorse_end", "submit",
                   "broadcast", "included", "applied"]


# -- toy validator (the replay_smoke.py wire form, reads-only lane) ---------


class _Ptx:
    def __init__(self, txid, idx):
        self.txid, self.idx, self.is_config = txid, idx, False


class _Pend:
    def __init__(self, block, txs, raw, overlay, extra):
        self.block, self.txs, self.raw = block, txs, raw
        self.overlay, self.extra, self.hd_bytes = overlay, extra, None

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ToyValidator:
    VALID, MVCC = 0, 11

    def __init__(self, state):
        self.state = state

    def preprocess(self, block):
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        txs = [_Ptx(t["id"], i) for i, t in enumerate(raw)]
        return _Pend(block, txs, raw, overlay, extra_txids)

    def _version(self, pr, over):
        if pr in over:
            return over[pr]
        vv = self.state.get_state(*pr)
        return None if vv is None else tuple(vv.version)

    def validate_finish(self, pend):
        over = {}
        if pend.overlay is not None:
            for pr, vv in pend.overlay.updates.items():
                over[pr] = None if vv.value is None else tuple(vv.version)
        codes, batch = [], UpdateBatch()
        num = pend.block.header.number
        for ptx, t in zip(pend.txs, pend.raw):
            ok = all(
                self._version(("cc", k), over)
                == (None if want is None else tuple(want))
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put("cc", k, val.encode(), (num, ptx.idx))
        return bytes(codes), batch, []


def build_chain(n_blocks=N_BLOCKS, n_tx=N_TX):
    """Dependent stream with one stale lane per block (→ MVCC) so the
    journal's verdict attribution is exercised, not just VALID."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"t{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [n - 1, 1]}
            if n > 1 and i == 4:
                t["reads"] = {f"k{n-2}_4": [0, 0]}  # stale → MVCC
            txs.append(t)
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="txflowsmoke")
    try:
        txflow.configure(registry=Registry())
        blocks = build_chain()
        txids = [json.loads(bytes(d))["id"]
                 for b in blocks for d in b.data.data]

        # 1. gateway-shaped stamps for every tx
        for tx in txids:
            txflow.endorse_begin(tx)
            txflow.endorse_end(tx)
            txflow.submit_begin(tx)
            txflow.broadcast_done(tx)

        # 2. the real pipeline + serial ledger commit
        state = MemVersionedDB()
        lg = KVLedger(os.path.join(tmp, "ledger"), state_db=state)

        def commit_fn(res):
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids)

        v = ToyValidator(state)
        with CommitPipeline(v, commit_fn, depth=2,
                            channel="smoke") as pipe:
            for b in blocks:
                pipe.submit(b)
            pipe.flush()
        lg.close()

        # 3. the invariants
        j = txflow.global_journal()
        rows = j.rows(len(txids))
        assert len(rows) == len(txids), (len(rows), len(txids))
        outcomes = {}
        for r in rows:
            ms = r["milestones"]
            present = [m for m in MILESTONE_ORDER if m in ms]
            assert present == MILESTONE_ORDER, (r["tx_id"], ms)
            assert all(ms[a] <= ms[b] for a, b in
                       zip(present, present[1:])), ms
            drift = abs(sum(r["stages_ms"].values()) - r["e2e_ms"])
            assert drift < 1e-3, (r["tx_id"], drift)  # rounding only
            outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
        assert outcomes.get("MVCC_READ_CONFLICT", outcomes.get(
            "code11", 0)) == N_BLOCKS - 2, outcomes
        rep = j.report(rows=4)
        assert rep["flows_completed"] == len(txids), rep
        for stage in ("endorse", "submit", "order", "apply"):
            assert rep["stages_ms"][stage]["n"] == len(txids), stage

        # 4. disarm: hooks back to None-check no-ops
        txflow.configure(enabled=False)
        assert not txflow.enabled()
        txflow.block_included(99, [("ghost", 0)])
        txflow.block_applied(99)

        print(json.dumps({
            "ok": True,
            "flows": len(rows),
            "outcomes": outcomes,
            "e2e_p99_ms": rep["e2e_ms"].get("VALID", {}).get("p99"),
            "stages": {s: rep["stages_ms"][s]["p50"]
                       for s in ("endorse", "submit", "order", "apply")},
        }))
        return 0
    finally:
        txflow.configure(enabled=False)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Text postmortem for black-box incident bundles — read a crash in a
terminal, no browser, no dashboard.

Input: one bundle JSON written by the black-box recorder
(``blackbox-<seq>-<kind>.json`` from the ``blackbox_dir`` knob, or
``curl :PORT/vitals?incident=K > bundle.json``).

Output, in postmortem reading order:

* the incident header (kind, detail, clocks),
* the trailing metric trails as ASCII sparklines (counters as
  per-interval deltas, gauges as levels, histograms as interval
  p99s) so the minutes BEFORE the incident are visible,
* the autopilot decision log as a timeline relative to the incident,
* the SLO burn snapshot and scheduler per-tenant rows,
* the device ledger (per-kernel compile/queue/execute decomposition,
  cache hit rates, HBM watermarks, the last raw launch rows),
* the commit-engine postmortem (apply-queue depth trail, last applied
  vs appended block height per async-commit channel),
* the fault-injection stats (what the chaos plan actually did), and
* the captured trace trees, rendered through scripts/traceview.py's
  waterfall.

Usage:
  python scripts/blackbox_view.py bundle.json [--series N] [--no-traces]
"""

from __future__ import annotations

import argparse
import json
import sys

_SPARK = "▁▂▃▄▅▆▇█"


def spark(values: list) -> str:
    """Numbers → a sparkline string (empty-safe; None points gap)."""
    nums = [v for v in values if isinstance(v, (int, float))]
    if not nums:
        return ""
    lo, hi = min(nums), max(nums)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if not isinstance(v, (int, float)):
            out.append(" ")
            continue
        i = int((v - lo) / span * (len(_SPARK) - 1))
        out.append(_SPARK[i])
    return "".join(out)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_header(b: dict) -> list[str]:
    lines = [
        "=" * 72,
        "BLACK-BOX BUNDLE #%s — incident: %s" % (
            b.get("seq", "?"), b.get("kind", "?")),
        "=" * 72,
        "  t_s (monotonic): %s   wall_s: %s" % (
            b.get("t_s", "?"), b.get("wall_s", "?")),
    ]
    for k, v in sorted((b.get("detail") or {}).items()):
        lines.append(f"  {k}: {_fmt(v)}")
    if b.get("truncated"):
        lines.append(
            "  ! truncated sections (size bound): "
            + ", ".join(b["truncated"])
        )
    return lines


def render_vitals(vitals: dict, limit: int | None = None) -> list[str]:
    lines = ["", "-- metric trails (newest right) " + "-" * 38]
    shown = 0
    for metric in sorted(vitals):
        for labels, series in sorted(vitals[metric].items()):
            pts = series.get("points", [])
            kind = series.get("kind", "?")
            if kind == "histogram":
                vals = [
                    (p[1] or {}).get("p99") for p in pts
                ]
                unit = "interval p99"
            else:
                vals = [p[1] for p in pts]
                unit = "delta/interval" if kind == "counter" else "level"
            nums = [v for v in vals if isinstance(v, (int, float))]
            if not nums:
                continue
            shown += 1
            if limit is not None and shown > limit:
                lines.append("  ... (more series; --series 0 for all)")
                return lines
            lines.append(
                "  %-44s %s" % (
                    f"{metric}{{{labels}}}"[:44], spark(vals[-48:]))
            )
            lines.append(
                "  %-44s last %s  min %s  max %s  (%s, %d pts)" % (
                    "", _fmt(nums[-1]), _fmt(min(nums)),
                    _fmt(max(nums)), unit, len(pts),
                )
            )
    if shown == 0:
        lines.append("  (no series — sampler was not armed)")
    return lines


def render_autopilot(ap: dict, t_incident: float | None) -> list[str]:
    lines = ["", "-- autopilot " + "-" * 57]
    knobs = ap.get("knobs", {})
    if knobs:
        lines.append("  knob vector: " + "  ".join(
            f"{k}={v.get('value')}" for k, v in sorted(knobs.items())
        ))
    tenants = ap.get("tenants", {})
    if tenants.get("shed"):
        lines.append("  SHED tenants: " + ", ".join(tenants["shed"]))
    if tenants.get("weights"):
        lines.append("  weights: " + "  ".join(
            f"{t}={w}" for t, w in sorted(tenants["weights"].items())
        ))
    decisions = ap.get("decisions", [])
    if decisions:
        lines.append("  decision log (dt = seconds before incident):")
        for d in decisions:
            dt = ""
            if t_incident is not None and isinstance(
                    d.get("t_s"), (int, float)):
                dt = "%+8.1fs " % (d["t_s"] - t_incident)
            tenant = f" tenant={d['tenant']}" if d.get("tenant") else ""
            lines.append(
                "    %s%-18s %-4s %s -> %s  (%s=%s > %s)%s" % (
                    dt, d.get("knob"), d.get("direction"),
                    d.get("from"), d.get("to"), d.get("signal"),
                    _fmt(d.get("value")), _fmt(d.get("threshold")),
                    tenant,
                )
            )
    return lines


def render_slo(slo: dict) -> list[str]:
    lines = ["", "-- slo burn snapshot " + "-" * 49]
    for o in slo.get("objectives", []):
        for channel, row in sorted(o.get("channels", {}).items()):
            lines.append(
                "  %-20s %-18s %-10s burns %s  (%d events, %d bad)" % (
                    o.get("name"), channel or "-",
                    row.get("status", "?"),
                    " ".join(
                        f"{w}={_fmt(v) if v is not None else '-'}"
                        for w, v in sorted(
                            (row.get("burn") or {}).items())
                    ),
                    row.get("events", 0), row.get("bad", 0),
                )
            )
    return lines


def render_scheduler(sched: dict) -> list[str]:
    lines = ["", "-- scheduler tenants " + "-" * 49]
    for name, r in sorted(sched.items()):
        age = r.get("queue_age_ms") or {}
        lines.append(
            "  %-12s w=%-5s depth=%-3s share=%-7s busy_rate=%-7s "
            "shed=%s age p99=%sms" % (
                name, r.get("weight"), r.get("depth"),
                r.get("share"), r.get("busy_rate"),
                r.get("shed"), age.get("p99"),
            )
        )
    return lines


def render_commit_engine(ce: dict, vitals: dict | None) -> list[str]:
    """The decoupled committer at the moment of death: per channel,
    how far the state-DB apply trailed the appended (durable) chain,
    the apply-queue posture, and — when the vitals sampler was armed —
    the queue-depth trail leading into the incident."""
    lines = ["", "-- commit engine (state apply vs appended chain) "
             + "-" * 21]
    for cid in sorted(ce):
        st = ce[cid] or {}
        applied = st.get("applied_num")
        appended = st.get("appended_height")
        lag = (appended - 1 - applied
               if isinstance(appended, (int, float))
               and isinstance(applied, (int, float)) else None)
        lines.append(
            "  %-12s applied block %s / appended height %s"
            " (synced %s)%s" % (
                cid, _fmt(applied), _fmt(appended),
                _fmt(st.get("synced_height")),
                f"  << {int(lag)} block(s) UNAPPLIED" if lag else "",
            )
        )
        lines.append(
            "  %-12s queue %s/%s  oldest %s ms  applies %s  "
            "backpressure %s%s" % (
                "", _fmt(st.get("queue_depth")),
                _fmt(st.get("queue_capacity")),
                _fmt(st.get("oldest_age_ms")),
                _fmt(st.get("applies_total")),
                _fmt(st.get("backpressure_total")),
                "  !! APPLIER FAILED (fail-stop latch)"
                if st.get("failed") else "",
            )
        )
    if not ce:
        lines.append("  (no async-commit channels)")
    depth = (vitals or {}).get("commit_apply_queue_depth") or {}
    for labels, series in sorted(depth.items()):
        vals = [p[1] for p in series.get("points", [])]
        if any(isinstance(v, (int, float)) for v in vals):
            lines.append("  depth trail %-32s %s" % (
                f"{{{labels}}}"[:32], spark(vals[-48:])))
    return lines


def render_faults(stats: dict) -> list[str]:
    lines = ["", "-- fault plan " + "-" * 56]
    for point, rules in sorted(stats.items()):
        for r in rules:
            lines.append(
                "  %-32s %-12s arrivals=%-5d fired=%d" % (
                    point, r.get("kind"), r.get("arrivals", 0),
                    r.get("fired", 0),
                )
            )
    return lines


def render_launches(led: dict) -> list[str]:
    """The device-time ledger section: per-kernel compile/queue/
    execute decomposition + cache hit rates, HBM owner watermarks,
    and the last raw launch rows — "was device_wait a compile?"
    answered inside the postmortem."""
    lines = ["", "-- device ledger " + "-" * 53]
    for name, k in sorted((led.get("kernels") or {}).items()):
        parts = [f"  {name:<16} launches={k.get('launches', 0):<5}"
                 f"hit_rate={_fmt(k.get('cache_hit_rate', 0))}"]
        for stage in ("compile_ms", "queue_ms", "execute_ms"):
            p = k.get(stage)
            if p:
                parts.append(
                    f"{stage[:-3]} p50={_fmt(p['p50'])}"
                    f"/p99={_fmt(p['p99'])}ms"
                )
        lines.append("  ".join(parts))
    hbm = led.get("hbm") or {}
    if hbm:
        lines.append("  [hbm watermarks]")
        for owner, row in sorted(hbm.items()):
            lines.append(
                "    %-16s current=%-12d watermark=%d" % (
                    owner, row.get("current_bytes", 0),
                    row.get("watermark_bytes", 0),
                )
            )
    recent = led.get("recent") or []
    if recent:
        lines.append("  [last launches]")
        for r in recent:
            lines.append(
                "    %-12s %-5s compile=%-8s queue=%-8s "
                "execute=%-8s block=%s" % (
                    r.get("kernel"), r.get("cache"),
                    _fmt(r.get("compile_ms")), _fmt(r.get("queue_ms")),
                    _fmt(r.get("execute_ms")), r.get("block", "-"),
                )
            )
    return lines


def render_txflow(tf: dict) -> list[str]:
    """The per-tx flow section: stage decomposition percentiles, e2e
    by validation outcome, the visibility-lag window and the last
    completed flows — "where did the p99 tx spend its second?"
    answered inside the postmortem."""
    lines = ["", "-- tx flows " + "-" * 58]
    lines.append(
        "  completed=%-6s inflight=%-6s evicted=%-6s partial=%-6s "
        "replayed=%s" % (
            tf.get("flows_completed", 0), tf.get("flows_inflight", 0),
            tf.get("flows_evicted", 0), tf.get("flows_partial", 0),
            tf.get("flows_replayed", 0),
        )
    )
    stages = tf.get("stages_ms") or {}
    if stages:
        lines.append("  [stages]")
        for stage, p in sorted(stages.items()):
            if not p:
                continue
            lines.append(
                "    %-10s n=%-6d p50=%-8s p99=%-8s max=%sms" % (
                    stage, p["n"], _fmt(p["p50"]), _fmt(p["p99"]),
                    _fmt(p["max"]),
                )
            )
    e2e = tf.get("e2e_ms") or {}
    if e2e:
        lines.append("  [e2e by outcome]")
        for outcome, p in sorted(e2e.items()):
            if not p:
                continue
            lines.append(
                "    %-22s n=%-6d p50=%-8s p99=%-8s max=%sms" % (
                    outcome, p["n"], _fmt(p["p50"]), _fmt(p["p99"]),
                    _fmt(p["max"]),
                )
            )
    lag = tf.get("visibility_lag_ms")
    if lag:
        lines.append(
            "  visibility_lag n=%-6d p50=%-8s p99=%-8s max=%sms" % (
                lag["n"], _fmt(lag["p50"]), _fmt(lag["p99"]),
                _fmt(lag["max"]),
            )
        )
    recent = tf.get("recent") or []
    if recent:
        lines.append("  [last flows]")
        for r in recent:
            stages_s = ",".join(
                f"{k}={_fmt(v)}" for k, v in
                sorted((r.get("stages_ms") or {}).items())
            )
            lines.append(
                "    %-16s %-12s blk=%-5s e2e=%-8sms %s" % (
                    (r.get("tx_id") or "")[:16], r.get("outcome"),
                    r.get("block", "-"), _fmt(r.get("e2e_ms")),
                    stages_s,
                )
            )
    return lines


def render_traces(traces: dict) -> list[str]:
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.abspath(__file__))
    )
    import traceview

    lines = ["", "-- trace trees " + "-" * 55]
    for ns in sorted(traces):
        trees = traces[ns] or []
        if not trees:
            continue
        lines.append(f"  [namespace {ns}]")
        for tree in trees:
            lines.extend(
                "  " + ln for ln in
                traceview.render_tree(tree).splitlines()
            )
            lines.append("")
    return lines


def render_bundle(b: dict, series_limit: int | None = 24,
                  traces: bool = True) -> str:
    lines = render_header(b)
    if "vitals" in b:
        lines += render_vitals(b["vitals"], limit=series_limit)
    if "autopilot" in b:
        lines += render_autopilot(b["autopilot"], b.get("t_s"))
    if "slo" in b:
        lines += render_slo(b["slo"])
    if "scheduler" in b:
        lines += render_scheduler(b["scheduler"])
    if "launches" in b:
        lines += render_launches(b["launches"])
    if "tx_flow" in b:
        lines += render_txflow(b["tx_flow"])
    if "commit_engine" in b:
        lines += render_commit_engine(b["commit_engine"],
                                      b.get("vitals"))
    if "faults" in b:
        lines += render_faults(b["faults"])
    if traces and "traces" in b:
        lines += render_traces(b["traces"])
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="black-box bundle JSON")
    ap.add_argument("--series", type=int, default=24,
                    help="max metric series rendered (0 = all)")
    ap.add_argument("--no-traces", action="store_true",
                    help="skip the trace-tree waterfalls")
    args = ap.parse_args(argv)
    with open(args.path) as f:
        bundle = json.load(f)
    print(render_bundle(
        bundle, series_limit=args.series or None,
        traces=not args.no_traces,
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# Regenerate the protobuf gencode (committed; run after editing .proto).
set -euo pipefail
cd "$(dirname "$0")/.."
protoc --python_out=. fabric_tpu/protos/*.proto
echo "generated: $(ls fabric_tpu/protos/*_pb2.py | wc -l) modules"

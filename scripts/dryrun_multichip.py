#!/usr/bin/env python
"""8-device virtual-mesh dryrun through the DECLARATIVE partition
rules (fabric_tpu/parallel/mesh.py).

What one run proves, in order:

1. the partition-rule table resolves and prints — every stage-2
   operand family has a declared PartitionSpec;
2. the ``MeshTopology(shape="8")`` path builds the 8-wide data mesh
   (the same resolution a pod-scale ``mesh_shape`` nodeconfig knob
   takes, minus ``jax.distributed``);
3. every data-sharded family actually places axis 0 across all 8
   devices — and the replicated family does not;
4. the key-range residency layout balances: ~512 keys over a
   1024-slot 8-shard table occupy EVERY shard with max/mean
   occupancy skew ≤ 2.0;
5. a mesh resize (8 → 4) reshards to a state identical to a manager
   born at 4 shards;
6. the full sharded ≡ unsharded kernel differential
   (``__graft_entry__.dryrun_multichip``): sha256, MVCC fixpoint,
   ECDSA verify, and the fused stage-2 program, bit-equal per lane.

Exit 0 = all green.  ``--out MULTICHIP_rNN.json`` records the run
(the repo's MULTICHIP_r0*.json series) with ``extras.shard_balance``.
"""

import json
import os
import sys

N_DEVICES = int(os.environ.get("FABTPU_DRYRUN_DEVICES", "8"))

# the virtual-device pins must land before ANY jax import
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=%d" % N_DEVICES
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def run() -> dict:
    import numpy as np

    import __graft_entry__ as graft

    graft._force_host_mesh_platform()
    import jax.numpy as jnp

    from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
    from fabric_tpu.parallel import mesh as pmesh
    from fabric_tpu.parallel.topology import MeshTopology
    from fabric_tpu.state import ResidencyManager, build_launch_pack

    # 1. the rule table
    table = pmesh.rules_table()
    print("partition rules (%d families):" % len(table))
    for row in table:
        print("  %-17s %-14s %s"
              % (row["family"], row["spec"], row["description"][:48]))

    # 2. declarative topology → the 8-wide data mesh
    mesh = pmesh.resolve_fabric(MeshTopology(shape=str(N_DEVICES)))
    assert mesh is not None, "mesh_shape resolution returned no mesh"
    assert pmesh.data_axis_size(mesh) == N_DEVICES, dict(mesh.shape)
    print("mesh: %s (data axis = %d)"
          % (dict(mesh.shape), pmesh.data_axis_size(mesh)))

    # 3. per-family placement
    for row in table:
        fam = row["family"]
        arr = pmesh.shard(
            mesh, fam, jnp.zeros((N_DEVICES * 4, 3), jnp.int32)
        )
        if pmesh.rule_for(fam).replicated:
            assert arr.sharding.is_fully_replicated, fam
        else:
            assert len(arr.sharding.device_set) == N_DEVICES, (
                fam, arr.sharding
            )
    assert not pmesh.fallback_stats().get("ragged_axis0", 0), (
        "the bucketed dryrun shapes must never hit the ragged fallback"
    )
    print("placement: all %d families correct" % len(table))

    # 4. key-range balance on the sharded resident table
    n_keys = 512
    state = MemVersionedDB()
    b = UpdateBatch()
    for u in range(n_keys):
        b.put("ns", "key%04d" % u, b"v", (1, u))
    state.apply_updates(b, (1, 0))
    res = ResidencyManager(slots=1024, range_bits=10, mesh=mesh)
    assert res.stats()["shards"] == N_DEVICES
    pairs = [("ns", "key%04d" % u) for u in range(n_keys)]
    out = build_launch_pack(res, pairs, state)
    assert out is not None
    balance = res.shard_balance()
    assert sum(balance["per_shard_keys"]) == n_keys
    assert all(k > 0 for k in balance["per_shard_keys"]), (
        "an empty shard at 512 keys over 8 ranges-of-ranges means the "
        "blake2b range→shard map broke", balance
    )
    skew = balance["imbalance_max_over_mean"]
    assert skew <= 2.0, ("key-range occupancy skew too high", balance)
    # ownership law: every slot sits in its range's shard block
    slots, _t = res.lookup(pairs)
    sps = balance["slots_per_shard"]
    for pr, slot in zip(pairs, slots):
        rid = res.range_of(*pr)
        own = (rid * N_DEVICES) >> res.range_bits
        assert slot // sps == own, (pr, int(slot), own)
    print("shard balance: keys/shard=%s skew=%.3f"
          % (balance["per_shard_keys"], skew))

    # 5. mesh-resize reshard ≡ fresh manager at the new size
    half = pmesh.resolve_mesh(N_DEVICES // 2)
    st = res.reshard(half)
    assert st["enabled"] and st["resident_keys"] == 0
    fresh = ResidencyManager(slots=1024, range_bits=10, mesh=half)
    for r in (res, fresh):
        build_launch_pack(r, pairs, state)
    s1, t1 = res.lookup(pairs)
    s2, t2 = fresh.lookup(pairs)
    assert np.array_equal(s1, s2)
    assert np.array_equal(np.asarray(t1)[s1], np.asarray(t2)[s2])
    print("reshard %d -> %d: identical post-rebuild state"
          % (N_DEVICES, N_DEVICES // 2))

    # 6. the sharded ≡ unsharded kernel differential
    graft.dryrun_multichip(N_DEVICES)
    print("dryrun_multichip(%d): sharded == unsharded on every lane"
          % N_DEVICES)

    return {
        "rules": len(table),
        "shard_balance": {
            "data_axis": N_DEVICES,
            "per_shard_keys": balance["per_shard_keys"],
            "slots_per_shard": balance["slots_per_shard"],
            "occupancy_max": balance["occupancy_max"],
            "occupancy_mean": balance["occupancy_mean"],
            "imbalance_max_over_mean": skew,
        },
    }


def main(argv) -> int:
    out_path = None
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    record = {"n_devices": N_DEVICES, "rc": 0, "ok": False,
              "skipped": False, "tail": ""}
    try:
        record["extras"] = run()
        record["ok"] = True
    except Exception as e:  # recorded, then re-raised for the CI log
        record["rc"] = 1
        record["tail"] = str(e)[:400]
        if out_path:
            with open(out_path, "w") as f:
                json.dump(record, f, indent=2)
        raise
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
        print("recorded -> %s" % out_path)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env bash
# Tier-1 CI gate: the ROADMAP's verify line plus the static-analysis
# battery, as one command with one exit code.
#
#   scripts/ci_tier1.sh            # full gate (tests + analyzer)
#   scripts/ci_tier1.sh --lint     # analyzer battery only
#
# The test half is the EXACT tier-1 line from ROADMAP.md (same
# markers, same plugin set, same DOTS_PASSED accounting) so CI and a
# laptop measure the identical thing; the analyzer half is the full
# fabric_tpu/ battery (scripts/lint.py) whose findings are errors —
# a clean tree prints 0 finding(s).
set -u

cd "$(dirname "$0")/.."

lint_only=0
[ "${1:-}" = "--lint" ] && lint_only=1

echo "== fabric_tpu analyzer battery =="
python scripts/lint.py
lint_rc=$?

if [ "$lint_only" = "1" ]; then
    exit "$lint_rc"
fi

echo "== multichip dryrun =="
# 8-virtual-device partition-rule dryrun (scripts/dryrun_multichip.py):
# rule table, per-family placement, key-range balance, reshard
# identity, and the sharded == unsharded kernel differential
timeout -k 10 300 python scripts/dryrun_multichip.py
mc_rc=$?

echo "== replay smoke =="
# crypto-free catch-up smoke (scripts/replay_smoke.py): toy chain
# through the REAL ReplayDriver + snapshot round-trip, pinning the
# source ≡ full-replay ≡ snapshot-join identity in seconds
timeout -k 10 120 env JAX_PLATFORMS=cpu python scripts/replay_smoke.py
smoke_rc=$?

echo "== txflow smoke =="
# crypto-free tx-flow journal smoke (scripts/txflow_smoke.py): toy
# chain through the REAL CommitPipeline + KVLedger, pinning the
# milestone-order and stage-telescoping (sum == e2e) invariants
timeout -k 10 120 env JAX_PLATFORMS=cpu python scripts/txflow_smoke.py
tf_rc=$?

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
    | tee /tmp/_t1.log
t1_rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"

[ "$lint_rc" -ne 0 ] && echo "analyzer battery FAILED (rc=$lint_rc)"
[ "$mc_rc" -ne 0 ] && echo "multichip dryrun FAILED (rc=$mc_rc)"
[ "$smoke_rc" -ne 0 ] && echo "replay smoke FAILED (rc=$smoke_rc)"
[ "$tf_rc" -ne 0 ] && echo "txflow smoke FAILED (rc=$tf_rc)"
[ "$t1_rc" -ne 0 ] && echo "tier-1 tests FAILED (rc=$t1_rc)"
[ "$lint_rc" -eq 0 ] && [ "$mc_rc" -eq 0 ] && [ "$smoke_rc" -eq 0 ] \
    && [ "$tf_rc" -eq 0 ] && [ "$t1_rc" -eq 0 ]

"""Declarative partition rules (fabric_tpu/parallel/mesh.py): the
sharded ≡ unsharded differential battery.

All crypto-free, all on the virtual 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``):

1. registry sanity — every stage-2 operand family has a rule, unknown
   families are loud, the table renders;
2. the fused stage-2 program through the named partition families is
   bit-equal to the unsharded host-oracle run on EVERY output lane at
   2/4/8 devices;
3. key-range residency — slot-block ownership (slot // slots_per_shard
   == owning shard of the key's range id), hit/commit/evict behaviour
   identical to the 1-shard host oracle;
4. mesh-resize resharding (disable-latch → cold rebuild) reaches a
   state identical to a fresh manager at the new size;
5. the silent single-device fallback counter fires on ragged axis-0;
6. the launch ledger's ``sharded`` row tag + per-kernel
   ``unsharded_launches``.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.parallel import mesh as pmesh
from fabric_tpu.parallel.topology import MeshTopology, parse_mesh_shape
from fabric_tpu.state import ResidencyManager, build_launch_pack

from tests.test_resident import (
    _KEYS,
    _run_host,
    _run_resident,
    _seed_state,
    _stage2_fixture,
)


# ---------------------------------------------------------------------------
# 1. registry sanity


#: every family the stage-2 fused dispatch + stage-1 verify upload
_STAGE2_FAMILIES = (
    "verify_lanes", "sign_rows", "launch_frame", "policy_table",
    "static_pack", "mvcc_frame", "read_versions", "state_table",
    "unique_read_pack",
)


def test_every_operand_family_has_a_rule():
    for fam in _STAGE2_FAMILIES:
        rule = pmesh.rule_for(fam)
        assert rule.family == fam
        assert rule.description.strip()
    # batch families split axis 0 over "data"; the unique-read pack
    # replicates (gathered from every shard)
    assert pmesh.rule_for("launch_frame").axes == (pmesh.DATA_AXIS,)
    assert pmesh.rule_for("unique_read_pack").replicated


def test_unknown_family_is_loud():
    with pytest.raises(KeyError, match="no partition rule"):
        pmesh.rule_for("mystery_operand")


def test_rules_table_renders():
    table = pmesh.rules_table()
    assert {r["family"] for r in table} >= set(_STAGE2_FAMILIES)
    for row in table:
        assert row["spec"] and row["description"]


def test_spec_pads_trailing_dims():
    # trailing dims are per-lane payload — always replicated
    assert pmesh.spec_for("launch_frame", 1) == pmesh.P("data")
    assert pmesh.spec_for("launch_frame", 3) == \
        pmesh.P("data", None, None)
    assert pmesh.spec_for("unique_read_pack", 2) == pmesh.P(None, None)


def test_topology_parse_and_resolution():
    assert parse_mesh_shape("8") == (8,)
    assert parse_mesh_shape("2x4") == (2, 4)
    for bad in ("", "0", "2x0", "ax4", "2x2x2"):
        with pytest.raises(ValueError):
            parse_mesh_shape(bad)
    # the unconfigured topology is a no-mesh no-op
    assert not MeshTopology().configured
    assert MeshTopology().resolve() is None
    # the classic count is the 1-process special case
    t = MeshTopology(devices=4)
    m = t.resolve()
    assert m is not None and pmesh.data_axis_size(m) == 4
    # a 1-D shape over the virtual devices
    m8 = MeshTopology(shape="8").resolve()
    assert m8 is not None and pmesh.data_axis_size(m8) == 8
    # data x replica grid: the data axis is dim 0
    m24 = MeshTopology(shape="2x4").resolve()
    assert m24 is not None and pmesh.data_axis_size(m24) == 2
    assert dict(m24.shape)[pmesh.REPLICA_AXIS] == 4
    # an unfit grid degrades to the local auto mesh, never refuses
    big = MeshTopology(shape="64x2").resolve()
    assert big is None or pmesh.data_axis_size(big) >= 1


# ---------------------------------------------------------------------------
# 2. sharded stage-2 ≡ unsharded, every output lane, 2/4/8 devices


def test_stage2_sharded_bit_equal_across_device_counts():
    from fabric_tpu.peer.device_block import DeviceBlockPipeline

    rng = np.random.default_rng(20260807)
    fx = _stage2_fixture(rng)
    pipe = DeviceBlockPipeline()
    base = _run_host(pipe, fx)
    assert base["valid"][:12].any() and not base["valid"][:12].all()
    for nd in (2, 4, 8):
        mesh = pmesh.resolve_mesh(nd)
        assert pmesh.data_axis_size(mesh) == nd
        res = ResidencyManager(slots=64, range_bits=5, mesh=mesh)
        _run_resident(pipe, fx, res, mesh=mesh)   # warm (admit)
        got = _run_resident(pipe, fx, res, mesh=mesh)
        for k in _KEYS:
            assert np.array_equal(base[k], got[k]), (nd, k)
        assert res.stats()["shards"] == nd


# ---------------------------------------------------------------------------
# 3. key-range sharded residency ≡ host oracle


def _shard_of(res, rid):
    """The ownership law, restated independently of the manager: top
    ``log2(n_shards)`` bits of the range id pick the shard."""
    return (rid * res.stats()["shards"]) >> res.range_bits


def test_key_range_slot_block_ownership():
    """Every admitted key lands in its owning shard's contiguous slot
    block — the invariant that makes the plain axis-0 NamedSharding
    over the table BE the key-range partition."""
    state = _seed_state(32, stale_every=0, absent_every=0)
    mesh = pmesh.resolve_mesh(4)
    res = ResidencyManager(slots=64, range_bits=6, mesh=mesh)
    st = res.stats()
    assert st["shards"] == 4 and st["slots_per_shard"] == 16
    pairs = [("ns", f"k{u}") for u in range(32)]
    build_launch_pack(res, pairs, state)
    slots, _t = res.lookup(pairs)
    assert (slots >= 0).all()
    for pr, slot in zip(pairs, slots):
        rid = res.range_of(*pr)
        assert slot // 16 == _shard_of(res, rid), (pr, int(slot))
    bal = res.shard_balance()
    assert sum(bal["per_shard_keys"]) == 32
    assert bal["occupancy_max"] <= 16


def test_key_range_sharded_hit_commit_evict_matches_oracle():
    """The 4-shard manager and the 1-shard oracle answer every lookup
    identically through admission, a commit delta scatter, and
    per-shard eviction churn."""
    state = _seed_state(24, stale_every=3, absent_every=4)
    oracle = ResidencyManager(slots=64, range_bits=6)
    mesh = pmesh.resolve_mesh(4)
    sharded = ResidencyManager(slots=64, range_bits=6, mesh=mesh)
    pairs = [("ns", f"k{u}") for u in range(24)]

    def versions(res):
        out = []
        slots, table = res.lookup(pairs)
        arr = np.asarray(table) if table is not None else None
        for s in slots:
            if s < 0:
                out.append("miss")
            else:
                row = arr[s]
                out.append(
                    tuple(int(x) for x in row[1:3]) if row[0] else None
                )
        return out

    for res in (oracle, sharded):
        build_launch_pack(res, pairs, state)
    assert versions(oracle) == versions(sharded)

    # commit delta: update, delete, and a write into a resident range
    cb = UpdateBatch()
    cb.put("ns", "k0", b"n", (7, 0))
    cb.delete("ns", "k1", (7, 1))
    for res in (oracle, sharded):
        res.apply_batch(cb)
    assert versions(oracle) == versions(sharded)

    # eviction churn: a small sharded cache over a large key stream
    # still answers exactly like the small unsharded one would for
    # keys both hold; per-shard eviction must fire
    small = ResidencyManager(slots=16, range_bits=4, mesh=mesh)
    ones = np.ones(1, bool)
    ver = np.asarray([[1, 0]], np.uint32)
    for i in range(200):
        small.admit([("ns", "c%d" % i)], ones, ver)
    st = small.stats()
    assert st["evictions_total"] > 0
    assert st["resident_keys"] <= small.capacity
    # ownership never broke under churn
    bal = small.shard_balance()
    assert sum(bal["per_shard_keys"]) == st["resident_keys"]
    occupied = sum(
        small.capacity // st["shards"] - f
        for f in bal["per_shard_free_slots"]
    )
    assert occupied == st["resident_keys"]


def test_non_dividing_mesh_degrades_to_one_shard():
    # capacity not divisible by the data axis → 1 logical shard (the
    # safe degrade), never a broken layout
    mesh3 = pmesh.resolve_mesh(3)
    assert pmesh.data_axis_size(mesh3) == 3
    res = ResidencyManager(slots=8, range_bits=3, mesh=mesh3)
    assert res.stats()["shards"] == 1
    # a mesh wider than capacity degrades too
    wide = ResidencyManager(slots=4, range_bits=3,
                            mesh=pmesh.resolve_mesh(8))
    assert wide.stats()["shards"] == 1


# ---------------------------------------------------------------------------
# 4. mesh-resize resharding


def test_reshard_reaches_identical_post_rebuild_state():
    """Resize 2 → 4 shards: the reshard path (disable-latch → cold
    rebuild) re-arms the manager, and after re-warming it is
    indistinguishable — same lookups, same slot-block ownership — from
    a manager BORN at 4 shards."""
    state = _seed_state(32, stale_every=0, absent_every=0)
    pairs = [("ns", f"k{u}") for u in range(32)]

    grown = ResidencyManager(slots=64, range_bits=6,
                             mesh=pmesh.resolve_mesh(2))
    build_launch_pack(grown, pairs, state)        # warm at 2 shards
    assert grown.stats()["shards"] == 2
    mesh4 = pmesh.resolve_mesh(4)
    st = grown.reshard(mesh4)
    assert st["shards"] == 4
    assert st["resident_keys"] == 0               # cold rebuild
    assert st["enabled"] is True                  # re-armed
    assert st["reshards_total"] == 1

    fresh = ResidencyManager(slots=64, range_bits=6, mesh=mesh4)
    for res in (grown, fresh):
        build_launch_pack(res, pairs, state)      # warm both at 4

    g_slots, g_table = grown.lookup(pairs)
    f_slots, f_table = fresh.lookup(pairs)
    assert np.array_equal(g_slots, f_slots)
    assert np.array_equal(
        np.asarray(g_table)[g_slots], np.asarray(f_table)[f_slots]
    )
    assert grown.shard_balance() == fresh.shard_balance()

    # reshard re-arms even a latched-off manager (operator resize)
    grown.disable("test latch")
    assert not grown.enabled
    st2 = grown.reshard(pmesh.resolve_mesh(2))
    assert st2["enabled"] is True and st2["reshards_total"] == 2


def test_reshard_verdicts_bit_equal_through_stage2():
    """The full loop: stage-2 verdicts through a 2-shard manager, a
    reshard to 4, and the re-warmed 4-shard run — all bit-equal to the
    host oracle."""
    from fabric_tpu.peer.device_block import DeviceBlockPipeline

    rng = np.random.default_rng(20260808)
    fx = _stage2_fixture(rng)
    pipe = DeviceBlockPipeline()
    base = _run_host(pipe, fx)
    mesh2, mesh4 = pmesh.resolve_mesh(2), pmesh.resolve_mesh(4)
    res = ResidencyManager(slots=64, range_bits=5, mesh=mesh2)
    got2 = _run_resident(pipe, fx, res, mesh=mesh2)
    for k in _KEYS:
        assert np.array_equal(base[k], got2[k]), ("pre-reshard", k)
    res.reshard(mesh4)
    _run_resident(pipe, fx, res, mesh=mesh4)      # re-warm cold
    got4 = _run_resident(pipe, fx, res, mesh=mesh4)
    for k in _KEYS:
        assert np.array_equal(base[k], got4[k]), ("post-reshard", k)
    assert res.stats()["hits_total"] > 0


# ---------------------------------------------------------------------------
# 5. the silent-fallback counter


def test_ragged_axis0_counts_fallback():
    mesh = pmesh.resolve_mesh(8)
    before = pmesh.fallback_stats().get("ragged_axis0", 0)
    arr = pmesh.shard(mesh, "launch_frame", jnp.zeros((12, 3)))
    assert arr.shape == (12, 3)                   # correct, unparallel
    after = pmesh.fallback_stats().get("ragged_axis0", 0)
    assert after == before + 1
    # empty axis 0 is its own reason
    b0 = pmesh.fallback_stats().get("empty_axis0", 0)
    pmesh.shard(mesh, "launch_frame", jnp.zeros((0, 3)))
    assert pmesh.fallback_stats().get("empty_axis0", 0) == b0 + 1
    # a dividing shape does NOT count
    b1 = pmesh.fallback_stats().get("ragged_axis0", 0)
    out = pmesh.shard(mesh, "launch_frame", jnp.zeros((16, 3)))
    assert pmesh.fallback_stats().get("ragged_axis0", 0) == b1
    assert len(out.sharding.device_set) == 8
    # replicated families never count
    b2 = dict(pmesh.fallback_stats())
    pmesh.shard(mesh, "unique_read_pack", jnp.zeros((13, 4)))
    assert pmesh.fallback_stats() == b2
    # no mesh → plain passthrough, not a "fallback"
    b3 = dict(pmesh.fallback_stats())
    pmesh.shard(None, "launch_frame", jnp.zeros((12, 3)))
    assert pmesh.fallback_stats() == b3


# ---------------------------------------------------------------------------
# 6. the launch ledger's sharded tag


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_ledger_sharded_tag_and_stats():
    from fabric_tpu.observe.ledger import LaunchLedger
    from fabric_tpu.observe.tracer import Tracer
    from fabric_tpu.ops_metrics import Registry

    clk = _Clock()
    led = LaunchLedger(
        registry=Registry(),
        tracer=Tracer(ring_blocks=8, slow_factor=0, clock=clk),
        clock=clk,
    )

    def run(sharded):
        rec = led.launch("stage2", compiled=False, lanes=16,
                         sharded=sharded)
        clk.t += 0.001
        rec.dispatched()
        rec.sync_begin()
        clk.t += 0.002
        rec.sync_end()

    run(True)      # sharded dispatch
    run(False)     # the ragged fallback the tag exists for
    run(None)      # no mesh configured: untagged
    rows = led.rows()
    assert rows[0]["sharded"] is True
    assert rows[1]["sharded"] is False
    assert "sharded" not in rows[2]
    st = led.stats()["kernels"]["stage2"]
    assert st["launches"] == 3
    assert st["unsharded_launches"] == 1

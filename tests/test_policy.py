"""Policy engine tests: DSL, compiler, interpreter, batch kernel.

Oracle relationships: the batch (count) evaluation must equal the
exact consumption interpreter whenever consumption_safe; the
interpreter itself is checked against hand-derived cases mirroring the
reference's cauthdsl semantics.
"""

from dataclasses import dataclass

import numpy as np
import pytest

from fabric_tpu.crypto import policy as pol


@dataclass
class FakeIdentity:
    msp_id: str
    role: str = "member"
    is_valid: bool = True


def _sat(rule, idents):
    plan = pol.compile_plan(rule)
    return plan, pol.match_matrix(idents, plan.principals)


def test_dsl_parse():
    r = pol.from_dsl("AND('Org1.member', OR('Org2.admin', 'Org3.peer'))")
    assert isinstance(r, pol.NOutOf) and r.n == 2
    inner = r.rules[1]
    assert isinstance(inner, pol.NOutOf) and inner.n == 1
    r2 = pol.from_dsl("OutOf(2, 'A.member', 'B.member', 'C.member')")
    assert r2.n == 2 and len(r2.rules) == 3
    with pytest.raises(ValueError):
        pol.from_dsl("NAND('A.member')")
    with pytest.raises(ValueError):
        pol.from_dsl("AND('A.superuser')")


def test_interpreter_basic_gates():
    a, b, c = (pol.SignedBy(pol.Principal(x)) for x in "ABC")
    idA, idB = FakeIdentity("A"), FakeIdentity("B")
    rule = pol.And(a, b)
    _, m = _sat(rule, [idA, idB])
    assert pol.evaluate(rule, m)
    _, m = _sat(rule, [idA])
    assert not pol.evaluate(rule, m)
    rule = pol.Or(a, c)
    _, m = _sat(rule, [idB])
    assert not pol.evaluate(rule, m)
    _, m = _sat(rule, [FakeIdentity("C")])
    assert pol.evaluate(rule, m)
    rule = pol.NOutOf(2, (a, b, c))
    _, m = _sat(rule, [idA, FakeIdentity("C")])
    assert pol.evaluate(rule, m)


def test_consumption_semantics():
    """One signature cannot satisfy two leaves (cauthdsl used-map)."""
    a1 = pol.SignedBy(pol.Principal("A"))
    a2 = pol.SignedBy(pol.Principal("A"))
    rule = pol.And(a1, a2)  # needs TWO A-signatures
    _, m = _sat(rule, [FakeIdentity("A")])
    assert not pol.evaluate(rule, m)
    _, m = _sat(rule, [FakeIdentity("A"), FakeIdentity("A")])
    assert pol.evaluate(rule, m)


def test_role_matching():
    admin_rule = pol.SignedBy(pol.Principal("A", pol.ROLE_ADMIN))
    plan = pol.compile_plan(admin_rule)
    m = pol.match_matrix([FakeIdentity("A", role="member")], plan.principals)
    assert not pol.evaluate(admin_rule, m)
    m = pol.match_matrix([FakeIdentity("A", role="admin")], plan.principals)
    assert pol.evaluate(admin_rule, m)
    # member principal accepts any valid role
    mem_rule = pol.SignedBy(pol.Principal("A", pol.ROLE_MEMBER))
    plan = pol.compile_plan(mem_rule)
    m = pol.match_matrix([FakeIdentity("A", role="admin")], plan.principals)
    assert pol.evaluate(mem_rule, m)
    m = pol.match_matrix([FakeIdentity("A", role="admin", is_valid=False)], plan.principals)
    assert not pol.evaluate(mem_rule, m)


def test_counts_equal_interpreter_when_safe(rng):
    """Randomized: count evaluation == consumption interpreter whenever
    consumption_safe says so (and safe must hold for org-distinct
    policies)."""
    orgs = ["O1", "O2", "O3", "O4"]
    for trial in range(200):
        k = int(rng.integers(1, 5))
        leaves = [pol.SignedBy(pol.Principal(o)) for o in rng.choice(orgs, k, replace=False)]
        n = int(rng.integers(1, k + 1))
        rule = pol.NOutOf(n, tuple(leaves))
        idents = [FakeIdentity(str(o)) for o in rng.choice(orgs, rng.integers(0, 5))]
        plan, m = _sat(rule, idents)
        assert plan.consumption_safe(m)
        assert plan.evaluate_counts(m) == pol.evaluate(rule, m)


def test_batch_kernel_matches_counts(rng):
    """Device kernel over a block == host count evaluation per tx."""
    from fabric_tpu.ops import policy_eval

    rule = pol.from_dsl("AND('O1.member', OR('O2.member', 'O3.admin'))")
    plan = pol.compile_plan(rule)
    T, S, P = 16, 3, len(plan.principals)
    valid = rng.random((T, S)) > 0.3
    sat = rng.random((T, S, P)) > 0.5
    got = np.asarray(policy_eval.eval_block(plan, valid, sat))
    for t in range(T):
        m = valid[t][:, None] & sat[t]
        assert got[t] == plan.evaluate_counts(m), t


def test_nested_plan_compile():
    rule = pol.from_dsl(
        "OutOf(2, 'A.member', AND('B.member', 'C.member'), OR('D.member', 'A.admin'))"
    )
    plan = pol.compile_plan(rule)
    assert plan.n_leaves == 5
    assert plan.gates[-1][0] == 2  # root gate
    idents = [FakeIdentity("B"), FakeIdentity("C"), FakeIdentity("D")]
    m = pol.match_matrix(idents, plan.principals)
    assert plan.evaluate_counts(m)
    assert pol.evaluate(rule, m)

"""Policy engine tests: DSL, compiler, interpreter, batch kernel.

Oracle relationships: the batch (count) evaluation must equal the
exact consumption interpreter whenever consumption_safe; the
interpreter itself is checked against hand-derived cases mirroring the
reference's cauthdsl semantics.
"""

from dataclasses import dataclass

import numpy as np
import pytest

from fabric_tpu.crypto import policy as pol


@dataclass
class FakeIdentity:
    msp_id: str
    role: str = "member"
    is_valid: bool = True


def _sat(rule, idents):
    plan = pol.compile_plan(rule)
    return plan, pol.match_matrix(idents, plan.principals)


def test_dsl_parse():
    r = pol.from_dsl("AND('Org1.member', OR('Org2.admin', 'Org3.peer'))")
    assert isinstance(r, pol.NOutOf) and r.n == 2
    inner = r.rules[1]
    assert isinstance(inner, pol.NOutOf) and inner.n == 1
    r2 = pol.from_dsl("OutOf(2, 'A.member', 'B.member', 'C.member')")
    assert r2.n == 2 and len(r2.rules) == 3
    with pytest.raises(ValueError):
        pol.from_dsl("NAND('A.member')")
    with pytest.raises(ValueError):
        pol.from_dsl("AND('A.superuser')")


def test_interpreter_basic_gates():
    a, b, c = (pol.SignedBy(pol.Principal(x)) for x in "ABC")
    idA, idB = FakeIdentity("A"), FakeIdentity("B")
    rule = pol.And(a, b)
    _, m = _sat(rule, [idA, idB])
    assert pol.evaluate(rule, m)
    _, m = _sat(rule, [idA])
    assert not pol.evaluate(rule, m)
    rule = pol.Or(a, c)
    _, m = _sat(rule, [idB])
    assert not pol.evaluate(rule, m)
    _, m = _sat(rule, [FakeIdentity("C")])
    assert pol.evaluate(rule, m)
    rule = pol.NOutOf(2, (a, b, c))
    _, m = _sat(rule, [idA, FakeIdentity("C")])
    assert pol.evaluate(rule, m)


def test_consumption_semantics():
    """One signature cannot satisfy two leaves (cauthdsl used-map)."""
    a1 = pol.SignedBy(pol.Principal("A"))
    a2 = pol.SignedBy(pol.Principal("A"))
    rule = pol.And(a1, a2)  # needs TWO A-signatures
    _, m = _sat(rule, [FakeIdentity("A")])
    assert not pol.evaluate(rule, m)
    _, m = _sat(rule, [FakeIdentity("A"), FakeIdentity("A")])
    assert pol.evaluate(rule, m)


def test_role_matching():
    admin_rule = pol.SignedBy(pol.Principal("A", pol.ROLE_ADMIN))
    plan = pol.compile_plan(admin_rule)
    m = pol.match_matrix([FakeIdentity("A", role="member")], plan.principals)
    assert not pol.evaluate(admin_rule, m)
    m = pol.match_matrix([FakeIdentity("A", role="admin")], plan.principals)
    assert pol.evaluate(admin_rule, m)
    # member principal accepts any valid role
    mem_rule = pol.SignedBy(pol.Principal("A", pol.ROLE_MEMBER))
    plan = pol.compile_plan(mem_rule)
    m = pol.match_matrix([FakeIdentity("A", role="admin")], plan.principals)
    assert pol.evaluate(mem_rule, m)
    m = pol.match_matrix([FakeIdentity("A", role="admin", is_valid=False)], plan.principals)
    assert not pol.evaluate(mem_rule, m)


def test_counts_equal_interpreter_when_safe(rng):
    """Randomized: count evaluation == consumption interpreter whenever
    consumption_safe says so (and safe must hold for org-distinct
    policies)."""
    orgs = ["O1", "O2", "O3", "O4"]
    for trial in range(200):
        k = int(rng.integers(1, 5))
        leaves = [pol.SignedBy(pol.Principal(o)) for o in rng.choice(orgs, k, replace=False)]
        n = int(rng.integers(1, k + 1))
        rule = pol.NOutOf(n, tuple(leaves))
        idents = [FakeIdentity(str(o)) for o in rng.choice(orgs, rng.integers(0, 5))]
        plan, m = _sat(rule, idents)
        assert plan.consumption_safe(m)
        assert plan.evaluate_counts(m) == pol.evaluate(rule, m)


def test_nested_plan_compile():
    rule = pol.from_dsl(
        "OutOf(2, 'A.member', AND('B.member', 'C.member'), OR('D.member', 'A.admin'))"
    )
    plan = pol.compile_plan(rule)
    assert plan.n_leaves == 5
    assert plan.gates[-1][0] == 2  # root gate
    idents = [FakeIdentity("B"), FakeIdentity("C"), FakeIdentity("D")]
    m = pol.match_matrix(idents, plan.principals)
    assert plan.evaluate_counts(m)
    assert pol.evaluate(rule, m)


def test_repeated_principal_needs_distinct_signatures():
    """OutOf(2, A, A) — the standard "two endorsers from one org"
    policy — must NOT be satisfied by one signature counted twice
    (round-1/2 endorsement-policy bypass regression)."""
    a = pol.SignedBy(pol.Principal("A"))
    rule = pol.NOutOf(2, (a, a))
    plan, m = _sat(rule, [FakeIdentity("A")])
    assert plan.consumption_safe(m)  # one column only — counts path taken
    assert not plan.evaluate_counts(m)
    assert not pol.evaluate(rule, m)
    plan, m = _sat(rule, [FakeIdentity("A"), FakeIdentity("A")])
    assert plan.evaluate_counts(m)
    assert pol.evaluate(rule, m)


def test_counts_equal_interpreter_with_repeats(rng):
    """Randomized with REPEATED principals allowed: counts == greedy
    interpreter whenever consumption_safe (single-column matches keep
    the condition true even with repeats)."""
    orgs = ["O1", "O2", "O3"]
    for trial in range(300):
        k = int(rng.integers(1, 6))
        leaves = [pol.SignedBy(pol.Principal(str(o)))
                  for o in rng.choice(orgs, k, replace=True)]
        n = int(rng.integers(0, k + 1))
        rule = pol.NOutOf(n, tuple(leaves))
        idents = [FakeIdentity(str(o)) for o in rng.choice(orgs, rng.integers(0, 6))]
        plan, m = _sat(rule, idents)
        assert plan.consumption_safe(m)
        assert plan.evaluate_counts(m) == pol.evaluate(rule, m), (rule, idents)


def test_nested_repeated_principals_across_gates(rng):
    """Leaves of the same principal under DIFFERENT gates share the
    signature pool (greedy DFS order), and counts must agree."""
    a = pol.SignedBy(pol.Principal("A"))
    b = pol.SignedBy(pol.Principal("B"))
    rule = pol.And(pol.Or(a, b), a)  # A-sig consumed by first OR branch
    plan, m = _sat(rule, [FakeIdentity("A")])
    assert plan.consumption_safe(m)
    assert plan.evaluate_counts(m) == pol.evaluate(rule, m) == False  # noqa: E712
    # [A, B]: the OR consumes BOTH (children always evaluated, no
    # short-circuit — cauthdsl), leaving nothing for the outer A leaf
    plan, m = _sat(rule, [FakeIdentity("A"), FakeIdentity("B")])
    assert plan.evaluate_counts(m) == pol.evaluate(rule, m) == False  # noqa: E712
    plan, m = _sat(rule, [FakeIdentity("A"), FakeIdentity("A")])
    assert plan.evaluate_counts(m) == pol.evaluate(rule, m) == True  # noqa: E712


def test_three_policy_implementations_agree(rng):
    """The consumption-count semantics exist in three places — the
    BatchPlan numpy batch path (the source of truth), the scalar
    wrappers, and the device kernel in peer/device_block._policy_reduce.
    Pin them together on randomized policies and match matrices."""
    import numpy as np
    import jax.numpy as jnp

    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.peer import device_block as db

    def random_policy(depth=0):
        if depth >= 2 or rng.random() < 0.4:
            org = f"Org{int(rng.integers(1, 4))}MSP"
            role = ["member", "peer", "admin"][int(rng.integers(0, 3))]
            return pol.SignedBy(pol.Principal(org, role))
        k = int(rng.integers(2, 4))
        rules = tuple(random_policy(depth + 1) for _ in range(k))
        return pol.NOutOf(int(rng.integers(1, k + 1)), rules)

    for trial in range(25):
        rule = random_policy()
        plan = pol.compile_plan(rule)
        P = len(plan.principals)
        T, S = 5, 4
        M = rng.random((T, S, P)) < 0.45

        ok_batch = plan.evaluate_counts_batch(M)
        safe_batch = plan.consumption_safe_batch(M)
        # scalar wrappers
        for t in range(T):
            assert plan.evaluate_counts(M[t]) == bool(ok_batch[t])
            assert plan.consumption_safe(M[t]) == bool(safe_batch[t])
            # exact interpreter agrees whenever safe
            if safe_batch[t]:
                assert pol.evaluate(rule, M[t]) == bool(ok_batch[t])
        # device kernel: identity gather wired to an all-valid sig batch
        sig = db.plan_sig(plan, T, S)
        sig_padded = jnp.asarray(np.append(np.ones(T * S, bool), False))
        endo_idx = jnp.asarray(np.arange(T * S, dtype=np.int32).reshape(T, S))
        ok_dev, safe_dev = db._policy_reduce(
            sig_padded, jnp.asarray(M), endo_idx, sig
        )
        assert [bool(v) for v in np.asarray(ok_dev)] == [bool(v) for v in ok_batch]
        assert [bool(v) for v in np.asarray(safe_dev)] == [bool(v) for v in safe_batch]

"""Chaincode install/package artifact flow (reference:
internal/peer/lifecycle/chaincode/{package,install,calculatepackageid,
getinstalledpackage}.go + core/chaincode/persistence): package format,
package-id computation, peer-side install store + RPC, approve binding
a package id, and the endorser resolving a committed definition to the
installed package's ccaas endpoint without manual registration."""

import asyncio
import json

import pytest

from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.peer import ccpackage
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.ccaas import ChaincodeServer
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.lifecycle import (
    ChaincodeDefinition, approval_key, definition_key,
)

CHANNEL, CC = "pkgchan", "pkgcc"


def run(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def test_package_format_and_id():
    raw = ccpackage.package_ccaas("kv_1.0", "127.0.0.1:9999")
    info = ccpackage.parse_package(raw)
    assert info["label"] == "kv_1.0"
    assert info["type"] == "ccaas"
    assert info["connection"] == {"address": "127.0.0.1:9999"}
    pid = ccpackage.package_id("kv_1.0", raw)
    assert pid.startswith("kv_1.0:") and len(pid.split(":")[1]) == 64
    # deterministic: the same logical package yields the same id
    assert ccpackage.package_ccaas("kv_1.0", "127.0.0.1:9999") == raw
    # malformed packages are rejected
    with pytest.raises(ValueError):
        ccpackage.parse_package(b"not a tarball")
    with pytest.raises(ValueError):
        ccpackage.package_ccaas("../evil", "x:1")


def test_package_store_roundtrip(tmp_path):
    store = ccpackage.PackageStore(str(tmp_path))
    raw = ccpackage.package_ccaas("asset.v2", "10.0.0.5:7777")
    got = store.install(raw)
    pid = got["package_id"]
    assert got["label"] == "asset.v2"
    # idempotent re-install
    assert store.install(raw)["package_id"] == pid
    assert store.list() == [{"package_id": pid, "label": "asset.v2"}]
    assert store.get(pid) == raw
    assert store.connection(pid) == {"address": "10.0.0.5:7777"}
    # survives reopen (persistence across peer restarts)
    store2 = ccpackage.PackageStore(str(tmp_path))
    assert store2.list() == [{"package_id": pid, "label": "asset.v2"}]
    # path-traversal ids never touch the filesystem
    assert store.get("../../etc/passwd:deadbeef") is None
    with pytest.raises(ValueError):
        store._path("../../etc/passwd:deadbeef")


def test_install_approve_commit_invoke_flow(tmp_path):
    """The operator walk the round-4 verdict called decorative: package
    → install (RPC) → approve binds the package id → committed
    definition → invoke launches the ccaas endpoint from the INSTALLED
    package, with no manual runtime registration."""
    from fabric_tpu.comm.rpc import RpcClient
    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.ledger.statedb import UpdateBatch
    from fabric_tpu.peer.lifecycle import LIFECYCLE_NS
    from fabric_tpu.peer.node import PeerNode
    from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider
    from fabric_tpu.protos import proposal_pb2

    async def scenario():
        cc_server = await ChaincodeServer().start()
        cc_server.register(CC, KVContract())
        org = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                     peers=1, users=1)
        mgr = MSPManager({"Org1MSP": org.msp()})
        peer = PeerNode(
            "p0", str(tmp_path / "p0"), mgr,
            cryptogen.signing_identity(org, "peer0.org1.example.com"),
            ChaincodeRuntime(),
        )
        await peer.start()
        client = cryptogen.signing_identity(org, "User1@org1.example.com")
        prov = PolicyProvider({}, default=NamespaceInfo(
            policy=pol.from_dsl("OutOf(1, 'Org1MSP.peer')")))
        ch = peer.join_channel(CHANNEL, prov)
        try:
            # 1. package + install over RPC
            raw = ccpackage.package_ccaas(
                "kv_1", f"127.0.0.1:{cc_server.port}"
            )
            cli = RpcClient("127.0.0.1", peer.port)
            await cli.connect()
            res = json.loads(await cli.unary("InstallChaincode", raw))
            assert res["status"] == 200
            pid = res["package_id"]
            assert pid == ccpackage.package_id("kv_1", raw)
            listed = json.loads(await cli.unary("QueryInstalled", b"{}"))
            assert listed["installed"] == [
                {"package_id": pid, "label": "kv_1"}
            ]

            # 2. committed definition + this org's approval binding the
            # package id (the lifecycle tx flow, compressed to its
            # committed state)
            cd = ChaincodeDefinition(name=CC, sequence=1)
            b = UpdateBatch()
            b.put(LIFECYCLE_NS, definition_key(CC), cd.to_bytes(), (2, 0))
            b.put(
                LIFECYCLE_NS, approval_key(CC, 1, "Org1MSP"),
                json.dumps({"package_id": pid}, sort_keys=True).encode(),
                (2, 0),
            )
            ch.ledger.state.apply_updates(b, (2, 0))

            # 3. invoke: the runtime resolves CC → installed package →
            # ccaas endpoint, with no register() call anywhere
            assert not peer.runtime.registered(CC)
            signed, _, _ = txa.create_signed_proposal(
                client, CHANNEL, CC, [b"put", b"k", b"v"]
            )
            raw_resp = await cli.unary(
                "Endorse", signed.SerializeToString()
            )
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw_resp)
            assert pr.response.status == 200, pr.response.message
            # resolution cached PER (channel, name), never globally
            assert (CHANNEL, CC) in peer.runtime._resolved
            assert not peer.runtime.registered(CC)
            # an upgrade (lifecycle write) drops the resolved binding
            b2 = UpdateBatch()
            b2.put(LIFECYCLE_NS, "namespaces/fields/other/Definition",
                   b"{}", (3, 0))
            peer.runtime.invalidate_resolved()
            assert (CHANNEL, CC) not in peer.runtime._resolved
            await cli.close()
        finally:
            await peer.stop()
            await cc_server.stop()

    run(scenario())


def test_install_admission(tmp_path):
    """The install surface's admission layers: the size cap rejects
    oversized packages before any parsing, and with
    ``install_require_admin`` only an admin-signed request envelope
    reaches the package store."""
    from fabric_tpu.comm.rpc import RpcClient
    from fabric_tpu.peer.node import PeerNode

    async def scenario():
        org = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                     peers=1, users=1)
        org2 = cryptogen.generate_org("Org2MSP", "org2.example.com",
                                      peers=1, users=0)
        mgr = MSPManager({"Org1MSP": org.msp(), "Org2MSP": org2.msp()})
        peer = PeerNode(
            "p0", str(tmp_path / "p0"), mgr,
            cryptogen.signing_identity(org, "peer0.org1.example.com"),
            ChaincodeRuntime(),
            max_package_size=16384,
            install_require_admin=True,
        )
        await peer.start()
        cli = RpcClient("127.0.0.1", peer.port)
        await cli.connect()
        try:
            raw = ccpackage.package_ccaas("kv_1", "127.0.0.1:9")

            def envelope(signer, pkg=None):
                pkg = raw if pkg is None else pkg
                return json.dumps({
                    "package": pkg.hex(),
                    "identity": signer.serialized.hex(),
                    "signature": signer.sign(pkg).hex(),
                }).encode()

            admin = cryptogen.signing_identity(
                org, "Admin@org1.example.com"
            )

            # a wire blob past the generous envelope bound: rejected
            # before any parsing
            res = json.loads(await cli.unary(
                "InstallChaincode", b"\x00" * (2 * 16384 + 65536 + 1)
            ))
            assert res["status"] == 413
            assert "install request too large" in res["message"]

            # an ADMIN-SIGNED envelope whose decoded package exceeds
            # the cap: auth passes, the size cap still rejects it
            res = json.loads(await cli.unary(
                "InstallChaincode",
                envelope(admin, pkg=raw + b"\x00" * 32768),
            ))
            assert res["status"] == 413
            assert "16384" in res["message"]

            # raw package bytes without the signed envelope: denied
            res = json.loads(await cli.unary("InstallChaincode", raw))
            assert res["status"] == 403

            # a valid org CLIENT is not an admin: denied
            user = cryptogen.signing_identity(org, "User1@org1.example.com")
            res = json.loads(await cli.unary(
                "InstallChaincode", envelope(user)
            ))
            assert res["status"] == 403
            assert "not an admin" in res["message"]

            # an ADMIN of a DIFFERENT channel org: denied — install
            # is the peer's LOCAL org admin surface
            org2_admin = cryptogen.signing_identity(
                org2, "Admin@org2.example.com"
            )
            res = json.loads(await cli.unary(
                "InstallChaincode", envelope(org2_admin)
            ))
            assert res["status"] == 403
            assert "not this peer's org" in res["message"]

            # admin envelope with a signature over DIFFERENT bytes: denied
            bad = json.loads(envelope(admin))
            bad["signature"] = admin.sign(b"something else").hex()
            res = json.loads(await cli.unary(
                "InstallChaincode", json.dumps(bad).encode()
            ))
            assert res["status"] == 403

            # the real thing: admin-signed → installed
            res = json.loads(await cli.unary(
                "InstallChaincode", envelope(admin)
            ))
            assert res["status"] == 200
            assert res["package_id"] == ccpackage.package_id("kv_1", raw)
            assert peer.packages.get(res["package_id"]) == raw

            # nothing from the denied attempts leaked into the store
            assert len(peer.packages.list()) == 1
        finally:
            await cli.close()
            await peer.stop()

    run(scenario())

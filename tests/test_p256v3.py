"""RNS ECDSA kernel (ops.rns + ops.p256v3) verification tests.

Oracle layers mirror tests/test_p256v2.py:
1. field core — tests/test_rns.py;
2. RCB complete point formulas over RNS vs crypto.ec_ref point ops,
   including the degenerate lanes (doubling, inverses, infinity);
3. full verify_batch vs the reference accept set
   (bccsp/sw/ecdsa.go:41-58 semantics: low-S, ranges, on-curve).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import p256v3 as v3
from fabric_tpu.ops import rns

P = ec_ref.P


def _pt_rv(points):
    """affine points (or None for ∞) → Montgomery projective RV triple."""
    xs, ys, zs = [], [], []
    for pt in points:
        if pt is None:
            xs.append(0); ys.append(rns.M_A % P); zs.append(0)
        else:
            xs.append(pt[0] * rns.M_A % P)
            ys.append(pt[1] * rns.M_A % P)
            zs.append(rns.M_A % P)
    return tuple(
        rns.RV(jnp.asarray(rns.ints_to_rns(v)), v3._BND_STATE)
        for v in (xs, ys, zs)
    )


def _affine(rv_triple):
    """RV projective triple → affine ints (or None for ∞) via CRT."""
    ctx = rns.ctx_for(P)
    out = []
    coords = [
        [v % P for v in rns.rv_to_ints(rns.from_mont(c, ctx).arr)]
        for c in rv_triple
    ]
    for x, y, z in zip(*coords):
        if z == 0:
            out.append(None)
        else:
            zi = pow(z, -1, P)
            out.append((x * zi % P, y * zi % P))
    return out


def test_rcb_complete_add_and_double(rng):
    """Complete addition handles: generic, doubling (P=Q), inverse
    (P=-Q → ∞), ∞ operands — all in one batch, no branches."""
    ctx = rns.ctx_for(P)
    b_m = v3._const_rv(v3.B_COEF * rns.M_A % P)
    G = (v3.GX, v3.GY)
    k2G = ec_ref.pt_mul(2, G)
    k3G = ec_ref.pt_mul(3, G)
    negG = (v3.GX, P - v3.GY)
    p1 = [G, G, G, None, k2G]
    p2 = [k2G, G, negG, k3G, None]
    want = [k3G, k2G, None, k3G, k2G]
    out = v3.pt_add(_pt_rv(p1), _pt_rv(p2), b_m, ctx)
    assert _affine(out) == want

    dbl = v3.pt_double(_pt_rv([G, k2G, None, k3G]), b_m, ctx)
    assert _affine(dbl) == [k2G, ec_ref.pt_mul(4, G), None, ec_ref.pt_mul(6, G)]


def test_rcb_mixed_add(rng):
    ctx = rns.ctx_for(P)
    b_m = v3._const_rv(v3.B_COEF * rns.M_A % P)
    G = (v3.GX, v3.GY)
    k2G = ec_ref.pt_mul(2, G)
    p1 = _pt_rv([k2G, None, G])
    # affine P2 = G for every lane (Montgomery residues)
    gx = rns.RV(jnp.asarray(rns.ints_to_rns([v3.GX * rns.M_A % P] * 3)), P)
    gy = rns.RV(jnp.asarray(rns.ints_to_rns([v3.GY * rns.M_A % P] * 3)), P)
    out = v3.pt_add_mixed(p1, gx, gy, b_m, ctx)
    assert _affine(out) == [ec_ref.pt_mul(3, G), G, k2G]


@pytest.fixture(scope="module")
def keys():
    return [ec_ref.SigningKey.generate() for _ in range(3)]


def test_verify_accepts_valid_and_rejects_adversarial(keys, rng):
    items, want = [], []
    for i in range(12):
        k = keys[i % 3]
        e = ec_ref.digest_int(b"payload-%d" % i)
        r, s = k.sign_digest(e)
        items.append((e, r, s, *k.public))
        want.append(True)
    e = ec_ref.digest_int(b"hs")
    r, s = keys[0].sign_digest(e)
    adversarial = [
        (ec_ref.digest_int(b"other"), r, s, *keys[0].public),  # wrong digest
        (e, r, ec_ref.N - s, *keys[0].public),                 # high-S
        (e, 0, s, *keys[0].public),                            # r = 0
        (e, r, 0, *keys[0].public),                            # s = 0
        (e, ec_ref.N, s, *keys[0].public),                     # r = n
        (e, s, r, *keys[0].public),                            # swapped
        (e, r, s, keys[0].public[0] + 1, keys[0].public[1]),   # off-curve Q
        (e, r, s, *keys[1].public),                            # wrong key
        (e, r, s, 0, 0),                                       # Q = ∞ encoding
    ]
    items += adversarial
    want += [False] * len(adversarial)
    got = v3.verify_host(items)
    assert got == want
    for (ei, ri, si, xi, yi), g in zip(items, got):
        assert g == ec_ref.verify_digest((xi, yi), ei, ri, si)


def test_verify_matches_oracle_randomized(keys, rng):
    items = []
    for i in range(48):
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        kind = i % 6
        if kind == 1:
            r = (r + int(rng.integers(0, 3))) % ec_ref.N
        elif kind == 2:
            s = (s + int(rng.integers(0, 3))) % ec_ref.N
        elif kind == 3:
            e = (e + int(rng.integers(0, 2))) % (1 << 256)
        items.append((e, r, s, *k.public))
    got = v3.verify_host(items)
    want = [ec_ref.verify_digest((x, y), e, r, s) for (e, r, s, x, y) in items]
    assert got == want
    assert any(want) and not all(want)


def test_chunked_launch_matches_monolithic(keys, rng):
    """Microbatched dispatch (verify_launch chunk=...) must reproduce
    the monolithic accept set bit for bit, with item i at device index
    i of the concatenated output — both for exact-multiple and ragged
    tails, and for chunk ≥ batch (degrades to one launch)."""
    items = []
    for i in range(41):  # ragged vs chunk=16: 16 + 16 + 9-lane tail
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        if i % 5 == 1:
            s = ec_ref.N - s  # high-S reject lane
        elif i % 5 == 3:
            e = (e + 1) % (1 << 256)  # wrong digest
        items.append((e, r, s, *k.public))
    mono = v3.verify_launch(items)()
    assert any(mono) and not all(mono)
    for chunk in (16, 32, 64):
        got = v3.verify_launch(items, chunk=chunk)()
        assert got == mono, f"chunk={chunk}"
    # exact multiple of the chunk (no padded tail)
    assert v3.verify_launch(items[:32], chunk=16)() == mono[:32]
    # chunk below MIN_BUCKET clamps instead of exploding into
    # per-signature launches
    assert v3.verify_launch(items, chunk=1)() == mono


def test_coalesced_launch_matches_per_block(keys, rng):
    """Multi-block launch coalescing (verify_launch_many) must be
    accept-set-equivalent to independent per-block launches — item i of
    block b at device index off_b + i, empty blocks inert — and stay
    equivalent when composed with chunk microbatching and with mesh
    sharding (conftest's 8 forced host devices)."""
    from fabric_tpu.parallel import mesh as pmesh

    def mk(n, tag):
        out = []
        for i in range(n):
            k = keys[i % 3]
            e = ec_ref.digest_int(b"%s-%d" % (tag, i))
            r, s = k.sign_digest(e)
            if i % 3 == 2:
                s = ec_ref.N - s  # reject lane
            out.append((e, r, s, *k.public))
        return out

    blocks = [mk(5, b"a"), [], mk(9, b"b"), mk(3, b"c")]
    solo = [v3.verify_launch(b)() for b in blocks]
    assert any(any(s) for s in solo) and not all(all(s) for s in solo if s)

    co = [h() for h in v3.verify_launch_many(blocks)]
    assert co == solo
    # composes with chunk microbatching (the coalesced batch chunks
    # like any other; per-block slices unchanged)
    assert [h() for h in v3.verify_launch_many(blocks, chunk=16)] == solo
    # composes with mesh sharding over the forced host devices
    mesh = pmesh.resolve_mesh(2)
    assert [h() for h in v3.verify_launch_many(blocks, mesh=mesh)] == solo
    # degenerate inputs: all-empty, and a single live block (falls back
    # to a solo launch, no concatenation)
    empty = v3.verify_launch_many([[], []])
    assert [h() for h in empty] == [[], []]
    one = v3.verify_launch_many([[], mk(5, b"a")])
    assert [h() for h in one] == [[], solo[0]]


def test_batch_inv_and_windows(rng):
    ss = [int.from_bytes(rng.bytes(32), "big") % ec_ref.N or 1 for _ in range(33)]
    inv = v3._batch_inv_mod_n(ss)
    for s, si in zip(ss, inv):
        assert s * si % ec_ref.N == 1
    us = [0, 1, 15, 16, (1 << 256) - 1] + [
        int.from_bytes(rng.bytes(32), "big") for _ in range(5)
    ]
    w = v3._windows(us)
    for u, row in zip(us, w):
        back = 0
        for d in row:
            back = (back << 4) | int(d)
        assert back == u


def test_device_recode_matches_host_windows(rng):
    """Recode-on-device bit-equality: the [B, 64] window digits the
    stage-1 kernel derives from 16-bit scalar limbs must equal the
    host ``_windows`` output for random scalars AND the edge cases
    (0, 1, n−1, high-bit-set, all-ones) — the wire-form inverse
    (``windows_to_limbs``) must round-trip too."""
    us = [0, 1, ec_ref.N - 1, 1 << 255, (1 << 256) - 1, 15, 16] + [
        int.from_bytes(rng.bytes(32), "big") for _ in range(25)
    ]
    host = v3._windows(us)
    limbs = v3._limbs16(us)
    assert limbs.dtype == np.int16 and limbs.shape == (len(us), 16)
    dev = np.asarray(v3.device_recode_windows(jnp.asarray(limbs)))
    assert np.array_equal(dev, host)
    # the native ec_prepare path packs C-computed digits into limbs:
    # digits → limbs → device digits must be the identity
    assert np.array_equal(v3.windows_to_limbs(host), limbs)
    # empty batch degenerates cleanly
    assert v3._limbs16([]).shape == (0, 16)
    assert v3.windows_to_limbs(np.zeros((0, 64), np.int32)).shape == (0, 16)


def test_recode_device_launch_matches_host(keys, rng):
    """verify_launch(recode_device=True) — the packed limb wire form +
    on-device recoding — must reproduce the host-recoded accept set
    bit for bit, with adversarial lanes load-bearing, and compose with
    chunking and coalescing."""
    items = []
    for i in range(16):
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        if i % 4 == 1:
            s = ec_ref.N - s  # high-S reject lane
        elif i % 4 == 3:
            e = (e + 1) % (1 << 256)  # wrong digest
        items.append((e, r, s, *k.public))
    base = v3.verify_launch(items)()
    assert any(base) and not all(base)
    assert v3.verify_launch(items, recode_device=True)() == base
    # prepared columns carry limbs, and the packed frame is smaller
    n, cols = v3._to_cols(items)
    args = v3.prepare_cols(*cols, pad_to=16, recode_device=True)
    assert args[4].shape == (16, 16) and args[4].dtype == np.int16
    assert v3._PKL_COLS < v3._PK_COLS
    # composes with coalescing (per-block slices unchanged)
    many = v3.verify_launch_many([items[:7], items[7:]],
                                 recode_device=True)
    assert many[0]() + many[1]() == base


def test_pooled_prepare_cols_matches_serial(keys, rng):
    """Host-pool-sharded staging must be BIT-equal to serial staging:
    all eight prepare_cols outputs identical (admission flags, batch
    inversion, window planes — host digits and device limbs alike —
    residues, padding lanes), and the pooled launch's accept set
    identical through the kernel."""
    from fabric_tpu.parallel.hostpool import HostStagePool

    items = []
    for i in range(100):
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        if i % 3 == 2:
            s = ec_ref.N - s
        items.append((e, r, s, *k.public))
    n, cols = v3._to_cols(items)
    with HostStagePool(2) as pool:
        # shard boundaries land at MIN_BUCKET multiples
        bounds = pool.slice_bounds(100, align=v3.MIN_BUCKET)
        assert len(bounds) == 2 and bounds[0][1] % v3.MIN_BUCKET == 0
        for recode in (False, True):
            serial = v3.prepare_cols(*cols, pad_to=128,
                                     recode_device=recode)
            pooled = v3._prepare_cols_pooled(cols, 128, pool,
                                             recode_device=recode)
            for i, (a, b) in enumerate(zip(serial, pooled)):
                a, b = np.asarray(a), np.asarray(b)
                assert a.dtype == b.dtype and np.array_equal(a, b), i
        # and through the kernel on a warm bucket-16 shape
        base = v3.verify_launch(items[:16])()
        assert v3.verify_launch(items[:16], pool=pool)() == base
        assert v3.verify_launch(items[:16], pool=pool,
                                recode_device=True)() == base


def test_prepare_cols_out_views_match_alloc(keys, rng):
    """``prepare_cols(out=...)`` — the pooled workers' direct-slab
    write path (no allocate-then-copy) — must be BIT-equal to the
    allocating form for host digits and device limbs alike, with every
    destination element written (slabs prefilled with garbage) and the
    pad tail zeroed.  ``bytes_to_rns(out=)`` rides the same path."""
    items = []
    for i in range(48):
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        if i % 4 == 1:
            s = ec_ref.N - s  # high-S reject lane
        if i % 11 == 0:
            r = ec_ref.N + 5  # out-of-range r
        items.append((e, r, s, *k.public))
    n, cols = v3._to_cols(items)
    pad = v3._bucket(n)
    assert pad > n  # the pad-tail zeroing is load-bearing here
    R = 2 * rns.N_CH
    for recode in (False, True):
        base = v3.prepare_cols(*cols, pad_to=pad, recode_device=recode)
        wcols = v3._PK_LIMBS if recode else v3.STEPS
        wdt = np.int16 if recode else np.int32
        out = (
            np.full((pad, R), 7, np.int32),
            np.full((pad, R), 7, np.int32),
            np.full((pad, R), 7, np.int32),
            np.full((pad, R), 7, np.int32),
            np.full((pad, wcols), 7, wdt),
            np.full((pad, wcols), 7, wdt),
            np.ones(pad, bool),
            np.ones(pad, bool),
        )
        got = v3.prepare_cols(*cols, pad_to=pad, recode_device=recode,
                              out=out)
        assert got is out
        for i, (a, b) in enumerate(zip(base, out)):
            a = np.asarray(a)
            assert a.dtype == b.dtype and np.array_equal(a, b), (recode, i)
        # row-slab views (what _prepare_cols_pooled hands workers):
        # stage [16:48) of fresh slabs in place, compare the rows
        slab = tuple(np.full_like(np.asarray(a), 3) for a in base)
        v3.prepare_cols(*(c[16:48] for c in cols), recode_device=recode,
                        out=tuple(d[16:48] for d in slab))
        for i, (a, b) in enumerate(zip(base, slab)):
            assert np.array_equal(np.asarray(a)[16:48], b[16:48]), (recode, i)

    # bytes_to_rns(out=) ≡ allocating form
    r_b = cols[1]
    dst = np.full((len(r_b), R), 9, np.int32)
    assert rns.bytes_to_rns(r_b, out=dst) is dst
    assert np.array_equal(dst, rns.bytes_to_rns(r_b))
    empty = np.zeros((0, R), np.int32)
    assert rns.bytes_to_rns(r_b[:0], out=empty) is empty

    # the mismatched-size guard fails loudly, not with silent wraps
    with pytest.raises(ValueError):
        v3.prepare_cols(*cols, pad_to=pad, out=tuple(a[:8] for a in out))


def test_prepare_cols_packed_matches_two_phase(keys, rng):
    """The single-pass packed staging (``prepare_cols_packed`` — the
    serial sig_prepare host-cycle eliminator: native STRIDED int16
    window/limb writes straight into the launch frame, one residue
    scratch, no intermediate eight-array staging) must be BYTE-equal
    to ``pack_cols(prepare_cols(...))`` / ``pack_cols_limbs(...)`` for
    host digits and device limbs alike — admission flags, reject
    lanes, out-of-range r, pad tail and all — with ``out=`` frame
    reuse over prefilled garbage, and identical through the kernel."""
    items = []
    for i in range(41):
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        if i % 4 == 1:
            s = ec_ref.N - s  # high-S reject lane
        if i % 13 == 0:
            r = ec_ref.N + 5  # out-of-range r
        items.append((e, r, s, *k.public))
    n, cols = v3._to_cols(items)
    pad = v3._bucket(n)
    assert pad > n  # pad-tail zeroing is load-bearing
    for recode in (False, True):
        args = v3.prepare_cols(*cols, pad_to=pad, recode_device=recode)
        two_phase = (v3.pack_cols_limbs(*args) if recode
                     else v3.pack_cols(*args))
        packed = v3.prepare_cols_packed(*cols, pad_to=pad,
                                        recode_device=recode)
        assert packed.dtype == np.int16
        assert np.array_equal(two_phase, packed), recode
        # out= reuse over garbage: every element rewritten or zeroed
        buf = np.full(packed.shape, 77, np.int16)
        got = v3.prepare_cols_packed(*cols, pad_to=pad,
                                     recode_device=recode, out=buf)
        assert got is buf and np.array_equal(buf, two_phase)
        # mis-shaped out fails loudly
        with pytest.raises(ValueError):
            v3.prepare_cols_packed(*cols, pad_to=pad,
                                   recode_device=recode,
                                   out=buf[:, :-1].copy())
    # empty batch: an all-zero (all-rejected) frame
    empty = v3.prepare_cols_packed(*(c[:0] for c in cols), pad_to=16)
    assert empty.shape == (16, v3._PK_COLS) and not empty.any()
    # and the kernel sees the same accept set either way (the serial
    # launch path now stages through prepare_cols_packed)
    base = [
        ec_ref.verify_digest((qx, qy), e, r, s)
        for (e, r, s, qx, qy) in items[:16]
    ]
    assert v3.verify_launch(items[:16])() == base


def test_prepare_cols_native_matches_python():
    """The native ec_prepare (batch inversion + window recoding +
    admission flags in C) must be bit-exact with the Python prepare
    path across valid, high-S, out-of-range and degenerate rows."""
    import numpy as np

    import fabric_tpu.native as nat
    from fabric_tpu.crypto import ec_ref
    from fabric_tpu.ops import p256v3

    keys = [ec_ref.SigningKey.generate() for _ in range(3)]
    items = []
    for i in range(41):
        k = keys[i % 3]
        e = ec_ref.digest_int(b"m%d" % i)
        r, s = k.sign_digest(e)
        if i % 7 == 0:
            s = ec_ref.N - s  # high-S: must reject
        if i % 11 == 0:
            r = ec_ref.N + 5  # out-of-range r
        if i % 13 == 0:
            s = 0
        if i % 17 == 0:
            r = ec_ref.P - ec_ref.N + 3  # rpn_ok boundary region
        items.append((e, r, s, *k.public))
    items.append((5, 0, 1, 0, 0))
    c = p256v3.SigCollector()
    for it in items:
        c.add_slow(it)
    cols = p256v3._assemble_cols(c)
    pad = p256v3._bucket(len(items))
    a_native = p256v3.prepare_cols(*cols, pad_to=pad)
    if nat.ecprep_lib() is None:
        import pytest

        pytest.skip("no native toolchain")
    nat._lib_failed.add("ecprep")
    nat._libs.pop("ecprep", None)
    try:
        a_python = p256v3.prepare_cols(*cols, pad_to=pad)
    finally:
        nat._lib_failed.discard("ecprep")
    for x, y, name in zip(
        a_native, a_python,
        ["qx", "qy", "r", "rpn", "w1", "w2", "rpn_ok", "pre_ok"],
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y)), name


def test_sigcollector_mixed_fast_slow_rows():
    """Interleaved fast (byte-array) and slow (tuple) rows through the
    collector must verify identically to the all-tuple path."""
    import numpy as np

    from fabric_tpu.crypto import ec_ref
    from fabric_tpu.ops import p256v3

    class _FakeIdent:
        def __init__(self, pub):
            self.public_numbers = pub

        @property
        def rns_pub(self):
            from fabric_tpu.ops import rns

            res = rns.ints_to_rns(list(self.public_numbers))
            return res[0], res[1]

    keys = [ec_ref.SigningKey.generate() for _ in range(2)]
    items = []
    for i in range(9):
        k = keys[i % 2]
        e = ec_ref.digest_int(b"x%d" % i)
        r, s = k.sign_digest(e)
        if i == 4:
            s = ec_ref.N - s  # invalid lane
        items.append((e, r, s, *k.public))
    n = len(items)
    d_arr = np.stack([
        np.frombuffer(int(e).to_bytes(32, "big"), np.uint8)
        for (e, r, s, qx, qy) in items
    ])
    r_arr = np.stack([
        np.frombuffer(int(r).to_bytes(32, "big"), np.uint8)
        for (e, r, s, qx, qy) in items
    ])
    s_arr = np.stack([
        np.frombuffer(int(s).to_bytes(32, "big"), np.uint8)
        for (e, r, s, qx, qy) in items
    ])
    c = p256v3.SigCollector()
    for i, it in enumerate(items):
        if i % 3 == 0:
            c.add_slow(it)
        else:
            c.add_fast((d_arr, r_arr, s_arr), i, _FakeIdent(it[3:]))
    got = p256v3.verify_launch(c)()
    want = p256v3.verify_host(items)
    assert got == want
    assert c.tuples() == items


def test_sigcollector_oversized_r_rejected():
    """A slow-row r or s ≥ 2^256 must be rejected, not wrapped — the
    column path truncating mod 2^256 would WIDEN the accept set vs the
    legacy int path (consensus divergence)."""
    from fabric_tpu.crypto import ec_ref
    from fabric_tpu.ops import p256v3

    k = ec_ref.SigningKey.generate()
    e = ec_ref.digest_int(b"oversize")
    r, s = k.sign_digest(e)
    bad = [
        (e, r + (1 << 256), s, *k.public),
        (e, r, s + (1 << 256), *k.public),
        (e, r, s, *k.public),  # control: valid
    ]
    c = p256v3.SigCollector()
    for it in bad:
        c.add_slow(it)
    got = p256v3.verify_launch(c)()
    assert got == [False, False, True]
    assert p256v3.verify_host(bad[:2]) == [False, False]

"""Channel configuration tests: bundle construction, implicit-meta
policy evaluation, config-update authorization, and config-tx
validation on the commit path (reference: common/channelconfig,
common/policies/implicitmeta.go, common/configtx/update.go,
v20/validator.go:397-419)."""

import pytest

from fabric_tpu import channelconfig as cc
from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.protos import common_pb2, configtx_pb2, policies_pb2, transaction_pb2
from fabric_tpu.tools import configtxgen as cg

C = transaction_pb2.TxValidationCode
CHANNEL = "confchan"


@pytest.fixture(scope="module")
def orgs():
    return [
        cryptogen.generate_org(f"Org{i}MSP", f"org{i}.example.com", peers=1)
        for i in (1, 2, 3)
    ]


@pytest.fixture(scope="module")
def profile(orgs):
    return cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(o.msp_id, o.msp()) for o in orgs],
    )


@pytest.fixture(scope="module")
def bundle(profile):
    return cc.Bundle(CHANNEL, cg.genesis_config(profile))


def _admin(org):
    return cryptogen.signing_identity(org, f"Admin@{org.domain}")


def _signed(signer, msg: bytes) -> cc.SignedData:
    return cc.SignedData(
        identity=signer.serialized, data=msg, signature=signer.sign(msg)
    )


def test_bundle_surface(bundle, orgs):
    assert bundle.application_orgs() == ["Org1MSP", "Org2MSP", "Org3MSP"]
    assert cc.CAP_V2_0 in bundle.application_capabilities()
    assert cc.CAP_V2_0 in bundle.channel_capabilities()
    endorsement = bundle.application_policy("Endorsement")
    assert isinstance(endorsement, cc.ImplicitMeta)
    # the MSPs inside the bundle can deserialize + validate org identities
    ident = bundle.msp_manager.deserialize_identity(_admin(orgs[0]).serialized)
    assert ident.is_valid and ident.role == "admin"


def test_implicit_meta_majority(bundle, orgs):
    msg = b"payload-to-sign"
    admins = [_admin(o) for o in orgs]
    two = [_signed(s, msg) for s in admins[:2]]
    one = [_signed(admins[0], msg)]
    three = [_signed(s, msg) for s in admins]
    # /Channel/Application/Admins is MAJORITY(Admins) over 3 orgs → need 2
    assert bundle.policy_manager.evaluate("/Channel/Application/Admins", two)
    assert bundle.policy_manager.evaluate("/Channel/Application/Admins", three)
    assert not bundle.policy_manager.evaluate("/Channel/Application/Admins", one)
    # ANY(Writers): one member suffices
    assert bundle.policy_manager.evaluate("/Channel/Application/Writers", one)
    # a repeated signature does not double-count toward MAJORITY
    dup = [two[0], two[0]]
    assert not bundle.policy_manager.evaluate("/Channel/Application/Admins", dup)


def test_implicit_meta_rejects_bad_signature(bundle, orgs):
    msg = b"payload"
    sd = _signed(_admin(orgs[0]), msg)
    bad = cc.SignedData(sd.identity, msg, sd.signature[:-2] + b"\x00\x00")
    assert not bundle.policy_manager.evaluate("/Channel/Application/Writers", [bad])


def _updated_config(profile, bundle):
    """Flip Org1's Endorsement policy to admin-only (a realistic
    policy-rotation update)."""
    new = configtx_pb2.Config()
    new.CopyFrom(bundle.config)
    org1 = new.channel_group.groups["Application"].groups["Org1MSP"]
    org1.policies["Endorsement"].CopyFrom(
        cc.config_policy(pol.SignedBy(pol.Principal("Org1MSP", pol.ROLE_ADMIN)))
    )
    return new


def test_config_update_flow(profile, bundle, orgs):
    new = _updated_config(profile, bundle)
    upd = cg.compute_update(CHANNEL, bundle.config, new)
    # modified element: Org1MSP Endorsement policy (mod_policy Admins →
    # Org1 admin alone controls its own org group)
    signed = cg.sign_update(upd, [_admin(orgs[0])])
    got = cc.authorize_update(bundle, signed)
    assert got.sequence == bundle.sequence + 1
    after = cc.Bundle(CHANNEL, got)
    assert isinstance(
        after.policy_manager.get("/Channel/Application/Org1MSP/Endorsement")[0],
        pol.SignedBy,
    )

    # unsigned: rejected
    unsigned = cg.sign_update(upd, [])
    with pytest.raises(cc.ConfigUpdateError):
        cc.authorize_update(bundle, unsigned)

    # wrong org's admin: rejected (mod_policy resolves to Org1 Admins)
    wrong = cg.sign_update(upd, [_admin(orgs[1])])
    with pytest.raises(cc.ConfigUpdateError):
        cc.authorize_update(bundle, wrong)


def test_config_update_version_discipline(bundle, orgs):
    new = _updated_config(None, bundle)
    upd = cg.compute_update(CHANNEL, bundle.config, new)
    # tamper: claim a version jump
    wr = upd.write_set.groups["Application"].groups["Org1MSP"]
    wr.policies["Endorsement"].version = 7
    signed = cg.sign_update(upd, [_admin(orgs[0])])
    with pytest.raises(cc.ConfigUpdateError):
        cc.authorize_update(bundle, signed)


def test_config_tx_processor(bundle, orgs):
    proc = cc.ConfigTxProcessor(bundle)
    new = _updated_config(None, bundle)
    upd = cg.compute_update(CHANNEL, bundle.config, new)
    new_applied = cc.authorize_update(bundle, cg.sign_update(upd, [_admin(orgs[0])]))
    env = cg.config_tx(
        CHANNEL, new_applied, cg.sign_update(upd, [_admin(orgs[0])]),
        signer=_admin(orgs[0]),
    )
    payload = pu.unmarshal(common_pb2.Payload, env.payload)
    cfg_env = pu.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
    assert proc.validate_config_tx(None, cfg_env) == C.VALID

    # a config whose content does not match its authorized update: rejected
    forged = configtx_pb2.ConfigEnvelope()
    forged.CopyFrom(cfg_env)
    forged.config.channel_group.values["Capabilities"].value = b"\x01"
    assert proc.validate_config_tx(None, forged) != C.VALID

    # apply rotates the bundle and bumps the sequence
    seen = []
    proc.listeners.append(lambda b: seen.append(b.sequence))
    proc.apply(cfg_env)
    assert proc.bundle.sequence == 1 and seen == [1]


def test_config_update_deletion(profile, bundle, orgs):
    """Removing an org: the write set bumps the parent group and lists
    exact surviving membership; apply deletes the org and the deletion
    is gated on the parent's mod_policy (MAJORITY Admins)."""
    new = configtx_pb2.Config()
    new.CopyFrom(bundle.config)
    del new.channel_group.groups["Application"].groups["Org3MSP"]
    upd = cg.compute_update(CHANNEL, bundle.config, new)
    admins = [_admin(o) for o in orgs]

    # one admin is not a majority of /Channel/Application/Admins
    with pytest.raises(cc.ConfigUpdateError):
        cc.authorize_update(bundle, cg.sign_update(upd, [admins[0]]))

    got = cc.authorize_update(bundle, cg.sign_update(upd, admins[:2]))
    after = cc.Bundle(CHANNEL, got)
    assert after.application_orgs() == ["Org1MSP", "Org2MSP"]
    # surviving orgs' policies still resolve
    assert after.policy_manager.get("/Channel/Application/Org1MSP/Admins")

"""Operator tooling: configtxlator (proto↔JSON, config deltas) and the
offline node ops verbs (reset / rollback / unjoin / rebuild-dbs) —
reference: internal/configtxlator/update, internal/peer/node/*.go."""

import json
import os

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import SqliteVersionedDB, UpdateBatch
from fabric_tpu.protos import common_pb2, configtx_pb2
from fabric_tpu.tools import configtxlator as ctl
from fabric_tpu.tools import configtxgen as cg
from fabric_tpu.tools import nodeops
from fabric_tpu.tools.ledgerutil import verify_ledger


@pytest.fixture(scope="module")
def config_bytes():
    org = cryptogen.generate_org("Org1MSP", "org1.tools.example.com")
    profile = cg.Profile(
        "toolschan",
        application_orgs=[cg.OrgProfile(org.msp_id, org.msp())],
    )
    return cg.genesis_config(profile).SerializeToString()


def test_proto_json_roundtrip(config_bytes):
    js = ctl.proto_decode("common.Config", config_bytes)
    assert '"channel_group"' in js
    back = ctl.proto_encode("common.Config", js)
    a = configtx_pb2.Config()
    a.ParseFromString(config_bytes)
    b = configtx_pb2.Config()
    b.ParseFromString(back)
    assert a == b  # message-level equality (map order may differ)
    with pytest.raises(ValueError, match="unknown message type"):
        ctl.proto_decode("no.Such", b"")


def test_compute_update_delta(config_bytes):
    cur = configtx_pb2.Config()
    cur.ParseFromString(config_bytes)
    new = configtx_pb2.Config()
    new.ParseFromString(config_bytes)
    # bump the orderer batch size
    from fabric_tpu.protos import orderer_pb2

    ordg = new.channel_group.groups["Orderer"]
    bs = orderer_pb2.BatchSize()
    bs.ParseFromString(ordg.values["BatchSize"].value)
    bs.max_message_count = 999
    ordg.values["BatchSize"].value = bs.SerializeToString()

    delta = ctl.compute_update(
        "toolschan", config_bytes, new.SerializeToString()
    )
    upd = configtx_pb2.ConfigUpdate()
    upd.ParseFromString(delta)
    assert upd.channel_id == "toolschan"
    assert "Orderer" in upd.write_set.groups
    assert "BatchSize" in upd.write_set.groups["Orderer"].values
    # the touched group's ancestry is pinned in the read set
    assert "Orderer" in upd.read_set.groups


def _mk_ledger(path, n_blocks=5):
    lg = KVLedger(path, state_db=SqliteVersionedDB(
        os.path.join(path, "state.db")))
    prev = b""
    for n in range(n_blocks):
        blk = pu.new_block(n, prev)
        blk.data.data.append(b"")
        blk = pu.finalize_block(blk)
        batch = UpdateBatch()
        batch.put("ns", f"k{n}", b"v%d" % n, (n, 0))
        lg.commit_block(blk, bytes([254]), batch, [])
        prev = pu.block_header_hash(blk.header)
    lg.close()


def test_rollback_reset_unjoin(tmp_path):
    chan_dir = str(tmp_path / "mychan")
    _mk_ledger(chan_dir, n_blocks=5)

    # rollback to block 2: chain truncates, derived DBs dropped
    res = nodeops.rollback(chan_dir, 2)
    assert res["truncated"]
    assert not os.path.exists(os.path.join(chan_dir, "state.db"))
    lg = KVLedger(chan_dir, state_db=SqliteVersionedDB(
        os.path.join(chan_dir, "state.db")))
    assert lg.blocks.height == 3
    # recovery machinery replays derived state from the kept blocks
    replayed = lg.recover(lambda blk: (
        bytes([254]),
        (lambda b: (b.put("ns", f"k{blk.header.number}",
                          b"v%d" % blk.header.number,
                          (blk.header.number, 0)), b)[1])(UpdateBatch()),
        [],
    ))
    assert replayed == 3
    assert lg.state.get_state("ns", "k2").value == b"v2"
    assert lg.state.get_state("ns", "k4") is None
    lg.close()
    v = verify_ledger(chan_dir)
    assert v.ok and v.height == 3

    # reset: blocks stay, derived DBs dropped
    res = nodeops.reset(chan_dir)
    assert "state.db" in res["dropped"]
    v = verify_ledger(chan_dir)
    assert v.ok and v.height == 3

    # unjoin removes the channel wholesale
    nodeops.unjoin(chan_dir)
    assert not os.path.exists(chan_dir)
    with pytest.raises(FileNotFoundError):
        nodeops.unjoin(chan_dir)

"""nwo-style integration: REAL processes launched via the fabric-tpu
CLI — cryptogen → configtxgen → orderer + ccaas chaincode + 2 peers →
gateway invoke/query → discovery → ledgerutil verify (the
integration/nwo harness pattern: declarative network, real binaries,
localhost ports)."""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHANNEL = "clichan"
CC = "clicc"


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cli_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
    env["PYTHONPATH"] = REPO
    return env


def _cli(*args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "fabric_tpu.cli", *args],
        cwd=REPO, env=_cli_env(), capture_output=True, text=True,
        timeout=kw.pop("timeout", 120), **kw,
    )


def _spawn(*args):
    return subprocess.Popen(
        [sys.executable, "-m", "fabric_tpu.cli", *args],
        cwd=REPO, env=_cli_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def _wait_port(port, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port), 1)
            s.close()
            return True
        except OSError:
            time.sleep(0.3)
    return False


@pytest.mark.slow
def test_cli_network(tmp_path):
    crypto = str(tmp_path / "crypto")
    res = _cli("cryptogen", "--org", "Org1MSP:org1.example.com",
               "--org", "Org2MSP:org2.example.com",
               "--org", "OrdererMSP:ord.example.com",
               "--orderers", "1", "--output", crypto)
    assert res.returncode == 0, res.stderr
    org1 = f"{crypto}/org1.example.com"
    org2 = f"{crypto}/org2.example.com"
    ordorg = f"{crypto}/ord.example.com"

    # one trusted TLS-CA bundle across the network: every listener
    # demands a client cert and every dial presents one (mutual TLS)
    ca_bundle = str(tmp_path / "tls-ca-bundle.pem")
    with open(ca_bundle, "wb") as bf:
        for od in (org1, org2, ordorg):
            with open(f"{od}/tlsca/tlsca-cert.pem", "rb") as cf:
                bf.write(cf.read())

    def tls_cfg(org_dir, node):
        tdir = f"{org_dir}/nodes/{node}/tls"
        return {"cert": f"{tdir}/server.pem", "key": f"{tdir}/key.pem",
                "ca": ca_bundle}

    profile = {
        "channel": CHANNEL,
        "application_orgs": [
            {"msp_id": "Org1MSP", "dir": org1},
            {"msp_id": "Org2MSP", "dir": org2},
        ],
        # orderer org in the genesis config: peers verify every
        # delivered block's signature against BlockValidation
        "orderer_orgs": [{"msp_id": "OrdererMSP", "dir": ordorg}],
        "max_message_count": 1, "batch_timeout_ms": 100,
    }
    prof_path = str(tmp_path / "profile.json")
    with open(prof_path, "w") as f:
        json.dump(profile, f)
    genesis = str(tmp_path / "genesis.block")
    res = _cli("configtxgen", "--profile", prof_path, "--output", genesis)
    assert res.returncode == 0, res.stderr

    cc_port = _free_port()
    ord_port = _free_port()
    p1_port, p2_port = _free_port(), _free_port()
    ops_port = _free_port()

    ord_cfg = {
        "id": "o0", "data_dir": str(tmp_path / "o0"), "port": ord_port,
        "cluster": {"o0": ["127.0.0.1", ord_port]},
        "max_message_count": 1, "batch_timeout_s": 0.1,
        "msp_id": "OrdererMSP",
        "msp_dir": f"{ordorg}/nodes/orderer0.ord.example.com/msp",
        "tls": tls_cfg(ordorg, "orderer0.ord.example.com"),
        "channels": [{"name": CHANNEL, "genesis": genesis}],
    }

    def peer_cfg(pid, port, org_dir, msp_id, other_port, other_msp):
        return {
            "id": pid, "data_dir": str(tmp_path / pid), "port": port,
            "msp_id": msp_id,
            "msp_dir": f"{org_dir}/nodes/peer0.{os.path.basename(org_dir)}/msp",
            "tls": tls_cfg(org_dir, f"peer0.{os.path.basename(org_dir)}"),
            "org_msps": [org1, org2],
            # NO static chaincode registration: the peers must resolve
            # CC from the INSTALLED package bound by their org's
            # approval (the install/package flow under test)
            "peers": [{"msp_id": other_msp, "host": "127.0.0.1",
                       "port": other_port}],
            "channels": [{
                "name": CHANNEL, "genesis": genesis,
                "orderers": [["127.0.0.1", ord_port]],
            }],
            "operations_port": ops_port if pid == "p1" else None,
        }

    cfgs = {
        "orderer": ord_cfg,
        "p1": peer_cfg("p1", p1_port, org1, "Org1MSP", p2_port, "Org2MSP"),
        "p2": peer_cfg("p2", p2_port, org2, "Org2MSP", p1_port, "Org1MSP"),
    }
    for name, cfg in cfgs.items():
        with open(tmp_path / f"{name}.json", "w") as f:
            json.dump(cfg, f)

    procs = []
    try:
        procs.append(_spawn("chaincode", "--name", CC, "--port", str(cc_port)))
        procs.append(_spawn("orderer", "--config", str(tmp_path / "orderer.json")))
        assert _wait_port(cc_port) and _wait_port(ord_port)
        procs.append(_spawn("peer", "--config", str(tmp_path / "p1.json")))
        procs.append(_spawn("peer", "--config", str(tmp_path / "p2.json")))
        assert _wait_port(p1_port) and _wait_port(p2_port)

        user_msp = f"{org1}/users/User1@org1.example.com/msp"
        cli_tls = ("--tls-ca", ca_bundle,
                   "--tls-cert",
                   f"{org1}/nodes/peer0.org1.example.com/tls/server.pem",
                   "--tls-key",
                   f"{org1}/nodes/peer0.org1.example.com/tls/key.pem")

        # chaincode package + install on BOTH peers (package.go /
        # install.go): the approve step then binds the package id
        pkg_path = str(tmp_path / "kv.tgz")
        res = _cli("ccpackage", "--label", "kv_1",
                   "--address", f"127.0.0.1:{cc_port}",
                   "--output", pkg_path)
        assert res.returncode == 0, res.stdout + res.stderr
        pkg_id = json.loads(res.stdout.strip().splitlines()[-1])["package_id"]
        for pp in (p1_port, p2_port):
            res = _cli(*cli_tls, "ccinstall", "--port", str(pp),
                       "--package", pkg_path)
            assert res.returncode == 0, res.stdout + res.stderr
            out = json.loads(res.stdout.strip().splitlines()[-1])
            assert out["status"] == 200 and out["package_id"] == pkg_id
        res = _cli(*cli_tls, "ccqueryinstalled", "--port", str(p1_port))
        assert res.returncode == 0, res.stdout + res.stderr
        assert json.loads(res.stdout.strip().splitlines()[-1])[
            "installed"] == [{"package_id": pkg_id, "label": "kv_1"}]

        # chaincode lifecycle: approve from EACH org (binding the
        # installed package id), then commit — the reference's
        # approve/commit flow driven through the gateway
        spec = json.dumps({"policy": {"ref": "Endorsement"},
                           "package_id": pkg_id})
        for msp_id, org_dir in (("Org1MSP", org1), ("Org2MSP", org2)):
            u = f"{org_dir}/users/User1@{os.path.basename(org_dir)}/msp"
            res = _cli(
                *cli_tls, "invoke", "--port", str(p1_port), "--channel", CHANNEL,
                "--chaincode", "_lifecycle", "--msp-dir", u,
                "--msp-id", msp_id, "approve", CC, "1", spec, timeout=600,
            )
            assert res.returncode == 0, res.stdout + res.stderr
            assert json.loads(res.stdout.strip().splitlines()[-1])["code"] == 0
        res = _cli(
            *cli_tls, "invoke", "--port", str(p1_port), "--channel", CHANNEL,
            "--chaincode", "_lifecycle", "--msp-dir", user_msp,
            "--msp-id", "Org1MSP", "commit", CC, "1", spec, timeout=300,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        assert json.loads(res.stdout.strip().splitlines()[-1])["code"] == 0

        # invoke through the gateway CLI (endorse across BOTH orgs per
        # the committed definition's Endorsement-ref policy)
        res = _cli(
            *cli_tls, "invoke", "--port", str(p1_port), "--channel", CHANNEL,
            "--chaincode", CC, "--msp-dir", user_msp, "--msp-id", "Org1MSP",
            "put", "city", "lucerne", timeout=600,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert out["code_name"] == "VALID", out

        res = _cli(
            *cli_tls, "query", "--port", str(p2_port), "--channel", CHANNEL,
            "--chaincode", CC, "--msp-dir", user_msp, "--msp-id", "Org1MSP",
            "get", "city", timeout=300,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        out = json.loads(res.stdout.strip().splitlines()[-1])
        assert out["payload"] == "lucerne", out

        res = _cli(*cli_tls, "discover", "--port", str(p1_port),
                   "--channel", CHANNEL,
                   "--query", "endorsers", "--chaincode", CC)
        desc = json.loads(res.stdout.strip().splitlines()[-1])
        assert desc["status"] == 200
        assert {"Org1MSP": 1, "Org2MSP": 1} in desc["descriptor"]["layouts"]

        # operations surface of a real peer process
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{ops_port}/healthz", timeout=5
        ) as r:
            assert json.loads(r.read())["status"] == "OK"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ops_port}/metrics", timeout=5
        ) as r:
            assert b"ledger_blockchain_height" in r.read()
    finally:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()

    # offline forensics on the stopped peers' ledgers
    res = _cli("ledgerutil", "verify", str(tmp_path / "p1" / CHANNEL))
    assert res.returncode == 0, res.stdout + res.stderr
    assert json.loads(res.stdout)["ok"]
    res = _cli("ledgerutil", "compare",
               str(tmp_path / "p1" / CHANNEL), str(tmp_path / "p2" / CHANNEL))
    assert res.returncode == 0, res.stdout
    assert json.loads(res.stdout)["identical"]

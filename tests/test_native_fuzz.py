"""Randomized fuzz over the native C++ wire parsers.

blockparse.cpp and mvccprep.cpp hand-roll protobuf walking with
pointer arithmetic on the adversarial input path (any orderer or peer
can send a block).  The reference leans on memory-safe Go + `-race`
across its suite; the C++ fast path needs the equivalent posture:

1. **No crash**: thousands of random mutations (bit flips, truncation,
   splices, random chunks, duplications) over valid envelopes must
   never kill the process — the parser either handles the envelope or
   hands it to the Python lane.
2. **Fallback equivalence**: whatever the native parser ACCEPTS must
   produce the exact TRANSACTIONS_FILTER / update batch the pure-
   Python path produces — a mutation the fast lane mis-parses instead
   of rejecting is a consensus fork between peers built with and
   without the toolchain.
"""

import random

import pytest

import fabric_tpu.native as nat
from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.validator import (
    BlockValidator, NamespaceInfo, PolicyProvider,
)

CHANNEL, CC = "fuzzchan", "fuzzcc"
N_TX = 16  # the native parser's minimum block size


@pytest.fixture(scope="module")
def net():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                  peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
    client = cryptogen.signing_identity(org1, "User1@org1.example.com")
    peers = [
        cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    ]
    envs = []
    for i in range(N_TX):
        _, _, prop = txa.create_signed_proposal(
            client, CHANNEL, CC, [b"invoke", b"%d" % i]
        )
        tx = TxRWSet()
        n = tx.ns_rwset(CC)
        n.reads[f"seed{i}"] = (1, i)
        n.writes[f"w{i}"] = b"value-%d" % i
        rw = tx.to_proto().SerializeToString()
        resps = [
            txa.create_proposal_response(prop, rw, e, CC) for e in peers
        ]
        envs.append(
            txa.assemble_transaction(prop, resps, client).SerializeToString()
        )
    prov = PolicyProvider({CC: NamespaceInfo(policy=pol.from_dsl(
        "OutOf(2, 'Org1MSP.peer', 'Org2MSP.peer')"))})
    return {"mgr": mgr, "prov": prov, "envs": envs, "client": client}


def _seed_state():
    db = MemVersionedDB()
    b = UpdateBatch()
    for i in range(N_TX):
        b.put(CC, f"seed{i}", b"v", (1, i))
    db.apply_updates(b, (1, 0))
    return db


def _mutate(rng: random.Random, raw: bytes) -> bytes:
    """One random structural mutation."""
    if not raw:
        return raw
    op = rng.randrange(6)
    b = bytearray(raw)
    if op == 0:  # flip 1-4 random bytes
        for _ in range(rng.randrange(1, 5)):
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        return bytes(b)
    if op == 1:  # truncate
        return bytes(b[: rng.randrange(len(b))])
    if op == 2:  # splice a random slice of itself somewhere else
        i, j = sorted(rng.randrange(len(b)) for _ in range(2))
        k = rng.randrange(len(b))
        return bytes(b[:k] + b[i:j] + b[k:])
    if op == 3:  # overwrite a chunk with random bytes
        k = rng.randrange(len(b))
        n = min(len(b) - k, rng.randrange(1, 64))
        b[k:k + n] = bytes(rng.getrandbits(8) for _ in range(n))
        return bytes(b)
    if op == 4:  # duplicate a chunk (length fields now lie)
        i, j = sorted(rng.randrange(len(b)) for _ in range(2))
        return bytes(b[:j] + b[i:j] + b[j:])
    return b""  # empty envelope


def _mutated_block(rng, envs, num=2):
    envs = list(envs)
    for _ in range(rng.randrange(1, 4)):  # mutate 1-3 envelopes
        i = rng.randrange(len(envs))
        envs[i] = _mutate(rng, envs[i])
    blk = pu.new_block(num, b"prev")
    for e in envs:
        blk.data.data.append(e)
    return pu.finalize_block(blk)


def test_fuzz_blockparse_mvccprep_no_crash(net):
    """10k mutated blocks through the native pre-parse + rwset prep:
    the process must survive every one (reject → Python lane is fine;
    a segfault is not)."""
    from fabric_tpu.native import blockparse as nbp
    from fabric_tpu.native import mvccprep_py

    if nat.blockparse_lib() is None:
        pytest.skip("no native toolchain")
    import numpy as np

    rng = random.Random(0xFAB)
    base = net["envs"]
    for it in range(10_000):
        envs = list(base)
        i = rng.randrange(len(envs))
        envs[i] = _mutate(rng, envs[i])
        if it % 7 == 0:  # sometimes mutate several
            j = rng.randrange(len(envs))
            envs[j] = _mutate(rng, envs[j])
        out = nbp.parse_envelopes(envs)
        if out is None:
            continue
        if it % 5 == 0 and out.ok.any():
            rwp = mvccprep_py.prep(out, np.ascontiguousarray(out.ok))
            if rwp is not None:
                # outputs must stay within their declared bounds —
                # garbage counts/statuses are the pre-segfault smell
                assert set(np.unique(rwp.status)) <= {0, 1, 2}
                assert 0 <= rwp.n_reads <= len(rwp.r_uid)
                assert 0 <= rwp.n_writes <= len(rwp.w_uid)
                assert 0 <= rwp.n_keys <= len(rwp.ukey_span)


def test_fuzz_native_python_verdict_equivalence(net):
    """Mutated blocks validated WITH the native fast lane and with it
    force-disabled must produce identical filters, update batches, and
    history — the fallback-equivalence contract
    (tests/test_native_parse.py pins targeted cases; this sweeps
    randomized ones)."""
    if nat.blockparse_lib() is None:
        pytest.skip("no native toolchain")
    rng = random.Random(0xC0FFEE)
    mismatches = []
    for it in range(300):
        blk = _mutated_block(rng, net["envs"], num=2 + it)

        v_nat = BlockValidator(net["mgr"], net["prov"], _seed_state())
        flt_n, batch_n, hist_n = v_nat.validate(blk)

        nat._lib_failed.add("blockparse")
        nat._libs.pop("blockparse", None)
        try:
            v_py = BlockValidator(net["mgr"], net["prov"], _seed_state())
            flt_p, batch_p, hist_p = v_py.validate(blk)
        finally:
            nat._lib_failed.discard("blockparse")

        def rows(b):
            return sorted(
                (k, vv.value, vv.metadata, vv.version)
                for k, vv in b.updates.items()
            )

        if (bytes(flt_n) != bytes(flt_p)
                or rows(batch_n) != rows(batch_p)
                or hist_n != hist_p):
            diff = [
                (i, a, b)
                for i, (a, b) in enumerate(zip(flt_n, flt_p)) if a != b
            ]
            # persist the repro for offline analysis
            with open(f"/tmp/fuzz_mismatch_{it}.bin", "wb") as f:
                f.write(blk.SerializeToString())
            mismatches.append((it, diff, rows(batch_n) == rows(batch_p),
                               hist_n == hist_p))
    assert not mismatches, mismatches[:3]


def test_duplicate_action_submessage_agrees(net):
    """upb MERGES duplicate singular submessages (endorsements
    concatenate across two `action` occurrences); last-occurrence
    extraction cannot replicate that, so the native lane must route
    such envelopes to Python — both lanes must agree on the verdict."""
    if nat.blockparse_lib() is None:
        pytest.skip("no native toolchain")
    from fabric_tpu.protos import common_pb2, proposal_pb2, transaction_pb2

    def varint(n):
        out = b""
        while True:
            b = n & 0x7F
            n >>= 7
            out += bytes([b | (0x80 if n else 0)])
            if not n:
                return out

    base = net["envs"][0]
    env = pu.unmarshal(common_pb2.Envelope, base)
    payload = pu.unmarshal(common_pb2.Payload, env.payload)
    tx = pu.unmarshal(transaction_pb2.Transaction, payload.data)
    cap = pu.unmarshal(
        transaction_pb2.ChaincodeActionPayload, tx.actions[0].payload
    )
    # split the two endorsements across TWO action occurrences
    cea1 = transaction_pb2.ChaincodeEndorsedAction()
    cea1.endorsements.add().CopyFrom(cap.action.endorsements[0])
    cea2 = transaction_pb2.ChaincodeEndorsedAction()
    cea2.proposal_response_payload = cap.action.proposal_response_payload
    cea2.endorsements.add().CopyFrom(cap.action.endorsements[1])
    b1, b2 = cea1.SerializeToString(), cea2.SerializeToString()
    wire = (b"\x0a" + varint(len(cap.chaincode_proposal_payload))
            + cap.chaincode_proposal_payload
            + b"\x12" + varint(len(b1)) + b1
            + b"\x12" + varint(len(b2)) + b2)
    # sanity: upb merges the endorsements back together
    merged = transaction_pb2.ChaincodeActionPayload()
    merged.ParseFromString(wire)
    assert len(merged.action.endorsements) == 2
    tx.actions[0].payload = wire
    payload.data = tx.SerializeToString()
    env2 = pu.sign_envelope(payload, net["client"])
    blk = pu.new_block(2, b"prev")
    blk.data.data.append(env2.SerializeToString())
    for e in net["envs"][1:]:
        blk.data.data.append(e)
    blk = pu.finalize_block(blk)

    v_nat = BlockValidator(net["mgr"], net["prov"], _seed_state())
    flt_n, _, _ = v_nat.validate(blk)
    nat._lib_failed.add("blockparse")
    nat._libs.pop("blockparse", None)
    try:
        v_py = BlockValidator(net["mgr"], net["prov"], _seed_state())
        flt_p, _, _ = v_py.validate(blk)
    finally:
        nat._lib_failed.discard("blockparse")
    assert bytes(flt_n) == bytes(flt_p)
    # and the merged-endorsement tx is VALID under the 2-of-2 policy
    assert flt_n[0] == 0, list(flt_n)

"""Gossip + private-data tests over real localhost sockets:
endorsement-time distribution into transient stores, commit-time
coordinator sourcing (transient hit AND pull path), missing-data
recording + background reconciliation, anti-entropy block transfer,
leader election (reference: gossip/privdata/{distributor,pull,
reconcile}.go, gossip/state/state.go:584, gossip/election)."""

import asyncio
import json

import pytest

from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.discovery import PeerInfo
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.node import PeerNode
from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider

CHANNEL = "pvtchan"
CC = "pvtcc"


def run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=15.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.03)
    return False


async def _mknet(tmp_path, n_peers=2):
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=2, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
    client = cryptogen.signing_identity(org1, "User1@org1.example.com")
    signers = [
        cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    ]
    orgs = ["Org1MSP", "Org2MSP"]

    orderer = OrdererNode(
        "o0", str(tmp_path / "o0"), {},
        batch_config=BatchConfig(max_message_count=1, batch_timeout_s=0.1),
    )
    await orderer.start()
    orderer.cluster["o0"] = ("127.0.0.1", orderer.port)
    orderer.join_channel(CHANNEL)

    policy = pol.from_dsl("OutOf(1, 'Org1MSP.peer', 'Org2MSP.peer')")
    peers = []
    for i in range(n_peers):
        rt = ChaincodeRuntime()
        rt.register(CC, KVContract())
        node = PeerNode(f"p{i}", str(tmp_path / f"p{i}"), mgr, signers[i], rt)
        await node.start()
        # collA spans both orgs; collPriv is Org1-only (the eligibility
        # filter under test); undefined collections disseminate nowhere.
        # max_peer_count must be ≥ required_peer_count (the reference
        # validates this) and 0 means NO endorsement-time push —
        # reconciliation-only (distributor maximumPeerCount contract)
        prov = PolicyProvider({CC: NamespaceInfo(policy=policy, collections={
            "collA": {"member_orgs": ["Org1MSP", "Org2MSP"],
                      "required_peer_count": 1, "max_peer_count": 2,
                      "btl": 0},
            "collB": {"member_orgs": ["Org1MSP", "Org2MSP"],
                      "required_peer_count": 0, "max_peer_count": 2,
                      "btl": 0},
            "collPriv": {"member_orgs": ["Org1MSP"],
                         "required_peer_count": 0, "max_peer_count": 2,
                         "btl": 0},
            # pull-only lane: eligible members but max_peer_count 0 —
            # eager push must SKIP it entirely
            "collPullOnly": {"member_orgs": ["Org1MSP", "Org2MSP"],
                             "required_peer_count": 0, "max_peer_count": 0,
                             "btl": 0},
        })})
        ch = node.join_channel(CHANNEL, prov)
        peers.append(node)
    for i, node in enumerate(peers):
        for j, other in enumerate(peers):
            if i != j:
                node.registry.add(
                    PeerInfo(orgs[j % 2], "127.0.0.1", other.port)
                )
    return orderer, peers, client


def test_pvt_distribution_and_pull(tmp_path):
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p1.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.channels[CHANNEL].validator.warmup()

            # endorse ONLY on p0 with transient value; p0 distributes
            # to p1's transient store at endorsement time
            from fabric_tpu.comm.rpc import RpcClient

            signed, tx_id, prop = txa.create_signed_proposal(
                client, CHANNEL, CC, [b"put_private", b"collA", b"secret-key"],
                transient={"value": b"secret-value"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            await cli.close()
            from fabric_tpu.protos import proposal_pb2

            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            assert pr.response.status == 200, pr.response.message

            # distribution reached p1's transient store
            assert await _wait(lambda: bool(
                p1.channels[CHANNEL].transient.get(tx_id)
            ))

            env = txa.assemble_transaction(prop, [pr], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            res = await bc.broadcast(CHANNEL, env.SerializeToString())
            assert res["status"] == 200
            await bc.close()

            # BOTH peers commit the cleartext into pvt state
            def committed(p):
                vv = p.channels[CHANNEL].ledger.state.get_state(
                    f"{CC}$collA", "secret-key"
                )
                return vv is not None and vv.value == b"secret-value"

            assert await _wait(lambda: committed(p0) and committed(p1), 20)
            # hashed state matches on both, cleartext never hit the rwset
            import hashlib

            kh = hashlib.sha256(b"secret-key").digest().hex()
            for p in (p0, p1):
                hv = p.channels[CHANNEL].ledger.state.get_state(
                    f"{CC}$collA#hashed", kh
                )
                assert hv is not None
                assert hv.value == hashlib.sha256(b"secret-value").digest()

            # pull-only collection (max_peer_count 0): endorsement-time
            # push must SKIP it — p1's transient store stays empty for
            # this txid; the data still arrives post-commit via the
            # reconciler (reconciliation-only delivery)
            signed2, tx_id2, prop2 = txa.create_signed_proposal(
                client, CHANNEL, CC,
                [b"put_private", b"collPullOnly", b"po-key"],
                transient={"value": b"po-value"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed2.SerializeToString())
            await cli.close()
            pr2 = proposal_pb2.ProposalResponse()
            pr2.ParseFromString(raw)
            assert pr2.response.status == 200, pr2.response.message
            await asyncio.sleep(1.0)  # window an eager push would use
            assert not p1.channels[CHANNEL].transient.get(tx_id2)

            env2 = txa.assemble_transaction(prop2, [pr2], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            res = await bc.broadcast(CHANNEL, env2.SerializeToString())
            assert res["status"] == 200
            await bc.close()

            def committed_po(p):
                vv = p.channels[CHANNEL].ledger.state.get_state(
                    f"{CC}$collPullOnly", "po-key"
                )
                return vv is not None and vv.value == b"po-value"

            assert await _wait(lambda: committed_po(p0), 20)
            assert await _wait(lambda: committed_po(p1), 25)
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_missing_then_reconcile(tmp_path):
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            # p1 gets NO distribution and cannot pull at commit time
            # (puller disabled) → records missing, then the reconciler
            # catches up once pulling is re-enabled
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p1.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.gossip_service._clients.clear()
            p0.registry.peers.clear()  # no distribution targets

            async def no_pull(*a):
                return None

            real_puller = p1.channels[CHANNEL].pvt_puller
            p1.channels[CHANNEL].pvt_puller = no_pull

            from fabric_tpu.comm.rpc import RpcClient
            from fabric_tpu.protos import proposal_pb2

            signed, tx_id, prop = txa.create_signed_proposal(
                client, CHANNEL, CC, [b"put_private", b"collB", b"k2"],
                transient={"value": b"v2"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            await cli.close()
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            assert pr.response.status == 200

            env = txa.assemble_transaction(prop, [pr], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            assert (await bc.broadcast(CHANNEL, env.SerializeToString()))["status"] == 200
            await bc.close()

            ch1 = p1.channels[CHANNEL]
            assert await _wait(lambda: ch1.height >= 1, 20)
            assert await _wait(
                lambda: bool(ch1.ledger.pvtdata.missing_data(ch1.height)), 10
            )
            assert ch1.ledger.state.get_state(f"{CC}$collB", "k2") is None

            # re-enable pulling and run the reconciler
            ch1.pvt_puller = real_puller
            p1.gossip_service.start_reconciler(CHANNEL, interval=0.2)
            assert await _wait(
                lambda: not ch1.ledger.pvtdata.missing_data(ch1.height), 15
            )
            vv = ch1.ledger.state.get_state(f"{CC}$collB", "k2")
            assert vv is not None and vv.value == b"v2"
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_anti_entropy_catchup(tmp_path):
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            # only p0 talks to the orderer (org leader); p1 relies on
            # anti-entropy pulls from p0
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.channels[CHANNEL].validator.warmup()
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            for i in range(3):
                signed, tx_id, prop = txa.create_signed_proposal(
                    client, CHANNEL, CC, [b"put", b"k%d" % i, b"v%d" % i]
                )
                from fabric_tpu.comm.rpc import RpcClient
                from fabric_tpu.protos import proposal_pb2

                cli = RpcClient("127.0.0.1", p0.port)
                await cli.connect()
                raw = await cli.unary("Endorse", signed.SerializeToString())
                await cli.close()
                pr = proposal_pb2.ProposalResponse()
                pr.ParseFromString(raw)
                env = txa.assemble_transaction(prop, [pr], client)
                assert (await bc.broadcast(CHANNEL, env.SerializeToString()))["status"] == 200
            await bc.close()
            assert await _wait(lambda: p0.channels[CHANNEL].height >= 3, 20)

            assert p1.channels[CHANNEL].height == 0
            p1.gossip_service.start_anti_entropy(CHANNEL, interval=0.2)
            assert await _wait(lambda: p1.channels[CHANNEL].height >= 3, 20)
            c0, c1 = p0.channels[CHANNEL], p1.channels[CHANNEL]
            for k in range(3):
                assert (c0.ledger.blocks.get_block(k).SerializeToString()
                        == c1.ledger.blocks.get_block(k).SerializeToString())

            # leader election: deterministic lowest endpoint
            gs = p0.gossip_service
            me = ("127.0.0.1", p0.port)
            others = [PeerInfo("Org1MSP", "127.0.0.1", p1.port, height=3)]
            assert gs.elect_leader(others, me) == (me < ("127.0.0.1", p1.port))
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_non_member_org_never_holds_cleartext(tmp_path):
    """collPriv is Org1-only: endorsement-time distribution must skip
    Org2's peer, a push targeting it must be refused, and a pull by an
    Org2 identity must be denied — collection confidentiality
    (distributor.go AccessFilter; ADVICE r3 high)."""
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers  # p0 = Org1 peer, p1 = Org2 peer
        try:
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p1.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.channels[CHANNEL].validator.warmup()

            from fabric_tpu.comm.rpc import RpcClient
            from fabric_tpu.protos import proposal_pb2

            signed, tx_id, prop = txa.create_signed_proposal(
                client, CHANNEL, CC,
                [b"put_private", b"collPriv", b"top-secret"],
                transient={"value": b"classified"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            assert pr.response.status == 200

            # p0 (member) holds the cleartext; p1 (non-member) must not
            assert p0.channels[CHANNEL].transient.get(tx_id)
            await asyncio.sleep(0.5)  # give any (wrong) push time to land
            assert not p1.channels[CHANNEL].transient.get(tx_id)

            # a direct PvtPush of collPriv data at p1 is refused
            import json as _json

            push = _json.dumps({
                "channel": CHANNEL, "txid": tx_id, "height": 0,
                "data": {f"{CC}\x00collPriv": {"top-secret": b"x".hex()}},
            }).encode()
            cli1 = RpcClient("127.0.0.1", p1.port)
            await cli1.connect()
            res = _json.loads(await cli1.unary("PvtPush", push))
            assert res["status"] == 403
            assert not p1.channels[CHANNEL].transient.get(tx_id)

            # commit the tx; p0 gets the pvt state, p1 records missing
            # and CANNOT reconcile it (its pulls are denied by org)
            env = txa.assemble_transaction(prop, [pr], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            assert (await bc.broadcast(
                CHANNEL, env.SerializeToString()))["status"] == 200
            await bc.close()
            assert await _wait(
                lambda: p0.channels[CHANNEL].height >= 1
                and p1.channels[CHANNEL].height >= 1, 20)
            vv = p0.channels[CHANNEL].ledger.state.get_state(
                f"{CC}$collPriv", "top-secret")
            assert vv is not None and vv.value == b"classified"
            assert p1.channels[CHANNEL].ledger.state.get_state(
                f"{CC}$collPriv", "top-secret") is None

            # p1's signed pull is refused by p0 (org not a member)
            pull = p1.gossip_service.pull_pvt_for(CHANNEL)
            got = await pull(tx_id, 0, 0, CC, "collPriv")
            assert got is None
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_btl_expiry_purges_state_and_store(tmp_path):
    """block_to_live: pvt data (store rows + cleartext state + hashed
    state) is purged once its BTL elapses (pvtstatepurgemgmt +
    pvtdatastorage expiry)."""
    import hashlib

    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            # tighten collA to btl=1: data expires 1 block after commit
            for p in peers:
                prov = p.channels[CHANNEL].validator.policies
                prov.infos[CC].collections["collA"]["btl"] = 1
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.channels[CHANNEL].validator.warmup()

            from fabric_tpu.comm.rpc import RpcClient
            from fabric_tpu.protos import proposal_pb2

            signed, tx_id, prop = txa.create_signed_proposal(
                client, CHANNEL, CC, [b"put_private", b"collA", b"ttl-key"],
                transient={"value": b"ephemeral"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            assert pr.response.status == 200
            env = txa.assemble_transaction(prop, [pr], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            assert (await bc.broadcast(
                CHANNEL, env.SerializeToString()))["status"] == 200

            ch0 = p0.channels[CHANNEL]
            assert await _wait(lambda: ch0.height >= 1, 20)
            blk_n = ch0.height - 1
            assert ch0.ledger.state.get_state(
                f"{CC}$collA", "ttl-key") is not None
            assert ch0.ledger.pvtdata.get_pvt_data(blk_n)

            # drive 3 more (public) blocks past the BTL horizon
            # (expiringBlk = committingBlk + btl + 1: data committed at
            # block 1 with btl=1 expires when block 3 commits)
            for i in range(3):
                s2, t2, prop2 = txa.create_signed_proposal(
                    client, CHANNEL, CC, [b"put", f"pub{i}".encode(), b"v"]
                )
                cli2 = RpcClient("127.0.0.1", p0.port)
                await cli2.connect()
                raw2 = await cli2.unary("Endorse", s2.SerializeToString())
                await cli2.close()
                pr2 = proposal_pb2.ProposalResponse()
                pr2.ParseFromString(raw2)
                assert pr2.response.status == 200, pr2.response.message
                env2 = txa.assemble_transaction(prop2, [pr2], client)
                assert (await bc.broadcast(
                    CHANNEL, env2.SerializeToString()))["status"] == 200
            await bc.close()
            assert await _wait(lambda: ch0.height >= 4, 20)

            # expired: store row gone, cleartext state gone, hash gone
            assert not ch0.ledger.pvtdata.get_pvt_data(blk_n)
            assert ch0.ledger.state.get_state(
                f"{CC}$collA", "ttl-key") is None
            kh = hashlib.sha256(b"ttl-key").hexdigest()
            assert ch0.ledger.state.get_state(
                f"{CC}$collA#hashed", kh) is None
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_dead_peer_excluded_from_election(tmp_path):
    """A peer whose probe failed must not win the org-leader election
    (liveness, gossip/discovery alive/dead expiration; ADVICE r3)."""
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            gs = p0.gossip_service
            # register a bogus (dead) lowest-endpoint peer in p0's org
            dead = PeerInfo("Org1MSP", "127.0.0.1", 1)
            p0.registry.add(dead)
            me = ("127.0.0.1", p0.port)
            org_peers = p0.registry.peers.get("Org1MSP", [])
            # before any probe the dead peer still counts (alive=None)
            assert not gs.elect_leader(org_peers, me)
            await gs.probe_members()
            assert dead.alive is False
            # after the failed probe it is excluded → we win
            assert gs.elect_leader(org_peers, me)
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())

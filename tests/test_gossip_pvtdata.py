"""Gossip + private-data tests over real localhost sockets:
endorsement-time distribution into transient stores, commit-time
coordinator sourcing (transient hit AND pull path), missing-data
recording + background reconciliation, anti-entropy block transfer,
leader election (reference: gossip/privdata/{distributor,pull,
reconcile}.go, gossip/state/state.go:584, gossip/election)."""

import asyncio
import json

import pytest

from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.discovery import PeerInfo
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.node import PeerNode
from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider

CHANNEL = "pvtchan"
CC = "pvtcc"


def run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=15.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.03)
    return False


async def _mknet(tmp_path, n_peers=2):
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=2, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
    client = cryptogen.signing_identity(org1, "User1@org1.example.com")
    signers = [
        cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    ]
    orgs = ["Org1MSP", "Org2MSP"]

    orderer = OrdererNode(
        "o0", str(tmp_path / "o0"), {},
        batch_config=BatchConfig(max_message_count=1, batch_timeout_s=0.1),
    )
    await orderer.start()
    orderer.cluster["o0"] = ("127.0.0.1", orderer.port)
    orderer.join_channel(CHANNEL)

    policy = pol.from_dsl("OutOf(1, 'Org1MSP.peer', 'Org2MSP.peer')")
    peers = []
    for i in range(n_peers):
        rt = ChaincodeRuntime()
        rt.register(CC, KVContract())
        node = PeerNode(f"p{i}", str(tmp_path / f"p{i}"), mgr, signers[i], rt)
        await node.start()
        prov = PolicyProvider({CC: NamespaceInfo(policy=policy)})
        ch = node.join_channel(CHANNEL, prov)
        peers.append(node)
    for i, node in enumerate(peers):
        for j, other in enumerate(peers):
            if i != j:
                node.registry.add(
                    PeerInfo(orgs[j % 2], "127.0.0.1", other.port)
                )
    return orderer, peers, client


def test_pvt_distribution_and_pull(tmp_path):
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p1.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.channels[CHANNEL].validator.warmup()

            # endorse ONLY on p0 with transient value; p0 distributes
            # to p1's transient store at endorsement time
            from fabric_tpu.comm.rpc import RpcClient

            signed, tx_id, prop = txa.create_signed_proposal(
                client, CHANNEL, CC, [b"put_private", b"collA", b"secret-key"],
                transient={"value": b"secret-value"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            await cli.close()
            from fabric_tpu.protos import proposal_pb2

            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            assert pr.response.status == 200, pr.response.message

            # distribution reached p1's transient store
            assert await _wait(lambda: bool(
                p1.channels[CHANNEL].transient.get(tx_id)
            ))

            env = txa.assemble_transaction(prop, [pr], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            res = await bc.broadcast(CHANNEL, env.SerializeToString())
            assert res["status"] == 200
            await bc.close()

            # BOTH peers commit the cleartext into pvt state
            def committed(p):
                vv = p.channels[CHANNEL].ledger.state.get_state(
                    f"{CC}$collA", "secret-key"
                )
                return vv is not None and vv.value == b"secret-value"

            assert await _wait(lambda: committed(p0) and committed(p1), 20)
            # hashed state matches on both, cleartext never hit the rwset
            import hashlib

            kh = hashlib.sha256(b"secret-key").digest().hex()
            for p in (p0, p1):
                hv = p.channels[CHANNEL].ledger.state.get_state(
                    f"{CC}$collA#hashed", kh
                )
                assert hv is not None
                assert hv.value == hashlib.sha256(b"secret-value").digest()
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_missing_then_reconcile(tmp_path):
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            # p1 gets NO distribution and cannot pull at commit time
            # (puller disabled) → records missing, then the reconciler
            # catches up once pulling is re-enabled
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p1.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.gossip_service._clients.clear()
            p0.registry.peers.clear()  # no distribution targets

            async def no_pull(*a):
                return None

            real_puller = p1.channels[CHANNEL].pvt_puller
            p1.channels[CHANNEL].pvt_puller = no_pull

            from fabric_tpu.comm.rpc import RpcClient
            from fabric_tpu.protos import proposal_pb2

            signed, tx_id, prop = txa.create_signed_proposal(
                client, CHANNEL, CC, [b"put_private", b"collB", b"k2"],
                transient={"value": b"v2"},
            )
            cli = RpcClient("127.0.0.1", p0.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            await cli.close()
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            assert pr.response.status == 200

            env = txa.assemble_transaction(prop, [pr], client)
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            assert (await bc.broadcast(CHANNEL, env.SerializeToString()))["status"] == 200
            await bc.close()

            ch1 = p1.channels[CHANNEL]
            assert await _wait(lambda: ch1.height >= 1, 20)
            assert await _wait(
                lambda: bool(ch1.ledger.pvtdata.missing_data(ch1.height)), 10
            )
            assert ch1.ledger.state.get_state(f"{CC}$collB", "k2") is None

            # re-enable pulling and run the reconciler
            ch1.pvt_puller = real_puller
            p1.gossip_service.start_reconciler(CHANNEL, interval=0.2)
            assert await _wait(
                lambda: not ch1.ledger.pvtdata.missing_data(ch1.height), 15
            )
            vv = ch1.ledger.state.get_state(f"{CC}$collB", "k2")
            assert vv is not None and vv.value == b"v2"
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())


def test_anti_entropy_catchup(tmp_path):
    async def scenario():
        orderer, peers, client = await _mknet(tmp_path)
        p0, p1 = peers
        try:
            # only p0 talks to the orderer (org leader); p1 relies on
            # anti-entropy pulls from p0
            p0.channels[CHANNEL].start_deliver([("127.0.0.1", orderer.port)])
            p0.channels[CHANNEL].validator.warmup()
            bc = BroadcastClient([("127.0.0.1", orderer.port)])
            for i in range(3):
                signed, tx_id, prop = txa.create_signed_proposal(
                    client, CHANNEL, CC, [b"put", b"k%d" % i, b"v%d" % i]
                )
                from fabric_tpu.comm.rpc import RpcClient
                from fabric_tpu.protos import proposal_pb2

                cli = RpcClient("127.0.0.1", p0.port)
                await cli.connect()
                raw = await cli.unary("Endorse", signed.SerializeToString())
                await cli.close()
                pr = proposal_pb2.ProposalResponse()
                pr.ParseFromString(raw)
                env = txa.assemble_transaction(prop, [pr], client)
                assert (await bc.broadcast(CHANNEL, env.SerializeToString()))["status"] == 200
            await bc.close()
            assert await _wait(lambda: p0.channels[CHANNEL].height >= 3, 20)

            assert p1.channels[CHANNEL].height == 0
            p1.gossip_service.start_anti_entropy(CHANNEL, interval=0.2)
            assert await _wait(lambda: p1.channels[CHANNEL].height >= 3, 20)
            c0, c1 = p0.channels[CHANNEL], p1.channels[CHANNEL]
            for k in range(3):
                assert (c0.ledger.blocks.get_block(k).SerializeToString()
                        == c1.ledger.blocks.get_block(k).SerializeToString())

            # leader election: deterministic lowest endpoint
            gs = p0.gossip_service
            me = ("127.0.0.1", p0.port)
            others = [PeerInfo("Org1MSP", "127.0.0.1", p1.port, height=3)]
            assert gs.elect_leader(others, me) == (me < ("127.0.0.1", p1.port))
        finally:
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())

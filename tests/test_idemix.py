"""Idemix anonymous credentials (CL-RSA): blind issuance, per-message
zero-knowledge presentation proofs, unlinkability, forgery rejection,
MSP integration, and an end-to-end block with an anonymous creator
through the TPU validator (reference: msp/idemix.go + IBM/idemix;
BASELINE config #5)."""

import json

import pytest

from fabric_tpu.crypto import cryptogen, idemix
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager


@pytest.fixture(scope="module")
def setup():
    issuer = idemix.IdemixIssuer("IdemixOrgMSP", bits=1024)
    holder = idemix.IdemixHolder(issuer.ipk)
    U, proof = holder.commitment()
    A, e, v_i = issuer.issue(U, proof, ou="org1", role="client")
    cred = holder.assemble(A, e, v_i, ou="org1", role="client")
    return {"issuer": issuer, "holder": holder, "cred": cred,
            "signer": idemix.IdemixSigningIdentity(
                "IdemixOrgMSP", issuer.ipk, cred)}


def test_issue_sign_verify(setup):
    ipk, cred = setup["issuer"].ipk, setup["cred"]
    sig = idemix.sign(ipk, cred, b"hello world")
    assert idemix.verify(ipk, "org1", "client", b"hello world", sig)
    # wrong message / wrong disclosed attributes → reject
    assert not idemix.verify(ipk, "org1", "client", b"other", sig)
    assert not idemix.verify(ipk, "org2", "client", b"hello world", sig)
    assert not idemix.verify(ipk, "org1", "admin", b"hello world", sig)


def test_signatures_are_unlinkable(setup):
    ipk, cred = setup["issuer"].ipk, setup["cred"]
    s1 = json.loads(idemix.sign(ipk, cred, b"m"))
    s2 = json.loads(idemix.sign(ipk, cred, b"m"))
    # fresh randomization every time: no shared values anywhere
    assert s1["A2"] != s2["A2"]
    assert s1["s_sk"] != s2["s_sk"]
    assert s1["c"] != s2["c"]


def test_forgery_without_credential_rejected(setup):
    """A party without an issued credential cannot produce a proof,
    even knowing the issuer public key and the attribute values."""
    ipk = setup["issuer"].ipk
    rogue_holder = idemix.IdemixHolder(ipk)
    fake = idemix.Credential(
        A=pow(3, 65537, ipk.n), e=idemix._gen_prime(idemix.L_E),
        v=idemix._rand_bits(ipk.n.bit_length()),
        sk=rogue_holder.sk, ou="org1", role="client",
    )
    sig = idemix.sign(ipk, fake, b"msg")
    assert not idemix.verify(ipk, "org1", "client", b"msg", sig)
    # tampered proof bytes
    good = bytearray(idemix.sign(ipk, setup["cred"], b"msg"))
    good[20] ^= 1
    assert not idemix.verify(ipk, "org1", "client", b"msg", bytes(good))


def test_small_exponent_forgery_rejected(setup):
    """Regression: with no lower-bound range proof on e, an attacker
    knowing only the issuer public key could pick e=1, random (sk, v),
    set A2 = z_d·S^-v·R_sk^-sk (no e-th root needed when e=1) and run
    the honest Schnorr proof — a universal forgery.  The offset form
    (responses over e' = e−2^(L_E-1), verifier folds A2^(c·2^(L_E-1))
    into t_hat, tight bound on s_e) must kill it."""
    ipk = setup["issuer"].ipk
    n = ipk.n
    sk = idemix._rand_bits(idemix.L_M)
    v = idemix._rand_bits(n.bit_length())
    ou, role = "org1", "admin"   # any attributes, no credential held
    z_d = (ipk.Z * pow(ipk.R_ou, -idemix._attr_int(ou), n)
           * pow(ipk.R_role, -idemix._attr_int(role), n)) % n
    A2 = (z_d * pow(ipk.S, -v, n) * pow(ipk.R_sk, -sk, n)) % n
    # A2^1 · S^v · R_sk^sk == z_d holds; run the honest Σ-protocol
    # exactly as the pre-fix signer did (responses over e itself)
    import secrets as _secrets
    r_e = idemix._rand_bits(idemix.L_E_PRIME + idemix.L_C + idemix.L_STAT)
    r_v = idemix._rand_bits(n.bit_length() + 2 * idemix.L_STAT
                            + idemix.L_C + idemix.L_E)
    r_sk = idemix._rand_bits(idemix.L_M + idemix.L_C + idemix.L_STAT)
    t = (pow(A2, r_e, n) * pow(ipk.S, r_v, n) * pow(ipk.R_sk, r_sk, n)) % n
    nonce = _secrets.token_hex(16)
    c = idemix._fs_challenge(ipk.to_json(), A2, t, ou, role, nonce, b"msg")
    sig = json.dumps({
        "A2": hex(A2), "c": hex(c), "nonce": nonce,
        "s_e": hex(r_e + c * 1),       # e = 1
        "s_v": hex(r_v + c * v),
        "s_sk": hex(r_sk + c * sk),
    }).encode()
    assert not idemix.verify(ipk, ou, role, b"msg", sig)
    # and the signer path itself cannot launder a small-e credential:
    # sign() computes responses over e−2^(L_E-1), which for e=1 drives
    # s_e negative → rejected by the range check
    fake = idemix.Credential(A=A2, e=1, v=v, sk=sk, ou=ou, role=role)
    sig2 = idemix.sign(ipk, fake, b"msg")
    assert not idemix.verify(ipk, ou, role, b"msg", sig2)


def test_issuer_rejects_bad_commitment_proof(setup):
    issuer = setup["issuer"]
    holder = idemix.IdemixHolder(issuer.ipk)
    U, proof = holder.commitment()
    proof = dict(proof)
    proof["s_sk"] += 1
    with pytest.raises(ValueError):
        issuer.issue(U, proof, ou="org1", role="client")


def test_msp_integration(setup):
    msp = idemix.IdemixMSP("IdemixOrgMSP", setup["issuer"].ipk)
    mgr = MSPManager()
    mgr.add(msp)
    signer = setup["signer"]
    ident = mgr.deserialize_identity(signer.serialized)
    assert ident.is_valid and ident.msp_id == "IdemixOrgMSP"
    assert ident.role == "client"
    msg = b"proposal-bytes"
    assert ident.verify(msg, signer.sign(msg))
    assert not ident.verify(msg, signer.sign(b"other"))
    # principal matching: member + exact role; NO EC key for the batch
    assert pol.Principal("IdemixOrgMSP", pol.ROLE_MEMBER).matched_by(ident)
    assert pol.Principal("IdemixOrgMSP", "client").matched_by(ident)
    assert not pol.Principal("IdemixOrgMSP", "peer").matched_by(ident)
    with pytest.raises(ValueError):
        ident.public_numbers
    # config round trip (MSPConfig type 1 → Bundle._build_msps branch)
    cfg = msp.to_config()
    assert cfg.type == 1
    msp2 = idemix.IdemixMSP.from_config(cfg.config)
    assert msp2.deserialize_identity(signer.serialized).verify(
        msg, signer.sign(msg)
    )


def test_anonymous_creator_through_validator(setup, tmp_path):
    """A block whose creator is an idemix identity (X.509 endorsers, as
    the reference requires) validates on BOTH the fused device path and
    the host path; a bad anonymous signature is rejected."""
    from fabric_tpu import protoutil as pu
    from fabric_tpu.ledger.rwset import TxRWSet
    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.peer import txassembly as txa
    from fabric_tpu.peer.validator import (
        BlockValidator, NamespaceInfo, PolicyProvider,
    )
    from fabric_tpu.protos import transaction_pb2

    C = transaction_pb2.TxValidationCode
    CHANNEL, CC = "idxchan", "idxcc"

    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1)
    peer = cryptogen.signing_identity(org1, "peer0.org1.example.com")
    imsp = idemix.IdemixMSP("IdemixOrgMSP", setup["issuer"].ipk)
    mgr = MSPManager({"Org1MSP": org1.msp()})
    mgr.add(imsp)
    anon = setup["signer"]

    def tx(writes, creator, tamper=False):
        _, _, prop = txa.create_signed_proposal(creator, CHANNEL, CC, [b"i"])
        t = TxRWSet()
        for k, v in writes:
            t.ns_rwset(CC).writes[k] = v
        rw = t.to_proto().SerializeToString()
        resps = [txa.create_proposal_response(prop, rw, peer, CC)]
        env = txa.assemble_transaction(prop, resps, creator)
        if tamper:
            env.signature = env.signature[:-6] + b"\x00" * 6
        return env

    envs = [
        tx([("a", b"1")], anon),
        tx([("b", b"2")], anon, tamper=True),  # broken anonymous proof
    ]
    blk = pu.new_block(2, b"prev")
    for e in envs:
        blk.data.data.append(e.SerializeToString())
    blk = pu.finalize_block(blk)

    policy = pol.from_dsl("OutOf(1, 'Org1MSP.peer')")
    prov = PolicyProvider({CC: NamespaceInfo(policy=policy)})
    v = BlockValidator(mgr, prov, MemVersionedDB())
    flt, batch, _ = v.validate(blk)
    assert flt[0] == C.VALID
    assert flt[1] == C.BAD_CREATOR_SIGNATURE
    assert ("idxcc", "a") in batch.updates

    # force the pure-host path: verdicts identical
    v2 = BlockValidator(mgr, prov, MemVersionedDB())
    pre = v2.preprocess(blk)
    flt2, _, _ = v2._validate_host(blk, pre[0], pre[1], pre[2], fb=pre[5])
    assert list(flt2) == list(flt)


def test_anonymous_creator_native_parse_fallback(setup):
    """Blocks big enough for the native pre-parser: idemix creators
    (non-DER proofs) make the fast path bow out PER ENVELOPE and take
    the Python lane, with verdicts identical to small blocks."""
    from fabric_tpu import protoutil as pu
    from fabric_tpu.ledger.rwset import TxRWSet
    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.peer import txassembly as txa
    from fabric_tpu.peer.validator import (
        BlockValidator, NamespaceInfo, PolicyProvider,
    )
    from fabric_tpu.protos import transaction_pb2

    C = transaction_pb2.TxValidationCode
    CC = "idxcc2"
    org1 = cryptogen.generate_org("Org1MSP", "org1n.example.com", peers=1,
                                  users=1)
    peer = cryptogen.signing_identity(org1, "peer0.org1n.example.com")
    x509_client = cryptogen.signing_identity(org1, "User1@org1n.example.com")
    mgr = MSPManager({"Org1MSP": org1.msp()})
    mgr.add(idemix.IdemixMSP("IdemixOrgMSP", setup["issuer"].ipk))
    anon = setup["signer"]

    def tx(i, creator, tamper=False):
        _, _, prop = txa.create_signed_proposal(creator, "c2", CC, [b"i"])
        t = TxRWSet()
        t.ns_rwset(CC).writes[f"n{i}"] = b"v"
        rw = t.to_proto().SerializeToString()
        env = txa.assemble_transaction(
            prop, [txa.create_proposal_response(prop, rw, peer, CC)], creator
        )
        if tamper:
            env.signature = env.signature[:-6] + b"\x00" * 6
        return env

    envs = []
    for i in range(18):  # >= 16 → native fast path engages
        creator = anon if i % 3 == 0 else x509_client
        envs.append(tx(i, creator, tamper=(i == 6)))
    blk = pu.new_block(2, b"prev")
    for e in envs:
        blk.data.data.append(e.SerializeToString())
    blk = pu.finalize_block(blk)

    prov = PolicyProvider({CC: NamespaceInfo(
        policy=pol.from_dsl("OutOf(1, 'Org1MSP.peer')"))})
    v = BlockValidator(mgr, prov, MemVersionedDB())
    flt, _, _ = v.validate(blk)
    want = [C.BAD_CREATOR_SIGNATURE if i == 6 else C.VALID
            for i in range(18)]
    assert list(flt) == want


def test_epoch_revocation():
    """Epoch-based revocation (the vendored IBM/idemix revocation
    handler's capability, on the CL-RSA scheme): the RA's signed epoch
    record gates verification; revoking a holder advances the epoch,
    survivors re-issue, and the revoked holder — refused re-issuance —
    can no longer produce accepting presentations anywhere the new
    record has propagated."""
    issuer = idemix.IdemixIssuer("RevMSP", bits=1024)
    ipk = issuer.ipk

    def enroll(handle):
        h = idemix.IdemixHolder(ipk)
        U, proof = h.commitment()
        A, e, v_i = issuer.issue(U, proof, ou="org1", role="client",
                                 handle=handle)
        return h, h.assemble(A, e, v_i, ou="org1", role="client",
                             epoch=issuer.epoch)

    alice_h, alice = enroll("alice")
    bob_h, bob = enroll("bob")
    rec0 = issuer.epoch_record
    assert rec0.verify(ipk)
    for cred in (alice, bob):
        sig = idemix.sign(ipk, cred, b"m")
        assert idemix.verify(ipk, "org1", "client", b"m", sig,
                             epoch_record=rec0)

    # revoke bob → epoch advances, new signed record
    issuer.revoke("bob")
    rec1 = issuer.epoch_record
    assert rec1.epoch == rec0.epoch + 1 and rec1.verify(ipk)

    # bob's old credential dies under the new record
    sig = idemix.sign(ipk, bob, b"m")
    assert not idemix.verify(ipk, "org1", "client", b"m", sig,
                             epoch_record=rec1)
    # ... and bob cannot lie about the epoch (it folds into the proof)
    forged = json.loads(sig)
    forged["epoch"] = rec1.epoch
    assert not idemix.verify(ipk, "org1", "client", b"m",
                             json.dumps(forged).encode(),
                             epoch_record=rec1)
    # ... and cannot re-issue
    U, proof = bob_h.commitment()
    with pytest.raises(ValueError, match="revoked"):
        issuer.issue(U, proof, ou="org1", role="client", handle="bob")

    # alice re-issues into the new epoch and keeps working
    U, proof = alice_h.commitment()
    A, e, v_i = issuer.issue(U, proof, ou="org1", role="client",
                             handle="alice")
    alice2 = alice_h.assemble(A, e, v_i, ou="org1", role="client",
                              epoch=issuer.epoch)
    sig = idemix.sign(ipk, alice2, b"m")
    assert idemix.verify(ipk, "org1", "client", b"m", sig,
                         epoch_record=rec1)

    # MSP integration: record rides the channel config; a replayed OLD
    # record must not re-admit the revoked credential
    msp = idemix.IdemixMSP("RevMSP", ipk, epoch_record=rec0)
    msp2 = idemix.IdemixMSP.from_config(msp.to_config().config)
    assert msp2.epoch_record.epoch == rec0.epoch
    msp.set_epoch_record(rec1)
    msp.set_epoch_record(rec0)  # replay: ignored (monotonic)
    assert msp.epoch_record.epoch == rec1.epoch
    ident = msp.deserialize_identity(
        idemix.IdemixSigningIdentity("RevMSP", ipk, bob).serialized
    )
    assert not ident.verify(b"m", idemix.sign(ipk, bob, b"m"))
    # forged records (wrong RA key) are refused outright
    from fabric_tpu.crypto import ec_ref

    rogue = ec_ref.SigningKey.generate()
    fake = idemix.EpochRecord(99, 0, 0)
    fake.r, fake.s = rogue.sign_digest(fake.digest(ipk))
    with pytest.raises(ValueError):
        msp.set_epoch_record(fake)

"""Endorser + simulator + chaincode runtime unit tests (reference
scenarios: core/endorser tests, txmgr simulator tests)."""

import pytest

from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract, MarblesContract
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.simulator import TxSimulator
from fabric_tpu.protos import proposal_pb2

CHANNEL, CC = "uchan", "kvcc"


@pytest.fixture(scope="module")
def org():
    return cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)


@pytest.fixture(scope="module")
def mgr(org):
    return MSPManager({"Org1MSP": org.msp()})


@pytest.fixture()
def state():
    db = MemVersionedDB()
    b = UpdateBatch()
    b.put(CC, "seeded", b"42", (3, 7))
    b.put(CC, "r1", b"1", (3, 8))
    b.put(CC, "r2", b"2", (3, 9))
    db.apply_updates(b, (3, 9))
    return db


def _endorser(org, mgr, state):
    rt = ChaincodeRuntime()
    rt.register(CC, KVContract())
    rt.register("marbles", MarblesContract())
    signer = cryptogen.signing_identity(org, "peer0.org1.example.com")
    return Endorser(mgr, signer, state, rt)


def test_simulator_records_reads_writes_ranges(state):
    sim = TxSimulator(state)
    assert sim.get_state(CC, "seeded") == b"42"
    assert sim.get_state(CC, "ghost") is None
    sim.set_state(CC, "new", b"x")
    assert sim.get_state(CC, "new") == b"x"  # read-your-writes
    out = sim.get_state_range(CC, "r1", "r3")
    assert [k for k, _ in out] == ["r1", "r2"]
    rw_bytes, _ = sim.done()
    rw = TxRWSet.from_bytes(rw_bytes)
    n = rw.ns[CC]
    assert n.reads["seeded"] == (3, 7)
    assert n.reads["ghost"] is None
    assert "new" not in n.reads  # own write: no spurious read
    assert n.writes["new"] == b"x"
    (start, end, results), = n.range_queries
    assert (start, end) == ("r1", "r3")
    assert results == [("r1", (3, 8)), ("r2", (3, 9))]


def test_simulator_private_data(state):
    sim = TxSimulator(state)
    sim.set_private_data(CC, "collA", "secret", b"payload")
    rw_bytes, clear = sim.done()
    rw = TxRWSet.from_bytes(rw_bytes)
    hashed = rw.ns[CC].hashed["collA"]["writes"]
    assert len(hashed) == 1  # only hashes on the public set
    assert clear[(CC, "collA")]["secret"] == b"payload"


def test_process_proposal_endorses_and_binds_signature(org, mgr, state):
    e = _endorser(org, mgr, state)
    client = cryptogen.signing_identity(org, "User1@org1.example.com")
    signed, tx_id, prop = txa.create_signed_proposal(
        client, CHANNEL, CC, [b"put", b"k", b"v"]
    )
    res = e.process_proposal(signed)
    assert res.response.response.status == 200
    assert res.tx_id == tx_id
    # endorsement signature verifies over prp || endorser
    prp = res.response.payload
    endr = res.response.endorsement
    ident = mgr.deserialize_identity(endr.endorser)
    assert ident.verify(prp + endr.endorser, endr.signature)
    # rwset contains the write, no state was applied
    cca = proposal_pb2.ChaincodeAction()
    prp_msg = proposal_pb2.ProposalResponsePayload()
    prp_msg.ParseFromString(prp)
    cca.ParseFromString(prp_msg.extension)
    rw = TxRWSet.from_bytes(cca.results)
    assert rw.ns[CC].writes["k"] == b"v"
    assert state.get_state(CC, "k") is None


def test_process_proposal_rejects_bad_signature(org, mgr, state):
    e = _endorser(org, mgr, state)
    client = cryptogen.signing_identity(org, "User1@org1.example.com")
    signed, _, _ = txa.create_signed_proposal(client, CHANNEL, CC, [b"get", b"seeded"])
    bad = proposal_pb2.SignedProposal(
        proposal_bytes=signed.proposal_bytes,
        signature=signed.signature[:-2] + bytes(2),
    )
    assert e.process_proposal(bad).response.response.status == 500


def test_process_proposal_rejects_failed_simulation(org, mgr, state):
    e = _endorser(org, mgr, state)
    client = cryptogen.signing_identity(org, "User1@org1.example.com")
    signed, _, _ = txa.create_signed_proposal(
        client, CHANNEL, CC, [b"get", b"missing-key"]
    )
    res = e.process_proposal(signed)
    assert res.response.response.status == 404
    assert not res.response.HasField("endorsement")
    # unknown chaincode
    signed, _, _ = txa.create_signed_proposal(client, CHANNEL, "nope", [b"x"])
    assert e.process_proposal(signed).response.response.status == 500


def test_cross_chaincode_invocation(org, mgr, state):
    rt = ChaincodeRuntime()
    rt.register(CC, KVContract())

    from fabric_tpu.peer.chaincode import Contract, Response

    class Caller(Contract):
        def relay(self, stub, key: bytes, value: bytes):
            r = stub.invoke_chaincode(CC, [b"put", key, value])
            return Response(r.status, r.payload)

    rt.register("caller", Caller())
    sim = TxSimulator(state)
    resp = rt.execute(sim, "caller", [b"relay", b"kk", b"vv"])
    assert resp.status == 200
    rw_bytes, _ = sim.done()
    rw = TxRWSet.from_bytes(rw_bytes)
    # callee's writes land under the CALLEE namespace
    assert rw.ns[CC].writes["kk"] == b"vv"
    assert "caller" not in rw.ns or not rw.ns["caller"].writes


def test_transient_data_not_in_proposal_response(org, mgr, state):
    e = _endorser(org, mgr, state)
    client = cryptogen.signing_identity(org, "User1@org1.example.com")
    signed, _, _ = txa.create_signed_proposal(
        client, CHANNEL, CC, [b"put_private", b"collA", b"sec"],
        transient={"value": b"top-secret"},
    )
    res = e.process_proposal(signed)
    assert res.response.response.status == 200
    assert b"top-secret" not in res.response.SerializeToString()
    assert res.pvt_cleartext[(CC, "collA")]["sec"] == b"top-secret"

"""Ordering service tests: blockcutter rules, raft consensus (leader
election, replication, failover, WAL recovery), and a 3-orderer
localhost cluster streaming identical blocks through Broadcast/Deliver
(the reference's raft integration-suite behaviors, scaled to unit
speed: orderer/common/blockcutter tests, etcdraft chain tests)."""

import asyncio
import json

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.ordering.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.ordering.node import BroadcastClient, DeliverClient, OrdererNode
from fabric_tpu.ordering.raft import Entry, RaftNode, WAL
from fabric_tpu.protos import common_pb2


def run(coro, timeout=30):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# blockcutter


def test_blockcutter_count_cut():
    bc = BlockCutter(BatchConfig(max_message_count=3))
    cut, pending = bc.ordered(b"a")
    assert cut == [] and pending
    cut, _ = bc.ordered(b"b")
    assert cut == []
    cut, pending = bc.ordered(b"c")
    assert cut == [[b"a", b"b", b"c"]] and not pending


def test_blockcutter_preferred_bytes():
    bc = BlockCutter(BatchConfig(max_message_count=100, preferred_max_bytes=10))
    bc.ordered(b"aaaa")            # 4 bytes pending
    cut, pending = bc.ordered(b"bbbbbbbb")  # 4+8 > 10: cut pending first
    assert cut == [[b"aaaa"]] and pending
    assert bc.cut() == [b"bbbbbbbb"]


def test_blockcutter_isolated_oversize():
    bc = BlockCutter(BatchConfig(max_message_count=100, preferred_max_bytes=10))
    bc.ordered(b"aa")
    cut, pending = bc.ordered(b"x" * 50)  # oversize: flush + isolate
    assert cut == [[b"aa"], [b"x" * 50]] and not pending


# ---------------------------------------------------------------------------
# raft core over an in-memory lossless transport


class Net:
    """In-memory transport: loop.call_soon delivery, droppable."""

    def __init__(self):
        self.nodes = {}
        self.down = set()

    def send(self, frm):
        def cb(peer, msg):
            if peer in self.down or frm in self.down:
                return
            node = self.nodes.get(peer)
            if node is not None:
                asyncio.get_event_loop().call_soon(node.handle, msg)
        return cb


async def _wait_for(cond, timeout=5.0, interval=0.01):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


def _mk_cluster(tmp_path, net, ids=("o1", "o2", "o3")):
    applied = {i: [] for i in ids}
    nodes = {}
    for i in ids:
        wal = WAL(str(tmp_path / i))
        nodes[i] = RaftNode(
            i, list(ids), wal,
            apply_cb=lambda e, i=i: applied[i].append(e),
            send_cb=net.send(i),
            election_timeout=(0.05, 0.12), heartbeat=0.02,
        )
    net.nodes = nodes
    return nodes, applied


async def _propose_retrying(candidates, data, timeout=15.0):
    """Find the CURRENT leader among ``candidates`` and propose,
    retrying through elections: on a loaded 2-core host a freshly
    observed leader can be deposed (or a second election can race)
    before ``propose`` runs — polling the live leader instead of
    pinning the first observation is what the reference clients do."""
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        leader = next(
            (n for n in candidates if n.state == "leader"), None
        )
        if leader is not None and leader.propose(data) is not None:
            return leader
        await asyncio.sleep(0.01)
    raise AssertionError(f"no leader accepted {data!r} within {timeout}s")


def test_raft_elects_replicates_and_fails_over(tmp_path):
    # timing-sensitive on loaded 2-core hosts: every deadline below is
    # a generous POLLED bound (the test finishes as soon as the
    # condition holds), and proposals retry through depositions
    # instead of assuming the first observed leader stays leader
    async def scenario():
        net = Net()
        nodes, applied = _mk_cluster(tmp_path, net)
        for n in nodes.values():
            n.start()
        assert await _wait_for(
            lambda: any(n.state == "leader" for n in nodes.values()),
            timeout=15)
        for i in range(5):
            data = b"entry-%d" % i
            await _propose_retrying(list(nodes.values()), data)
            # serialize through COMMITMENT, not just leader acceptance:
            # an entry accepted on a leader deposed before replication
            # is lost — proposing entry i+1 only after entry i applied
            # everywhere keeps the expected log exact
            assert await _wait_for(
                lambda: all(
                    any(e.data == data for e in applied[n])
                    for n in applied
                ), timeout=15), data
        assert await _wait_for(
            lambda: all(len(applied[i]) == 5 for i in applied),
            timeout=15)
        assert [e.data for e in applied["o1"]] == [b"entry-%d" % i for i in range(5)]
        assert applied["o1"] == applied["o2"] == applied["o3"]

        # kill the leader: a new one rises and the log continues
        leader = next(n for n in nodes.values() if n.state == "leader")
        net.down.add(leader.id)
        leader.stop()
        rest = [n for n in nodes.values() if n.id != leader.id]
        assert await _wait_for(
            lambda: any(n.state == "leader" for n in rest), timeout=15)
        data = b"after-failover"
        await _propose_retrying(rest, data)
        live = [i for i in applied if i != leader.id]
        assert await _wait_for(
            lambda: all(
                any(e.data == data for e in applied[i]) for i in live
            ), timeout=15)
        assert await _wait_for(
            lambda: all(len(applied[i]) == 6 for i in live), timeout=15)
        for n in rest:
            n.stop()

    run(scenario(), timeout=90)


def test_raft_wal_recovery(tmp_path):
    wal = WAL(str(tmp_path / "w"))
    wal.save_meta(3, "o2")
    wal.append([Entry(1, 1, b"a"), Entry(1, 2, b"b"), Entry(3, 3, b"c")])
    wal.close()
    # torn tail: append garbage half-frame
    with open(str(tmp_path / "w" / "wal.bin"), "ab") as f:
        f.write(b"\x00\x00\x00\x10partial")
    w2 = WAL(str(tmp_path / "w"))
    assert w2.term == 3 and w2.voted_for == "o2"
    assert [(e.term, e.index, e.data) for e in w2.entries] == [
        (1, 1, b"a"), (1, 2, b"b"), (3, 3, b"c")
    ]
    w2.close()


# ---------------------------------------------------------------------------
# 3-orderer cluster over real localhost sockets


def _env(i: int) -> bytes:
    ch = pu.make_channel_header(common_pb2.HeaderType.ENDORSER_TRANSACTION, "ch1")
    sh = pu.make_signature_header(b"creator-%d" % i, b"nonce-%d" % i)
    payload = pu.make_payload(ch, sh, b"tx-payload-%d" % i)
    return common_pb2.Envelope(
        payload=payload.SerializeToString(), signature=b"sig"
    ).SerializeToString()


@pytest.mark.slow
def test_orderer_cluster_end_to_end(tmp_path):
    async def scenario():
        cluster = {}
        nodes = []
        for i in range(3):
            n = OrdererNode(f"o{i}", str(tmp_path / f"o{i}"), cluster)
            await n.start()
            cluster[n.id] = ("127.0.0.1", n.port)
            nodes.append(n)
        cfg = BatchConfig(max_message_count=4, batch_timeout_s=0.3)
        for n in nodes:
            n.cluster.update(cluster)  # all addresses known before joining
            n.batch_config = cfg
            n.join_channel("ch1")

        assert await _wait_for(
            lambda: any(n.chains["ch1"].raft.state == "leader" for n in nodes),
            timeout=10)

        client = BroadcastClient([cluster[n.id] for n in nodes])
        for i in range(10):
            res = await client.broadcast("ch1", _env(i))
            assert res["status"] == 200, res

        # all nodes converge to identical chains (10 txs = 2 full
        # batches of 4 + timeout batch of 2)
        assert await _wait_for(
            lambda: all(n.chains["ch1"].height >= 3 for n in nodes), timeout=10)
        chains = []
        for n in nodes:
            blks = [n.chains["ch1"].blocks.get_block(k).SerializeToString()
                    for k in range(3)]
            chains.append(blks)
        assert chains[0] == chains[1] == chains[2]
        total = sum(
            len(nodes[0].chains["ch1"].blocks.get_block(k).data.data)
            for k in range(3)
        )
        assert total == 10

        # deliver stream from a random node matches
        got = []
        dc = DeliverClient(*cluster["o1"])
        async for blk in dc.blocks("ch1", 0, 2):
            got.append(blk.SerializeToString())
        assert got == chains[0]

        # kill the leader; a client keeps submitting and the cluster
        # keeps cutting identical blocks
        leader = next(n for n in nodes if n.chains["ch1"].raft.state == "leader")
        await leader.stop()
        rest = [n for n in nodes if n is not leader]
        assert await _wait_for(
            lambda: any(n.chains["ch1"].raft.state == "leader" for n in rest),
            timeout=10)
        for i in range(10, 14):
            res = await client.broadcast("ch1", _env(i))
            assert res["status"] == 200, res
        assert await _wait_for(
            lambda: all(n.chains["ch1"].height >= 4 for n in rest), timeout=10)
        h = min(n.chains["ch1"].height for n in rest)
        for k in range(h):
            assert (rest[0].chains["ch1"].blocks.get_block(k).SerializeToString()
                    == rest[1].chains["ch1"].blocks.get_block(k).SerializeToString())

        await client.close()
        for n in rest:
            await n.stop()

    run(scenario(), timeout=60)


def test_chain_restart_does_not_duplicate_blocks(tmp_path):
    """WAL replay after restart must not re-append materialized
    batches (with and without a genesis block in the store)."""
    async def scenario(subdir, genesis):
        from fabric_tpu.ordering.chain import OrderingChain

        sent = []
        chain = OrderingChain(
            "chz", "solo", ["solo"], str(tmp_path / subdir),
            send_cb=lambda p, m: sent.append((p, m)),
            config=BatchConfig(max_message_count=1),
            genesis_block=genesis,
        )
        chain.start()
        assert await _wait_for(lambda: chain.raft.state == "leader")
        for i in range(3):
            await chain.broadcast(_env(i))
        base = 1 if genesis is not None else 0
        assert await _wait_for(lambda: chain.height == base + 3)
        blocks = [chain.blocks.get_block(k).SerializeToString()
                  for k in range(chain.height)]
        chain.stop()

        chain2 = OrderingChain(
            "chz", "solo", ["solo"], str(tmp_path / subdir),
            send_cb=lambda p, m: None,
            config=BatchConfig(max_message_count=1),
        )
        chain2.start()
        assert await _wait_for(lambda: chain2.raft.state == "leader")
        await chain2.broadcast(_env(99))
        assert await _wait_for(lambda: chain2.height == base + 4)
        # replay did not duplicate: prefix identical, one new block
        for k in range(base + 3):
            assert chain2.blocks.get_block(k).SerializeToString() == blocks[k]
        chain2.stop()

    gen = pu.finalize_block(pu.new_block(0, b"\x00" * 32))
    run(scenario("with_gen", gen))
    run(scenario("no_gen", None))

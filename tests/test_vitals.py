"""Flight-data recorder battery (fabric_tpu.observe.timeseries +
.blackbox) — crypto-free, injected clock.

Layers:

* sampler delta semantics for all three metric kinds (counter deltas,
  gauge levels, histogram interval {n, sum, p99}), ring retention and
  live resize, counter-reset clamping, and the OFF contract — no
  sampler thread exists and no global state is built;
* black-box trigger edges: DeviceLaneGuard degrade latch, autopilot
  SHED decision, SLO fast burn, CommitPipeline ``_fail_closed``, and
  the injected-crash last-gasp path via a CHILD process;
* bundle bounds: per-kind rate limiting and the size cap's honest
  ``truncated`` section list;
* ``/vitals`` round-trip over a live OperationsServer (index +
  ?metric + ?incident + 404s + unarmed honesty);
* the bench-extras capture smoke (``FABTPU_BENCH_VITALS``).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

from fabric_tpu.observe import blackbox, timeseries
from fabric_tpu.observe.timeseries import MetricsSampler
from fabric_tpu.ops_metrics import Registry


class Clock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_globals():
    """Every test leaves the process-global recorder OFF — the default
    contract the acceptance pins."""
    yield
    timeseries.configure(0)
    blackbox.configure(enabled=False)


def _sampler(clk, retention=8):
    reg = Registry()
    return reg, MetricsSampler(interval_s=1.0, retention=retention,
                               registry=reg, clock=clk)


# ---------------------------------------------------------------------------
# sampler delta semantics


def test_counter_series_records_deltas_not_monotones():
    clk = Clock()
    reg, s = _sampler(clk)
    c = reg.counter("reqs_total", "t")
    c.add(5, tenant="a")
    s.sample()
    clk.advance(1.0)
    c.add(2, tenant="a")
    s.sample()
    clk.advance(1.0)
    s.sample()  # idle interval → delta 0
    pts = s.series()["reqs_total"]["tenant=a"]["points"]
    assert [v for _t, v in pts] == [5.0, 2.0, 0.0]
    # rate over the trailing window divides deltas by elapsed time
    assert s.rate("reqs_total", tenant="a") == pytest.approx(1.0)

def test_counter_reset_clamps_to_new_level():
    clk = Clock()
    reg, s = _sampler(clk)
    c = reg.counter("x_total", "t")
    c.add(10)
    s.sample()
    # a "reset" (negative delta) records the new raw level, never a
    # negative rate
    with c._lock:
        c._values[()] = 3.0
    clk.advance(1.0)
    s.sample()
    pts = s.series()["x_total"]["_"]["points"]
    assert [v for _t, v in pts] == [10.0, 3.0]


def test_gauge_series_records_levels():
    clk = Clock()
    reg, s = _sampler(clk)
    g = reg.gauge("depth", "t")
    g.set(3, tenant="a")
    s.sample()
    clk.advance(1.0)
    g.set(1, tenant="a")
    s.sample()
    pts = s.series()["depth"]["tenant=a"]["points"]
    assert [v for _t, v in pts] == [3.0, 1.0]


def test_histogram_series_records_interval_deltas_and_p99():
    clk = Clock()
    reg, s = _sampler(clk)
    h = reg.histogram("lat_s", "t")
    h.observe(0.002)
    h.observe(0.3)
    s.sample()
    clk.advance(1.0)
    h.observe(0.004)
    s.sample()
    clk.advance(1.0)
    s.sample()
    pts = [p for _t, p in s.series()["lat_s"]["_"]["points"]]
    # first interval: both observations; p99 covers the slow one
    assert pts[0]["n"] == 2 and pts[0]["sum"] == pytest.approx(0.302)
    assert pts[0]["p99"] == 0.5
    # second interval: ONLY the new observation — not the cumulative
    assert pts[1]["n"] == 1 and pts[1]["sum"] == pytest.approx(0.004)
    assert pts[1]["p99"] == 0.005
    # idle interval: empty, p99 None (no traffic is not a latency)
    assert pts[2] == {"n": 0, "sum": 0.0, "p99": None}
    # the report's sparkline carries interval p99s
    rep = s.report()["metrics"]["lat_s"]["_"]
    assert rep["kind"] == "histogram" and rep["spark"] == [0.5, 0.005]


# ---------------------------------------------------------------------------
# retention, resize, validation, OFF contract


def test_ring_retention_and_live_resize():
    clk = Clock()
    reg, s = _sampler(clk, retention=4)
    g = reg.gauge("v", "t")
    for i in range(7):
        g.set(i)
        s.sample()
        clk.advance(1.0)
    pts = s.series()["v"]["_"]["points"]
    assert len(pts) == 4 and [v for _t, v in pts] == [3.0, 4.0, 5.0, 6.0]
    s.configure(retention=2)
    pts = s.series()["v"]["_"]["points"]
    assert [v for _t, v in pts] == [5.0, 6.0]
    # and the next samples respect the new bound
    g.set(9)
    s.sample()
    assert len(s.series()["v"]["_"]["points"]) == 2


def test_sampler_validation():
    with pytest.raises(ValueError):
        MetricsSampler(interval_s=-1, registry=Registry())
    with pytest.raises(ValueError):
        MetricsSampler(retention=0, registry=Registry())
    _reg, s = _sampler(Clock())
    with pytest.raises(ValueError):
        s.configure(retention=0)


def test_recorder_off_means_no_thread_and_no_global():
    """The acceptance's OFF half: interval 0 builds nothing."""
    assert timeseries.configure(0) is None
    assert timeseries.global_sampler() is None
    assert not any(
        t.name == "fabtpu-vitals" for t in threading.enumerate()
    )
    # and arming then disarming stops the thread
    s = timeseries.configure(0.05, retention=4, registry=Registry())
    assert s is not None and timeseries.global_sampler() is s
    assert any(t.name == "fabtpu-vitals" for t in threading.enumerate())
    timeseries.configure(0)
    assert timeseries.global_sampler() is None
    for t in threading.enumerate():
        assert t.name != "fabtpu-vitals" or not t.is_alive()


def test_acquire_release_refcounts_colocated_holders(tmp_path):
    """Two colocated nodes share ONE sampler and ONE recorder; the
    first stop() — creator or not — must not strand the survivor,
    and the last one out disarms.  (PeerNode start/stop pairs
    acquire/release.)"""
    s1 = timeseries.acquire(0.05, retention=4, registry=Registry())
    s2 = timeseries.acquire(0.05, retention=4)
    assert s1 is s2 and timeseries.global_sampler() is s1
    b1 = blackbox.acquire(out_dir=str(tmp_path), sampler=s1)
    b2 = blackbox.acquire(out_dir=str(tmp_path / "other"))
    # second acquire REUSES the live recorder (first-arm wins for the
    # out_dir wiring — replacing would discard b1's incident index)
    assert b1 is b2 and blackbox.global_blackbox() is b1
    timeseries.release()           # first node stops...
    blackbox.release()
    assert timeseries.global_sampler() is s1   # ...survivor keeps both
    assert blackbox.global_blackbox() is b1
    timeseries.release()           # last one out disarms
    blackbox.release()
    assert timeseries.global_sampler() is None
    assert blackbox.global_blackbox() is None
    # the hard OFF (configure) zeroes the refcount for the next test
    s3 = timeseries.acquire(0.05, retention=4, registry=Registry())
    assert s3 is not None
    timeseries.configure(0)
    assert timeseries.global_sampler() is None
    timeseries.release()           # over-release after hard OFF: no-op
    assert timeseries.global_sampler() is None
    # interval<=0 acquires nothing and holds nothing
    assert timeseries.acquire(0) is None
    timeseries.release()


def test_nodeconfig_validates_vitals_knobs():
    from fabric_tpu.nodeconfig import ConfigError, load_peer_config

    base = {"id": "p", "data_dir": "/tmp/x", "msp_id": "m",
            "msp_dir": "/tmp/m"}
    with pytest.raises(ConfigError, match="vitals_interval_s"):
        load_peer_config({**base, "vitals_interval_s": -1}, environ={})
    with pytest.raises(ConfigError, match="vitals_retention"):
        load_peer_config({**base, "vitals_retention": 0}, environ={})
    cfg = load_peer_config(
        {**base, "vitals_interval_s": 2.5, "vitals_retention": 32,
         "blackbox_dir": "/tmp/bb"}, environ={},
    )
    assert cfg.vitals_interval_s == 2.5
    assert cfg.vitals_retention == 32
    assert cfg.blackbox_dir == "/tmp/bb"


# ---------------------------------------------------------------------------
# black-box trigger edges


def test_degrade_latch_produces_exactly_one_bundle(tmp_path):
    """THE acceptance edge: a SEEDED fault that latches the degrade
    guard produces exactly one bundle carrying the decision log, the
    metric trails, and the trace trees."""
    from fabric_tpu import faults
    from fabric_tpu.control import Autopilot, Signals
    from fabric_tpu.observe import Tracer
    from fabric_tpu.peer.degrade import DeviceLaneGuard

    clk = Clock()
    reg, s = _sampler(clk)
    reg.counter("fallback_seen_total", "t").add(3, channel="ch1")
    s.sample()
    tr = Tracer(ring_blocks=4, slow_factor=0)
    tr.finish_block(tr.begin_block(7, channel="ch1"))
    # an autopilot with one prior actuation in its log — the bundle
    # must carry the decision history, not just the moment
    ap = Autopilot(None, lambda k, v: None,
                   tracer=Tracer(ring_blocks=4, slow_factor=0,
                                 clock=clk),
                   clock=clk, registry=reg)
    d = ap.tick(Signals(queue_age_p99_ms={"t1": 500.0}, clock_s=clk()))
    assert d is not None and d.knob == "coalesce_blocks"
    bb = blackbox.configure(
        out_dir=str(tmp_path), sampler=s, tracer=tr, autopilot=ap,
        clock=clk, registry=reg,
    )
    guard = DeviceLaneGuard(fail_threshold=2, retries=1,
                            channel="ch1", registry=reg, clock=clk,
                            sleep=lambda _s: None)
    plan = faults.FaultPlan("validator.verify_launch:raise:n=4",
                            seed=7)
    faults.install(plan)
    try:
        # one guarded launch = 2 seeded failed attempts → the latch
        out = guard.run_launch(lambda: "device",
                               lambda: "cpu-fallback")
        assert out == "cpu-fallback" and guard.degraded
    finally:
        faults.reset()
    idx = bb.bundles()
    assert len(idx) == 1 and idx[0]["kind"] == "degrade_latch"
    assert idx[0]["detail"]["channel"] == "ch1"
    assert idx[0]["detail"]["consecutive_failures"] == 2
    bundle = bb.bundle(idx[0]["seq"])
    # decision log + trails + trace trees all rode along
    assert bundle["autopilot"]["decisions"][0]["knob"] == (
        "coalesce_blocks"
    )
    assert "fallback_seen_total" in bundle["vitals"]
    assert bundle["traces"]["_"][0]["block"] == 7
    # the seeded plan's own stats are in the bundle too
    assert bundle["faults"]["validator.verify_launch"][0]["fired"] == 2
    # a SECOND latch inside the rate-limit window records nothing new
    guard.record_success()
    guard.record_failure(RuntimeError("again"))
    guard.record_failure(RuntimeError("again"))
    assert len(bb.bundles()) == 1
    # and the bundle landed on disk, bounded-name form
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["blackbox-0001-degrade_latch.json"]
    on_disk = json.loads((tmp_path / files[0]).read_text())
    assert on_disk["kind"] == "degrade_latch"


def test_autopilot_shed_decision_records_bundle():
    from fabric_tpu.control import Autopilot, Signals
    from fabric_tpu.observe import Tracer

    clk = Clock()
    reg, s = _sampler(clk)
    ap = Autopilot(
        None, lambda k, v: None, set_shed=lambda t, on: None,
        tracer=Tracer(ring_blocks=8, slow_factor=0, clock=clk),
        clock=clk, registry=reg,
    )
    bb = blackbox.configure(sampler=s, autopilot=ap, clock=clk,
                            registry=reg)
    d = ap.tick(Signals(burn={("lat", "sidecar:noisy"): 9.0},
                        clock_s=clk()))
    assert (d.knob, d.direction) == ("shed", "on")
    idx = bb.bundles()
    assert len(idx) == 1 and idx[0]["kind"] == "autopilot_shed"
    assert idx[0]["detail"]["tenant"] == "noisy"
    # the decision log itself is in the bundle (explicit source)
    bundle = bb.bundle(idx[0]["seq"])
    assert bundle["autopilot"]["decisions"][0]["knob"] == "shed"


def test_slo_fast_burn_records_bundle():
    from fabric_tpu.observe.slo import Objective, SloEngine

    clk = Clock()
    reg, s = _sampler(clk)
    bb = blackbox.configure(sampler=s, clock=clk, registry=reg)
    eng = SloEngine(
        [Objective(name="lat", kind="latency", ms=10.0,
                   windows=(60.0,), min_events=1)],
        clock=clk, registry=reg,
    )
    for _ in range(3):
        eng.record(eng.objectives[0], "ch1", good=False)
    idx = bb.bundles()
    assert len(idx) == 1 and idx[0]["kind"] == "slo_fast_burn"
    assert idx[0]["detail"]["slo"] == "lat"
    assert idx[0]["detail"]["channel"] == "ch1"


def test_pipeline_fail_closed_records_bundle():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_commit_pipeline import ToyValidator, _stream

    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.peer.pipeline import CommitPipeline

    clk = Clock()
    reg, s = _sampler(clk)
    bb = blackbox.configure(sampler=s, clock=clk, registry=reg)
    blocks = _stream(n_blocks=3)
    v = ToyValidator(MemVersionedDB())

    def commit_fn(res):
        raise RuntimeError("committer wedged")

    pipe = CommitPipeline(v, commit_fn, depth=2, channel="ch1")
    with pytest.raises(RuntimeError):
        for b in blocks:
            pipe.submit(b)
        pipe.flush()
    idx = bb.bundles()
    assert len(idx) == 1 and idx[0]["kind"] == "pipeline_fail_closed"
    assert idx[0]["detail"]["channel"] == "ch1"
    assert idx[0]["detail"]["stage"] == "commit"
    # pipe latched closed exactly as before (the edge observes, never
    # changes containment semantics)
    with pytest.raises(RuntimeError, match="closed"):
        pipe.submit(blocks[0])


def test_injected_crash_dumps_bundle_in_child(tmp_path):
    """The last-gasp path: a FaultPlan ``crash`` fault hard-exits the
    child with 86, but not before the armed recorder writes its
    bundle (the one edge atexit can never see)."""
    script = r"""
import sys
from fabric_tpu import faults
from fabric_tpu.observe import blackbox
blackbox.configure(out_dir=sys.argv[1])
faults.configure("toy.point:crash")
faults.fire("toy.point")
raise SystemExit("unreachable: the crash fault must exit first")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 86, (proc.stdout, proc.stderr)
    files = [p for p in tmp_path.iterdir()
             if p.name.endswith("injected_crash.json")]
    assert len(files) == 1, list(tmp_path.iterdir())
    bundle = json.loads(files[0].read_text())
    assert bundle["kind"] == "injected_crash"
    assert bundle["detail"]["point"] == "toy.point"
    # the chaos plan's own stats made it into the bundle
    assert bundle["faults"]["toy.point"][0]["fired"] == 1


def test_atexit_flushes_fault_stats_for_bundle_less_chaos_run(tmp_path):
    """A chaos-armed process that fired faults but recorded no
    incident bundle still leaves ONE stats bundle at clean exit."""
    script = r"""
import sys
from fabric_tpu import faults
from fabric_tpu.observe import blackbox
blackbox.configure(out_dir=sys.argv[1])
faults.configure("toy.point:raise:n=1")
try:
    faults.fire("toy.point")
except faults.InjectedFault:
    pass
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    files = [p for p in tmp_path.iterdir()
             if p.name.endswith("fault_stats_at_exit.json")]
    assert len(files) == 1, list(tmp_path.iterdir())


# ---------------------------------------------------------------------------
# bundle bounds


def test_rate_limit_is_per_kind_and_expires():
    clk = Clock()
    reg, s = _sampler(clk)
    bb = blackbox.BlackBox(sampler=s, clock=clk, registry=reg,
                           min_interval_s=30.0)
    assert bb.record("degrade_latch") is not None
    assert bb.record("degrade_latch") is None        # limited
    assert bb.record("autopilot_shed") is not None   # other kind flows
    clk.advance(31.0)
    assert bb.record("degrade_latch") is not None    # window expired
    assert reg.counter(
        "blackbox_rate_limited_total", ""
    ).value(kind="degrade_latch") == 1


def test_size_bound_drops_sections_honestly():
    from fabric_tpu.observe import Tracer

    clk = Clock()
    reg, s = _sampler(clk, retention=256)
    g = reg.gauge("wide", "t")
    for i in range(400):  # many label variants × many points
        g.set(i, series=f"s{i % 40}")
    for _ in range(64):
        s.sample()
        clk.advance(1.0)
    bb = blackbox.BlackBox(sampler=s, clock=clk, registry=reg,
                           tracer=Tracer(ring_blocks=0),
                           max_bytes=20_000)
    bundle = bb.record("degrade_latch", channel="ch1")
    assert len(json.dumps(bundle)) <= 20_000
    assert "vitals" in bundle.get("truncated", [])
    assert bundle["detail"]["channel"] == "ch1"  # the header survives
    # index names the truncation
    assert bb.bundles()[0]["truncated"] == bundle["truncated"]


def test_restart_resumes_seq_and_prunes_prior_run_files(tmp_path):
    """A restarted recorder (the crash-then-restart flow it exists
    for) must never overwrite the crashed run's bundles, and the disk
    cap must count prior-run files."""
    clk = Clock()
    reg, s = _sampler(clk)
    kw = dict(sampler=s, clock=clk, registry=reg, max_bundles=3,
              min_interval_s=0.0, out_dir=str(tmp_path))
    bb1 = blackbox.BlackBox(**kw)
    bb1.record("degrade_latch")
    bb1.record("injected_crash")
    first_run = sorted(p.name for p in tmp_path.iterdir())
    assert first_run == ["blackbox-0001-degrade_latch.json",
                        "blackbox-0002-injected_crash.json"]
    # "restart": a fresh recorder over the same directory
    bb2 = blackbox.BlackBox(**kw)
    bb2.record("degrade_latch")
    bb2.record("autopilot_shed")
    names = sorted(p.name for p in tmp_path.iterdir())
    # seq resumed past the prior run, nothing overwritten, and the
    # oldest prior-run file was pruned to honor max_bundles=3
    assert names == ["blackbox-0002-injected_crash.json",
                     "blackbox-0003-degrade_latch.json",
                     "blackbox-0004-autopilot_shed.json"]


def test_bundle_ring_is_bounded(tmp_path):
    clk = Clock()
    reg, s = _sampler(clk)
    bb = blackbox.BlackBox(sampler=s, clock=clk, registry=reg,
                           max_bundles=3, min_interval_s=0.0,
                           out_dir=str(tmp_path))
    for i in range(6):
        clk.advance(1.0)
        assert bb.record(f"kind{i}") is not None
    idx = bb.bundles()
    assert [b["kind"] for b in idx] == ["kind3", "kind4", "kind5"]
    assert len(list(tmp_path.iterdir())) == 3  # disk bounded too


# ---------------------------------------------------------------------------
# /vitals round-trip


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, json.loads(r.read())


def test_vitals_endpoint_roundtrip():
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    clk = Clock()
    reg, s = _sampler(clk)
    c = reg.counter("reqs_total", "t")
    c.add(4, tenant="a")
    s.sample()
    clk.advance(1.0)
    c.add(1, tenant="a")
    s.sample()
    bb = blackbox.BlackBox(sampler=s, clock=clk, registry=reg)
    bb.record("degrade_latch", channel="ch1")

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=reg, health=HealthRegistry(),
            vitals=s, blackbox=bb,
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, idx = await loop.run_in_executor(
                None, _get, srv.port, "/vitals"
            )
            assert st == 200 and idx["enabled"]
            assert idx["samples"] == 2
            spark = idx["metrics"]["reqs_total"]["tenant=a"]
            assert spark["kind"] == "counter"
            assert spark["spark"] == [4.0, 1.0]
            assert [b["kind"] for b in idx["incidents"]] == [
                "degrade_latch"
            ]
            st, m = await loop.run_in_executor(
                None, _get, srv.port, "/vitals?metric=reqs_total"
            )
            assert st == 200
            pts = m["series"]["tenant=a"]["points"]
            assert [v for _t, v in pts] == [4.0, 1.0]
            st, b = await loop.run_in_executor(
                None, _get, srv.port, "/vitals?incident=1"
            )
            assert st == 200 and b["kind"] == "degrade_latch"
            for bad in ("/vitals?metric=nope", "/vitals?incident=99"):
                try:
                    await loop.run_in_executor(
                        None, _get, srv.port, bad
                    )
                    raise AssertionError(f"expected 404 for {bad}")
                except urllib.error.HTTPError as e:
                    assert e.code == 404
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


def test_vitals_metric_label_filter_and_exemplars():
    """/vitals?metric=N&label=k=v keeps only the matching variants —
    one metric with many label variants no longer returns every ring.
    404 semantics unchanged for unknown metrics; an exemplar-armed
    histogram's rings ride the payload."""
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    clk = Clock()
    reg, s = _sampler(clk)
    c = reg.counter("reqs_total", "t")
    c.add(4, tenant="a")
    c.add(9, tenant="b")
    h = reg.histogram("stage_seconds", "t", exemplars=2)
    h.observe(0.5, exemplar="blk7", stage="launch")
    s.sample()

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=reg, health=HealthRegistry(), vitals=s,
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, m = await loop.run_in_executor(
                None, _get, srv.port,
                "/vitals?metric=reqs_total&label=tenant=a",
            )
            assert st == 200
            assert list(m["series"]) == ["tenant=a"]
            # no filter still returns every variant (unchanged)
            st, m2 = await loop.run_in_executor(
                None, _get, srv.port, "/vitals?metric=reqs_total"
            )
            assert sorted(m2["series"]) == ["tenant=a", "tenant=b"]
            # exemplar-armed histogram: rings ride the payload
            st, m3 = await loop.run_in_executor(
                None, _get, srv.port, "/vitals?metric=stage_seconds"
            )
            assert m3["exemplars"]["stage=launch"] == [[0.5, "blk7"]]
            # 404s: unknown metric (unchanged), and a label matching
            # no variant of a known metric
            for bad in ("/vitals?metric=nope&label=tenant=a",
                        "/vitals?metric=reqs_total&label=tenant=zz"):
                try:
                    await loop.run_in_executor(None, _get, srv.port, bad)
                    raise AssertionError(f"expected 404 for {bad}")
                except urllib.error.HTTPError as e:
                    assert e.code == 404
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


def test_vitals_endpoint_unarmed_is_honest():
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=Registry(), health=HealthRegistry(),
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, idx = await loop.run_in_executor(
                None, _get, srv.port, "/vitals"
            )
            assert st == 200
            assert idx["enabled"] is False
            assert idx["incidents"] == []
            assert "metrics" not in idx
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# recorder armed over a real crypto-free pipeline run: delta-correct
# series for all three kinds off live traffic (the acceptance's ON half)


def test_recorder_over_live_pipeline_run():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_commit_pipeline import ToyValidator, _stream

    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.observe import Tracer
    from fabric_tpu.ops_metrics import global_registry
    from fabric_tpu.peer.pipeline import CommitPipeline

    reg = global_registry()  # the pipeline publishes here
    s = MetricsSampler(interval_s=1.0, retention=64, registry=reg)
    s.sample()  # baseline pass: later deltas cover ONLY this run
    state = MemVersionedDB()
    v = ToyValidator(state)
    committed = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        committed.append(res.block.header.number)

    tr = Tracer(ring_blocks=8, slow_factor=0)
    with CommitPipeline(v, commit_fn, depth=2, channel="vit",
                        tracer=tr) as pipe:
        for b in _stream(n_blocks=4):
            pipe.submit(b)
    assert committed and sorted(committed) == [0, 1, 2, 3]
    s.sample()
    series = s.series()
    # counter: the pipelined-block count delta equals this run's blocks
    ctr = series["commit_pipeline_blocks_total"]
    run_total = sum(
        v for labels, sr in ctr.items()
        if "channel=vit" in labels for _t, v in sr["points"]
    )
    assert run_total == 4
    # gauge: inflight ended drained at 0
    g = series["commit_pipeline_inflight"]["channel=vit"]
    assert g["kind"] == "gauge" and g["points"][-1][1] == 0.0
    # histogram: stage seconds saw exactly this run's finish count
    h = series["commit_pipeline_stage_seconds"]
    fin = [sr for labels, sr in h.items()
           if "channel=vit" in labels and "stage=finish" in labels]
    assert len(fin) == 1
    assert sum(p["n"] for _t, p in fin[0]["points"]) == 4


# ---------------------------------------------------------------------------
# blackbox_view renders a bundle as a text postmortem


def test_blackbox_view_renders_postmortem(tmp_path):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    import blackbox_view

    from fabric_tpu.control import Autopilot, Signals
    from fabric_tpu.observe import Tracer

    clk = Clock()
    reg, s = _sampler(clk)
    c = reg.counter("reqs_total", "t")
    for i in range(5):
        c.add(i + 1, tenant="a")
        s.sample()
        clk.advance(1.0)
    tr = Tracer(ring_blocks=4, slow_factor=0, clock=clk)
    tr.finish_block(tr.begin_block(3, channel="ch1"))
    ap = Autopilot(None, lambda k, v: None,
                   set_shed=lambda t, on: None, tracer=tr, clock=clk,
                   registry=reg)
    ap.tick(Signals(burn={("lat", "sidecar:noisy"): 9.0},
                    clock_s=clk()))
    bb = blackbox.BlackBox(sampler=s, tracer=tr, autopilot=ap,
                           clock=clk, registry=reg,
                           out_dir=str(tmp_path))
    bundle = bb.record("autopilot_shed", tenant="noisy")
    text = blackbox_view.render_bundle(bundle)
    assert "incident: autopilot_shed" in text
    assert "reqs_total{tenant=a}" in text
    assert "shed" in text and "burn" in text
    assert "block 3" in text  # the trace waterfall rode along
    # the CLI end of it renders the on-disk file too
    path = next(tmp_path.iterdir())
    rc = blackbox_view.main([str(path), "--no-traces"])
    assert rc == 0


# ---------------------------------------------------------------------------
# bench-extras capture smoke


def test_bench_vitals_capture_smoke(monkeypatch):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    import bench

    monkeypatch.delenv("FABTPU_BENCH_VITALS", raising=False)
    assert bench._vitals_capture() is None
    assert bench._vitals_extras(None) is None
    monkeypatch.setenv("FABTPU_BENCH_VITALS", "1")
    monkeypatch.setenv("FABTPU_BENCH_VITALS_INTERVAL_S", "0.01")
    s = bench._vitals_capture()
    assert s is not None
    from fabric_tpu.ops_metrics import global_registry

    global_registry().counter("bench_vitals_smoke_total", "t").add(3)
    extras = bench._vitals_extras(s)
    assert extras is not None and extras["series_count"] > 0
    smoke = extras["series"]["bench_vitals_smoke_total"]["_"]
    assert smoke["kind"] == "counter"
    assert sum(v for _t, v in smoke["points"]) == 3.0
    json.dumps(extras)  # BENCH_*.json-serializable end to end

"""Multi-tenant validation sidecar battery (fabric_tpu.sidecar +
comm.rpc satellites) — crypto-free by construction (toy device lanes
over the REAL server/scheduler/link/wire stack):

* wire codec round trips (unpackable lanes degrade to invalid),
* weighted-deficit-round-robin fairness, starvation freedom, bounded
  admission,
* loopback server ≡ local serial oracle through the depth-2
  CommitPipeline — identical accept set AND state, bad-sig lanes
  included,
* 2-tenant storm: observed served shares track the weights,
* bounded-queue backpressure surfaces as client BUSY backoff, never
  deadlock,
* sidecar kill/restart mid-stream: blocks route through the local
  fallback latch and the client re-attaches via recovery probes,
* comm.rpc satellites: send-path MAX_FRAME typed error, method names
  in ERR frames.
"""

import asyncio
import json
import threading
import time

import pytest

from fabric_tpu import faults
from fabric_tpu import protoutil as pu
from fabric_tpu.comm import rpc
from fabric_tpu.comm.rpc import (
    FrameTooLargeError,
    RpcClient,
    RpcError,
    RpcServer,
)
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.ops_metrics import Registry
from fabric_tpu.peer.degrade import DeviceLaneGuard
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.sidecar import (
    SidecarLink,
    SidecarServer,
    SidecarUnavailable,
    WeightedScheduler,
)
from fabric_tpu.sidecar import wire
from fabric_tpu.sidecar.scheduler import Request
from fabric_tpu.utils.backoff import Backoff


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.reset()
    yield
    faults.reset()


class LoopThread:
    """A private asyncio loop on a daemon thread — hosts the sidecar
    server while tests drive clients synchronously."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self._main, name="test-sidecar-loop", daemon=True
        )
        self.thread.start()

    def _main(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=15.0):
        return asyncio.run_coroutine_threadsafe(
            coro, self.loop
        ).result(timeout)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5.0)


@pytest.fixture()
def loop_thread():
    lt = LoopThread()
    yield lt
    lt.stop()


def toy_verify(itemsets):
    """Toy device lane: item = (seq, valid_flag, 0, 0, 0)."""
    return [[bool(it[1]) for it in items] for items in itemsets]


def make_server(loop_thread, **kw):
    kw.setdefault("verify_fn", toy_verify)
    kw.setdefault("registry", Registry())
    srv = SidecarServer(**kw)
    loop_thread.run(srv.start())
    return srv


def make_link(srv, tenant="chan", **kw):
    kw.setdefault("registry", Registry())
    return SidecarLink("127.0.0.1", srv.port, tenant=tenant, **kw)


# -- wire codec -------------------------------------------------------------


class TestWire:
    def test_request_roundtrip(self):
        t = [(1, 1, 0, 0, 0), (2, 0, 3, 4, 5)]
        hdr, items = wire.decode_request(wire.encode_request(9, t))
        assert hdr["seq"] == 9 and hdr["n"] == 2
        assert items == t

    def test_response_roundtrip(self):
        hdr, v = wire.decode_response(
            wire.encode_response(3, [True, False, True])
        )
        assert hdr == {"seq": 3}
        assert v == [True, False, True]
        hdr, v = wire.decode_response(wire.encode_busy(4, 20.0))
        assert hdr["status"] == "BUSY" and v == []
        hdr, v = wire.decode_response(wire.encode_error(5, "x" * 900))
        assert hdr["status"] == "ERROR" and len(hdr["error"]) <= 500

    def test_unpackable_item_degrades_to_invalid(self):
        # a component too wide for 32 bytes (malformed DER can carry
        # arbitrary ints) must become the all-zero REJECTED item, not
        # a protocol error — and never a valid lane
        big = 1 << 300
        _, items = wire.decode_request(
            wire.encode_request(1, [(1, big, 2, 3, 4), (9, 1, 0, 0, 0)])
        )
        assert items[0] == wire.INVALID_ITEM
        assert items[1] == (9, 1, 0, 0, 0)

    def test_torn_payload_is_a_typed_error(self):
        buf = wire.encode_request(1, [(1, 1, 0, 0, 0)])
        with pytest.raises(ValueError):
            wire.decode_request(buf[:-3])


# -- scheduler --------------------------------------------------------------


def _sched(**kw):
    kw.setdefault("registry", Registry())
    return WeightedScheduler(**kw)


class TestScheduler:
    def test_weighted_shares_track_weights(self):
        s = _sched(queue_limit=100, quantum=1)
        s.register("a", 1.0)
        s.register("b", 3.0)
        for i in range(40):
            assert s.submit(Request("a", i, [0]))
            assert s.submit(Request("b", i, [0]))
        served = {"a": 0, "b": 0}
        checked = False
        while True:
            batch = s.next_batch(4)
            if not batch:
                break
            for r in batch:
                served[r.tenant] += 1
            if not checked and sum(served.values()) >= 20:
                # mid-drain (both still backlogged): shares must sit
                # at the weight ratio, well inside the 20% criterion
                share_b = served["b"] / sum(served.values())
                assert abs(share_b - 0.75) < 0.75 * 0.2
                checked = True
        assert checked
        assert served == {"a": 40, "b": 40}  # everyone fully drains

    def test_no_starvation_with_costly_head(self):
        # a head request costlier than one round's credit takes extra
        # rounds but IS served — and the cheap tenant is not blocked
        s = _sched(queue_limit=10, quantum=2)
        s.register("heavy", 1.0)
        s.register("light", 1.0)
        s.submit(Request("heavy", 0, [0] * 50))  # cost 50 >> quantum 2
        s.submit(Request("light", 0, [0]))
        got = []
        for _ in range(10):
            got += [(r.tenant, r.seq) for r in s.next_batch(1)]
            if len(got) == 2:
                break
        assert sorted(t for t, _ in got) == ["heavy", "light"]

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            _sched(queue_limit=0)
        with pytest.raises(ValueError):
            _sched(quantum=0)  # would spin next_batch forever
        s = _sched()
        with pytest.raises(ValueError):
            s.register("a", 0.0)  # weightless tenant never drains

    def test_bounded_queue_rejects(self):
        s = _sched(queue_limit=2)
        s.register("a", 1.0)
        assert s.submit(Request("a", 0, [0]))
        assert s.submit(Request("a", 1, [0]))
        assert not s.submit(Request("a", 2, [0]))  # BUSY
        assert s.stats()["a"]["rejected"] == 1
        s.next_batch(1)
        assert s.submit(Request("a", 3, [0]))  # drained → admits again

    def test_stats_survive_disconnect_and_reconnect(self):
        # the fairness picture must outlive the stream teardown that
        # reads it (bench joins AFTER the tenants close their links),
        # and a reconnecting tenant resumes its served totals
        s = _sched(queue_limit=4)
        s.register("a", 2.0)
        s.submit(Request("a", 0, [0] * 5))
        s.next_batch(1)
        s.unregister("a")
        assert s.stats()["a"]["served_cost"] == 5
        assert s.stats()["a"]["depth"] == 0
        s.register("a", 2.0)
        s.submit(Request("a", 1, [0] * 3))
        s.next_batch(1)
        assert s.stats()["a"]["served_cost"] == 8

    def test_unregister_returns_orphans(self):
        s = _sched(queue_limit=4)
        s.register("a", 1.0)
        s.register("a", 2.0)  # second connection, same tenant
        s.submit(Request("a", 0, [0]))
        assert s.unregister("a") == []  # one ref left: queue survives
        orphans = s.unregister("a")
        assert [r.seq for r in orphans] == [0]
        assert s.pending() == 0
        with pytest.raises(KeyError):
            s.submit(Request("a", 1, [0]))


# -- comm.rpc satellites ----------------------------------------------------


class TestRpcSatellites:
    def test_send_path_enforces_max_frame(self, monkeypatch, loop_thread):
        monkeypatch.setattr(rpc, "MAX_FRAME", 64)

        async def scenario():
            srv = RpcServer()

            async def echo(req):
                return req

            srv.register_unary("Echo", echo)
            await srv.start()
            try:
                cli = RpcClient("127.0.0.1", srv.port)
                await cli.connect()
                assert await cli.unary("Echo", b"small") == b"small"
                with pytest.raises(FrameTooLargeError):
                    await cli.unary("Echo", b"x" * 100)
                # the typed error surfaced CLIENT-side; the link lives
                assert await cli.unary("Echo", b"again") == b"again"
                await cli.close()
            finally:
                await srv.stop()

        loop_thread.run(scenario())

    def test_err_frames_carry_the_method_name(self, loop_thread):
        async def scenario():
            srv = RpcServer()

            async def boom(req):
                raise ValueError("kaputt")

            srv.register_unary("Frobnicate", boom)
            await srv.start()
            try:
                cli = RpcClient("127.0.0.1", srv.port)
                await cli.connect()
                with pytest.raises(RpcError, match="Frobnicate"):
                    await cli.unary("Frobnicate", b"x")
                with pytest.raises(RpcError, match="NoSuchMethod"):
                    await cli.unary("NoSuchMethod", b"x")
                await cli.close()
            finally:
                await srv.stop()

        loop_thread.run(scenario())


# -- loopback link ----------------------------------------------------------


class TestLoopback:
    def test_round_trip_and_share_metrics(self, loop_thread):
        srv = make_server(loop_thread)
        link = make_link(srv, tenant="chanA")
        try:
            h = link.submit([(1, 1, 0, 0, 0), (2, 0, 0, 0, 0)])
            assert h.fetch() == [True, False]
            assert h() == [True, False]  # cached refetch shape
            many = link.submit_many([[(1, 1, 0, 0, 0)], [(2, 0, 0, 0, 0)]])
            assert [m() for m in many] == [[True], [False]]
            stats = srv.scheduler.stats()["chanA"]
            assert stats["enqueued"] == 3 and stats["rejected"] == 0
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_dispatch_fault_is_a_typed_error_not_a_dead_stream(
        self, loop_thread
    ):
        srv = make_server(loop_thread)
        link = make_link(srv)
        faults.configure("sidecar.dispatch:raise:n=1")
        try:
            with pytest.raises(SidecarUnavailable, match="dispatch error"):
                link.submit([(1, 1, 0, 0, 0)]).fetch()
            # the stream survived the typed error: next batch serves
            assert link.submit([(2, 1, 0, 0, 0)]).fetch() == [True]
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_short_verdict_vector_is_rejected_not_indexed(
        self, loop_thread
    ):
        # the sidecar is a remote trust boundary: a verdict vector that
        # does not match the batch length must surface as
        # SidecarUnavailable (→ local re-verify), never flow onward
        srv = make_server(
            loop_thread, verify_fn=lambda sets: [[True] for _ in sets]
        )
        link = make_link(srv)
        try:
            with pytest.raises(SidecarUnavailable, match="2-signature"):
                link.submit([(1, 1, 0, 0, 0), (2, 0, 0, 0, 0)]).fetch()
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_set_coalesce_applies_at_drain_boundary(self, loop_thread):
        """The PR-10 follow-up actuator: a latched coalesce re-knob
        applies before the NEXT batch pop, never between a pop and its
        dispatch — pinned by gating the device lane while the backlog
        builds and the knob changes."""
        gate = threading.Event()
        sizes = []

        def gated_verify(itemsets):
            sizes.append(len(itemsets))
            if len(sizes) == 1:
                gate.wait(10.0)
            return toy_verify(itemsets)

        srv = make_server(loop_thread, verify_fn=gated_verify,
                          coalesce=4, queue_blocks=8)
        link = make_link(srv)
        try:
            # first batch pops alone and wedges the dispatcher on the
            # gate; four more queue up behind it
            handles = [link.submit([(1, 1, 0, 0, 0)])]
            deadline = time.monotonic() + 5.0
            while not sizes and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sizes == [1]
            handles += [
                link.submit([(i, 1, 0, 0, 0)]) for i in range(2, 6)
            ]
            deadline = time.monotonic() + 5.0
            while srv.scheduler.pending() < 4 and (
                    time.monotonic() < deadline):
                time.sleep(0.01)
            srv.set_coalesce(2)          # latched mid-backlog
            srv.set_verify_chunk(1024)   # rides the same boundary
            assert srv.coalesce == 4     # not yet applied
            gate.set()
            assert [h.fetch() for h in handles] == [[True]] * 5
            # the drain boundary adopted both knobs; the backlog went
            # out in groups of the NEW size
            assert srv.coalesce == 2 and srv.verify_chunk == 1024
            assert sizes == [1, 2, 2]
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_sidecar_local_autopilot_actuates_coalesce(
        self, loop_thread
    ):
        """Server-side knob actuation off the sidecar's OWN scheduler
        stats: a queue-age signal drives the local controller, whose
        decision lands on the live dispatch via set_coalesce."""
        from fabric_tpu.control import Autopilot, Signals
        from fabric_tpu.observe import Tracer

        srv = make_server(loop_thread, coalesce=4)
        ap = Autopilot(
            None,
            lambda k, v: (srv.set_coalesce(int(v))
                          if k == "coalesce_blocks" else None),
            set_weight=srv.scheduler.set_weight,
            set_shed=srv.scheduler.set_shed,
            scheduler=srv.scheduler,
            tracer=Tracer(ring_blocks=4, slow_factor=0),
            registry=Registry(),
            initial={"coalesce_blocks": 4},
        )
        link = make_link(srv)
        try:
            d = ap.tick(Signals(queue_age_p99_ms={"chan": 500.0},
                                clock_s=20.0))
            assert (d.knob, d.direction, d.new) == (
                "coalesce_blocks", "up", 5
            )
            assert srv._pending_coalesce == 5   # latched on the server
            # one round trip crosses a drain boundary → applied
            assert link.submit([(1, 1, 0, 0, 0)]).fetch() == [True]
            assert srv.coalesce == 5
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_rpc_frame_fault_cuts_the_link_then_reattaches(
        self, loop_thread
    ):
        srv = make_server(loop_thread)
        link = make_link(srv)
        try:
            assert link.submit([(1, 1, 0, 0, 0)]).fetch() == [True]
            # cut ONE frame send on the live link: the in-flight fetch
            # fails typed, the next submit reconnects transparently
            faults.configure("rpc.frame:disconnect:n=1")
            with pytest.raises(SidecarUnavailable):
                link.submit([(2, 1, 0, 0, 0)]).fetch()
            faults.reset()
            assert link.submit([(3, 0, 0, 0, 0)]).fetch() == [False]
        finally:
            link.close()
            loop_thread.run(srv.stop())


# -- backpressure -----------------------------------------------------------


def test_backpressure_surfaces_as_busy_backoff_not_deadlock(loop_thread):
    """Queue bound 2, gated dispatch, 10 concurrent batches: the
    overflow answers BUSY, the client's Backoff absorbs it, everything
    completes — no deadlock, no drop."""
    gate = threading.Event()

    def gated_verify(itemsets):
        assert gate.wait(timeout=20.0), "test gate never opened"
        return toy_verify(itemsets)

    reg = Registry()
    srv = make_server(loop_thread, verify_fn=gated_verify,
                      queue_blocks=2, coalesce=1, registry=reg)
    clireg = Registry()
    link = make_link(
        srv, busy_retries=100, registry=clireg,
        backoff=Backoff(base=0.005, cap=0.05, jitter=0.5),
        timeout_s=20.0,
    )
    try:
        handles = [
            link.submit([(i, i % 2, 0, 0, 0)]) for i in range(10)
        ]
        # let the overflow hit the bounded queue before opening
        deadline = time.monotonic() + 5.0
        while (srv.scheduler.stats().get("chan", {}).get("rejected", 0) == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        gate.set()
        got = [h.fetch() for h in handles]
        assert got == [[bool(i % 2)] for i in range(10)]
        busy = clireg.counter("sidecar_client_busy_total")
        assert busy.value(tenant="chan") > 0  # backpressure really bit
        assert srv.scheduler.stats()["chan"]["rejected"] > 0
    finally:
        gate.set()
        link.close()
        loop_thread.run(srv.stop())


# -- toy validator over the sidecar (the differential) ----------------------


class ToyPtx:
    __slots__ = ("txid", "idx", "is_config")

    def __init__(self, txid, idx, is_config=False):
        self.txid, self.idx, self.is_config = txid, idx, is_config


class ToyPending:
    def __init__(self, block, txs, raw, sigs, overlay, extra):
        self.block, self.txs, self.raw = block, txs, raw
        self.sigs, self.overlay, self.extra = sigs, overlay, extra
        self.hd_bytes = None

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class SidecarToyValidator:
    """The crypto-free toy-validator protocol with its signature lane
    behind a SidecarLink + DeviceLaneGuard — DeviceToyValidator-style
    lanes over the REAL server/scheduler/link stack.  Sidecar lane and
    local lane compute identical verdicts, so the differential proves
    the sidecar changes WHERE signatures verify, never WHAT commits."""

    VALID, DUP, BADSIG, MVCC = 0, 2, 8, 11

    def __init__(self, state, link=None, guard=None):
        self.state = state
        self.link = link
        self.guard = guard
        self.lanes: list = []  # "sidecar" | "local" per block

    def _sig_verdicts(self, tuples):
        def local():
            return [bool(t[1]) for t in tuples]

        if self.link is None:
            self.lanes.append("local")
            return local()
        if self.guard is None:
            self.lanes.append("sidecar")
            return self.link.submit(tuples).fetch()
        out = self.guard.run_launch(
            lambda: self.link.submit(tuples), local
        )
        if isinstance(out, list):  # the guard routed to the local lane
            self.lanes.append("local")
            return out
        try:
            verdicts = out.fetch()
        except SidecarUnavailable:
            # fetch-side loss: count toward the latch, verify locally
            self.guard.record_failure()
            self.guard.count_fallback()
            self.lanes.append("local")
            return local()
        self.guard.record_success()
        self.lanes.append("sidecar")
        return verdicts

    def preprocess(self, block):
        raw = [json.loads(bytes(d)) for d in block.data.data]
        tuples = [
            (i, 0 if t.get("sig", True) is False else 1, 0, 0, 0)
            for i, t in enumerate(raw)
        ]
        return raw, self._sig_verdicts(tuples)

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw, sigs = pre if pre is not None else self.preprocess(block)
        txs = [
            ToyPtx(t["id"], i, bool(t.get("config")))
            for i, t in enumerate(raw)
        ]
        return ToyPending(block, txs, raw, sigs, overlay, extra_txids)

    def _version(self, ns, key, overlay):
        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                return None if vv.value is None else list(vv.version)
        vv = self.state.get_state(ns, key)
        return None if vv is None else list(vv.version)

    @staticmethod
    def _ns(key):
        return "_lifecycle" if key.startswith("_lifecycle/") else "ns"

    def validate_finish(self, pend):
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for ptx, t, sig_ok in zip(pend.txs, pend.raw, pend.sigs):
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            if not sig_ok:
                codes.append(self.BADSIG)
                continue
            ok = all(
                self._version(self._ns(k), k, pend.overlay) == want
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put(self._ns(k), k, val.encode(), (num, ptx.idx))
        return bytes(codes), batch, []


def _toy_stream(n_blocks=10, n_tx=5):
    """Dependent toy stream: an overlay-read lane, a stale-read lane,
    a bad-signature lane, and a mid-stream lifecycle barrier."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"tx{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}  # via overlay
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [0, 0]}      # stale → MVCC
            if i == 2 and n % 3 == 1:
                t["sig"] = False                         # bad signature
            txs.append(t)
        if n == 4:
            txs[-1]["writes"]["_lifecycle/cc1"] = "defn"  # barrier
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _drive(blocks, validator, depth=2):
    state = validator.state
    filters: dict[int, list] = {}
    height = [0]

    def commit_fn(res):
        num = res.block.header.number
        assert num == height[0], "commit out of order"
        state.apply_updates(res.batch, (num, 0))
        filters[num] = list(res.tx_filter)
        height[0] = num + 1

    with CommitPipeline(validator, commit_fn, depth=depth) as pipe:
        for blk in blocks:
            pipe.submit(blk)
        pipe.flush()
    return filters, dict(state._data)


def _toy_guard(recovery_s=0.0):
    return DeviceLaneGuard(
        retries=0, fail_threshold=1, recovery_s=recovery_s,
        backoff=Backoff(base=0.001, cap=0.002, jitter=0.0),
        sleep=lambda s: None, channel="toy", registry=Registry(),
    )


def test_sidecar_matches_local_serial_oracle(loop_thread):
    """THE differential: a block stream validated through the loopback
    sidecar (depth-2 pipeline, guard armed) commits the identical
    accept set AND state as the in-process serial oracle — bad-sig,
    MVCC, dup and barrier lanes included."""
    blocks = _toy_stream(10, 5)

    f_oracle, s_oracle = _drive(
        blocks, SidecarToyValidator(MemVersionedDB()), depth=1
    )
    assert sorted(f_oracle) == list(range(10))

    srv = make_server(loop_thread)
    link = make_link(srv, tenant="toychan")
    try:
        v = SidecarToyValidator(MemVersionedDB(), link=link,
                                guard=_toy_guard())
        f_side, s_side = _drive(blocks, v, depth=2)
    finally:
        link.close()
        loop_thread.run(srv.stop())

    assert f_side == f_oracle
    assert s_side == s_oracle
    assert set(v.lanes) == {"sidecar"}  # every block rode the sidecar
    # the load-bearing lanes really exercised failure codes
    flat = [c for codes in f_oracle.values() for c in codes]
    assert SidecarToyValidator.BADSIG in flat
    assert SidecarToyValidator.MVCC in flat


def test_two_tenant_storm_shares_track_weights(loop_thread):
    """2 tenants, weights 1:3, queues pre-filled behind a gated
    dispatch: while both are backlogged, the served-signature shares
    must sit within 20% of the weight ratio, and nobody starves."""
    gate = threading.Event()
    snapshots = []

    srv_ref = []

    def gated_verify(itemsets):
        assert gate.wait(timeout=20.0), "test gate never opened"
        snapshots.append(srv_ref[0].scheduler.stats())
        return toy_verify(itemsets)

    srv = make_server(loop_thread, verify_fn=gated_verify,
                      queue_blocks=32, coalesce=1, quantum=8)
    srv_ref.append(srv)
    la = make_link(srv, tenant="tenantA", weight=1.0, timeout_s=30.0)
    lb = make_link(srv, tenant="tenantB", weight=3.0, timeout_s=30.0)
    try:
        n_req, cost = 20, 8
        batch = [(i, 1, 0, 0, 0) for i in range(cost)]
        ha = [la.submit(batch) for _ in range(n_req)]
        hb = [lb.submit(batch) for _ in range(n_req)]
        # wait for the backlog to land in the scheduler queues
        deadline = time.monotonic() + 5.0
        while (srv.scheduler.pending() < 2 * n_req - 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        gate.set()
        for h in ha + hb:
            assert h.fetch() == [True] * cost  # nobody starves
        # mid-drain snapshot (both tenants still backlogged): shares
        # must track weights within the 20% acceptance tolerance
        mid = None
        for snap in snapshots:
            a, b = snap.get("tenantA"), snap.get("tenantB")
            if not a or not b:
                continue
            served = a["served_cost"] + b["served_cost"]
            if a["depth"] > 0 and b["depth"] > 0 and served >= 12 * cost:
                mid = (a, b)
        assert mid is not None, "no mid-drain snapshot with backlog"
        a, b = mid
        total = a["served_cost"] + b["served_cost"]
        assert abs(b["served_cost"] / total - 0.75) < 0.75 * 0.20
        assert abs(a["served_cost"] / total - 0.25) < 0.25 * 0.20 + 0.05
    finally:
        gate.set()
        la.close()
        lb.close()
        loop_thread.run(srv.stop())


def test_sidecar_kill_restart_recovers_through_probe(loop_thread):
    """Kill the sidecar mid-stream: in-flight and subsequent blocks
    route through the local fallback (guard latches, channel stays
    live), and once the sidecar returns the recovery probe re-attaches
    — the accept set equals the fault-free oracle throughout."""
    blocks = _toy_stream(12, 4)
    f_oracle, s_oracle = _drive(
        blocks, SidecarToyValidator(MemVersionedDB()), depth=1
    )

    srv = make_server(loop_thread)
    port = srv.port
    link = make_link(srv, tenant="killchan", timeout_s=5.0)
    guard = _toy_guard(recovery_s=0.0)  # probe on every block
    v = SidecarToyValidator(MemVersionedDB(), link=link, guard=guard)

    state = v.state
    filters: dict[int, list] = {}
    height = [0]

    def commit_fn(res):
        num = res.block.header.number
        assert num == height[0]
        state.apply_updates(res.batch, (num, 0))
        filters[num] = list(res.tx_filter)
        height[0] = num + 1

    restarted = []
    try:
        with CommitPipeline(v, commit_fn, depth=2) as pipe:
            for blk in blocks:
                n = blk.header.number
                if n == 4:
                    # mid-stream kill — requests in flight die typed
                    loop_thread.run(srv.stop())
                if n == 8:
                    # sidecar returns ON THE SAME PORT; the guard's
                    # next probe must re-attach the stream
                    srv2 = make_server(loop_thread, port=port)
                    restarted.append(srv2)
                pipe.submit(blk)
            pipe.flush()
    finally:
        link.close()
        for s in restarted:
            loop_thread.run(s.stop())
        if not restarted:
            loop_thread.run(srv.stop())

    # identical accept set and state across kill + restart
    assert filters == f_oracle
    assert dict(state._data) == s_oracle
    # the lane actually degraded AND re-attached
    assert "local" in v.lanes and "sidecar" in v.lanes
    assert v.lanes[0] == "sidecar"          # attached at start
    assert "local" in v.lanes[3:8]          # rode the latch while down
    assert v.lanes[-1] == "sidecar"         # re-attached at the end
    assert not guard.degraded               # probe re-armed the lane


# -- cross-process trace propagation (ISSUE 9 tentpole) ----------------------


class _SkewClock:
    """perf_counter shifted by a constant — a 'different process
    clock' for offset-estimation tests."""

    def __init__(self, skew_s: float):
        self.skew = float(skew_s)

    def __call__(self) -> float:
        return time.perf_counter() + self.skew


def _stitched(root):
    return [c for c in root.children if c.name == "sidecar_request"]


def test_trace_stitches_across_the_wire_under_clock_skew(loop_thread):
    """THE tentpole shape: the peer's block root gains the sidecar's
    queue_wait/dispatch children on sidecar-labelled process rows,
    with the remote clock's +123s skew estimated away by the
    request/response midpoints."""
    from fabric_tpu.observe import Tracer

    SKEW = 123.0
    server_tr = Tracer(ring_blocks=8, slow_factor=0,
                       clock=_SkewClock(SKEW))
    srv = make_server(loop_thread, tracer=server_tr)
    client_tr = Tracer(ring_blocks=8, slow_factor=0)
    link = make_link(srv, tenant="chanA", tracer=client_tr)
    try:
        root = client_tr.begin_block(7, channel="chanA")
        tok = client_tr.attach(root)
        try:
            # submit from UNDER a child span, the validator shape —
            # the stitch must still target the block ROOT
            with client_tr.span("sig_prepare_launch", parent=root):
                h = link.submit([(1, 1, 0, 0, 0), (2, 0, 0, 0, 0)])
            assert h.fetch() == [True, False]
        finally:
            client_tr.detach(tok)
        client_tr.finish_block(root)

        (remote,) = _stitched(root)
        assert remote.proc == "sidecar"
        names = [c.name for c in remote.children]
        assert "queue_wait" in names and "dispatch" in names
        assert all(c.proc == "sidecar" for c in remote.children)
        # the server rooted its tree under the propagated context
        assert remote.attrs.get("peer_block") == 7
        assert remote.attrs.get("ns") == "sidecar"
        # offset estimation: the +123s skew is recovered to within
        # loopback round-trip slack
        off_ms = remote.attrs["clock_offset_ms"]
        assert abs(off_ms - SKEW * 1000.0) < 100.0
        assert remote.attrs["rtt_ms"] >= 0.0
        # timestamps aligned: the stitched subtree lands inside the
        # local block window (the acceptance 'offsets sane' criterion)
        eps = 0.1
        assert root.t0 - eps <= remote.t0 <= root.t1 + eps
        assert remote.t1 <= root.t1 + eps
        for c in remote.children:
            assert root.t0 - eps <= c.t0 and c.t1 <= root.t1 + eps

        # the whole waterfall survives the JSON tree and the Chrome
        # export with a DISTINCT process row
        tree = client_tr.block(7)
        procs = {
            ch.get("proc") for ch in tree["children"]
        }
        assert "sidecar" in procs
        events = client_tr.chrome_events()
        pnames = {
            e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert "sidecar" in pnames.values() and "local" in pnames.values()
        sidecar_pid = next(p for p, n in pnames.items() if n == "sidecar")
        remote_evs = [e for e in events if e.get("ph") == "X"
                      and e.get("pid") == sidecar_pid]
        assert {e["name"] for e in remote_evs} >= {
            "sidecar_request", "queue_wait", "dispatch"
        }
        # remote events carry the PEER block number, so Perfetto (and
        # traceview) group the full cross-process waterfall per block
        assert all(e["args"]["block"] == 7 for e in remote_evs)
    finally:
        link.close()
        loop_thread.run(srv.stop())


def test_no_trace_context_no_remote_payload(loop_thread):
    """A submit with no current span (no block in flight) must not
    grow response frames — the remote field is opt-in per request."""
    from fabric_tpu.observe import Tracer

    srv = make_server(loop_thread, tracer=Tracer(ring_blocks=8,
                                                 slow_factor=0))
    link = make_link(srv, tracer=Tracer(ring_blocks=8, slow_factor=0))
    try:
        assert link.submit([(1, 1, 0, 0, 0)]).fetch() == [True]
    finally:
        link.close()
        loop_thread.run(srv.stop())


def test_wire_trace_header_roundtrip():
    t = [(1, 1, 0, 0, 0)]
    trace = {"block": 9, "root": 42, "tenant": "chanX"}
    hdr, items = wire.decode_request(wire.encode_request(3, t, trace))
    assert hdr["trace"] == trace and items == t
    hdr, _ = wire.decode_request(wire.encode_request(4, t))
    assert "trace" not in hdr
    remote = {"spans": {"name": "block"}, "t_rx": 1.0, "t_tx": 2.0}
    hdr, v = wire.decode_response(
        wire.encode_response(3, [True], remote=remote)
    )
    assert hdr["remote"] == remote and v == [True]


def test_sidecar_requests_get_their_own_ring(loop_thread):
    """The satellite collision fix: a colocated server sharing the
    peer's tracer must neither evict peer block trees with its
    request trees nor shadow block numbers at block()/trace?block=N."""
    from fabric_tpu.observe import Tracer

    tr = Tracer(ring_blocks=4, slow_factor=0)
    # peer blocks 0..3 fill the default ring
    for n in range(4):
        tr.finish_block(tr.begin_block(n, channel="chanA"))
    srv = make_server(loop_thread, tracer=tr)
    link = make_link(srv, tenant="chanA", tracer=tr)
    try:
        # a storm of MORE requests than the ring holds
        for i in range(8):
            assert link.submit([(i, 1, 0, 0, 0)]).fetch() == [True]
    finally:
        link.close()
        loop_thread.run(srv.stop())
    # peer trees all survived the request storm
    assert [b["block"] for b in tr.blocks()] == [0, 1, 2, 3]
    # request trees live in their own namespace, ids never colliding
    # with peer block numbers
    reqs = tr.blocks(ns="sidecar")
    assert len(reqs) == 4  # ring-bounded, evicting only each other
    assert [b["block"] for b in reqs] == [5, 6, 7, 8]
    # block 2 resolves to the PEER tree; request 2 was evicted from
    # its own ring without touching it
    assert tr.block(2)["attrs"]["channel"] == "chanA"
    assert tr.block(2, ns="sidecar") is None
    assert tr.block(6, ns="sidecar")["attrs"]["channel"] == "sidecar:chanA"
    assert tr.namespaces() == {"": 4, "sidecar": 4}


def test_scheduler_telemetry_queue_age_deficit_busy():
    reg = Registry()
    s = WeightedScheduler(queue_limit=2, quantum=4, registry=reg)
    s.register("a", 1.0)
    s.submit(Request("a", 0, [0]))
    s.submit(Request("a", 1, [0]))
    assert not s.submit(Request("a", 2, [0]))  # BUSY
    assert reg.counter("sidecar_busy_total").value(tenant="a") == 1
    time.sleep(0.01)
    batch = s.next_batch(4)
    assert len(batch) == 2
    age = reg.metric("sidecar_queue_age_seconds").value(tenant="a")
    assert age["count"] == 2 and age["sum"] > 0.0
    st = s.stats()["a"]
    assert st["queue_age_ms"]["n"] == 2
    assert st["queue_age_ms"]["p99"] >= st["queue_age_ms"]["p50"] > 0.0
    assert st["busy_rate"] == pytest.approx(1 / 3, abs=1e-4)
    assert "deficit" in st
    # ages survive a disconnect + re-register like the other totals
    s.unregister("a")
    s.register("a", 1.0)
    assert s.stats()["a"]["queue_age_ms"]["n"] == 2


# -- SLO fast burn under an injected latency fault ---------------------------


class _StepClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


def test_slo_burn_trips_under_latency_fault_and_recovers(loop_thread):
    """The acceptance criterion: a 5x latency fault on
    sidecar.dispatch drives the request-latency SLO burn ≥ 1; after
    the fault clears (and the window rolls), burn returns < 1."""
    from fabric_tpu.observe import Tracer
    from fabric_tpu.observe.slo import SloEngine, parse_slos

    tr = Tracer(ring_blocks=16, slow_factor=0)
    clk = _StepClock()
    eng = SloEngine(
        parse_slos("req:latency:ms=50:target=0.8:windows=60:fast=0"),
        clock=clk, registry=Registry(),
    )
    tr.add_listener(eng.on_block)
    srv = make_server(loop_thread, tracer=tr)
    link = make_link(srv, tenant="chan", tracer=tr)

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    ops = loop_thread.run(OperationsServer(
        port=0, registry=Registry(), health=HealthRegistry(),
        tracer=tr, slo=eng,
    ).start())

    def slo_burn():
        """The operator's view: burn off a live GET /slo."""
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{ops.port}/slo", timeout=10
        ) as r:
            rep = json.loads(r.read())
        (obj,) = rep["objectives"]
        return obj["channels"]["sidecar:chan"]["burn"]["60s"]

    try:
        for i in range(5):  # healthy baseline: ~ms round trips
            assert link.submit([(i, 1, 0, 0, 0)]).fetch() == [True]
            clk.advance(1.0)
        assert eng.burn("req", "sidecar:chan") == 0.0

        # 5x the threshold: every dispatch sleeps 250ms > 50ms budget
        faults.configure("sidecar.dispatch:latency:ms=250")
        for i in range(4):
            assert link.submit([(i, 1, 0, 0, 0)]).fetch() == [True]
            clk.advance(1.0)
        assert slo_burn() >= 1.0  # /slo reports the burn

        faults.reset()
        clk.advance(120.0)  # the storm ages out of the window
        for i in range(5):
            assert link.submit([(i, 1, 0, 0, 0)]).fetch() == [True]
            clk.advance(1.0)
        assert slo_burn() < 1.0  # recovered
    finally:
        tr.remove_listener(eng.on_block)
        link.close()
        loop_thread.run(ops.stop())
        loop_thread.run(srv.stop())


# -- live re-weighting + shed mode (ISSUE 11) -------------------------------


class TestSetWeightAndShed:
    def test_set_weight_updates_live_registration_in_place(self):
        """The satellite pin: a weight change preserves the deficit
        credit and trailing stats — no disconnect/re-register."""
        s = _sched(queue_limit=100, quantum=10)
        s.register("a", 1.0)
        s.register("b", 1.0)
        for i in range(6):
            assert s.submit(Request("a", i, [0] * 25))
            assert s.submit(Request("b", i, [0] * 25))
        s.next_batch(2)  # builds served totals, ages and deficits
        before = s.stats()["a"]
        assert s.set_weight("a", 4.0) is True
        after = s.stats()["a"]
        assert after["weight"] == 4.0
        # everything else carried over IN PLACE
        for key in ("served_cost", "enqueued", "rejected", "deficit",
                    "queue_age_ms", "depth"):
            assert after[key] == before[key], key
        # and the rotation honors the new weight going forward: a
        # drains ~4x b's signatures from here
        served = {"a": 0, "b": 0}
        while True:
            batch = s.next_batch(1)
            if not batch:
                break
            for r in batch:
                served[r.tenant] += r.cost
        assert served["a"] == served["b"]  # both fully drain

    def test_set_weight_unknown_tenant_updates_retired(self):
        s = _sched()
        assert s.set_weight("ghost", 2.0) is False
        s.register("t", 1.0)
        s.unregister("t")
        assert s.set_weight("t", 5.0) is False  # retired, not live
        s.register("t")  # re-register picks the retired default? no —
        # register()'s OWN weight argument wins; the retired update
        # only matters for bookkeeping continuity
        assert s.weight("t") == 1.0

    def test_set_weight_rejects_nonpositive(self):
        s = _sched()
        s.register("t", 1.0)
        with pytest.raises(ValueError):
            s.set_weight("t", 0.0)

    def test_shed_mode_bounces_arrivals_and_accounts_exactly(self):
        reg = Registry()
        s = _sched(queue_limit=4, registry=reg)
        s.register("t", 1.0)
        assert s.submit(Request("t", 1, [0]))       # admitted
        s.set_shed("t", True)
        assert s.is_shed("t")
        for i in range(2, 5):
            assert not s.submit(Request("t", i, [0]))
        st = s.stats()["t"]
        assert st["shed"] is True
        assert st["shed_count"] == 3 and st["rejected"] == 3
        assert reg.counter("sidecar_shed_total").value(tenant="t") == 3
        assert reg.counter("sidecar_busy_total").value(tenant="t") == 3
        # what was ADMITTED still completes — shed bounds new work only
        assert [r.seq for r in s.next_batch(8)] == [1]
        s.set_shed("t", False)
        assert s.submit(Request("t", 9, [0]))
        assert s.stats()["t"]["shed_count"] == 3   # no more shed counts

    def test_shed_survives_reconnect(self):
        s = _sched()
        s.register("t", 1.0)
        s.set_shed("t", True)
        s.unregister("t")
        s.register("t", 1.0)
        assert not s.submit(Request("t", 1, [0]))  # still shed by name

    def test_rehello_over_the_wire_updates_weight_in_place(
            self, loop_thread):
        srv = make_server(loop_thread, queue_blocks=8)
        link = make_link(srv, tenant="chan", weight=1.0)
        try:
            assert link.submit([(1, 1, 0, 0, 0)]).fetch() == [True]
            before = srv.scheduler.stats()["chan"]
            assert before["weight"] == 1.0
            assert link.set_weight(3.0) is True
            after = srv.scheduler.stats()["chan"]
            assert after["weight"] == 3.0
            # live registration updated in place: the stream never
            # dropped and the trailing stats carried over
            assert after["enqueued"] == before["enqueued"]
            assert after["served_cost"] == before["served_cost"]
            # the stream still serves requests after the re-hello
            assert link.submit([(2, 0, 0, 0, 0)]).fetch() == [False]
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_rehello_cannot_reweight_another_tenant(self, loop_thread):
        import json as _json

        srv = make_server(loop_thread, queue_blocks=8)
        srv.scheduler.register("victim", 1.0)
        link = make_link(srv, tenant="chan", weight=1.0)
        try:
            assert link.submit([(1, 1, 0, 0, 0)]).fetch() == [True]

            asyncio.run_coroutine_threadsafe(
                link._stream.send(_json.dumps(
                    {"tenant": "victim", "weight": 9.0}
                ).encode()),
                link._loop,
            ).result(5.0)
            # the server answers a typed error and tears the stream;
            # the victim's weight is untouched
            import time as _t

            for _ in range(100):
                if srv.scheduler.weight("victim") != 1.0:
                    break
                _t.sleep(0.01)
            assert srv.scheduler.weight("victim") == 1.0
        finally:
            link.close()
            loop_thread.run(srv.stop())

    def test_shed_end_to_end_answers_busy_with_long_retry(
            self, loop_thread):
        from fabric_tpu.sidecar.client import SidecarUnavailable
        from fabric_tpu.sidecar.server import SHED_RETRY_MS

        srv = make_server(loop_thread, queue_blocks=8)
        link = make_link(srv, tenant="chan", busy_retries=1,
                         timeout_s=10.0)
        try:
            assert link.submit([(1, 1, 0, 0, 0)]).fetch() == [True]
            srv.scheduler.set_shed("chan", True)
            with pytest.raises(SidecarUnavailable):
                link.submit([(2, 1, 0, 0, 0)]).fetch()
            st = srv.scheduler.stats()["chan"]
            assert st["shed_count"] >= 1
            # the status counter distinguishes shed from queue-full
            assert srv._req_ctr.value(tenant="chan", status="shed") >= 1
            assert SHED_RETRY_MS > 20.0  # back-off-hard advisory
            srv.scheduler.set_shed("chan", False)
            assert link.submit([(3, 1, 0, 0, 0)]).fetch() == [True]
        finally:
            link.close()
            loop_thread.run(srv.stop())


def test_pct_is_nearest_rank():
    # round(x + .5) is NOT ceil: banker's rounding sends exact .5
    # midpoints to the even rank (p50 of 2 samples returned rank 2)
    from fabric_tpu.sidecar.scheduler import _pct

    assert _pct([1.0, 2.0], 50) == 1.0
    assert _pct([1.0, 2.0, 3.0, 4.0, 5.0, 6.0], 50) == 3.0
    assert _pct([1.0, 2.0, 3.0], 99) == 3.0
    assert _pct([], 50) == 0.0


def test_stitch_tolerates_malformed_remote_payload():
    """The remote tree is trust-boundary metadata: a skewed sidecar
    shipping garbage must not fail the verify path (which would feed
    the caller's degrade latch)."""
    from fabric_tpu.observe import Tracer
    from fabric_tpu.sidecar.client import SidecarLink

    tr = Tracer(ring_blocks=4, slow_factor=0)
    link = SidecarLink.__new__(SidecarLink)  # no connection needed
    link.tracer = tr
    root = tr.begin_block(1)
    for bad in (
        {"spans": "not a tree", "t_rx": 1.0, "t_tx": 2.0},
        {"spans": {"children": ["not a span"]}, "t_rx": 1.0, "t_tx": 2.0},
        {"spans": {"name": "x"}, "t_rx": "nan?", "t_tx": None},
        {"t_rx": 1.0, "t_tx": 2.0},
        "not a dict",
    ):
        link._stitch(root, bad, 0.0, 0.0)  # must not raise
    # nothing half-stitched leaked into the tree
    assert [c.name for c in root.children] == []


def test_nodeconfig_rejects_bad_slo_spec():
    from fabric_tpu.nodeconfig import ConfigError, load_peer_config

    base = {"id": "p0", "data_dir": "/tmp/x", "msp_id": "Org1MSP",
            "msp_dir": "/tmp/msp"}
    with pytest.raises(ConfigError, match="slos"):
        load_peer_config({**base, "slos": "req:frobnicate:ms=5"},
                         environ={})
    cfg = load_peer_config(
        {**base, "slos": "req:latency:ms=50;busy:busy:pct=5"},
        environ={},
    )
    assert cfg.slos.startswith("req:")

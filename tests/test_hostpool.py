"""Host staging pool unit battery (parallel/hostpool.py): knob
resolution semantics, bucket-aligned lane sharding, ordered fan-out,
error propagation, telemetry, and the process-mode smoke."""

import os

import pytest

from fabric_tpu.parallel.hostpool import HostStagePool, resolve_host_pool


def test_resolve_semantics():
    # 0 = off; 1 = pointless (queue overhead, no parallelism)
    assert resolve_host_pool(0) is None
    assert resolve_host_pool(1) is None
    cores = os.cpu_count() or 1
    auto = resolve_host_pool(-1)
    if cores >= 2:
        assert auto is not None and auto.workers == cores
        auto.shutdown()
        p = resolve_host_pool(2)
        assert p is not None and p.workers == 2
        p.shutdown()
        # clamped to the core count
        big = resolve_host_pool(10_000)
        assert big is not None and big.workers == cores
        big.shutdown()
    else:
        assert auto is None


def test_constructor_guards():
    with pytest.raises(ValueError):
        HostStagePool(1)
    with pytest.raises(ValueError):
        HostStagePool(2, mode="fork")


def test_slice_bounds_bucket_aligned():
    with HostStagePool(2) as p:
        assert p.slice_bounds(0, align=16) == []
        # every interior boundary is a multiple of align; the union
        # covers [0, n) exactly with the tail absorbing the remainder
        for n in (1, 15, 16, 17, 100, 128, 3072):
            bounds = p.slice_bounds(n, align=16)
            assert bounds[0][0] == 0 and bounds[-1][1] == n
            for (a, b), (c, d) in zip(bounds, bounds[1:]):
                assert b == c and b % 16 == 0
            assert len(bounds) <= p.workers
        # a sub-bucket batch stays one slice (serial fallback upstream)
        assert p.slice_bounds(8, align=16) == [(0, 8)]


def test_map_ordered_and_map_slices():
    with HostStagePool(2) as p:
        assert p.map(lambda x: x * x, range(20), stage="sq") == [
            x * x for x in range(20)
        ]
        got = p.map_slices(100, lambda lo, hi: (lo, hi), align=16)
        assert got[0][0] == 0 and got[-1][1] == 100
        stats = p.stats()
        assert stats["workers"] == 2 and stats["tasks"] >= 21
        assert stats["per_shard_p50_ms"] >= 0.0


def test_error_propagates():
    def boom(x):
        if x == 3:
            raise RuntimeError("shard failed")
        return x

    with HostStagePool(2) as p:
        with pytest.raises(RuntimeError, match="shard failed"):
            p.map(boom, range(6))


def test_error_carries_stage_and_worker_labels():
    """A raising worker task must not wedge the ordered map or drop a
    shard silently: the FIRST error propagates with the failing
    stage/worker attached (message suffix + attributes), type
    preserved."""

    def boom(x):
        if x == 2:
            raise ValueError("bad shard")
        return x

    with HostStagePool(2) as p:
        with pytest.raises(ValueError, match=r"bad shard \[host pool "
                           r"stage=recode worker=") as ei:
            p.map(boom, range(6), stage="recode")
        assert ei.value.fab_stage == "recode"
        assert ei.value.fab_worker
        # the pool still serves after the failure — nothing wedged
        assert p.map(lambda x: x + 1, range(4), stage="recode") == [
            1, 2, 3, 4
        ]


def test_injected_worker_fault_labeled_and_pool_survives():
    """The ``hostpool.task`` chaos point: exactly one task dies, the
    gather raises it (labeled), and the next map is clean once the
    budget is spent."""
    from fabric_tpu import faults

    faults.configure("hostpool.task:raise:n=1")
    try:
        with HostStagePool(2) as p:
            with pytest.raises(faults.InjectedFault) as ei:
                p.map(lambda x: x, range(8), stage="parse")
            assert ei.value.fab_stage == "parse"
            assert p.map(lambda x: x * 2, range(4), stage="parse") == [
                0, 2, 4, 6
            ]
    finally:
        faults.reset()


def test_telemetry_labels():
    from fabric_tpu.ops_metrics import global_registry

    with HostStagePool(2) as p:
        p.map(lambda x: x, range(4), stage="unit_probe")
    text = global_registry().render()
    assert "host_stage_pool_seconds" in text
    assert 'stage="unit_probe"' in text


def _wait_idle(p, timeout=5.0):
    """Done-callbacks (the in-flight decrement) can lag ``result()``
    by a beat; the resize contract is 'the next submit that finds the
    pool idle', so the tests wait for genuine idleness first."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with p._lock:
            if p._active == 0:
                return
        time.sleep(0.005)
    raise AssertionError("pool never drained")


def test_set_workers_resizes_at_idle_task_boundary():
    """The autopilot's host_stage_workers actuator: a latched resize
    applies drain-and-rebuild at the next submit that finds the pool
    idle — ordered results stay exact across the swap, and the live
    worker count follows."""
    with HostStagePool(2) as p:
        assert p.map(lambda x: x + 1, range(8), stage="rs") == list(
            range(1, 9)
        )
        cores = os.cpu_count() or 1
        want = max(2, min(3, cores))
        p.set_workers(want)
        # latched, not yet applied (no submit happened)
        assert p.stats().get("pending_workers") in (want, None)
        _wait_idle(p)
        assert p.map(lambda x: x * 2, range(8), stage="rs") == [
            2 * x for x in range(8)
        ]
        assert p.workers == want
        assert p.stats().get("pending_workers") is None
        # shrink back down; clamps below 2 (a pool below 2 workers is
        # a close, not a resize)
        p.set_workers(1)
        _wait_idle(p)
        p.map(lambda x: x, range(4), stage="rs")
        assert p.workers == 2


def test_set_workers_same_value_is_a_noop():
    with HostStagePool(2) as p:
        p.set_workers(2)
        assert p.stats().get("pending_workers") is None
        assert p.map(lambda x: x, range(4)) == [0, 1, 2, 3]
        assert p.workers == 2


def test_set_workers_never_strands_inflight_tasks():
    """A resize requested while tasks are in flight applies only once
    the pool drains — every in-flight shard completes on the executor
    that started it."""
    import threading
    import time

    gate = threading.Event()

    def slow(x):
        gate.wait(5.0)
        return x * 10

    with HostStagePool(2) as p:
        futs = [p.submit(slow, i, stage="slow") for i in range(4)]
        p.set_workers(3)
        # mid-flight submit must NOT trigger the swap (pool busy)
        extra = p.submit(slow, 99, stage="slow")
        assert p.workers == 2
        gate.set()
        assert [f.result(timeout=10) for f in futs] == [0, 10, 20, 30]
        assert extra.result(timeout=10) == 990
        # first idle submit adopts the resize
        _wait_idle(p)
        assert p.map(lambda x: x, range(4), stage="slow") == [0, 1, 2, 3]
        assert p.workers == max(2, min(3, os.cpu_count() or 1))


def test_validator_set_host_stage_workers_latches_at_block_boundary():
    """BlockValidator's actuator seam: latch → applied at the next
    ``_apply_pending_knobs`` (what preprocess() runs first) — build,
    resize, and close-to-serial transitions."""
    if (os.cpu_count() or 1) < 2:
        pytest.skip("needs 2 cores")
    pytest.importorskip("cryptography")  # validator imports the MSP stack
    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.peer.validator import BlockValidator

    class _NoPolicies:
        pass

    v = BlockValidator(None, _NoPolicies(), MemVersionedDB())
    try:
        assert v.host_pool is None
        # build a pool where none existed
        v.set_host_stage_workers(2)
        assert v.host_pool is None          # latched only
        v._apply_pending_knobs()
        assert v.host_pool is not None and v.host_pool.workers == 2
        assert v.host_stage_workers == 2
        pool = v.host_pool
        # resize the live pool (applies at ITS next idle submit)
        v.set_host_stage_workers(2)
        v._apply_pending_knobs()
        assert v.host_pool is pool          # same pool, no rebuild
        # close back to serial staging
        v.set_host_stage_workers(0)
        v._apply_pending_knobs()
        assert v.host_pool is None and v.host_stage_workers == 0
    finally:
        v.close()


@pytest.mark.skipif((os.cpu_count() or 1) < 2, reason="needs 2 cores")
def test_process_mode_smoke():
    # spawn-context children re-import task functions by qualified
    # name, so use a builtin (always importable in the child)
    with HostStagePool(2, mode="process") as p:
        assert p.map(abs, range(-4, 4)) == [abs(x) for x in range(-4, 4)]
        assert p.stats()["mode"] == "process"

"""Host staging pool unit battery (parallel/hostpool.py): knob
resolution semantics, bucket-aligned lane sharding, ordered fan-out,
error propagation, telemetry, and the process-mode smoke."""

import os

import pytest

from fabric_tpu.parallel.hostpool import HostStagePool, resolve_host_pool


def test_resolve_semantics():
    # 0 = off; 1 = pointless (queue overhead, no parallelism)
    assert resolve_host_pool(0) is None
    assert resolve_host_pool(1) is None
    cores = os.cpu_count() or 1
    auto = resolve_host_pool(-1)
    if cores >= 2:
        assert auto is not None and auto.workers == cores
        auto.shutdown()
        p = resolve_host_pool(2)
        assert p is not None and p.workers == 2
        p.shutdown()
        # clamped to the core count
        big = resolve_host_pool(10_000)
        assert big is not None and big.workers == cores
        big.shutdown()
    else:
        assert auto is None


def test_constructor_guards():
    with pytest.raises(ValueError):
        HostStagePool(1)
    with pytest.raises(ValueError):
        HostStagePool(2, mode="fork")


def test_slice_bounds_bucket_aligned():
    with HostStagePool(2) as p:
        assert p.slice_bounds(0, align=16) == []
        # every interior boundary is a multiple of align; the union
        # covers [0, n) exactly with the tail absorbing the remainder
        for n in (1, 15, 16, 17, 100, 128, 3072):
            bounds = p.slice_bounds(n, align=16)
            assert bounds[0][0] == 0 and bounds[-1][1] == n
            for (a, b), (c, d) in zip(bounds, bounds[1:]):
                assert b == c and b % 16 == 0
            assert len(bounds) <= p.workers
        # a sub-bucket batch stays one slice (serial fallback upstream)
        assert p.slice_bounds(8, align=16) == [(0, 8)]


def test_map_ordered_and_map_slices():
    with HostStagePool(2) as p:
        assert p.map(lambda x: x * x, range(20), stage="sq") == [
            x * x for x in range(20)
        ]
        got = p.map_slices(100, lambda lo, hi: (lo, hi), align=16)
        assert got[0][0] == 0 and got[-1][1] == 100
        stats = p.stats()
        assert stats["workers"] == 2 and stats["tasks"] >= 21
        assert stats["per_shard_p50_ms"] >= 0.0


def test_error_propagates():
    def boom(x):
        if x == 3:
            raise RuntimeError("shard failed")
        return x

    with HostStagePool(2) as p:
        with pytest.raises(RuntimeError, match="shard failed"):
            p.map(boom, range(6))


def test_error_carries_stage_and_worker_labels():
    """A raising worker task must not wedge the ordered map or drop a
    shard silently: the FIRST error propagates with the failing
    stage/worker attached (message suffix + attributes), type
    preserved."""

    def boom(x):
        if x == 2:
            raise ValueError("bad shard")
        return x

    with HostStagePool(2) as p:
        with pytest.raises(ValueError, match=r"bad shard \[host pool "
                           r"stage=recode worker=") as ei:
            p.map(boom, range(6), stage="recode")
        assert ei.value.fab_stage == "recode"
        assert ei.value.fab_worker
        # the pool still serves after the failure — nothing wedged
        assert p.map(lambda x: x + 1, range(4), stage="recode") == [
            1, 2, 3, 4
        ]


def test_injected_worker_fault_labeled_and_pool_survives():
    """The ``hostpool.task`` chaos point: exactly one task dies, the
    gather raises it (labeled), and the next map is clean once the
    budget is spent."""
    from fabric_tpu import faults

    faults.configure("hostpool.task:raise:n=1")
    try:
        with HostStagePool(2) as p:
            with pytest.raises(faults.InjectedFault) as ei:
                p.map(lambda x: x, range(8), stage="parse")
            assert ei.value.fab_stage == "parse"
            assert p.map(lambda x: x * 2, range(4), stage="parse") == [
                0, 2, 4, 6
            ]
    finally:
        faults.reset()


def test_telemetry_labels():
    from fabric_tpu.ops_metrics import global_registry

    with HostStagePool(2) as p:
        p.map(lambda x: x, range(4), stage="unit_probe")
    text = global_registry().render()
    assert "host_stage_pool_seconds" in text
    assert 'stage="unit_probe"' in text


@pytest.mark.skipif((os.cpu_count() or 1) < 2, reason="needs 2 cores")
def test_process_mode_smoke():
    # spawn-context children re-import task functions by qualified
    # name, so use a builtin (always importable in the child)
    with HostStagePool(2, mode="process") as p:
        assert p.map(abs, range(-4, 4)) == [abs(x) for x in range(-4, 4)]
        assert p.stats()["mode"] == "process"

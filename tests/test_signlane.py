"""Sign-batch ingest (peer/signlane) + Gateway.endorse error paths.

Crypto-free: identities are faked at the MSP boundary (the endorser's
creator checks are injected), signing runs on `ec_ref` RFC 6979 —
deterministic, so the concurrent-clients differential (N async
clients through the batcher ≡ N serial endorsements) compares exact
payload bytes.
"""

import asyncio
import threading
import time
from types import SimpleNamespace

import pytest

from fabric_tpu.crypto import ec_ref
from fabric_tpu.crypto import policy as pol
from fabric_tpu.discovery import PeerInfo
from fabric_tpu.ledger.statedb import MemVersionedDB
from fabric_tpu.peer import signlane
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.gateway import Gateway, GatewayError
from fabric_tpu.protos import common_pb2, proposal_pb2
from fabric_tpu.utils.locks import AsyncRWLock

D = 0xC9AFA9D845BA75166B5C215767B1D6934E50C3DB36E89B127B8A622B120F6721
CHANNEL, CC = "signchan", "kvcc"


def run(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


# -- SignBatcher unit battery ------------------------------------------------


def test_ctor_validation():
    with pytest.raises(ValueError):
        signlane.SignBatcher(lambda d: [], batch_max=0)
    with pytest.raises(ValueError):
        signlane.SignBatcher(lambda d: [], wait_ms=-1)


def test_concurrent_equals_serial_cpu_backend():
    """THE batcher differential: N concurrent clients through the
    batcher produce exactly the serial oracle's signatures (RFC 6979
    makes both pure functions of the digest)."""
    b = signlane.SignBatcher(
        signlane.cpu_sign_backend(D), batch_max=8, wait_ms=10.0
    ).start()
    try:
        msgs = [b"msg-%d" % i for i in range(24)]
        out = [None] * len(msgs)

        def worker(i):
            out[i] = b.sign(msgs[i])

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(len(msgs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        key = ec_ref.SigningKey(D)
        for m, der in zip(msgs, out):
            r, s = key.sign_digest(ec_ref.digest_int(m))
            assert der == ec_ref.der_encode_sig(r, s)
        st = b.stats()
        assert st["signed_total"] == len(msgs)
        assert st["busy_total"] == 0
        # coalescing actually happened: far fewer flushes than requests
        assert st["batches_total"] <= len(msgs) // 2
        assert st["occupancy"]["max"] <= 8  # batch_max respected
    finally:
        b.stop()


def test_busy_overflow_is_typed_and_bounded():
    gate = threading.Event()

    def slow_backend(digests):
        gate.wait(5)
        return signlane.cpu_sign_backend(D)(digests)

    b = signlane.SignBatcher(slow_backend, batch_max=2,
                             wait_ms=0.0).start()
    try:
        errs, oks = [], []

        def worker():
            try:
                oks.append(b.sign(b"x"))
            except signlane.SignBusy as e:
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(10)]
        for t in ts:
            t.start()
        time.sleep(0.3)
        gate.set()
        for t in ts:
            t.join()
        # cap = 2 × batch_max: the flusher may drain one batch into
        # the gated backend, so at most cap + batch_max admit overall
        assert errs, "expected BUSY bounces"
        assert len(oks) + len(errs) == 10
        e = errs[0]
        assert e.retry_ms == signlane.SIGN_RETRY_MS
        assert "retry" in str(e)
        st = b.stats()
        assert st["busy_total"] == len(errs)
        assert st["busy_rate"] > 0
    finally:
        b.stop()


def test_backend_error_reaches_every_waiter_and_lane_survives():
    calls = []

    def flaky(digests):
        calls.append(len(digests))
        if len(calls) == 1:
            raise RuntimeError("device fell over")
        return signlane.cpu_sign_backend(D)(digests)

    b = signlane.SignBatcher(flaky, batch_max=4, wait_ms=5.0).start()
    try:
        errs = []

        def worker():
            try:
                b.sign(b"boom")
            except RuntimeError as e:
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(errs) == 3  # one backend failure surfaces to all
        # the batcher thread survived: the next batch signs fine
        key = ec_ref.SigningKey(D)
        r, s = key.sign_digest(ec_ref.digest_int(b"after"))
        assert b.sign(b"after") == ec_ref.der_encode_sig(r, s)
    finally:
        b.stop()


def test_runtime_setters_and_stop_semantics():
    b = signlane.SignBatcher(
        signlane.cpu_sign_backend(D), batch_max=4, wait_ms=50.0
    ).start()
    b.set_batch_max(16)
    assert b.batch_max == 16
    b.set_batch_max(0)  # clamps at 1
    assert b.batch_max == 1
    b.set_wait_ms(0.0)
    b.stop()
    with pytest.raises(RuntimeError):
        b.sign_digest(5)


def test_busy_rate_decays_on_idle_lane():
    """The autopilot signal is TIME-windowed: a BUSY burst followed by
    silence ages out, so an idle lane reads busy_rate 0.0 / wait n=0
    instead of ratcheting sign_batch_max up forever."""

    class Clk:
        t = 1000.0

        def __call__(self):
            return self.t

    clk = Clk()
    b = signlane.SignBatcher(
        signlane.cpu_sign_backend(D), batch_max=1, wait_ms=0.0,
        clock=clk,
    )
    # never started → nothing drains; fill the 2-slot window, then
    # every submit bounces
    b._pending.extend([None, None])  # type: ignore[list-item]
    for _ in range(4):
        with pytest.raises(signlane.SignBusy):
            b.sign_digest(1)
    assert b.stats()["busy_rate"] == 1.0
    clk.t += signlane._SIGNAL_WINDOW_S + 1
    st = b.stats()
    assert st["busy_rate"] == 0.0
    assert st["wait_ms"]["n"] == 0
    assert st["busy_total"] == 4  # lifetime totals keep the history


def test_batched_signer_delegates_to_base():
    base = SimpleNamespace(
        serialized=b"base-identity", msp_id="Org1MSP", d=D
    )
    b = signlane.SignBatcher(
        signlane.cpu_sign_backend(D), batch_max=4, wait_ms=0.0
    ).start()
    try:
        s = signlane.BatchedSigner(base, b)
        assert s.serialized == b"base-identity"
        assert s.msp_id == "Org1MSP"
        key = ec_ref.SigningKey(D)
        r, sg = key.sign_digest(ec_ref.digest_int(b"deleg"))
        assert s.sign(b"deleg") == ec_ref.der_encode_sig(r, sg)
    finally:
        b.stop()


def test_private_scalar_extraction():
    assert signlane.private_scalar(ec_ref.SigningKey(D)) == D

    class FakeKey:
        def private_numbers(self):
            return SimpleNamespace(private_value=42)

    assert signlane.private_scalar(SimpleNamespace(key=FakeKey())) == 42
    with pytest.raises(ValueError):
        signlane.private_scalar(object())


def test_device_backend_through_batcher_matches_oracle():
    """Concurrent clients through the DEVICE backend ≡ the serial
    oracle — the end-to-end sign lane at 16-lane buckets."""
    b = signlane.SignBatcher(
        signlane.device_sign_backend(D), batch_max=16, wait_ms=10.0
    ).start()
    try:
        msgs = [b"dev-%d" % i for i in range(12)]
        out = [None] * len(msgs)

        def worker(i):
            out[i] = b.sign(msgs[i])

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(len(msgs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        key = ec_ref.SigningKey(D)
        for m, der in zip(msgs, out):
            r, s = key.sign_digest(ec_ref.digest_int(m))
            assert der == ec_ref.der_encode_sig(r, s)
    finally:
        b.stop()


# -- Gateway.endorse error paths (fake network) ------------------------------


class _FakeClientSigner:
    """Creator identity for proposals: opaque signature (the fake MSP
    accepts it)."""

    msp_id = "Org1MSP"
    serialized = common_pb2.SerializedIdentity(
        mspid="Org1MSP", id_bytes=b"fake-client-cert"
    ).SerializeToString()

    def sign(self, message: bytes) -> bytes:
        return b"client-sig"


class _FakeIdent:
    is_valid = True

    def verify(self, message, sig):
        return sig == b"client-sig"


class _FakeMSP:
    def deserialize_identity(self, data):
        return _FakeIdent()


class _EcSigner:
    """Serial ESCC signer over ec_ref — the oracle the batched
    provider must match byte for byte."""

    msp_id = "Org1MSP"
    serialized = common_pb2.SerializedIdentity(
        mspid="Org1MSP", id_bytes=b"fake-peer-cert"
    ).SerializeToString()

    def __init__(self, d=D):
        self._key = ec_ref.SigningKey(d)

    def sign(self, message: bytes) -> bytes:
        r, s = self._key.sign_digest(ec_ref.digest_int(message))
        return ec_ref.der_encode_sig(r, s)


class _FakeChan:
    def __init__(self, escc_signer, policy_dsl="OR('Org1MSP.peer')"):
        self.commit_lock = AsyncRWLock()
        self.escc_signer = escc_signer
        rule = pol.from_dsl(policy_dsl)
        self.validator = SimpleNamespace(
            policies=SimpleNamespace(
                info=lambda cc: SimpleNamespace(policy=rule)
            )
        )
        self.state = MemVersionedDB()

    def make_endorser(self, msp, signer, runtime):
        return Endorser(msp, signer, self.state, runtime)


class _FakeRegistry:
    def __init__(self, peers=None):
        self.peers = peers or {}

    def for_org(self, org):
        return self.peers.get(org, [])


def _fake_node(chan, registry=None, endorse_signer=None):
    rt = ChaincodeRuntime()
    rt.register(CC, KVContract())
    node = SimpleNamespace(
        channels={CHANNEL: chan},
        signer=_FakeClientSigner(),  # my_org = Org1MSP
        msp=_FakeMSP(),
        runtime=rt,
        registry=registry or _FakeRegistry(),
    )
    if endorse_signer is not None:
        node.endorse_signer = endorse_signer
    return node


def _proposal(args, client=None):
    signed, tx_id, _prop = txa.create_signed_proposal(
        client or _FakeClientSigner(), CHANNEL, CC, args
    )
    return signed.SerializeToString(), tx_id


def test_gateway_remote_endorse_failure_propagates():
    """A dead remote peer surfaces as a retryable GatewayError(503)
    naming the endpoint — after every layout fails over."""
    chan = _FakeChan(
        _EcSigner(),
        policy_dsl="AND('Org1MSP.peer', 'Org2MSP.peer')",
    )
    registry = _FakeRegistry(
        {"Org2MSP": [PeerInfo("Org2MSP", "127.0.0.1", 1)]}  # dead port
    )
    gw = Gateway(_fake_node(chan, registry, endorse_signer=_EcSigner()))
    req, _ = _proposal([b"put", b"k", b"v"])
    with pytest.raises(GatewayError) as ei:
        run(gw.endorse(req))
    assert ei.value.status == 503
    assert "remote endorse" in str(ei.value)


def test_gateway_not_enough_peers_503():
    chan = _FakeChan(
        _EcSigner(),
        policy_dsl="AND('Org1MSP.peer', 'Org3MSP.peer')",
    )
    gw = Gateway(_fake_node(chan, endorse_signer=_EcSigner()))
    req, _ = _proposal([b"put", b"k", b"v"])
    with pytest.raises(GatewayError) as ei:
        run(gw.endorse(req))
    assert ei.value.status == 503
    assert "not enough peers" in str(ei.value)


def test_gateway_busy_answer_from_full_sign_batcher():
    """Overflowed sign batcher → endorser's typed 429 → GatewayError
    with the retry hint, while admitted requests still endorse."""
    gate = threading.Event()

    def gated_backend(digests):
        gate.wait(10)
        return signlane.cpu_sign_backend(D)(digests)

    batcher = signlane.SignBatcher(
        gated_backend, batch_max=1, wait_ms=0.0
    ).start()
    base = _EcSigner()
    provider = signlane.BatchedSigner(base, batcher)
    chan = _FakeChan(base)
    gw = Gateway(_fake_node(chan, endorse_signer=provider))

    async def scenario():
        reqs = [_proposal([b"put", b"bk%d" % i, b"v"])[0]
                for i in range(8)]
        tasks = [asyncio.ensure_future(gw.endorse(r)) for r in reqs]
        # let the flood hit the 2-slot admission window, then open
        await asyncio.sleep(0.3)
        gate.set()
        return await asyncio.gather(*tasks, return_exceptions=True)

    try:
        results = run(scenario())
    finally:
        batcher.stop()
    busy = [r for r in results if isinstance(r, GatewayError)]
    ok = [r for r in results if isinstance(r, bytes)]
    assert busy, "expected BUSY answers from the full batcher"
    assert all(e.status == 429 for e in busy)
    assert "retry" in str(busy[0])
    assert ok, "admitted requests must still endorse"
    for other in (r for r in results
                  if not isinstance(r, (GatewayError, bytes))):
        raise other


def test_gateway_concurrent_clients_differential():
    """THE ingest differential: N concurrent gateway clients through
    the SignBatcher produce byte-identical prepared transactions to N
    serial endorsements with the plain serial signer — deterministic
    nonces make the whole payload a pure function of the proposal."""
    n = 12
    reqs = [_proposal([b"put", b"ck%d" % i, b"v%d" % i])[0]
            for i in range(n)]

    # serial oracle: plain signer, one endorsement at a time
    serial_chan = _FakeChan(_EcSigner())
    serial_gw = Gateway(
        _fake_node(serial_chan, endorse_signer=_EcSigner())
    )
    want = [run(serial_gw.endorse(r)) for r in reqs]

    # batched lane: same key behind the SignBatcher, all at once
    batcher = signlane.SignBatcher(
        signlane.cpu_sign_backend(D), batch_max=8, wait_ms=10.0
    ).start()
    provider = signlane.BatchedSigner(_EcSigner(), batcher)
    chan = _FakeChan(_EcSigner())
    gw = Gateway(_fake_node(chan, endorse_signer=provider))

    async def scenario():
        return await asyncio.gather(
            *(gw.endorse(r) for r in reqs)
        )

    try:
        got = run(scenario())
    finally:
        st = batcher.stats()
        batcher.stop()
    assert got == want
    assert st["signed_total"] == n
    # concurrency actually coalesced: fewer flushes than requests
    assert st["batches_total"] < n


def test_gateway_evaluate_surfaces_sign_busy_status():
    """evaluate() on a saturated lane forwards the 429 response
    instead of crashing (the response-status path, not an
    exception)."""
    always_busy = signlane.SignBatcher(
        signlane.cpu_sign_backend(D), batch_max=1, wait_ms=0.0
    )
    # never started → no flusher drains; fill the 2-slot window so the
    # NEXT request overflows deterministically
    always_busy._pending.extend([None, None])  # type: ignore[list-item]
    provider = signlane.BatchedSigner(_EcSigner(), always_busy)
    chan = _FakeChan(_EcSigner())
    gw = Gateway(_fake_node(chan, endorse_signer=provider))
    req, _ = _proposal([b"put", b"k", b"v"])
    raw = run(gw.evaluate(req))
    resp = proposal_pb2.Response()
    resp.ParseFromString(raw)
    assert resp.status == 429
    assert "retry" in resp.message

"""Property tests: MVCC kernel vs the serial reference semantics.

The oracle (`mvcc_serial_reference`) re-implements the reference's
serial loop (validator.go:81-118) directly; the kernel must agree on
every randomly generated block, including Zipf-contended ones.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from fabric_tpu.ops import mvcc
from fabric_tpu.ops.mvcc import TxRWSet


def _check(txs, committed, pre_ok=None):
    want = mvcc.mvcc_serial_reference(txs, committed, pre_ok)
    got, _, _ = mvcc.mvcc_validate_block(txs, committed, pre_ok)
    assert list(got) == want, (list(got), want)
    return want


def test_simple_version_conflict():
    committed = {"a": (1, 0), "b": (2, 3)}
    txs = [
        TxRWSet(reads=[("a", (1, 0))], writes=["a"], range_reads=[]),   # valid
        TxRWSet(reads=[("a", (1, 0))], writes=[], range_reads=[]),      # conflict: tx0 wrote a
        TxRWSet(reads=[("b", (9, 9))], writes=[], range_reads=[]),      # stale version
        TxRWSet(reads=[("b", (2, 3))], writes=["b"], range_reads=[]),   # valid
        TxRWSet(reads=[("zzz", None)], writes=[], range_reads=[]),      # absent key, valid
        TxRWSet(reads=[("zzz", (1, 1))], writes=[], range_reads=[]),    # expects present, absent
    ]
    want = _check(txs, committed)
    assert want == [True, False, False, True, True, False]


def test_invalid_writer_unblocks_reader():
    """tx1 invalid ⇒ its writes must NOT mask tx2's reads (the
    write-visibility chain the serial loop encodes)."""
    committed = {"k": (1, 0), "x": (1, 0)}
    txs = [
        TxRWSet(reads=[("x", (0, 0))], writes=["k"], range_reads=[]),  # invalid (stale x)
        TxRWSet(reads=[("k", (1, 0))], writes=[], range_reads=[]),     # valid: tx0 invalid
    ]
    assert _check(txs, committed) == [False, True]


def test_dependency_chain_depth():
    """a→b→c→d chain: alternating validity through the chain."""
    committed = {c: (1, 0) for c in "abcd"}
    txs = [
        TxRWSet(reads=[("a", (1, 0))], writes=["b"], range_reads=[]),
        TxRWSet(reads=[("b", (1, 0))], writes=["c"], range_reads=[]),  # invalid (tx0 wrote b)
        TxRWSet(reads=[("c", (1, 0))], writes=["d"], range_reads=[]),  # valid (tx1 invalid)
        TxRWSet(reads=[("d", (1, 0))], writes=[], range_reads=[]),     # invalid (tx2 wrote d)
    ]
    assert _check(txs, committed) == [True, False, True, False]


def test_phantom_range_conflict():
    committed = {"k3": (1, 0)}
    txs = [
        TxRWSet(reads=[], writes=["k5"], range_reads=[]),
        TxRWSet(reads=[], writes=[], range_reads=[("k1", "k9")]),  # phantom: k5 inserted
        TxRWSet(reads=[], writes=[], range_reads=[("k6", "k9")]),  # k5 < k6: ok
    ]
    want = _check(txs, committed)
    assert want == [True, False, True]
    _, conflict, phantom = mvcc.mvcc_validate_block(txs, committed)
    assert list(phantom) == [False, True, False]


def test_pre_ok_masks_writes():
    """A tx invalidated upstream (bad signature) must not mask later reads."""
    committed = {"k": (1, 0)}
    txs = [
        TxRWSet(reads=[], writes=["k"], range_reads=[]),
        TxRWSet(reads=[("k", (1, 0))], writes=[], range_reads=[]),
    ]
    assert _check(txs, committed, pre_ok=[False, True]) == [False, True]


@pytest.mark.parametrize("seed", range(6))
def test_random_blocks_match_serial(seed):
    rng = np.random.default_rng(seed)
    T = int(rng.integers(2, 40))
    nkeys = int(rng.integers(4, 30))  # high contention
    keys = [f"k{i:04d}" for i in range(nkeys)]
    committed = {
        k: (int(rng.integers(0, 3)), int(rng.integers(0, 4)))
        for k in keys
        if rng.random() < 0.8
    }
    txs = []
    for _ in range(T):
        reads = []
        for k in rng.choice(keys, size=rng.integers(0, 5), replace=False):
            if rng.random() < 0.75 and k in committed:
                ver = committed[k]  # fresh read
            elif rng.random() < 0.5:
                ver = (int(rng.integers(0, 3)), int(rng.integers(0, 4)))
            else:
                ver = None
            reads.append((str(k), ver))
        writes = [str(k) for k in rng.choice(keys, size=rng.integers(0, 4), replace=False)]
        rqs = []
        if rng.random() < 0.3:
            lo, hi = sorted(rng.choice(keys, size=2, replace=False))
            rqs.append((str(lo), str(hi)))
        txs.append(TxRWSet(reads=reads, writes=writes, range_reads=rqs))
    pre_ok = rng.random(T) > 0.1
    _check(txs, committed, list(pre_ok))


def test_zipf_contention_block():
    """BASELINE config #3: Zipf key access over 10k keys, larger block."""
    rng = np.random.default_rng(99)
    nkeys, T = 10_000, 256
    committed = {f"key{i:06d}": (1, i % 7) for i in range(nkeys)}
    zipf = np.minimum(rng.zipf(1.3, size=(T, 8)) - 1, nkeys - 1)
    txs = []
    for j in range(T):
        ks = [f"key{k:06d}" for k in zipf[j]]
        reads = [(k, committed[k] if rng.random() < 0.9 else (9, 9)) for k in ks[:4]]
        txs.append(TxRWSet(reads=reads, writes=ks[4:], range_reads=[]))
    _check(txs, committed)

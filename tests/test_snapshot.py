"""Ledger snapshot tests: export → verify → join-from-snapshot, and
the VERDICT gate — a fresh peer bootstrapped from a snapshot validates
the next block identically to the peer that took the snapshot
(reference: kvledger/snapshot.go:93 generateSnapshot, :222
CreateFromSnapshot, :368 verification)."""

import asyncio
import json

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.ledger import snapshot as snap
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.peer import lifecycle as lc
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.node import PeerChannel
from fabric_tpu.protos import transaction_pb2
from fabric_tpu.tools import configtxgen as cg

C = transaction_pb2.TxValidationCode
CHANNEL = "snapchan"
CC = "snapcc"


@pytest.fixture(scope="module")
def material():
    orgs = [
        cryptogen.generate_org(f"Org{i}MSP", f"org{i}.example.com", peers=1, users=1)
        for i in (1, 2)
    ]
    profile = cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(o.msp_id, o.msp()) for o in orgs],
    )
    return {
        "genesis": cg.genesis_block(profile),
        "client": cryptogen.signing_identity(orgs[0], "User1@org1.example.com"),
        "peers": [
            cryptogen.signing_identity(o, f"peer0.org{i}.example.com")
            for i, o in zip((1, 2), orgs)
        ],
    }


def _tx(material, writes, ns=CC, reads=()):
    signer = material["client"]
    signed, tx_id, prop = txa.create_signed_proposal(signer, CHANNEL, ns, [b"invoke"])
    tx = TxRWSet()
    n = tx.ns_rwset(ns)
    for k, ver in reads:
        n.reads[k] = ver
    for k, v in writes:
        n.writes[k] = v
    rw = tx.to_proto().SerializeToString()
    responses = [
        txa.create_proposal_response(prop, rw, e, ns) for e in material["peers"]
    ]
    return txa.assemble_transaction(prop, responses, signer), tx_id


def _commit(ch, envs):
    prev = pu.block_header_hash(ch.ledger.blocks.get_block(ch.height - 1).header)
    blk = pu.new_block(ch.height, prev)
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    blk = pu.finalize_block(blk)
    return asyncio.run(ch.commit_block(blk)), blk


def test_snapshot_roundtrip_and_join(material, tmp_path):
    src = PeerChannel(
        CHANNEL, str(tmp_path / "src"), genesis_block=material["genesis"]
    )
    cd = lc.ChaincodeDefinition(name=CC, sequence=1)
    env_lc, _ = _tx(material, [(lc.definition_key(CC), cd.to_bytes())],
                    ns=lc.LIFECYCLE_NS)
    flt, _ = _commit(src, [env_lc])
    assert list(flt) == [C.VALID]
    env1, txid1 = _tx(material, [("alpha", b"1"), ("beta", b"2")])
    flt, _ = _commit(src, [env1])
    assert list(flt) == [C.VALID]

    meta = asyncio.run(src.snapshot(str(tmp_path / "snap")))
    assert meta["last_block_number"] == 2
    assert snap.verify_snapshot(str(tmp_path / "snap"))

    # tamper detection
    state_file = tmp_path / "snap" / snap.STATE_FILE
    data = state_file.read_bytes()
    state_file.write_bytes(data[:-1] + bytes([data[-1] ^ 1]))
    with pytest.raises(ValueError):
        snap.verify_snapshot(str(tmp_path / "snap"))
    state_file.write_bytes(data)

    # join a fresh peer from the snapshot
    dst = PeerChannel(
        CHANNEL, str(tmp_path / "dst"), snapshot_dir=str(tmp_path / "snap")
    )
    assert dst.height == src.height == 3
    assert dst.ledger.state.get_state(CC, "alpha").value == b"1"
    # trust anchor restored: bundle orgs + lifecycle definition visible
    assert dst.processor.bundle.application_orgs() == ["Org1MSP", "Org2MSP"]
    assert dst.validator.policies.info(CC) is not None
    # dup-txid protection covers pre-snapshot history
    assert dst.ledger.blocks.tx_exists(txid1)

    # the next block commits IDENTICALLY on both peers
    env2, _ = _tx(material, [("gamma", b"3")],
                  reads=[("alpha", (2, 0))])
    flt_src, blk_src = _commit(src, [env2])
    prev = pu.block_header_hash(src.ledger.blocks.get_block(2).header)
    blk = pu.new_block(3, prev)
    blk.data.data.append(env2.SerializeToString())
    blk = pu.finalize_block(blk)
    flt_dst = asyncio.run(dst.commit_block(blk))
    assert list(flt_src) == list(flt_dst) == [C.VALID]
    assert src.ledger.commit_hash == dst.ledger.commit_hash
    # replaying a pre-snapshot txid on the joined peer: DUPLICATE
    flt_dup, _ = _commit(dst, [env1])
    assert list(flt_dup) == [C.DUPLICATE_TXID]
    src.stop()
    dst.stop()


def test_snapshot_metadata_height_and_savepoint(material, tmp_path):
    """ISSUE 18: the export records the boundary height and the
    exporter's state savepoint, and the import reproduces both — the
    replay driver resumes from ``meta['height']`` with savepoint/height
    reconciliation the identity on reopen."""
    src = PeerChannel(
        CHANNEL, str(tmp_path / "src"), genesis_block=material["genesis"]
    )
    cd = lc.ChaincodeDefinition(name=CC, sequence=1)
    env_lc, _ = _tx(material, [(lc.definition_key(CC), cd.to_bytes())],
                    ns=lc.LIFECYCLE_NS)
    _commit(src, [env_lc])
    env1, _ = _tx(material, [("alpha", b"1")])
    _commit(src, [env1])

    meta = asyncio.run(src.snapshot(str(tmp_path / "snap")))
    assert meta["height"] == src.height == 3
    assert meta["height"] == meta["last_block_number"] + 1
    sp = meta["state_savepoint"]
    assert sp is not None and tuple(sp)[0] == meta["last_block_number"]

    dst = PeerChannel(
        CHANNEL, str(tmp_path / "dst"), snapshot_dir=str(tmp_path / "snap")
    )
    assert tuple(dst.ledger.state.savepoint()) == tuple(sp)
    src.stop()
    dst.stop()


def test_snapshot_join_state_digest_matches_source(material, tmp_path):
    """The order-insensitive state digest (ledger/snapshot.py) is the
    byte-identity oracle: a joined peer's digest equals the serving
    peer's at the boundary AND after both commit the next block."""
    src = PeerChannel(
        CHANNEL, str(tmp_path / "src"), genesis_block=material["genesis"]
    )
    cd = lc.ChaincodeDefinition(name=CC, sequence=1)
    env_lc, _ = _tx(material, [(lc.definition_key(CC), cd.to_bytes())],
                    ns=lc.LIFECYCLE_NS)
    _commit(src, [env_lc])
    env1, _ = _tx(material, [("alpha", b"1"), ("beta", b"2")])
    _commit(src, [env1])

    asyncio.run(src.snapshot(str(tmp_path / "snap")))
    dst = PeerChannel(
        CHANNEL, str(tmp_path / "dst"), snapshot_dir=str(tmp_path / "snap")
    )
    assert (dst.ledger.state_digest() == src.ledger.state_digest())

    env2, _ = _tx(material, [("gamma", b"3")])
    _flt, blk = _commit(src, [env2])
    blk2 = type(blk)()
    blk2.CopyFrom(src.ledger.blocks.get_block(3))
    asyncio.run(dst.commit_block(blk2))
    assert dst.ledger.state_digest() == src.ledger.state_digest()
    assert dst.ledger.commit_hash == src.ledger.commit_hash
    src.stop()
    dst.stop()

"""Gateway + discovery tests over a real localhost network: evaluate,
endorse→sign→submit→commit-status round trip, chaincode events,
discovery peers/endorsers (reference: internal/pkg/gateway/*.go,
discovery/endorsement/endorsement.go:84)."""

import asyncio
import json

import pytest

from fabric_tpu.comm.rpc import RpcClient
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.discovery import PeerInfo, layouts_for_policy
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import OrdererNode
from fabric_tpu.peer.chaincode import ChaincodeRuntime, MarblesContract, KVContract
from fabric_tpu.peer.gateway import GatewayClient, GatewayError
from fabric_tpu.peer.node import PeerNode
from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider

CHANNEL = "gwchan"
CC = "gwcc"


def run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def test_layouts_for_policy():
    rule = pol.from_dsl("AND('Org1MSP.peer', OR('Org2MSP.peer', 'Org3MSP.peer'))")
    lays = layouts_for_policy(rule)
    assert {"Org1MSP": 1, "Org2MSP": 1} in lays
    assert {"Org1MSP": 1, "Org3MSP": 1} in lays
    two_of_same = pol.from_dsl("OutOf(2, 'Org1MSP.peer', 'Org1MSP.peer')")
    assert layouts_for_policy(two_of_same) == [{"Org1MSP": 2}]


@pytest.mark.slow
def test_gateway_round_trip(tmp_path):
    async def scenario():
        org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
        org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
        from fabric_tpu.crypto.msp import MSPManager

        mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
        client = cryptogen.signing_identity(org1, "User1@org1.example.com")
        p1 = cryptogen.signing_identity(org1, "peer0.org1.example.com")
        p2 = cryptogen.signing_identity(org2, "peer0.org2.example.com")

        orderer = OrdererNode(
            "o0", str(tmp_path / "o0"), {},
            batch_config=BatchConfig(max_message_count=1, batch_timeout_s=0.1),
        )
        await orderer.start()
        orderer.cluster["o0"] = ("127.0.0.1", orderer.port)
        orderer.join_channel(CHANNEL)

        policy = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.peer')")
        peers = []
        for name, signer in (("p1", p1), ("p2", p2)):
            rt = ChaincodeRuntime()
            rt.register(CC, KVContract())
            rt.register("marbles", MarblesContract())
            node = PeerNode(name, str(tmp_path / name), mgr, signer, rt)
            await node.start()
            prov = PolicyProvider({
                CC: NamespaceInfo(policy=policy),
                "marbles": NamespaceInfo(policy=policy),
            })
            ch = node.join_channel(CHANNEL, prov)
            ch.start_deliver([("127.0.0.1", orderer.port)])
            peers.append(node)
        # cross-register each peer in the other's registry
        peers[0].registry.add(PeerInfo("Org2MSP", "127.0.0.1", peers[1].port))
        peers[1].registry.add(PeerInfo("Org1MSP", "127.0.0.1", peers[0].port))
        peers[0].channels[CHANNEL].validator.warmup()

        gw = GatewayClient("127.0.0.1", peers[0].port, client)
        try:
            # submit via the full gateway flow
            tx_id, status = await gw.submit_transaction(
                CHANNEL, CC, [b"put", b"city", b"zurich"]
            )
            assert status["code"] == 0 and status["code_name"] == "VALID"
            # read-your-writes honesty: the status distinguishes the
            # block being IN the ledger from its writes being READABLE
            assert isinstance(status["applied"], bool)
            assert status["applied_height"] >= 0
            assert status["durable_height"] >= status["block"]

            # evaluate reads the committed state without ordering
            resp = await gw.evaluate(CHANNEL, CC, [b"get", b"city"])
            assert resp.payload == b"zurich"

            # commit-status for an unknown tx times out with 408
            with pytest.raises(GatewayError) as ei:
                await gw._unwrap(await (await gw._client()).unary(
                    "GwCommitStatus",
                    json.dumps({"channel": CHANNEL, "tx_id": "nope",
                                "timeout": 0.3}).encode(),
                ))
            assert ei.value.status == 408

            # chaincode events stream
            tx2, status2 = await gw.submit_transaction(
                CHANNEL, "marbles", [b"create", b"m1", b"red", b"5", b"alice"]
            )
            assert status2["code"] == 0
            cli = RpcClient("127.0.0.1", peers[0].port)
            await cli.connect()
            stream = await cli.open_stream("GwChaincodeEvents")
            await stream.send(json.dumps(
                {"channel": CHANNEL, "chaincode": "marbles", "start": 0}
            ).encode())
            ev = json.loads(await asyncio.wait_for(stream.__anext__(), 10))
            assert ev["event_name"] == "marble_created"
            assert bytes.fromhex(ev["payload"]) == b"m1"
            await cli.close()

            # discovery: endorsers descriptor lists both orgs
            cli2 = RpcClient("127.0.0.1", peers[0].port)
            await cli2.connect()
            raw = await cli2.unary("Discover", json.dumps(
                {"query": "endorsers", "channel": CHANNEL, "chaincode": CC}
            ).encode())
            desc = json.loads(raw)
            assert desc["status"] == 200
            assert {"Org1MSP": 1, "Org2MSP": 1} in desc["descriptor"]["layouts"]
            await cli2.close()
        finally:
            await gw.close()
            for p in peers:
                await p.stop()
            await orderer.stop()

    run(scenario())

"""Mutual TLS across the assembled network + the broadcast signature
filter: every listener demands a client certificate, plaintext and
un-certified clients are refused at the transport, and the orderer's
admission rejects envelopes that do not satisfy the channel's Writers
policy (reference: internal/pkg/comm/server.go:45 mutual TLS;
orderer/common/msgprocessor/sigfilter.go)."""

import asyncio

import pytest

from fabric_tpu.comm.rpc import RpcClient, TlsProfile
from fabric_tpu.crypto import cryptogen
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.node import PeerNode
from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider
from fabric_tpu.tools import configtxgen as cg

CHANNEL = "tlschan"
CC = "tlscc"


def run(coro, timeout=90):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=15.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.03)
    return False


def _material():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                  peers=1, users=1)
    oorg = cryptogen.generate_org("OrdererMSP", "ord.example.com",
                                  peers=0, orderers=1, users=0)
    ca_bundle = org1.tls_ca.cert_pem + oorg.tls_ca.cert_pem

    def tls_of(org, name):
        enr = org.tls[name]
        return TlsProfile(enr.cert_pem, enr.key_pem, ca_bundle)

    from fabric_tpu.crypto.msp import MSPManager

    profile = cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(org1.msp_id, org1.msp())],
        orderer_orgs=[cg.OrgProfile(oorg.msp_id, oorg.msp())],
    )
    return {
        "org1": org1,
        "oorg": oorg,
        "mgr": MSPManager({"Org1MSP": org1.msp(), "OrdererMSP": oorg.msp()}),
        "genesis": cg.genesis_block(profile),
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "peer": cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        "orderer": cryptogen.signing_identity(oorg, "orderer0.ord.example.com"),
        "peer_tls": tls_of(org1, "peer0.org1.example.com"),
        "ord_tls": tls_of(oorg, "orderer0.ord.example.com"),
        "ca_bundle": ca_bundle,
    }


def _env(m, key=b"k", sign_with=None):
    _, _, prop = txa.create_signed_proposal(m["client"], CHANNEL, CC, [b"i"])
    tx = TxRWSet()
    tx.ns_rwset(CC).writes[key.decode()] = b"v"
    rw = tx.to_proto().SerializeToString()
    resps = [txa.create_proposal_response(prop, rw, m["peer"], CC)]
    env = txa.assemble_transaction(prop, resps, sign_with or m["client"])
    return env


def test_mtls_network_and_sig_filter(tmp_path):
    async def scenario():
        m = _material()
        orderer = OrdererNode(
            "o0", str(tmp_path / "o0"), {},
            batch_config=BatchConfig(max_message_count=1, batch_timeout_s=0.1),
            signer=m["orderer"], tls=m["ord_tls"],
        )
        await orderer.start()
        orderer.cluster["o0"] = ("127.0.0.1", orderer.port)
        orderer.join_channel(CHANNEL, genesis_block=m["genesis"])

        rt = ChaincodeRuntime()
        rt.register(CC, KVContract())
        peer = PeerNode("p0", str(tmp_path / "p0"), m["mgr"], m["peer"],
                        rt, tls=m["peer_tls"])
        await peer.start()
        chan = peer.join_channel(CHANNEL, genesis_block=m["genesis"])
        chan.start_deliver([("127.0.0.1", orderer.port)])
        try:
            # 1. plaintext client → no RPC succeeds (the TCP connect
            # may open, but the TLS-expecting server kills the session
            # before any frame round-trips)
            for port in (orderer.port, peer.port):
                plain = RpcClient("127.0.0.1", port)
                with pytest.raises(Exception):
                    await asyncio.wait_for(plain.connect(), 5)
                    await asyncio.wait_for(
                        plain.unary("Info", b"{}", timeout=3), 5
                    )

            # 2. TLS WITHOUT a client certificate → handshake refused
            from fabric_tpu.comm.rpc import make_client_tls

            nocert = RpcClient(
                "127.0.0.1", orderer.port,
                ssl_ctx=make_client_tls(m["ca_bundle"]),
            )
            with pytest.raises(Exception):
                await asyncio.wait_for(nocert.connect(), 5)
                # some stacks only fail on first IO after handshake
                await asyncio.wait_for(
                    nocert.unary("Info", b"{}", timeout=3), 5
                )

            # 3. proper mTLS client: broadcast flows end to end
            bc = BroadcastClient(
                [("127.0.0.1", orderer.port)],
                ssl_ctx=m["peer_tls"].client_ctx(),
            )
            res = await bc.broadcast(
                CHANNEL, _env(m).SerializeToString(), retries=40
            )
            assert res["status"] == 200
            assert await _wait(lambda: chan.height >= 2, 30)

            # 4. broadcast signature filter: an envelope whose creator
            # signature is broken fails the Writers policy → 400
            bad = _env(m, key=b"k2")
            bad.signature = bad.signature[:-3] + bytes(3)
            res = await bc.broadcast(
                CHANNEL, bad.SerializeToString(), retries=3
            )
            assert res["status"] == 400
            assert "Writers" in res.get("info", "")

            # 5. an identity outside the channel's orgs → 400 too
            rogue_org = cryptogen.generate_org(
                "RogueMSP", "rogue.example.com", peers=1, users=1
            )
            rogue = cryptogen.signing_identity(
                rogue_org, "User1@rogue.example.com"
            )
            res = await bc.broadcast(
                CHANNEL, _env(m, key=b"k3", sign_with=rogue)
                .SerializeToString(), retries=3,
            )
            assert res["status"] == 400
            await bc.close()
        finally:
            await peer.stop()
            await orderer.stop()

    run(scenario())

"""Concurrent endorsement: simulations take the SHARED side of the
commit lock (reference endorser.go:379-401 + lockbased_txmgr RW lock)
— N proposals endorse in parallel with each other, and only the
committer excludes them."""

import asyncio
import time

import pytest

from fabric_tpu.utils.locks import AsyncRWLock


def run(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def test_rwlock_semantics():
    async def scenario():
        lock = AsyncRWLock()
        events = []

        async def reader(name, hold):
            async with lock.reader():
                events.append(("r+", name))
                await asyncio.sleep(hold)
                events.append(("r-", name))

        async def writer(name, hold):
            async with lock.writer():
                events.append(("w+", name))
                await asyncio.sleep(hold)
                events.append(("w-", name))

        # readers overlap each other
        t0 = time.perf_counter()
        await asyncio.gather(reader("a", 0.1), reader("b", 0.1),
                             reader("c", 0.1))
        assert time.perf_counter() - t0 < 0.25  # parallel, not 0.3 serial

        # a writer excludes readers and vice versa; a WAITING writer
        # blocks new readers (no starvation)
        events.clear()
        r1 = asyncio.ensure_future(reader("r1", 0.15))
        await asyncio.sleep(0.02)
        w = asyncio.ensure_future(writer("w", 0.05))
        await asyncio.sleep(0.02)
        r2 = asyncio.ensure_future(reader("r2", 0.01))
        await asyncio.gather(r1, w, r2)
        order = [e for e in events]
        # r1 finished before w started; r2 queued BEHIND the writer
        assert order.index(("r-", "r1")) < order.index(("w+", "w"))
        assert order.index(("w-", "w")) < order.index(("r+", "r2"))

    run(scenario())


@pytest.mark.slow
def test_parallel_endorsements_during_commit(tmp_path):
    """N concurrent Endorse RPCs proceed while a (slow) block commit
    holds the exclusive side only for its own duration: endorsements
    overlap each other, and total wall time shows parallelism."""
    from fabric_tpu.comm.rpc import RpcClient
    from fabric_tpu.crypto import cryptogen
    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.crypto.msp import MSPManager
    from fabric_tpu.peer import txassembly as txa
    from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
    from fabric_tpu.peer.node import PeerNode
    from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider
    from fabric_tpu.protos import proposal_pb2

    CHANNEL, CC = "concchan", "conccc"

    async def scenario():
        org1 = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                      peers=1, users=1)
        mgr = MSPManager({"Org1MSP": org1.msp()})
        client = cryptogen.signing_identity(org1, "User1@org1.example.com")
        signer = cryptogen.signing_identity(org1, "peer0.org1.example.com")
        rt = ChaincodeRuntime()

        class SlowKV(KVContract):
            def put(self, stub, key, value):
                time.sleep(0.15)  # slow simulation (worker thread)
                return super().put(stub, key, value)

        rt.register(CC, SlowKV())
        node = PeerNode("p0", str(tmp_path / "p0"), mgr, signer, rt)
        await node.start()
        prov = PolicyProvider({CC: NamespaceInfo(
            policy=pol.from_dsl("OutOf(1, 'Org1MSP.peer')"))})
        chan = node.join_channel(CHANNEL, prov)
        try:
            async def endorse(i):
                signed, _, _ = txa.create_signed_proposal(
                    client, CHANNEL, CC, [b"put", b"k%d" % i, b"v"]
                )
                cli = RpcClient("127.0.0.1", node.port)
                await cli.connect()
                try:
                    raw = await cli.unary(
                        "Endorse", signed.SerializeToString(), timeout=30
                    )
                finally:
                    await cli.close()
                pr = proposal_pb2.ProposalResponse()
                pr.ParseFromString(raw)
                assert pr.response.status == 200, pr.response.message
                return pr

            await endorse(999)  # warm caches
            n = 6
            t0 = time.perf_counter()
            await asyncio.gather(*(endorse(i) for i in range(n)))
            wall = time.perf_counter() - t0
            # serial would be >= n * 0.15 = 0.9s; shared-lock parallel
            # endorsements overlap their sleeps in worker threads
            assert wall < 0.15 * n * 0.7, wall

            # a held WRITER (commit in progress) delays endorsements,
            # proving the commit still excludes
            async def hold_commit():
                async with chan.commit_lock.writer():
                    await asyncio.sleep(0.3)

            t0 = time.perf_counter()
            holder = asyncio.ensure_future(hold_commit())
            await asyncio.sleep(0.02)
            await endorse(1000)
            assert time.perf_counter() - t0 >= 0.28
            await holder
        finally:
            await node.stop()

    run(scenario())

"""Raft WAL compaction, snapshot catch-up from the block store, and
consenter-set reconfiguration via committed config blocks (reference:
orderer/consensus/etcdraft/storage.go WAL+snapshots, chain.go:1045
catchUp / :1115 reconfiguration, orderer/common/follower)."""

import asyncio
import json

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
from fabric_tpu.ordering.raft import WAL, Entry, RaftNode
from fabric_tpu.protos import common_pb2, configtx_pb2, orderer_pb2

CHANNEL = "compchan"


def run(coro, timeout=90):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=20.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.03)
    return False


def test_wal_compaction_and_reload(tmp_path):
    """compact_to drops materialized entries, persists the snapshot
    watermark, and a reloaded WAL (crash/restart) starts past it."""
    wal = WAL(str(tmp_path / "w"))
    wal.append([Entry(term=1, index=i, data=b"d%d" % i) for i in range(1, 21)])
    assert len(wal.entries) == 20
    dropped = wal.compact_to(12)
    assert dropped == 12
    assert wal.snap_index == 12 and wal.snap_term == 1
    assert [e.index for e in wal.entries] == list(range(13, 21))
    wal.close()

    re = WAL(str(tmp_path / "w"))
    assert re.snap_index == 12
    assert [e.index for e in re.entries] == list(range(13, 21))
    # a raft node over the compacted WAL resumes from the watermark
    node = RaftNode("n0", ["n0"], re, apply_cb=lambda e: None,
                    send_cb=lambda *a: None)
    assert node.last_applied == 12 and node.commit_index == 12
    assert node.last_index == 20
    re.close()


async def _mk_orderers(tmp_path, ids, retention=4, batch=1):
    cluster = {}
    nodes = {}
    for oid in ids:
        n = OrdererNode(
            oid, str(tmp_path / oid), cluster,
            batch_config=BatchConfig(max_message_count=batch,
                                     batch_timeout_s=0.1),
        )
        await n.start()
        cluster[oid] = ("127.0.0.1", n.port)
        nodes[oid] = n
    for n in nodes.values():
        n.cluster.update(cluster)
        chain = n.join_channel(CHANNEL)
        chain.wal_retention = retention
    return nodes, cluster


def test_snapshot_catchup_from_compacted_leader(tmp_path):
    """A follower that slept through the leader's compaction window
    recovers via block-store catch-up (snap hint → Deliver pull →
    install_snapshot) instead of an infinite AppendEntries history."""
    async def scenario():
        nodes, cluster = await _mk_orderers(tmp_path, ["o0", "o1", "o2"],
                                            retention=4)
        bc = BroadcastClient(list(cluster.values()))
        try:
            # establish a leader, then knock o2 out (stop consensus +
            # drop its inbox by stopping the whole node)
            assert (await bc.broadcast(CHANNEL, b"warm", retries=60))["status"] == 200
            victim = nodes["o2"]
            await victim.stop()

            for i in range(16):  # enough to compact past o2
                res = await bc.broadcast(CHANNEL, b"m%d" % i, retries=60)
                assert res["status"] == 200
            leader = next(
                n for n in (nodes["o0"], nodes["o1"])
                if n.chains[CHANNEL].raft.state == "leader"
            )
            lwal = leader.chains[CHANNEL].raft.wal
            assert await _wait(lambda: lwal.snap_index > 0, 10)
            assert lwal.entries[0].index > 1  # genuinely compacted

            # restart o2 from its ON-DISK state: it is far behind and
            # the entries it needs are gone at the leader
            o2 = OrdererNode("o2", str(tmp_path / "o2"), dict(cluster))
            await o2.start()
            cluster["o2"] = ("127.0.0.1", o2.port)
            for n in (nodes["o0"], nodes["o1"]):
                n.cluster["o2"] = cluster["o2"]
            o2.cluster.update(cluster)
            ch2 = o2.join_channel(CHANNEL)
            ch2.wal_retention = 4
            nodes["o2"] = o2

            target = leader.chains[CHANNEL].height
            assert await _wait(lambda: ch2.height >= target, 30)
            assert ch2.raft.last_applied >= lwal.snap_index
            # and it keeps up with NEW traffic post-catch-up
            assert (await bc.broadcast(CHANNEL, b"after", retries=60))["status"] == 200
            assert await _wait(
                lambda: ch2.height == leader.chains[CHANNEL].height, 20
            )
            h = ch2.height
            for k in range(h):
                a = ch2.blocks.get_block(k).header
                b = leader.chains[CHANNEL].blocks.get_block(k).header
                assert a.SerializeToString() == b.SerializeToString()
            await bc.close()
        finally:
            for n in nodes.values():
                try:
                    await n.stop()
                except Exception:
                    pass

    run(scenario())


def _config_env(consenters):
    """A CONFIG envelope whose Orderer group carries a new consenter
    set (host, port, id) — the reconfiguration trigger."""
    meta = orderer_pb2.RaftConfigMetadata(consenters=[
        orderer_pb2.RaftConsenter(host=h, port=p, id=i)
        for h, p, i in consenters
    ])
    ct = orderer_pb2.ConsensusType(type="raft", metadata=meta.SerializeToString())
    root = configtx_pb2.ConfigGroup()
    root.groups["Orderer"].values["ConsensusType"].value = ct.SerializeToString()
    cfg_env = configtx_pb2.ConfigEnvelope(
        config=configtx_pb2.Config(sequence=1, channel_group=root)
    )
    ch = common_pb2.ChannelHeader(
        type=common_pb2.HeaderType.CONFIG, channel_id=CHANNEL
    )
    payload = common_pb2.Payload(data=cfg_env.SerializeToString())
    payload.header.channel_header = ch.SerializeToString()
    return common_pb2.Envelope(payload=payload.SerializeToString())


def test_add_orderer_to_live_channel(tmp_path):
    """Consenter-set growth via a committed config block: the running
    cluster re-wires membership + transport, and the new node catches
    up and participates."""
    async def scenario():
        nodes, cluster = await _mk_orderers(tmp_path, ["o0", "o1"],
                                            retention=1000)
        bc = BroadcastClient(list(cluster.values()))
        try:
            for i in range(3):
                assert (await bc.broadcast(
                    CHANNEL, b"pre%d" % i, retries=60))["status"] == 200

            # bring up o2 and commit the config block adding it
            o2 = OrdererNode("o2", str(tmp_path / "o2"), {})
            await o2.start()
            new_addr = ("127.0.0.1", o2.port)
            consenters = [
                (h, p, oid) for oid, (h, p) in cluster.items()
            ] + [(new_addr[0], new_addr[1], "o2")]
            env = _config_env(consenters)
            res = await bc.broadcast(
                CHANNEL, env.SerializeToString(), retries=60
            )
            assert res["status"] == 200

            # existing nodes adopted the new membership
            assert await _wait(lambda: all(
                "o2" in n.chains[CHANNEL].raft.peers
                for n in nodes.values()
            ), 15)
            assert all(n.cluster.get("o2") == new_addr for n in nodes.values())

            # o2 joins the channel and replicates the whole chain
            o2.cluster.update({**cluster, "o2": new_addr})
            ch2 = o2.join_channel(CHANNEL)
            nodes["o2"] = o2
            h0 = nodes["o0"].chains[CHANNEL].height
            assert await _wait(lambda: ch2.height >= h0, 30)

            # and it participates in NEW agreement
            assert (await bc.broadcast(CHANNEL, b"post", retries=60))["status"] == 200
            assert await _wait(
                lambda: ch2.height == nodes["o0"].chains[CHANNEL].height, 20
            )
            await bc.close()
        finally:
            for n in nodes.values():
                try:
                    await n.stop()
                except Exception:
                    pass

    run(scenario())

"""Traffic-autopilot battery (fabric_tpu.control) — crypto-free.

Three layers:

* the controller state machine under an injected clock — knob-spec
  parsing, hysteresis bands, per-knob cooldowns, clamp enforcement,
  max-one-step-per-tick, no-flap under a steady signal, the
  shed-then-recover round trip, disabled ⇒ zero actuations, and the
  observability contract (counter + tracer event + report);
* the runtime re-knobbing seams — CommitPipeline.set_depth /
  set_coalesce_blocks and BlockValidator.set_verify_chunk apply at
  block boundaries and never change verdicts;
* THE acceptance differential: a deterministic open-loop bursty
  overload (seeded invalid-sig storms via ``faults/``) through the
  real WeightedScheduler + SLO engine on one fake clock — autopilot
  OFF breaches the latency SLO (burn ≥ 1) while autopilot ON sheds a
  bounded, exactly-accounted request set and converges back under it,
  and the ledger accept set for every ADMITTED block is identical to
  the fault-free serial oracle through a real KVLedger.
"""

import json
import urllib.request

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.control import (
    Autopilot,
    KnobSpecError,
    Signals,
    parse_knob_specs,
)
from fabric_tpu.control.autopilot import Decision
from fabric_tpu.faults import FaultPlan, InjectedFault
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.observe import Tracer
from fabric_tpu.observe.slo import SloEngine, parse_slos
from fabric_tpu.ops_metrics import Registry
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.sidecar.scheduler import Request, WeightedScheduler


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def set(self, t: float) -> None:
        self.t = max(self.t, t)

    def advance(self, dt: float) -> None:
        self.t += dt


def _pilot(clk, *, acts=None, enabled=True, bands=None, specs=None,
           set_shed=None, set_weight=None, slo=None, scheduler=None,
           tracer=None, registry=None, initial=None):
    acts = acts if acts is not None else []
    return Autopilot(
        specs, lambda k, v: acts.append((k, v)),
        set_shed=set_shed, set_weight=set_weight, slo=slo,
        scheduler=scheduler,
        tracer=tracer or Tracer(ring_blocks=16, slow_factor=0,
                                clock=clk),
        clock=clk, registry=registry or Registry(), enabled=enabled,
        bands=bands,
        initial=initial or {"coalesce_blocks": 0, "verify_chunk": 0,
                            "pipeline_depth": 2},
    ), acts


# ---------------------------------------------------------------------------
# knob spec parsing


class TestKnobSpecs:
    def test_defaults_and_ladders(self):
        ks = parse_knob_specs("")
        assert ks["coalesce_blocks"].ladder() == (0, 2, 3, 4, 5, 6, 7, 8)
        assert ks["verify_chunk"].ladder() == (0, 4096, 2048, 1024, 512)
        assert ks["pipeline_depth"].ladder() == (2, 3, 4)
        assert ks["weight"].lo == 0.125 and ks["weight"].hi == 8

    def test_operator_override_merges_with_defaults(self):
        ks = parse_knob_specs(
            "verify_chunk:min=256:max=1024;pipeline_depth:max=3:cool=2"
        )
        assert ks["verify_chunk"].ladder() == (0, 1024, 512, 256)
        assert ks["pipeline_depth"].ladder() == (2, 3)
        assert ks["pipeline_depth"].cooldown_s == 2.0
        # untouched knobs keep their defaults
        assert ks["coalesce_blocks"].hi == 8

    @pytest.mark.parametrize("bad", [
        "frobnicate:min=1",            # unknown knob
        "verify_chunk:min=9:max=2",    # max < min
        "pipeline_depth:min=1",        # serial boundary not a target
        "weight:min=0",                # scheduler rejects w <= 0
        "verify_chunk:bogus=1",        # unknown key
        "verify_chunk:min",            # not k=v
        "verify_chunk:min=abc",        # unparsable
        "shed:cool=-1",                # negative cooldown
        "host_stage_workers:min=1",    # a 1-worker pool does not exist
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(KnobSpecError):
            parse_knob_specs(bad)


# ---------------------------------------------------------------------------
# controller state machine (injected clock, injected signals)


class TestController:
    def test_hysteresis_dead_band_holds(self):
        clk = Clock(100.0)
        ap, acts = _pilot(clk)
        # between the bands (5 < 20 < 50): no actuation, ever
        for i in range(50):
            clk.advance(1.0)
            d = ap.tick(Signals(queue_age_p99_ms={"t": 20.0},
                                clock_s=clk()))
            assert d is None
        assert acts == []

    def test_steps_up_above_hi_down_below_lo(self):
        clk = Clock(100.0)
        ap, acts = _pilot(clk)
        d = ap.tick(Signals(queue_age_p99_ms={"t": 80.0}, clock_s=clk()))
        assert (d.knob, d.direction, d.new) == ("coalesce_blocks", "up", 2)
        clk.advance(60.0)
        d = ap.tick(Signals(queue_age_p99_ms={"t": 1.0}, clock_s=clk()))
        assert (d.knob, d.direction, d.new) == ("coalesce_blocks",
                                                "down", 0)
        assert acts == [("coalesce_blocks", 2), ("coalesce_blocks", 0)]

    def test_cooldown_blocks_consecutive_steps(self):
        clk = Clock(100.0)
        ap, acts = _pilot(clk)
        assert ap.tick(Signals(queue_age_p99_ms={"t": 80.0},
                               clock_s=clk())) is not None
        for dt in (1.0, 3.0, 5.0):  # still inside the 10s cooldown
            assert ap.tick(Signals(queue_age_p99_ms={"t": 80.0},
                                   clock_s=clk() + dt)) is None
        d = ap.tick(Signals(queue_age_p99_ms={"t": 80.0},
                            clock_s=clk() + 10.0))
        assert d is not None and d.new == 3

    def test_clamps_at_ladder_ends_and_stops(self):
        clk = Clock(0.0)
        ap, acts = _pilot(clk)
        ladder = ap.specs["coalesce_blocks"].ladder()
        # drive the hi signal long past saturation
        for i in range(30):
            clk.advance(20.0)
            ap.tick(Signals(queue_age_p99_ms={"t": 500.0},
                            clock_s=clk()))
        values = [v for k, v in acts if k == "coalesce_blocks"]
        assert values == list(ladder[1:])          # walked to the clamp
        assert ap.values["coalesce_blocks"] == ladder[-1]
        n = len(acts)
        for i in range(10):                        # and STOPPED there
            clk.advance(20.0)
            assert ap.tick(Signals(queue_age_p99_ms={"t": 500.0},
                                   clock_s=clk())) is None
        assert len(acts) == n
        assert all(v in ladder for v in values)    # never out of range

    def test_max_one_step_per_tick(self):
        clk = Clock(0.0)
        ap, acts = _pilot(clk)
        # every rule's hi signal at once → exactly one actuation
        s = Signals(
            queue_age_p99_ms={"t": 500.0}, launch_p99_ms=900.0,
            overlap_coverage=0.05, clock_s=20.0,
        )
        d = ap.tick(s)
        assert d is not None
        assert len(acts) == 1

    def test_no_flap_under_steady_signal(self):
        """A constant signal converges (one step at most toward its
        band) and then produces ZERO further actuations — the
        hysteresis acceptance."""
        clk = Clock(0.0)
        ap, acts = _pilot(clk)
        for i in range(60):
            clk.advance(20.0)  # past every cooldown
            ap.tick(Signals(launch_p99_ms=150.0,  # inside the dead band
                            queue_age_p99_ms={"t": 20.0},
                            overlap_coverage=0.5, clock_s=clk()))
        assert acts == []

    def test_chunk_ladder_shrinks_then_recovers(self):
        clk = Clock(0.0)
        ap, acts = _pilot(clk)
        for i in range(4):
            clk.advance(20.0)
            ap.tick(Signals(launch_p99_ms=900.0, clock_s=clk()))
        assert [v for k, v in acts if k == "verify_chunk"] == [
            4096, 2048, 1024, 512
        ]
        acts.clear()
        for i in range(8):
            clk.advance(20.0)
            ap.tick(Signals(launch_p99_ms=5.0, clock_s=clk()))
        # walks back down the ladder to monolithic and stops
        assert [v for k, v in acts if k == "verify_chunk"][-1] == 0

    def test_depth_steps_down_on_wasted_window(self):
        clk = Clock(0.0)
        ap, acts = _pilot(clk, initial={"coalesce_blocks": 0,
                                        "verify_chunk": 0,
                                        "pipeline_depth": 4})
        d = ap.tick(Signals(overlap_coverage=0.1, clock_s=20.0))
        assert (d.knob, d.direction, d.new) == ("pipeline_depth",
                                                "down", 3)
        d = ap.tick(Signals(overlap_coverage=0.95, clock_s=60.0))
        assert (d.knob, d.direction, d.new) == ("pipeline_depth",
                                                "up", 4)

    def test_host_stage_workers_ladder_and_defaults(self):
        ks = parse_knob_specs("")
        # 1 is meaningless (resolve_host_pool returns None below 2):
        # the ladder jumps serial → 2 workers
        assert ks["host_stage_workers"].ladder() == (0, 2, 3, 4)
        ks = parse_knob_specs("host_stage_workers:min=2:max=6")
        assert ks["host_stage_workers"].ladder() == (2, 3, 4, 5, 6)

    def test_host_workers_initial_resolution_never_inverts(self):
        """Raw −1 (one worker per core) must reach the ladder snap as
        the RESOLVED pool size — snapping it to 0 would make the
        first slow-feeder 'up' step SHRINK a per-core pool."""
        from fabric_tpu.control import resolve_host_workers_initial

        assert resolve_host_workers_initial(-1, cores=8) == 8
        assert resolve_host_workers_initial(-1, cores=1) == 0
        assert resolve_host_workers_initial(0, cores=8) == 0
        assert resolve_host_workers_initial(1, cores=8) == 0
        assert resolve_host_workers_initial(3, cores=8) == 3
        assert resolve_host_workers_initial(16, cores=2) == 2

    def test_host_workers_ladder_clamps_to_cores(self):
        """Rungs above the core count would charge cooldowns and log
        decisions the pool can never act on — the spec clamps to the
        machine before the controller is built."""
        from fabric_tpu.control import host_clamped_specs

        specs = host_clamped_specs(parse_knob_specs(""), cores=3)
        assert specs["host_stage_workers"].ladder() == (0, 2, 3)
        # other knobs untouched
        assert specs["pipeline_depth"].ladder() == (2, 3, 4)
        # a 1-core host leaves the knob structurally inert (1 rung)
        one = host_clamped_specs(parse_knob_specs(""), cores=1)
        assert one["host_stage_workers"].ladder() == (0,)
        clk = Clock(0.0)
        ap, acts = _pilot(clk, specs=one)
        assert ap.tick(Signals(prefetch_p99_ms=900.0,
                               clock_s=20.0)) is None
        assert acts == []
        # already inside the machine: the spec passes through as-is
        ok = parse_knob_specs("")
        assert host_clamped_specs(ok, cores=16) is ok

    def test_host_stage_workers_steps_on_prefetch_p99(self):
        """The PR-10 follow-up knob: a slow feeder (prefetch p99 over
        the band) grows the staging pool; a comfortably-ahead feeder
        walks it back toward serial."""
        clk = Clock(0.0)
        ap, acts = _pilot(clk)
        d = ap.tick(Signals(prefetch_p99_ms=500.0, clock_s=20.0))
        assert (d.knob, d.direction, d.new) == ("host_stage_workers",
                                                "up", 2)
        clk.advance(60.0)
        d = ap.tick(Signals(prefetch_p99_ms=500.0, clock_s=80.0))
        assert (d.knob, d.new) == ("host_stage_workers", 3)
        clk.advance(60.0)
        d = ap.tick(Signals(prefetch_p99_ms=1.0, clock_s=140.0))
        assert (d.knob, d.direction, d.new) == ("host_stage_workers",
                                                "down", 2)
        # dead band holds — no flap between the thresholds
        clk.advance(60.0)
        assert ap.tick(Signals(prefetch_p99_ms=80.0,
                               clock_s=200.0)) is None
        assert [v for k, v in acts if k == "host_stage_workers"] == [
            2, 3, 2
        ]

    def test_host_stage_workers_actuates_a_real_validator_pool(self):
        """Pinned end-to-end actuation: decision → apply_knob →
        BlockValidator.set_host_stage_workers → HostStagePool built/
        resized at the block boundary (what preprocess() runs first)."""
        import os

        if (os.cpu_count() or 1) < 2:
            pytest.skip("needs 2 cores")
        # validator imports the MSP stack (seed condition on this host)
        pytest.importorskip("cryptography")
        from fabric_tpu.peer.validator import BlockValidator

        v = BlockValidator(None, object(), MemVersionedDB())
        try:
            clk = Clock(0.0)
            ap, _ = _pilot(clk)
            ap.apply_knob = lambda k, val: (
                v.set_host_stage_workers(int(val))
                if k == "host_stage_workers" else None
            )
            d = ap.tick(Signals(prefetch_p99_ms=500.0, clock_s=20.0))
            assert d.knob == "host_stage_workers" and d.new == 2
            assert v.host_pool is None          # latched, block boundary
            v._apply_pending_knobs()            # what preprocess() runs
            assert v.host_pool is not None
            assert v.host_pool.workers == 2
            # recovery: the loop can walk the pool away again
            clk.advance(60.0)
            d = ap.tick(Signals(prefetch_p99_ms=1.0, clock_s=80.0))
            assert (d.knob, d.new) == ("host_stage_workers", 0)
            v._apply_pending_knobs()
            assert v.host_pool is None
        finally:
            v.close()

    def test_shed_then_recover_round_trip(self):
        clk = Clock(0.0)
        sheds = []
        ap, acts = _pilot(
            clk, set_shed=lambda t, on: sheds.append((t, on)),
        )
        burn = {("lat", "sidecar:noisy"): 9.0}
        d = ap.tick(Signals(burn=burn, clock_s=20.0))
        assert (d.knob, d.direction, d.tenant) == ("shed", "on", "noisy")
        assert sheds == [("noisy", True)]
        # still burning → shed stays (no flapping off)
        assert ap.tick(Signals(burn=burn, clock_s=40.0)) is None
        # burn aged out (None) + queue drained → shed off after cooldown
        d = ap.tick(Signals(burn={("lat", "sidecar:noisy"): None},
                            queue_depth={"noisy": 0},
                            clock_s=60.0))
        assert (d.knob, d.direction, d.tenant) == ("shed", "off", "noisy")
        assert sheds == [("noisy", True), ("noisy", False)]

    def test_shed_still_queued_holds(self):
        """A shed tenant whose queue has not drained stays shed even
        with the burn aged out — what was admitted must finish first."""
        clk = Clock(0.0)
        sheds = []
        ap, _ = _pilot(clk,
                       set_shed=lambda t, on: sheds.append((t, on)))
        ap.tick(Signals(burn={("lat", "sidecar:noisy"): 9.0},
                        clock_s=20.0))
        d = ap.tick(Signals(burn={("lat", "sidecar:noisy"): None},
                            queue_depth={"noisy": 7}, clock_s=60.0))
        assert d is None
        assert sheds == [("noisy", True)]

    def test_shed_targets_the_deepest_queue_not_the_victim(self):
        """Under a shared lane the overload VICTIM burns too (its
        requests wait behind the offender's) — the shed rule must pick
        the tenant holding the pressure, never the bystander."""
        clk = Clock(0.0)
        sheds = []
        ap, _ = _pilot(clk,
                       set_shed=lambda t, on: sheds.append((t, on)))
        s = Signals(
            burn={("lat", "sidecar:noisy"): 9.0,
                  ("lat", "sidecar:quiet"): 8.0},
            queue_depth={"noisy": 60, "quiet": 2},
            clock_s=20.0,
        )
        d = ap.tick(s)
        assert (d.knob, d.tenant) == ("shed", "noisy")
        # with noisy shed but still draining (deepest queue), the
        # burning victim is protected from a follow-up shed
        s2 = Signals(
            burn={("lat", "sidecar:quiet"): 8.0},
            queue_depth={"noisy": 40, "quiet": 2},
            clock_s=60.0,
        )
        assert ap.tick(s2) is None
        assert sheds == [("noisy", True)]

    def test_one_shed_at_a_time(self):
        """While a shed is active no second tenant sheds — every
        neighbor's burn is contaminated by the incident being bounded;
        a real second offender is re-evaluated once the knife lifts."""
        clk = Clock(0.0)
        sheds = []
        ap, _ = _pilot(clk,
                       set_shed=lambda t, on: sheds.append((t, on)))
        ap.tick(Signals(burn={("lat", "sidecar:a"): 9.0},
                        clock_s=20.0))
        assert sheds == [("a", True)]
        # b burns just as hard while a is shed: held
        assert ap.tick(Signals(
            burn={("lat", "sidecar:a"): 9.0, ("lat", "sidecar:b"): 9.0},
            clock_s=40.0,
        )) is None
        # a recovers and lifts; b still burning → b sheds next
        d = ap.tick(Signals(burn={("lat", "sidecar:b"): 9.0},
                            clock_s=60.0))
        assert (d.knob, d.direction, d.tenant) == ("shed", "off", "a")
        d = ap.tick(Signals(burn={("lat", "sidecar:b"): 9.0},
                            clock_s=80.0))
        assert (d.knob, d.direction, d.tenant) == ("shed", "on", "b")
        assert sheds == [("a", True), ("a", False), ("b", True)]

    def test_shed_catches_the_serial_offender_by_share(self):
        """A serial-submitting offender waits on each verdict, so its
        queue depth stays 0 — but it dominates the served share.  The
        rule must shed it; a depth-0 tenant being OUT-consumed by a
        neighbor is a victim and stays protected."""
        clk = Clock(0.0)
        sheds = []
        ap, _ = _pilot(clk,
                       set_shed=lambda t, on: sheds.append((t, on)))
        victim = Signals(
            burn={("lat", "sidecar:quiet"): 9.0},
            queue_depth={"noisy": 0, "quiet": 0},
            share={"noisy": 0.9, "quiet": 0.1},
            clock_s=20.0,
        )
        assert ap.tick(victim) is None     # quiet burns but consumes
        offender = Signals(                # little — protected
            burn={("lat", "sidecar:noisy"): 9.0},
            queue_depth={"noisy": 0, "quiet": 0},
            share={"noisy": 0.9, "quiet": 0.1},
            clock_s=40.0,
        )
        d = ap.tick(offender)
        assert (d.knob, d.tenant, d.direction) == ("shed", "noisy", "on")
        assert sheds == [("noisy", True)]

    def test_reweight_down_and_restore(self):
        clk = Clock(0.0)
        weights = []
        ap, _ = _pilot(
            clk, set_weight=lambda t, w: weights.append((t, w)),
        )
        ap.observe_hello("t0", 4.0)
        d = ap.tick(Signals(burn={("lat", "sidecar:t0"): 2.0},
                            clock_s=20.0))
        assert (d.knob, d.direction, d.new) == ("weight", "down", 2.0)
        d = ap.tick(Signals(burn={("lat", "sidecar:t0"): 0.1},
                            clock_s=40.0))
        assert (d.knob, d.direction, d.new) == ("weight", "up", 4.0)
        assert weights == [("t0", 2.0), ("t0", 4.0)]

    def test_disabled_means_zero_actuations(self):
        clk = Clock(0.0)
        sheds = []
        ap, acts = _pilot(
            clk, enabled=False,
            set_shed=lambda t, on: sheds.append((t, on)),
        )
        for i in range(20):
            clk.advance(20.0)
            d = ap.tick(Signals(
                queue_age_p99_ms={"t": 500.0}, launch_p99_ms=900.0,
                overlap_coverage=0.05,
                burn={("lat", "sidecar:t"): 50.0}, clock_s=clk(),
            ))
            assert d is None
        assert acts == [] and sheds == []
        assert ap.report()["decisions"] == []

    def test_every_actuation_is_observable(self):
        clk = Clock(0.0)
        reg = Registry()
        tr = Tracer(ring_blocks=16, slow_factor=0, clock=clk)
        ap, acts = _pilot(clk, registry=reg, tracer=tr)
        ap.tick(Signals(queue_age_p99_ms={"t": 500.0}, clock_s=20.0))
        # counter
        assert reg.counter("autopilot_actuations_total").value(
            knob="coalesce_blocks", direction="up"
        ) == 1
        # tracer event in the autopilot namespace ring
        trees = tr.blocks(ns="autopilot")
        assert len(trees) == 1
        assert trees[0]["attrs"]["knob"] == "coalesce_blocks"
        # /autopilot report
        rep = ap.report()
        (dec,) = rep["decisions"]
        assert dec["knob"] == "coalesce_blocks"
        assert dec["signal"] == "queue_age_p99_ms"
        assert rep["knobs"]["coalesce_blocks"]["value"] == 2
        # enabled gauge
        assert reg.gauge("autopilot_enabled").value() == 1
        ap.set_enabled(False)
        assert reg.gauge("autopilot_enabled").value() == 0


# ---------------------------------------------------------------------------
# /autopilot endpoint


def test_autopilot_endpoint_over_live_opsserver():
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    clk = Clock(0.0)
    ap, _ = _pilot(clk)
    ap.tick(Signals(queue_age_p99_ms={"t": 500.0}, clock_s=20.0))

    def _get(port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return r.status, json.loads(r.read())

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=Registry(), health=HealthRegistry(),
            tracer=Tracer(ring_blocks=4, slow_factor=0),
            autopilot=ap,
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, rep = await loop.run_in_executor(
                None, _get, srv.port, "/autopilot"
            )
            assert st == 200
            assert rep["configured"] is True and rep["enabled"] is True
            assert rep["knobs"]["coalesce_blocks"]["value"] == 2
            assert rep["decisions"][0]["knob"] == "coalesce_blocks"
            assert rep["signals"]["queue_age_p99_ms"] == {"t": 500.0}
        finally:
            await srv.stop()

    import asyncio as _a

    loop = _a.new_event_loop()
    try:
        loop.run_until_complete(_a.wait_for(scenario(), 30))
    finally:
        loop.close()


def test_autopilot_endpoint_unconfigured():
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=Registry(), health=HealthRegistry(),
            tracer=Tracer(ring_blocks=4, slow_factor=0),
        ).start()
        try:
            loop = asyncio.get_event_loop()

            def _get():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/autopilot", timeout=10
                ) as r:
                    return r.status, json.loads(r.read())

            st, rep = await loop.run_in_executor(None, _get)
            assert st == 200
            assert rep == {"enabled": False, "configured": False}
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


def test_nodeconfig_validates_autopilot_knobs():
    from fabric_tpu.nodeconfig import ConfigError, load_peer_config

    base = {"id": "p0", "data_dir": "/tmp/x", "msp_id": "Org1MSP",
            "msp_dir": "/tmp/msp"}
    with pytest.raises(ConfigError, match="autopilot_knobs"):
        load_peer_config(
            {**base, "autopilot": True,
             "autopilot_knobs": "frobnicate:min=1"}, environ={},
        )
    with pytest.raises(ConfigError, match="autopilot_tick_s"):
        load_peer_config({**base, "autopilot_tick_s": 0}, environ={})
    cfg = load_peer_config(
        {**base, "autopilot": True, "autopilot_tick_s": 0.5,
         "autopilot_knobs": "pipeline_depth:max=3"}, environ={},
    )
    assert cfg.autopilot is True and cfg.autopilot_tick_s == 0.5


# ---------------------------------------------------------------------------
# runtime re-knobbing seams (block-boundary application)


class MiniPtx:
    def __init__(self, txid, idx):
        self.txid, self.idx, self.is_config = txid, idx, False


class MiniPending:
    def __init__(self, block, txs, raw):
        self.block, self.txs, self.raw = block, txs, raw
        self.hd_bytes = None

    @property
    def txids(self):
        return {p.txid for p in self.txs}


class MiniValidator:
    """Toy validator: a tx is VALID unless it carries a ``reads`` map
    whose versions mismatch committed state (the storm lanes read a
    never-written key at a bogus version → MVCC fail); every valid tx
    writes its own id."""

    VALID, MVCC = 0, 11

    def __init__(self, state):
        self.state = state

    def preprocess(self, block):
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        txs = [MiniPtx(t["id"], i) for i, t in enumerate(raw)]
        return MiniPending(block, txs, raw)

    def validate_finish(self, pend):
        codes, batch = [], UpdateBatch()
        num = pend.block.header.number
        for ptx, t in zip(pend.txs, pend.raw):
            ok = all(
                (None if (vv := self.state.get_state("ns", k)) is None
                 else list(vv.version)) == want
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            batch.put("ns", ptx.txid, b"v", (num, ptx.idx))
        return bytes(codes), batch, []


def _mini_block(num, prev, txs):
    blk = pu.new_block(num, prev)
    for t in txs:
        blk.data.data.append(json.dumps(t).encode())
    return pu.finalize_block(blk)


def _mini_stream(n_blocks, n_tx=4):
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = [{"id": f"tx{n}_{i}"} for i in range(n_tx)]
        blk = _mini_block(n, prev, txs)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def test_pipeline_set_depth_applies_at_block_boundary():
    """Depth re-knobbed mid-stream: filters and state match the
    serial oracle exactly, and the new depth is live for the rest of
    the stream (block-boundary application, never mid-window)."""
    blocks = _mini_stream(8)

    def run(reknob):
        state = MemVersionedDB()
        v = MiniValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, depth=2) as pipe:
            for b in blocks:
                if reknob and b.header.number == 3:
                    pipe.set_depth(4)
                if reknob and b.header.number == 6:
                    pipe.set_depth(2)
                r = pipe.submit(b)
                if r is not None:
                    filters.append((r.block.header.number,
                                    list(r.tx_filter)))
                if reknob and b.header.number == 3:
                    # latched value applied at THIS submit boundary
                    assert pipe.depth == 4
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        assert pipe.depth == 2 if reknob else True
        return sorted(filters), dict(state._data)

    assert run(reknob=True) == run(reknob=False)


def test_pipeline_set_depth_never_crosses_serial_boundary():
    state = MemVersionedDB()
    v = MiniValidator(state)
    pipe = CommitPipeline(v, lambda res: None, depth=1)
    pipe.set_depth(4)          # serial pipe stays serial
    pipe.submit(_mini_stream(1)[0])
    assert pipe.depth == 1
    pipe.close()
    pipe2 = CommitPipeline(v, lambda res: None, depth=2)
    pipe2.set_depth(1)         # pipelined pipe never drops below 2
    pipe2.submit(_mini_stream(1)[0])
    assert pipe2.depth == 2
    pipe2.close()


def test_pipeline_set_coalesce_blocks_latches():
    state = MemVersionedDB()
    v = MiniValidator(state)
    pipe = CommitPipeline(v, lambda res: None, depth=2,
                          coalesce_blocks=4)
    pipe.set_coalesce_blocks(1)  # < 2 → off
    pipe.submit(_mini_stream(1)[0])
    assert pipe.coalesce_blocks == 0
    pipe.set_coalesce_blocks(3)
    pipe.submit_many(_mini_stream(2)[1:])
    assert pipe.coalesce_blocks == 3
    pipe.close(flush=False)


def test_validator_set_verify_chunk_latches_at_preprocess():
    pytest.importorskip("cryptography")  # validator imports the MSP stack
    from fabric_tpu.peer.validator import BlockValidator, PolicyProvider

    v = BlockValidator(None, PolicyProvider({}), MemVersionedDB())
    assert v.verify_chunk == 0
    v.set_verify_chunk(1024)
    assert v.verify_chunk == 0        # not yet — block boundary only
    v._apply_pending_knobs()          # what preprocess() runs first
    assert v.verify_chunk == 1024
    v.set_verify_chunk(-5)
    v._apply_pending_knobs()
    assert v.verify_chunk == 0        # clamped at the monolithic floor
    v.close()


# ---------------------------------------------------------------------------
# THE acceptance differential: deterministic open-loop overload


def _run_overload(enabled: bool, seed: int = 11):
    """Discrete-event simulation of the sidecar admission path on ONE
    fake clock: open-loop arrivals, a single device lane with
    deterministic service times, seeded invalid-sig storms (via a
    local ``faults`` FaultPlan), the REAL WeightedScheduler + SLO
    engine + Autopilot.  Returns everything the assertions need."""
    clk = Clock(0.0)
    reg = Registry()
    tracer = Tracer(ring_blocks=512, slow_factor=0, clock=clk)
    engine = SloEngine(
        parse_slos("lat:latency:ms=100:target=0.9:windows=30:"
                   "min_events=3:fast=3"),
        clock=clk, registry=reg,
    )
    tracer.add_listener(engine.on_block)
    sched = WeightedScheduler(queue_limit=64, clock=clk, registry=reg)
    sched.register("noisy")
    sched.register("quiet")
    pilot = Autopilot(
        None, lambda k, v: None, set_shed=sched.set_shed,
        slo=engine, scheduler=sched, tracer=tracer, clock=clk,
        registry=reg, enabled=enabled,
        bands={"shed_hi": 3.0, "shed_lo": 1.0},
    )
    # seeded storm membership: which noisy requests arrive as an
    # invalid-sig storm — the faults registry is the deterministic
    # replay machinery (a LOCAL plan; nothing global is armed)
    storm_plan = FaultPlan("sim.storm:raise:p=0.85", seed=seed)

    arrivals = []
    t = 5.0
    while t < 25.0:                 # the overload phase
        arrivals.append((round(t, 3), "noisy"))
        t += 0.05
    t = 25.0
    while t < 60.0:                 # noisy calms down
        arrivals.append((round(t, 3), "noisy"))
        t += 0.5
    t = 0.0
    while t < 60.0:                 # the collateral-damage tenant
        arrivals.append((round(t, 3), "quiet"))
        t += 0.5
    arrivals.sort()

    state = {
        "server_free": 0.0, "last_tick": 0.0, "seq": 0,
        "admitted": [], "shed": [], "busy": [],
    }
    inflight: dict[int, tuple] = {}  # seq → (root, completion, lanes)

    def maybe_tick():
        while clk() - state["last_tick"] >= 1.0:
            state["last_tick"] += 1.0
            pilot.tick()

    def service_s(lanes):
        bad = sum(1 for l in lanes if l["bad"])
        return 0.4 if bad else 0.02

    def make_lanes(tenant, seq):
        storm = False
        if tenant == "noisy" and clk() < 25.0:
            try:
                storm_plan.fire("sim.storm")
            except InjectedFault:
                storm = True
        n = 4 if tenant == "noisy" else 2
        return [
            {"id": f"{tenant}-{seq}-{i}", "bad": storm and i % 2 == 0}
            for i in range(n)
        ]

    def serve_until(limit):
        while sched.pending():
            start = max(state["server_free"], clk())
            if start >= limit:
                return
            clk.set(start)
            maybe_tick()
            batch = sched.next_batch(1)
            if not batch:
                return
            (req,) = batch
            root, lanes = inflight.pop(req.seq)
            done = start + service_s(lanes)
            state["server_free"] = done
            clk.set(done)
            maybe_tick()
            tracer.finish_block(root)
            state["admitted"].append(lanes)

    for t_arr, tenant in arrivals:
        serve_until(t_arr)
        clk.set(t_arr)
        maybe_tick()
        state["seq"] += 1
        seq = state["seq"]
        lanes = make_lanes(tenant, seq)
        root = tracer.begin_block(
            seq, ns="sidecar", channel=f"sidecar:{tenant}"
        )
        req = Request(tenant=tenant, seq=seq, items=lanes,
                      t_enqueue=clk())
        if sched.submit(req):
            inflight[seq] = (root, lanes)
        else:
            tracer.set_attrs(root, busy=True)
            tracer.finish_block(root)
            (state["shed"] if sched.is_shed(tenant)
             else state["busy"]).append((tenant, seq))
    serve_until(float("inf"))
    clk.set(max(clk(), 61.0))
    maybe_tick()
    return {
        "clk": clk, "engine": engine, "sched": sched, "pilot": pilot,
        "tracer": tracer, **state,
    }


def _commit_blocks(admitted, ledger_dir, depth):
    """Admitted request lanes → toy blocks 0..n−1 through the real
    CommitPipeline + KVLedger; → per-block filters recounted OFF THE
    LEDGER (pu.get_tx_filter)."""
    from fabric_tpu.ledger.kvledger import KVLedger

    blocks, prev = [], b""
    for num, lanes in enumerate(admitted):
        txs = [
            {"id": l["id"],
             **({"reads": {"missing": [9, 9]}} if l["bad"] else {})}
            for l in lanes
        ]
        blk = _mini_block(num, prev, txs)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    state = MemVersionedDB()
    v = MiniValidator(state)
    lg = KVLedger(str(ledger_dir), state_db=state)

    def commit_fn(res):
        lg.commit_block(res.block, res.tx_filter, res.batch,
                        res.history, None, res.txids)

    with CommitPipeline(v, commit_fn, depth=depth) as pipe:
        for b in blocks:
            pipe.submit(b)
        pipe.flush()
    assert lg.blocks.height == len(admitted)
    filters = [
        list(pu.get_tx_filter(lg.blocks.get_block(n)))
        for n in range(lg.blocks.height)
    ]
    st = dict(state._data)
    lg.close()
    return filters, st


def test_bursty_overload_differential(tmp_path):
    """THE acceptance scenario: the same seeded bursty overload run
    autopilot-OFF breaches the latency SLO (burn ≥ 1 at end of run)
    while autopilot-ON sheds a bounded, exactly-accounted request set
    and converges back under it — and the ledger accept set for every
    ADMITTED block is identical to the fault-free serial oracle."""
    off = _run_overload(enabled=False)
    on = _run_overload(enabled=True)

    # -- OFF breaches: the storm's backlog keeps landing bad latency
    # samples in the trailing window; burn ≥ 1 sustained at end
    off_burn = off["engine"].burn("lat", "sidecar:noisy")
    assert off_burn is not None and off_burn >= 1.0
    assert off["shed"] == []            # nothing shed without the loop
    assert list(off["pilot"].decisions) == []

    # -- ON converges: shed mode bounded the overload and the end-of-
    # run burn is back under 1 on every channel
    assert len(on["shed"]) > 0
    for chan in ("sidecar:noisy", "sidecar:quiet"):
        b = on["engine"].burn("lat", chan)
        assert b is None or b < 1.0, (chan, b)
    # the shed set is EXACTLY accounted: harness count == scheduler
    # count == counter, and admitted + shed + queue-full == arrivals
    stats = on["sched"].stats()
    assert stats["noisy"]["shed_count"] == len(on["shed"])
    assert all(t == "noisy" for t, _s in on["shed"])
    total_arrivals = on["seq"]
    assert (len(on["admitted"]) + len(on["shed"])
            + len(on["busy"])) == total_arrivals
    # shed happened THROUGH the autopilot: its decision log shows the
    # on (and later off) transitions, every one clamp-legal
    kinds = [(d.knob, d.direction) for d in on["pilot"].decisions]
    assert ("shed", "on") in kinds
    for d in on["pilot"].decisions:
        if d.knob in on["pilot"].specs and on["pilot"].specs[
                d.knob].ladder():
            assert d.new in on["pilot"].specs[d.knob].ladder()
    # recovery: noisy is NOT shed at end of run (round trip closed)
    assert not on["sched"].is_shed("noisy")

    # -- ledger differential: admitted blocks through the real
    # depth-2 CommitPipeline + KVLedger ≡ the fault-free serial
    # oracle (depth 1, fresh state) — overload machinery never
    # changes a verdict of admitted work
    f2, s2 = _commit_blocks(on["admitted"], tmp_path / "d2", depth=2)
    f1, s1 = _commit_blocks(on["admitted"], tmp_path / "d1", depth=1)
    assert f2 == f1
    assert s2 == s1
    # and the storm lanes really were load-bearing: some MVCC rejects
    flat = [c for flt in f2 for c in flt]
    assert MiniValidator.MVCC in flat and MiniValidator.VALID in flat


# ---------------------------------------------------------------------------
# sign_batch_max: the endorsement sign-lane knob (ISSUE 13)


class TestSignBatchKnob:
    def test_spec_defaults_and_ladder(self):
        ks = parse_knob_specs("")
        assert ks["sign_batch_max"].ladder() == (
            64, 128, 256, 512, 1024, 2048, 4096
        )
        # operator override reshapes the doubling ladder; max is
        # always a reachable rung
        ks = parse_knob_specs("sign_batch_max:min=32:max=100")
        assert ks["sign_batch_max"].ladder() == (32, 64, 100)

    def test_malformed_spec_raises(self):
        with pytest.raises(KnobSpecError):
            parse_knob_specs("sign_batch_max:min=0")

    def test_up_on_busy_down_on_quiet_dead_band_holds(self):
        clk = Clock()
        pilot, acts = _pilot(
            clk, initial={"sign_batch_max": 256},
        )
        # no sign lane → no signal → never a decision
        assert pilot.tick(Signals(clock_s=clk.t)) is None
        # busy above the band → one step up the doubling ladder
        clk.advance(30)
        d = pilot.tick(Signals(sign_busy_rate=0.2, clock_s=clk.t))
        assert (d.knob, d.direction, d.old, d.new) == (
            "sign_batch_max", "up", 256, 512
        )
        assert ("sign_batch_max", 512) in acts
        # cooldown holds even under continued pressure
        clk.advance(1)
        assert pilot.tick(
            Signals(sign_busy_rate=0.2, clock_s=clk.t)
        ) is None
        # dead band: moderate busy rate holds steady
        clk.advance(30)
        assert pilot.tick(
            Signals(sign_busy_rate=0.02, clock_s=clk.t)
        ) is None
        # quiet AND draining fast → step back down
        d = pilot.tick(Signals(
            sign_busy_rate=0.0, sign_wait_p99_ms=1.0, clock_s=clk.t
        ))
        assert (d.knob, d.direction, d.new) == (
            "sign_batch_max", "down", 256
        )
        # quiet but waits long (filling lane) → hold, don't shrink
        clk.advance(30)
        assert pilot.tick(Signals(
            sign_busy_rate=0.0, sign_wait_p99_ms=50.0, clock_s=clk.t
        )) is None

    def test_sign_source_signal_to_real_batcher_actuation(self):
        """read_signals() ingests the SignBatcher stats shape and the
        decision lands on a REAL batcher through apply_knob — the
        PeerNode wiring, minus the network."""
        from types import SimpleNamespace

        from fabric_tpu.peer.signlane import SignBatcher

        batcher = SignBatcher(lambda d: [(1, 1)] * len(d),
                              batch_max=256, wait_ms=0.0)
        clk = Clock(100.0)
        source = SimpleNamespace(stats=lambda: {
            "busy_rate": 0.5, "wait_ms": {"n": 9, "p99": 80.0},
        })
        pilot = Autopilot(
            None,
            lambda k, v: (k == "sign_batch_max"
                          and batcher.set_batch_max(int(v))),
            sign_source=source, clock=clk, registry=Registry(),
            initial={"sign_batch_max": 256},
        )
        s = pilot.read_signals()
        assert s.sign_busy_rate == 0.5
        assert s.sign_wait_p99_ms == 80.0
        d = pilot.tick()
        assert d is not None and d.knob == "sign_batch_max"
        assert batcher.batch_max == 512

# ---------------------------------------------------------------------------
# sign_batch_wait_ms: the coalescing-window knob (ISSUE 14 satellite —
# the ROADMAP PR-13 follow-up: drive wait_ms alongside the batch cap)


class TestSignWaitKnob:
    def test_spec_defaults_and_ladder(self):
        ks = parse_knob_specs("")
        assert ks["sign_batch_wait_ms"].ladder() == (
            0.5, 1.0, 2.0, 4.0, 8.0, 16.0
        )
        # operator override reshapes the doubling ladder; the max is
        # always a reachable rung
        ks = parse_knob_specs("sign_batch_wait_ms:min=1:max=6")
        assert ks["sign_batch_wait_ms"].ladder() == (1.0, 2.0, 4.0, 6.0)

    def test_malformed_spec_raises(self):
        # a 0 floor cannot seed a doubling ladder — operator-grade
        # error at config load, not a silent dead knob
        with pytest.raises(KnobSpecError):
            parse_knob_specs("sign_batch_wait_ms:min=0:max=8")
        with pytest.raises(KnobSpecError):
            parse_knob_specs("sign_batch_wait_ms:min=-1")

    def test_down_on_wait_up_on_empty_flushes_dead_band_cooldown(self):
        clk = Clock()
        pilot, acts = _pilot(
            clk, initial={"sign_batch_wait_ms": 2.0},
        )
        # no sign lane → no signal → never a decision
        assert pilot.tick(Signals(clock_s=clk.t)) is None
        # wait p99 past its band → the linger IS the latency: step DOWN
        clk.advance(30)
        d = pilot.tick(Signals(
            sign_wait_p99_ms=50.0, clock_s=clk.t
        ))
        assert (d.knob, d.direction, d.old, d.new) == (
            "sign_batch_wait_ms", "down", 2.0, 1.0
        )
        assert ("sign_batch_wait_ms", 1.0) in acts
        # cooldown holds under continued pressure
        clk.advance(1)
        assert pilot.tick(Signals(
            sign_wait_p99_ms=50.0, clock_s=clk.t
        )) is None
        # dead band: short waits + healthy fill hold steady
        clk.advance(30)
        assert pilot.tick(Signals(
            sign_wait_p99_ms=3.0, sign_fill=0.6, clock_s=clk.t
        )) is None
        # flowing lane flushing nearly-empty batches → linger longer
        d = pilot.tick(Signals(
            sign_wait_p99_ms=1.0, sign_fill=0.05, clock_s=clk.t
        ))
        assert (d.knob, d.direction, d.new) == (
            "sign_batch_wait_ms", "up", 2.0
        )
        # busy pressure outranks the window knob (6b before 6c)
        clk.advance(30)
        pilot2, _ = _pilot(clk, initial={
            "sign_batch_max": 256, "sign_batch_wait_ms": 2.0,
        })
        d = pilot2.tick(Signals(
            sign_busy_rate=0.5, sign_wait_p99_ms=50.0, clock_s=clk.t
        ))
        assert d.knob == "sign_batch_max"

    def test_dropped_spec_leaves_knob_structurally_inert(self):
        """The PeerNode wiring for an operator-configured
        sign_batch_wait_ms=0 (flush immediately): the knob's spec is
        DROPPED before the controller is built, so no signal can ever
        actuate it — the static choice is never silently overridden."""
        clk = Clock()
        specs = {k: v for k, v in parse_knob_specs("").items()
                 if k != "sign_batch_wait_ms"}
        pilot, acts = _pilot(clk, specs=specs,
                             initial={"sign_batch_wait_ms": 0.0})
        assert "sign_batch_wait_ms" not in pilot.values
        clk.advance(30)
        assert pilot.tick(Signals(
            sign_wait_p99_ms=50.0, sign_fill=0.01, clock_s=clk.t
        )) is None
        assert acts == []

    def test_fill_signal_to_real_batcher_actuation(self):
        """read_signals() derives the occupancy-fill fraction from the
        SignBatcher stats shape and the decision lands on a REAL
        batcher through set_wait_ms — the PeerNode wiring, minus the
        network."""
        from types import SimpleNamespace

        from fabric_tpu.peer.signlane import SignBatcher

        batcher = SignBatcher(lambda d: [(1, 1)] * len(d),
                              batch_max=256, wait_ms=2.0)
        clk = Clock(100.0)
        source = SimpleNamespace(stats=lambda: {
            "busy_rate": 0.0, "batch_max": 256,
            "wait_ms": {"n": 9, "p99": 1.0},
            "occupancy": {"n": 9, "p50": 8, "max": 12},
        })
        pilot = Autopilot(
            None,
            lambda k, v: (k == "sign_batch_wait_ms"
                          and batcher.set_wait_ms(float(v))),
            sign_source=source, clock=clk, registry=Registry(),
            initial={"sign_batch_wait_ms": 2.0},
        )
        s = pilot.read_signals()
        assert s.sign_fill == 8 / 256
        d = pilot.tick()
        assert d is not None and d.knob == "sign_batch_wait_ms"
        assert d.direction == "up"
        assert batcher._wait_ms == 4.0

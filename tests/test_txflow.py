"""Per-transaction flow journal battery (observe/txflow.py) —
tier-1 speed, crypto-free.

Covers the tentpole's acceptance geometry: the stage-identity
invariant (stages telescope over present milestones, so their sum IS
the e2e wall) on an injected clock, the bounded in-flight LRU, the
structurally-zero disarmed path, the ``/txflow`` surface over a live
OperationsServer, partial (orderer-side-only) records, replay
tagging, visibility lag against a REAL ``AsyncApplyEngine`` with a
stalled applier, and an end-to-end flow through the REAL
``CommitPipeline`` + serial ``KVLedger`` commit seam with the toy
JSON validator — every milestone landing in order on one clock.
"""

import asyncio
import json
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from fabric_tpu.observe import txflow
from fabric_tpu.observe.txflow import FlowJournal
from fabric_tpu.ops_metrics import Registry


class Clock:
    """Injected monotonic clock: tests advance it explicitly."""

    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _journal(**kw):
    kw.setdefault("registry", Registry())
    kw.setdefault("tracer", SimpleNamespace())
    return FlowJournal(**kw)


def _full_flow(j, clk, tx="tx-1", num=7, code=0, channel="ch"):
    j.endorse_begin(tx); clk.tick(0.010)
    j.endorse_end(tx); clk.tick(0.004)
    j.submit_begin(tx); clk.tick(0.002)
    j.broadcast_done(tx); clk.tick(0.030)
    j.block_included(num, [(tx, code)], channel=channel); clk.tick(0.005)
    j.block_durable(num); clk.tick(0.003)
    j.block_applied(num)


# -- stage identity ---------------------------------------------------------


def test_stage_identity_full_flow():
    """sum(stages) == e2e EXACTLY on one injected clock — the
    telescoping invariant the /txflow smoke re-asserts in CI."""
    clk = Clock()
    j = _journal(clock=clk)
    _full_flow(j, clk)
    (row,) = j.rows(8)
    assert row["outcome"] == "VALID"
    assert row["partial"] is False
    assert row["stages_ms"] == {
        "endorse": 10.0, "submit": 6.0, "order": 30.0,
        "durable": 5.0, "apply": 3.0,
    }
    assert abs(sum(row["stages_ms"].values()) - row["e2e_ms"]) < 1e-9
    assert row["visibility_lag_ms"] == pytest.approx(3.0)
    # milestones are offsets from the first stamp, strictly ordered
    ms = row["milestones"]
    order = ["endorse_begin", "endorse_end", "submit", "broadcast",
             "included", "durable", "applied"]
    assert list(ms) == order
    assert all(ms[a] < ms[b] for a, b in zip(order, order[1:]))


def test_stage_identity_partial_flow():
    """A tx first seen at inclusion (orderer-side) still satisfies
    the identity: its stages start at ``durable``/``apply``."""
    clk = Clock()
    j = _journal(clock=clk)
    j.block_included(3, [("txP", 0)]); clk.tick(0.008)
    j.block_durable(3); clk.tick(0.002)
    j.block_applied(3)
    (row,) = j.rows(8)
    assert row["partial"] is True
    assert row["stages_ms"] == {"durable": 8.0, "apply": 2.0}
    assert abs(sum(row["stages_ms"].values()) - row["e2e_ms"]) < 1e-9
    assert "endorse" not in row["stages_ms"]


def test_missing_durable_merges_into_apply():
    """No durable fence observed (mem-state serial path) → the
    interval merges into ``apply`` and the identity still holds;
    visibility lag is honestly absent."""
    clk = Clock()
    j = _journal(clock=clk)
    j.block_included(1, [("txM", 0)]); clk.tick(0.009)
    j.block_applied(1)
    (row,) = j.rows(8)
    assert row["stages_ms"] == {"apply": 9.0}
    assert row["visibility_lag_ms"] is None
    assert abs(sum(row["stages_ms"].values()) - row["e2e_ms"]) < 1e-9


def test_invalid_verdict_labels_outcome():
    clk = Clock()
    j = _journal(clock=clk)
    j.block_included(2, [("txV", 0), ("txI", 11)]); clk.tick(0.001)
    j.block_applied(2)
    rows = j.rows(8)
    outcomes = {r["tx_id"]: r["outcome"] for r in rows}
    assert outcomes["txV"] == "VALID"
    assert outcomes["txI"] in ("MVCC_READ_CONFLICT", "code11")
    st = j.stats()
    assert set(st["e2e_ms"]) == set(outcomes.values())


def test_failed_endorse_completes_flow():
    """ok=False terminates the flow immediately (bounded behavior —
    no inclusion can ever come) with an endorse_error outcome."""
    clk = Clock()
    j = _journal(clock=clk)
    j.endorse_begin("txE"); clk.tick(0.006)
    j.endorse_end("txE", ok=False)
    (row,) = j.rows(8)
    assert row["outcome"] == "ENDORSE_ERROR"
    assert row["stages_ms"] == {"endorse": 6.0}
    assert j.stats()["flows_inflight"] == 0


def test_stamps_are_first_wins():
    clk = Clock()
    j = _journal(clock=clk)
    j.endorse_begin("tx"); clk.tick(0.005)
    j.endorse_begin("tx")  # duplicate: must NOT move the stamp
    clk.tick(0.005)
    j.endorse_end("tx"); clk.tick(0.0)
    j.block_included(0, [("tx", 0)])
    j.block_durable(0)
    j.block_durable(0)  # second fence: idempotent
    j.block_applied(0)
    (row,) = j.rows(8)
    assert row["stages_ms"]["endorse"] == 10.0


# -- bounded LRU ------------------------------------------------------------


def test_inflight_lru_evicts_abandoned_flows():
    clk = Clock()
    j = _journal(clock=clk, inflight=4)
    for i in range(10):
        j.endorse_begin(f"tx{i}")
    st = j.stats()
    assert st["flows_inflight"] == 4
    assert st["flows_evicted"] == 6
    # the survivors are the NEWEST four
    assert j.lookup("tx9") is not None
    assert j.lookup("tx0") is None
    reg = j.registry
    ctr = reg.counter("tx_flow_evicted_total")
    assert ctr.value() == 6


def test_lru_touch_refreshes_recency():
    clk = Clock()
    j = _journal(clock=clk, inflight=2)
    j.endorse_begin("a")
    j.endorse_begin("b")
    j.endorse_end("a")  # touches a → b becomes oldest
    j.endorse_begin("c")
    assert j.lookup("a") is not None
    assert j.lookup("b") is None


def test_block_map_bounded():
    clk = Clock()
    j = _journal(clock=clk, blocks=3)
    for n in range(6):
        j.block_included(n, [(f"t{n}", 0)])
    # blocks 0..2 fell off the bounded map: their fences are no-ops
    j.block_applied(0)
    assert all(r["tx_id"] != "t0" for r in j.rows(16))
    j.block_applied(5)
    assert any(r["tx_id"] == "t5" for r in j.rows(16))


# -- disarmed path ----------------------------------------------------------


def test_disarmed_hooks_are_none_checks():
    """Module hooks with no armed journal: no instruments, no state,
    no exceptions — the structural-zero contract."""
    assert txflow.global_journal() is None
    assert txflow.enabled() is False
    txflow.endorse_begin("x")
    txflow.endorse_end("x")
    txflow.submit_begin("x")
    txflow.broadcast_done("x")
    txflow.block_included(0, [("x", 0)])
    txflow.block_durable(0)
    txflow.block_applied(0)
    obs = txflow.sign_observer()
    obs(1.5, False)  # armed later or never — quiet either way
    assert txflow.global_journal() is None


def test_acquire_release_refcount():
    reg = Registry()
    try:
        j1 = txflow.acquire(registry=reg)
        j2 = txflow.acquire()
        assert j1 is j2 and txflow.enabled()
        txflow.release()
        assert txflow.enabled()  # one holder left
        txflow.release()
        assert not txflow.enabled()
    finally:
        txflow.configure(enabled=False)


def test_registry_untouched_until_armed():
    reg = Registry()
    assert "tx_flow_stage_seconds" not in reg.render()
    try:
        txflow.configure(registry=reg)
        assert "tx_flow_stage_seconds" in reg.render()
    finally:
        txflow.configure(enabled=False)


# -- registry surface -------------------------------------------------------


def test_histograms_and_exemplars_recorded():
    clk = Clock()
    reg = Registry()
    j = _journal(clock=clk, registry=reg)
    _full_flow(j, clk, tx="txH", num=9, channel="mych")
    text = reg.render()
    assert 'tx_flow_stage_seconds_count{stage="endorse"} 1' in text
    assert 'tx_flow_e2e_seconds_count{outcome="VALID"} 1' in text
    assert "tx_flow_visibility_lag_seconds_count 1" in text
    h = reg.histogram("tx_flow_e2e_seconds")
    rings = h.exemplar_snapshot()
    assert rings, "e2e histogram must carry trace exemplars"
    ((_, ring),) = rings.items()
    assert ring[0][1] == "mych:9"


def test_sign_event_feeds_stage_histogram_only():
    clk = Clock()
    reg = Registry()
    j = _journal(clock=clk, registry=reg)
    j.sign_event(2.5, False)
    j.sign_event(None, True)   # BUSY bounce: not a latency sample
    text = reg.render()
    assert 'tx_flow_stage_seconds_count{stage="sign_wait"} 1' in text
    assert j.stats()["sign_wait_ms"]["n"] == 1
    assert j.stats()["flows_completed"] == 0


def test_slo_feed_per_completed_flow():
    from fabric_tpu.observe import slo as slomod

    clk = Clock()
    j = _journal(clock=clk)
    engine = slomod.SloEngine(registry=Registry())
    engine.set_objectives(slomod.parse_slos(slomod.DEFAULT_COMMIT_SLOS))
    j.slo_feed = slomod.commit_feed(engine)
    _full_flow(j, clk, tx="ok")                 # 54 ms, VALID → good
    j.block_included(8, [("bad", 11)]); j.block_applied(8)
    rep = engine.report()
    by_name = {o["name"]: o for o in rep["objectives"]}
    e2e = by_name["commit_e2e"]["channels"]["commit"]
    assert e2e["events"] == 2 and e2e["bad"] == 0   # both under 1000 ms
    vld = by_name["commit_valid"]["channels"]["commit"]
    assert vld["events"] == 2 and vld["bad"] == 1   # the invalidated tx


# -- replay awareness -------------------------------------------------------


def test_replay_records_never_inherit_endorse_stamps():
    clk = Clock()
    j = _journal(clock=clk)
    # a live flow with the SAME txid is in flight endorse-side
    j.endorse_begin("txR"); clk.tick(0.050)
    j.block_included(4, [("txR", 0)], replay=True); clk.tick(0.002)
    j.block_applied(4)
    (row,) = j.rows(8)
    assert row["origin"] == "replay"
    assert row["partial"] is True
    assert "endorse" not in row["stages_ms"]
    assert row["e2e_ms"] == pytest.approx(2.0)
    assert j.stats()["flows_replayed"] == 1


def test_pipeline_replay_flag_tags_flows(tmp_path):
    """CommitPipeline(replay=True) — the ReplayDriver's pipeline —
    tags every inclusion as replay through the module hook."""
    from test_commit_pipeline import MemVersionedDB, ToyValidator, _stream

    from fabric_tpu.peer.pipeline import CommitPipeline

    reg = Registry()
    try:
        txflow.configure(registry=reg)
        state = MemVersionedDB()
        v = ToyValidator(state)

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))
            txflow.block_applied(res.block.header.number)

        with CommitPipeline(v, commit_fn, depth=1, replay=True) as pipe:
            for b in _stream(2, 3):
                pipe.submit(b)
            pipe.flush()
        rows = txflow.global_journal().rows(32)
        assert rows and all(r["origin"] == "replay" for r in rows)
    finally:
        txflow.configure(enabled=False)


# -- /txflow surface --------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, r.read()


def test_txflow_endpoint_roundtrip():
    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    clk = Clock()
    reg = Registry()
    j = _journal(clock=clk, registry=reg)
    _full_flow(j, clk, tx="txweb", num=11)
    j.endorse_begin("txlive")  # an in-flight flow for ?tx= lookup

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=reg, health=HealthRegistry(), txflow=j,
        ).start()
        loop = asyncio.get_event_loop()
        try:
            st, body = await loop.run_in_executor(
                None, _get, srv.port, "/txflow"
            )
            assert st == 200
            idx = json.loads(body)
            assert idx["enabled"] is True
            assert idx["flows_completed"] == 1
            assert idx["stages_ms"]["endorse"]["p50"] == 10.0
            assert idx["e2e_ms"]["VALID"]["n"] == 1
            assert idx["recent"][0]["tx_id"] == "txweb"
            # bounded rows: n=0 → none
            st, body = await loop.run_in_executor(
                None, _get, srv.port, "/txflow?n=0"
            )
            assert json.loads(body)["recent"] == []
            # one completed flow by tx id
            st, body = await loop.run_in_executor(
                None, _get, srv.port, "/txflow?tx=txweb"
            )
            flow = json.loads(body)["flow"]
            assert flow["outcome"] == "VALID"
            assert list(flow["milestones"])[0] == "endorse_begin"
            # an in-flight flow answers with its live snapshot
            st, body = await loop.run_in_executor(
                None, _get, srv.port, "/txflow?tx=txlive"
            )
            assert json.loads(body)["flow"]["inflight"] is True
            # unknown tx → 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                await loop.run_in_executor(
                    None, _get, srv.port, "/txflow?tx=nope"
                )
            assert ei.value.code == 404
            # bad n → 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                await loop.run_in_executor(
                    None, _get, srv.port, "/txflow?n=zap"
                )
            assert ei.value.code == 400
        finally:
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(scenario(), 30)
    )


def test_txflow_endpoint_unarmed_is_honest():
    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    assert txflow.global_journal() is None

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=Registry(), health=HealthRegistry(),
        ).start()
        loop = asyncio.get_event_loop()
        try:
            st, body = await loop.run_in_executor(
                None, _get, srv.port, "/txflow"
            )
            assert st == 200
            assert json.loads(body) == {"enabled": False}
        finally:
            await srv.stop()

    asyncio.new_event_loop().run_until_complete(
        asyncio.wait_for(scenario(), 30)
    )


# -- visibility lag against the real AsyncApplyEngine -----------------------


class _StalledDB:
    """Durable-claiming inner DB whose apply blocks on a gate — the
    decoupled committer's visibility window, made arbitrarily wide."""

    durable = True

    def __init__(self):
        from fabric_tpu.ledger.statedb import MemVersionedDB

        self._mem = MemVersionedDB()
        self.gate = threading.Event()

    def apply_updates(self, batch, sp):
        self.gate.wait(10.0)
        self._mem.apply_updates(batch, sp)

    def __getattr__(self, name):
        return getattr(self._mem, name)


def test_visibility_lag_with_stalled_applier():
    """Real AsyncApplyEngine, real applier thread, real clock: the
    durable fence stamps at ensure_synced, apply stalls ≥ 50 ms, and
    the completed flow's visibility lag covers the stall."""
    from fabric_tpu.ledger.committer import AsyncApplyEngine
    from fabric_tpu.ledger.statedb import UpdateBatch

    reg = Registry()
    inner = _StalledDB()
    fake_blocks = SimpleNamespace(ensure_synced=lambda num: None)
    eng = AsyncApplyEngine(inner, blocks=fake_blocks, queue_blocks=4)
    try:
        txflow.configure(registry=reg)
        j = txflow.global_journal()
        j.block_included(0, [("txlag", 0)])
        batch = UpdateBatch()
        batch.put("ns", "k", b"v", (0, 0))
        eng.submit(0, batch, (0, 0))
        time.sleep(0.06)
        inner.gate.set()
        assert eng.wait_applied(0, timeout=10.0)
        # completion happens on the applier thread right before
        # wait_applied unblocks — poll briefly for the row
        for _ in range(100):
            rows = j.rows(4)
            if rows:
                break
            time.sleep(0.005)
        (row,) = rows
        assert row["tx_id"] == "txlag"
        assert row["visibility_lag_ms"] >= 50.0
        assert row["stages_ms"]["apply"] >= 50.0
        assert eng.stats()["applied_num"] == 0
    finally:
        txflow.configure(enabled=False)
        eng.close()


# -- end-to-end through the real CommitPipeline + KVLedger ------------------


def test_e2e_flow_through_real_pipeline_and_kvledger(tmp_path):
    """The full seam, crypto-free: gateway-shaped endorse/submit
    stamps via the module hooks, toy blocks through the REAL
    CommitPipeline (inclusion stamped in _run_commit), the REAL
    serial KVLedger commit (applied stamped in commit_block) — every
    milestone lands, in order, on the journal's one clock."""
    from test_commit_pipeline import MemVersionedDB, ToyValidator, _stream

    from fabric_tpu.ledger.kvledger import KVLedger
    from fabric_tpu.peer.pipeline import CommitPipeline

    reg = Registry()
    blocks = _stream(3, 4)
    txids = [json.loads(bytes(d))["id"]
             for b in blocks for d in b.data.data]
    try:
        txflow.configure(registry=reg)
        # gateway-side stamps for every tx of the stream
        for tx in txids:
            txflow.endorse_begin(tx)
            txflow.endorse_end(tx)
            txflow.submit_begin(tx)
            txflow.broadcast_done(tx)
        state = MemVersionedDB()
        v = ToyValidator(state)
        lg = KVLedger(str(tmp_path / "ledger"), state_db=state,
                      async_commit=False)

        def commit_fn(res):
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids)

        with CommitPipeline(v, commit_fn, depth=2,
                            channel="toy") as pipe:
            for b in blocks:
                pipe.submit(b)
            pipe.flush()
        lg.close()

        j = txflow.global_journal()
        rows = j.rows(64)
        by_tx = {r["tx_id"]: r for r in rows}
        assert set(by_tx) == set(txids)
        order = ["endorse_begin", "endorse_end", "submit",
                 "broadcast", "included", "applied"]
        for r in rows:
            assert r["partial"] is False
            assert r["channel"] == "toy"
            ms = r["milestones"]
            present = [m for m in order if m in ms]
            assert present == order
            assert all(ms[a] <= ms[b]
                       for a, b in zip(present, present[1:]))
            # published values are rounded to 4 decimals, so the
            # telescoping identity holds to rounding tolerance here
            assert abs(sum(r["stages_ms"].values()) - r["e2e_ms"]) < 1e-3
        # the dependent stream's stale-read lane invalidates txs —
        # verdicts ride the inclusion stamp
        outcomes = {r["outcome"] for r in rows}
        assert "VALID" in outcomes and len(outcomes) >= 2
        assert j.stats()["flows_completed"] == len(txids)
    finally:
        txflow.configure(enabled=False)

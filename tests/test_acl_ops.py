"""Tests for ACL enforcement, orderer broadcast throttling, and the
Snapshot RPC — the operator-surface features (reference: core/aclmgmt,
orderer/common/throttle, core/ledger/snapshotgrpc)."""

import asyncio
import json

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.node import PeerChannel
from fabric_tpu.tools import configtxgen as cg

CHANNEL = "aclchan"
CC = "aclcc"


@pytest.fixture(scope="module")
def material():
    orgs = [
        cryptogen.generate_org(f"Org{i}MSP", f"org{i}.example.com", peers=1, users=1)
        for i in (1, 2)
    ]
    # Org2 is NOT an application org → its members are not Writers
    profile = cg.Profile(
        CHANNEL, application_orgs=[cg.OrgProfile(orgs[0].msp_id, orgs[0].msp())]
    )
    return {
        "orgs": orgs,
        "genesis": cg.genesis_block(profile),
        "writer": cryptogen.signing_identity(orgs[0], "User1@org1.example.com"),
        "outsider": cryptogen.signing_identity(orgs[1], "User1@org2.example.com"),
        "peer_signer": cryptogen.signing_identity(orgs[0], "peer0.org1.example.com"),
    }


def test_acl_propose_writers_gate(material, tmp_path):
    """peer/Propose maps to /Channel/Application/Writers: a member of a
    non-channel org is rejected with 403 before simulation."""
    from fabric_tpu.crypto.msp import MSPManager
    from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract

    ch = PeerChannel(
        CHANNEL, str(tmp_path / "p"), genesis_block=material["genesis"]
    )
    # the endorser-side MSP manager knows BOTH orgs (the outsider has a
    # valid identity — only the ACL can reject it)
    mgr = MSPManager({
        o.msp_id: o.msp() for o in material["orgs"]
    })
    rt = ChaincodeRuntime()
    rt.register(CC, KVContract())
    endorser = ch.make_endorser(mgr, material["peer_signer"], rt)

    ok_prop, _, _ = txa.create_signed_proposal(
        material["writer"], CHANNEL, CC, [b"put", b"k", b"v"]
    )
    res = endorser.process_proposal(ok_prop)
    assert res.response.response.status == 200, res.response.response.message

    bad_prop, _, _ = txa.create_signed_proposal(
        material["outsider"], CHANNEL, CC, [b"put", b"k", b"v"]
    )
    res = endorser.process_proposal(bad_prop)
    assert res.response.response.status == 403
    ch.stop()


def test_snapshot_rpc(material, tmp_path):
    """The Snapshot RPC exports a verifiable snapshot of a channel."""
    import urllib.request

    from fabric_tpu.comm.rpc import RpcClient
    from fabric_tpu.crypto.msp import MSPManager
    from fabric_tpu.ledger import snapshot as snap
    from fabric_tpu.peer.node import PeerNode

    async def scenario():
        mgr = MSPManager({material["orgs"][0].msp_id: material["orgs"][0].msp()})
        node = PeerNode(
            "p0", str(tmp_path / "node"), mgr, material["peer_signer"]
        )
        await node.start(operations_port=0)
        node.join_channel(CHANNEL, genesis_block=material["genesis"])
        try:
            cli = RpcClient("127.0.0.1", node.port)
            await cli.connect()
            out_dir = str(tmp_path / "snap")
            raw = await cli.unary("Snapshot", json.dumps(
                {"channel": CHANNEL, "out_dir": out_dir}
            ).encode(), timeout=60)
            res = json.loads(raw)
            assert res["status"] == 200, res
            assert res["metadata"]["last_block_number"] == 0
            assert snap.verify_snapshot(out_dir)
            # unknown channel → 404
            raw = await cli.unary("Snapshot", json.dumps(
                {"channel": "nope", "out_dir": out_dir}
            ).encode())
            assert json.loads(raw)["status"] == 404
            await cli.close()
            # the operations server is live alongside
            st = await asyncio.get_event_loop().run_in_executor(
                None,
                lambda: urllib.request.urlopen(
                    f"http://127.0.0.1:{node.operations.port}/healthz", timeout=5
                ).status,
            )
            assert st == 200
        finally:
            await node.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 90))
    finally:
        loop.close()


def test_broadcast_throttle(tmp_path):
    """Token-bucket rate limit: overflow traffic gets 429; sub-1/s
    rates still admit the first message."""
    from fabric_tpu.ordering.blockcutter import BatchConfig
    from fabric_tpu.ordering.node import OrdererNode

    async def scenario():
        n = OrdererNode(
            "o0", str(tmp_path / "o0"), {},
            batch_config=BatchConfig(max_message_count=100, batch_timeout_s=5),
        )
        await n.start()
        n.cluster["o0"] = ("127.0.0.1", n.port)
        n.join_channel("tchan")
        n.broadcast_rate = 2.0
        try:
            hdr = json.dumps({"channel": "tchan"}).encode()
            req = len(hdr).to_bytes(4, "big") + hdr + b"env"
            codes = []
            for _ in range(6):
                # drive the handler directly; the limiter acts before
                # consensus sees the message
                codes.append(json.loads(await n._on_broadcast(req))["status"])
            assert codes.count(429) >= 3, codes
            assert codes[0] != 429

            n.broadcast_rate = 0.5  # sub-1/s must still pass initially
            n._throttle.clear()
            first = json.loads(await n._on_broadcast(req))["status"]
            assert first != 429
            second = json.loads(await n._on_broadcast(req))["status"]
            assert second == 429
        finally:
            await n.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 60))
    finally:
        loop.close()

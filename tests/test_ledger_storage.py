"""Ledger storage tests: statedb backends, block store recovery,
history, kvledger commit-hash chain + crash recovery (scenarios
modeled on the reference's blkstorage/kvledger test coverage)."""

import os

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import (
    MemVersionedDB,
    SqliteVersionedDB,
    UpdateBatch,
)
from fabric_tpu.protos import common_pb2


@pytest.fixture(params=["mem", "sqlite"])
def db(request, tmp_path):
    if request.param == "mem":
        d = MemVersionedDB()
    else:
        d = SqliteVersionedDB(str(tmp_path / "state.db"))
    d.open()
    yield d
    d.close()


def test_statedb_basic(db):
    b = UpdateBatch()
    b.put("ns1", "k1", b"v1", (1, 0))
    b.put("ns1", "k2", b"v2", (1, 1))
    b.put("ns2", "k1", b"other", (1, 2))
    db.apply_updates(b, (1, 0))
    assert db.get_state("ns1", "k1").value == b"v1"
    assert db.get_version("ns1", "k2") == (1, 1)
    assert db.get_state("ns1", "zz") is None
    assert db.savepoint() == (1, 0)
    vers = db.get_versions_bulk([("ns1", "k1"), ("ns1", "nope"), ("ns2", "k1")])
    assert vers == {("ns1", "k1"): (1, 0), ("ns2", "k1"): (1, 2)}
    # delete
    b2 = UpdateBatch()
    b2.delete("ns1", "k1", (2, 0))
    db.apply_updates(b2, (2, 0))
    assert db.get_state("ns1", "k1") is None


def test_statedb_range_and_rich_query(db):
    b = UpdateBatch()
    for i in range(10):
        b.put("ns", f"key{i}", b'{"color":"%s","size":%d}' % (b"red" if i % 2 else b"blue", i), (1, i))
    db.apply_updates(b, (1, 0))
    got = [k for k, _ in db.get_state_range("ns", "key2", "key6")]
    assert got == ["key2", "key3", "key4", "key5"]
    got = [k for k, _ in db.get_state_range("ns", "key8", "")]
    assert got == ["key8", "key9"]
    got = [k for k, _ in db.get_state_range("ns", "key0", "key9", limit=3)]
    assert got == ["key0", "key1", "key2"]
    rich = [k for k, _ in db.execute_query("ns", {"selector": {"color": "red"}})]
    assert rich == [f"key{i}" for i in range(10) if i % 2]


def _block(num, prev, payloads, channel="ch"):
    blk = pu.new_block(num, prev)
    for i, p in enumerate(payloads):
        ch = pu.make_channel_header(
            common_pb2.HeaderType.ENDORSER_TRANSACTION, channel, tx_id=f"tx{num}-{i}"
        )
        sh = pu.make_signature_header(b"creator", b"n")
        payload = pu.make_payload(ch, sh, p)
        env = common_pb2.Envelope(payload=payload.SerializeToString(), signature=b"s")
        blk.data.data.append(env.SerializeToString())
    return pu.finalize_block(blk)


def test_blockstore_append_get_and_txids(tmp_path):
    bs = BlockStore(str(tmp_path / "chains"))
    assert bs.height == 0
    prev = b""
    for n in range(5):
        blk = _block(n, prev, [b"a", b"b"])
        bs.add_block(blk)
        prev = pu.block_header_hash(blk.header)
    assert bs.height == 5
    b3 = bs.get_block(3)
    assert b3.header.number == 3
    assert bs.get_block_by_hash(pu.block_header_hash(b3.header)).header.number == 3
    assert bs.get_tx_loc("tx3-1") == (3, 1, 254)
    assert bs.tx_exists("tx0-0") and not bs.tx_exists("nope")
    with pytest.raises(ValueError):
        bs.add_block(_block(9, b"", [b"x"]))
    bs.close()


def test_blockstore_reopen_and_torn_write_recovery(tmp_path):
    path = str(tmp_path / "chains")
    bs = BlockStore(path)
    prev = b""
    for n in range(3):
        blk = _block(n, prev, [b"p"])
        bs.add_block(blk)
        prev = pu.block_header_hash(blk.header)
    bs.close()
    # simulate crash mid-append: torn record at the tail
    seg = os.path.join(path, "blocks_000000.bin")
    with open(seg, "ab") as f:
        f.write(b"\xff\xff\x00\x00garbage")
    bs2 = BlockStore(path)
    assert bs2.height == 3
    assert bs2.get_block(2).header.number == 2
    # still appendable after recovery
    bs2.add_block(_block(3, prev, [b"q"]))
    assert bs2.height == 4
    bs2.close()


def test_blockstore_group_commit_index_clamp(tmp_path):
    """Group commit lets the sqlite index run durably ahead of an
    unsynced segment tail; after a crash truncates the tail, _recover
    must clamp the index BACK to the files (the files are the source
    of truth in both directions)."""
    path = str(tmp_path / "chains")
    bs = BlockStore(path, group_commit=8)
    prev = b""
    offs = []
    for n in range(5):
        blk = _block(n, prev, [b"p%d" % n])
        offs.append(os.path.getsize(os.path.join(path, "blocks_000000.bin"))
                    if n else 0)
        bs.add_block(blk)
        prev = pu.block_header_hash(blk.header)
    # crash inside the group window: blocks 3-4's bytes never hit disk
    bs._fh.close()
    bs._idx.close()
    seg = os.path.join(path, "blocks_000000.bin")
    with open(seg, "r+b") as f:
        f.truncate(offs[3])
    bs2 = BlockStore(path)
    assert bs2.height == 3  # index clamped to the surviving files
    assert bs2.get_block(2) is not None
    assert bs2.get_block(3) is None
    assert bs2.get_tx_loc("tx3-0") is None  # txid rows clamped too
    # the chain continues from the clamped tip
    prev3 = pu.block_header_hash(bs2.get_block(2).header)
    bs2.add_block(_block(3, prev3, [b"re-delivered"]))
    assert bs2.height == 4
    reblk = bs2.get_block(3)
    assert reblk.header.number == 3
    assert b"re-delivered" in reblk.data.data[0]
    bs2.close()


def test_blockstore_index_rebuild(tmp_path):
    path = str(tmp_path / "chains")
    bs = BlockStore(path)
    prev = b""
    for n in range(3):
        blk = _block(n, prev, [b"p"])
        bs.add_block(blk)
        prev = pu.block_header_hash(blk.header)
    bs.close()
    os.remove(os.path.join(path, "index.db"))
    bs2 = BlockStore(path)
    assert bs2.height == 3
    assert bs2.get_tx_loc("tx1-0") is not None
    bs2.close()


def _commit_n(ledger, n, start=0, prev=None):
    prev = prev if prev is not None else b""
    for num in range(start, start + n):
        blk = _block(num, prev, [b"data%d" % num])
        batch = UpdateBatch()
        batch.put("ns", f"k{num}", b"v%d" % num, (num, 0))
        ledger.commit_block(blk, bytes([0]), batch, [("ns", f"k{num}", 0)])
        prev = pu.block_header_hash(blk.header)
    return prev


def test_kvledger_commit_and_hash_chain(tmp_path):
    led = KVLedger(str(tmp_path / "ledger"))
    _commit_n(led, 3)
    assert led.height == 3
    assert led.state.get_state("ns", "k1").value == b"v1"
    assert list(led.history.get_history_for_key("ns", "k2")) == [(2, 0)]
    h1 = led.commit_hash
    assert h1 and len(h1) == 32
    blk2 = led.blocks.get_block(2)
    assert blk2.metadata.metadata[common_pb2.BlockMetadataIndex.COMMIT_HASH] == h1
    led.close()
    # reopen: commit hash reloaded from last block
    led2 = KVLedger(str(tmp_path / "ledger"))
    assert led2.commit_hash == h1
    led2.close()


def test_kvledger_crash_recovery_replays_state(tmp_path):
    led = KVLedger(str(tmp_path / "ledger"))
    prev = _commit_n(led, 2)
    # crash: block 2 reaches the block store but not the state db
    blk = _block(2, prev, [b"late"])
    pu.set_tx_filter(blk, bytes([0]))
    blk.metadata.metadata[common_pb2.BlockMetadataIndex.COMMIT_HASH] = b"x" * 32
    led.blocks.add_block(blk)
    led.close()

    led2 = KVLedger(str(tmp_path / "ledger"))
    assert led2.height == 3
    assert led2.state.savepoint() == (1, 0)  # behind

    def replayer(block):
        batch = UpdateBatch()
        num = block.header.number
        batch.put("ns", f"k{num}", b"replayed", (num, 0))
        return bytes([0]), batch, [("ns", f"k{num}", 0)]

    replayed = led2.recover(replayer)
    assert replayed == 1
    assert led2.state.get_state("ns", "k2").value == b"replayed"
    assert led2.state.savepoint() == (2, 0)
    led2.close()


def test_pvtdata_store_roundtrip_and_expiry(tmp_path):
    led = KVLedger(str(tmp_path / "ledger"))
    prev = b""
    blk = _block(0, prev, [b"x"])
    batch = UpdateBatch()
    led.commit_block(
        blk, bytes([0]), batch, None,
        pvt_data={(0, "ns", "collA"): (b"pvt-rwset", 5)},
    )
    assert led.pvtdata.get_pvt_data(0) == {(0, "ns", "collA"): b"pvt-rwset"}
    assert led.pvtdata.purge_expired(4) == []
    purged = led.pvtdata.purge_expired(5)
    assert [r[:4] for r in purged] == [(0, 0, "ns", "collA")]
    assert purged[0][4] == b"pvt-rwset"
    assert led.pvtdata.get_pvt_data(0) == {}
    led.close()

"""MXU-first ECDSA kernel (ops.digits + ops.p256v2) tests.

Three layers of oracle:
1. digit field core vs Python ints (adversarial magnitudes, long
   chains, the certified bound schedule);
2. RCB complete point formulas vs crypto.ec_ref point ops, including
   every degenerate case (doubling lane, inverse lane, infinity);
3. full verify_batch vs the reference accept set (ec_ref /
   bccsp/sw/ecdsa.go:41-58 semantics: low-S, ranges, on-curve).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import digits as dg
from fabric_tpu.ops import p256v2 as v2


def _to_digits(vals):
    return jnp.asarray(dg.ints_to_digits(vals))


def _from_digits_mod(arr, m):
    return [dg.digits_to_int(r) % m for r in np.asarray(arr)]


# ---------------------------------------------------------------------------
# field core


def test_bound_certificates():
    """The interval certificates the kernel relies on must hold."""
    side = v2._MAX_SIDE
    assert dg.SETTLED_MAX * 6 <= side
    assert v2.MODP.bound_check(side, side) <= dg.SETTLED_MAX
    assert v2.MODN.bound_check(side, side) <= dg.SETTLED_MAX


@pytest.mark.parametrize("mod", [v2.MODP, v2.MODN], ids=["p", "n"])
def test_mul_chain_exact(mod, rng):
    """300 chained muls bit-exact vs Python ints; digits stay within
    the certified settled bound."""
    B = 8
    m = mod.m
    a_int = [int.from_bytes(rng.bytes(32), "big") % m for _ in range(B)]
    b_int = [m - 1, 1, 0, 2] + [
        int.from_bytes(rng.bytes(32), "big") % m for _ in range(B - 4)
    ]
    mul = jax.jit(mod.mul)
    a = _to_digits(a_int)
    b = _to_digits(b_int)
    want = list(a_int)
    maxd = 0
    for it in range(300):
        a = mul(a, b)
        for lane in range(B):
            want[lane] = want[lane] * b_int[lane] % m
        if it % 97 == 0 or it == 299:
            maxd = max(maxd, int(np.abs(np.asarray(a)).max()))
            assert _from_digits_mod(a, m) == want, it
    assert maxd <= dg.SETTLED_MAX


@pytest.mark.parametrize("mod", [v2.MODP, v2.MODN], ids=["p", "n"])
def test_mul_adversarial_magnitudes(mod):
    """Inputs at the pairing limit, mixed signs — f32 exactness and
    settle bounds must hold at the extremes, not just on average."""
    side = v2._MAX_SIDE
    m = mod.m
    patterns = [
        np.full(dg.K, side, np.int32),
        np.full(dg.K, -side, np.int32),
        np.array([side if i % 2 else -side for i in range(dg.K)], np.int32),
        np.array([(-1) ** i * (side - i) for i in range(dg.K)], np.int32),
    ]
    a = jnp.asarray(np.stack(patterns))
    b = jnp.asarray(np.stack(patterns[::-1]))
    out = jax.jit(mod.mul)(a, b)
    assert int(np.abs(np.asarray(out)).max()) <= dg.SETTLED_MAX
    for lane in range(len(patterns)):
        av = dg.digits_to_int(patterns[lane])
        bv = dg.digits_to_int(patterns[::-1][lane])
        assert _from_digits_mod(out, m)[lane] == (av * bv) % m


@pytest.mark.parametrize("mod", [v2.MODP, v2.MODN], ids=["p", "n"])
def test_canonical(mod, rng):
    m = mod.m
    vals = [0, 1, m - 1, m, m + 1, 2 * m + 5]
    vals += [int.from_bytes(rng.bytes(33), "big") % (1 << 258) for _ in range(6)]
    t = _to_digits([v % (1 << 258) for v in vals])
    got = _from_digits_mod(jax.jit(mod.canonical)(t), 1 << 300)
    assert got == [v % m for v in vals]
    # negative representations (from subtraction chains)
    neg = jnp.asarray(dg.ints_to_digits([5])) - jnp.asarray(dg.ints_to_digits([7]))
    got = _from_digits_mod(jax.jit(mod.canonical)(neg), 1 << 300)
    assert got == [(5 - 7) % m]


# ---------------------------------------------------------------------------
# point ops


def _rand_pt(rng):
    k = int.from_bytes(rng.bytes(32), "big") % ec_ref.N or 1
    return ec_ref.pt_mul(k, (ec_ref.GX, ec_ref.GY))


def _proj(pts):
    xs = _to_digits([p[0] if p else 0 for p in pts])
    ys = _to_digits([p[1] if p else 1 for p in pts])
    zs = _to_digits([0 if p is None else 1 for p in pts])
    return xs, ys, zs


def _fv3(arrs, bound=63):
    return tuple(v2.FV(a, bound, v2.MODP) for a in arrs)


def _affine(arrs):
    X = _from_digits_mod(v2.MODP.canonical(arrs[0]), ec_ref.P)
    Y = _from_digits_mod(v2.MODP.canonical(arrs[1]), ec_ref.P)
    Z = _from_digits_mod(v2.MODP.canonical(arrs[2]), ec_ref.P)
    out = []
    for x, y, z in zip(X, Y, Z):
        if z == 0:
            out.append(None)
        else:
            zi = pow(z, -1, ec_ref.P)
            out.append((x * zi % ec_ref.P, y * zi % ec_ref.P))
    return out


def test_rcb_complete_add_and_double(rng):
    pts1 = [_rand_pt(rng) for _ in range(5)]
    pts2 = [_rand_pt(rng) for _ in range(5)]
    pts1[1] = pts2[1]                                   # doubling lane
    pts2[2] = (pts1[2][0], (-pts1[2][1]) % ec_ref.P)    # inverse → ∞
    pts2[3] = None                                      # ∞ operand
    pts1[4] = None

    def run_add(a, b):
        b_fv = v2._const_fv(ec_ref.B, a[0], v2.MODP)
        return [t.arr for t in v2.pt_add(_fv3(a), _fv3(b), b_fv)]

    got = _affine(jax.jit(run_add)(_proj(pts1), _proj(pts2)))
    assert got == [ec_ref.pt_add(a, b) for a, b in zip(pts1, pts2)]

    def run_dbl(a):
        b_fv = v2._const_fv(ec_ref.B, a[0], v2.MODP)
        return [t.arr for t in v2.pt_double(_fv3(a), b_fv)]

    got = _affine(jax.jit(run_dbl)(_proj(pts1)))
    assert got == [ec_ref.pt_double(a) for a in pts1]


def test_rcb_mixed_add(rng):
    pts1 = [_rand_pt(rng) for _ in range(4)]
    pts2 = [_rand_pt(rng) for _ in range(4)]
    pts1[2] = None          # ∞ + affine
    pts1[3] = pts2[3]       # doubling via mixed

    def run(a, x2, y2):
        b_fv = v2._const_fv(ec_ref.B, x2, v2.MODP)
        return [
            t.arr for t in v2.pt_add_mixed(
                _fv3(a), v2.FV(x2, 63, v2.MODP), v2.FV(y2, 63, v2.MODP), b_fv
            )
        ]

    got = _affine(jax.jit(run)(
        _proj(pts1),
        _to_digits([p[0] for p in pts2]),
        _to_digits([p[1] for p in pts2]),
    ))
    assert got == [ec_ref.pt_add(a, b) for a, b in zip(pts1, pts2)]


# ---------------------------------------------------------------------------
# full verify


@pytest.fixture(scope="module")
def sigs(rng):
    keys = [ec_ref.SigningKey.generate() for _ in range(3)]
    return keys


def test_verify_accepts_valid_and_rejects_adversarial(sigs, rng):
    keys = sigs
    items, want = [], []
    for i in range(12):
        k = keys[i % 3]
        e = ec_ref.digest_int(b"payload-%d" % i)
        r, s = k.sign_digest(e)
        items.append((e, r, s, *k.public))
        want.append(True)
    e = ec_ref.digest_int(b"hs")
    r, s = keys[0].sign_digest(e)
    adversarial = [
        (ec_ref.digest_int(b"other"), r, s, *keys[0].public),  # wrong digest
        (e, r, ec_ref.N - s, *keys[0].public),                 # high-S
        (e, 0, s, *keys[0].public),                            # r = 0
        (e, r, 0, *keys[0].public),                            # s = 0
        (e, ec_ref.N, s, *keys[0].public),                     # r = n
        (e, s, r, *keys[0].public),                            # swapped
        (e, r, s, keys[0].public[0] + 1, keys[0].public[1]),   # off-curve Q
        (e, r, s, *keys[1].public),                            # wrong key
        (e, r, s, 0, 0),                                       # Q = ∞ encoding
    ]
    items += adversarial
    want += [False] * len(adversarial)
    got = v2.verify_host(items)
    assert got == want
    # oracle agreement on every case
    for (ei, ri, si, xi, yi), g in zip(items, got):
        assert g == ec_ref.verify_digest((xi, yi), ei, ri, si)


def test_verify_matches_oracle_randomized(sigs, rng):
    """Random mutations of valid signatures — kernel accept set must
    equal the oracle accept set exactly."""
    keys = sigs
    items = []
    for i in range(48):
        k = keys[i % 3]
        e = ec_ref.digest_int(rng.bytes(16))
        r, s = k.sign_digest(e)
        kind = i % 6
        if kind == 1:
            r = (r + int(rng.integers(0, 3))) % ec_ref.N
        elif kind == 2:
            s = (s + int(rng.integers(0, 3))) % ec_ref.N
        elif kind == 3:
            e = (e + int(rng.integers(0, 2))) % (1 << 256)
        items.append((e, r, s, *k.public))
    got = v2.verify_host(items)
    want = [ec_ref.verify_digest((x, y), e, r, s) for (e, r, s, x, y) in items]
    assert got == want
    assert any(want) and not all(want)

"""Operations surface tests: /metrics (Prometheus text), /healthz
aggregation, /logspec live level changes, /version — and the commit
path's metric emission (reference: core/operations/system.go:89-209,
kv_ledger.go:712 commit breakdown)."""

import asyncio
import json
import logging
import urllib.request

import pytest

from fabric_tpu.ops_metrics import Registry
from fabric_tpu.opsserver import HealthRegistry, OperationsServer, apply_logspec


def run(coro, timeout=30):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read()


def test_registry_render():
    reg = Registry()
    c = reg.counter("endorse_total", "endorsements")
    c.add(3, channel="ch1")
    c.add(1, channel="ch2")
    g = reg.gauge("height")
    g.set(7, channel="ch1")
    h = reg.histogram("commit_seconds")
    h.observe(0.004, channel="ch1")
    h.observe(2.0, channel="ch1")
    text = reg.render()
    assert 'endorse_total{channel="ch1"} 3.0' in text
    assert '# TYPE endorse_total counter' in text
    assert 'height{channel="ch1"} 7.0' in text
    assert 'commit_seconds_count{channel="ch1"} 2' in text
    assert 'commit_seconds_bucket{channel="ch1",le="0.005"} 1' in text
    assert 'commit_seconds_bucket{channel="ch1",le="+Inf"} 2' in text


def test_ops_endpoints():
    async def scenario():
        reg = Registry()
        reg.counter("x_total").add(5)
        health = HealthRegistry()
        health.register("good", lambda: None)
        srv = await OperationsServer(port=0, registry=reg, health=health).start()
        loop = asyncio.get_event_loop()
        st, body = await loop.run_in_executor(None, _get, srv.port, "/metrics")
        assert st == 200 and b"x_total 5.0" in body
        st, body = await loop.run_in_executor(None, _get, srv.port, "/healthz")
        assert st == 200 and json.loads(body)["status"] == "OK"
        st, body = await loop.run_in_executor(None, _get, srv.port, "/version")
        assert st == 200 and "fabric-tpu" in json.loads(body)["Version"]

        # a failing checker flips /healthz to 503
        health.register("bad", lambda: "on fire")
        try:
            await loop.run_in_executor(None, _get, srv.port, "/healthz")
            raise AssertionError("expected 503")
        except Exception as e:
            assert "503" in str(e)
        await srv.stop()

    run(scenario())


def test_logspec():
    apply_logspec("warning:fabric_tpu.peer=debug")
    assert logging.getLogger("fabric_tpu").level == logging.WARNING
    assert logging.getLogger("fabric_tpu.peer").level == logging.DEBUG
    apply_logspec("error")
    assert logging.getLogger("fabric_tpu").level == logging.ERROR
    logging.getLogger("fabric_tpu.peer").setLevel(logging.NOTSET)
    logging.getLogger("fabric_tpu").setLevel(logging.NOTSET)


def test_debug_profiling_surface():
    """Live profiling endpoints (peer.profile pprof analog,
    start.go:861-876): thread-stack dumps and a timed cProfile
    window."""
    async def scenario():
        from fabric_tpu.opsserver import HealthRegistry, OperationsServer

        srv = OperationsServer(health=HealthRegistry())
        await srv.start()
        try:
            import urllib.request

            loop = asyncio.get_event_loop()

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=10
                ) as r:
                    return r.status, r.read()

            status, body = await loop.run_in_executor(
                None, get, "/debug/stacks"
            )
            assert status == 200
            assert b"--- thread" in body

            status, body = await loop.run_in_executor(
                None, get, "/debug/profile?seconds=0.2"
            )
            assert status == 200
            assert b"wall-clock samples" in body
            # the sampler must see OTHER threads, not just the event
            # loop — this request itself runs in an executor worker
            # blocked in urlopen, so a worker thread must appear
            assert b"ThreadPoolExecutor" in body or b"asyncio" in body
        finally:
            await srv.stop()

    run(scenario())

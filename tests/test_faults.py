"""Chaos-hardening battery (fabric_tpu.faults + peer.degrade +
utils.backoff): fault-plan mechanics, the device-lane degradation
state machine, and the two acceptance differentials —

* a seeded FaultPlan (device faults + a host-pool worker fault + one
  injected mid-stream disconnect + a commit fault) driven through a
  depth-2 CommitPipeline commits the EXACT block/tx accept-set of a
  fault-free serial run (crypto-free toy validator);
* a kill-mid-fsync child process leaves a ledger that reopens at a
  consistent height, replays state, and keeps accepting blocks.
"""

import json
import os
import subprocess
import sys
import textwrap
from dataclasses import dataclass

import pytest

from fabric_tpu import faults
from fabric_tpu import protoutil as pu
from fabric_tpu.faults import FaultPlan, FaultSpecError, InjectedFault
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer.degrade import DeviceLaneGuard
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.utils.backoff import Backoff

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends with no armed global plan."""
    faults.reset()
    yield
    faults.reset()


# -- FaultPlan mechanics ----------------------------------------------------


class TestFaultPlan:
    def test_parse_errors_name_the_problem(self):
        for bad in ("point-only", "p:unknownkind", "p:raise:p=2",
                    "p:raise:bogus=1", "p:latency", "p:raise:n=x"):
            with pytest.raises(FaultSpecError):
                FaultPlan(bad)

    def test_raise_budget_and_after(self):
        p = FaultPlan("x:raise:n=2:after=1")
        p.fire("x")  # after=1: first arrival passes
        with pytest.raises(InjectedFault):
            p.fire("x")
        with pytest.raises(InjectedFault):
            p.fire("x")
        p.fire("x")  # budget n=2 exhausted
        assert p.fired("x") == 2
        s = p.stats()["x"][0]
        assert s == {"kind": "raise", "arrivals": 4, "fired": 2}

    def test_unmatched_points_never_trigger(self):
        p = FaultPlan("x:raise")
        p.fire("y")  # no rule for y
        assert p.fired() == 0

    def test_probability_is_seed_deterministic(self):
        def run(seed):
            p = FaultPlan("x:raise:p=0.5", seed=seed)
            hits = []
            for _ in range(32):
                try:
                    p.fire("x")
                    hits.append(0)
                except InjectedFault:
                    hits.append(1)
            return hits

        a, b = run(7), run(7)
        assert a == b
        assert 0 < sum(a) < 32  # actually probabilistic
        assert run(8) != a      # and seed-sensitive

    def test_probability_replay_survives_other_points_interleaving(self):
        """Each rule draws from its OWN seeded RNG: arrivals at OTHER
        points (whose thread interleaving varies run to run) must not
        shift which of THIS point's arrivals fire."""
        def run(noise_every):
            p = FaultPlan("x:raise:p=0.5;y:raise:p=0.5", seed=7)
            hits = []
            for i in range(32):
                if noise_every and i % noise_every == 0:
                    try:
                        p.fire("y")  # a differently-interleaved thread
                    except InjectedFault:
                        pass
                try:
                    p.fire("x")
                    hits.append(0)
                except InjectedFault:
                    hits.append(1)
            return hits

        assert run(0) == run(1) == run(3)

    def test_latency_sleeps(self):
        import time

        p = FaultPlan("x:latency:ms=30:n=1")
        t0 = time.perf_counter()
        p.fire("x")
        assert time.perf_counter() - t0 >= 0.025
        p.fire("x")  # budget spent: no sleep

    def test_afire_latency_keeps_the_event_loop_live(self):
        """The async hook must asyncio.sleep a latency fault so other
        tasks keep running, and still raise the raising kinds."""
        import asyncio

        faults.configure("d.read:latency:ms=60:n=1;d.cut:disconnect")
        ticks = []

        async def ticker():
            for _ in range(8):
                ticks.append(1)
                await asyncio.sleep(0.005)

        async def scenario():
            t = asyncio.ensure_future(ticker())
            await faults.afire("d.read")   # 60ms latency, loop live
            with pytest.raises(ConnectionResetError):
                await faults.afire("d.cut")
            await t

        asyncio.new_event_loop().run_until_complete(
            asyncio.wait_for(scenario(), 10))
        # the ticker made progress DURING the injected latency — a
        # blocking time.sleep would have frozen it at 1 tick
        assert len(ticks) == 8
        assert faults.plan().fired("d.read") == 1

    def test_disconnect_and_truncate_raise_connection_errors(self):
        p = FaultPlan("a:disconnect;b:truncate")
        with pytest.raises(ConnectionResetError):
            p.fire("a")
        with pytest.raises(ConnectionResetError, match="truncated"):
            p.fire("b")

    def test_shield_suppresses_recovery_path(self):
        faults.configure("x:raise")
        with pytest.raises(InjectedFault):
            faults.fire("x")
        with faults.shield():
            faults.fire("x")  # recovery path: no trigger
            with faults.shield():
                faults.fire("x")  # nesting
            faults.fire("x")
        with pytest.raises(InjectedFault):
            faults.fire("x")  # shield released

    def test_global_configure_and_reset(self):
        assert faults.plan() is None
        faults.fire("anything")  # no plan: free no-op
        p = faults.configure("x:raise:n=1")
        assert faults.plan() is p
        with pytest.raises(InjectedFault):
            faults.fire("x")
        faults.reset()
        assert faults.plan() is None

    def test_configure_defaults_seed_from_env(self, monkeypatch):
        """A peer re-arming the plan from nodeconfig ``faults`` must
        keep the FABTPU_FAULTS_SEED determinism, not drop it."""
        monkeypatch.setenv(faults.ENV_SEED, "41")
        p = faults.configure("x:raise:p=0.5")
        assert p.seed == 41
        monkeypatch.delenv(faults.ENV_SEED)
        assert faults.configure("x:raise").seed is None
        assert faults.configure("x:raise", seed=9).seed == 9

    def test_env_spec_arms_child_processes(self, tmp_path):
        script = textwrap.dedent(f"""\
            import sys
            sys.path.insert(0, {REPO!r})
            from fabric_tpu import faults
            try:
                faults.fire("child.point")
                print("NOFIRE")
            except faults.InjectedFault:
                print("FIRED")
        """)
        path = tmp_path / "child.py"
        path.write_text(script)
        env = dict(os.environ, FABTPU_FAULTS="child.point:raise",
                   JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, str(path)], env=env, capture_output=True,
            text=True, timeout=60,
        )
        assert "FIRED" in out.stdout, (out.stdout, out.stderr)

    def test_injected_counter_rides_registry(self):
        from fabric_tpu.ops_metrics import global_registry

        ctr = global_registry().counter("faults_injected_total")
        before = ctr.value(point="m.count", kind="raise")
        faults.configure("m.count:raise:n=2")
        for _ in range(3):
            try:
                faults.fire("m.count")
            except InjectedFault:
                pass
        assert ctr.value(point="m.count", kind="raise") == before + 2


# -- Backoff ---------------------------------------------------------------


class TestBackoff:
    def test_growth_cap_and_jitter_bounds(self):
        import random

        bo = Backoff(base=0.1, cap=1.0, factor=2.0, jitter=0.5,
                     rng=random.Random(3))
        seen = [bo.next() for _ in range(8)]
        # each delay within [peek*(1-jitter), peek] of its attempt
        expect = [min(1.0, 0.1 * 2 ** i) for i in range(8)]
        for d, e in zip(seen, expect):
            assert e * 0.5 <= d <= e + 1e-12
        assert bo.peek() == 1.0  # capped

    def test_long_outage_never_overflows(self):
        """~2000 consecutive failures (a multi-hour orderer outage at
        cap cadence) must keep returning cap, not raise OverflowError
        out of factor**attempt and kill the reconnect loop for good."""
        bo = Backoff(base=0.2, cap=15.0, jitter=0.0)
        for _ in range(2000):
            d = bo.next()
            assert 0.2 <= d <= 15.0
        assert bo.attempt == 2000
        assert bo.peek() == 15.0
        bo.reset()
        assert bo.next() == 0.2

    def test_reset_returns_to_base(self):
        bo = Backoff(base=0.2, cap=5.0, jitter=0.0)
        assert bo.next() == 0.2
        assert bo.next() == 0.4
        bo.reset()
        assert bo.attempt == 0
        assert bo.next() == 0.2

    def test_validation(self):
        for kw in ({"base": 0}, {"base": 1, "cap": 0.5},
                   {"factor": 0.5}, {"jitter": 2.0}):
            with pytest.raises(ValueError):
                Backoff(**kw)


# -- DeviceLaneGuard --------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _guard(**kw):
    from fabric_tpu.ops_metrics import Registry

    clock = kw.pop("clock", None) or _Clock()
    reg = Registry()  # isolated: assertions read exact counts
    g = DeviceLaneGuard(
        registry=reg, clock=clock, sleep=lambda s: None,
        backoff=Backoff(base=0.001, cap=0.002, jitter=0.0),
        channel="t", **kw,
    )
    return g, reg, clock


def _ctr(reg, name):
    m = reg.metric(name)
    return m.value(channel="t") if m else 0.0


class TestDeviceLaneGuard:
    def test_threshold_zero_is_a_construction_error(self):
        with pytest.raises(ValueError):
            _guard(fail_threshold=0)

    def test_retry_then_success(self):
        g, reg, _ = _guard(retries=2, fail_threshold=5)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return "device"

        assert g.run_launch(flaky, lambda: "cpu", eager=True) == "device"
        assert calls["n"] == 3
        assert _ctr(reg, "device_verify_retries_total") == 2
        assert not g.degraded
        assert g.consecutive_failures == 0  # success reset

    def test_exhausted_retries_route_to_fallback(self):
        g, reg, _ = _guard(retries=1, fail_threshold=10)

        def dead():
            raise RuntimeError("boom")

        assert g.run_launch(dead, lambda: "cpu", eager=True) == "cpu"
        assert _ctr(reg, "fallback_blocks_total") == 1
        assert not g.degraded  # threshold 10 not reached

    def test_latch_fallback_probe_and_recovery(self):
        g, reg, clock = _guard(retries=0, fail_threshold=2,
                               recovery_s=10.0)
        state = {"dead": True}

        def lane():
            if state["dead"]:
                raise RuntimeError("device gone")
            return "device"

        gauge = reg.metric("validator_degraded")
        # two consecutive failures latch degraded
        assert g.run_launch(lane, lambda: "cpu", eager=True) == "cpu"
        assert not g.degraded
        assert g.run_launch(lane, lambda: "cpu", eager=True) == "cpu"
        assert g.degraded
        assert gauge.value(channel="t") == 1
        # degraded: straight to fallback, NO device attempt
        before = state.copy()
        clock.t += 5.0  # < recovery_s: not yet probing
        assert g.run_launch(lane, lambda: "cpu", eager=True) == "cpu"
        assert _ctr(reg, "fallback_blocks_total") == 3
        # probe due, device still dead: stays degraded, block on CPU
        clock.t += 10.0
        assert g.run_launch(lane, lambda: "cpu", eager=True) == "cpu"
        assert g.degraded
        # next probe finds the device back: lane re-arms
        state["dead"] = False
        clock.t += 10.0
        assert g.run_launch(lane, lambda: "cpu", eager=True) == "device"
        assert not g.degraded
        assert gauge.value(channel="t") == 0
        assert g.degraded_seconds() == pytest.approx(25.0)

    def test_shielded_fallback_survives_persistent_fault(self):
        # a persistent fault at the SHARED ops entry point must not
        # chase the CPU fallback — faults.shield() around fallback_fn
        faults.configure("validator.verify_launch:raise")
        g, reg, _ = _guard(retries=0, fail_threshold=1)

        def cpu():
            faults.fire("validator.verify_launch")  # shared entry
            return "cpu"

        assert g.run_launch(lambda: "device", cpu, eager=True) == "cpu"
        assert g.degraded

    def test_deadline_counts_toward_latch(self):
        clock = _Clock()
        g, reg, _ = _guard(retries=0, fail_threshold=2,
                           deadline_ms=50.0, clock=clock)

        def slow():
            clock.t += 0.2  # 200ms > 50ms deadline
            return "device"

        # result still used, but each over-deadline attempt counts
        assert g.run_launch(slow, lambda: "cpu", eager=True) == "device"
        assert g.consecutive_failures == 1
        assert not g.degraded
        assert g.run_launch(slow, lambda: "cpu", eager=True) == "device"
        assert g.degraded  # latched by slowness alone


# -- the REAL validator's device lane (crypto-free via ec_ref) --------------


def _ecref_items():
    """5 deterministic P-256 signature tuples (4 valid, 1 corrupted)
    from the pure-Python oracle — no `cryptography` needed."""
    from fabric_tpu.crypto import ec_ref

    k = ec_ref.SigningKey(d=0x1F2E3D4C5B6A79885746352413021100DEADBEEF)
    items = []
    for i in range(5):
        e = ec_ref.digest_int(b"payload-%d" % i)
        r, s = k.sign_digest(e, k=0xA5A5A5A5 + 977 * i)
        if i == 4:
            r ^= 1  # corrupt: must reject on EVERY lane
        items.append((e, r, s, *k.public))
    return items, [True, True, True, True, False]


def _real_validator(**kw):
    # peer.validator imports crypto.identity → needs `cryptography`
    # (the seed condition); the crypto-free differential below covers
    # the same machinery through the toy validator on bare containers
    pytest.importorskip("cryptography")
    from fabric_tpu.peer.validator import BlockValidator, PolicyProvider

    return BlockValidator(
        msp_manager=None, policy_provider=PolicyProvider({}),
        state_db=MemVersionedDB(), channel="lane", **kw,
    )


class TestValidatorDeviceLane:
    def test_guarded_device_lane_verdicts(self):
        items, want = _ecref_items()
        v = _real_validator(device_fail_threshold=3, device_retries=0)
        h = v._verify_launch_guarded(items)
        assert hasattr(h, "device_out")  # device lane, guarded wrapper
        assert [bool(x) for x in h()] == want
        assert not v.device_guard.degraded

    def test_persistent_launch_fault_latches_cpu_fallback(self):
        items, want = _ecref_items()
        v = _real_validator(device_fail_threshold=1, device_retries=0)
        faults.configure("validator.verify_launch:raise")
        h = v._verify_launch_guarded(items)
        assert getattr(h, "device_out", None) is None  # host MVCC path
        assert [bool(x) for x in h()] == want          # verdicts equal
        assert v.device_guard.degraded

    def test_fetch_side_failure_reverifies_on_cpu(self):
        items, want = _ecref_items()
        v = _real_validator(device_fail_threshold=2, device_retries=0)
        from fabric_tpu.peer.validator import _GuardedHandle

        class DeadHandle:
            device_out = object()
            n_real = len(items)

            def __call__(self):
                raise RuntimeError("device died after launch")

        g = _GuardedHandle(DeadHandle(), v.device_guard, v, items)
        assert [bool(x) for x in g()] == want  # CPU re-verify, correct
        assert v.device_guard.consecutive_failures == 1

    def test_last_ditch_ecref_when_host_lane_dies(self, monkeypatch):
        items, want = _ecref_items()
        v = _real_validator(device_fail_threshold=1, device_retries=0)
        from fabric_tpu.ops import p256

        def dead(*a, **kw):
            raise RuntimeError("jax runtime gone")

        monkeypatch.setattr(p256, "verify_host", dead)
        assert [bool(x) for x in v._host_verify_fallback(items)] == want


# -- /healthz surfaces a degraded lane (end-to-end, crypto-free) ------------


def test_healthz_reflects_degraded_lane():
    """The node registers a ``device_verify_lane`` health check over
    its channels' guards; a degraded lane must flip /healthz to 503
    with an explanatory reason, and recovery must flip it back."""
    import asyncio
    import urllib.error
    import urllib.request

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    g, _, clock = _guard(retries=0, fail_threshold=1, recovery_s=10.0)
    guards = {"chan0": g}

    def _device_lanes():  # the PeerNode.start checker, in miniature
        for cid, gd in guards.items():
            if gd is not None and gd.degraded:
                return (
                    f"channel {cid}: device verify lane DEGRADED — "
                    "committing via CPU fallback, recovery probe armed"
                )
        return None

    health = HealthRegistry()
    health.register("device_verify_lane", _device_lanes)

    def _get(port):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    async def scenario():
        loop = asyncio.get_event_loop()
        srv = await OperationsServer(port=0, health=health).start()
        try:
            st, body = await loop.run_in_executor(None, _get, srv.port)
            assert st == 200 and body["status"] == "OK"
            # latch the lane degraded
            g.run_launch(lambda: (_ for _ in ()).throw(
                RuntimeError("dead")), lambda: "cpu", eager=True)
            assert g.degraded
            st, body = await loop.run_in_executor(None, _get, srv.port)
            assert st == 503
            (check,) = body["failed_checks"]
            assert check["component"] == "device_verify_lane"
            assert "DEGRADED" in check["reason"]
            assert "chan0" in check["reason"]
            # recovery probe succeeds → healthy again
            clock.t += 20.0
            assert g.run_launch(lambda: "device", lambda: "cpu",
                                eager=True) == "device"
            st, body = await loop.run_in_executor(None, _get, srv.port)
            assert st == 200
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


# -- chaos differential through the depth-2 CommitPipeline ------------------


@dataclass
class ToyPtx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class ToyPending:
    block: object
    txs: list
    raw: list
    sigs: list
    overlay: object
    extra: object
    hd_bytes: bytes = None

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ChaosToyValidator:
    """The toy-validator protocol with an explicit DEVICE LANE: the
    signature phase runs through a DeviceLaneGuard (so the
    ``validator.verify_launch`` injection point, retries, degraded CPU
    fallback and recovery probes are all in play) and the parse phase
    optionally shards over a HostStagePool (so ``hostpool.task``
    worker faults hit the prefetch stage).  Device lane and CPU lane
    compute the same verdicts — the differential proves chaos changes
    WHERE work runs, never WHAT commits.

    tx wire form: {"id", "sig"?: false, "config"?, "reads": {k: [b,t]},
    "writes": {k: v}} — "_lifecycle/"-prefixed keys write the barrier
    namespace."""

    VALID, DUP, BADSIG, MVCC = 0, 2, 8, 11

    def __init__(self, state, guard=None, pool=None):
        self.state = state
        self.guard = guard
        self.pool = pool
        self.lanes: list = []  # "device" | "cpu" per preprocess

    def preprocess(self, block):
        datas = list(block.data.data)
        if self.pool is not None:
            raw = self.pool.map(
                lambda d: json.loads(bytes(d)), datas, stage="parse"
            )
        else:
            raw = [json.loads(bytes(d)) for d in datas]

        def device_lane():
            return ("device", [bool(t.get("sig", True)) for t in raw])

        def cpu_lane():
            return ("cpu", [bool(t.get("sig", True)) for t in raw])

        if self.guard is not None:
            lane, sigs = self.guard.run_launch(
                device_lane, cpu_lane, eager=True
            )
        else:
            lane, sigs = device_lane()
        self.lanes.append(lane)
        return raw, sigs

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw, sigs = pre if pre is not None else self.preprocess(block)
        txs = [
            ToyPtx(t["id"], i, bool(t.get("config")))
            for i, t in enumerate(raw)
        ]
        return ToyPending(block, txs, raw, sigs, overlay, extra_txids)

    def _version(self, ns, key, overlay):
        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                return None if vv.value is None else list(vv.version)
        vv = self.state.get_state(ns, key)
        return None if vv is None else list(vv.version)

    @staticmethod
    def _ns(key):
        return "_lifecycle" if key.startswith("_lifecycle/") else "ns"

    def validate_finish(self, pend):
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for ptx, t, sig_ok in zip(pend.txs, pend.raw, pend.sigs):
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            if not sig_ok:
                codes.append(self.BADSIG)
                continue
            ok = all(
                self._version(self._ns(k), k, pend.overlay) == want
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put(self._ns(k), k, val.encode(), (num, ptx.idx))
        return bytes(codes), batch, []


def _chaos_stream(n_blocks=12, n_tx=6):
    """Dependent stream with an overlay lane, a stale lane, a bad-sig
    lane, and one mid-stream lifecycle BARRIER block."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"tx{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}  # via overlay
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [0, 0]}      # stale → MVCC
            if i == 2 and n % 3 == 1:
                t["sig"] = False                         # bad signature
            txs.append(t)
        if n == 5:
            txs[-1]["writes"]["_lifecycle/cc1"] = "defn"  # barrier
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _drive_chaotic(blocks, make_validator, depth=2, max_restarts=300):
    """The deliver driver's containment loop, in miniature: submit the
    stream; a pipeline stage exception drains the (fail-closed) pipe,
    rebuilds it, and resumes from the last COMMITTED height — exactly
    what _run_deliver_pipelined does via stream reconnect."""
    state = MemVersionedDB()
    v = make_validator(state)
    filters: dict[int, list] = {}
    height = [0]

    def commit_fn(res):
        num = res.block.header.number
        assert num == height[0], "commit out of order"
        assert num not in filters, "block committed twice"
        state.apply_updates(res.batch, (num, 0))
        filters[num] = list(res.tx_filter)
        height[0] = num + 1

    restarts = 0
    pipe = CommitPipeline(v, commit_fn, depth=depth)
    while True:
        try:
            for blk in blocks[height[0]:]:
                if blk.header.number < height[0]:
                    continue  # replayed (committed while we restarted)
                pipe.submit(blk)
            pipe.flush()
            break
        except Exception:
            restarts += 1
            assert restarts < max_restarts, "chaos run cannot converge"
            pipe.close(flush=False)
            pipe = CommitPipeline(v, commit_fn, depth=depth)
    pipe.close()
    return filters, dict(state._data), v, restarts


def test_chaos_differential_matches_fault_free_serial():
    """THE acceptance criterion: device-launch faults (probabilistic,
    seeded), one host-pool worker fault, one injected mid-stream
    pipeline disconnect and one commit-stage fault, driven through a
    depth-2 CommitPipeline with retry/fallback/containment — the
    committed block/tx accept-set equals a fault-free depth-1 run."""
    from fabric_tpu.parallel.hostpool import HostStagePool

    blocks = _chaos_stream(12, 6)

    # fault-free serial oracle
    f_serial, s_serial, v0, r0 = _drive_chaotic(
        blocks, lambda st: ChaosToyValidator(st), depth=1
    )
    assert r0 == 0
    assert sorted(f_serial) == list(range(12))

    plan = FaultPlan(
        "validator.verify_launch:raise:p=0.6;"
        "hostpool.task:raise:n=1:after=6;"
        "pipeline.prefetch:raise:n=1:after=4;"   # the mid-stream cut
        "pipeline.commit:raise:n=1:after=2",
        seed=20260803,
    )
    faults.install(plan)
    pool = HostStagePool(2)
    try:
        def make_validator(st):
            g = DeviceLaneGuard(
                retries=1, fail_threshold=2, recovery_s=0.0,
                backoff=Backoff(base=0.001, cap=0.002, jitter=0.0),
                sleep=lambda s: None, channel="chaos",
            )
            return ChaosToyValidator(st, guard=g, pool=pool)

        f_chaos, s_chaos, v, restarts = _drive_chaotic(
            blocks, make_validator, depth=2
        )
    finally:
        pool.shutdown()
        faults.reset()

    # the differential: EXACT accept set and final state
    assert f_chaos == f_serial
    assert s_chaos == s_serial
    # and the chaos actually bit: device faults fired, blocks rode the
    # CPU lane, the pipe was torn down and resumed at least once
    assert plan.fired("validator.verify_launch") > 0
    assert plan.fired("pipeline.prefetch") == 1
    assert plan.fired("pipeline.commit") == 1
    assert plan.fired("hostpool.task") == 1
    assert "cpu" in v.lanes and "device" in v.lanes
    assert restarts >= 2  # prefetch cut + commit fault (+ pool fault)


def test_chaos_latency_faults_change_nothing():
    """Latency-only chaos (slow device, slow commit) must not change
    verdicts, state, or require any restart."""
    blocks = _chaos_stream(6, 4)
    f_serial, s_serial, _, _ = _drive_chaotic(
        blocks, lambda st: ChaosToyValidator(st), depth=1
    )
    faults.install(FaultPlan(
        "validator.verify_launch:latency:ms=5:p=0.5;"
        "pipeline.commit:latency:ms=5:p=0.5", seed=11,
    ))
    try:
        f, s, _, restarts = _drive_chaotic(
            blocks,
            lambda st: ChaosToyValidator(st, guard=DeviceLaneGuard(
                retries=1, fail_threshold=3, recovery_s=0.0,
                deadline_ms=1.0,  # every slow launch counts a failure
                backoff=Backoff(base=0.001, cap=0.002, jitter=0.0),
                sleep=lambda s_: None, channel="lat",
            )),
            depth=2,
        )
    finally:
        faults.reset()
    assert restarts == 0
    assert f == f_serial and s == s_serial


# -- crash consistency: kill mid-fsync, replay on restart -------------------


_CRASH_CHILD = """\
import json, sys
sys.path.insert(0, {repo!r})
from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch

lg = KVLedger(sys.argv[1], state_db=MemVersionedDB(),
              enable_history=False)
lg.blocks.group_commit = 4
prev = b""
for n in range(int(sys.argv[2])):
    blk = pu.new_block(n, prev)
    blk.data.data.append(
        json.dumps({{"id": "tx%d" % n, "key": "k%d" % n}}).encode()
    )
    blk = pu.finalize_block(blk)
    batch = UpdateBatch()
    batch.put("ns", "k%d" % n, b"v%d" % n, (n, 0))
    lg.commit_block(blk, bytes([0]), batch, [], None, [("tx%d" % n, 0)])
    prev = pu.block_header_hash(blk.header)
print("HEIGHT", lg.height)
lg.close()
"""


def _run_crash_child(tmp_path, n_blocks, fault_spec):
    script = tmp_path / "crash_child.py"
    script.write_text(_CRASH_CHILD.format(repo=REPO))
    ledger_dir = str(tmp_path / "ledger")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FABTPU_FAULTS", None)
    if fault_spec:
        env["FABTPU_FAULTS"] = fault_spec
    out = subprocess.run(
        [sys.executable, str(script), ledger_dir, str(n_blocks)],
        env=env, capture_output=True, text=True, timeout=120,
    )
    return ledger_dir, out


def _reopen_and_verify(ledger_dir, expect_height, indexed_txids=None):
    """Reopen the crashed ledger: consistent height, linked chain,
    state replay via recover(), and the store still accepts blocks.
    ``indexed_txids``: blocks whose txid-index rows must have survived
    (the recovery re-index parses real envelopes, not these toy JSON
    payloads, so a tail block re-indexed from the FILES keeps its
    block row but not its toy txids)."""
    from fabric_tpu.ledger.kvledger import KVLedger

    lg = KVLedger(ledger_dir, state_db=MemVersionedDB(),
                  enable_history=False)
    try:
        assert lg.height == expect_height
        prev = b""
        for n in range(lg.height):
            blk = lg.blocks.get_block(n)
            assert blk is not None, f"block {n} unreadable"
            assert blk.header.previous_hash == prev
            if n < (expect_height if indexed_txids is None
                    else indexed_txids):
                assert lg.blocks.tx_exists(f"tx{n}")
            prev = pu.block_header_hash(blk.header)
        assert lg.blocks.get_block(lg.height) is None
        # state replays forward from the block files (mem state starts
        # empty: savepoint None → full replay)
        def replayer(block):
            t = json.loads(bytes(block.data.data[0]))
            batch = UpdateBatch()
            batch.put("ns", t["key"], b"r", (block.header.number, 0))
            return bytes([0]), batch, []

        replayed = lg.recover(replayer)
        assert replayed == expect_height
        for n in range(expect_height):
            assert lg.state.get_state("ns", f"k{n}") is not None
        # and the channel keeps accepting: commit the next block
        h = lg.height
        blk = pu.new_block(h, prev)
        blk.data.data.append(json.dumps({"id": f"tx{h}"}).encode())
        blk = pu.finalize_block(blk)
        lg.commit_block(blk, bytes([0]), UpdateBatch(), [], None,
                        [(f"tx{h}", 0)])
        assert lg.height == h + 1
    finally:
        lg.close()


@pytest.mark.parametrize("hook", ["before", "after"])
def test_kill_mid_fsync_replays_to_consistent_height(tmp_path, hook):
    """Child commits 12 blocks (group_commit=4) and is hard-killed at
    its SECOND fsync (os._exit inside the hook — nothing flushed, no
    atexit): block 7's record is on disk but unindexed.  Reopen must
    re-index forward to height 8, link the chain, replay state, and
    accept block 8."""
    ledger_dir, out = _run_crash_child(
        tmp_path, 12, f"ledger.fsync.{hook}:crash:after=1"
    )
    assert out.returncode == 86, (out.stdout, out.stderr)
    assert "HEIGHT" not in out.stdout  # died mid-stream, as intended
    _reopen_and_verify(ledger_dir, expect_height=8, indexed_txids=7)


def test_torn_tail_after_crash_truncates_and_recovers(tmp_path):
    """The unsynced tail a crash can tear: chop the last segment file
    mid-record (what a power loss does to the un-fsynced window) —
    _recover must truncate to the last complete record, clamp the
    index back to the files, and the ledger must keep accepting."""
    ledger_dir, out = _run_crash_child(
        tmp_path, 12, "ledger.fsync.before:crash:after=1"
    )
    assert out.returncode == 86
    seg = os.path.join(ledger_dir, "chains", "blocks_000000.bin")
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 7)  # mid-record: block 7's tail is torn
    _reopen_and_verify(ledger_dir, expect_height=7)


def test_no_fault_child_is_clean(tmp_path):
    """The same child with NO fault plan commits all 12 blocks — pins
    that the harness itself (env spec, group commit) is inert."""
    ledger_dir, out = _run_crash_child(tmp_path, 12, "")
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "HEIGHT 12" in out.stdout
    _reopen_and_verify(ledger_dir, expect_height=12)


# -- crash consistency under the PIPELINED windowed fsync --------------------

_PIPE_CRASH_CHILD = """\
import json, sys
sys.path.insert(0, {repo!r})
from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer.pipeline import CommitPipeline


class V:  # minimal validator protocol over 1-tx JSON blocks
    def preprocess(self, block):
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        return type("P", (), {{
            "block": block, "raw": raw, "txs": [],
            "txids": {{t["id"] for t in raw}},
        }})()

    def validate_finish(self, pend):
        batch = UpdateBatch()
        num = pend.block.header.number
        for i, t in enumerate(pend.raw):
            batch.put("ns", t["key"], b"v", (num, i))
        return bytes([0] * len(pend.raw)), batch, []


lg = KVLedger(sys.argv[1], state_db=MemVersionedDB(),
              enable_history=False)
lg.blocks.group_commit = 4
depth = int(sys.argv[3])
mode = sys.argv[4]  # "honor" = node discipline; "windowed" = pure
                    # group-commit batching (no forced per-block sync)


def commit_fn(res):
    lg.commit_block(res.block, res.tx_filter, res.batch, res.history,
                    None, [(t["id"], i)
                           for i, t in enumerate(res.pend.raw)])
    # the node's windowed-fsync discipline: mid-window DEEP-pipelined
    # commits defer; everything else forces the window closed
    if mode == "honor" and not res.defer_sync:
        lg.blocks.sync()


prev = b""
blocks = []
for n in range(int(sys.argv[2])):
    blk = pu.new_block(n, prev)
    blk.data.data.append(
        json.dumps({{"id": "tx%d" % n, "key": "k%d" % n}}).encode()
    )
    blk = pu.finalize_block(blk)
    prev = pu.block_header_hash(blk.header)
    blocks.append(blk)
with CommitPipeline(V(), commit_fn, depth=depth) as pipe:
    for blk in blocks:
        pipe.submit(blk)
print("HEIGHT", lg.height)
lg.close()
"""


def _run_pipe_crash_child(tmp_path, n_blocks, depth, fault_spec,
                          mode="honor"):
    script = tmp_path / "pipe_crash_child.py"
    script.write_text(_PIPE_CRASH_CHILD.format(repo=REPO))
    ledger_dir = str(tmp_path / "ledger")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FABTPU_FAULTS", None)
    if fault_spec:
        env["FABTPU_FAULTS"] = fault_spec
    out = subprocess.run(
        [sys.executable, str(script), ledger_dir, str(n_blocks),
         str(depth), mode],
        env=env, capture_output=True, text=True, timeout=120,
    )
    return ledger_dir, out


@pytest.mark.parametrize("hook", ["before", "after"])
def test_pipelined_windowed_fsync_crash_replays_depth3(tmp_path, hook):
    """THE windowed-fsync durability re-pin at depth 3: mid-window
    commits carry defer_sync=True, the node discipline skips their
    forced fsync, and group_commit=4 batches the window — a hard kill
    at the SECOND group fsync must reopen at the last group-commit
    boundary (height 8: block 7's record on disk but unindexed), link
    the chain, replay state forward, and keep accepting blocks."""
    ledger_dir, out = _run_pipe_crash_child(
        tmp_path, 12, 3, f"ledger.fsync.{hook}:crash:after=1"
    )
    assert out.returncode == 86, (out.stdout, out.stderr)
    assert "HEIGHT" not in out.stdout  # died mid-stream, as intended
    _reopen_and_verify(ledger_dir, expect_height=8, indexed_txids=7)


def test_pipelined_depth2_keeps_classic_per_block_durability(tmp_path):
    """Depth 2 NEVER defers (defer_sync is a depth ≥ 3 behavior): the
    honor-discipline child force-fsyncs every commit, so the same
    crash plan fires at the SECOND per-block sync and only blocks 0–1
    are on disk — the default config's acknowledged-durability
    semantics are exactly the pre-depth-N ones."""
    ledger_dir, out = _run_pipe_crash_child(
        tmp_path, 12, 2, "ledger.fsync.before:crash:after=1"
    )
    assert out.returncode == 86, (out.stdout, out.stderr)
    _reopen_and_verify(ledger_dir, expect_height=2)


@pytest.mark.parametrize("hook", ["before", "after"])
def test_pipelined_windowed_fsync_crash_depth2_group_knob(tmp_path,
                                                          hook):
    """The depth-2 windowed story rides the group_commit KNOB, not
    defer_sync: a committer that opts out of forced per-block syncs
    entirely (mode=windowed) batches fsyncs every 4 blocks at depth 2
    too, and the kill-mid-group replay holds there as well."""
    ledger_dir, out = _run_pipe_crash_child(
        tmp_path, 12, 2, f"ledger.fsync.{hook}:crash:after=1",
        mode="windowed",
    )
    assert out.returncode == 86, (out.stdout, out.stderr)
    _reopen_and_verify(ledger_dir, expect_height=8, indexed_txids=7)


@pytest.mark.parametrize("depth", [2, 3])
def test_pipelined_windowed_fsync_clean_run(tmp_path, depth):
    """No fault: the pipelined honor-discipline child commits all 12
    blocks and the TAIL commit closes any open window (the stream's
    last block arrives with defer_sync=False), so everything is
    durable at exit even before close()."""
    ledger_dir, out = _run_pipe_crash_child(tmp_path, 12, depth, "")
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "HEIGHT 12" in out.stdout
    _reopen_and_verify(ledger_dir, expect_height=12)

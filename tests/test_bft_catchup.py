"""BFT replica state transfer + live membership reconfiguration.

Round-4 left two admitted gaps: a BFT replica whose last_applied lags
the cluster had no way back (ordering/bft.py's own docstring said so),
and the consenter set was fixed at construction.  These tests pin the
new paths: catch-up via block pull + install_snapshot when live
traffic references sequences past the replica's application point
(SmartBFT synchronizer.go:40 Sync analog), and consenter ADDITION via
a committed config block carrying the new node's identity, with f and
the quorum recomputed and the message-verifier registry rotated
(smartbft configverifier.go)."""

import asyncio

import pytest

from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
from fabric_tpu.protos import common_pb2, configtx_pb2, orderer_pb2

CHANNEL = "bftcat"


def run(coro, timeout=120):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=25.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.03)
    return False


def _bft_material(n=5):
    org = cryptogen.generate_org("OrdererMSP", "orderer.example.com",
                                 peers=0, orderers=n, users=0)
    mgr = MSPManager({"OrdererMSP": org.msp()})
    ids = [f"o{i}" for i in range(n)]
    signers = {
        oid: cryptogen.signing_identity(
            org, f"orderer{i}.orderer.example.com")
        for i, oid in enumerate(ids)
    }
    verifiers = {
        oid: mgr.deserialize_identity(signers[oid].serialized)
        for oid in ids
    }
    return ids, signers, verifiers


def _mk_node(tmp_path, oid, cluster, signers, verifiers, retention=4):
    return OrdererNode(
        oid, str(tmp_path / oid), cluster,
        batch_config=BatchConfig(max_message_count=1, batch_timeout_s=0.1),
        consensus="bft", signer=signers[oid], verifiers=dict(verifiers),
        view_timeout=1.0,
    )


async def _mk_bft_cluster(tmp_path, ids, signers, verifiers, retention=4):
    cluster = {}
    nodes = {}
    for oid in ids:
        n = _mk_node(tmp_path, oid, cluster, signers, verifiers)
        await n.start()
        cluster[oid] = ("127.0.0.1", n.port)
        nodes[oid] = n
    for n in nodes.values():
        n.cluster.update(cluster)
        chain = n.join_channel(CHANNEL)
        chain.wal_retention = retention
    return nodes, cluster


def test_bft_replica_catchup_after_compaction(tmp_path):
    """A BFT replica that slept through the cluster's compaction window
    recovers via block catch-up: live COMMIT traffic references
    sequences past its application point, the chain pulls the missing
    blocks (verifying their 2f+1 commit proofs), install_snapshot
    fast-forwards the consensus state, and the replica rejoins
    agreement."""
    async def scenario():
        ids, signers, verifiers = _bft_material(4)
        ids = ids[:4]
        nodes, cluster = await _mk_bft_cluster(
            tmp_path, ids, signers, verifiers, retention=4
        )
        bc = BroadcastClient(list(cluster.values()))
        try:
            assert (await bc.broadcast(
                CHANNEL, b"warm", retries=90))["status"] == 200
            victim = nodes["o3"]
            await victim.stop()

            for i in range(14):  # past retention AND the catchup gap
                res = await bc.broadcast(CHANNEL, b"m%d" % i, retries=90)
                assert res["status"] == 200
            live = [nodes[i] for i in ("o0", "o1", "o2")]
            assert await _wait(lambda: all(
                n.chains[CHANNEL].height >= 15 for n in live
            ), 30)
            wal0 = nodes["o0"].chains[CHANNEL].raft.wal
            assert await _wait(lambda: wal0.snap_index > 0, 10)

            # restart o3 from disk: far behind, pre-prepares long gone
            o3 = _mk_node(tmp_path, "o3", dict(cluster), signers, verifiers)
            await o3.start()
            cluster["o3"] = ("127.0.0.1", o3.port)
            for n in live:
                n.cluster["o3"] = cluster["o3"]
            o3.cluster.update(cluster)
            ch3 = o3.join_channel(CHANNEL)
            ch3.wal_retention = 4
            nodes["o3"] = o3

            # new traffic makes the gap visible to o3's catch-up probe
            for i in range(10):
                res = await bc.broadcast(
                    CHANNEL, b"post%d" % i, retries=90)
                assert res["status"] == 200
            target = nodes["o0"].chains[CHANNEL].height
            assert await _wait(lambda: ch3.height >= target, 40)
            assert ch3.raft.last_applied >= wal0.snap_index
            # identical headers across the cluster
            for k in range(target):
                a = ch3.blocks.get_block(k).header.SerializeToString()
                b = nodes["o0"].chains[CHANNEL].blocks.get_block(
                    k).header.SerializeToString()
                assert a == b
            await bc.close()
        finally:
            for n in nodes.values():
                try:
                    await n.stop()
                except Exception:
                    pass

    run(scenario())


def _bft_config_env(consenters, identities):
    """CONFIG envelope carrying a BFT consenter set WITH identities."""
    meta = orderer_pb2.RaftConfigMetadata(consenters=[
        orderer_pb2.RaftConsenter(
            host=h, port=p, id=i, identity=identities.get(i, b"")
        )
        for h, p, i in consenters
    ])
    ct = orderer_pb2.ConsensusType(
        type="bft", metadata=meta.SerializeToString()
    )
    root = configtx_pb2.ConfigGroup()
    root.groups["Orderer"].values["ConsensusType"].value = \
        ct.SerializeToString()
    cfg_env = configtx_pb2.ConfigEnvelope(
        config=configtx_pb2.Config(sequence=1, channel_group=root)
    )
    ch = common_pb2.ChannelHeader(
        type=common_pb2.HeaderType.CONFIG, channel_id=CHANNEL
    )
    payload = common_pb2.Payload(data=cfg_env.SerializeToString())
    payload.header.channel_header = ch.SerializeToString()
    return common_pb2.Envelope(payload=payload.SerializeToString())


def test_bft_add_fifth_consenter_live(tmp_path):
    """Consenter ADDITION on a live BFT channel: the committed config
    block (carrying the new node's identity) grows the membership to
    n=5 — f recomputes to 1, the quorum to 3 — existing replicas admit
    the newcomer's signed messages, and the newcomer replicates the
    chain and participates in new agreement."""
    async def scenario():
        ids5, signers, verifiers = _bft_material(5)
        ids4 = ids5[:4]
        # the initial cluster only knows o0..o3 (o4's identity arrives
        # via the config block, NOT provisioning)
        v4 = {k: v for k, v in verifiers.items() if k != "o4"}
        nodes, cluster = await _mk_bft_cluster(
            tmp_path, ids4, signers, v4, retention=1000
        )
        bc = BroadcastClient(list(cluster.values()))
        try:
            for i in range(3):
                assert (await bc.broadcast(
                    CHANNEL, b"pre%d" % i, retries=90))["status"] == 200

            o4 = OrdererNode(
                "o4", str(tmp_path / "o4"), {},
                batch_config=BatchConfig(max_message_count=1,
                                         batch_timeout_s=0.1),
                consensus="bft", signer=signers["o4"],
                verifiers=dict(verifiers),  # operator provisions its own
                view_timeout=1.0,
            )
            await o4.start()
            new_addr = ("127.0.0.1", o4.port)
            consenters = [(h, p, oid) for oid, (h, p) in cluster.items()]
            consenters.append((new_addr[0], new_addr[1], "o4"))
            env = _bft_config_env(
                consenters, {"o4": signers["o4"].serialized}
            )
            res = await bc.broadcast(
                CHANNEL, env.SerializeToString(), retries=90
            )
            assert res["status"] == 200

            # membership + thresholds + verifier registry all rotated
            assert await _wait(lambda: all(
                "o4" in n.chains[CHANNEL].raft.peers
                and n.chains[CHANNEL].raft.n == 5
                and n.chains[CHANNEL].raft.quorum == 3
                and "o4" in n.chains[CHANNEL].raft.verifiers
                for n in nodes.values()
            ), 20)

            # o4 joins; it detects its gap from live COMMIT traffic
            # (sequences past its application point) and closes it by
            # block catch-up, then participates in new agreement
            o4.cluster.update({**cluster, "o4": new_addr})
            ch4 = o4.join_channel(CHANNEL)
            nodes["o4"] = o4
            for i in range(10):
                assert (await bc.broadcast(
                    CHANNEL, b"post%d" % i, retries=90))["status"] == 200
            assert await _wait(
                lambda: ch4.height == nodes["o0"].chains[CHANNEL].height,
                40,
            )
            assert ch4.height >= 14  # pre + config + post all present
            await bc.close()
        finally:
            for n in nodes.values():
                try:
                    await n.stop()
                except Exception:
                    pass

    run(scenario())


def test_peer_censorship_monitor_rotates_off_withholding_orderer(tmp_path):
    """BFT deliver-client stance: an orderer that keeps the Deliver
    stream open while WITHHOLDING blocks cannot stall the peer — the
    monitor cross-checks other orderers' heights and rotates
    (blocksprovider/bft_censorship_monitor.go).  A disconnect-only
    failover never fires here because the censor never disconnects."""
    import json as _json

    from fabric_tpu.comm.rpc import RpcServer
    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.peer.chaincode import ChaincodeRuntime
    from fabric_tpu.peer.node import PeerNode
    from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider

    async def scenario():
        # one REAL (solo-bft dev) orderer with a few blocks
        orderer = OrdererNode(
            "o0", str(tmp_path / "o0"), {},
            batch_config=BatchConfig(max_message_count=1,
                                     batch_timeout_s=0.1),
        )
        await orderer.start()
        orderer.cluster["o0"] = ("127.0.0.1", orderer.port)
        orderer.join_channel("cns")
        bc = BroadcastClient([("127.0.0.1", orderer.port)])
        for i in range(3):
            assert (await bc.broadcast(
                "cns", b"m%d" % i, retries=60))["status"] == 200
        await bc.close()

        # the CENSOR: accepts Deliver and sends NOTHING, forever
        censor = RpcServer("127.0.0.1", 0)

        async def _black_hole(stream):
            await stream.__anext__()  # consume the seek request
            await asyncio.sleep(3600)
            yield b""  # pragma: no cover — keeps this an async gen

        censor.register("Deliver", _black_hole)
        await censor.start()

        org = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                     peers=1, users=1)
        mgr = MSPManager({"Org1MSP": org.msp()})
        peer = PeerNode(
            "p0", str(tmp_path / "p0"), mgr,
            cryptogen.signing_identity(org, "peer0.org1.example.com"),
            ChaincodeRuntime(),
        )
        await peer.start()
        prov = PolicyProvider({}, default=NamespaceInfo(
            policy=pol.from_dsl("OutOf(1, 'Org1MSP.peer')")))
        ch = peer.join_channel("cns", prov)
        try:
            # censor FIRST in the failover list: without the monitor
            # the peer would hang on its silent stream forever
            ch.start_deliver(
                [("127.0.0.1", censor.port),
                 ("127.0.0.1", orderer.port)],
                censorship_check_s=0.5,
            )
            assert await _wait(lambda: ch.height >= 3, 25), ch.height
        finally:
            await peer.stop()
            await censor.stop()
            await orderer.stop()

    run(scenario())

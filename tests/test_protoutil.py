"""Wire-format tests: proto round-trips, hashes, tx extraction, rwset."""

import hashlib

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.protos import common_pb2, proposal_pb2, transaction_pb2


class _FakeSigner:
    def sign(self, data):
        return b"sig:" + hashlib.sha256(data).digest()[:8]


def test_der_header_hash_known_vector():
    h = common_pb2.BlockHeader(number=5, previous_hash=b"\x01" * 32, data_hash=b"\x02" * 32)
    der = pu.block_header_bytes(h)
    # SEQUENCE(INTEGER 5, OCTETS 32, OCTETS 32)
    assert der[0] == 0x30
    assert der[2:5] == b"\x02\x01\x05"
    assert pu.block_header_hash(h) == hashlib.sha256(der).digest()
    # large number needs multi-byte INTEGER with sign handling
    h2 = common_pb2.BlockHeader(number=2**40 + 129, previous_hash=b"", data_hash=b"")
    der2 = pu.block_header_bytes(h2)
    assert der2[0] == 0x30


def test_der_int_sign_padding():
    # number with MSB set in leading byte must get a 0x00 pad
    assert pu._der_int(0x80) == b"\x02\x02\x00\x80"
    assert pu._der_int(0x7F) == b"\x02\x01\x7f"
    assert pu._der_int(0) == b"\x02\x01\x00"


def test_block_roundtrip_and_filter():
    blk = pu.new_block(3, b"prev" * 8)
    blk.data.data.append(b"env1")
    blk.data.data.append(b"env2")
    pu.finalize_block(blk)
    assert blk.header.data_hash == hashlib.sha256(b"env1env2").digest()
    flags = pu.new_tx_filter(2)
    assert not pu.tx_flag_is_valid(flags, 0)
    flags[0] = transaction_pb2.TxValidationCode.VALID
    pu.set_tx_filter(blk, flags)
    got = pu.get_tx_filter(blk)
    assert pu.tx_flag_is_valid(got, 0) and not pu.tx_flag_is_valid(got, 1)


def _make_endorser_tx(channel="ch1", txid="tx1"):
    cca = proposal_pb2.ChaincodeAction(results=b"rwset-bytes")
    prp = proposal_pb2.ProposalResponsePayload(
        proposal_hash=b"h" * 32, extension=cca.SerializeToString()
    )
    cap = transaction_pb2.ChaincodeActionPayload()
    cap.action.proposal_response_payload = prp.SerializeToString()
    cap.action.endorsements.add(endorser=b"E1", signature=b"S1")
    tx = transaction_pb2.Transaction()
    tx.actions.add(header=b"", payload=cap.SerializeToString())
    ch = pu.make_channel_header(
        common_pb2.HeaderType.ENDORSER_TRANSACTION, channel, tx_id=txid
    )
    sh = pu.make_signature_header(b"creator", b"nonce")
    payload = pu.make_payload(ch, sh, tx.SerializeToString())
    return pu.sign_envelope(payload, _FakeSigner())


def test_extract_action():
    env = _make_endorser_tx()
    ch, sh, cap, prp, cca = pu.extract_action(env)
    assert ch.channel_id == "ch1" and ch.tx_id == "tx1"
    assert sh.creator == b"creator"
    assert cca.results == b"rwset-bytes"
    assert cap.action.endorsements[0].endorser == b"E1"


def test_extract_action_errors():
    import pytest

    C = transaction_pb2.TxValidationCode
    with pytest.raises(pu.TxParseError) as ei:
        pu.extract_action(common_pb2.Envelope())
    assert ei.value.code == C.NIL_ENVELOPE
    # config-type envelope rejected as unknown for this path
    ch = pu.make_channel_header(common_pb2.HeaderType.CONFIG, "ch1")
    sh = pu.make_signature_header(b"c", b"n")
    env = pu.sign_envelope(pu.make_payload(ch, sh, b""), _FakeSigner())
    with pytest.raises(pu.TxParseError) as ei:
        pu.extract_action(env)
    assert ei.value.code == C.UNKNOWN_TX_TYPE


def test_signed_data_and_txid():
    env = _make_endorser_tx()
    sd = pu.envelope_as_signed_data(env)
    assert sd.identity == b"creator"
    assert sd.data == env.payload and sd.signature == env.signature
    assert pu.compute_tx_id(b"n", b"c") == hashlib.sha256(b"nc").hexdigest()


def test_rwset_roundtrip():
    tx = TxRWSet()
    n = tx.ns_rwset("mycc")
    n.reads["a"] = (3, 1)
    n.reads["absent"] = None
    n.writes["b"] = b"val"
    n.writes["del"] = None
    n.range_queries.append(("k1", "k9", [("k3", (2, 0))]))
    n.metadata_writes["b"] = {"VALIDATION_PARAMETER": b"pol"}
    n.hashed["collA"] = {
        "reads": {b"\xaa" * 32: (1, 0)},
        "writes": {b"\xbb" * 32: (b"\xcc" * 32, False)},
        "pvt_hash": b"\xdd" * 32,
    }
    data = tx.to_proto().SerializeToString()
    tx2 = TxRWSet.from_bytes(data)
    n2 = tx2.ns["mycc"]
    assert n2.reads == n.reads
    assert n2.writes == n.writes
    assert n2.range_queries == n.range_queries
    assert n2.metadata_writes == n.metadata_writes
    assert n2.hashed["collA"]["reads"] == n.hashed["collA"]["reads"]
    assert n2.hashed["collA"]["writes"] == n.hashed["collA"]["writes"]

    reads, writes, rqs = tx2.mvcc_form()
    keys = [k for k, _ in reads]
    assert ("pub", "mycc", "a") in keys
    assert ("pvt", "mycc", "collA", b"\xaa" * 32) in keys
    assert ("pub", "mycc", "b") in writes
    assert ("pvt", "mycc", "collA", b"\xbb" * 32) in writes
    assert rqs == [(("pub", "mycc", "k1"), ("pub", "mycc", "k9"))]


def test_block_header_data_bytes_roundtrip():
    """The hand-framed header+data serialization plus spliced metadata
    must parse identically to the upb full-block serialization (the
    commit path writes these bytes to the block files)."""
    blk = pu.new_block(7, b"\x01" * 32)
    for i in range(5):
        blk.data.data.append(b"envelope-%d" % i * (i + 1))
    blk = pu.finalize_block(blk)
    pu.set_tx_filter(blk, bytes([0, 1, 0, 2, 0]))
    blk.metadata.metadata[0] = b"sig-meta"
    hd = pu.block_header_data_bytes(blk)
    full = pu.append_block_metadata(hd, blk)
    ref = common_pb2.Block()
    ref.ParseFromString(full)
    assert ref.SerializeToString() == blk.SerializeToString()
    assert ref.header.number == 7
    assert list(ref.data.data) == list(blk.data.data)
    assert list(ref.metadata.metadata) == list(blk.metadata.metadata)
    # empty data block: parse-equivalent (upb omits an unset empty
    # submessage, so byte equality is not required there)
    empty = pu.new_block(0, b"")
    empty = pu.finalize_block(empty)
    e2 = common_pb2.Block()
    e2.ParseFromString(
        pu.append_block_metadata(pu.block_header_data_bytes(empty), empty)
    )
    assert e2.header == empty.header
    assert list(e2.data.data) == list(empty.data.data)
    assert list(e2.metadata.metadata) == list(empty.metadata.metadata)

"""The static-analysis battery: per-rule fixtures + the tier-1 gate.

Each rule gets a known-bad snippet (asserting the exact finding
location), a known-clean snippet, and a ``# fabtpu: noqa(RULE)``
suppression check.  ``test_repo_is_clean`` runs the full battery over
``fabric_tpu/`` in-process and fails on any non-baselined finding —
that test IS the enforcement: a PR that introduces a jit-purity bug
or a lock-order inversion fails tier-1.
"""

import os
import textwrap

from fabric_tpu.analysis import analyze_paths, load_baseline
from fabric_tpu.analysis.core import default_baseline_path
from fabric_tpu.analysis.rules.host_sync import HostSyncRule
from fabric_tpu.analysis.rules.jit_purity import JitPurityRule
from fabric_tpu.analysis.rules.lock_discipline import LockDisciplineRule
from fabric_tpu.analysis.rules.retrace_hazard import RetraceHazardRule
from fabric_tpu.analysis.rules.swallowed_exception import (
    SwallowedExceptionRule,
)
from fabric_tpu.analysis.rules.kernel_dtype import KernelDtypeMismatchRule
from fabric_tpu.analysis.rules.union_env import UnionEnvCoercionRule
from fabric_tpu.analysis.rules.asyncio_task_leak import AsyncioTaskLeakRule
from fabric_tpu.analysis.rules.blocking_wait import BlockingWaitRule


def run_rule(tmp_path, rule, files: dict[str, str]):
    """files: relpath → source.  → findings sorted by (path, line)."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    res = analyze_paths(
        [str(tmp_path)], root=str(tmp_path), rules=[rule], baseline=None
    )
    return res.findings


# -- FT001 jit-purity -------------------------------------------------------

BAD_JIT = """\
import time

import jax


@jax.jit
def kernel(x):
    t0 = time.perf_counter()
    return x + t0
"""


class TestJitPurity:
    def test_flags_wall_clock(self, tmp_path):
        got = run_rule(tmp_path, JitPurityRule(), {"mod.py": BAD_JIT})
        assert [(f.rule, f.path, f.line) for f in got] == [
            ("FT001", "mod.py", 8)
        ]
        assert "time.perf_counter" in got[0].message

    def test_flags_call_form_and_mutation(self, tmp_path):
        src = """\
        import jax

        _CACHE = {}


        def impl(x):
            _CACHE[x.shape] = x
            return x * 2


        fast = jax.jit(impl)
        """
        got = run_rule(tmp_path, JitPurityRule(), {"mod.py": src})
        assert len(got) == 1
        assert got[0].line == 7
        assert "_CACHE" in got[0].message

    def test_clean_kernel_passes(self, tmp_path):
        src = """\
        import jax
        import jax.numpy as jnp


        @jax.jit
        def kernel(x, y):
            local = {}
            local["t"] = x + y
            return local["t"] * 2
        """
        assert run_rule(tmp_path, JitPurityRule(), {"mod.py": src}) == []

    def test_noqa_suppresses(self, tmp_path):
        src = BAD_JIT.replace(
            "t0 = time.perf_counter()",
            "t0 = time.perf_counter()  # fabtpu: noqa(FT001)",
        )
        assert run_rule(tmp_path, JitPurityRule(), {"mod.py": src}) == []

    def test_noqa_by_name_suppresses(self, tmp_path):
        src = BAD_JIT.replace(
            "t0 = time.perf_counter()",
            "t0 = time.perf_counter()  # fabtpu: noqa(jit-purity)",
        )
        assert run_rule(tmp_path, JitPurityRule(), {"mod.py": src}) == []


# -- FT002 retrace-hazard ---------------------------------------------------


class TestRetraceHazard:
    def test_mutable_default(self, tmp_path):
        src = """\
        import jax


        @jax.jit
        def f(x, opts={}):
            return x
        """
        got = run_rule(tmp_path, RetraceHazardRule(), {"mod.py": src})
        assert [(f.line, f.col) for f in got] == [(5, 14)]
        assert "opts" in got[0].message

    def test_closure_over_mutated_module_list(self, tmp_path):
        src = """\
        import jax

        SCALE = [1.0]


        @jax.jit
        def f(x):
            return x * SCALE[0]


        def bump():
            SCALE[0] = 2.0
        """
        got = run_rule(tmp_path, RetraceHazardRule(), {"mod.py": src})
        assert len(got) == 1 and got[0].line == 8
        assert "SCALE" in got[0].message

    def test_unhashable_static_arg(self, tmp_path):
        src = """\
        import jax
        from functools import partial


        @partial(jax.jit, static_argnames=("shape",))
        def f(x, shape):
            return x.reshape(shape)


        def caller(x):
            return f(x, shape=[4, 4])
        """
        got = run_rule(tmp_path, RetraceHazardRule(), {"mod.py": src})
        assert len(got) == 1 and got[0].line == 11
        assert "shape" in got[0].message

    def test_clean(self, tmp_path):
        src = """\
        import jax

        SCALE = (1.0, 2.0)


        @jax.jit
        def f(x, n=4):
            return x * SCALE[0] + n
        """
        assert run_rule(tmp_path, RetraceHazardRule(), {"mod.py": src}) == []

    def test_noqa_suppresses(self, tmp_path):
        src = """\
        import jax


        @jax.jit
        def f(x, opts={}):  # fabtpu: noqa(FT002)
            return x
        """
        assert run_rule(tmp_path, RetraceHazardRule(), {"mod.py": src}) == []


# -- FT003 host-sync-in-hot-path -------------------------------------------


class TestHostSync:
    def test_flags_sync_reachable_from_validator(self, tmp_path):
        files = {
            "peer/validator.py": """\
            from ops import helper


            def validate(block):
                return helper(block)
            """,
            "ops.py": """\
            import jax


            def helper(x):
                y = jax.device_get(x)
                x.block_until_ready()
                return y
            """,
            "cold.py": """\
            import jax


            def unreachable(x):
                return jax.device_get(x)
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [
            ("ops.py", 5), ("ops.py", 6),
        ]
        assert all(f.rule == "FT003" for f in got)

    def test_item_and_asarray_of_call(self, tmp_path):
        files = {
            "peer/coordinator.py": """\
            import numpy as np


            def gather(run):
                total = run().item()
                arr = np.asarray(run())
                host = np.asarray(sorted([3, 1]))
                return total, arr, host
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        # sorted() is host memory by construction — never flagged
        assert [(f.line,) for f in got] == [(5,), (6,)]

    def test_noqa_marks_intended_sync(self, tmp_path):
        files = {
            "peer/validator.py": """\
            def validate(fetch):
                return fetch().item()  # fabtpu: noqa(FT003)
            """,
        }
        assert run_rule(tmp_path, HostSyncRule(), files) == []

    def test_import_aware_module_attr_resolution(self, tmp_path):
        """``p256.verify_host()`` links only to the imported module's
        def — the same-named def in an unimported module stays cold."""
        files = {
            "peer/validator.py": """\
            from ops import p256


            def validate(block):
                return p256.verify_host(block)
            """,
            "ops/p256.py": """\
            import jax


            def verify_host(x):
                return jax.device_get(x)
            """,
            "ops/p256_other.py": """\
            import jax


            def verify_host(x):
                return jax.device_get(x)  # cold: never imported
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [("ops/p256.py", 5)]

    def test_import_aware_from_import_and_rename(self, tmp_path):
        """``from mod import foo as bar`` resolves ``bar()`` to mod's
        ``foo`` only; a same-named def elsewhere stays cold.  Imports
        inside function bodies count (the hot path imports lazily)."""
        files = {
            "peer/validator.py": """\
            def validate(block):
                from kernels import sync_fetch as fetch_fn

                return fetch_fn(block)
            """,
            "kernels.py": """\
            import jax


            def sync_fetch(x):
                return jax.device_get(x)
            """,
            "cold.py": """\
            import jax


            def fetch_fn(x):
                return jax.device_get(x)  # bare name matches; module not imported
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [("kernels.py", 5)]

    def test_external_import_produces_no_edges(self, tmp_path):
        """A name imported from a clearly-external package (no analyzed
        module shares its root) cannot reach analyzed defs — the
        over-approximation that linked every same-named def is gone."""
        files = {
            "peer/validator.py": """\
            from concurrent.futures import wait


            def validate(futs):
                return wait(futs)
            """,
            "threadutil.py": """\
            import jax


            def wait(x):
                return jax.device_get(x)  # same bare name, never imported
            """,
        }
        assert run_rule(tmp_path, HostSyncRule(), files) == []

    def test_unresolved_project_import_falls_back(self, tmp_path):
        """A project-looking import that does not resolve (e.g. a
        native/generated module outside the analyzed set) must fall
        back to bare-name linking — never under-approximate."""
        files = {
            "peer/validator.py": """\
            from peer.native_ext import helper


            def validate(block):
                return helper(block)
            """,
            "somewhere.py": """\
            import jax


            def helper(x):
                return jax.device_get(x)
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [("somewhere.py", 5)]

    def test_reexported_name_falls_back_to_bare(self, tmp_path):
        """``from pkg import helper`` where pkg/__init__.py re-exports
        ``helper`` from an implementation module: the package has no
        def of that name, so resolution must degrade to bare-name and
        still reach the real callee — re-exports must not blind the
        graph."""
        files = {
            "peer/validator.py": """\
            from pkg import helper


            def validate(block):
                return helper(block)
            """,
            "pkg/__init__.py": """\
            from pkg.impl import helper
            """,
            "pkg/impl.py": """\
            import jax


            def helper(x):
                return jax.device_get(x)
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [("pkg/impl.py", 5)]

    def test_submodule_attr_precision_survives_package_init(self, tmp_path):
        """``from pkg import sub`` where pkg HAS an __init__.py: the
        attr call ``sub.f()`` must still resolve only to the
        submodule's def — the object-in-package hedge must not degrade
        the resolution to bare-name (the ROADMAP case verbatim)."""
        files = {
            "peer/validator.py": """\
            from pkg import sub


            def validate(block):
                return sub.f(block)
            """,
            "pkg/__init__.py": "",
            "pkg/sub.py": """\
            import jax


            def f(x):
                return jax.device_get(x)
            """,
            "pkg/other.py": """\
            import jax


            def f(x):
                return jax.device_get(x)  # cold: never imported
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [("pkg/sub.py", 5)]

    def test_package_root_absolute_import_falls_back(self, tmp_path):
        """Analyzing the PACKAGE directory itself (dotted forms like
        "ops.p256"): an absolute ``from fabric_tpu.gen import helper``
        whose module is outside the analyzed set must still fall back
        to bare-name linking — the root's own directory name counts as
        a project root, so the import is not misread as external."""
        import textwrap

        pkg = tmp_path / "fabric_tpu"
        files = {
            "peer/validator.py": """\
            from fabric_tpu.gen import helper


            def validate(block):
                return helper(block)
            """,
            "somewhere.py": """\
            import jax


            def helper(x):
                return jax.device_get(x)
            """,
        }
        for rel, src in files.items():
            path = pkg / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(textwrap.dedent(src))
        res = analyze_paths(
            [str(pkg)], root=str(pkg), rules=[HostSyncRule()],
            baseline=None,
        )
        assert [(f.path, f.line) for f in res.findings] == [
            ("somewhere.py", 5),
        ]

    def test_local_def_shadows_external_import(self, tmp_path):
        """A module-local def with the same name as an external import
        stays linked (the shadowing guard)."""
        files = {
            "peer/validator.py": """\
            from time import monotonic


            def monotonic(x):  # local shadow wins at runtime
                return x.block_until_ready()


            def validate(block):
                return monotonic(block)
            """,
        }
        got = run_rule(tmp_path, HostSyncRule(), files)
        assert [(f.path, f.line) for f in got] == [
            ("peer/validator.py", 5),
        ]


# -- FT004 lock-discipline --------------------------------------------------


class TestLockDiscipline:
    def test_order_cycle_across_modules(self, tmp_path):
        files = {
            "a.py": """\
            async def commit(self):
                async with self.commit_lock.writer():
                    async with self.state_lock.writer():
                        pass
            """,
            "b.py": """\
            async def snapshot(self):
                async with self.state_lock.reader():
                    async with self.commit_lock.reader():
                        pass
            """,
        }
        got = run_rule(tmp_path, LockDisciplineRule(), files)
        # BOTH sides of the inversion are reported — each site points
        # at the other, like the race detector's paired stacks
        assert [(f.path, f.line) for f in got] == [
            ("a.py", 3), ("b.py", 3),
        ]
        for f in got:
            assert "cycle" in f.message
            assert {"commit_lock", "state_lock"} <= set(
                f.message.replace("'", " ").split()
            )

    def test_blocking_call_under_lock(self, tmp_path):
        files = {
            "a.py": """\
            import os
            import time


            def flush(self, fd, fut):
                with self._lock:
                    os.fsync(fd)
                    time.sleep(0.1)
                    fut.result()
            """,
        }
        got = run_rule(tmp_path, LockDisciplineRule(), files)
        assert [(f.line,) for f in got] == [(7,), (8,), (9,)]
        assert all("_lock" in f.message for f in got)

    def test_consistent_order_is_clean(self, tmp_path):
        files = {
            "a.py": """\
            async def commit(self):
                async with self.commit_lock.writer():
                    async with self.state_lock.writer():
                        pass


            async def endorse(self):
                async with self.commit_lock.reader():
                    async with self.state_lock.reader():
                        pass
            """,
        }
        assert run_rule(tmp_path, LockDisciplineRule(), files) == []

    def test_self_deadlock(self, tmp_path):
        files = {
            "a.py": """\
            def nested(self):
                with self._lock:
                    with self._lock:
                        pass
            """,
        }
        got = run_rule(tmp_path, LockDisciplineRule(), files)
        assert len(got) == 1 and "re-acquired" in got[0].message

    def test_noqa_suppresses(self, tmp_path):
        files = {
            "a.py": """\
            import os


            def flush(self, fd):
                with self._lock:
                    os.fsync(fd)  # fabtpu: noqa(FT004)
            """,
        }
        assert run_rule(tmp_path, LockDisciplineRule(), files) == []


# -- FT005 swallowed-exception ---------------------------------------------


class TestSwallowedException:
    def test_flags_pure_drops(self, tmp_path):
        src = """\
        def f(items):
            out = []
            for it in items:
                try:
                    out.append(parse(it))
                except Exception:
                    continue
            try:
                cleanup()
            except:
                pass
            return out
        """
        got = run_rule(
            tmp_path, SwallowedExceptionRule(), {"mod.py": src}
        )
        assert [(f.line,) for f in got] == [(6,), (10,)]

    def test_verdicts_and_logging_pass(self, tmp_path):
        src = """\
        import logging

        log = logging.getLogger(__name__)


        def f(x):
            try:
                return parse(x)
            except Exception:
                return None


        def g(x):
            try:
                return parse(x)
            except Exception as e:
                log.warning("parse failed: %s", e)
                return False


        def h(x):
            try:
                return parse(x)
            except ValueError:
                pass
        """
        assert run_rule(
            tmp_path, SwallowedExceptionRule(), {"mod.py": src}
        ) == []

    def test_noqa_suppresses(self, tmp_path):
        src = """\
        def f():
            try:
                cleanup()
            except Exception:  # fabtpu: noqa(FT005)
                pass
        """
        assert run_rule(
            tmp_path, SwallowedExceptionRule(), {"mod.py": src}
        ) == []


# -- FT006 union-env-coercion ----------------------------------------------

# the exact pre-fix shape of nodeconfig._apply_env (ADVICE round 5)
PRE_FIX_ENV = """\
import dataclasses
import os
import typing
from dataclasses import dataclass


@dataclass
class TlsConfig:
    cert: str = ""


@dataclass
class PeerConfig:
    port: int = 0
    operations_port: int | None = None
    tls: TlsConfig | None = None


def _coerce(val, typ):
    return val


def _apply_env(cfg, environ=None):
    env = os.environ if environ is None else environ
    for f in dataclasses.fields(cfg):
        typ = f.type
        key = "FABTPU_" + f.name.upper()
        if key in env:
            setattr(cfg, f.name, _coerce(env[key], typ))
"""


class TestUnionEnvCoercion:
    def test_flags_pre_fix_shape(self, tmp_path):
        got = run_rule(
            tmp_path, UnionEnvCoercionRule(), {"mod.py": PRE_FIX_ENV}
        )
        # Optional[int] is coercible; Optional[TlsConfig] is the bug
        assert [(f.line,) for f in got] == [(16,)]
        assert "PeerConfig.tls" in got[0].message
        assert "_apply_env" in got[0].message

    def test_get_args_guard_clears(self, tmp_path):
        src = PRE_FIX_ENV.replace(
            "        if key in env:",
            "        args = typing.get_args(typ)\n"
            "        if key in env:",
        )
        assert run_rule(
            tmp_path, UnionEnvCoercionRule(), {"mod.py": src}
        ) == []

    def test_no_env_loop_is_clean(self, tmp_path):
        src = """\
        from dataclasses import dataclass


        @dataclass
        class Holder:
            payload: dict | None = None
        """
        assert run_rule(
            tmp_path, UnionEnvCoercionRule(), {"mod.py": src}
        ) == []

    def test_noqa_suppresses(self, tmp_path):
        src = PRE_FIX_ENV.replace(
            "    tls: TlsConfig | None = None",
            "    tls: TlsConfig | None = None  # fabtpu: noqa(FT006)",
        )
        assert run_rule(
            tmp_path, UnionEnvCoercionRule(), {"mod.py": src}
        ) == []


# -- FT007 kernel-dtype-mismatch --------------------------------------------

# an ops/ kernel declaring int32 lanes via the repo's trailing-comment
# convention, plus a docstring-declared lane
KERNEL_MOD = '''\
def mvcc_check(
    read_keys,      # [T, R] int32 block-local key ids
    ver_ok,         # [T] bool
    write_keys,     # [T, W] int32
    windows=None,
):
    """Kernel.

    windows: [B, 64] int32 4-bit window digits.
    """
    return read_keys
'''

BAD_CALLER = """\
import numpy as np

from fabric_tpu.ops.kern import mvcc_check


def launch(n):
    rk = np.zeros((n, 4), np.int64)
    ok = np.ones(n, bool)
    mvcc_check(rk, ok, np.arange(n)[:, None])
"""


class TestKernelDtypeMismatch:
    def _files(self, caller):
        return {"ops/kern.py": KERNEL_MOD, "peer/caller.py": caller}

    def test_flags_int64_into_int32_lane(self, tmp_path):
        got = run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(BAD_CALLER)
        )
        # rk (assigned int64) into read_keys AND the dtype-less arange
        # (platform int64) into write_keys — the bool arg is clean
        assert [(f.rule, f.path, f.line) for f in got] == [
            ("FT007", "peer/caller.py", 9),
            ("FT007", "peer/caller.py", 9),
        ]
        msgs = " ".join(f.message for f in got)
        assert "read_keys" in msgs and "write_keys" in msgs

    def test_keyword_and_docstring_lane(self, tmp_path):
        src = """\
        import numpy as np

        from fabric_tpu.ops.kern import mvcc_check


        def launch(n):
            w = np.asarray([1, 2], np.int64)
            mvcc_check(
                np.zeros((n, 4), np.int32), np.ones(n, bool),
                np.zeros((n, 2), np.int32), windows=w,
            )
        """
        got = run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(src)
        )
        assert len(got) == 1
        assert "windows" in got[0].message

    def test_int32_caller_is_clean(self, tmp_path):
        src = BAD_CALLER.replace("np.int64", "np.int32").replace(
            "np.arange(n)[:, None]",
            "np.arange(n, dtype=np.int32)[:, None]",
        )
        assert run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(src)
        ) == []

    def test_unknown_dtype_not_flagged(self, tmp_path):
        src = """\
        from fabric_tpu.ops.kern import mvcc_check


        def launch(rk, ok, wk):
            mvcc_check(rk[:, :4], ok, wk)
        """
        assert run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(src)
        ) == []

    def test_non_ops_def_not_a_kernel(self, tmp_path):
        # the same def OUTSIDE ops/ declares nothing → callers clean
        files = {"peer/kern.py": KERNEL_MOD, "peer/caller.py": BAD_CALLER}
        assert run_rule(
            tmp_path, KernelDtypeMismatchRule(), files
        ) == []

    def test_call_in_closure_flagged_once(self, tmp_path):
        # the staging-closure pattern (ops/p256v3 stage() closures):
        # walk_functions yields outer AND inner defs — the call must
        # not be double-counted from both scopes
        src = """\
        import numpy as np

        from fabric_tpu.ops.kern import mvcc_check


        def launch(n):
            rk = np.zeros((n, 4), np.int64)

            def stage(lo, hi):
                return mvcc_check(rk, None, np.arange(hi - lo)[:, None])

            return stage
        """
        got = run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(src)
        )
        # exactly one finding (the arange into write_keys); rk's dtype
        # lives in the OUTER scope's env — the closure's own env does
        # not see it (under-approximation, never a duplicate)
        assert len(got) == 1
        assert "write_keys" in got[0].message

    def test_same_named_local_helper_not_matched(self, tmp_path):
        # a project function that merely SHARES a kernel's name must
        # not drag its callers into the rule (import-aware gate)
        src = """\
        import numpy as np


        def mvcc_check(a, b, c):
            return a


        def launch(n):
            rk = np.zeros((n, 4), np.int64)
            mvcc_check(rk, None, np.arange(n))
        """
        assert run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(src)
        ) == []

    def test_noqa_suppresses(self, tmp_path):
        src = BAD_CALLER.replace(
            "    mvcc_check(rk, ok, np.arange(n)[:, None])",
            "    mvcc_check(rk, ok, np.arange(n)[:, None])"
            "  # fabtpu: noqa(FT007)",
        )
        assert run_rule(
            tmp_path, KernelDtypeMismatchRule(), self._files(src)
        ) == []


# -- FT008 asyncio-task-leak ------------------------------------------------

BAD_TASK_LEAK = """\
import asyncio


async def fire(coro, other):
    asyncio.ensure_future(coro())
    t = asyncio.create_task(other())
    return 1
"""


class TestAsyncioTaskLeak:
    def test_flags_discard_and_dead_binding(self, tmp_path):
        got = run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": BAD_TASK_LEAK}
        )
        assert [(f.rule, f.path, f.line) for f in got] == [
            ("FT008", "mod.py", 5),
            ("FT008", "mod.py", 6),
        ]
        assert "discarded" in got[0].message
        assert "'t'" in got[1].message

    def test_stored_awaited_cancelled_clean(self, tmp_path):
        src = """\
        import asyncio


        class Svc:
            def __init__(self):
                self._tasks = set()

            def start(self, coro, loop_coro):
                t = asyncio.ensure_future(coro())
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)
                self._main = asyncio.ensure_future(loop_coro())

            async def run(self, coro):
                t = asyncio.create_task(coro())
                try:
                    return await asyncio.wait_for(asyncio.shield(t), 1.0)
                finally:
                    if not t.done():
                        t.cancel()
        """
        assert run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        ) == []

    def test_cancel_in_nested_closure_clean(self, tmp_path):
        # the strong ref lives in the outer scope; only a CLOSURE
        # touches it — still not a leak
        src = """\
        import asyncio


        def start(coro, stoppers):
            t = asyncio.ensure_future(coro())

            def stop():
                t.cancel()

            stoppers.append(stop)
        """
        assert run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        ) == []

    def test_loop_var_and_chained_create_task_flagged(self, tmp_path):
        src = """\
        import asyncio


        def kick(coro, other):
            loop = asyncio.get_event_loop()
            loop.create_task(coro())
            asyncio.get_running_loop().create_task(other())
        """
        got = run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        )
        assert [(f.rule, f.line) for f in got] == [
            ("FT008", 6), ("FT008", 7),
        ]

    def test_from_import_rename_flagged(self, tmp_path):
        src = """\
        from asyncio import ensure_future as spawn


        def kick(coro):
            spawn(coro())
        """
        got = run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        )
        assert len(got) == 1 and got[0].line == 5

    def test_same_named_local_helper_not_matched(self, tmp_path):
        # a project function that merely SHARES the spawner name must
        # not be dragged in (import-aware gate, the FT003 lesson) —
        # asyncio is imported for unrelated reasons
        src = """\
        import asyncio


        def create_task(x):
            return x


        def sched(items):
            create_task(items)
            tracker = object()
            tracker.create_task(items)
        """
        assert run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        ) == []

    def test_passed_or_returned_clean(self, tmp_path):
        src = """\
        import asyncio


        def start(coro, registry):
            t = asyncio.ensure_future(coro())
            registry.append(t)


        def handoff(coro):
            return asyncio.ensure_future(coro())
        """
        assert run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        ) == []

    def test_noqa_suppresses(self, tmp_path):
        src = BAD_TASK_LEAK.replace(
            "    asyncio.ensure_future(coro())",
            "    asyncio.ensure_future(coro())  # fabtpu: noqa(FT008)",
        ).replace(
            "    t = asyncio.create_task(other())",
            "    t = asyncio.create_task(other())  # fabtpu: noqa(FT008)",
        )
        assert run_rule(
            tmp_path, AsyncioTaskLeakRule(), {"mod.py": src}
        ) == []


# -- engine plumbing --------------------------------------------------------


class TestEngine:
    def test_parse_error_is_a_finding(self, tmp_path):
        (tmp_path / "broken.py").write_text("def f(:\n")
        got = analyze_paths(
            [str(tmp_path / "broken.py")], root=str(tmp_path),
            rules=[], baseline=None,
        )
        assert [f.rule for f in got.findings] == ["FT000"]

    def test_baseline_absorbs_exactly_count(self, tmp_path):
        import json as _json

        src = "try:\n    f()\nexcept Exception:\n    pass\n"
        (tmp_path / "one.py").write_text(src)
        (tmp_path / "two.py").write_text(src)
        rule = SwallowedExceptionRule()
        live = analyze_paths(
            [str(tmp_path)], root=str(tmp_path), rules=[rule],
            baseline=None,
        )
        assert len(live.findings) == 2
        bl = tmp_path / "baseline.json"
        bl.write_text(_json.dumps({"findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in live.findings
        ]}))
        gated = analyze_paths(
            [str(tmp_path)], root=str(tmp_path), rules=[rule],
            baseline=load_baseline(str(bl)),
        )
        assert gated.findings == [] and len(gated.baselined) == 2

    def test_stale_baseline_reported(self, tmp_path):
        import json as _json

        (tmp_path / "ok.py").write_text("x = 1\n")
        bl = tmp_path / "baseline.json"
        bl.write_text(_json.dumps({"findings": [
            {"rule": "FT005", "path": "gone.py", "message": "old"}
        ]}))
        res = analyze_paths(
            [str(tmp_path)], root=str(tmp_path),
            rules=[SwallowedExceptionRule()],
            baseline=load_baseline(str(bl)),
        )
        assert res.stale_baseline == [("FT005", "gone.py", "old")]

    def test_cli_exit_codes(self, tmp_path):
        from fabric_tpu.analysis.__main__ import main

        (tmp_path / "bad.py").write_text(
            "try:\n    f()\nexcept Exception:\n    pass\n"
        )
        assert main([str(tmp_path / "bad.py"), "--no-baseline"]) == 1
        (tmp_path / "good.py").write_text("x = 1\n")
        assert main([str(tmp_path / "good.py"), "--no-baseline"]) == 0
        assert main(["--list-rules"]) == 0


# -- the tier-1 gate --------------------------------------------------------


def test_repo_is_clean():
    """The whole battery over fabric_tpu/ must report ZERO findings
    beyond the checked-in baseline.  If this fails, run

        python -m fabric_tpu.analysis

    fix what it prints (or noqa a deliberate exception with a comment
    saying why), and only baseline as a last resort."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = analyze_paths(
        [os.path.join(pkg, "fabric_tpu")], root=pkg,
        baseline=load_baseline(default_baseline_path()),
    )
    assert not res.findings, (
        "static-analysis findings:\n"
        + "\n".join(f.render() for f in res.findings)
    )
    assert not res.stale_baseline, (
        f"stale baseline entries (findings fixed — prune them): "
        f"{res.stale_baseline}"
    )


def test_host_sync_roots_resolve():
    """FT003 seeds its call-graph BFS from peer/validator.py +
    peer/coordinator.py.  If those modules are renamed the rule would
    silently check nothing — this pins that the roots still resolve
    (update HostSyncRule.root_modules alongside any rename)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rule = HostSyncRule()
    analyze_paths(
        [os.path.join(pkg, "fabric_tpu")], root=pkg, rules=[rule],
        baseline=None,
    )
    assert rule.last_root_count > 0, (
        "host-sync rule found no root functions — were the root "
        "modules renamed? fix HostSyncRule.root_modules"
    )


# -- FT009 unbounded-blocking-wait ------------------------------------------

BAD_WAITS = """\
import queue
import threading
from concurrent.futures import ThreadPoolExecutor


def feeder():
    q = queue.Queue()
    item = q.get()
    return item


def joiner():
    t = threading.Thread(target=feeder)
    t.start()
    t.join()


def eventer():
    ev = threading.Event()
    ev.wait()


def futures():
    ex = ThreadPoolExecutor(2)
    f = ex.submit(feeder)
    f.result()
    ex.submit(feeder).result()
"""

SELF_ATTR_POP = """\
from concurrent.futures import ThreadPoolExecutor


class Pipe:
    def __init__(self):
        self._ex = ThreadPoolExecutor(1)
        self._fut = None

    def push(self, fn):
        self._fut = self._ex.submit(fn)

    def drain(self):
        fut, self._fut = self._fut, None
        fut.result()
"""

CLEAN_WAITS = """\
import asyncio
import queue
import threading
from concurrent.futures import ThreadPoolExecutor


def bounded():
    q = queue.Queue()
    q.get(True, 5)
    q.get(timeout=1.0)
    q.get_nowait()
    q.get(False)        # non-blocking: raises Empty immediately
    q.get(block=False)  # ditto
    ev = threading.Event()
    ev.wait(2.0)
    ev.wait(timeout=0.1)
    t = threading.Thread(target=bounded)
    t.join(1)
    ex = ThreadPoolExecutor(1)
    ex.submit(bounded).result(timeout=5)


def unknown(fut, q):
    fut.result()
    q.get()


async def aio():
    q = asyncio.Queue()
    await q.get()
    ev = asyncio.Event()
    await ev.wait()
"""


class TestBlockingWait:
    def test_flags_each_wait_kind(self, tmp_path):
        got = run_rule(tmp_path, BlockingWaitRule(), {"mod.py": BAD_WAITS})
        assert [(f.rule, f.line) for f in got] == [
            ("FT009", 8),    # q.get()
            ("FT009", 15),   # t.join()
            ("FT009", 20),   # ev.wait()
            ("FT009", 26),   # f.result() via ex.submit
            ("FT009", 27),   # chained ex.submit(...).result()
        ]
        assert "Queue.get()" in got[0].message
        assert "timeout=" in got[0].message

    def test_flags_self_attr_pop_idiom(self, tmp_path):
        # the `fut, self._fut = self._fut, None` pop before an
        # unbounded wait — the exact pipeline committer idiom
        got = run_rule(
            tmp_path, BlockingWaitRule(), {"mod.py": SELF_ATTR_POP}
        )
        assert [(f.rule, f.line) for f in got] == [("FT009", 14)]

    def test_flags_run_coroutine_threadsafe_bridge(self, tmp_path):
        src = """\
        import asyncio


        def bridge(loop, coro):
            fut = asyncio.run_coroutine_threadsafe(coro, loop)
            return fut.result()
        """
        got = run_rule(tmp_path, BlockingWaitRule(), {"mod.py": src})
        assert [(f.rule, f.line) for f in got] == [("FT009", 6)]

    def test_flags_renamed_from_import(self, tmp_path):
        src = """\
        from threading import Event as Ev


        def go():
            e = Ev()
            e.wait()
        """
        got = run_rule(tmp_path, BlockingWaitRule(), {"mod.py": src})
        assert [(f.rule, f.line) for f in got] == [("FT009", 6)]

    def test_clean_bounded_unknown_and_awaited(self, tmp_path):
        got = run_rule(
            tmp_path, BlockingWaitRule(), {"mod.py": CLEAN_WAITS}
        )
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        got = run_rule(tmp_path, BlockingWaitRule(), {
            "test_mod.py": BAD_WAITS,
            "tests/helper.py": BAD_WAITS,
            "conftest.py": BAD_WAITS,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        src = "\n".join([
            "import threading",
            "",
            "",
            "def go():",
            "    ev = threading.Event()",
            "    ev.wait()  # fabtpu: noqa(FT009)",
            "",
        ])
        got = run_rule(tmp_path, BlockingWaitRule(), {"mod.py": src})
        assert got == []


# -- FT010 unfinished-span ---------------------------------------------------

BAD_SPANS = """\
def discarded(tracer, block):
    tracer.begin_block(block.number)


def parent_only(self, tracer, block):
    root = tracer.begin_block(block.number, channel="c")
    with tracer.span("launch", parent=root):
        pass
    tracer.add("state_fill", 0.0, 0.001, parent=root)
"""

CLEAN_SPANS = """\
def finished(tracer, block):
    root = tracer.begin_block(block.number)
    try:
        with tracer.span("launch", parent=root):
            pass
    finally:
        tracer.finish_block(root)


def escapes_to_call(tracer, scheduler, block):
    root = tracer.begin_block(block.number)
    scheduler.submit(Request(root=root))


def escapes_to_container(tracer, blocks):
    roots = []
    for b in blocks:
        r = tracer.begin_block(b.number)
        roots.append(r)
    return roots


def escapes_via_return(tracer, block):
    root = tracer.begin_block(block.number)
    return root


def truth_test_then_finished(tracer, block):
    root = tracer.begin_block(block.number)
    if root is not None:
        tracer.set_attrs(root, tail=True)
    tracer.finish_block(root)


def finished_in_closure(tracer, executor, block):
    root = tracer.begin_block(block.number)

    def done():
        tracer.finish_block(root)

    executor.submit(done)


def local_def_never_matches(block):
    def begin_block(n):
        return n

    begin_block(block.number)
"""


class TestUnfinishedSpan:
    def test_flags_discard_and_parent_only(self, tmp_path):
        from fabric_tpu.analysis.rules.unfinished_span import (
            UnfinishedSpanRule,
        )

        got = run_rule(tmp_path, UnfinishedSpanRule(),
                       {"mod.py": BAD_SPANS})
        assert [(f.rule, f.line) for f in got] == [
            ("FT010", 2),   # discarded expression statement
            ("FT010", 6),   # root only ever a span parent
        ]
        assert "flight recorder" in got[0].message
        assert "finish_block" in got[1].message

    def test_clean_finish_escape_and_shadow(self, tmp_path):
        from fabric_tpu.analysis.rules.unfinished_span import (
            UnfinishedSpanRule,
        )

        got = run_rule(tmp_path, UnfinishedSpanRule(),
                       {"mod.py": CLEAN_SPANS})
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.unfinished_span import (
            UnfinishedSpanRule,
        )

        got = run_rule(tmp_path, UnfinishedSpanRule(), {
            "test_mod.py": BAD_SPANS,
            "tests/helper.py": BAD_SPANS,
            "conftest.py": BAD_SPANS,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.unfinished_span import (
            UnfinishedSpanRule,
        )

        src = "\n".join([
            "def keep(tracer, n):",
            "    tracer.begin_block(n)  # fabtpu: noqa(FT010)",
            "",
        ])
        got = run_rule(tmp_path, UnfinishedSpanRule(), {"mod.py": src})
        assert got == []


# -- FT011 device-buffer-lifetime --------------------------------------------

BAD_BUFFER = """\
import jax
from fabric_tpu.parallel.mesh import shard_batch
from fabric_tpu.ops.p256v3 import pack_cols


def pinned_past_fetch(kern, args, handle):
    packed = pack_cols(*args)
    out = kern(packed)
    return handle.fetch()


def device_put_pinned(kern, arr, handle):
    buf = jax.device_put(arr)
    kern(buf)
    res = handle.fetch()
    return res


def shard_pinned(mesh, kern, arr, handle):
    sharded = shard_batch(mesh, arr)
    kern(sharded)
    return handle.fetch()
"""

CLEAN_BUFFER = """\
import jax
from fabric_tpu.ops.p256v3 import pack_cols


def deleted_after_dispatch(kern, args, handle):
    packed = pack_cols(*args)
    out = kern(packed)
    del packed
    return handle.fetch()


def used_after_sync(kern, args, handle):
    packed = pack_cols(*args)
    kern(packed)
    bits = handle.fetch()
    return packed.nbytes, bits


def escapes_via_return(kern, args, handle):
    packed = pack_cols(*args)
    kern(packed)
    handle.fetch()
    return packed


def escapes_to_container(kern, args, handles, frames):
    packed = pack_cols(*args)
    frames.append(packed)
    return [h.fetch() for h in handles]


def rebound_narrows_lifetime(kern, args, handle):
    packed = pack_cols(*args)
    kern(packed)
    packed = None
    return handle.fetch()


def no_sync_in_scope(kern, args):
    packed = pack_cols(*args)
    return kern(packed)


def in_loop_is_skipped(kern, argsets, handle):
    for args in argsets:
        packed = pack_cols(*args)
        kern(packed)
    return handle.fetch()


def local_def_never_matches(kern, args, handle):
    def pack_cols(*a):
        return a

    packed = pack_cols(*args)
    kern(packed)
    return handle.fetch()
"""


class TestDeviceBufferLifetime:
    def test_flags_pinned_uploads(self, tmp_path):
        from fabric_tpu.analysis.rules.device_buffer_lifetime import (
            DeviceBufferLifetimeRule,
        )

        got = run_rule(tmp_path, DeviceBufferLifetimeRule(),
                       {"mod.py": BAD_BUFFER})
        assert [(f.rule, f.line) for f in got] == [
            ("FT011", 7),    # pack_cols frame outlives handle.fetch()
            ("FT011", 13),   # jax.device_put result pinned past fetch
            ("FT011", 20),   # shard_batch result pinned past fetch
        ]
        assert "del" in got[0].message

    def test_clean_shapes(self, tmp_path):
        from fabric_tpu.analysis.rules.device_buffer_lifetime import (
            DeviceBufferLifetimeRule,
        )

        got = run_rule(tmp_path, DeviceBufferLifetimeRule(),
                       {"mod.py": CLEAN_BUFFER})
        assert got == []

    def test_local_def_shadow_never_matches(self, tmp_path):
        # a module with NO qualifying imports never produces findings,
        # even with the same call names (the FT003 lesson)
        from fabric_tpu.analysis.rules.device_buffer_lifetime import (
            DeviceBufferLifetimeRule,
        )

        src = "\n".join([
            "def pack_cols(*a):",
            "    return a",
            "",
            "def f(kern, args, handle):",
            "    packed = pack_cols(*args)",
            "    kern(packed)",
            "    return handle.fetch()",
            "",
        ])
        got = run_rule(tmp_path, DeviceBufferLifetimeRule(),
                       {"mod.py": src})
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.device_buffer_lifetime import (
            DeviceBufferLifetimeRule,
        )

        got = run_rule(tmp_path, DeviceBufferLifetimeRule(), {
            "test_mod.py": BAD_BUFFER,
            "tests/helper.py": BAD_BUFFER,
            "conftest.py": BAD_BUFFER,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.device_buffer_lifetime import (
            DeviceBufferLifetimeRule,
        )

        src = "\n".join([
            "from fabric_tpu.ops.p256v3 import pack_cols",
            "",
            "def f(kern, args, handle):",
            "    packed = pack_cols(*args)  # fabtpu: noqa(FT011)",
            "    kern(packed)",
            "    return handle.fetch()",
            "",
        ])
        got = run_rule(tmp_path, DeviceBufferLifetimeRule(),
                       {"mod.py": src})
        assert got == []


# -- FT012 pvtdata-purge-race ------------------------------------------------

BAD_PURGE = """\
from concurrent.futures import ThreadPoolExecutor
import threading


def races_executor(store, height, rows):
    pool = ThreadPoolExecutor(2)
    pool.submit(store.persist, "tx", rows, height)
    store.purge_below(height - 100)


def races_thread(store, num):
    t = threading.Thread(target=store.resolve_missing, args=(num,))
    t.start()
    return store.purge_expired(num)


def races_loop(loop, store, num, data):
    loop.run_in_executor(None, store.commit_block, num, data)
    store.purge_expired(num)


def purge_dispatched_writer_inline(store, pool, num, data):
    store.commit_block(num, data)
    pool2 = ThreadPoolExecutor(1)
    pool2.submit(lambda: store.purge_expired(num))
"""

CLEAN_PURGE = """\
from concurrent.futures import ThreadPoolExecutor


def inline_is_serialized(store, height, rows):
    store.persist("tx", rows, height)
    store.purge_below(height - 100)


def different_receivers(a, b, height):
    pool = ThreadPoolExecutor(2)
    pool.submit(a.persist, "tx", height)
    b.purge_below(height)


def no_writer_in_scope(store, pool, height, job):
    pool = ThreadPoolExecutor(2)
    pool.submit(job)
    store.purge_expired(height)


def unknown_submit_is_not_an_executor(scheduler, store, h):
    scheduler.submit(store.persist)
    store.purge_below(h)


def no_purge_in_scope(store, h, rows):
    pool = ThreadPoolExecutor(2)
    pool.submit(store.persist, "tx", rows, h)
"""


class TestPvtdataPurgeRace:
    def test_flags_dispatched_writers_racing_the_walk(self, tmp_path):
        from fabric_tpu.analysis.rules.pvtdata_purge_race import (
            PvtdataPurgeRaceRule,
        )

        got = run_rule(tmp_path, PvtdataPurgeRaceRule(),
                       {"mod.py": BAD_PURGE})
        assert [(f.rule, f.line) for f in got] == [
            ("FT012", 8),    # purge_below vs executor-submitted persist
            ("FT012", 14),   # purge_expired vs Thread(resolve_missing)
            ("FT012", 19),   # purge_expired vs run_in_executor commit
            ("FT012", 25),   # DISPATCHED purge vs inline commit_block
        ]
        assert "SELECT-then-DELETE" in got[0].message

    def test_clean_shapes(self, tmp_path):
        from fabric_tpu.analysis.rules.pvtdata_purge_race import (
            PvtdataPurgeRaceRule,
        )

        got = run_rule(tmp_path, PvtdataPurgeRaceRule(),
                       {"mod.py": CLEAN_PURGE})
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.pvtdata_purge_race import (
            PvtdataPurgeRaceRule,
        )

        got = run_rule(tmp_path, PvtdataPurgeRaceRule(), {
            "test_mod.py": BAD_PURGE,
            "tests/helper.py": BAD_PURGE,
            "conftest.py": BAD_PURGE,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.pvtdata_purge_race import (
            PvtdataPurgeRaceRule,
        )

        src = "\n".join([
            "from concurrent.futures import ThreadPoolExecutor",
            "",
            "def f(store, h, rows):",
            "    pool = ThreadPoolExecutor(2)",
            "    pool.submit(store.persist, rows, h)",
            "    store.purge_below(h)  # fabtpu: noqa(FT012)",
            "",
        ])
        got = run_rule(tmp_path, PvtdataPurgeRaceRule(),
                       {"mod.py": src})
        assert got == []


# -- FT013 metric-label-cardinality ------------------------------------------

BAD_LABELS = """\
class Server:
    def __init__(self, registry):
        self._ctr = registry.counter("requests_total", "reqs")

    def handle(self, req, block):
        self._ctr.add(1, txid=req.txid)
        self._ctr.add(1, block=block.header.number)


def chained(registry, tx):
    registry.counter("seen_total", "x").add(1, tx_id=tx.tx_id)


def via_local(registry, req):
    ctr = registry.counter("done_total", "x")
    request_id = req.request_id
    ctr.add(1, req=request_id)


def wrapped(registry, block):
    h = registry.histogram("lat_seconds", "x")
    h.observe(0.1, block=str(block.header.number))


def fstring(registry, ptx):
    g = registry.gauge("height", "x")
    g.set(1, key=f"blk-{ptx.txid}")
"""

CLEAN_LABELS = """\
class Server:
    def __init__(self, registry):
        self._ctr = registry.counter("requests_total", "reqs")
        self._other = object()

    def handle(self, req, channel):
        self._ctr.add(1, channel=channel, status="ok")
        # not a registry instrument: receiver unproven
        self._other.add(1, txid=req.txid)


def closed_sets(registry, tenant, stage):
    h = registry.histogram("lat_seconds", "x")
    h.observe(0.1, tenant=tenant, stage=stage)


def unknown_names_stay_silent(registry, thing):
    ctr = registry.counter("x_total", "x")
    ctr.add(1, label=thing.some_field)


def not_a_metric_ctor(queue, req):
    # .counter() without a literal metric name is not a registration
    c = queue.counter(req)
    c.add(1, txid=req.txid)


def positional_value_only(registry, req):
    registry.counter("y_total", "x").add(2)
"""


class TestMetricLabelCardinality:
    def test_flags_per_request_label_values(self, tmp_path):
        from fabric_tpu.analysis.rules.metric_label_cardinality import (
            MetricLabelCardinalityRule,
        )

        got = run_rule(tmp_path, MetricLabelCardinalityRule(),
                       {"mod.py": BAD_LABELS})
        assert [(f.rule, f.line) for f in got] == [
            ("FT013", 6),    # self-attr counter, txid label
            ("FT013", 7),    # self-attr counter, block number label
            ("FT013", 11),   # chained ctor call, tx_id label
            ("FT013", 17),   # local metric + local assigned from req id
            ("FT013", 22),   # str()-wrapped block number
            ("FT013", 27),   # f-string carrying a txid
        ]
        assert "label variant" in got[0].message

    def test_clean_shapes_never_flag(self, tmp_path):
        from fabric_tpu.analysis.rules.metric_label_cardinality import (
            MetricLabelCardinalityRule,
        )

        got = run_rule(tmp_path, MetricLabelCardinalityRule(),
                       {"mod.py": CLEAN_LABELS})
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.metric_label_cardinality import (
            MetricLabelCardinalityRule,
        )

        got = run_rule(tmp_path, MetricLabelCardinalityRule(), {
            "test_mod.py": BAD_LABELS,
            "tests/helper.py": BAD_LABELS,
            "conftest.py": BAD_LABELS,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.metric_label_cardinality import (
            MetricLabelCardinalityRule,
        )

        src = "\n".join([
            "def f(registry, req):",
            "    c = registry.counter('x_total', 'x')",
            "    c.add(1, txid=req.txid)  # fabtpu: noqa(FT013)",
            "",
        ])
        got = run_rule(tmp_path, MetricLabelCardinalityRule(),
                       {"mod.py": src})
        assert got == []


# -- FT014 nonce-reuse-hazard -------------------------------------------------

BAD_NONCES = """\
import os
import secrets
import random as rnd
from secrets import randbelow as below
from random import SystemRandom


def direct(key, e, n):
    key.sign_digest(e, k=secrets.randbelow(n - 1) + 1)


def positional(key, e, n):
    key.sign_digest(e, rnd.randrange(1, n))


def via_local(key, e, n):
    k = below(n - 1) + 1
    key.sign_digest(e, k=k)


def wrapped(key, e):
    key.sign_digest(e, k=int.from_bytes(os.urandom(32), "big"))


def sysrand(key, e, n):
    key.sign_digest(e, k=SystemRandom().randrange(1, n))


def bare_sign_kw(signer, msg, n):
    signer.sign(msg, k=rnd.getrandbits(256) % n)
"""

CLEAN_NONCES = """\
import secrets
from fabric_tpu.crypto import ec_ref


def deterministic(key, e):
    key.sign_digest(e)  # RFC 6979 default — no k at all


def pinned_vector(key, e, vec_k):
    key.sign_digest(e, k=vec_k)  # provenance unknown: stays silent


def counter_nonce(key, e, i):
    key.sign_digest(e, k=i + 1)  # not provably random


def other_arg_random(key, msgs, n):
    # randomness NOT reaching a k argument
    key.sign(msgs[secrets.randbelow(len(msgs))])


def local_sign_helper(e, n):
    # a same-named local def is still a sign-family call, but the k
    # is a parameter — provenance unknown, silent
    def sign_digest(e, k):
        return (e, k)
    return sign_digest(e, n - 1)


def reassigned_local(key, e, n):
    k = 1
    k = k + 1  # NOT single-assignment: provenance unprovable
    key.sign_digest(e, k=k)


def tuple_rebound_local(key, e, rotate):
    import secrets
    k = secrets.randbelow(100) + 1
    k, tag = rotate(e)  # tuple target REBINDS k: random seed is gone
    key.sign_digest(e, k=k)


def walrus_rebound_local(key, e, nxt):
    import secrets
    k = secrets.randbelow(100) + 1
    if (k := nxt(e)):  # walrus rebinds: provenance unprovable
        key.sign_digest(e, k=k)
"""


class TestNonceReuseHazard:
    def test_flags_random_nonces(self, tmp_path):
        from fabric_tpu.analysis.rules.nonce_reuse import (
            NonceReuseHazardRule,
        )

        got = run_rule(tmp_path, NonceReuseHazardRule(),
                       {"mod.py": BAD_NONCES})
        assert [(f.rule, f.line) for f in got] == [
            ("FT014", 9),    # secrets.randbelow keyword
            ("FT014", 13),   # random positional arg 2
            ("FT014", 18),   # through one single-assignment local
            ("FT014", 22),   # int.from_bytes(os.urandom) wrapper
            ("FT014", 26),   # SystemRandom().randrange chain
            ("FT014", 30),   # .sign(k=getrandbits % n) BinOp
        ]
        assert "RFC 6979" in got[0].message

    def test_clean_shapes_never_flag(self, tmp_path):
        from fabric_tpu.analysis.rules.nonce_reuse import (
            NonceReuseHazardRule,
        )

        got = run_rule(tmp_path, NonceReuseHazardRule(),
                       {"mod.py": CLEAN_NONCES})
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.nonce_reuse import (
            NonceReuseHazardRule,
        )

        got = run_rule(tmp_path, NonceReuseHazardRule(), {
            "test_mod.py": BAD_NONCES,
            "tests/helper.py": BAD_NONCES,
            "conftest.py": BAD_NONCES,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.nonce_reuse import (
            NonceReuseHazardRule,
        )

        src = "\n".join([
            "import secrets",
            "",
            "def f(key, e, n):",
            "    key.sign_digest(e, k=secrets.randbelow(n))"
            "  # fabtpu: noqa(FT014)",
            "",
        ])
        got = run_rule(tmp_path, NonceReuseHazardRule(),
                       {"mod.py": src})
        assert got == []


# -- FT015 resident-state-bypass ---------------------------------------------

BAD_RESIDENT = """\
from fabric_tpu.state import ResidencyManager, resolve_residency


def local_manager_bypass(state, batch):
    res = ResidencyManager(capacity_mb=1)
    state.apply_updates(batch, None)
    return res


def via_resolver(state, batch):
    res = resolve_residency(True, 64, 12)
    state.apply_updates(batch, None)
    return res


class Committer:
    def __init__(self, state):
        self.state = state
        self.resident = ResidencyManager(capacity_mb=1)

    def commit(self, batch):
        self.state.apply_updates(batch, None)
"""

BAD_RESIDENT_ALIAS = """\
import fabric_tpu.state as st


def aliased(state, batch):
    res = st.ResidencyManager(capacity_mb=1)
    state.apply_updates(batch, None)
    return res
"""

CLEAN_RESIDENT = """\
from fabric_tpu.state import ResidencyManager


def hooked_apply_batch(state, batch):
    res = ResidencyManager(capacity_mb=1)
    state.apply_updates(batch, None)
    res.apply_batch(batch)


def hooked_invalidate(state, batch):
    res = ResidencyManager(capacity_mb=1)
    state.apply_updates(batch, None)
    res.invalidate_keys(batch.updates)


def hooked_disable(state, batch):
    res = ResidencyManager(capacity_mb=1)
    state.apply_updates(batch, None)
    res.disable("replacing the table")


def no_manager_in_scope(state, batch):
    # apply_updates with no provable manager binding: silent — the
    # rule polices code that HAS the cache and forgets it
    state.apply_updates(batch, None)


def reassigned_local(state, batch, other):
    res = ResidencyManager(capacity_mb=1)
    res = other  # provenance unknown: never counts as a manager
    state.apply_updates(batch, None)


class HookedCommitter:
    def __init__(self, state):
        self.state = state
        self.resident = ResidencyManager(capacity_mb=1)

    def commit(self, batch):
        self.state.apply_updates(batch, None)
        self.resident.apply_batch(batch)

    def unrelated(self):
        return self.state  # no writer here: nothing to flag
"""

CLEAN_RESIDENT_SHADOW = """\
def ResidencyManager(x):  # a same-named local helper never matches
    return x


def shadowed(state, batch):
    res = ResidencyManager(1)
    state.apply_updates(batch, None)
"""


class TestResidentStateBypass:
    def test_flags_bypassing_writes(self, tmp_path):
        from fabric_tpu.analysis.rules.resident_bypass import (
            ResidentStateBypassRule,
        )

        got = run_rule(tmp_path, ResidentStateBypassRule(),
                       {"mod.py": BAD_RESIDENT})
        assert [(f.rule, f.line) for f in got] == [
            ("FT015", 6),    # local manager, write, no hook
            ("FT015", 12),   # via resolve_residency
            ("FT015", 22),   # class self-attr manager, method write
        ]
        assert "stale" in got[0].message.lower() or (
            "OLD version" in got[0].message
        )

    def test_flags_module_alias_ctor(self, tmp_path):
        from fabric_tpu.analysis.rules.resident_bypass import (
            ResidentStateBypassRule,
        )

        got = run_rule(tmp_path, ResidentStateBypassRule(),
                       {"mod.py": BAD_RESIDENT_ALIAS})
        assert [(f.rule, f.line) for f in got] == [("FT015", 6)]

    def test_clean_shapes_never_flag(self, tmp_path):
        from fabric_tpu.analysis.rules.resident_bypass import (
            ResidentStateBypassRule,
        )

        got = run_rule(tmp_path, ResidentStateBypassRule(), {
            "mod.py": CLEAN_RESIDENT,
            "shadow.py": CLEAN_RESIDENT_SHADOW,
        })
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.resident_bypass import (
            ResidentStateBypassRule,
        )

        got = run_rule(tmp_path, ResidentStateBypassRule(), {
            "test_mod.py": BAD_RESIDENT,
            "tests/helper.py": BAD_RESIDENT,
            "conftest.py": BAD_RESIDENT,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.resident_bypass import (
            ResidentStateBypassRule,
        )

        src = "\n".join([
            "from fabric_tpu.state import ResidencyManager",
            "",
            "def f(state, batch):",
            "    res = ResidencyManager(capacity_mb=1)",
            "    state.apply_updates(batch, None)  "
            "# fabtpu: noqa(FT015)",
            "    return res",
            "",
        ])
        got = run_rule(tmp_path, ResidentStateBypassRule(),
                       {"mod.py": src})
        assert got == []


# -- FT016 unattributed-device-sync ------------------------------------------

BAD_UNATTRIBUTED = """\
import jax
import numpy as np


def fetch_unledgered(handle):
    return np.asarray(handle.device_out)


def local_chain(handle):
    out = handle.device_out
    return np.asarray(out)


def direct_get(x):
    return jax.device_get(x)


def blocks_here(x):
    x.block_until_ready()
    return x


def np_array_variant(self):
    return np.array(self.device_out)
"""

BAD_UNATTRIBUTED_ALIASES = """\
import jax as j
from jax import device_get as dg


def via_alias(x):
    return j.device_get(x)


def via_bare_rename(x):
    return dg(x)
"""

CLEAN_UNATTRIBUTED = """\
import jax
import numpy as np
from fabric_tpu.observe import ledger


def bracketed(handle, rec):
    rec.sync_begin()
    out = np.asarray(handle.device_out)
    rec.sync_end(d2h_bytes=out.nbytes)
    return out


def opens_its_own_record(handle):
    rec = ledger.launch("verify")
    return np.asarray(handle.device_out)


def unknown_provenance(arr):
    # a parameter is not a provable device value
    return np.asarray(arr)


def reassigned_local(handle, other):
    out = handle.device_out
    out = other  # provenance unknown: never counts
    return np.asarray(out)


def host_producer(xs):
    return np.asarray(sorted(xs))


def block_until_ready_with_args(x):
    # not the zero-arg jax-array method shape
    x.block_until_ready(5)
"""

CLEAN_UNATTRIBUTED_SHADOW = """\
import numpy as np


def device_get(x):  # a same-named local helper never matches
    return x


def uses_local_helper(x):
    return device_get(x)


def np_not_imported_as_numpy(handle):
    # this module's `np` IS numpy, but `asarray` of a non-device
    # value stays silent; and without a numpy import the converter
    # check never arms in other modules
    return np.asarray([1, 2])
"""


class TestUnattributedDeviceSync:
    def test_flags_unledgered_syncs(self, tmp_path):
        from fabric_tpu.analysis.rules.unattributed_sync import (
            UnattributedDeviceSyncRule,
        )

        got = run_rule(tmp_path, UnattributedDeviceSyncRule(),
                       {"mod.py": BAD_UNATTRIBUTED})
        assert [(f.rule, f.line) for f in got] == [
            ("FT016", 6),    # np.asarray(handle.device_out)
            ("FT016", 11),   # single-assignment device local
            ("FT016", 15),   # jax.device_get
            ("FT016", 19),   # .block_until_ready()
            ("FT016", 24),   # np.array(self.device_out)
        ]
        assert "launch-ledger" in got[0].message

    def test_flags_import_aliases(self, tmp_path):
        from fabric_tpu.analysis.rules.unattributed_sync import (
            UnattributedDeviceSyncRule,
        )

        got = run_rule(tmp_path, UnattributedDeviceSyncRule(),
                       {"mod.py": BAD_UNATTRIBUTED_ALIASES})
        assert [(f.rule, f.line) for f in got] == [
            ("FT016", 6),    # j.device_get through the alias
            ("FT016", 10),   # renamed bare from-import
        ]

    def test_clean_shapes_never_flag(self, tmp_path):
        from fabric_tpu.analysis.rules.unattributed_sync import (
            UnattributedDeviceSyncRule,
        )

        got = run_rule(tmp_path, UnattributedDeviceSyncRule(), {
            "mod.py": CLEAN_UNATTRIBUTED,
            "shadow.py": CLEAN_UNATTRIBUTED_SHADOW,
        })
        assert got == []

    def test_test_code_exempt(self, tmp_path):
        from fabric_tpu.analysis.rules.unattributed_sync import (
            UnattributedDeviceSyncRule,
        )

        got = run_rule(tmp_path, UnattributedDeviceSyncRule(), {
            "test_mod.py": BAD_UNATTRIBUTED,
            "tests/helper.py": BAD_UNATTRIBUTED,
            "conftest.py": BAD_UNATTRIBUTED,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        from fabric_tpu.analysis.rules.unattributed_sync import (
            UnattributedDeviceSyncRule,
        )

        src = "\n".join([
            "import numpy as np",
            "",
            "def f(handle):",
            "    return np.asarray(handle.device_out)  "
            "# fabtpu: noqa(FT016)",
            "",
        ])
        got = run_rule(tmp_path, UnattributedDeviceSyncRule(),
                       {"mod.py": src})
        assert got == []


def test_rule_battery_registered():
    from fabric_tpu.analysis import all_rules

    ids = {r.id: r.name for r in all_rules()}
    assert ids == {
        "FT001": "jit-purity",
        "FT002": "retrace-hazard",
        "FT003": "host-sync-in-hot-path",
        "FT004": "lock-discipline",
        "FT005": "swallowed-exception",
        "FT006": "union-env-coercion",
        "FT007": "kernel-dtype-mismatch",
        "FT008": "asyncio-task-leak",
        "FT009": "unbounded-blocking-wait",
        "FT010": "unfinished-span",
        "FT011": "device-buffer-lifetime",
        "FT012": "pvtdata-purge-race",
        "FT013": "metric-label-cardinality",
        "FT014": "nonce-reuse-hazard",
        "FT015": "resident-state-bypass",
        "FT016": "unattributed-device-sync",
        "FT017": "cross-thread-state",
        "FT018": "lost-update",
        "FT019": "unruled-sharding",
        "FT020": "clock-mixing",
    }


# -- FT017 cross-thread-state -----------------------------------------------

# the PR-13 shape: ingest appends with no lock, the flusher drains
# under the condition — the deque corrupts under load, never under test
BAD_CROSS_THREAD = """\
import threading
from collections import deque


class SignLane:
    def __init__(self):
        self._cond = threading.Condition()
        self._pending = deque()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, item):
        self._pending.append(item)
        return item

    def _run(self):
        while True:
            with self._cond:
                while not self._pending:
                    self._cond.wait()
                self._pending.popleft()
"""

# worker role from an executor submit: the pool thread writes the
# stats dict bare while readers take the lock
BAD_CROSS_THREAD_EXECUTOR = """\
import threading
from concurrent.futures import ThreadPoolExecutor


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._ex = ThreadPoolExecutor(2)
        self._stats = {}

    def kick(self, key):
        self._ex.submit(self._work, key)

    def totals(self):
        with self._lock:
            return dict(self._stats)

    def _work(self, key):
        self._stats[key] = self._stats.get(key, 0) + 1
"""

# every cross-thread path holds the condition — including the ingest
# side, which reaches the deque through a *_locked helper (the
# interprocedural held-set propagation)
CLEAN_CROSS_THREAD = """\
import threading
from collections import deque


class LockedLane:
    def __init__(self):
        self._cond = threading.Condition()
        self._pending = deque()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, item):
        with self._cond:
            self._append_locked(item)
            self._cond.notify()

    def _append_locked(self, item):
        self._pending.append(item)

    def _run(self):
        with self._cond:
            while not self._pending:
                self._cond.wait()
            self._pending.popleft()
"""

# unprovable shapes stay silent: an attr-chain thread target (unknown
# provenance — not a class method), and a class that never locks the
# shared flag anywhere (a different discipline the rule cannot prove
# wrong)
CLEAN_CROSS_THREAD_UNKNOWN = """\
import threading


class Looper:
    def __init__(self, loop):
        self.loop = loop
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self.loop.run_forever)
        self._thread.start()


class Flag:
    def __init__(self):
        self._stop = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def halt(self):
        self._stop = True

    def _run(self):
        while not self._stop:
            pass
"""


# task role from an asyncio spawn: the coroutine drains under the
# lock while the synchronous caller appends bare — awaits are the
# preemption points, so the interleaving races exactly like a thread's
BAD_CROSS_THREAD_TASK = """\
import asyncio


class Feeder:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._buf = []
        self._task = None

    def start(self):
        self._task = asyncio.create_task(self._drain())

    def push(self, item):
        self._buf.append(item)

    async def _drain(self):
        async with self._lock:
            self._buf.clear()
"""

# executor role from a loop.run_in_executor dispatch: the pool thread
# writes the totals dict bare while the snapshot reader takes the lock
BAD_CROSS_THREAD_RUN_IN_EXECUTOR = """\
import threading


class Offloader:
    def __init__(self, loop):
        self._lock = threading.Lock()
        self._loop = loop
        self._totals = {}

    def kick(self, key):
        self._loop.run_in_executor(None, self._work, key)

    def snapshot(self):
        with self._lock:
            return dict(self._totals)

    def _work(self, key):
        self._totals[key] = self._totals.get(key, 0) + 1
"""

# unprovable asyncio shapes stay silent: a task over a free-function
# coroutine, and a create_task handed a bound method WITHOUT calling
# it (not the provable ``self.m()`` coroutine shape)
CLEAN_CROSS_THREAD_TASK_UNKNOWN = """\
import asyncio


async def pump():
    pass


class Quiet:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._buf = []
        self._t1 = None
        self._t2 = None

    def start(self):
        self._t1 = asyncio.create_task(pump())
        self._t2 = asyncio.ensure_future(self._gen)

    def push(self, item):
        with self._lock:
            self._buf.append(item)

    async def _gen(self):
        async with self._lock:
            self._buf.clear()
"""

# the async commit applier shape (ledger/committer.py): a LAZILY
# spawned apply thread draining a deque the submitter appends — the
# role must stay visible so a lock regression in the real engine can
# never go quiet.  This variant drops the lock on the submit side.
BAD_CROSS_THREAD_APPLIER = """\
import threading
from collections import deque


class ApplyEngine:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = deque()
        self._thread = None

    def submit(self, entry):
        self._queue.append(entry)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._apply_loop, daemon=True
            )
            self._thread.start()

    def _apply_loop(self):
        while True:
            with self._cond:
                while not self._queue:
                    self._cond.wait()
                self._queue.popleft()
"""


class TestCrossThreadState:
    def _rule(self):
        from fabric_tpu.analysis.rules.cross_thread_state import (
            CrossThreadStateRule,
        )

        return CrossThreadStateRule()

    def test_flags_unlocked_deque_shape(self, tmp_path):
        got = run_rule(tmp_path, self._rule(),
                       {"mod.py": BAD_CROSS_THREAD})
        assert [(f.rule, f.path, f.line) for f in got] == [
            ("FT017", "mod.py", 16),
        ]
        assert "_pending" in got[0].message
        assert "thread(_run)" in got[0].message

    def test_flags_executor_worker_role(self, tmp_path):
        got = run_rule(tmp_path, self._rule(),
                       {"mod.py": BAD_CROSS_THREAD_EXECUTOR})
        assert [(f.line,) for f in got] == [(19,)]
        assert "_stats" in got[0].message
        assert "worker(_work)" in got[0].message

    def test_flags_asyncio_task_role(self, tmp_path):
        got = run_rule(tmp_path, self._rule(),
                       {"mod.py": BAD_CROSS_THREAD_TASK})
        assert [(f.rule, f.path, f.line) for f in got] == [
            ("FT017", "mod.py", 14),
        ]
        assert "_buf" in got[0].message
        assert "task(_drain)" in got[0].message

    def test_flags_run_in_executor_role(self, tmp_path):
        got = run_rule(tmp_path, self._rule(),
                       {"mod.py": BAD_CROSS_THREAD_RUN_IN_EXECUTOR})
        assert [(f.line,) for f in got] == [(18,)]
        assert "_totals" in got[0].message
        assert "executor(_work)" in got[0].message

    def test_asyncio_unprovable_shapes_silent(self, tmp_path):
        assert run_rule(
            tmp_path, self._rule(),
            {"mod.py": CLEAN_CROSS_THREAD_TASK_UNKNOWN},
        ) == []

    def test_flags_lazy_applier_thread_role(self, tmp_path):
        # the commit-engine applier shape: lazy spawn inside the very
        # method that races
        got = run_rule(tmp_path, self._rule(),
                       {"mod.py": BAD_CROSS_THREAD_APPLIER})
        assert [(f.rule, f.path, f.line) for f in got] == [
            ("FT017", "mod.py", 12),
        ]
        assert "_queue" in got[0].message
        assert "thread(_apply_loop)" in got[0].message

    def test_real_commit_engine_clean(self, tmp_path):
        # the REAL AsyncApplyEngine must scan clean under the extended
        # role inference — its one-condition discipline is the fixture
        # above with the lock present on both sides
        import pathlib

        src = (pathlib.Path(__file__).resolve().parent.parent
               / "fabric_tpu" / "ledger" / "committer.py").read_text()
        assert run_rule(tmp_path, self._rule(),
                        {"committer.py": src}) == []

    def test_lock_held_paths_clean(self, tmp_path):
        assert run_rule(tmp_path, self._rule(),
                        {"mod.py": CLEAN_CROSS_THREAD}) == []

    def test_unknown_provenance_and_no_locks_clean(self, tmp_path):
        assert run_rule(tmp_path, self._rule(),
                        {"mod.py": CLEAN_CROSS_THREAD_UNKNOWN}) == []

    def test_test_code_exempt(self, tmp_path):
        got = run_rule(tmp_path, self._rule(), {
            "test_mod.py": BAD_CROSS_THREAD,
            "tests/helper.py": BAD_CROSS_THREAD,
            "conftest.py": BAD_CROSS_THREAD,
        })
        assert got == []

    def test_noqa_suppresses(self, tmp_path):
        src = BAD_CROSS_THREAD.replace(
            "        self._pending.append(item)",
            "        self._pending.append(item)"
            "  # fabtpu: noqa(FT017)",
        )
        assert run_rule(tmp_path, self._rule(), {"mod.py": src}) == []


# -- FT018 lost-update ------------------------------------------------------

# the PR-12 lost-actuation class: three unlocked read-modify-write
# shapes of attrs the class reads under its lock in snapshot()
BAD_LOST_UPDATE = """\
import threading


class Pilot:
    def __init__(self):
        self._lock = threading.Lock()
        self._knob = 0
        self._limit = None

    def snapshot(self):
        with self._lock:
            return (self._knob, self._limit)

    def actuate(self, step):
        self._knob += step

    def rescale(self):
        cur = self._knob
        self._knob = cur * 2

    def ensure_limit(self):
        if self._limit is None:
            self._limit = 16
"""

CLEAN_LOST_UPDATE = """\
import threading


class SafePilot:
    def __init__(self):
        self._lock = threading.Lock()
        self._knob = 0
        self._limit = None

    def snapshot(self):
        with self._lock:
            return (self._knob, self._limit)

    def actuate(self, step):
        with self._lock:
            self._knob += step

    def bump(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self._knob += 1

    def rebound(self):
        cur = 0
        cur = self._knob
        self._knob = cur * 2

    def ensure_limit(self):
        if self._limit is None:
            with self._lock:
                if self._limit is None:
                    self._limit = 16


class NoLocks:
    def __init__(self):
        self._n = 0

    def inc(self):
        self._n += 1
"""


class TestLostUpdate:
    def _rule(self):
        from fabric_tpu.analysis.rules.lost_update import LostUpdateRule

        return LostUpdateRule()

    def test_flags_all_three_rmw_shapes(self, tmp_path):
        got = run_rule(tmp_path, self._rule(),
                       {"mod.py": BAD_LOST_UPDATE})
        assert [(f.rule, f.line) for f in got] == [
            ("FT018", 15),   # augmented assign
            ("FT018", 19),   # read-then-store through a local
            ("FT018", 23),   # check-then-act
        ]
        assert "augmented assign" in got[0].message
        assert "read-then-store" in got[1].message
        assert "check-then-act" in got[2].message

    def test_clean_shapes_never_flag(self, tmp_path):
        # locked RMW, the *_locked helper (entry-held propagation),
        # a POISONED local (reassigned → unknown provenance), the
        # double-checked idiom, and a lock-free class
        assert run_rule(tmp_path, self._rule(),
                        {"mod.py": CLEAN_LOST_UPDATE}) == []

    def test_test_code_exempt(self, tmp_path):
        got = run_rule(tmp_path, self._rule(), {
            "test_mod.py": BAD_LOST_UPDATE,
            "tests/helper.py": BAD_LOST_UPDATE,
            "conftest.py": BAD_LOST_UPDATE,
        })
        assert got == []

    def test_noqa_suppresses_one_site(self, tmp_path):
        src = BAD_LOST_UPDATE.replace(
            "        self._knob += step",
            "        self._knob += step  # fabtpu: noqa(FT018)",
        )
        got = run_rule(tmp_path, self._rule(), {"mod.py": src})
        assert [(f.line,) for f in got] == [(19,), (23,)]


# -- FT019 unruled-sharding -------------------------------------------------

# hand-built layouts at a dispatch site: the exact ad-hoc shape the
# partition-rule registry (parallel/mesh.py) replaced
BAD_UNRULED = """\
import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def put(mesh, arr):
    spec = P("data")
    s = NamedSharding(mesh, spec)
    return jax.device_put(arr, s)


def raw(mesh):
    return jax.sharding.PositionalSharding(mesh.devices)
"""

# the ruled path: layouts come from the registry, plain device_put
# (no sharding construction) stays untouched
CLEAN_UNRULED = """\
import jax


def put(mesh, arr):
    from fabric_tpu.parallel.mesh import shard

    return shard(mesh, "verify_lanes", arr)


def replicate(arr):
    return jax.device_put(arr)


def local_helper(mesh, spec):
    def NamedSharding(m, s):
        return (m, s)

    return NamedSharding(mesh, spec)
"""


class TestUnruledSharding:
    def _rule(self):
        from fabric_tpu.analysis.rules.unruled_sharding import (
            UnruledShardingRule,
        )

        return UnruledShardingRule()

    def test_flags_raw_constructors(self, tmp_path):
        got = run_rule(tmp_path, self._rule(),
                       {"fabric_tpu/peer/launcher.py": BAD_UNRULED})
        assert [(f.rule, f.line) for f in got] == [
            ("FT019", 6),    # P("data") — the PartitionSpec alias
            ("FT019", 7),    # NamedSharding(...)
            ("FT019", 12),   # jax.sharding.PositionalSharding(...)
        ]
        assert "sharding_for" in got[0].message

    def test_ruled_path_never_flags(self, tmp_path):
        # registry calls, bare device_put, and a same-named LOCAL
        # helper (import-aware resolution must not match it)
        assert run_rule(
            tmp_path, self._rule(),
            {"fabric_tpu/peer/launcher.py": CLEAN_UNRULED},
        ) == []

    def test_partition_layer_exempt(self, tmp_path):
        # fabric_tpu/parallel/ IS the layer raw constructors belong in
        assert run_rule(
            tmp_path, self._rule(),
            {"fabric_tpu/parallel/mesh.py": BAD_UNRULED},
        ) == []

    def test_out_of_package_exempt(self, tmp_path):
        # bench/scripts drivers are not part of the dispatch surface
        assert run_rule(
            tmp_path, self._rule(),
            {"scripts/driver.py": BAD_UNRULED,
             "bench.py": BAD_UNRULED},
        ) == []

    def test_test_code_exempt(self, tmp_path):
        assert run_rule(
            tmp_path, self._rule(),
            {"tests/test_launcher.py": BAD_UNRULED},
        ) == []

    def test_noqa_suppresses_one_site(self, tmp_path):
        src = BAD_UNRULED.replace(
            "    s = NamedSharding(mesh, spec)",
            "    s = NamedSharding(mesh, spec)  # fabtpu: noqa(FT019)",
        )
        got = run_rule(tmp_path, self._rule(),
                       {"fabric_tpu/peer/launcher.py": src})
        assert [f.line for f in got] == [6, 12]


# -- FT020 clock-mixing -----------------------------------------------------

# the milestone-delta corruption shape: one end read from the wall
# clock, the other from the monotonic clock — plausible arithmetic,
# meaningless number (different epochs + NTP slew)
BAD_CLOCK_MIX = """\
import time
from time import perf_counter as pc


def flow_delta(entry):
    start = time.time()
    d1 = time.monotonic() - start
    d2 = float(time.time()) - pc()
    return d1, d2
"""

CLEAN_CLOCK_MIX = """\
import time


def stamp(row):
    # same-domain durations, wall-clock METADATA (no subtraction
    # against a monotonic reading), and unprovable operands all stay
    # silent
    t0 = time.perf_counter()
    dur = time.perf_counter() - t0
    row["wall_s"] = time.time()
    age = time.time() - row.get("wall_s", 0.0)
    mixed_unknown = time.monotonic() - row["t0"]
    return dur, age, mixed_unknown
"""


class TestClockMixing:
    def _rule(self):
        from fabric_tpu.analysis.rules.clock_mixing import ClockMixingRule

        return ClockMixingRule()

    def test_flags_cross_domain_subtraction(self, tmp_path):
        got = run_rule(
            tmp_path, self._rule(),
            {"fabric_tpu/observe/timing.py": BAD_CLOCK_MIX},
        )
        assert [(f.rule, f.line) for f in got] == [
            ("FT020", 7),   # time.monotonic() - wall-derived local
            ("FT020", 8),   # wrapped wall - aliased perf_counter
        ]
        assert "monotonic" in got[0].message
        assert "duration" in got[0].message

    def test_same_domain_and_unknown_stay_silent(self, tmp_path):
        assert run_rule(
            tmp_path, self._rule(),
            {"fabric_tpu/observe/timing.py": CLEAN_CLOCK_MIX},
        ) == []

    def test_rebound_local_poisons(self, tmp_path):
        # a start that is assigned twice is unprovable — silence
        src = BAD_CLOCK_MIX.replace(
            "    start = time.time()",
            "    start = time.time()\n    start = entry",
        ).replace("    d2 = float(time.time()) - pc()\n", "")
        assert run_rule(
            tmp_path, self._rule(),
            {"fabric_tpu/observe/timing.py": src},
        ) == []

    def test_out_of_package_exempt(self, tmp_path):
        # bench/scripts drivers may stamp wall-clock metadata freely
        assert run_rule(
            tmp_path, self._rule(),
            {"scripts/driver.py": BAD_CLOCK_MIX,
             "bench.py": BAD_CLOCK_MIX},
        ) == []

    def test_test_code_exempt(self, tmp_path):
        assert run_rule(
            tmp_path, self._rule(),
            {"tests/test_timing.py": BAD_CLOCK_MIX},
        ) == []

    def test_noqa_suppresses_one_site(self, tmp_path):
        src = BAD_CLOCK_MIX.replace(
            "    d1 = time.monotonic() - start",
            "    d1 = time.monotonic() - start  # fabtpu: noqa(FT020)",
        )
        got = run_rule(
            tmp_path, self._rule(),
            {"fabric_tpu/observe/timing.py": src},
        )
        assert [f.line for f in got] == [8]


# -- the ported-rule differential pin ---------------------------------------


def test_ported_rules_match_pre_port_pin(tmp_path):
    """FT013/FT014/FT015/FT016 were rewritten onto the shared
    provenance engine; this pin (captured from the pre-port rules on
    the same fixtures) proves the port changed NOTHING — path, line,
    col, severity, and message, byte for byte."""
    import json

    from fabric_tpu.analysis import analyze_paths as run
    from fabric_tpu.analysis import all_rules

    pin_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "data", "ported_rules_pin.json",
    )
    with open(pin_path, encoding="utf-8") as f:
        pin = json.load(f)

    fixtures = {
        "FT013": {"bad.py": BAD_LABELS, "clean.py": CLEAN_LABELS},
        "FT014": {"bad.py": BAD_NONCES, "clean.py": CLEAN_NONCES},
        "FT015": {"bad.py": BAD_RESIDENT,
                  "alias.py": BAD_RESIDENT_ALIAS,
                  "clean.py": CLEAN_RESIDENT,
                  "shadow.py": CLEAN_RESIDENT_SHADOW},
        "FT016": {"bad.py": BAD_UNATTRIBUTED,
                  "alias.py": BAD_UNATTRIBUTED_ALIASES,
                  "clean.py": CLEAN_UNATTRIBUTED,
                  "shadow.py": CLEAN_UNATTRIBUTED_SHADOW},
    }
    rules = {r.id: r for r in all_rules()}
    assert set(fixtures) == set(pin)
    for rid, files in fixtures.items():
        d = tmp_path / rid
        for rel, src in files.items():
            p = d / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        res = run([str(d)], root=str(d), rules=[rules[rid]],
                  baseline=None)
        got = sorted(
            [f.path, f.line, f.col, f.severity, f.message]
            for f in res.findings
        )
        assert got == sorted(pin[rid]), (
            f"{rid}: ported rule drifted from the pre-port pin"
        )


# -- registry-wide meta-battery ---------------------------------------------

# one representative bad + clean fixture per registered rule; the
# meta-test below proves EVERY rule has a working fixture pair,
# honors # fabtpu: noqa(FTnnn) at its finding lines, and exempts
# test paths engine-wide
_META_MUTABLE_DEFAULT = """\
import jax


@jax.jit
def f(x, opts={}):
    return x
"""

_META_JIT_CLEAN = """\
import jax


@jax.jit
def kernel(x, y):
    local = {}
    local["t"] = x + y
    return local["t"] * 2
"""

_META_RETRACE_CLEAN = """\
import jax

SCALE = (1.0, 2.0)


@jax.jit
def f(x, n=4):
    return x * SCALE[0] + n
"""

_META_SYNC_FILES = {
    "peer/validator.py": """\
    from ops import helper


    def validate(block):
        return helper(block)
    """,
    "ops.py": """\
    import jax


    def helper(x):
        y = jax.device_get(x)
        return y
    """,
}

_META_SYNC_CLEAN = {
    "peer/validator.py": """\
    def validate(block):
        return block
    """,
}

_META_SELF_DEADLOCK = """\
def nested(self):
    with self._lock:
        with self._lock:
            pass
"""

_META_LOCK_CLEAN = """\
def flush(self):
    with self._lock:
        return self.queue.copy()
"""

_META_SWALLOW = """\
def f():
    try:
        cleanup()
    except Exception:
        pass
"""

_META_SWALLOW_CLEAN = """\
import logging

log = logging.getLogger(__name__)


def g(x):
    try:
        return parse(x)
    except Exception as e:
        log.warning("parse failed: %s", e)
        return False
"""

_META_ENV_CLEAN = """\
from dataclasses import dataclass


@dataclass
class Holder:
    payload: dict | None = None
"""

_META_TASK_CLEAN = """\
import asyncio


async def run(coro):
    t = asyncio.create_task(coro())
    try:
        return await asyncio.wait_for(asyncio.shield(t), 1.0)
    finally:
        if not t.done():
            t.cancel()
"""


def _meta_fixtures():
    kernel_caller_clean = BAD_CALLER.replace("np.int64", "np.int32").replace(
        "np.arange(n)[:, None]",
        "np.arange(n, dtype=np.int32)[:, None]",
    )
    bad = {
        "FT001": {"mod.py": BAD_JIT},
        "FT002": {"mod.py": _META_MUTABLE_DEFAULT},
        "FT003": dict(_META_SYNC_FILES),
        "FT004": {"mod.py": _META_SELF_DEADLOCK},
        "FT005": {"mod.py": _META_SWALLOW},
        "FT006": {"mod.py": PRE_FIX_ENV},
        "FT007": {"ops/kern.py": KERNEL_MOD, "peer/caller.py": BAD_CALLER},
        "FT008": {"mod.py": BAD_TASK_LEAK},
        "FT009": {"mod.py": BAD_WAITS},
        "FT010": {"mod.py": BAD_SPANS},
        "FT011": {"mod.py": BAD_BUFFER},
        "FT012": {"mod.py": BAD_PURGE},
        "FT013": {"mod.py": BAD_LABELS},
        "FT014": {"mod.py": BAD_NONCES},
        "FT015": {"mod.py": BAD_RESIDENT},
        "FT016": {"mod.py": BAD_UNATTRIBUTED},
        "FT017": {"mod.py": BAD_CROSS_THREAD},
        "FT018": {"mod.py": BAD_LOST_UPDATE},
        "FT019": {"fabric_tpu/peer/launcher.py": BAD_UNRULED},
        "FT020": {"fabric_tpu/observe/timing.py": BAD_CLOCK_MIX},
    }
    clean = {
        "FT001": {"mod.py": _META_JIT_CLEAN},
        "FT002": {"mod.py": _META_RETRACE_CLEAN},
        "FT003": dict(_META_SYNC_CLEAN),
        "FT004": {"mod.py": _META_LOCK_CLEAN},
        "FT005": {"mod.py": _META_SWALLOW_CLEAN},
        "FT006": {"mod.py": _META_ENV_CLEAN},
        "FT007": {"ops/kern.py": KERNEL_MOD,
                  "peer/caller.py": kernel_caller_clean},
        "FT008": {"mod.py": _META_TASK_CLEAN},
        "FT009": {"mod.py": CLEAN_WAITS},
        "FT010": {"mod.py": CLEAN_SPANS},
        "FT011": {"mod.py": CLEAN_BUFFER},
        "FT012": {"mod.py": CLEAN_PURGE},
        "FT013": {"mod.py": CLEAN_LABELS},
        "FT014": {"mod.py": CLEAN_NONCES},
        "FT015": {"mod.py": CLEAN_RESIDENT},
        "FT016": {"mod.py": CLEAN_UNATTRIBUTED},
        "FT017": {"mod.py": CLEAN_CROSS_THREAD},
        "FT018": {"mod.py": CLEAN_LOST_UPDATE},
        "FT019": {"fabric_tpu/peer/launcher.py": CLEAN_UNRULED,
                  "scripts/driver.py": BAD_UNRULED},
        "FT020": {"fabric_tpu/observe/timing.py": CLEAN_CLOCK_MIX,
                  "scripts/driver.py": BAD_CLOCK_MIX},
    }
    return bad, clean


def _inject_noqa(files, findings, rule_id):
    """Append ``# fabtpu: noqa(rule)`` to every finding line."""
    by_path: dict[str, set] = {}
    for f in findings:
        by_path.setdefault(f.path, set()).add(f.line)
    out = {}
    for rel, src in files.items():
        src = textwrap.dedent(src)
        if rel in by_path:
            lines = src.splitlines()
            for ln in by_path[rel]:
                lines[ln - 1] += f"  # fabtpu: noqa({rule_id})"
            src = "\n".join(lines) + "\n"
        out[rel] = src
    return out


def test_registry_meta_battery(tmp_path):
    """Every registered rule: non-empty description, a bad fixture
    that fires, a clean fixture that stays silent, line-anchored
    noqa suppression, and tests/-path exemption."""
    from fabric_tpu.analysis import all_rules

    rules = all_rules()
    assert len(rules) == 20
    bad_fixtures, clean_fixtures = _meta_fixtures()
    for rule in rules:
        assert rule.description.strip(), f"{rule.id}: empty description"
        assert rule.exempt_tests, f"{rule.id}: must exempt test code"
        assert rule.id in bad_fixtures, f"{rule.id}: no bad fixture"
        assert rule.id in clean_fixtures, f"{rule.id}: no clean fixture"

        bad = run_rule(tmp_path / rule.id / "bad", rule,
                       bad_fixtures[rule.id])
        assert bad, f"{rule.id}: bad fixture produced no findings"
        assert all(f.rule == rule.id for f in bad)

        clean = run_rule(tmp_path / rule.id / "clean", rule,
                         clean_fixtures[rule.id])
        assert clean == [], (
            f"{rule.id}: clean fixture flagged: "
            + "; ".join(f.render() for f in clean)
        )

        noqa = run_rule(
            tmp_path / rule.id / "noqa", rule,
            _inject_noqa(bad_fixtures[rule.id], bad, rule.id),
        )
        assert noqa == [], f"{rule.id}: noqa(...) not honored"

        exempt = run_rule(
            tmp_path / rule.id / "exempt", rule,
            {f"tests/{rel}": src
             for rel, src in bad_fixtures[rule.id].items()},
        )
        assert exempt == [], f"{rule.id}: tests/ paths not exempt"


# -- battery wall-time budget -----------------------------------------------


def test_battery_wall_time_budget():
    """The full 18-rule sweep of fabric_tpu/ must stay comfortably
    interactive — per-rule wall time is reported by analyze_paths so
    a quadratic regression names its culprit."""
    from fabric_tpu.analysis import all_rules

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = analyze_paths(
        [os.path.join(pkg, "fabric_tpu")], root=pkg,
        baseline=load_baseline(default_baseline_path()),
    )
    assert set(res.timings) == {r.id for r in all_rules()}
    total = sum(res.timings.values())
    worst = max(res.timings, key=res.timings.get)
    assert total < 60.0, (
        f"battery took {total:.1f}s (worst: {worst} "
        f"{res.timings[worst]:.1f}s) — a rule went quadratic"
    )


# -- CLI round-trips --------------------------------------------------------


class TestCliRoundTrips:
    def _write(self, d, files):
        for rel, src in files.items():
            p = d / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        return str(d)

    def test_exit_codes(self, tmp_path, capsys):
        from fabric_tpu.analysis.__main__ import main

        clean = self._write(tmp_path / "clean", {"mod.py": "X = 1\n"})
        bad = self._write(tmp_path / "bad", {"mod.py": BAD_JIT})
        assert main([clean, "--no-baseline"]) == 0
        assert main([bad, "--no-baseline"]) == 1
        assert main([bad, "--rule", "FTnope"]) == 2
        capsys.readouterr()

    def test_json_reports_per_rule_timings(self, tmp_path, capsys):
        import json

        from fabric_tpu.analysis.__main__ import main

        bad = self._write(tmp_path / "bad", {"mod.py": BAD_JIT})
        rc = main([bad, "--json", "--no-baseline", "--rule", "FT001"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert list(out["timings"]) == ["FT001"]
        assert out["timings"]["FT001"] >= 0.0
        assert out["findings"][0]["rule"] == "FT001"

    def test_sarif_round_trip(self, tmp_path, capsys, monkeypatch):
        import json

        import fabric_tpu.analysis.__main__ as cli
        from fabric_tpu.analysis.__main__ import main

        bad = self._write(tmp_path / "bad", {"mod.py": BAD_JIT})
        monkeypatch.setattr(cli, "_repo_root", lambda: bad)
        rc = main([bad, "--sarif", "--no-baseline", "--rule", "FT001"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "fabric_tpu.analysis"
        assert [r["id"] for r in run["tool"]["driver"]["rules"]] == [
            "FT001",
        ]
        res = run["results"][0]
        assert res["ruleId"] == "FT001"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "mod.py"
        assert loc["region"]["startLine"] == 8
        # --sarif and --json together is a usage error
        assert main([bad, "--sarif", "--json"]) == 2

    def test_stale_baseline_fails_and_fix_rewrites(self, tmp_path, capsys,
                                                   monkeypatch):
        import json

        import fabric_tpu.analysis.__main__ as cli
        from fabric_tpu.analysis.__main__ import main

        clean = self._write(tmp_path / "clean", {"mod.py": "X = 1\n"})
        bad = self._write(tmp_path / "bad", {"mod.py": BAD_JIT})
        monkeypatch.setattr(cli, "_repo_root", lambda: bad)
        bfile = tmp_path / "baseline.json"
        bfile.write_text(json.dumps({"findings": [
            {"rule": "FT001", "path": "gone.py", "message": "old"},
        ]}))

        # a baseline entry nothing matches is a FAILURE, not a shrug
        rc = main([clean, "--baseline", str(bfile)])
        err = capsys.readouterr().err
        assert rc == 1
        assert "STALE" in err and "gone.py" in err

        # --fix-baseline rewrites from the live run and exits 0
        rc = main([bad, "--baseline", str(bfile), "--fix-baseline"])
        capsys.readouterr()
        assert rc == 0
        rewritten = json.loads(bfile.read_text())
        assert [e["rule"] for e in rewritten["findings"]] == ["FT001"]
        assert rewritten["findings"][0]["path"] == "mod.py"

        # the rewritten baseline absorbs the finding
        rc = main([bad, "--baseline", str(bfile)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "1 baselined" in out

    def test_changed_mode_analyzes_only_the_diff(self, tmp_path, capsys,
                                                 monkeypatch):
        import subprocess

        import fabric_tpu.analysis.__main__ as cli

        repo = tmp_path / "repo"
        repo.mkdir()
        git = ["git", "-C", str(repo),
               "-c", "user.email=ci@example.invalid",
               "-c", "user.name=ci"]
        subprocess.run(git[:3] + ["init", "-q"], check=True)
        (repo / "clean.py").write_text("X = 1\n")
        subprocess.run(git + ["add", "."], check=True)
        subprocess.run(git + ["commit", "-qm", "seed"], check=True)

        monkeypatch.setattr(cli, "_repo_root", lambda: str(repo))

        # an uncommitted bad module is picked up via the diff
        (repo / "bad.py").write_text(textwrap.dedent(BAD_JIT))
        rc = cli.main(["--changed", "--no-baseline", str(repo)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "bad.py:8" in out

        # committed → nothing differs from HEAD → clean exit
        subprocess.run(git + ["add", "."], check=True)
        subprocess.run(git + ["commit", "-qm", "more"], check=True)
        rc = cli.main(["--changed", "--no-baseline", str(repo)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "0 finding(s)" in out

"""Chain replay + snapshot join battery (fabric_tpu/peer/replay.py,
ledger/snapshot.py) — crypto-free.

Layers:

1. replay ≡ serial oracle differential: a dependent toy chain staged
   into a real ``KVLedger``/``BlockStore``, replayed through
   ``ReplayDriver`` at depths 1/2/4 — state digest, commit hash and
   height identical to a no-pipeline serial validate+commit loop over
   the same store;
2. kill-mid-replay chaos: a commit-stage crash stops the driver with
   the destination at the exact failed height; a fresh ``replay_into``
   resumes from there and every block commits EXACTLY once (the
   ledger's in-order check makes a double-apply structurally
   impossible — pinned by tracking committed block numbers);
3. snapshot-then-replay differential under the async committer ON and
   OFF: export at a mid-chain boundary, bootstrap a fresh ledger,
   replay the suffix — byte-identical (digest + commit hash) to the
   replay-from-genesis oracle;
4. resident-cache warm off snapshot key ranges: free-slot-only bulk
   admission, zero evictions, warmed keys serve lookup hits;
5. the autopilot throughput hold: shed/weight overload rules are
   suppressed while a replay holds the pilot, re-arm on release.
"""

import json
import os
from dataclasses import dataclass

import numpy as np
import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.control import Autopilot, Signals
from fabric_tpu.ledger import snapshot as snap
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.observe import Tracer
from fabric_tpu.ops_metrics import Registry
from fabric_tpu.peer.replay import (
    ReplayCheckpoint,
    ReplayDriver,
    replay_into,
)
from fabric_tpu.state import ResidencyManager

N_BLOCKS = 8
N_TX = 5


# ---------------------------------------------------------------------------
# the toy validator (the test_resident.py host-oracle wire form)


@dataclass
class _Ptx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class _Pend:
    block: object
    txs: list
    raw: list
    overlay: object
    extra: object
    hd_bytes: bytes | None = None

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ToyValidator:
    """Crypto-free pipeline validator: JSON txs {"id", "reads",
    "writes", "deletes"}, MVCC against the ledger state with the
    in-flight overlay honored."""

    VALID, DUP, MVCC = 0, 2, 11

    def __init__(self, state):
        self.state = state

    def preprocess(self, block):
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        txs = [_Ptx(t["id"], i) for i, t in enumerate(raw)]
        return _Pend(block, txs, raw, overlay, extra_txids)

    def _version(self, pr, over):
        if pr in over:
            return over[pr]
        vv = self.state.get_state(*pr)
        return None if vv is None else tuple(vv.version)

    def validate_finish(self, pend):
        over = {}
        if pend.overlay is not None:
            for pr, vv in pend.overlay.updates.items():
                over[pr] = None if vv.value is None else tuple(vv.version)
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for ptx, t in zip(pend.txs, pend.raw):
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            ok = all(
                self._version(("cc", k), over)
                == (None if want is None else tuple(want))
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put("cc", k, val.encode(), (num, ptx.idx))
            for k in t.get("deletes", ()):
                batch.delete("cc", k, (num, ptx.idx))
        return bytes(codes), batch, []


def _build_chain(n_blocks=N_BLOCKS, n_tx=N_TX):
    """Dependent stream: hot re-reads, k→k+1 reads crossing the
    pipeline window, a stale lane per block (non-trivial filters) and
    deletes."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"t{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if i == 0:
                t["reads"] = {"hot": [0, 0] if n else None}
                if n == 0:
                    t["writes"]["hot"] = "h"
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [n - 1, 1]}
            if n > 1 and i == 3:
                t["reads"] = {f"k{n-2}_3": [0, 0]}  # stale → MVCC
            if n > 0 and i == 4:
                t["deletes"] = [f"k{n-1}_4"]
                t["reads"] = {f"k{n-1}_4": [n - 1, 4]}
            txs.append(t)
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _commit_fn(ledger, log=None):
    def commit(res):
        ledger.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)
        if log is not None:
            log.append(res.block.header.number)

    return commit


@pytest.fixture()
def source(tmp_path):
    """The staged source chain: a real KVLedger whose BlockStore every
    replay below reads (fresh proto decodes per iteration — the
    in-memory blocks are mutated by their one staging commit)."""
    lg = KVLedger(str(tmp_path / "src"), state_db=MemVersionedDB())
    drv = ReplayDriver(ToyValidator(lg.state), _commit_fn(lg), depth=2)
    drv.run(iter(_build_chain()))
    assert lg.height == N_BLOCKS
    yield lg
    lg.close()


def _ident(lg):
    return lg.state_digest(), lg.commit_hash, lg.height


# ---------------------------------------------------------------------------
# 1. replay ≡ serial oracle


class TestReplayDifferential:
    def _serial_oracle(self, source, tmp_path):
        lg = KVLedger(str(tmp_path / "oracle"), state_db=MemVersionedDB())
        v = ToyValidator(lg.state)
        for blk in source.blocks.iter_blocks(0):
            pend = v.validate_launch(blk)
            codes, batch, hist = v.validate_finish(pend)
            lg.commit_block(blk, codes, batch, hist, None,
                            [(p.txid, p.idx) for p in pend.txs], None)
        return lg

    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_depths_match_serial(self, source, tmp_path, depth):
        oracle = self._serial_oracle(source, tmp_path)
        lg = KVLedger(str(tmp_path / f"d{depth}"),
                      state_db=MemVersionedDB())
        stats = replay_into(lg, ToyValidator(lg.state), source.blocks,
                            depth=depth)
        assert stats["blocks"] == N_BLOCKS
        assert stats["resumed_from"] == 0
        assert stats["submitted"] == N_BLOCKS
        # commit hash chains over every tx_filter: equality pins the
        # per-block verdicts, not just the end state
        assert _ident(lg) == _ident(oracle)
        lg.close()
        oracle.close()

    def test_stats_and_checkpoint(self, source, tmp_path):
        ck = str(tmp_path / "ck.json")
        lg = KVLedger(str(tmp_path / "dest"), state_db=MemVersionedDB())
        stats = replay_into(lg, ToyValidator(lg.state), source.blocks,
                            depth=2, checkpoint=ck, checkpoint_every=3)
        assert stats["txs_valid"] == sum(
            1 for b in range(N_BLOCKS) for _ in range(N_TX)
        ) - 6  # one MVCC-stale lane per block from #2 on
        assert ReplayCheckpoint(ck).load() == N_BLOCKS
        # replaying an up-to-date ledger is a no-op, not an error
        again = replay_into(lg, ToyValidator(lg.state), source.blocks,
                            depth=2)
        assert again["blocks"] == 0 and again["resumed_from"] == N_BLOCKS
        assert lg.height == N_BLOCKS
        lg.close()

    def test_checkpoint_corrupt_file_loads_none(self, tmp_path):
        p = tmp_path / "ck.json"
        p.write_text("{not json")
        assert ReplayCheckpoint(str(p)).load() is None
        ReplayCheckpoint(str(p)).save(7)
        assert ReplayCheckpoint(str(p)).load() == 7


# ---------------------------------------------------------------------------
# 2. kill mid-replay, resume, no double-apply


class TestKillResume:
    @pytest.mark.parametrize("kill_at", [2, 5])
    def test_crash_resume_exactly_once(self, source, tmp_path, kill_at):
        lg = KVLedger(str(tmp_path / "dest"), state_db=MemVersionedDB())
        committed: list[int] = []
        inner = _commit_fn(lg, committed)

        def crashing(res):
            if res.block.header.number == kill_at:
                raise RuntimeError("killed mid-replay")
            inner(res)

        ck = str(tmp_path / "ck.json")
        drv = ReplayDriver(ToyValidator(lg.state), crashing, depth=2,
                           checkpoint=ck, checkpoint_every=1)
        with pytest.raises(RuntimeError, match="killed"):
            drv.run(source.blocks.iter_blocks(0), start=0)
        assert lg.height == kill_at
        # the checkpoint never runs ahead of the committed height
        saved = ReplayCheckpoint(ck).load()
        assert saved is not None and saved <= kill_at

        # resume with a fresh driver off the destination height, the
        # SAME commit log spanning both passes
        drv2 = ReplayDriver(ToyValidator(lg.state),
                            _commit_fn(lg, committed), depth=2,
                            checkpoint=ck)
        stats = drv2.run(source.blocks.iter_blocks(lg.height),
                         start=lg.height)
        assert stats["blocks"] == N_BLOCKS - kill_at
        assert ReplayCheckpoint(ck).load() == N_BLOCKS
        # across crash + resume, every block committed EXACTLY once
        assert committed == list(range(N_BLOCKS))

        oracle = KVLedger(str(tmp_path / "oracle"),
                          state_db=MemVersionedDB())
        replay_into(oracle, ToyValidator(oracle.state), source.blocks,
                    depth=2)
        assert _ident(lg) == _ident(oracle)
        lg.close()
        oracle.close()

    def test_double_apply_is_structurally_impossible(self, source,
                                                     tmp_path):
        lg = KVLedger(str(tmp_path / "dest"), state_db=MemVersionedDB())
        replay_into(lg, ToyValidator(lg.state), source.blocks, depth=2)
        blk = next(iter(source.blocks.iter_blocks(3)))
        v = ToyValidator(lg.state)
        pend = v.validate_launch(blk)
        codes, batch, hist = v.validate_finish(pend)
        with pytest.raises(ValueError, match="out of order"):
            lg.commit_block(blk, codes, batch, hist, None, [], None)
        lg.close()


# ---------------------------------------------------------------------------
# 3. snapshot-then-replay ≡ replay-from-genesis (async ON and OFF)


class TestSnapshotJoinDifferential:
    @pytest.mark.parametrize("async_commit", [False, True])
    def test_join_byte_identical(self, tmp_path, async_commit):
        join_at = 4
        blocks = _build_chain()
        src = KVLedger(str(tmp_path / "src"), state_db=MemVersionedDB(),
                       async_commit=async_commit)
        drv = ReplayDriver(ToyValidator(src.state), _commit_fn(src),
                           depth=2)
        drv.run(iter(blocks[:join_at]))
        snap_dir = str(tmp_path / "snap")
        meta = snap.generate_snapshot(src, snap_dir, channel_id="t")
        # the export records the boundary height AND the exporter's
        # recovery anchor (drained first under the async engine)
        assert meta["height"] == join_at
        assert meta["state_savepoint"] is not None
        ReplayDriver(ToyValidator(src.state), _commit_fn(src),
                     depth=2).run(iter(blocks), start=src.height)
        assert src.height == N_BLOCKS

        join, jmeta = snap.create_from_snapshot(
            snap_dir, str(tmp_path / "join"), state_db=MemVersionedDB(),
            async_commit=async_commit,
        )
        assert jmeta["height"] == join_at
        js = replay_into(join, ToyValidator(join.state), src.blocks,
                         depth=2)
        assert js["resumed_from"] == join_at
        assert js["blocks"] == N_BLOCKS - join_at

        full = KVLedger(str(tmp_path / "full"),
                        state_db=MemVersionedDB(),
                        async_commit=async_commit)
        replay_into(full, ToyValidator(full.state), src.blocks, depth=2)

        assert _ident(join) == _ident(full) == _ident(src)
        for lg in (src, join, full):
            lg.close()

    def test_state_digest_order_insensitive(self):
        a, b = MemVersionedDB(), MemVersionedDB()
        for db, order in ((a, (0, 1, 2)), (b, (2, 0, 1))):
            for i in order:
                batch = UpdateBatch()
                batch.put("cc", f"k{i}", b"v%d" % i, (1, i))
                db.apply_updates(batch, (1, i))
        assert snap.state_digest(a) == snap.state_digest(b)
        extra = UpdateBatch()
        extra.put("cc", "k9", b"v9", (2, 0))
        b.apply_updates(extra, (2, 0))
        assert snap.state_digest(a) != snap.state_digest(b)


# ---------------------------------------------------------------------------
# 4. resident warm off snapshot key ranges


class TestResidentWarm:
    def _triples(self, n, ns="cc"):
        return [(ns, f"w{i:04d}", (1, i)) for i in range(n)]

    def test_warm_fills_free_slots_and_serves_hits(self):
        res = ResidencyManager(slots=32, range_bits=4)
        n = res.warm(self._triples(8))
        assert n == 8
        st = res.stats()
        assert st["resident_keys"] == 8 and st["evictions_total"] == 0
        slots, table = res.lookup([("cc", "w0003"), ("cc", "w0007"),
                                   ("cc", "nope")])
        assert slots[0] >= 0 and slots[1] >= 0 and slots[2] == -1
        row = np.asarray(table)[slots[0]]
        assert row[0] == 1  # present
        assert tuple(int(x) for x in row[1:3].view(np.uint32)) == (1, 3)

    def test_warm_stops_at_capacity_without_evicting(self):
        res = ResidencyManager(slots=8, range_bits=4)
        n = res.warm(self._triples(64))
        assert 0 < n <= 8
        st = res.stats()
        assert st["evictions_total"] == 0
        assert st["resident_keys"] == n
        # a later warm of already-resident keys admits nothing new
        assert res.warm(self._triples(4)) == 0

    def test_warm_respects_limit_and_disabled(self):
        res = ResidencyManager(slots=32, range_bits=4)
        assert res.warm(self._triples(16), limit=5) == 5
        res.disable("test latch")
        assert res.warm(self._triples(16)) == 0

    def test_warm_resident_reads_snapshot(self, tmp_path):
        src = KVLedger(str(tmp_path / "src"), state_db=MemVersionedDB())
        ReplayDriver(ToyValidator(src.state), _commit_fn(src),
                     depth=2).run(iter(_build_chain(4)))
        snap_dir = str(tmp_path / "snap")
        snap.generate_snapshot(src, snap_dir, channel_id="t")
        res = ResidencyManager(slots=256, range_bits=4)
        n = snap.warm_resident(res, snap_dir)
        assert n == res.stats()["resident_keys"] > 0
        # every exported record is a lookup hit now
        recs = list(snap.iter_state_records(snap_dir))
        slots, _tbl = res.lookup([(ns, k) for ns, k, *_ in recs])
        assert all(s >= 0 for s in slots)
        assert snap.warm_resident(None, snap_dir) == 0
        src.close()


# ---------------------------------------------------------------------------
# 5. autopilot throughput hold


class _Clk:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _pilot(clk, sheds):
    return Autopilot(
        None, lambda k, v: None,
        set_shed=lambda t, on: sheds.append((t, on)),
        tracer=Tracer(ring_blocks=16, slow_factor=0, clock=clk),
        clock=clk, registry=Registry(),
        initial={"coalesce_blocks": 0, "verify_chunk": 0,
                 "pipeline_depth": 2},
    )


class TestThroughputHold:
    BURN = {("lat", "sidecar:noisy"): 9.0}

    def test_hold_suppresses_shed_release_rearms(self):
        clk, sheds = _Clk(), []
        ap = _pilot(clk, sheds)
        ap.hold_throughput()
        assert ap.throughput_mode
        assert ap.report()["throughput_mode"] is True
        # a closed-loop replay keeps queues full by design: the
        # overload rules must not fire while the hold is up
        clk.t = 20.0
        assert ap.tick(Signals(burn=self.BURN, clock_s=20.0)) is None
        assert sheds == []
        ap.release_throughput()
        assert not ap.throughput_mode
        clk.t = 40.0
        d = ap.tick(Signals(burn=self.BURN, clock_s=40.0))
        assert d is not None and d.knob == "shed"
        assert sheds == [("noisy", True)]

    def test_hold_is_refcounted(self):
        clk, sheds = _Clk(), []
        ap = _pilot(clk, sheds)
        ap.hold_throughput()
        ap.hold_throughput()
        ap.release_throughput()
        assert ap.throughput_mode  # one replay still running
        clk.t = 20.0
        assert ap.tick(Signals(burn=self.BURN, clock_s=20.0)) is None
        ap.release_throughput()
        assert not ap.throughput_mode

    def test_driver_takes_and_releases_hold(self, source, tmp_path):
        clk, sheds = _Clk(), []
        ap = _pilot(clk, sheds)
        lg = KVLedger(str(tmp_path / "dest"), state_db=MemVersionedDB())
        seen = []

        def probe(res):
            seen.append(ap.throughput_mode)
            _commit_fn(lg)(res)

        ReplayDriver(ToyValidator(lg.state), probe, depth=2,
                     autopilot=ap).run(source.blocks.iter_blocks(0))
        assert seen and all(seen)  # held for every commit...
        assert not ap.throughput_mode  # ...released at the end
        lg.close()

    def test_hold_released_even_when_replay_crashes(self, source,
                                                    tmp_path):
        clk, sheds = _Clk(), []
        ap = _pilot(clk, sheds)
        lg = KVLedger(str(tmp_path / "dest"), state_db=MemVersionedDB())

        def boom(res):
            raise RuntimeError("commit exploded")

        drv = ReplayDriver(ToyValidator(lg.state), boom, depth=2,
                           autopilot=ap)
        with pytest.raises(RuntimeError, match="exploded"):
            drv.run(source.blocks.iter_blocks(0))
        assert not ap.throughput_mode
        lg.close()

"""Pipelined commit path: validate_launch/validate_finish with the
predecessor-overlay, in-flight dup-txid checks, and the committer-thread
overlap — the depth-2 pipeline bench.py drives, pinned against the
serial validate() verdicts."""

import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.validator import BlockValidator, NamespaceInfo, PolicyProvider
from fabric_tpu.protos import common_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL, CC = "pipechan", "pipecc"


@pytest.fixture(scope="module")
def net():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    policy = pol.from_dsl("OutOf(2, 'Org1MSP.peer', 'Org2MSP.peer')")
    return {
        "mgr": MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()}),
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "peers": [
            cryptogen.signing_identity(org1, "peer0.org1.example.com"),
            cryptogen.signing_identity(org2, "peer0.org2.example.com"),
        ],
        "prov": PolicyProvider({CC: NamespaceInfo(policy=policy)}),
    }


def _tx(net, reads=(), writes=(), deletes=(), ranges=()):
    _, _, prop = txa.create_signed_proposal(net["client"], CHANNEL, CC, [b"i"])
    tx = TxRWSet()
    ns = tx.ns_rwset(CC)
    for k, ver in reads:
        ns.reads[k] = ver
    for k, v in writes:
        ns.writes[k] = v
    for k in deletes:
        ns.writes[k] = None
    for start, end, results in ranges:
        ns.range_queries.append((start, end, list(results)))
    rw = tx.to_proto().SerializeToString()
    resps = [txa.create_proposal_response(prop, rw, e, CC) for e in net["peers"]]
    return txa.assemble_transaction(prop, resps, net["client"])


def _block(num, prev, envs, pad_net=None):
    raw = [e.SerializeToString() for e in envs]
    if pad_net is not None:
        while len(raw) < 16:  # engage the native fast path
            raw.append(_tx(
                pad_net, writes=[(f"pad{num}_{len(raw)}", b"x")]
            ).SerializeToString())
    blk = pu.new_block(num, prev)
    for r in raw:
        blk.data.data.append(r)
    return pu.finalize_block(blk)


def _state(net):
    db = MemVersionedDB()
    seed = UpdateBatch()
    seed.put(CC, "s1", b"v", (1, 0))
    seed.put(CC, "s2", b"v", (1, 0))
    seed.put(CC, "dkey", b"v", (1, 0))
    db.apply_updates(seed, (1, 0))
    return db


def test_overlay_versions_and_dup_txid(net):
    """launch(n+1) with block n's UpdateBatch as overlay (commit NOT
    yet applied) must reach the same verdicts as committing n first:
    cross-block read-your-predecessor versions, stale reads of keys a
    VALID predecessor tx rewrote, deletes, and duplicate txids."""
    env_w = _tx(net, reads=[("s1", (1, 0))], writes=[("w1", b"1"), ("s2", b"n")])
    env_del = _tx(net, deletes=["dkey"], reads=[("dkey", (1, 0))])
    b2 = _block(2, b"p2", [env_w, env_del], pad_net=net)

    # block 3: reads that depend on block 2's outcome + a replayed env
    env_ok = _tx(net, reads=[("w1", (2, 0))], writes=[("x", b"1")])
    env_stale = _tx(net, reads=[("s2", (1, 0))], writes=[("y", b"1")])
    env_gone = _tx(net, reads=[("dkey", (1, 0))], writes=[("z", b"1")])
    b3 = _block(3, b"p3", [env_ok, env_stale, env_gone, env_w], pad_net=net)

    for mode in ("overlay", "committed"):
        state = _state(net)
        v = BlockValidator(net["mgr"], net["prov"], state)
        p2 = v.validate_launch(b2)
        flt2, batch2, _ = v.validate_finish(p2)
        assert flt2[0] == C.VALID and flt2[1] == C.VALID
        if mode == "committed":
            state.apply_updates(batch2, (2, 0))
            overlay, extra = None, None
        else:
            overlay, extra = batch2, p2.txids  # commit still "in flight"
        p3 = v.validate_launch(b3, overlay=overlay, extra_txids=extra)
        flt3, _, _ = v.validate_finish(p3)
        assert flt3[0] == C.VALID, mode            # sees (2,0) via overlay
        assert flt3[1] == C.MVCC_READ_CONFLICT, mode  # s2 rewritten by b2
        assert flt3[2] == C.MVCC_READ_CONFLICT, mode  # dkey deleted by b2
        if mode == "overlay":
            assert flt3[3] == C.DUPLICATE_TXID     # via extra_txids
        # committed mode: without a block store the replayed env is not
        # detectable — the store-backed path is covered in test_e2e


def test_overlay_range_phantom(net):
    """A key written by the in-flight predecessor inside a recorded
    range (and absent from its results) must yield
    PHANTOM_READ_CONFLICT — the overlay arm of range re-execution."""
    env_w = _tx(net, writes=[("r5", b"new")])
    b2 = _block(2, b"p2", [env_w], pad_net=net)
    env_rq = _tx(
        net, writes=[("q", b"1")],
        ranges=[("r0", "r9", [("r1", (1, 0))])],  # r5 not in results
    )
    env_rq_ok = _tx(
        net, writes=[("q2", b"1")],
        ranges=[("t0", "t9", [])],  # disjoint range: unaffected
    )
    b3 = _block(3, b"p3", [env_rq, env_rq_ok], pad_net=net)

    state = _state(net)
    seed = UpdateBatch()
    seed.put(CC, "r1", b"v", (1, 0))
    state.apply_updates(seed, (1, 0))
    v = BlockValidator(net["mgr"], net["prov"], state)
    p2 = v.validate_launch(b2)
    flt2, batch2, _ = v.validate_finish(p2)
    assert flt2[0] == C.VALID
    p3 = v.validate_launch(b3, overlay=batch2, extra_txids=p2.txids)
    flt3, _, _ = v.validate_finish(p3)
    assert flt3[0] == C.PHANTOM_READ_CONFLICT
    assert flt3[1] == C.VALID


def test_pipelined_stream_matches_serial(net):
    """Full depth-2 pipelined drive (prefetch + committer threads, as
    in bench.py) over a dependent stream — filters and final state must
    equal the serial validate()+commit run.  Blocks with range queries
    ride along, exercising the state-DB iteration lock against the
    concurrent apply_updates."""
    def build_blocks():
        blocks, prev = [], b"genesis"
        for n in range(2, 8):
            envs = [
                _tx(net, reads=[(f"k{n-1}", (n - 1, 0))] if n > 2 else (),
                    writes=[(f"k{n}", b"v")]),
                _tx(net, writes=[(f"m{n}", b"v")],
                    ranges=[(f"k{n-1}", f"k{n-1}~", [])] if n % 2 == 0 else ()),
            ]
            blk = _block(n, prev, envs, pad_net=net)
            prev = pu.block_header_hash(blk.header)
            blocks.append(blk)
        return blocks

    def fresh():
        state = MemVersionedDB()
        seed = UpdateBatch()
        seed.put(CC, "k1", b"v", (1, 0))
        state.apply_updates(seed, (1, 0))
        return state, BlockValidator(net["mgr"], net["prov"], state)

    blocks = build_blocks()

    # serial reference
    state_s, v_s = fresh()
    serial_filters = []
    for n, b in enumerate(blocks, start=2):
        flt, batch, _ = v_s.validate(b)
        state_s.apply_updates(batch, (n, 0))
        serial_filters.append(flt)

    # pipelined run with a real committer thread (delayed apply to
    # widen the race window the overlay must cover)
    state_p, v_p = fresh()
    filters = []
    with ThreadPoolExecutor(1) as committer:
        prev_pend = overlay = extra = None
        commit_fut = None
        prev_num = None

        def commit(batch, num):
            time.sleep(0.01)  # hold the commit in flight
            state_p.apply_updates(batch, (num, 0))

        for n, b in enumerate(blocks, start=2):
            if prev_pend is not None:
                flt, batch, _ = v_p.validate_finish(prev_pend)
                filters.append(flt)
                if commit_fut is not None:
                    commit_fut.result()
                commit_fut = committer.submit(commit, batch, prev_num)
                overlay, extra = batch, prev_pend.txids
            prev_pend = v_p.validate_launch(b, overlay=overlay, extra_txids=extra)
            prev_num = n
        flt, batch, _ = v_p.validate_finish(prev_pend)
        filters.append(flt)
        if commit_fut is not None:
            commit_fut.result()
        state_p.apply_updates(batch, (prev_num, 0))

    assert [list(f) for f in filters] == [list(f) for f in serial_filters]
    assert dict(state_p._data) == dict(state_s._data)

"""Pipelined commit path: validate_launch/validate_finish with the
predecessor-overlay, in-flight dup-txid checks, and the committer-thread
overlap — the depth-2 pipeline the production CommitPipeline
(peer/pipeline.py) drives for both the node's deliver loop and
bench.py, pinned against the serial validate() verdicts.  The
crypto-free pipeline-engine semantics live in
tests/test_commit_pipeline.py."""

import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.peer.validator import BlockValidator, NamespaceInfo, PolicyProvider
from fabric_tpu.protos import common_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL, CC = "pipechan", "pipecc"


@pytest.fixture(scope="module")
def net():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    policy = pol.from_dsl("OutOf(2, 'Org1MSP.peer', 'Org2MSP.peer')")
    return {
        "mgr": MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()}),
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "peers": [
            cryptogen.signing_identity(org1, "peer0.org1.example.com"),
            cryptogen.signing_identity(org2, "peer0.org2.example.com"),
        ],
        "prov": PolicyProvider({CC: NamespaceInfo(policy=policy)}),
    }


def _tx(net, reads=(), writes=(), deletes=(), ranges=()):
    _, _, prop = txa.create_signed_proposal(net["client"], CHANNEL, CC, [b"i"])
    tx = TxRWSet()
    ns = tx.ns_rwset(CC)
    for k, ver in reads:
        ns.reads[k] = ver
    for k, v in writes:
        ns.writes[k] = v
    for k in deletes:
        ns.writes[k] = None
    for start, end, results in ranges:
        ns.range_queries.append((start, end, list(results)))
    rw = tx.to_proto().SerializeToString()
    resps = [txa.create_proposal_response(prop, rw, e, CC) for e in net["peers"]]
    return txa.assemble_transaction(prop, resps, net["client"])


def _block(num, prev, envs, pad_net=None):
    raw = [e.SerializeToString() for e in envs]
    if pad_net is not None:
        while len(raw) < 16:  # engage the native fast path
            raw.append(_tx(
                pad_net, writes=[(f"pad{num}_{len(raw)}", b"x")]
            ).SerializeToString())
    blk = pu.new_block(num, prev)
    for r in raw:
        blk.data.data.append(r)
    return pu.finalize_block(blk)


def _state(net):
    db = MemVersionedDB()
    seed = UpdateBatch()
    seed.put(CC, "s1", b"v", (1, 0))
    seed.put(CC, "s2", b"v", (1, 0))
    seed.put(CC, "dkey", b"v", (1, 0))
    db.apply_updates(seed, (1, 0))
    return db


def test_overlay_versions_and_dup_txid(net):
    """launch(n+1) with block n's UpdateBatch as overlay (commit NOT
    yet applied) must reach the same verdicts as committing n first:
    cross-block read-your-predecessor versions, stale reads of keys a
    VALID predecessor tx rewrote, deletes, and duplicate txids."""
    env_w = _tx(net, reads=[("s1", (1, 0))], writes=[("w1", b"1"), ("s2", b"n")])
    env_del = _tx(net, deletes=["dkey"], reads=[("dkey", (1, 0))])
    b2 = _block(2, b"p2", [env_w, env_del], pad_net=net)

    # block 3: reads that depend on block 2's outcome + a replayed env
    env_ok = _tx(net, reads=[("w1", (2, 0))], writes=[("x", b"1")])
    env_stale = _tx(net, reads=[("s2", (1, 0))], writes=[("y", b"1")])
    env_gone = _tx(net, reads=[("dkey", (1, 0))], writes=[("z", b"1")])
    b3 = _block(3, b"p3", [env_ok, env_stale, env_gone, env_w], pad_net=net)

    for mode in ("overlay", "committed"):
        state = _state(net)
        v = BlockValidator(net["mgr"], net["prov"], state)
        p2 = v.validate_launch(b2)
        flt2, batch2, _ = v.validate_finish(p2)
        assert flt2[0] == C.VALID and flt2[1] == C.VALID
        if mode == "committed":
            state.apply_updates(batch2, (2, 0))
            overlay, extra = None, None
        else:
            overlay, extra = batch2, p2.txids  # commit still "in flight"
        p3 = v.validate_launch(b3, overlay=overlay, extra_txids=extra)
        flt3, _, _ = v.validate_finish(p3)
        assert flt3[0] == C.VALID, mode            # sees (2,0) via overlay
        assert flt3[1] == C.MVCC_READ_CONFLICT, mode  # s2 rewritten by b2
        assert flt3[2] == C.MVCC_READ_CONFLICT, mode  # dkey deleted by b2
        if mode == "overlay":
            assert flt3[3] == C.DUPLICATE_TXID     # via extra_txids
        # committed mode: without a block store the replayed env is not
        # detectable — the store-backed path is covered in test_e2e


def test_overlay_range_phantom(net):
    """A key written by the in-flight predecessor inside a recorded
    range (and absent from its results) must yield
    PHANTOM_READ_CONFLICT — the overlay arm of range re-execution."""
    env_w = _tx(net, writes=[("r5", b"new")])
    b2 = _block(2, b"p2", [env_w], pad_net=net)
    env_rq = _tx(
        net, writes=[("q", b"1")],
        ranges=[("r0", "r9", [("r1", (1, 0))])],  # r5 not in results
    )
    env_rq_ok = _tx(
        net, writes=[("q2", b"1")],
        ranges=[("t0", "t9", [])],  # disjoint range: unaffected
    )
    b3 = _block(3, b"p3", [env_rq, env_rq_ok], pad_net=net)

    state = _state(net)
    seed = UpdateBatch()
    seed.put(CC, "r1", b"v", (1, 0))
    state.apply_updates(seed, (1, 0))
    v = BlockValidator(net["mgr"], net["prov"], state)
    p2 = v.validate_launch(b2)
    flt2, batch2, _ = v.validate_finish(p2)
    assert flt2[0] == C.VALID
    p3 = v.validate_launch(b3, overlay=batch2, extra_txids=p2.txids)
    flt3, _, _ = v.validate_finish(p3)
    assert flt3[0] == C.PHANTOM_READ_CONFLICT
    assert flt3[1] == C.VALID


def test_pipelined_stream_matches_serial(net):
    """Full depth-2 pipelined drive (prefetch + committer threads, as
    in bench.py) over a dependent stream — filters and final state must
    equal the serial validate()+commit run.  Blocks with range queries
    ride along, exercising the state-DB iteration lock against the
    concurrent apply_updates."""
    def build_blocks():
        blocks, prev = [], b"genesis"
        for n in range(2, 8):
            envs = [
                _tx(net, reads=[(f"k{n-1}", (n - 1, 0))] if n > 2 else (),
                    writes=[(f"k{n}", b"v")]),
                _tx(net, writes=[(f"m{n}", b"v")],
                    ranges=[(f"k{n-1}", f"k{n-1}~", [])] if n % 2 == 0 else ()),
            ]
            blk = _block(n, prev, envs, pad_net=net)
            prev = pu.block_header_hash(blk.header)
            blocks.append(blk)
        return blocks

    def fresh():
        state = MemVersionedDB()
        seed = UpdateBatch()
        seed.put(CC, "k1", b"v", (1, 0))
        state.apply_updates(seed, (1, 0))
        return state, BlockValidator(net["mgr"], net["prov"], state)

    blocks = build_blocks()

    # serial reference
    state_s, v_s = fresh()
    serial_filters = []
    for n, b in enumerate(blocks, start=2):
        flt, batch, _ = v_s.validate(b)
        state_s.apply_updates(batch, (n, 0))
        serial_filters.append(flt)

    # pipelined run with a real committer thread (delayed apply to
    # widen the race window the overlay must cover)
    state_p, v_p = fresh()
    filters = []
    with ThreadPoolExecutor(1) as committer:
        prev_pend = overlay = extra = None
        commit_fut = None
        prev_num = None

        def commit(batch, num):
            time.sleep(0.01)  # hold the commit in flight
            state_p.apply_updates(batch, (num, 0))

        for n, b in enumerate(blocks, start=2):
            if prev_pend is not None:
                flt, batch, _ = v_p.validate_finish(prev_pend)
                filters.append(flt)
                if commit_fut is not None:
                    commit_fut.result()
                commit_fut = committer.submit(commit, batch, prev_num)
                overlay, extra = batch, prev_pend.txids
            prev_pend = v_p.validate_launch(b, overlay=overlay, extra_txids=extra)
            prev_num = n
        flt, batch, _ = v_p.validate_finish(prev_pend)
        filters.append(flt)
        if commit_fut is not None:
            commit_fut.result()
        state_p.apply_updates(batch, (prev_num, 0))

    assert [list(f) for f in filters] == [list(f) for f in serial_filters]
    assert dict(state_p._data) == dict(state_s._data)


def _tx_ns(net, ns_writes: dict):
    """A tx writing into explicit namespaces (e.g. _lifecycle)."""
    _, _, prop = txa.create_signed_proposal(net["client"], CHANNEL, CC, [b"i"])
    tx = TxRWSet()
    for ns_name, writes in ns_writes.items():
        ns = tx.ns_rwset(ns_name)
        for k, v in writes:
            ns.writes[k] = v
    rw = tx.to_proto().SerializeToString()
    resps = [txa.create_proposal_response(prop, rw, e, CC) for e in net["peers"]]
    return txa.assemble_transaction(prop, resps, net["client"])


def _drive_pipeline(net, blocks, prov=None, depth=2, commit_sleep=0.01):
    """Run ``blocks`` through the production CommitPipeline with a
    delayed committer (widening the race window the overlay must
    cover).  → (filters, final state dict, launch log, commit log)."""
    state = _state(net)
    v = BlockValidator(net["mgr"], prov or net["prov"], state)
    committed: list = []
    launches: list = []

    orig_launch = v.validate_launch

    def launch(b, pre=None, overlay=None, extra_txids=None):
        launches.append((
            b.header.number, overlay is not None, list(committed),
        ))
        return orig_launch(b, pre=pre, overlay=overlay,
                           extra_txids=extra_txids)

    v.validate_launch = launch

    def commit_fn(res):
        time.sleep(commit_sleep)  # hold the commit in flight
        state.apply_updates(res.batch, (res.block.header.number, 0))
        committed.append(res.block.header.number)

    filters = []
    with CommitPipeline(v, commit_fn, depth=depth) as pipe:
        for b in blocks:
            r = pipe.submit(b)
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    return filters, dict(state._data), launches, committed


def test_commit_pipeline_matches_serial(net):
    """The production CommitPipeline over a dependent stream (block
    n+1 reading a key block n wrote, range queries riding along) must
    produce the serial validate()+commit filters and state."""
    def build_blocks():
        blocks, prev = [], b"genesis"
        for n in range(2, 8):
            envs = [
                _tx(net, reads=[(f"k{n-1}", (n - 1, 0))] if n > 2 else (),
                    writes=[(f"k{n}", b"v")]),
                _tx(net, writes=[(f"m{n}", b"v")],
                    ranges=[(f"k{n-1}", f"k{n-1}~", [])] if n % 2 == 0 else ()),
            ]
            blk = _block(n, prev, envs, pad_net=net)
            prev = pu.block_header_hash(blk.header)
            blocks.append(blk)
        return blocks

    blocks = build_blocks()

    # serial reference
    state_s = _state(net)
    v_s = BlockValidator(net["mgr"], net["prov"], state_s)
    serial = []
    for n, b in enumerate(blocks, start=2):
        flt, batch, _ = v_s.validate(b)
        state_s.apply_updates(batch, (n, 0))
        serial.append((n, list(flt)))

    filters, state_p, launches, _ = _drive_pipeline(net, blocks)
    assert filters == serial
    assert state_p == dict(state_s._data)
    # depth-2 actually overlapped: every non-first launch carried the
    # predecessor's batch as overlay
    assert [ov for _, ov, _ in launches] == [False] + [True] * 5

    # serial mode through the same engine: identical verdicts, no
    # overlays anywhere
    filters1, state1, launches1, _ = _drive_pipeline(net, blocks, depth=1)
    assert filters1 == serial and state1 == state_p
    assert all(not ov for _, ov, _ in launches1)


def test_commit_pipeline_depth3_matches_serial(net):
    """Depth-3 over the FULL BlockValidator: a stream whose RW
    dependencies span BOTH in-flight predecessors (k→k+1 and k→k+2
    fresh reads, a hot key overwritten every block and read at the
    immediate predecessor's version — merged-overlay newest-wins)
    must equal the serial oracle in filters and state, and depth 4
    rides along."""
    def build_blocks(lo=2, hi=9):
        blocks, prev = [], b"genesis"
        for n in range(lo, hi):
            reads = []
            if n > lo:
                reads.append((f"k{n-1}", (n - 1, 1)))
                reads.append(("hot", (n - 1, 1)))
            if n > lo + 1:
                reads.append((f"q{n-2}", (n - 2, 1)))
            envs = [
                # reader FIRST: its hot read validates against the
                # predecessor's version, not this block's own writer
                _tx(net, reads=reads),
                _tx(net, writes=[(f"k{n}", b"v"), (f"q{n}", b"v"),
                                 ("hot", b"h%d" % n)]),
            ]
            blk = _block(n, prev, envs, pad_net=net)
            prev = pu.block_header_hash(blk.header)
            blocks.append(blk)
        return blocks

    blocks = build_blocks()

    # serial reference
    state_s = _state(net)
    v_s = BlockValidator(net["mgr"], net["prov"], state_s)
    serial = []
    for n, b in enumerate(blocks, start=2):
        flt, batch, _ = v_s.validate(b)
        state_s.apply_updates(batch, (n, 0))
        serial.append((n, list(flt)))
    # every lane VALID: the conflict chains are all fresh by design
    assert all(all(c == 0 for c in flt) for _, flt in serial)

    for depth in (3, 4):
        filters, state_p, launches, _ = _drive_pipeline(
            net, blocks, depth=depth
        )
        assert filters == serial, f"depth {depth}"
        assert state_p == dict(state_s._data), f"depth {depth}"
        assert [ov for _, ov, _ in launches] == [False] + [True] * 6


def test_commit_pipeline_depth3_merged_overlay_forced(net):
    """Deterministic merged-overlay proof on the full validator: the
    commits of BOTH predecessors are gated closed while block 4
    launches, so its k→k+1, k→k+2 and hot-key reads can resolve only
    through the merged overlay chain."""
    import threading

    blocks, prev = [], b"genesis"
    for n in (2, 3, 4):
        reads = []
        if n > 2:
            reads.append((f"k{n-1}", (n - 1, 1)))
            reads.append(("hot", (n - 1, 1)))
        if n > 3:
            reads.append((f"q{n-2}", (n - 2, 1)))
        envs = [
            _tx(net, reads=reads),
            _tx(net, writes=[(f"k{n}", b"v"), (f"q{n}", b"v"),
                             ("hot", b"h%d" % n)]),
        ]
        blk = _block(n, prev, envs, pad_net=net)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)

    state = _state(net)
    v = BlockValidator(net["mgr"], net["prov"], state)
    gate = threading.Event()
    committed: list = []

    def commit_fn(res):
        if res.block.header.number < 4:
            assert gate.wait(60.0), "commit gate never opened"
        state.apply_updates(res.batch, (res.block.header.number, 0))
        committed.append(res.block.header.number)

    filters = []
    with CommitPipeline(v, commit_fn, depth=3) as pipe:
        for b in blocks:
            r = pipe.submit(b)
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        # block 4 is launched; blocks 2 and 3 are still uncommitted
        assert committed == []
        gate.set()
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    assert committed == [2, 3, 4]
    # every read resolved fresh through the merged chain
    assert all(all(c == 0 for c in flt) for _, flt in filters)


def test_commit_pipeline_lifecycle_barrier(net):
    """A block writing ``_lifecycle`` must commit FULLY before its
    successor launches, and the successor launches with the overlay
    dropped — then pipelining resumes."""
    prov = PolicyProvider({
        CC: net["prov"].infos[CC],
        "_lifecycle": net["prov"].infos[CC],
    })
    blocks, prev = [], b"genesis"
    envs_by_n = {
        2: [_tx(net, writes=[("a2", b"v")])],
        3: [_tx_ns(net, {
            "_lifecycle": [("namespaces/fields/cc1/Definition", b"d")],
            CC: [("a3", b"v")],
        })],
        4: [_tx(net, reads=[("a3", (3, 0))], writes=[("a4", b"v")])],
        5: [_tx(net, writes=[("a5", b"v")])],
    }
    for n in range(2, 6):
        blk = _block(n, prev, envs_by_n[n], pad_net=net)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)

    filters, state_p, launches, committed = _drive_pipeline(
        net, blocks, prov=prov
    )
    assert all(c == 0 for _, flt in filters for c in flt)
    info = {n: (ov, done) for n, ov, done in launches}
    # successor of the barrier: overlay dropped AND block 3 fully
    # committed before launch
    assert info[4][0] is False
    assert 3 in info[4][1]
    # pipelining resumed after the barrier
    assert info[5][0] is True
    assert committed == [2, 3, 4, 5]


def test_commit_pipeline_resident_state_matches_serial(net):
    """ISSUE 14: the device-resident MVCC state path over the FULL
    BlockValidator ≡ the host state_fill oracle — a hot key re-read
    every block (residency hits), k→k+1 reads crossing the in-flight
    window, per-block stale lanes and deletes churning the cache —
    verdict- and state-identical at depths 2 and 3, plus an 8-slot
    eviction-churn variant."""
    from fabric_tpu.state import ResidencyManager

    def build_blocks(lo=2, hi=9):
        blocks, prev = [], b"genesis"
        for n in range(lo, hi):
            envs = [
                _tx(net, reads=[("s1", (1, 0))],
                    writes=[(f"a{n}", b"x")]),
                _tx(net,
                    reads=([(f"k{n-1}", (n - 1, 3))] if n > lo else []),
                    writes=[(f"b{n}", b"y")]),
                _tx(net, reads=[("s2", (9, 9))],
                    writes=[(f"c{n}", b"z")]),
                _tx(net, writes=[(f"k{n}", b"v")],
                    deletes=([f"k{n-2}"] if n > lo + 1 else [])),
            ]
            blk = _block(n, prev, envs, pad_net=net)
            prev = pu.block_header_hash(blk.header)
            blocks.append(blk)
        return blocks

    blocks = build_blocks()

    # serial host-oracle reference (state_resident OFF — the exact
    # existing path)
    state_s = _state(net)
    v_s = BlockValidator(net["mgr"], net["prov"], state_s)
    serial = []
    for n, b in enumerate(blocks, start=2):
        flt, batch, _ = v_s.validate(b)
        state_s.apply_updates(batch, (n, 0))
        serial.append((n, list(flt)))
    # the lanes are load-bearing: hot-hit VALID, stale MVCC, k→k+1 fresh
    for n, flt in serial:
        assert flt[0] == C.VALID
        assert flt[2] == C.MVCC_READ_CONFLICT
        if n > 2:
            assert flt[1] == C.VALID

    for depth, tiny in ((2, False), (3, False), (2, True)):
        state_p = _state(net)
        v_p = BlockValidator(
            net["mgr"], net["prov"], state_p,
            state_resident=True, state_resident_mb=1,
        )
        assert v_p.resident is not None
        if tiny:
            # eviction churn: an 8-slot table over this stream keeps
            # admitting and evicting, never changing a verdict
            v_p.resident = ResidencyManager(slots=8, range_bits=2)
        filters = []

        def commit_fn(res, _state=state_p):
            _state.apply_updates(
                res.batch, (res.block.header.number, 0)
            )

        with CommitPipeline(v_p, commit_fn, depth=depth) as pipe:
            for b in blocks:
                r = pipe.submit(b)
                if r is not None:
                    filters.append(
                        (r.block.header.number, list(r.tx_filter))
                    )
            r = pipe.flush()
            if r is not None:
                filters.append(
                    (r.block.header.number, list(r.tx_filter))
                )
        filters.sort()
        assert filters == serial, (depth, tiny)
        assert dict(state_p._data) == dict(state_s._data), (depth, tiny)
        st = v_p.resident.stats()
        if tiny:
            assert st["evictions_total"] > 0
        else:
            assert st["hits_total"] > 0, (
                "the hot working set never hit the resident table"
            )
        v_p.close()

"""Bit-exactness of the batched SHA-256 kernel vs hashlib."""

import hashlib

import numpy as np
import pytest

from fabric_tpu.ops import sha256 as s


def test_empty_and_abc():
    got = s.sha256_host([b"", b"abc"])
    assert got[0] == hashlib.sha256(b"").digest()
    assert got[1] == hashlib.sha256(b"abc").digest()


def test_block_boundaries():
    msgs = [b"x" * n for n in (55, 56, 63, 64, 65, 119, 120, 128, 129)]
    got = s.sha256_host(msgs)
    for m, g in zip(msgs, got):
        assert g == hashlib.sha256(m).digest(), len(m)


def test_random_batch(rng):
    msgs = [rng.bytes(int(n)) for n in rng.integers(0, 700, size=64)]
    got = s.sha256_host(msgs)
    for m, g in zip(msgs, got):
        assert g == hashlib.sha256(m).digest()


def test_max_blocks_padding(rng):
    msgs = [b"hello", rng.bytes(100)]
    got = s.sha256_host(msgs, max_blocks=8)
    for m, g in zip(msgs, got):
        assert g == hashlib.sha256(m).digest()


def test_overflow_rejected():
    with pytest.raises(ValueError):
        s.pad_messages([b"x" * 200], max_blocks=2)

"""Multi-device (mesh-sharded) production-dispatch tests — the first
pytest battery to actually USE conftest's 8 forced host devices.

Layers:

1. the verify kernel sharded over the data mesh is bit-equal to
   single-device (``verify_launch(mesh=...)``);
2. the FUSED stage-2 program (policy reduction + MVCC fixpoint
   consuming the device-resident signature vector) sharded through
   ``DeviceBlockPipeline.run(mesh=...)`` is bit-equal on every output
   lane, for 2- and 8-device meshes;
3. the depth-2 CommitPipeline with mesh sharding AND multi-block
   launch coalescing (``submit_many``/``preprocess_many``) produces
   filters and state identical to the serial unsharded oracle —
   crypto-free (ec_ref signatures), so it runs on containers without
   the ``cryptography`` package;
4. the full BlockValidator (real MSP identities) sharded vs
   single-device — crypto-gated, the seed condition on this container.

Shapes are chosen to reuse compile-cache entries other tier-1 tests
already create (buckets 16/64) — a new (shape × sharding) pair costs a
fresh XLA compile on the 2-core host.
"""

import json

import numpy as np
import jax.numpy as jnp
import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import ec_ref
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.ops import mvcc as mvcc_ops
from fabric_tpu.ops import p256v3 as v3
from fabric_tpu.parallel import mesh as pmesh
from fabric_tpu.peer.pipeline import CommitPipeline


def test_mesh_resolution():
    # conftest forces 8 host devices: auto (-1) sees all of them
    assert pmesh.resolve_mesh(0) is None
    m = pmesh.resolve_mesh(-1)
    assert m is not None and m.size == 8
    assert pmesh.resolve_mesh(2).size == 2
    assert pmesh.resolve_mesh(1) is None  # 1-device mesh = overhead only
    # ragged axis 0 degrades to unsharded instead of crashing
    arr = jnp.zeros((10, 3), jnp.int32)
    out = pmesh.shard_batch(pmesh.resolve_mesh(8), arr)
    assert out.shape == (10, 3)


@pytest.fixture(scope="module")
def key():
    return ec_ref.SigningKey.generate()


def _items(key, n, tag=b"md", bad_stride=3):
    out = []
    for i in range(n):
        e = ec_ref.digest_int(b"%s-%d" % (tag, i))
        r, s = key.sign_digest(e)
        if bad_stride and i % bad_stride == 2:
            s = ec_ref.N - s  # high-S reject lane
        out.append((e, r, s, *key.public))
    return out


def test_sharded_verify_bit_equal(key):
    """verify_launch over the full 8-device host mesh must reproduce
    the single-device accept set bit for bit (the verify is per-lane
    independent; sharding only partitions the batch dim)."""
    items = _items(key, 16)
    solo = v3.verify_launch(items)()
    mesh8 = pmesh.resolve_mesh(-1)
    assert v3.verify_launch(items, mesh=mesh8)() == solo
    assert any(solo) and not all(solo)


def test_sharded_fused_stage2_bit_equal():
    """The fused stage-1+stage-2 dispatch (DeviceBlockPipeline.run)
    sharded over 2- and 8-device meshes is bit-equal to single-device
    on every output lane — policy scatter-min and the MVCC fixpoint
    collectives included."""
    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.peer.device_block import DeviceBlockPipeline

    rng = np.random.default_rng(20260803)
    policy = pol.from_dsl("OutOf(2, 'O1.peer', 'O2.peer', 'O3.peer')")
    plan = pol.compile_plan(policy)
    P = len(plan.principals)
    S, Eb, T, n_sig = 4, 16, 16, 16
    handle = v3.VerifyHandle(jnp.asarray(rng.random(n_sig) < 0.75), n_sig)
    match = np.zeros((Eb, S, P), np.int32)
    endo_idx = np.full((Eb, S), -1, np.int32)
    tx_of = np.full(Eb, -1, np.int32)
    for e in range(12):
        tx_of[e] = e % T
        for s in range(3):
            endo_idx[e, s] = (e * 3 + s) % n_sig
            match[e, s, s % P] = 1
    gp = np.zeros((Eb, S * P + S + 1), np.int32)
    gp[:, :S * P] = match.reshape(Eb, -1)
    gp[:, S * P:S * P + S] = endo_idx
    gp[:, -1] = tx_of
    # dependent writes so the fixpoint actually iterates (conflict
    # chains cross shard boundaries on the 8-way mesh)
    txs = [
        mvcc_ops.TxRWSet(
            reads=[("k%d" % i, (1, 0))],
            writes=["k%d" % ((i + 1) % 12)],
            range_reads=[],
        )
        for i in range(12)
    ]
    static = mvcc_ops.prepare_block_static(txs, bucketed=True)
    launch_vec = np.zeros((T, 3), np.int32)
    launch_vec[:, 0] = np.arange(T) % n_sig
    launch_vec[:12, 1] = 1
    launch_vec[:12, 2] = 1

    pipe = DeviceBlockPipeline()
    base = pipe.run(handle, launch_vec, [(plan, jnp.asarray(gp), Eb, S)],
                    static.packed_static(), static.dims, T)()
    for nd in (2, 8):
        mesh = pmesh.resolve_mesh(nd)
        groups = [(plan, pmesh.shard_batch(mesh, jnp.asarray(gp)), Eb, S)]
        got = pipe.run(handle, launch_vec, groups, static.packed_static(),
                       static.dims, T, mesh=mesh)()
        for k in ("valid", "conflict", "phantom", "creator_ok",
                  "policy_ok", "sig_valid"):
            assert np.array_equal(base[k], got[k]), (nd, k)
        assert all(
            np.array_equal(a, b) for a, b in zip(base["safe"], got["safe"])
        ), nd
    # something actually validated and something conflicted
    assert base["valid"][:12].any() and not base["valid"][:12].all()


# ---------------------------------------------------------------------------
# crypto-free pipelined equivalence: a device-backed toy validator


from dataclasses import dataclass  # noqa: E402


@dataclass
class _Ptx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class _Pending:
    block: object
    txs: list
    raw: list
    overlay: object
    extra: object
    fetch: object  # device VerifyHandle — synced at validate_finish

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class DeviceToyValidator:
    """ToyValidator (tests/test_commit_pipeline.py) whose launch path
    REALLY dispatches the p256v3 device verify — per-tx ec_ref
    signatures ride ``verify_launch`` (solo) or ``verify_launch_many``
    (coalesced prefetch), optionally mesh-sharded — so the CommitPipeline
    equivalence below exercises the production device lane without the
    ``cryptography`` package.

    tx wire form: {"id", "sig": [e, r, s, qx, qy] (decimal strings),
    "reads": {key: [blk, tx]}, "writes": {key: val}}.
    """

    VALID, BADSIG, DUP, MVCC = 0, 4, 2, 11

    def __init__(self, state, mesh=None, chunk=0, pool=None,
                 recode_device=False):
        self.state = state
        self.mesh = mesh
        self.chunk = int(chunk)
        self.pool = pool
        self.recode_device = bool(recode_device)
        self.coalesced_calls = 0
        self.launch_order = []

    @staticmethod
    def _decode(block):
        raw = [json.loads(bytes(d)) for d in block.data.data]
        items = [tuple(int(x) for x in t["sig"]) for t in raw]
        return raw, items

    def preprocess(self, block):
        raw, items = self._decode(block)
        fetch = v3.verify_launch(items, chunk=self.chunk or None,
                                 mesh=self.mesh, pool=self.pool,
                                 recode_device=self.recode_device)
        return raw, fetch

    def preprocess_many(self, blocks):
        self.coalesced_calls += 1
        decoded = [self._decode(b) for b in blocks]
        fetches = v3.verify_launch_many(
            [items for _, items in decoded],
            chunk=self.chunk or None, mesh=self.mesh, pool=self.pool,
            recode_device=self.recode_device,
        )
        return [(raw, f) for (raw, _), f in zip(decoded, fetches)]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw, fetch = pre if pre is not None else self.preprocess(block)
        self.launch_order.append((block.header.number, overlay is not None))
        txs = [_Ptx(t["id"], i) for i, t in enumerate(raw)]
        return _Pending(block, txs, raw, overlay, extra_txids, fetch)

    def _version(self, key, overlay):
        if overlay is not None:
            vv = overlay.updates.get(("ns", key))
            if vv is not None:
                return None if vv.value is None else list(vv.version)
        vv = self.state.get_state("ns", key)
        return None if vv is None else list(vv.version)

    def validate_finish(self, pend):
        bits = pend.fetch()  # device sync — the production seam
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for i, (ptx, t) in enumerate(zip(pend.txs, pend.raw)):
            if not bits[i]:
                codes.append(self.BADSIG)
                continue
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            ok = all(
                self._version(k, pend.overlay) == want
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put("ns", k, val.encode(), (num, ptx.idx))
        return bytes(codes), batch, []


def _device_stream(key, n_blocks=6, n_tx=8):
    """Dependent block stream (overlay + stale lanes like
    test_commit_pipeline._stream) with REAL per-tx signatures; every
    third signature is corrupted so the device verdicts matter."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            e = ec_ref.digest_int(b"tx%d_%d" % (n, i))
            r, s = key.sign_digest(e)
            if i % 3 == 2:
                s = ec_ref.N - s  # high-S → device rejects
            t = {
                "id": f"tx{n}_{i}",
                "sig": [str(v) for v in (e, r, s, *key.public)],
                "writes": {f"k{n}_{i}": f"v{n}"},
            }
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}  # fresh via overlay
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [0, 0]}      # stale → MVCC
            txs.append(t)
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _device_stream_deep(key, n_blocks=6, n_tx=6):
    """Depth-3 shape with REAL signatures: RW dependencies spanning
    BOTH in-flight predecessors — block n reads block n−1's AND block
    n−2's writes at the written versions (fresh only through the
    merged overlay chain), a per-block stale k→k+2 lane, and the usual
    corrupted-signature lane so device verdicts stay load-bearing."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            e = ec_ref.digest_int(b"dtx%d_%d" % (n, i))
            r, s = key.sign_digest(e)
            if i == 2:
                s = ec_ref.N - s  # high-S → device rejects
            t = {
                "id": f"dtx{n}_{i}",
                "sig": [str(v) for v in (e, r, s, *key.public)],
                "writes": {f"k{n}_{i}": f"v{n}"},
            }
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}   # k→k+1 fresh
            if n > 1 and i == 1:
                t["reads"] = {f"k{n-2}_1": [n - 2, 1]}   # k→k+2 fresh
            if n > 1 and i == 4:
                t["reads"] = {f"k{n-2}_4": [0, 0]}       # stale → MVCC
            txs.append(t)
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _run_device_pipe(blocks, depth, mesh=None, coalesce=0, pool=None,
                     recode_device=False, chunk=0):
    state = MemVersionedDB()
    v = DeviceToyValidator(state, mesh=mesh, pool=pool,
                           recode_device=recode_device, chunk=chunk)
    filters = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))

    with CommitPipeline(v, commit_fn, depth=depth,
                        coalesce_blocks=coalesce) as pipe:
        if coalesce >= 2:
            for r in pipe.submit_many(blocks):
                filters.append((r.block.header.number, list(r.tx_filter)))
        else:
            for b in blocks:
                r = pipe.submit(b)
                if r is not None:
                    filters.append(
                        (r.block.header.number, list(r.tx_filter))
                    )
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    return filters, dict(state._data), v


def test_sharded_coalesced_pipeline_matches_serial(key):
    """The tentpole acceptance gate: depth-2 CommitPipeline with the
    verify dispatch mesh-sharded over 2 devices AND coalesced 3 blocks
    per launch must produce filters and final state identical to the
    serial unsharded oracle — and it must have actually coalesced."""
    blocks = _device_stream(key, n_blocks=6, n_tx=8)
    f_serial, s_serial, _ = _run_device_pipe(blocks, depth=1)
    f_shard, s_shard, v = _run_device_pipe(
        blocks, depth=2, mesh=pmesh.resolve_mesh(2), coalesce=3
    )
    assert f_shard == f_serial
    assert s_shard == s_serial
    assert v.coalesced_calls == 2  # 6 blocks in groups of 3
    # depth-2 actually pipelined (overlay launches happened)
    assert any(ov for _, ov in v.launch_order)
    # the device verdicts are load-bearing: bad-sig lanes rejected
    for _, flt in f_serial:
        assert flt[2] == DeviceToyValidator.BADSIG
        assert DeviceToyValidator.VALID in flt


def test_depth3_device_pipeline_matches_serial(key):
    """THE depth-3 acceptance gate through the REAL device lane:
    a stream whose conflict chains span both in-flight predecessors
    (k→k+1 and k→k+2 fresh reads, k→k+2 stale lane, corrupted-sig
    lanes) must produce filters and final state identical to the
    serial oracle at depth 3 — solo, chunked (the double-buffered
    dispatch under the pipeline), and mesh-sharded + coalesced."""
    blocks = _device_stream_deep(key, n_blocks=6, n_tx=6)
    f1, s1, _ = _run_device_pipe(blocks, depth=1)
    # the stream exercises what it claims: bad-sig lanes rejected,
    # fresh k→k+2 lanes valid, stale lanes MVCC-failed
    for n, flt in f1:
        assert flt[2] == DeviceToyValidator.BADSIG
        if n > 1:
            assert flt[1] == DeviceToyValidator.VALID
            assert flt[4] == DeviceToyValidator.MVCC

    f3, s3, v = _run_device_pipe(blocks, depth=3)
    assert f3 == f1
    assert s3 == s1
    assert all(ov for n, ov in v.launch_order if n >= 1)

    f3c, s3c, _ = _run_device_pipe(blocks, depth=3, chunk=16)
    assert f3c == f1 and s3c == s1

    f3m, s3m, vm = _run_device_pipe(
        blocks, depth=3, mesh=pmesh.resolve_mesh(2), coalesce=3
    )
    assert f3m == f1 and s3m == s1
    assert vm.coalesced_calls == 2


def test_pooled_staging_pipeline_matches_serial(key):
    """The host-staging acceptance gate: depth-2 CommitPipeline with
    pooled host staging (2 workers), recode-on-device, the verify
    dispatch sharded over the full 8-device mesh AND 3-block launch
    coalescing must produce filters and final state identical to the
    serial unpooled/unsharded/host-recode oracle.  The coalesced
    3×bucket-16 concatenation pads to 64 lanes, so the pool really
    shards (two 32-lane slabs per staging call)."""
    from fabric_tpu.parallel.hostpool import HostStagePool

    blocks = _device_stream(key, n_blocks=6, n_tx=8)
    f_serial, s_serial, _ = _run_device_pipe(blocks, depth=1)
    with HostStagePool(2) as pool:
        f_pool, s_pool, v = _run_device_pipe(
            blocks, depth=2, mesh=pmesh.resolve_mesh(8), coalesce=3,
            pool=pool, recode_device=True,
        )
        stats = pool.stats()
    assert f_pool == f_serial
    assert s_pool == s_serial
    assert v.coalesced_calls == 2
    assert any(ov for _, ov in v.launch_order)  # depth-2 pipelined
    assert stats["tasks"] > 0  # the pool actually staged shards
    # device verdicts are load-bearing under pooling+recode too
    for _, flt in f_pool:
        assert flt[2] == DeviceToyValidator.BADSIG
        assert DeviceToyValidator.VALID in flt


def test_pooled_block_validator_preprocess_many(tmp_path):
    """BlockValidator._preprocess_many_pooled (parse fan-out + pooled
    device_pre + pooled coalesced staging) vs the serial
    preprocess_many: identical filters and update batches through
    validate_launch/finish.  Crypto-gated — the seed condition on
    containers without the ``cryptography`` package."""
    pytest.importorskip("cryptography")
    from bench import _build_commit_network
    from fabric_tpu.peer.validator import BlockValidator
    from fabric_tpu.protos import common_pb2

    (blocks, fresh_state, _fv, mgr, prov, _cc,
     _ninv) = _build_commit_network(6, 2)

    def run(workers, recode):
        state = fresh_state()
        v = BlockValidator(mgr, prov, state, host_stage_workers=workers,
                           recode_device=recode)
        out = []
        copies = []
        for blk in blocks:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            copies.append(b)
        pres = v.preprocess_many(copies)
        for b, pre in zip(copies, pres):
            flt, batch, history = v.validate_finish(
                v.validate_launch(b, pre=pre)
            )
            state.apply_updates(batch, (b.header.number, 0))
            out.append((list(flt), sorted(batch.updates), history))
        v.close()  # staging pool worker threads
        return out

    assert run(2, True) == run(0, False)


def test_full_validator_sharded_block(tmp_path):
    """Full BlockValidator (real MSP identities, fused device path) on
    a 2-device mesh: bit-equal filter/updates vs single-device, through
    the pipelined validator.  Crypto-gated — the seed condition on
    containers without the ``cryptography`` package."""
    pytest.importorskip("cryptography")
    from bench import _build_commit_network

    (blocks, fresh_state, _fresh_validator, mgr, prov, _cc,
     _ninv) = _build_commit_network(6, 2)
    from fabric_tpu.peer.validator import BlockValidator

    def run(mesh_devices):
        state = fresh_state()
        v = BlockValidator(mgr, prov, state, mesh_devices=mesh_devices)
        out = []
        from fabric_tpu.protos import common_pb2

        for blk in blocks:
            b = common_pb2.Block()
            b.CopyFrom(blk)
            flt, batch, history = v.validate(b)
            state.apply_updates(batch, (b.header.number, 0))
            out.append((list(flt), sorted(batch.updates), history))
        return out

    assert run(2) == run(0)

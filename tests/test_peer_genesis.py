"""Peer genesis bootstrap: a channel joined from an admin-provided
genesis block derives its trust anchor (MSPs, policies, lifecycle
provider, config processor) from the genesis config, commits block 0
locally without network validation, and validates subsequent blocks
against the bundle (reference: core/peer/peer.go:235 createChannel +
join-with-genesis)."""

import asyncio

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.peer import lifecycle as lc
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.node import PeerChannel
from fabric_tpu.protos import transaction_pb2
from fabric_tpu.tools import configtxgen as cg

C = transaction_pb2.TxValidationCode
CHANNEL = "genchan"
CC = "gencc"


@pytest.fixture(scope="module")
def material():
    orgs = [
        cryptogen.generate_org(f"Org{i}MSP", f"org{i}.example.com", peers=1, users=1)
        for i in (1, 2)
    ]
    profile = cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(o.msp_id, o.msp()) for o in orgs],
    )
    return {
        "orgs": orgs,
        "genesis": cg.genesis_block(profile),
        "client": cryptogen.signing_identity(orgs[0], "User1@org1.example.com"),
        "peers": [
            cryptogen.signing_identity(o, f"peer0.org{i}.example.com")
            for i, o in zip((1, 2), orgs)
        ],
    }


def _tx(material, endorsers, writes, ns=CC):
    signer = material["client"]
    signed, tx_id, prop = txa.create_signed_proposal(signer, CHANNEL, ns, [b"invoke"])
    tx = TxRWSet()
    n = tx.ns_rwset(ns)
    for k, v in writes:
        n.writes[k] = v
    rw = tx.to_proto().SerializeToString()
    responses = [txa.create_proposal_response(prop, rw, e, ns) for e in endorsers]
    return txa.assemble_transaction(prop, responses, signer)


def _block(envs, num, prev):
    blk = pu.new_block(num, prev)
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    return pu.finalize_block(blk)


def test_genesis_join_and_bundle_backed_validation(material, tmp_path):
    ch = PeerChannel(
        CHANNEL, str(tmp_path / "peer"), genesis_block=material["genesis"]
    )
    # genesis committed locally as the trust anchor
    assert ch.height == 1
    assert ch.processor.bundle.application_orgs() == ["Org1MSP", "Org2MSP"]
    # MSPs derived from genesis validate org identities
    ident = ch.validator.msp.deserialize_identity(material["peers"][0].serialized)
    assert ident.is_valid and ident.role == "peer"

    async def commit(envs):
        prev = pu.block_header_hash(
            ch.ledger.blocks.get_block(ch.height - 1).header
        )
        blk = _block(envs, ch.height, prev)
        return await ch.commit_block(blk)

    # before any lifecycle definition: writes to CC are INVALID_CHAINCODE
    env = _tx(material, material["peers"], [("k", b"v")])
    flt = asyncio.run(commit([env]))
    assert list(flt) == [C.INVALID_CHAINCODE]

    # commit a lifecycle definition (policy = channel Endorsement ref →
    # MAJORITY of org Endorsement policies = both orgs here)
    cd = lc.ChaincodeDefinition(name=CC, sequence=1)
    env_lc = _tx(
        material, material["peers"],
        [(lc.definition_key(CC), cd.to_bytes())], ns=lc.LIFECYCLE_NS,
    )
    flt = asyncio.run(commit([env_lc]))
    assert list(flt) == [C.VALID]

    # now: both-org endorsement valid, single-org fails MAJORITY
    env_ok = _tx(material, material["peers"], [("k", b"v1")])
    env_one = _tx(material, material["peers"][:1], [("k2", b"v2")])
    flt = asyncio.run(commit([env_ok, env_one]))
    assert list(flt) == [C.VALID, C.ENDORSEMENT_POLICY_FAILURE]
    assert ch.ledger.state.get_state(CC, "k").value == b"v1"
    assert ch.ledger.state.get_state(CC, "k2") is None
    ch.stop()

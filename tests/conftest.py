"""Test harness configuration.

All unit tests run on a virtual 8-device CPU mesh so that sharding code
paths (pjit/shard_map over a Mesh) are exercised without TPU hardware,
mirroring how the driver dry-runs the multi-chip path.
"""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    import numpy as np

    return np.random.default_rng(20260729)

"""Test harness configuration.

All unit tests run on a virtual 8-device CPU mesh so that sharding code
paths (pjit/shard_map over a Mesh) are exercised without TPU hardware,
mirroring how the driver dry-runs the multi-chip path.

The axon sitecustomize registers the tunneled real-TPU backend in every
python process and sets jax_platforms="axon,cpu" via jax.config —
overriding the JAX_PLATFORMS env var.  Tests must never touch the real
chip (per-shape compiles take minutes and the tunnel is single-client),
so we force the config back to cpu BEFORE any backend initialization.
"""

import os

from fabric_tpu.utils.xla_env import (
    ensure_cpu_compile_workaround,
    ensure_host_device_count,
)

# Belt: env for any subprocesses tests may spawn.
os.environ["JAX_PLATFORMS"] = "cpu"
ensure_host_device_count(8)
ensure_cpu_compile_workaround()

# Suspenders: the axon register() already ran (sitecustomize) and set
# jax_platforms="axon,cpu"; override it back before backends init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the P-256 verify graph takes ~8 min to
# compile on a 1-core host; cache it across test runs.
jax.config.update("jax_compilation_cache_dir", str(os.path.join(os.path.dirname(__file__), "..", ".jax_cache")))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    import numpy as np

    return np.random.default_rng(20260729)

"""CommitPipeline engine smoke tests — tier-1 speed, crypto-free.

These drive the REAL pipeline machinery (prefetch/committer threads,
overlay handoff, lifecycle/config barrier, serial mode) and the real
KVLedger commit seam with a toy JSON validator, so pipeline
regressions fail fast without the full bench — and on containers
without the ``cryptography`` package (this container's seed
condition).  The cryptographic validator equivalence lives in
tests/test_pipeline.py.
"""

import json
from dataclasses import dataclass

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer.pipeline import CommitPipeline


@dataclass
class ToyPtx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class ToyPending:
    block: object
    txs: list
    raw: list           # decoded tx dicts
    overlay: object
    extra: object
    hd_bytes: bytes = None

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ToyValidator:
    """The validator protocol (preprocess / validate_launch /
    validate_finish) over JSON transactions with MVCC version checks
    against committed state + the in-flight predecessor overlay —
    the same contract BlockValidator exposes, minus the crypto.

    tx wire form: {"id", "config"?, "reads": {key: [blk, tx]},
    "writes": {key: value-str}} — writes keyed ("ns", k), or
    ("_lifecycle", k) for keys starting "_lifecycle/" (barrier lane).
    """

    VALID, DUP, MVCC = 0, 2, 11

    def __init__(self, state):
        self.state = state
        self.preprocess_order: list = []
        self.launch_order: list = []

    def preprocess(self, block):
        # record whether the barrier lane's lifecycle write was
        # visible in committed state at parse time — the stale-prefetch
        # regression check reads this
        self.preprocess_order.append((
            block.header.number,
            self.state.get_state("_lifecycle", "_lifecycle/cc1")
            is not None,
        ))
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        self.launch_order.append(
            (block.header.number, overlay is not None)
        )
        txs = [
            ToyPtx(t["id"], i, bool(t.get("config")))
            for i, t in enumerate(raw)
        ]
        return ToyPending(block, txs, raw, overlay, extra_txids)

    def _version(self, ns, key, overlay):
        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                return None if vv.value is None else list(vv.version)
        vv = self.state.get_state(ns, key)
        return None if vv is None else list(vv.version)

    @staticmethod
    def _ns(key):
        return "_lifecycle" if key.startswith("_lifecycle/") else "ns"

    def validate_finish(self, pend):
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for ptx, t in zip(pend.txs, pend.raw):
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            ok = all(
                self._version(self._ns(k), k, pend.overlay) == want
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put(self._ns(k), k, val.encode(), (num, ptx.idx))
        return bytes(codes), batch, []


def _block(num, prev, txs):
    blk = pu.new_block(num, prev)
    for t in txs:
        blk.data.data.append(json.dumps(t).encode())
    return pu.finalize_block(blk)


def _stream(n_blocks=3, n_tx=8):
    """Dependent stream: block n writes k{n}_*, block n+1 reads its
    predecessor's first key at the version the predecessor wrote — the
    overlay case (block n+1 reading a key block n wrote while block
    n's commit is still in flight), plus one stale-read lane."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"tx{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}  # fresh via overlay
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [0, 0]}      # stale → MVCC
            txs.append(t)
        blk = _block(n, prev, txs)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _run(blocks, depth, commit_log=None, barrier_hook=None):
    state = MemVersionedDB()
    v = ToyValidator(state)
    filters = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        if commit_log is not None:
            commit_log.append(("commit", res.block.header.number,
                               res.barrier))
    with CommitPipeline(v, commit_fn, depth=depth) as pipe:
        for b in blocks:
            r = pipe.submit(b)
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    return filters, dict(state._data), v


def test_pipelined_matches_serial_8tx_3blocks():
    """The tiny end-to-end CI gate: 8-tx, 3-block dependent stream —
    depth-2 (overlay in play) and depth-1 (serial oracle) must produce
    identical filters and final state."""
    blocks = _stream(3, 8)
    f2, s2, v2 = _run(blocks, depth=2)
    f1, s1, v1 = _run(blocks, depth=1)
    assert f2 == f1
    assert s2 == s1
    # every block returned, every filter has 8 verdicts
    assert [n for n, _ in f2] == [0, 1, 2]
    assert all(len(flt) == 8 for _, flt in f2)
    # the overlay lane was VALID (read the in-flight write), the stale
    # lane MVCC-failed, everything else committed
    for n, flt in f2[1:]:
        assert flt[0] == ToyValidator.VALID
        assert flt[1] == ToyValidator.MVCC
        assert all(c == ToyValidator.VALID for c in flt[2:])
    # depth-2 actually pipelined: block n+1 launched with an overlay
    assert (1, True) in v2.launch_order and (2, True) in v2.launch_order
    assert all(not ov for _, ov in v1.launch_order)


def test_commits_through_real_kvledger(tmp_path):
    """End-to-end through KVLedger.commit_block on the committer
    thread: committed heights, filters in block metadata, and state
    all land; the txid index rides res.txids."""
    from fabric_tpu.ledger.kvledger import KVLedger

    blocks = _stream(3, 8)
    state = MemVersionedDB()
    v = ToyValidator(state)
    lg = KVLedger(str(tmp_path / "ledger"), state_db=state)

    def commit_fn(res):
        lg.commit_block(res.block, res.tx_filter, res.batch,
                        res.history, None, res.txids)

    with CommitPipeline(v, commit_fn, depth=2) as pipe:
        for b in blocks:
            pipe.submit(b)
        pipe.flush()
    assert lg.blocks.height == 3
    assert state.get_state("ns", "k2_7").value == b"v2"
    assert lg.blocks.tx_exists("tx1_3")
    lg.close()


def test_lifecycle_barrier_flushes_and_drops_overlay():
    """A block writing the ``_lifecycle`` namespace must commit FULLY
    before the successor launches, with the overlay dropped — the
    config/lifecycle barrier (stale policy plans would fork a
    pipelined peer from a serial one)."""
    blocks = _stream(4, 4)
    # block 1 additionally writes a lifecycle key → barrier
    lc = json.loads(bytes(blocks[1].data.data[2]))
    lc["writes"]["_lifecycle/cc1"] = "defn"
    blocks[1].data.data[2] = json.dumps(lc).encode()

    log = []
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        log.append(("commit", res.block.header.number, res.barrier))

    launches = v.launch_order
    with CommitPipeline(v, commit_fn, depth=2) as pipe:
        for b in blocks:
            pipe.submit(b)
            # barrier ordering: by the time block 2 launches, block
            # 1's commit must have fully flushed
            if launches and launches[-1][0] == 2:
                assert ("commit", 1, True) in log
        pipe.flush()
    assert ("commit", 1, True) in log
    # successor of the barrier launched WITHOUT an overlay; later
    # blocks resume pipelining with one
    by_num = dict(launches)
    assert by_num[2] is False
    assert by_num[3] is True
    # commits stayed in block order
    assert [e[1] for e in log] == [0, 1, 2, 3]
    # the barrier successor's ORIGINAL prefetch ran against
    # pre-barrier state and must have been REDONE after the barrier
    # committed — state-backed policy providers rotate in place, so
    # only a fresh parse sees the new definitions
    pre2 = [seen for n, seen in v.preprocess_order if n == 2]
    assert len(pre2) == 2, v.preprocess_order
    assert pre2[-1] is True  # the redo saw the lifecycle write


def test_dup_txid_caught_via_inflight_extra_txids():
    """A txid replayed in block n+1 while block n is still committing
    must be caught through the pipeline's extra_txids handoff."""
    blocks = _stream(2, 4)
    dup = json.loads(bytes(blocks[0].data.data[0]))
    blocks[1].data.data.append(json.dumps(dup).encode())
    blocks[1] = pu.finalize_block(blocks[1])
    f, _, _ = _run(blocks, depth=2)
    assert f[1][1][-1] == ToyValidator.DUP


def test_config_block_is_a_barrier():
    blocks = _stream(3, 2)
    cfg = {"id": "cfgtx", "config": True, "writes": {}}
    blocks[1].data.data.append(json.dumps(cfg).encode())
    blocks[1] = pu.finalize_block(blocks[1])
    log = []
    f, _, v = _run(blocks, depth=2, commit_log=log)
    assert ("commit", 1, True) in log
    assert dict(v.launch_order)[2] is False  # overlay dropped


def test_serial_mode_commits_inline():
    """depth=1: submit returns the SAME block, committed, before the
    next submit — the correctness-oracle mode behind the config."""
    blocks = _stream(2, 2)
    log = []
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        log.append(res.block.header.number)
        state.apply_updates(res.batch, (res.block.header.number, 0))

    with CommitPipeline(v, commit_fn, depth=1) as pipe:
        r0 = pipe.submit(blocks[0])
        assert r0.block.header.number == 0 and log == [0]
        r1 = pipe.submit(blocks[1])
        assert r1.block.header.number == 1 and log == [0, 1]
        assert pipe.flush() is None


def test_flush_midstream_then_resume():
    """The deliver loop flushes the in-flight tail when the stream
    goes idle (a quiet channel must not leave its newest block
    uncommitted), then keeps submitting when traffic resumes — the
    pipeline must support flush/submit interleaving with verdicts and
    state identical to an uninterrupted run."""
    blocks = _stream(6, 4)

    def run(flush_after):
        state = MemVersionedDB()
        v = ToyValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, depth=2) as pipe:
            for i, b in enumerate(blocks):
                r = pipe.submit(b)
                if r is not None:
                    filters.append((r.block.header.number,
                                    list(r.tx_filter)))
                if i in flush_after:  # stream went idle here
                    r = pipe.flush()
                    if r is not None:
                        filters.append((r.block.header.number,
                                        list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        filters.sort()
        return filters, dict(state._data)

    f_idle, s_idle = run(flush_after={1, 3})
    f_cont, s_cont = run(flush_after=set())
    assert f_idle == f_cont
    assert s_idle == s_cont
    assert [n for n, _ in f_idle] == [0, 1, 2, 3, 4, 5]


def test_barrier_flushed_as_tail_does_not_poison_next_prefetch():
    """A barrier committed as the FLUSH tail must not mark the next
    submitted block's prefetch stale — that prefetch starts after the
    barrier landed and must not be discarded and redone serially."""
    blocks = _stream(3, 2)
    lc = json.loads(bytes(blocks[1].data.data[0]))
    lc["writes"]["_lifecycle/cc1"] = "d"
    blocks[1].data.data[0] = json.dumps(lc).encode()
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))

    with CommitPipeline(v, commit_fn, depth=2) as pipe:
        pipe.submit(blocks[0])
        pipe.submit(blocks[1])
        pipe.flush()  # the barrier commits as the tail here
        pipe.submit(blocks[2])
        pipe.flush()
    assert [n for n, _ in v.preprocess_order].count(2) == 1, \
        v.preprocess_order


class CoalescingToyValidator(ToyValidator):
    """ToyValidator + the preprocess_many seam submit_many coalesces
    through — models the coalesced timing exactly: the WHOLE group is
    staged before any of it launches."""

    def preprocess_many(self, blocks):
        return [self.preprocess(b) for b in blocks]


def test_coalesced_group_barrier_redoes_every_later_prefetch():
    """A barrier INSIDE a coalesced group taints every remaining slice
    of that group's prefetch (they were all staged before the barrier
    committed), not just the immediate successor — each must be redone
    against post-barrier state, and verdicts/state must equal the
    serial oracle."""
    blocks = _stream(4, 4)
    # block 1 writes a lifecycle key → barrier mid-group
    lc = json.loads(bytes(blocks[1].data.data[2]))
    lc["writes"]["_lifecycle/cc1"] = "defn"
    blocks[1].data.data[2] = json.dumps(lc).encode()

    def run_coalesced():
        state = MemVersionedDB()
        v = CoalescingToyValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, depth=2,
                            coalesce_blocks=4) as pipe:
            for r in pipe.submit_many(blocks):
                filters.append((r.block.header.number, list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        filters.sort()
        return filters, dict(state._data), v

    f_co, s_co, v = run_coalesced()
    f_serial, s_serial, _ = _run(blocks, depth=1)
    assert f_co == f_serial
    assert s_co == s_serial
    # blocks 2 AND 3 were prefetched in the group stage (pre-barrier:
    # lifecycle key not yet visible) and BOTH must have been redone
    # post-barrier — the redo sees the committed lifecycle write
    for n in (2, 3):
        seen = [lc_seen for num, lc_seen in v.preprocess_order if num == n]
        assert len(seen) == 2, (n, v.preprocess_order)
        assert seen[0] is False and seen[-1] is True, (
            n, v.preprocess_order
        )


def test_submit_many_without_coalescing_degrades_to_submit():
    """coalesce off / custom prefetch_fn / serial depth → submit_many
    is per-block submit with identical results."""
    blocks = _stream(3, 4)

    def run(**kw):
        state = MemVersionedDB()
        v = CoalescingToyValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, **kw) as pipe:
            for r in pipe.submit_many(blocks):
                filters.append((r.block.header.number, list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        filters.sort()
        return filters, dict(state._data)

    base = run(depth=1)
    assert run(depth=2, coalesce_blocks=0) == base
    assert run(depth=2, coalesce_blocks=2) == base
    assert run(depth=2, coalesce_blocks=8) == base  # group > stream


def test_commit_failure_surfaces_and_tail_not_silently_lost():
    """A committer-thread failure must raise at the next submit/flush,
    not vanish."""
    blocks = _stream(3, 2)
    state = MemVersionedDB()
    v = ToyValidator(state)
    boom = {"n": 0}

    def commit_fn(res):
        boom["n"] += 1
        raise RuntimeError("disk on fire")

    pipe = CommitPipeline(v, commit_fn, depth=2)
    try:
        with pytest.raises(RuntimeError, match="disk on fire"):
            for b in blocks:
                pipe.submit(b)
            pipe.flush()
        assert boom["n"] >= 1
    finally:
        pipe.close(flush=False)


def _no_live_pipeline_threads():
    import threading

    return [
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(
            ("fabtpu-prefetch", "fabtpu-committer"))
    ]


def test_committer_exception_during_flush_fails_closed():
    """Committer-thread exception surfacing at flush: the pipe drains,
    the error surfaces exactly ONCE, the next submit raises 'pipeline
    is closed' cleanly, and no non-daemon worker threads survive."""
    blocks = _stream(3, 2)
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        raise RuntimeError("fsync wedged")

    pipe = CommitPipeline(v, commit_fn, depth=2)
    pipe.submit(blocks[0])
    pipe.submit(blocks[1])  # block 0's commit fails on the committer
    with pytest.raises(RuntimeError, match="fsync wedged"):
        pipe.flush()
    # once: the stored future was popped before the wait — the next
    # calls see a cleanly closed pipe, not the same error again
    with pytest.raises(RuntimeError, match="pipeline is closed"):
        pipe.submit(blocks[2])
    assert pipe.close() is None  # idempotent, no re-raise
    assert _no_live_pipeline_threads() == []
    assert pipe.last_failure is not None
    assert pipe.last_failure[1] == "commit"


def test_barrier_redo_prefetch_failure_no_wedged_threads():
    """A barrier block whose successor's prefetch REDO itself fails:
    the error surfaces as a prefetch-stage failure, the pipe fails
    closed, and both worker threads drain — no wedged non-daemon
    threads."""
    blocks = _stream(4, 2)
    lc = json.loads(bytes(blocks[1].data.data[0]))
    lc["writes"]["_lifecycle/cc1"] = "defn"  # block 1 = barrier
    blocks[1].data.data[0] = json.dumps(lc).encode()
    state = MemVersionedDB()

    class RedoBoomValidator(ToyValidator):
        def preprocess(self, block):
            out = super().preprocess(block)
            n_parses = [n for n, _ in self.preprocess_order].count(2)
            if block.header.number == 2 and n_parses == 2:
                raise RuntimeError("redo boom")
            return out

    v = RedoBoomValidator(state)
    committed = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        committed.append(res.block.header.number)

    pipe = CommitPipeline(v, commit_fn, depth=2)
    pipe.submit(blocks[0])
    pipe.submit(blocks[1])
    # submitting block 3 finishes the barrier (block 2's prefetch goes
    # stale) and the post-barrier REDO of block 2 blows up
    with pytest.raises(RuntimeError, match="redo boom"):
        pipe.submit(blocks[2])
        pipe.submit(blocks[3])
        pipe.flush()
    assert pipe.last_failure == (2, "prefetch")
    with pytest.raises(RuntimeError, match="pipeline is closed"):
        pipe.submit(blocks[3])
    assert pipe.close(flush=False) is None
    assert _no_live_pipeline_threads() == []
    # everything BEFORE the quarantined block committed in order
    assert committed == [0, 1]


def test_stage_failure_metrics_and_resume_from_height():
    """The containment contract end to end: an injected prefetch fault
    fails the pipe closed with the stage counter bumped; a fresh pipe
    resumes from the committed height and the stream completes with
    serial-identical verdicts."""
    from fabric_tpu import faults
    from fabric_tpu.ops_metrics import global_registry

    blocks = _stream(5, 4)
    f_serial, s_serial, _ = _run(blocks, depth=1)
    ctr = global_registry().counter(
        "commit_pipeline_stage_failures_total"
    )
    before = ctr.value(channel="", stage="prefetch")
    faults.configure("pipeline.prefetch:raise:n=1:after=2")
    state = MemVersionedDB()
    v = ToyValidator(state)
    filters = {}
    height = [0]

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        filters[res.block.header.number] = list(res.tx_filter)
        height[0] = res.block.header.number + 1

    try:
        restarts = 0
        pipe = CommitPipeline(v, commit_fn, depth=2)
        while True:
            try:
                for b in blocks[height[0]:]:
                    if b.header.number < height[0]:
                        continue
                    pipe.submit(b)
                pipe.flush()
                break
            except Exception:
                restarts += 1
                assert restarts < 10
                pipe.close(flush=False)
                pipe = CommitPipeline(v, commit_fn, depth=2)
        pipe.close()
    finally:
        faults.reset()
    assert restarts == 1
    assert ctr.value(channel="", stage="prefetch") == before + 1
    assert sorted((n, f) for n, f in filters.items()) == f_serial
    assert dict(state._data) == s_serial

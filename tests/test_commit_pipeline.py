"""CommitPipeline engine smoke tests — tier-1 speed, crypto-free.

These drive the REAL pipeline machinery (prefetch/committer threads,
overlay handoff, lifecycle/config barrier, serial mode) and the real
KVLedger commit seam with a toy JSON validator, so pipeline
regressions fail fast without the full bench — and on containers
without the ``cryptography`` package (this container's seed
condition).  The cryptographic validator equivalence lives in
tests/test_pipeline.py.
"""

import json
from dataclasses import dataclass

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer.pipeline import CommitPipeline


@dataclass
class ToyPtx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class ToyPending:
    block: object
    txs: list
    raw: list           # decoded tx dicts
    overlay: object
    extra: object
    hd_bytes: bytes = None

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ToyValidator:
    """The validator protocol (preprocess / validate_launch /
    validate_finish) over JSON transactions with MVCC version checks
    against committed state + the in-flight predecessor overlay —
    the same contract BlockValidator exposes, minus the crypto.

    tx wire form: {"id", "config"?, "reads": {key: [blk, tx]},
    "writes": {key: value-str}} — writes keyed ("ns", k), or
    ("_lifecycle", k) for keys starting "_lifecycle/" (barrier lane).
    """

    VALID, DUP, MVCC = 0, 2, 11

    def __init__(self, state):
        self.state = state
        self.preprocess_order: list = []
        self.launch_order: list = []

    def preprocess(self, block):
        # record whether the barrier lane's lifecycle write was
        # visible in committed state at parse time — the stale-prefetch
        # regression check reads this
        self.preprocess_order.append((
            block.header.number,
            self.state.get_state("_lifecycle", "_lifecycle/cc1")
            is not None,
        ))
        return [json.loads(bytes(d)) for d in block.data.data]

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw = pre if pre is not None else self.preprocess(block)
        self.launch_order.append(
            (block.header.number, overlay is not None)
        )
        txs = [
            ToyPtx(t["id"], i, bool(t.get("config")))
            for i, t in enumerate(raw)
        ]
        return ToyPending(block, txs, raw, overlay, extra_txids)

    def _version(self, ns, key, overlay):
        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                return None if vv.value is None else list(vv.version)
        vv = self.state.get_state(ns, key)
        return None if vv is None else list(vv.version)

    @staticmethod
    def _ns(key):
        return "_lifecycle" if key.startswith("_lifecycle/") else "ns"

    def validate_finish(self, pend):
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for ptx, t in zip(pend.txs, pend.raw):
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            ok = all(
                self._version(self._ns(k), k, pend.overlay) == want
                for k, want in t.get("reads", {}).items()
            )
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                if val is None:  # JSON null = delete
                    batch.delete(self._ns(k), k, (num, ptx.idx))
                else:
                    batch.put(self._ns(k), k, val.encode(),
                              (num, ptx.idx))
        return bytes(codes), batch, []


def _block(num, prev, txs):
    blk = pu.new_block(num, prev)
    for t in txs:
        blk.data.data.append(json.dumps(t).encode())
    return pu.finalize_block(blk)


def _stream(n_blocks=3, n_tx=8):
    """Dependent stream: block n writes k{n}_*, block n+1 reads its
    predecessor's first key at the version the predecessor wrote — the
    overlay case (block n+1 reading a key block n wrote while block
    n's commit is still in flight), plus one stale-read lane."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"tx{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}  # fresh via overlay
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [0, 0]}      # stale → MVCC
            txs.append(t)
        blk = _block(n, prev, txs)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _run(blocks, depth, commit_log=None, barrier_hook=None):
    state = MemVersionedDB()
    v = ToyValidator(state)
    filters = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        if commit_log is not None:
            commit_log.append(("commit", res.block.header.number,
                               res.barrier))
    with CommitPipeline(v, commit_fn, depth=depth) as pipe:
        for b in blocks:
            r = pipe.submit(b)
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    return filters, dict(state._data), v


def test_pipelined_matches_serial_8tx_3blocks():
    """The tiny end-to-end CI gate: 8-tx, 3-block dependent stream —
    depth-2 (overlay in play) and depth-1 (serial oracle) must produce
    identical filters and final state."""
    blocks = _stream(3, 8)
    f2, s2, v2 = _run(blocks, depth=2)
    f1, s1, v1 = _run(blocks, depth=1)
    assert f2 == f1
    assert s2 == s1
    # every block returned, every filter has 8 verdicts
    assert [n for n, _ in f2] == [0, 1, 2]
    assert all(len(flt) == 8 for _, flt in f2)
    # the overlay lane was VALID (read the in-flight write), the stale
    # lane MVCC-failed, everything else committed
    for n, flt in f2[1:]:
        assert flt[0] == ToyValidator.VALID
        assert flt[1] == ToyValidator.MVCC
        assert all(c == ToyValidator.VALID for c in flt[2:])
    # depth-2 actually pipelined: block n+1 launched with an overlay
    assert (1, True) in v2.launch_order and (2, True) in v2.launch_order
    assert all(not ov for _, ov in v1.launch_order)


def test_commits_through_real_kvledger(tmp_path):
    """End-to-end through KVLedger.commit_block on the committer
    thread: committed heights, filters in block metadata, and state
    all land; the txid index rides res.txids."""
    from fabric_tpu.ledger.kvledger import KVLedger

    blocks = _stream(3, 8)
    state = MemVersionedDB()
    v = ToyValidator(state)
    lg = KVLedger(str(tmp_path / "ledger"), state_db=state)

    def commit_fn(res):
        lg.commit_block(res.block, res.tx_filter, res.batch,
                        res.history, None, res.txids)

    with CommitPipeline(v, commit_fn, depth=2) as pipe:
        for b in blocks:
            pipe.submit(b)
        pipe.flush()
    assert lg.blocks.height == 3
    assert state.get_state("ns", "k2_7").value == b"v2"
    assert lg.blocks.tx_exists("tx1_3")
    lg.close()


def test_lifecycle_barrier_flushes_and_drops_overlay():
    """A block writing the ``_lifecycle`` namespace must commit FULLY
    before the successor launches, with the overlay dropped — the
    config/lifecycle barrier (stale policy plans would fork a
    pipelined peer from a serial one)."""
    blocks = _stream(4, 4)
    # block 1 additionally writes a lifecycle key → barrier
    lc = json.loads(bytes(blocks[1].data.data[2]))
    lc["writes"]["_lifecycle/cc1"] = "defn"
    blocks[1].data.data[2] = json.dumps(lc).encode()

    log = []
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        log.append(("commit", res.block.header.number, res.barrier))

    launches = v.launch_order
    with CommitPipeline(v, commit_fn, depth=2) as pipe:
        for b in blocks:
            pipe.submit(b)
            # barrier ordering: by the time block 2 launches, block
            # 1's commit must have fully flushed
            if launches and launches[-1][0] == 2:
                assert ("commit", 1, True) in log
        pipe.flush()
    assert ("commit", 1, True) in log
    # successor of the barrier launched WITHOUT an overlay; later
    # blocks resume pipelining with one
    by_num = dict(launches)
    assert by_num[2] is False
    assert by_num[3] is True
    # commits stayed in block order
    assert [e[1] for e in log] == [0, 1, 2, 3]
    # the barrier successor's ORIGINAL prefetch ran against
    # pre-barrier state and must have been REDONE after the barrier
    # committed — state-backed policy providers rotate in place, so
    # only a fresh parse sees the new definitions
    pre2 = [seen for n, seen in v.preprocess_order if n == 2]
    assert len(pre2) == 2, v.preprocess_order
    assert pre2[-1] is True  # the redo saw the lifecycle write


def test_dup_txid_caught_via_inflight_extra_txids():
    """A txid replayed in block n+1 while block n is still committing
    must be caught through the pipeline's extra_txids handoff."""
    blocks = _stream(2, 4)
    dup = json.loads(bytes(blocks[0].data.data[0]))
    blocks[1].data.data.append(json.dumps(dup).encode())
    blocks[1] = pu.finalize_block(blocks[1])
    f, _, _ = _run(blocks, depth=2)
    assert f[1][1][-1] == ToyValidator.DUP


def test_config_block_is_a_barrier():
    blocks = _stream(3, 2)
    cfg = {"id": "cfgtx", "config": True, "writes": {}}
    blocks[1].data.data.append(json.dumps(cfg).encode())
    blocks[1] = pu.finalize_block(blocks[1])
    log = []
    f, _, v = _run(blocks, depth=2, commit_log=log)
    assert ("commit", 1, True) in log
    assert dict(v.launch_order)[2] is False  # overlay dropped


def test_serial_mode_commits_inline():
    """depth=1: submit returns the SAME block, committed, before the
    next submit — the correctness-oracle mode behind the config."""
    blocks = _stream(2, 2)
    log = []
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        log.append(res.block.header.number)
        state.apply_updates(res.batch, (res.block.header.number, 0))

    with CommitPipeline(v, commit_fn, depth=1) as pipe:
        r0 = pipe.submit(blocks[0])
        assert r0.block.header.number == 0 and log == [0]
        r1 = pipe.submit(blocks[1])
        assert r1.block.header.number == 1 and log == [0, 1]
        assert pipe.flush() is None


def test_flush_midstream_then_resume():
    """The deliver loop flushes the in-flight tail when the stream
    goes idle (a quiet channel must not leave its newest block
    uncommitted), then keeps submitting when traffic resumes — the
    pipeline must support flush/submit interleaving with verdicts and
    state identical to an uninterrupted run."""
    blocks = _stream(6, 4)

    def run(flush_after):
        state = MemVersionedDB()
        v = ToyValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, depth=2) as pipe:
            for i, b in enumerate(blocks):
                r = pipe.submit(b)
                if r is not None:
                    filters.append((r.block.header.number,
                                    list(r.tx_filter)))
                if i in flush_after:  # stream went idle here
                    r = pipe.flush()
                    if r is not None:
                        filters.append((r.block.header.number,
                                        list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        filters.sort()
        return filters, dict(state._data)

    f_idle, s_idle = run(flush_after={1, 3})
    f_cont, s_cont = run(flush_after=set())
    assert f_idle == f_cont
    assert s_idle == s_cont
    assert [n for n, _ in f_idle] == [0, 1, 2, 3, 4, 5]


def test_barrier_flushed_as_tail_does_not_poison_next_prefetch():
    """A barrier committed as the FLUSH tail must not mark the next
    submitted block's prefetch stale — that prefetch starts after the
    barrier landed and must not be discarded and redone serially."""
    blocks = _stream(3, 2)
    lc = json.loads(bytes(blocks[1].data.data[0]))
    lc["writes"]["_lifecycle/cc1"] = "d"
    blocks[1].data.data[0] = json.dumps(lc).encode()
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))

    with CommitPipeline(v, commit_fn, depth=2) as pipe:
        pipe.submit(blocks[0])
        pipe.submit(blocks[1])
        pipe.flush()  # the barrier commits as the tail here
        pipe.submit(blocks[2])
        pipe.flush()
    assert [n for n, _ in v.preprocess_order].count(2) == 1, \
        v.preprocess_order


class CoalescingToyValidator(ToyValidator):
    """ToyValidator + the preprocess_many seam submit_many coalesces
    through — models the coalesced timing exactly: the WHOLE group is
    staged before any of it launches."""

    def preprocess_many(self, blocks):
        return [self.preprocess(b) for b in blocks]


def test_coalesced_group_barrier_redoes_every_later_prefetch():
    """A barrier INSIDE a coalesced group taints every remaining slice
    of that group's prefetch (they were all staged before the barrier
    committed), not just the immediate successor — each must be redone
    against post-barrier state, and verdicts/state must equal the
    serial oracle."""
    blocks = _stream(4, 4)
    # block 1 writes a lifecycle key → barrier mid-group
    lc = json.loads(bytes(blocks[1].data.data[2]))
    lc["writes"]["_lifecycle/cc1"] = "defn"
    blocks[1].data.data[2] = json.dumps(lc).encode()

    def run_coalesced():
        state = MemVersionedDB()
        v = CoalescingToyValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, depth=2,
                            coalesce_blocks=4) as pipe:
            for r in pipe.submit_many(blocks):
                filters.append((r.block.header.number, list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        filters.sort()
        return filters, dict(state._data), v

    f_co, s_co, v = run_coalesced()
    f_serial, s_serial, _ = _run(blocks, depth=1)
    assert f_co == f_serial
    assert s_co == s_serial
    # blocks 2 AND 3 were prefetched in the group stage (pre-barrier:
    # lifecycle key not yet visible) and BOTH must have been redone
    # post-barrier — the redo sees the committed lifecycle write
    for n in (2, 3):
        seen = [lc_seen for num, lc_seen in v.preprocess_order if num == n]
        assert len(seen) == 2, (n, v.preprocess_order)
        assert seen[0] is False and seen[-1] is True, (
            n, v.preprocess_order
        )


def test_submit_many_without_coalescing_degrades_to_submit():
    """coalesce off / custom prefetch_fn / serial depth → submit_many
    is per-block submit with identical results."""
    blocks = _stream(3, 4)

    def run(**kw):
        state = MemVersionedDB()
        v = CoalescingToyValidator(state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))

        with CommitPipeline(v, commit_fn, **kw) as pipe:
            for r in pipe.submit_many(blocks):
                filters.append((r.block.header.number, list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        filters.sort()
        return filters, dict(state._data)

    base = run(depth=1)
    assert run(depth=2, coalesce_blocks=0) == base
    assert run(depth=2, coalesce_blocks=2) == base
    assert run(depth=2, coalesce_blocks=8) == base  # group > stream


def test_commit_failure_surfaces_and_tail_not_silently_lost():
    """A committer-thread failure must raise at the next submit/flush,
    not vanish."""
    blocks = _stream(3, 2)
    state = MemVersionedDB()
    v = ToyValidator(state)
    boom = {"n": 0}

    def commit_fn(res):
        boom["n"] += 1
        raise RuntimeError("disk on fire")

    pipe = CommitPipeline(v, commit_fn, depth=2)
    try:
        with pytest.raises(RuntimeError, match="disk on fire"):
            for b in blocks:
                pipe.submit(b)
            pipe.flush()
        assert boom["n"] >= 1
    finally:
        pipe.close(flush=False)


def _no_live_pipeline_threads():
    import threading

    return [
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(
            ("fabtpu-prefetch", "fabtpu-committer"))
    ]


def test_committer_exception_during_flush_fails_closed():
    """Committer-thread exception surfacing at flush: the pipe drains,
    the error surfaces exactly ONCE, the next submit raises 'pipeline
    is closed' cleanly, and no non-daemon worker threads survive."""
    blocks = _stream(3, 2)
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        raise RuntimeError("fsync wedged")

    pipe = CommitPipeline(v, commit_fn, depth=2)
    pipe.submit(blocks[0])
    pipe.submit(blocks[1])  # block 0's commit fails on the committer
    with pytest.raises(RuntimeError, match="fsync wedged"):
        pipe.flush()
    # once: the stored future was popped before the wait — the next
    # calls see a cleanly closed pipe, not the same error again
    with pytest.raises(RuntimeError, match="pipeline is closed"):
        pipe.submit(blocks[2])
    assert pipe.close() is None  # idempotent, no re-raise
    assert _no_live_pipeline_threads() == []
    assert pipe.last_failure is not None
    assert pipe.last_failure[1] == "commit"


def test_barrier_redo_prefetch_failure_no_wedged_threads():
    """A barrier block whose successor's prefetch REDO itself fails:
    the error surfaces as a prefetch-stage failure, the pipe fails
    closed, and both worker threads drain — no wedged non-daemon
    threads."""
    blocks = _stream(4, 2)
    lc = json.loads(bytes(blocks[1].data.data[0]))
    lc["writes"]["_lifecycle/cc1"] = "defn"  # block 1 = barrier
    blocks[1].data.data[0] = json.dumps(lc).encode()
    state = MemVersionedDB()

    class RedoBoomValidator(ToyValidator):
        def preprocess(self, block):
            out = super().preprocess(block)
            n_parses = [n for n, _ in self.preprocess_order].count(2)
            if block.header.number == 2 and n_parses == 2:
                raise RuntimeError("redo boom")
            return out

    v = RedoBoomValidator(state)
    committed = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        committed.append(res.block.header.number)

    pipe = CommitPipeline(v, commit_fn, depth=2)
    pipe.submit(blocks[0])
    pipe.submit(blocks[1])
    # submitting block 3 finishes the barrier (block 2's prefetch goes
    # stale) and the post-barrier REDO of block 2 blows up
    with pytest.raises(RuntimeError, match="redo boom"):
        pipe.submit(blocks[2])
        pipe.submit(blocks[3])
        pipe.flush()
    assert pipe.last_failure == (2, "prefetch")
    with pytest.raises(RuntimeError, match="pipeline is closed"):
        pipe.submit(blocks[3])
    assert pipe.close(flush=False) is None
    assert _no_live_pipeline_threads() == []
    # everything BEFORE the quarantined block committed in order
    assert committed == [0, 1]


# -- depth-N: merged overlay chains, widened dup window, deferred fsync ------


def _stream_deep(n_blocks=6, n_tx=6):
    """Conflict chains spanning BOTH in-flight predecessors (the
    depth-3 shape): block n reads block n−1's AND block n−2's writes
    at the versions they wrote (fresh — resolvable only through the
    merged overlay chain while both commits are in flight), overwrites
    a shared hot key every block (newest-wins resolution), reads the
    hot key at the IMMEDIATE predecessor's version, and carries one
    stale lane per block (must fail MVCC at every depth)."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"tx{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if i == 3:
                t["writes"]["hot"] = f"h{n}"
            if n > 0 and i == 0:
                t["reads"] = {f"k{n-1}_0": [n - 1, 0]}   # k→k+1 fresh
            if n > 1 and i == 1:
                t["reads"] = {f"k{n-2}_1": [n - 2, 1]}   # k→k+2 fresh
            if n > 1 and i == 2:
                t["reads"] = {f"k{n-2}_2": [0, 0]}       # stale → MVCC
            if n > 0 and i == 4:
                t["reads"] = {"hot": [n - 1, 3]}         # newest-wins
            txs.append(t)
        blk = _block(n, prev, txs)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def test_depth3_matches_serial_with_k2_conflict_chains(tmp_path):
    """THE depth-3 differential: accept set AND post-commit ledger
    state ≡ the serial oracle on a stream whose RW dependencies span
    both in-flight predecessors (k→k+1, k→k+2, hot-key newest-wins),
    through a real KVLedger — depths 4 and 2 ride along."""
    from fabric_tpu.ledger.kvledger import KVLedger

    blocks = _stream_deep(6, 6)

    def run(depth, sub):
        state = MemVersionedDB()
        v = ToyValidator(state)
        lg = KVLedger(str(tmp_path / f"lg{sub}"), state_db=state)
        filters = []

        def commit_fn(res):
            state.apply_updates(res.batch, (res.block.header.number, 0))
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids)

        with CommitPipeline(v, commit_fn, depth=depth) as pipe:
            for b in blocks:
                r = pipe.submit(b)
                if r is not None:
                    filters.append((r.block.header.number,
                                    list(r.tx_filter)))
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number,
                                list(r.tx_filter)))
        height = lg.blocks.height
        lg.close()
        filters.sort()
        return filters, dict(state._data), height, v

    f1, s1, h1, _ = run(1, "serial")
    for depth in (2, 3, 4):
        fd, sd, hd, v = run(depth, f"d{depth}")
        assert fd == f1, f"depth {depth} filters diverged"
        assert sd == s1, f"depth {depth} state diverged"
        assert hd == h1 == len(blocks)
        if depth >= 3:
            # actually pipelined deep: every non-head block launched
            # with an overlay
            assert all(ov for n, ov in v.launch_order if n >= 1)
    # the stale lane failed and the fresh k→k+2 lane passed, serially
    for n, flt in f1:
        if n > 1:
            assert flt[1] == ToyValidator.VALID   # k→k+2 fresh
            assert flt[2] == ToyValidator.MVCC    # stale
            assert flt[4] == ToyValidator.VALID   # hot newest-wins


def test_depth3_overlay_chain_spans_two_inflight_predecessors():
    """Deterministic merged-overlay proof: commits of blocks 0 AND 1
    are gated closed on the committer thread, so block 2's reads can
    resolve ONLY through the merged overlay chain — newest-wins for
    the twice-written key, delete override, and an oldest-batch key
    surviving the merge."""
    import threading

    b0 = _block(0, b"", [
        {"id": "a0", "writes": {"x": "a", "y": "a", "z": "a"}},
    ])
    b1 = _block(1, pu.block_header_hash(b0.header), [
        {"id": "a1", "writes": {"x": "b", "y": None}},  # overwrite + delete
    ])
    b2 = _block(2, pu.block_header_hash(b1.header), [
        {"id": "a2", "reads": {"x": [1, 0]}, "writes": {}},   # newest wins
        {"id": "a3", "reads": {"y": None}, "writes": {}},     # deleted
        {"id": "a4", "reads": {"z": [0, 0]}, "writes": {}},   # oldest survives
        {"id": "a5", "reads": {"x": [0, 0]}, "writes": {}},   # stale → MVCC
    ])
    state = MemVersionedDB()
    v = ToyValidator(state)
    gate = threading.Event()
    committed = []

    def commit_fn(res):
        num = res.block.header.number
        if num < 2:
            assert gate.wait(30.0), "commit gate never opened"
        state.apply_updates(res.batch, (num, 0))
        committed.append(num)

    results = []
    with CommitPipeline(v, commit_fn, depth=3) as pipe:
        for b in (b0, b1, b2):
            r = pipe.submit(b)
            if r is not None:
                results.append(r)
        # block 2 launched with BOTH predecessors still uncommitted;
        # open the gate so the flush can drain
        assert committed == []
        gate.set()
        r = pipe.flush()
        if r is not None:
            results.append(r)
    by_num = {r.block.header.number: list(r.tx_filter) for r in results}
    V, M = ToyValidator.VALID, ToyValidator.MVCC
    assert by_num[2] == [V, V, V, M]
    assert committed == [0, 1, 2]
    # pipelined mid-window commits defer their fsync; the tail closes
    # the window
    defer = {r.block.header.number: r.defer_sync for r in results}
    assert defer[0] is True and defer[1] is True and defer[2] is False


def test_dup_txid_across_widened_window_depth3():
    """A txid replayed two blocks later, while BOTH predecessors are
    in the in-flight window: depth 3's widened extra_txids must catch
    it (depth 2's single-predecessor window structurally cannot — the
    block store's tx_exists covers it there)."""
    blocks = _stream(3, 3)
    dup = json.loads(bytes(blocks[0].data.data[0]))
    blocks[2].data.data.append(json.dumps(dup).encode())
    blocks[2] = pu.finalize_block(blocks[2])
    # re-link the chain after mutating block 2
    f3, _, _ = _run(blocks, depth=3)
    assert f3[2][1][-1] == ToyValidator.DUP


def test_depth3_barrier_drains_whole_window_and_taints_successor():
    """A lifecycle barrier at depth 3 drains BOTH in-flight commits
    before committing inline, drops the whole overlay chain, and the
    staged successor's prefetch is redone post-barrier."""
    blocks = _stream(5, 4)
    lc = json.loads(bytes(blocks[2].data.data[2]))
    lc["writes"]["_lifecycle/cc1"] = "defn"
    blocks[2].data.data[2] = json.dumps(lc).encode()

    log = []
    state = MemVersionedDB()
    v = ToyValidator(state)

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        log.append((res.block.header.number, res.barrier))

    with CommitPipeline(v, commit_fn, depth=3) as pipe:
        for b in blocks:
            pipe.submit(b)
            if v.launch_order and v.launch_order[-1][0] == 3:
                # by block 3's launch the barrier committed — and so
                # did everything before it (window fully drained)
                assert (2, True) in log
                assert [n for n, _ in log] == [0, 1, 2]
        pipe.flush()
    assert [n for n, _ in log] == [0, 1, 2, 3, 4]
    by_num = dict(v.launch_order)
    assert by_num[3] is False   # overlay chain dropped at the barrier
    assert by_num[4] is True    # pipelining resumed
    # the barrier successor's pre-barrier prefetch was redone
    pre3 = [seen for n, seen in v.preprocess_order if n == 3]
    assert len(pre3) == 2 and pre3[-1] is True


def test_coalesced_barrier_taints_both_successors_depth3():
    """Config/lifecycle barrier mid-chain inside a coalesced group at
    DEPTH 3: both staged successors redo their prefetch post-barrier
    and verdicts/state equal the serial oracle (the group-wide taint
    extends to every later slice at deep depths too)."""
    blocks = _stream(4, 4)
    lc = json.loads(bytes(blocks[1].data.data[2]))
    lc["writes"]["_lifecycle/cc1"] = "defn"
    blocks[1].data.data[2] = json.dumps(lc).encode()

    state = MemVersionedDB()
    v = CoalescingToyValidator(state)
    filters = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))

    with CommitPipeline(v, commit_fn, depth=3,
                        coalesce_blocks=4) as pipe:
        for r in pipe.submit_many(blocks):
            filters.append((r.block.header.number, list(r.tx_filter)))
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    f_serial, s_serial, _ = _run(blocks, depth=1)
    assert filters == f_serial
    assert dict(state._data) == s_serial
    for n in (2, 3):
        seen = [s for num, s in v.preprocess_order if num == n]
        assert len(seen) == 2, (n, v.preprocess_order)
        assert seen[0] is False and seen[-1] is True


def test_update_batch_merged_semantics():
    """The merged-overlay primitive itself: newest-wins key
    resolution, SBE has_meta union, delete override, singleton
    identity (the depth-2 fast path), empty → None."""
    from fabric_tpu.ledger.statedb import UpdateBatch

    a = UpdateBatch()
    a.put("ns", "x", b"a", (0, 0))
    a.put("ns", "z", b"z", (0, 1), metadata=b"pol")  # SBE metadata
    b = UpdateBatch()
    b.put("ns", "x", b"b", (1, 0))   # overwrite
    b.delete("ns", "y", (1, 1))      # delete rides through
    assert a.has_meta and not b.has_meta

    m = UpdateBatch.merged([a, b])
    assert m is not a and m is not b
    assert m.updates[("ns", "x")].value == b"b"          # newest wins
    assert m.updates[("ns", "x")].version == (1, 0)
    assert m.updates[("ns", "y")].value is None          # delete kept
    assert m.updates[("ns", "z")].metadata == b"pol"     # oldest survives
    assert m.has_meta                                    # union
    # reversed chain order flips the winner
    m2 = UpdateBatch.merged([b, a])
    assert m2.updates[("ns", "x")].value == b"a"
    # singleton: the batch ITSELF (pointer identity — depth-2 path)
    assert UpdateBatch.merged([a]) is a
    assert UpdateBatch.merged([None, a, None]) is a
    assert UpdateBatch.merged([]) is None
    assert UpdateBatch.merged([None]) is None
    # a later metadata-less overwrite keeps the union flag (the SBE
    # gate must stay engaged for the whole window)
    c = UpdateBatch()
    c.put("ns", "z", b"plain", (2, 0))
    m3 = UpdateBatch.merged([a, c])
    assert m3.updates[("ns", "z")].metadata is None
    assert m3.has_meta


def test_stage_failure_metrics_and_resume_from_height():
    """The containment contract end to end: an injected prefetch fault
    fails the pipe closed with the stage counter bumped; a fresh pipe
    resumes from the committed height and the stream completes with
    serial-identical verdicts."""
    from fabric_tpu import faults
    from fabric_tpu.ops_metrics import global_registry

    blocks = _stream(5, 4)
    f_serial, s_serial, _ = _run(blocks, depth=1)
    ctr = global_registry().counter(
        "commit_pipeline_stage_failures_total"
    )
    before = ctr.value(channel="", stage="prefetch")
    faults.configure("pipeline.prefetch:raise:n=1:after=2")
    state = MemVersionedDB()
    v = ToyValidator(state)
    filters = {}
    height = [0]

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        filters[res.block.header.number] = list(res.tx_filter)
        height[0] = res.block.header.number + 1

    try:
        restarts = 0
        pipe = CommitPipeline(v, commit_fn, depth=2)
        while True:
            try:
                for b in blocks[height[0]:]:
                    if b.header.number < height[0]:
                        continue
                    pipe.submit(b)
                pipe.flush()
                break
            except Exception:
                restarts += 1
                assert restarts < 10
                pipe.close(flush=False)
                pipe = CommitPipeline(v, commit_fn, depth=2)
        pipe.close()
    finally:
        faults.reset()
    assert restarts == 1
    assert ctr.value(channel="", stage="prefetch") == before + 1
    assert sorted((n, f) for n, f in filters.items()) == f_serial
    assert dict(state._data) == s_serial

"""Async group-commit storage engine (fabric_tpu/ledger/committer.py):
the decoupled committer's differential battery.

Layers:

1. AsyncApplyEngine unit semantics — read-your-writes through the
   pending overlay (point reads, bulk/column version gathers, range
   scans, rich queries with pending-rewrite suppression), bounded-
   queue backpressure, fail-stop error latch;
2. columnar write batches — ``ColumnarUpdateBatch`` dict equivalence
   (content AND order), post-build overrides, and the sqlite
   executemany fast path landing byte-identical state;
3. crash recovery — the applier killed at EVERY queue depth via the
   ``ledger.apply.before`` fault point, reopened serial, replayed from
   the chain files: state byte-identical to the synchronous oracle,
   savepoint reconciled to the block height;
4. the depth-3 CommitPipeline differential: async ON vs OFF produce
   identical verdicts and final state (the toy validator reads
   through the engine, so MVCC preloads exercise the overlay).
"""

import threading
import time

import numpy as np
import pytest

from fabric_tpu import faults
from fabric_tpu import protoutil as pu
from fabric_tpu.ledger.committer import AsyncApplyEngine
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import (
    ColumnarUpdateBatch,
    MemVersionedDB,
    SqliteVersionedDB,
    UpdateBatch,
)
from fabric_tpu.protos import common_pb2


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class _GatedDB(MemVersionedDB):
    """Inner backend whose applies park on a gate — the pending
    overlay becomes deterministic to probe."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()

    def seed(self, batch, savepoint):
        MemVersionedDB.apply_updates(self, batch, savepoint)

    def apply_updates(self, batch, savepoint):
        assert self.gate.wait(30.0), "apply gate never opened"
        MemVersionedDB.apply_updates(self, batch, savepoint)


def _b(num, puts=(), dels=()):
    b = UpdateBatch()
    for i, (ns, k, v) in enumerate(puts):
        b.put(ns, k, v, (num, i))
    for ns, k in dels:
        b.delete(ns, k, (num, 0))
    return b


# ---------------------------------------------------------------------------
# 1. engine unit semantics


def test_overlay_read_your_writes_point_and_versions():
    inner = _GatedDB()
    inner.open()
    s = UpdateBatch()
    s.put("ns", "a", b"old", (0, 0))
    s.put("ns", "gone", b"x", (0, 1))
    inner.seed(s, (0, 0))
    eng = AsyncApplyEngine(inner, queue_blocks=8)
    eng.submit(1, _b(1, puts=[("ns", "a", b"new1"), ("ns", "b", b"b1")],
                     dels=[("ns", "gone")]), (1, 0))
    eng.submit(2, _b(2, puts=[("ns", "a", b"new2")]), (2, 0))
    # newest pending batch wins; deletes read as absent
    assert eng.get_state("ns", "a").value == b"new2"
    assert eng.get_state("ns", "b").value == b"b1"
    assert eng.get_state("ns", "gone") is None
    keys = [("ns", "a"), ("ns", "gone"), ("ns", "b"), ("ns", "nope")]
    assert eng.get_versions_bulk(keys) == {
        ("ns", "a"): (2, 0), ("ns", "b"): (1, 1),
    }
    present, vers = eng.get_versions_cols(keys)
    assert present.tolist() == [True, False, True, False]
    assert vers[0].tolist() == [2, 0] and vers[2].tolist() == [1, 1]
    # savepoint reads ahead to the newest queued batch
    assert eng.savepoint() == (2, 0)
    assert eng.stats()["queue_depth"] == 2
    # drain: the applied state serves the SAME answers
    inner.gate.set()
    eng.drain()
    assert eng.get_state("ns", "a").value == b"new2"
    assert eng.get_state("ns", "gone") is None
    assert inner.savepoint() == (2, 0)
    st = eng.stats()
    assert st["queue_depth"] == 0 and st["applied_num"] == 2
    assert st["applies_total"] == 2
    eng.close()


def test_overlay_range_scan_and_query_suppression():
    inner = _GatedDB()
    inner.open()
    s = UpdateBatch()
    for i in range(6):
        color = b"red" if i in (1, 2, 5) else b"blue"
        s.put("ns", f"key{i}", b'{"color":"%s"}' % color, (0, i))
    inner.seed(s, (0, 0))
    eng = AsyncApplyEngine(inner, queue_blocks=8)
    pend = UpdateBatch()
    pend.put("ns", "key2", b'{"color":"blue"}', (1, 0))  # rewrite
    pend.delete("ns", "key3", (1, 1))
    pend.put("ns", "key6", b'{"color":"red"}', (1, 2))   # new row
    eng.submit(1, pend, (1, 0))

    def rng(*a, **kw):
        return [(k, vv.value) for k, vv in eng.get_state_range(*a, **kw)]

    assert rng("ns", "key1", "key5") == [
        ("key1", b'{"color":"red"}'),
        ("key2", b'{"color":"blue"}'),   # pending rewrite wins
        ("key4", b'{"color":"blue"}'),   # key3: pending delete
    ]
    # limit counts OUTPUT rows, not inner rows eaten by suppression
    assert [k for k, _ in rng("ns", "key2", "", limit=2)] == [
        "key2", "key4",
    ]
    # rich query: the pending rewrite of key2 no longer matches red and
    # must SUPPRESS the committed (still-matching) row; pending key6
    # matches and merges in key order
    got = [k for k, _ in eng.execute_query(
        "ns", {"selector": {"color": "red"}})]
    assert got == ["key1", "key5", "key6"]
    inner.gate.set()
    eng.drain()
    # applied: identical answers with an empty queue
    assert [k for k, _ in eng.execute_query(
        "ns", {"selector": {"color": "red"}})] == ["key1", "key5", "key6"]
    eng.close()


def test_backpressure_parks_submitter_at_capacity():
    inner = _GatedDB()
    inner.open()
    eng = AsyncApplyEngine(inner, queue_blocks=2)
    eng.submit(0, _b(0, puts=[("ns", "k0", b"v")]), (0, 0))
    eng.submit(1, _b(1, puts=[("ns", "k1", b"v")]), (1, 0))
    entered = threading.Event()

    def third():
        eng.submit(2, _b(2, puts=[("ns", "k2", b"v")]), (2, 0))
        entered.set()

    t = threading.Thread(target=third, daemon=True)
    t.start()
    assert not entered.wait(0.3), "bounded queue admitted past capacity"
    inner.gate.set()
    assert entered.wait(10.0)
    t.join(10.0)
    eng.drain()
    assert eng.stats()["backpressure_total"] >= 1
    assert inner.get_state("ns", "k2").value == b"v"
    eng.close()


def test_fail_stop_latch_reraises_at_submit_and_drain():
    inner = MemVersionedDB()
    inner.open()
    eng = AsyncApplyEngine(inner, queue_blocks=4)
    faults.configure("ledger.apply.before:raise:n=1")
    eng.submit(0, _b(0, puts=[("ns", "k0", b"v")]), (0, 0))
    with pytest.raises(RuntimeError):
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            eng.submit(1, _b(1, puts=[("ns", "k1", b"v")]), (1, 0))
            time.sleep(0.02)
        pytest.fail("latched applier failure never re-raised")
    assert eng.stats()["failed"]
    with pytest.raises(RuntimeError):
        eng.drain()
    eng.abort()


# ---------------------------------------------------------------------------
# 2. columnar write batches


def _columnar():
    """Hand-built slab batch: rows in apply order with a same-key
    rewrite (uid 0 written twice — last wins) and one delete."""
    blob = b"AAABBCCCC"
    return ColumnarUpdateBatch(
        5,
        ["ns", "zz"], ["a", "b", "c"], np.array([0, 0, 1]),
        np.array([0, 1, 0, 2]),            # uids: a, b, a again, c
        np.array([False, False, False, True]),
        np.array([0, 3, 5, 0]), np.array([3, 2, 4, 0]),
        np.array([0, 0, 1, 2], np.int64), blob,
    )


def _columnar_oracle():
    o = UpdateBatch()
    o.put("ns", "a", b"AAA", (5, 0))
    o.put("ns", "b", b"BB", (5, 0))
    o.put("ns", "a", b"CCCC", (5, 1))   # rewrite shadows
    o.delete("zz", "c", (5, 2))
    return o


def test_columnar_batch_matches_dict_form():
    cb, o = _columnar(), _columnar_oracle()
    assert list(cb.updates.items()) == list(o.updates.items())
    assert cb.touches_namespace("ns") and cb.touches_namespace("zz")
    assert not cb.touches_namespace("other")
    # post-build override shadows the slab rows everywhere
    cb.put("ns", "a", b"extra", (5, 9))
    assert cb.updates[("ns", "a")].value == b"extra"
    skipped = {k for dels, rows in cb.sqlite_columns()
               for k in ([d[1] for d in dels] + [r[1] for r in rows])}
    assert "a" not in skipped            # extras-shadowed slab row
    assert dict(cb.extra_items())[("ns", "a")].value == b"extra"
    assert cb.touches_namespace("pvt") is False
    cb.put("pvt", "h", b"x", (5, 9))
    assert cb.touches_namespace("pvt")


def test_columnar_sqlite_fast_path_byte_identical(tmp_path):
    fast = SqliteVersionedDB(str(tmp_path / "fast.db"))
    slow = SqliteVersionedDB(str(tmp_path / "slow.db"))
    fast.open()
    slow.open()
    # pre-existing row the columnar delete must remove
    pre = UpdateBatch()
    pre.put("zz", "c", b"stale", (1, 0))
    fast.apply_updates(pre, (1, 0))
    slow.apply_updates(pre, (1, 0))
    cb, o = _columnar(), _columnar_oracle()
    cb.put("ns", "d", b"late", (5, 3))   # extras ride the classic path
    o.put("ns", "d", b"late", (5, 3))
    fast.apply_updates(cb, (5, 0))       # isinstance → executemany path
    slow.apply_updates(o, (5, 0))
    assert sorted(fast.iter_all()) == sorted(slow.iter_all())
    assert fast.savepoint() == slow.savepoint() == (5, 0)
    fast.close()
    slow.close()


# ---------------------------------------------------------------------------
# 3. crash recovery at every queue depth


def _block(num, prev, payloads, channel="ch"):
    blk = pu.new_block(num, prev)
    for i, p in enumerate(payloads):
        ch = pu.make_channel_header(
            common_pb2.HeaderType.ENDORSER_TRANSACTION, channel,
            tx_id=f"tx{num}-{i}",
        )
        sh = pu.make_signature_header(b"creator", b"n")
        payload = pu.make_payload(ch, sh, p)
        env = common_pb2.Envelope(
            payload=payload.SerializeToString(), signature=b"s"
        )
        blk.data.data.append(env.SerializeToString())
    return pu.finalize_block(blk)


def _commit_stream(lg, n):
    prev = b""
    for num in range(n):
        blk = _block(num, prev, [b"data%d" % num])
        prev = pu.block_header_hash(blk.header)
        batch = UpdateBatch()
        batch.put("ns", f"k{num}", b"v%d" % num, (num, 0))
        if num:
            batch.delete("ns", f"k{num - 1}", (num, 0))
        lg.commit_block(blk, bytes([0]), batch, [("ns", f"k{num}", 0)])


def _replayer(block):
    num = block.header.number
    batch = UpdateBatch()
    batch.put("ns", f"k{num}", b"v%d" % num, (num, 0))
    if num:
        batch.delete("ns", f"k{num - 1}", (num, 0))
    return bytes([0]), batch, [("ns", f"k{num}", 0)]


def _dump(state):
    return sorted(
        (ns, key, vv.value, vv.metadata, vv.version)
        for (ns, key), vv in state.iter_all()
    )


def test_crash_recovery_differential_every_depth(tmp_path):
    n_blocks = 8
    oracle = KVLedger(str(tmp_path / "oracle"))
    _commit_stream(oracle, n_blocks)
    want = _dump(oracle.state)
    want_hist = list(oracle.history.get_history_for_key("ns", "k5"))
    oracle.close()

    for kill_at in range(1, 5):
        d = str(tmp_path / f"async{kill_at}")
        faults.configure(
            f"ledger.apply.before:raise:after={kill_at}:n=1"
        )
        lg = KVLedger(d, async_commit=True, apply_queue_blocks=4)
        try:
            _commit_stream(lg, n_blocks)
        except RuntimeError:
            pass  # the latched apply failure surfacing at a submit
        # die mid-queue: drop the pending tail, no graceful drain
        lg.engine.abort()
        lg.blocks.close()
        lg.history.close()
        lg.pvtdata.close()
        faults.reset()

        lg2 = KVLedger(d)  # reopen SERIAL
        assert lg2.height >= kill_at
        sp = lg2.state.savepoint()
        assert sp is not None and sp[0] + 1 < lg2.height, (
            f"kill_at={kill_at}: savepoint {sp} not behind height "
            f"{lg2.height}"
        )
        replayed = lg2.recover(_replayer)
        assert replayed == lg2.height - (sp[0] + 1)
        assert lg2.state.savepoint() == (lg2.height - 1, 0)
        if lg2.height == n_blocks:
            # full chain survived in the block files: state must be
            # BYTE-identical to the synchronous oracle
            assert _dump(lg2.state) == want
            assert list(
                lg2.history.get_history_for_key("ns", "k5")
            ) == want_hist
        lg2.close()


def test_async_end_to_end_commit_reopen(tmp_path):
    d = str(tmp_path / "ledger")
    lg = KVLedger(d, async_commit=True, apply_queue_blocks=2)
    _commit_stream(lg, 6)
    # read-your-writes straight after the last commit
    assert lg.state.get_state("ns", "k5").value == b"v5"
    assert lg.state.get_state("ns", "k4") is None
    assert lg.state.savepoint() == (5, 0)
    assert set(lg.last_commit_timings) == {"ledger_append", "state_apply"}
    lg.close()  # drains
    lg2 = KVLedger(d)
    assert lg2.height == 6
    assert lg2.state.savepoint() == (5, 0)
    assert lg2.state.get_state("ns", "k5").value == b"v5"
    lg2.close()


# ---------------------------------------------------------------------------
# 4. depth-3 pipeline differential: async ON vs OFF


def test_pipeline_depth3_differential_async_vs_serial(tmp_path):
    from test_commit_pipeline import ToyValidator, _stream

    from fabric_tpu.peer.pipeline import CommitPipeline

    blocks = _stream(5, 6)

    def run(async_on):
        state = MemVersionedDB()
        lg = KVLedger(
            str(tmp_path / ("async" if async_on else "serial")),
            state_db=state, async_commit=async_on,
            apply_queue_blocks=2,
        )
        # the validator reads through lg.state: under the async engine
        # that is the pending overlay — MVCC verdicts must not change
        v = ToyValidator(lg.state)
        filters = []

        def commit_fn(res):
            lg.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids)

        with CommitPipeline(v, commit_fn, depth=3) as pipe:
            for b in blocks:
                r = pipe.submit(b)
                if r is not None:
                    filters.append(
                        (r.block.header.number, list(r.tx_filter))
                    )
            r = pipe.flush()
            if r is not None:
                filters.append((r.block.header.number, list(r.tx_filter)))
        lg.drain_state()
        snap = dict(state._data)
        sp = lg.state.savepoint()
        height = lg.height
        lg.close()
        filters.sort()
        return filters, snap, sp, height

    fa, sa, spa, ha = run(True)
    fs, ss, sps, hs = run(False)
    assert fa == fs
    assert sa == ss
    assert spa == sps and ha == hs == 5
    # sanity: verdicts actually exercised both lanes
    assert any(
        c != 0 for _n, flt in fa for c in flt
    ) and any(c == 0 for _n, flt in fa for c in flt)

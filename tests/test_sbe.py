"""State-based (key-level) endorsement, end to end.

Scenarios mirror the reference's integration/sbe suite over
statebased/validator_keylevel.go + vpmanagerimpl.go: a key's
VALIDATION_PARAMETER (a serialized SignaturePolicyEnvelope written via
SetStateValidationParameter) overrides the namespace endorsement policy
for every write to that key — committed cross-block, in effect
IN-BLOCK from earlier plugin-valid txs, changeable only under the
current policy, deletable (falling back to the namespace policy), a
no-op on absent keys, preserved across plain value writes, and a
version bump for MVCC purposes.
"""

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import policy_to_proto
from fabric_tpu.ledger.rwset import (
    VALIDATION_PARAMETER, TxRWSet, decode_metadata,
)
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.validator import (
    BlockValidator, NamespaceInfo, PolicyProvider,
)
from fabric_tpu.protos import transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL = "sbechan"
CC = "sbecc"


@pytest.fixture(scope="module")
def net():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
    return {
        "mgr": mgr,
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "p1": cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        "p2": cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    }


from fabric_tpu.crypto.msp import MSPManager  # noqa: E402


def org_policy_bytes(msp_id: str) -> bytes:
    """Serialized SignaturePolicyEnvelope requiring one ``msp_id`` peer."""
    ast = pol.from_dsl(f"OutOf(1, '{msp_id}.peer')")
    return policy_to_proto(ast).SerializeToString()


def _tx(net, endorsers, reads=(), writes=(), meta=None):
    signer = net["client"]
    signed, tx_id, prop = txa.create_signed_proposal(
        signer, CHANNEL, CC, [b"invoke"]
    )
    tx = TxRWSet()
    n = tx.ns_rwset(CC)
    for k, ver in reads:
        n.reads[k] = ver
    for k, v in writes:
        n.writes[k] = v
    for k, entries in (meta or {}).items():
        n.metadata_writes[k] = dict(entries)
    rw = tx.to_proto().SerializeToString()
    responses = [
        txa.create_proposal_response(prop, rw, e, CC) for e in endorsers
    ]
    return txa.assemble_transaction(prop, responses, signer)


def _block(envs, num=2, prev=b"prev"):
    blk = pu.new_block(num, prev)
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    return pu.finalize_block(blk)


def _fresh(net, seed=None):
    """(state, validator) with a 1-of-(Org1|Org2) namespace policy and
    optional seeded keys [(key, value, metadata_bytes)]."""
    state = MemVersionedDB()
    b = UpdateBatch()
    for key, value, md in seed or []:
        b.put(CC, key, value, (1, 0), metadata=md)
    state.apply_updates(b, (1, 0))
    ns_policy = pol.from_dsl("OutOf(1, 'Org1MSP.peer', 'Org2MSP.peer')")
    prov = PolicyProvider({CC: NamespaceInfo(policy=ns_policy)})
    return state, BlockValidator(net["mgr"], prov, state)


def _sbe_meta(msp_id: str) -> dict:
    return {VALIDATION_PARAMETER: org_policy_bytes(msp_id)}


def test_key_policy_enforced_cross_block(net):
    state, v = _fresh(net)
    # block 2: set value + Org2-only key policy on "k" (no policy yet,
    # so the 1-of-any namespace policy admits the Org1 endorsement)
    env = _tx(net, [net["p1"]], writes=[("k", b"v0")], meta={"k": _sbe_meta("Org2MSP")})
    flt, batch, _ = v.validate(_block([env], num=2))
    assert list(flt) == [C.VALID]
    vv = batch.updates[(CC, "k")]
    assert decode_metadata(vv.metadata)[VALIDATION_PARAMETER]
    state.apply_updates(batch, (2, 0))
    assert state.meta_count == 1

    # block 3: an Org1-only write to "k" violates the key policy even
    # though it satisfies the namespace policy
    bad = _tx(net, [net["p1"]], writes=[("k", b"v1")])
    flt, batch, _ = v.validate(_block([bad], num=3))
    assert list(flt) == [C.ENDORSEMENT_POLICY_FAILURE]
    assert (CC, "k") not in batch.updates

    # an Org2 write passes, and the key policy survives the value write
    good = _tx(net, [net["p2"]], writes=[("k", b"v2")])
    flt, batch, _ = v.validate(_block([good], num=3))
    assert list(flt) == [C.VALID]
    assert decode_metadata(
        batch.updates[(CC, "k")].metadata
    )[VALIDATION_PARAMETER] == org_policy_bytes("Org2MSP")

    # writes to OTHER keys stay under the namespace policy
    other = _tx(net, [net["p1"]], writes=[("unrelated", b"x")])
    flt, _, _ = v.validate(_block([other], num=3))
    assert list(flt) == [C.VALID]


def test_in_block_policy_takes_effect_for_later_txs(net):
    """vpmanagerimpl.go:47-199 semantics: tx1 sets the key policy, and
    tx2 IN THE SAME BLOCK is already judged under it; tx3 satisfying
    the new policy commits."""
    state, v = _fresh(net)
    tx1 = _tx(net, [net["p1"]], writes=[("k", b"v")], meta={"k": _sbe_meta("Org2MSP")})
    tx2 = _tx(net, [net["p1"]], writes=[("k", b"later")])   # violates new policy
    tx3 = _tx(net, [net["p2"]], writes=[("k", b"fine")])    # satisfies it
    flt, batch, _ = v.validate(_block([tx1, tx2, tx3], num=2))
    assert list(flt) == [C.VALID, C.ENDORSEMENT_POLICY_FAILURE, C.VALID]
    assert batch.updates[(CC, "k")].value == b"fine"


def test_policy_change_requires_current_policy(net):
    state, v = _fresh(net, seed=[
        ("k", b"v", None),
    ])
    # install Org2 policy first
    env = _tx(net, [net["p1"]], meta={"k": _sbe_meta("Org2MSP")})
    flt, batch, _ = v.validate(_block([env], num=2))
    assert list(flt) == [C.VALID]
    state.apply_updates(batch, (2, 0))

    # Org1 tries to flip the policy to Org1-only: the metadata write
    # itself is a write to "k" and must satisfy the CURRENT Org2 policy
    coup = _tx(net, [net["p1"]], meta={"k": _sbe_meta("Org1MSP")})
    flt, batch, _ = v.validate(_block([coup], num=3))
    assert list(flt) == [C.ENDORSEMENT_POLICY_FAILURE]
    assert not batch.updates

    # Org2 legitimately rotates it
    rotate = _tx(net, [net["p2"]], meta={"k": _sbe_meta("Org1MSP")})
    flt, batch, _ = v.validate(_block([rotate], num=3))
    assert list(flt) == [C.VALID]
    state.apply_updates(batch, (3, 0))
    # now Org1 writes pass and Org2-only writes fail
    flt, _, _ = v.validate(_block(
        [_tx(net, [net["p1"]], writes=[("k", b"w")])], num=4))
    assert list(flt) == [C.VALID]
    flt, _, _ = v.validate(_block(
        [_tx(net, [net["p2"]], writes=[("k", b"w")])], num=4))
    assert list(flt) == [C.ENDORSEMENT_POLICY_FAILURE]


def test_policy_delete_falls_back_to_namespace(net):
    state, v = _fresh(net, seed=[
        ("k", b"v", None),
    ])
    env = _tx(net, [net["p1"]], meta={"k": _sbe_meta("Org2MSP")})
    flt, batch, _ = v.validate(_block([env], num=2))
    state.apply_updates(batch, (2, 0))
    assert state.meta_count == 1

    # Org2 clears the metadata (empty map) — requires the Org2 policy
    clear = _tx(net, [net["p2"]], meta={"k": {}})
    flt, batch, _ = v.validate(_block([clear], num=3))
    assert list(flt) == [C.VALID]
    state.apply_updates(batch, (3, 0))
    assert state.meta_count == 0
    assert state.get_state(CC, "k").metadata is None

    # namespace policy (1-of-any) governs again
    flt, _, _ = v.validate(_block(
        [_tx(net, [net["p1"]], writes=[("k", b"w")])], num=4))
    assert list(flt) == [C.VALID]


def test_metadata_write_on_absent_key_is_noop(net):
    state, v = _fresh(net)
    # tx1 metadata-writes a non-existent key; tx2 reads it as absent —
    # the no-op must NOT make tx1 a writer, so tx2 stays valid (the
    # reference's applyWriteSet leaves the batch untouched)
    env = _tx(net, [net["p1"]], meta={"ghost": _sbe_meta("Org2MSP")})
    rdr = _tx(net, [net["p1"]], reads=[("ghost", None)],
              writes=[("out", b"x")])
    flt, batch, _ = v.validate(_block([env, rdr], num=2))
    assert list(flt) == [C.VALID, C.VALID]
    assert (CC, "ghost") not in batch.updates
    state.apply_updates(batch, (2, 0))
    assert state.get_state(CC, "ghost") is None
    assert state.meta_count == 0


def test_metadata_write_bumps_version_for_mvcc(net):
    state, v = _fresh(net, seed=[("k", b"v", None)])
    # tx1 metadata-writes k (valid); tx2 then reads k at the seeded
    # version → in-block writer conflict, exactly as a value write
    tx1 = _tx(net, [net["p1"]], meta={"k": _sbe_meta("Org1MSP")})
    tx2 = _tx(net, [net["p1"]], reads=[("k", (1, 0))], writes=[("out", b"x")])
    flt, batch, _ = v.validate(_block([tx1, tx2], num=2))
    assert list(flt) == [C.VALID, C.MVCC_READ_CONFLICT]
    # the metadata-only update carries the key's existing value with a
    # bumped version
    vv = batch.updates[(CC, "k")]
    assert vv.value == b"v"
    assert vv.version == (2, 0)
    state.apply_updates(batch, (2, 0))
    # cross-block: a reader still citing (1, 0) now conflicts
    stale = _tx(net, [net["p1"]], reads=[("k", (1, 0))], writes=[("o2", b"y")])
    flt, _, _ = v.validate(_block([stale], num=3))
    assert list(flt) == [C.MVCC_READ_CONFLICT]


def test_sbe_via_chaincode_stub(net):
    """The shim surface: SetStateValidationParameter from a contract
    through the simulator produces the exact rwset the validator
    enforces."""
    from fabric_tpu.peer.chaincode import ChaincodeRuntime, Contract, Response
    from fabric_tpu.peer.simulator import TxSimulator

    class EPContract(Contract):
        def lock(self, stub, key, msp):
            stub.put_state(key.decode(), b"locked")
            stub.set_state_validation_parameter(
                key.decode(), org_policy_bytes(msp.decode())
            )
            return Response(200)

    state, v = _fresh(net)
    rt = ChaincodeRuntime()
    rt.register(CC, EPContract())
    sim = TxSimulator(state)
    resp = rt.execute(sim, CC, [b"lock", b"asset1", b"Org2MSP"])
    assert resp.status == 200
    rw_bytes, _ = sim.done()
    parsed = TxRWSet.from_bytes(rw_bytes)
    assert parsed.ns[CC].metadata_writes["asset1"][VALIDATION_PARAMETER]
    # and GetStateValidationParameter reads the committed policy back
    signed = _tx(net, [net["p1"]], writes=[("asset1", b"locked")],
                 meta={"asset1": _sbe_meta("Org2MSP")})
    flt, batch, _ = v.validate(_block([signed], num=2))
    state.apply_updates(batch, (2, 0))
    sim2 = TxSimulator(state)
    assert sim2.get_state_validation_parameter(CC, "asset1") == \
        org_policy_bytes("Org2MSP")

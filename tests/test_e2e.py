"""End-to-end network tests: client → endorsers → raft orderers →
peer commit pipeline → state, all over real localhost sockets.

The nwo-harness analog (integration/nwo + integration/e2e): a network
description (2 orgs × 1 peer, 3 orderers, one channel, KV chaincode)
is brought up in-process, then exercised through the same protocol
surfaces a real deployment uses."""

import asyncio
import json

import pytest

from fabric_tpu.comm.rpc import RpcClient
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ordering.blockcutter import BatchConfig
from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.node import PeerNode
from fabric_tpu.peer.validator import NamespaceInfo, PolicyProvider
from fabric_tpu.protos import proposal_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL = "e2echan"
CC = "kvcc"


def run(coro, timeout=90):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=15.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return False


@pytest.fixture(scope="module")
def material():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
    return {
        "mgr": mgr,
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "p1": cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        "p2": cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    }


class Network:
    """2 peers (one per org), 3 orderers, one channel, KV chaincode."""

    def __init__(self, material, tmp_path):
        self.m = material
        self.tmp = tmp_path
        self.orderers = []
        self.peers = []
        self.client = None

    async def up(self):
        cluster = {}
        for i in range(3):
            n = OrdererNode(
                f"o{i}", str(self.tmp / f"o{i}"), cluster,
                batch_config=BatchConfig(max_message_count=3, batch_timeout_s=0.2),
            )
            await n.start()
            cluster[n.id] = ("127.0.0.1", n.port)
            self.orderers.append(n)
        for n in self.orderers:
            n.cluster.update(cluster)
            n.join_channel(CHANNEL)

        policy = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.peer')")
        orderer_addrs = list(cluster.values())
        for name, signer in (("peer1", self.m["p1"]), ("peer2", self.m["p2"])):
            runtime = ChaincodeRuntime()
            runtime.register(CC, KVContract())
            p = PeerNode(name, str(self.tmp / name), self.m["mgr"], signer, runtime)
            await p.start()
            prov = PolicyProvider({CC: NamespaceInfo(policy=policy)})
            ch = p.join_channel(CHANNEL, prov)
            ch.start_deliver(orderer_addrs)
            self.peers.append(p)
        # one warmup loads the verify kernel into the in-process jit
        # cache for BOTH peers (first-block commits must not eat it)
        self.peers[0].channels[CHANNEL].validator.warmup()
        self.client = BroadcastClient(orderer_addrs)
        assert await _wait(lambda: any(
            n.chains[CHANNEL].raft.state == "leader" for n in self.orderers))

    async def down(self):
        if self.client:
            await self.client.close()
        for p in self.peers:
            await p.stop()
        for n in self.orderers:
            await n.stop()

    async def endorse(self, args, signer=None, transient=None):
        signer = signer or self.m["client"]
        signed, tx_id, prop = txa.create_signed_proposal(
            signer, CHANNEL, CC, args, transient=transient
        )
        responses = []
        for p in self.peers:
            cli = RpcClient("127.0.0.1", p.port)
            await cli.connect()
            raw = await cli.unary("Endorse", signed.SerializeToString())
            await cli.close()
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            responses.append(pr)
        return prop, responses, tx_id

    async def submit(self, args, signer=None, endorsers=None):
        signer = signer or self.m["client"]
        prop, responses, tx_id = await self.endorse(args, signer)
        good = [r for r in responses if r.response.status < 400]
        use = good if endorsers is None else good[:endorsers]
        env = txa.assemble_transaction(prop, use, signer)
        res = await self.client.broadcast(CHANNEL, env.SerializeToString())
        assert res["status"] == 200, res
        return tx_id

    async def query(self, peer, key):
        cli = RpcClient("127.0.0.1", peer.port)
        await cli.connect()
        resp = json.loads(await cli.unary("Query", json.dumps(
            {"channel": CHANNEL, "ns": CC, "key": key}
        ).encode()))
        await cli.close()
        return bytes.fromhex(resp["value"]) if resp.get("value") else None

    async def heights(self):
        return [p.channels[CHANNEL].height for p in self.peers]

    async def wait_all(self, h, timeout=20):
        for p in self.peers:
            await p.channels[CHANNEL].wait_height(h, timeout)

    def tx_code(self, peer, tx_num_from_end=0):
        from fabric_tpu import protoutil as pu

        ch = peer.channels[CHANNEL]
        blk = ch.ledger.blocks.get_block(ch.height - 1)
        return list(pu.get_tx_filter(blk))


@pytest.mark.slow
def test_e2e_submit_endorse_order_commit(material, tmp_path):
    async def scenario():
        net = Network(material, tmp_path)
        await net.up()
        try:
            # happy path: put k1=v1, both endorsers
            await net.submit([b"put", b"k1", b"v1"])
            await net.submit([b"put", b"k2", b"v2"])
            await net.submit([b"put", b"acct-a", b"100"])
            await net.wait_all(1)
            await _wait(lambda: False, timeout=0.5)  # settle timeout batch
            # all peers converge and agree
            for p in net.peers:
                await _wait(
                    lambda p=p: None not in
                    (net.peers[0].channels[CHANNEL].ledger.state.get_state(CC, "acct-a"),),
                    timeout=10,
                )
            assert await _wait(lambda: all(
                p.channels[CHANNEL].ledger.state.get_state(CC, "k1") is not None
                for p in net.peers), timeout=10)
            for p in net.peers:
                assert (await net.query(p, "k1")) == b"v1"
                assert (await net.query(p, "k2")) == b"v2"
                assert (await net.query(p, "acct-a")) == b"100"

            # read-modify-write through chaincode; endorsed state matches
            await net.submit([b"transfer", b"acct-a", b"acct-b", b"30"])

            def _b_is_30(p):
                vv = p.channels[CHANNEL].ledger.state.get_state(CC, "acct-b")
                return vv is not None and vv.value == b"30"

            assert await _wait(
                lambda: all(_b_is_30(p) for p in net.peers), timeout=10)
            for p in net.peers:
                assert (await net.query(p, "acct-a")) == b"70"

            # identical chains on both peers
            h = min(await net.heights())
            c0 = net.peers[0].channels[CHANNEL]
            c1 = net.peers[1].channels[CHANNEL]
            for k in range(h):
                assert (c0.ledger.blocks.get_block(k).SerializeToString()
                        == c1.ledger.blocks.get_block(k).SerializeToString())
            assert c0.ledger.commit_hash == c1.ledger.commit_hash
        finally:
            await net.down()

    run(scenario())


@pytest.mark.slow
def test_e2e_policy_and_mvcc_rejections(material, tmp_path):
    async def scenario():
        net = Network(material, tmp_path)
        await net.up()
        try:
            await net.submit([b"put", b"bal", b"100"])
            assert await _wait(lambda: all(
                p.channels[CHANNEL].ledger.state.get_state(CC, "bal") is not None
                for p in net.peers), timeout=10)

            # under-endorsed tx (1 of 2 required orgs): committed as
            # ENDORSEMENT_POLICY_FAILURE, state unchanged
            h0 = net.peers[0].channels[CHANNEL].height
            await net.submit([b"put", b"bal", b"999"], endorsers=1)
            assert await _wait(lambda: net.peers[0].channels[CHANNEL].height > h0,
                               timeout=10)
            for p in net.peers:
                assert (await net.query(p, "bal")) == b"100"
            codes = net.tx_code(net.peers[0])
            assert C.ENDORSEMENT_POLICY_FAILURE in codes

            # double-spend race: two txs endorsed against the same
            # version; the second to order must MVCC-fail
            prop_a, resp_a, _ = await net.endorse([b"transfer", b"bal", b"x", b"60"])
            prop_b, resp_b, _ = await net.endorse([b"transfer", b"bal", b"y", b"70"])
            env_a = txa.assemble_transaction(prop_a, resp_a, net.m["client"])
            env_b = txa.assemble_transaction(prop_b, resp_b, net.m["client"])
            for env in (env_a, env_b):
                res = await net.client.broadcast(CHANNEL, env.SerializeToString())
                assert res["status"] == 200
            assert await _wait(lambda: all(
                (p.channels[CHANNEL].ledger.state.get_state(CC, "x") is not None
                 or p.channels[CHANNEL].ledger.state.get_state(CC, "y") is not None)
                for p in net.peers), timeout=10)
            await _wait(lambda: False, timeout=1.0)  # let both commit
            for p in net.peers:
                x = await net.query(p, "x")
                y = await net.query(p, "y")
                bal = await net.query(p, "bal")
                # exactly one transfer won
                assert (x, y, bal) in ((b"60", None, b"40"), (None, b"70", b"30"))
            # both peers agree on the winner
            assert (await net.query(net.peers[0], "x")) == (await net.query(net.peers[1], "x"))
        finally:
            await net.down()

    run(scenario())

"""Device batch-sign lane (ops/p256sign) vs the RFC 6979 serial
oracle (crypto/ec_ref) — bit-equality across random and edge scalars,
knob composition, and the verify-after-sign self-check.  Crypto-free:
everything here runs on the pure-Python oracle + the jax CPU mesh.
"""

import numpy as np
import pytest

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import p256sign

N = ec_ref.N
P = ec_ref.P


# -- RFC 6979 (satellite 1: the host oracle the device lane matches) --------

# RFC 6979 A.2.5, P-256 + SHA-256 published vectors
_X = 0xC9AFA9D845BA75166B5C215767B1D6934E50C3DB36E89B127B8A622B120F6721
_VECTORS = [
    (b"sample",
     0xA6E3C57DD01ABE90086538398355DD4C3B17AA873382B0F24D6129493D8AAD60,
     0xEFD48B2AACB6A8FD1140DD9CD45E81D69D2C877B56AAF991C34D0EA84EAF3716,
     0xF7CB1C942D657C41D436C7A1B6E29F65F3E900DBB9AFF4064DC4AB2F843ACDA8),
    (b"test",
     0xD16B6AE827F17175E040871A1C7EC3500192C4C92677336EC2537ACAEE0008E0,
     0xF1ABB023518351CD71D881567B1EA663ED3EFCF6C5132B354F28D3B0B7D38367,
     0x019F4113742A2B14BD25926B49C649155F267E60D3814B4C0CC84250E46F0083),
]


def test_rfc6979_published_vectors():
    for msg, want_k, want_r, want_s in _VECTORS:
        e = ec_ref.digest_int(msg)
        assert ec_ref.rfc6979_k(_X, e) == want_k
        r, s = ec_ref.SigningKey(_X).sign_digest(e)
        assert r == want_r
        # the repo signs low-S (bccsp/sw ToLowS); the RFC publishes the
        # raw s — equal directly when already low, else as n − s
        assert s == (want_s if want_s <= ec_ref.HALF_N else N - want_s)
        assert s <= ec_ref.HALF_N
        assert ec_ref.verify_digest(
            ec_ref.SigningKey(_X).public, e, r, s
        )


def test_sign_digest_default_is_deterministic():
    key = ec_ref.SigningKey(_X)
    e = ec_ref.digest_int(b"replay me")
    assert key.sign_digest(e) == key.sign_digest(e)


def test_rfc6979_rejects_bad_scalar():
    with pytest.raises(ValueError):
        ec_ref.rfc6979_k(0, 5)
    with pytest.raises(ValueError):
        ec_ref.rfc6979_k(N, 5)


def test_der_codec_round_trip():
    r, s = ec_ref.SigningKey(_X).sign_digest(ec_ref.digest_int(b"der"))
    der = ec_ref.der_encode_sig(r, s)
    assert ec_ref.der_decode_sig(der) == (r, s)
    # tiny integers keep a minimal encoding and still round-trip
    # (ranges permitting: encode rejects out-of-range r/s)
    small = ec_ref.der_encode_sig(1, 2)
    assert ec_ref.der_decode_sig(small) == (1, 2)
    for bad in (b"", b"\x30\x00", der[:-1], der + b"\x00",
                b"\x31" + der[1:]):
        with pytest.raises(ValueError):
            ec_ref.der_decode_sig(bad)
    with pytest.raises(ValueError):
        ec_ref.der_encode_sig(0, 2)
    with pytest.raises(ValueError):
        ec_ref.der_encode_sig(1, N)


# -- device lane ≡ oracle ----------------------------------------------------


@pytest.fixture(scope="module")
def warm():
    """Compile the 16-lane sign kernel once for the whole module."""
    p256sign.sign_digests([ec_ref.digest_int(b"warm")], _X)
    return True


def test_sign_batch_matches_oracle_random(warm):
    rng = np.random.default_rng(29)
    digests = [int.from_bytes(rng.bytes(32), "big") for _ in range(16)]
    ds = [int.from_bytes(rng.bytes(32), "big") % (N - 1) + 1
          for _ in range(16)]
    assert p256sign.sign_digests(digests, ds) == p256sign.sign_host(
        digests, ds
    )


def test_sign_edge_scalars(warm):
    """The acceptance edge sweep: k and d near 0/1/n−1, high-bit and
    over-n digests — every lane bit-equal to the fixed-k oracle."""
    es = [0, 1, 1 << 255, N - 1, N, (1 << 256) - 1]
    lanes = []
    for d in (1, 2, N - 1):
        for k in (1, 2, N - 2, N - 1):
            lanes.append((es[len(lanes) % len(es)], d, k))
    lanes = lanes[:16]
    digests = [e for e, _, _ in lanes]
    ds = [d for _, d, _ in lanes]
    ks = [k for _, _, k in lanes]
    got = p256sign.sign_digests(digests, ds, ks=ks)
    want = [
        ec_ref.SigningKey(d).sign_digest(e, k=k)
        for e, d, k in lanes
    ]
    assert got == want


def test_sign_nonbucket_batch_pads_clean(warm):
    """5 lanes pad to the 16 bucket with k=1 pad rows; real lanes are
    untouched and the handle returns exactly n_real results."""
    digests = [ec_ref.digest_int(b"p%d" % i) for i in range(5)]
    got = p256sign.sign_digests(digests, _X)
    assert len(got) == 5
    assert got == p256sign.sign_host(digests, _X)


def test_sign_chunked_matches_oracle(warm):
    """chunk=16 over 20 lanes: two 16-lane dispatches (the tail
    absorbs the bucket padding) — same signatures as the oracle."""
    rng = np.random.default_rng(31)
    digests = [int.from_bytes(rng.bytes(32), "big") for _ in range(20)]
    got = p256sign.sign_digests(digests, _X, chunk=16)
    assert got == p256sign.sign_host(digests, _X)


def test_sign_mesh_sharded_matches_oracle(warm):
    from fabric_tpu.parallel.mesh import resolve_mesh

    mesh = resolve_mesh(8)
    assert mesh is not None  # conftest forces 8 host devices
    digests = [ec_ref.digest_int(b"m%d" % i) for i in range(16)]
    got = p256sign.sign_digests(digests, _X, mesh=mesh)
    assert got == p256sign.sign_host(digests, _X)


def test_sign_round_trips_through_verify_launch(warm):
    """Acceptance: every device-signed (e, r, s) verifies through the
    EXISTING device verify lane, and a tampered lane is rejected."""
    from fabric_tpu.ops import p256v3

    digests = [ec_ref.digest_int(b"rt%d" % i) for i in range(4)]
    sigs = p256sign.sign_digests(digests, _X)
    qx, qy = ec_ref.pt_mul(_X, ec_ref.G)
    items = [(e, r, s, qx, qy) for e, (r, s) in zip(digests, sigs)]
    assert p256v3.verify_launch(items)() == [True] * 4
    # tamper one digest → only that lane flips
    bad = list(items)
    e0, r0, s0, x0, y0 = bad[1]
    bad[1] = (e0 ^ 1, r0, s0, x0, y0)
    assert p256v3.verify_launch(bad)() == [True, False, True, True]


def test_verify_after_sign_self_check(warm):
    digests = [ec_ref.digest_int(b"sc%d" % i) for i in range(3)]
    # clean batch passes through the self-check lane unchanged
    assert (p256sign.sign_digests(digests, _X, verify_after=True)
            == p256sign.sign_host(digests, _X))
    # a corrupted signature is refused before release
    good = p256sign.sign_host(digests, _X)
    r0, s0 = good[1]
    good[1] = (r0 ^ 1, s0)
    with pytest.raises(RuntimeError, match="verify-after-sign"):
        p256sign._self_check(digests, [_X] * 3, good)


def test_sign_launch_validation():
    e = ec_ref.digest_int(b"v")
    with pytest.raises(ValueError):
        p256sign.sign_launch([e], 0)  # d out of range
    with pytest.raises(ValueError):
        p256sign.sign_launch([e], N)
    with pytest.raises(ValueError):
        p256sign.sign_launch([e], [_X, _X])  # per-lane length mismatch
    with pytest.raises(ValueError):
        p256sign.sign_launch([e], _X, ks=[0])  # nonce out of range
    with pytest.raises(ValueError):
        p256sign.sign_launch([e], _X, ks=[1, 2])  # nonce length
    assert p256sign.sign_launch([], _X).fetch() == []


def test_derive_nonces_pooled_matches_serial():
    from fabric_tpu.parallel.hostpool import HostStagePool

    digests = [ec_ref.digest_int(b"n%d" % i) for i in range(48)]
    ds = [_X] * 48
    serial = p256sign.derive_nonces(digests, ds)
    assert serial == [
        ec_ref.rfc6979_k(_X, e) for e in digests
    ]
    pool = HostStagePool(2)
    try:
        assert p256sign.derive_nonces(digests, ds, pool=pool) == serial
    finally:
        pool.shutdown()

"""MSP + identity + cryptogen tests (reference semantics:
msp/mspimpl.go Setup/DeserializeIdentity/SatisfiesPrincipal)."""

import pytest

from fabric_tpu.crypto import cryptogen, ec_ref, msp as msp_mod, policy as pol
from fabric_tpu.crypto.identity import Identity, SigningIdentity
from fabric_tpu.protos import policies_pb2


@pytest.fixture(scope="module")
def org():
    return cryptogen.generate_org("Org1MSP", "org1.example.com", peers=2, users=1)


@pytest.fixture(scope="module")
def org2():
    return cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)


def test_sign_verify_roundtrip(org):
    si = cryptogen.signing_identity(org, "peer0.org1.example.com")
    msg = b"endorsement payload"
    sig = si.sign(msg)
    ident = si.identity
    assert ident.verify(msg, sig)
    assert not ident.verify(msg + b"x", sig)
    # low-S enforced at signing
    from fabric_tpu.crypto.identity import sig_to_ints

    _, s = sig_to_ints(sig)
    assert s <= ec_ref.HALF_N


def test_deserialize_validate_roles(org):
    m = org.msp()
    peer = cryptogen.signing_identity(org, "peer0.org1.example.com")
    ident = m.deserialize_identity(peer.serialized)
    assert ident.is_valid and ident.role == "peer"
    admin = cryptogen.signing_identity(org, "Admin@org1.example.com")
    aident = m.deserialize_identity(admin.serialized)
    assert aident.is_valid and aident.role == "admin"
    user = cryptogen.signing_identity(org, "User1@org1.example.com")
    uident = m.deserialize_identity(user.serialized)
    assert uident.is_valid and uident.role == "client"
    # cache hit returns same object
    assert m.deserialize_identity(peer.serialized) is ident


def test_foreign_and_forged_identities_rejected(org, org2):
    m = org.msp()
    foreign = cryptogen.signing_identity(org2, "peer0.org2.example.com")
    ident = m.deserialize_identity(foreign.serialized)
    assert not ident.is_valid  # wrong msp id → not validated against Org1 roots
    # forged: Org1 msp id but cert from Org2's CA
    forged = SigningIdentity("Org1MSP", foreign.key, foreign.cert)
    fident = m.deserialize_identity(forged.serialized)
    assert fident.msp_id == "Org1MSP" and not fident.is_valid


def test_satisfies_principal_proto(org, org2):
    mgr = msp_mod.MSPManager({"Org1MSP": org.msp(), "Org2MSP": org2.msp()})
    peer = cryptogen.signing_identity(org, "peer0.org1.example.com")
    ident = mgr.deserialize_identity(peer.serialized)

    def role_principal(mspid, role):
        return policies_pb2.MSPPrincipal(
            principal_classification=policies_pb2.MSPPrincipal.ROLE,
            principal=policies_pb2.MSPRole(
                msp_identifier=mspid, role=role
            ).SerializeToString(),
        )

    assert mgr.satisfies_principal(ident, role_principal("Org1MSP", policies_pb2.MSPRole.MEMBER))
    assert mgr.satisfies_principal(ident, role_principal("Org1MSP", policies_pb2.MSPRole.PEER))
    assert not mgr.satisfies_principal(ident, role_principal("Org1MSP", policies_pb2.MSPRole.ADMIN))
    assert not mgr.satisfies_principal(ident, role_principal("Org2MSP", policies_pb2.MSPRole.MEMBER))
    # OU principal
    oup = policies_pb2.MSPPrincipal(
        principal_classification=policies_pb2.MSPPrincipal.ORGANIZATION_UNIT,
        principal=policies_pb2.OrganizationUnit(
            msp_identifier="Org1MSP", organizational_unit_identifier="peer"
        ).SerializeToString(),
    )
    assert mgr.satisfies_principal(ident, oup)
    # IDENTITY principal
    idp = policies_pb2.MSPPrincipal(
        principal_classification=policies_pb2.MSPPrincipal.IDENTITY,
        principal=peer.serialized,
    )
    assert mgr.satisfies_principal(ident, idp)


def test_match_matrix_and_policy_bridge(org, org2):
    mgr = msp_mod.MSPManager({"Org1MSP": org.msp(), "Org2MSP": org2.msp()})
    rule = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.member')")
    plan = pol.compile_plan(rule)
    s1 = cryptogen.signing_identity(org, "peer0.org1.example.com").serialized
    s2 = cryptogen.signing_identity(org2, "peer0.org2.example.com").serialized
    m = mgr.match_matrix([s1, s2], plan.principals)
    assert pol.evaluate(rule, m)
    assert plan.consumption_safe(m) and plan.evaluate_counts(m)
    m1 = mgr.match_matrix([s1], plan.principals)
    assert not pol.evaluate(rule, m1)


def test_policy_proto_roundtrip():
    rule = pol.from_dsl("OutOf(2, 'A.member', 'B.admin', 'C.peer')")
    env = msp_mod.policy_to_proto(rule)
    back = msp_mod.policy_from_proto(env)
    assert back == rule


def test_msp_config_proto_roundtrip(org):
    m = org.msp()
    cfg = m.to_proto()
    m2 = msp_mod.MSP.from_proto(cfg)
    assert m2.msp_id == "Org1MSP" and m2.node_ous
    peer = cryptogen.signing_identity(org, "peer1.org1.example.com")
    assert m2.deserialize_identity(peer.serialized).is_valid


def test_revocation(org):
    m = org.msp()
    peer = cryptogen.signing_identity(org, "peer0.org1.example.com")
    m.revoked_serials.add(peer.cert.serial_number)
    ident = m.deserialize_identity(peer.serialized)
    assert not ident.is_valid


def _make_intermediate_chain(expired_intermediate=False):
    """root → intermediate → leaf, with the intermediate optionally
    already expired (leaf window always valid)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec

    now = datetime.datetime.now(datetime.timezone.utc)
    day = datetime.timedelta(days=1)
    root = cryptogen.CA.create("chain.example.com")

    ikey = ec.generate_private_key(ec.SECP256R1())
    istart = now - 30 * day
    iend = now - day if expired_intermediate else now + 365 * day
    icert = (
        x509.CertificateBuilder()
        .subject_name(cryptogen._name("ica.chain.example.com", "chain.example.com"))
        .issuer_name(root.cert.subject)
        .public_key(ikey.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(istart)
        .not_valid_after(iend)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .sign(root.key, hashes.SHA256())
    )
    ica = cryptogen.CA(
        org="chain.example.com", cn="ica.chain.example.com", key=ikey, cert=icert
    )
    leaf = ica.issue("peer0.chain.example.com", ou="peer")
    m = msp_mod.MSP(
        "ChainMSP",
        root_certs=[root.cert_pem],
        intermediate_certs=[cryptogen._pem_cert(icert)],
    )
    si = SigningIdentity("ChainMSP", leaf.key, leaf.cert)
    return m, si, icert


def test_expired_intermediate_invalidates_chain():
    """Validity windows apply to EVERY cert in the chain — an expired
    intermediate must not validate a fresh leaf (round-2 VERDICT weak
    #7 regression)."""
    m, si, _ = _make_intermediate_chain(expired_intermediate=True)
    assert not m.deserialize_identity(si.serialized).is_valid
    m2, si2, _ = _make_intermediate_chain(expired_intermediate=False)
    assert m2.deserialize_identity(si2.serialized).is_valid


def test_revoked_intermediate_invalidates_chain():
    """CRL serials apply to intermediates, not just leaves."""
    m, si, icert = _make_intermediate_chain()
    m.revoked_serials.add(icert.serial_number)
    assert not m.deserialize_identity(si.serialized).is_valid

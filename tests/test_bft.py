"""BFT consenter tests: quorum agreement with signed messages, forged
traffic rejection, leader-crash view change with re-proposal, and a
4-orderer socket network surviving leader failure (reference:
orderer/consensus/smartbft, SmartBFT 3f+1 semantics)."""

import asyncio
import json

import pytest

from fabric_tpu.crypto import cryptogen
from fabric_tpu.ordering.bft import BFTNode, PREPARE, _signable
from fabric_tpu.ordering.raft import WAL


def run(coro, timeout=60):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


async def _wait(cond, timeout=10.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return False


def _mk_cluster(tmp_path, n=4, view_timeout=0.5):
    org = cryptogen.generate_org("OrdererMSP", "orderer.example.com",
                                 peers=0, orderers=n, users=0)
    ids = [f"o{i}" for i in range(n)]
    signers = {
        oid: cryptogen.signing_identity(org, f"orderer{i}.orderer.example.com")
        for i, oid in enumerate(ids)
    }
    from fabric_tpu.crypto.msp import MSPManager

    mgr = MSPManager({"OrdererMSP": org.msp()})
    verifiers = {
        oid: mgr.deserialize_identity(signers[oid].serialized)
        for oid in ids
    }
    nodes: dict[str, BFTNode] = {}
    applied: dict[str, list] = {oid: [] for oid in ids}
    down: set = set()

    def send_cb_for(src):
        def send(dst, msg):
            if dst in down or src in down:
                return
            node = nodes.get(dst)
            if node is not None:
                # async delivery like a real transport; deep-copy via json
                asyncio.get_event_loop().call_soon(
                    node.handle, json.loads(json.dumps(msg))
                )
        return send

    for i, oid in enumerate(ids):
        nodes[oid] = BFTNode(
            oid, ids, WAL(str(tmp_path / oid)),
            apply_cb=(lambda o: (lambda e: applied[o].append(e)))(oid),
            send_cb=send_cb_for(oid),
            signer=signers[oid], verifiers=verifiers,
            view_timeout=view_timeout,
        )
    return nodes, applied, down, signers, verifiers


def test_bft_normal_case_and_order(tmp_path):
    async def scenario():
        nodes, applied, down, _, _ = _mk_cluster(tmp_path)
        for n in nodes.values():
            n.start()
        leader = nodes["o0"]
        assert leader.state == "leader"
        for i in range(5):
            seq = leader.propose(b"batch-%d" % i)
            assert seq == i + 1
        assert await _wait(lambda: all(
            len(applied[o]) == 5 for o in nodes
        ))
        for o, entries in applied.items():
            assert [e.data for e in entries] == [b"batch-%d" % i for i in range(5)]
            assert [e.index for e in entries] == list(range(1, 6))
        for n in nodes.values():
            n.stop()

    run(scenario())


def test_bft_rejects_forged_messages(tmp_path):
    async def scenario():
        nodes, applied, down, signers, verifiers = _mk_cluster(tmp_path)
        n0 = nodes["o0"]
        n0.start()
        # a message claiming to be from o1 but signed by o3 (byzantine)
        forged = {"type": PREPARE, "from": "o1", "view": 0, "seq": 1,
                  "digest": "00" * 32}
        forged["sig"] = signers["o3"].sign(_signable(forged)).hex()
        n0.handle(forged)
        assert "o1" not in n0._slot(1).prepares
        # unsigned message: dropped too
        n0.handle({"type": PREPARE, "from": "o2", "view": 0, "seq": 1,
                   "digest": "00" * 32})
        assert "o2" not in n0._slot(1).prepares
        # properly signed message: accepted
        good = {"type": PREPARE, "from": "o1", "view": 0, "seq": 1,
                "digest": "11" * 32}
        good["sig"] = signers["o1"].sign(_signable(good)).hex()
        n0.handle(good)
        assert n0._slot(1).prepares.get("o1") == "11" * 32
        n0.stop()

    run(scenario())


def test_bft_view_change_on_leader_crash(tmp_path):
    async def scenario():
        nodes, applied, down, _, _ = _mk_cluster(tmp_path, view_timeout=0.4)
        for n in nodes.values():
            n.start()
        leader = nodes["o0"]
        leader.propose(b"committed-before-crash")
        assert await _wait(lambda: all(len(applied[o]) == 1 for o in nodes))

        # leader dies; a client demand at a follower starts the clock
        down.add("o0")
        nodes["o0"].stop()
        for oid in ("o1", "o2", "o3"):
            nodes[oid].note_client_request()
        assert await _wait(
            lambda: nodes["o1"].view == 1 and nodes["o1"].state == "leader", 10
        )
        # the new leader makes progress
        seq = nodes["o1"].propose(b"after-view-change")
        assert seq is not None
        assert await _wait(lambda: all(
            len(applied[o]) == 2 for o in ("o1", "o2", "o3")
        ))
        for o in ("o1", "o2", "o3"):
            assert applied[o][1].data == b"after-view-change"
        for n in nodes.values():
            n.stop()

    run(scenario())


@pytest.mark.slow
def test_bft_orderer_network(tmp_path):
    """4 BFT orderers over real sockets: ordered batches replicate;
    killing the leader does not lose the chain."""
    from fabric_tpu.ordering.blockcutter import BatchConfig
    from fabric_tpu.ordering.node import BroadcastClient, OrdererNode
    from fabric_tpu.crypto.msp import MSPManager

    CHANNEL = "bftchan"

    async def scenario():
        org = cryptogen.generate_org("OrdererMSP", "orderer.example.com",
                                     peers=0, orderers=4, users=0)
        mgr = MSPManager({"OrdererMSP": org.msp()})
        ids = [f"o{i}" for i in range(4)]
        signers = {
            oid: cryptogen.signing_identity(
                org, f"orderer{i}.orderer.example.com")
            for i, oid in enumerate(ids)
        }
        verifiers = {
            oid: mgr.deserialize_identity(signers[oid].serialized)
            for oid in ids
        }
        cluster = {}
        nodes = []
        for oid in ids:
            n = OrdererNode(
                oid, str(tmp_path / oid), cluster,
                batch_config=BatchConfig(max_message_count=1,
                                         batch_timeout_s=0.1),
                consensus="bft", signer=signers[oid], verifiers=verifiers,
                view_timeout=0.8,
            )
            await n.start()
            cluster[oid] = ("127.0.0.1", n.port)
            nodes.append(n)
        for n in nodes:
            n.cluster.update(cluster)
            n.join_channel(CHANNEL)
        try:
            bc = BroadcastClient(list(cluster.values()))
            env = b"envelope-payload-1"
            res = await bc.broadcast(CHANNEL, env)
            assert res["status"] == 200, res
            assert await _wait(lambda: all(
                n.chains[CHANNEL].height >= 1 for n in nodes
            ), 15)

            # kill the current leader; the cluster re-forms and accepts
            leader_id = nodes[0].chains[CHANNEL].raft.leader_id
            victim = next(n for n in nodes if n.id == leader_id)
            await victim.stop()
            nodes.remove(victim)

            res = await bc.broadcast(CHANNEL, b"envelope-payload-2", retries=60)
            assert res["status"] == 200, res
            assert await _wait(lambda: all(
                n.chains[CHANNEL].height >= 2 for n in nodes
            ), 15)
            # headers + data are identical across orderers; the
            # SIGNATURES metadata differs per node (each consenter
            # signs its own materialized copy — peers verify whichever
            # copy they receive, and the hash chain covers headers
            # only, so copies are interchangeable)
            import json as _json

            from fabric_tpu import protoutil as pu
            from fabric_tpu.protos import common_pb2

            hd = [
                [(n.chains[CHANNEL].blocks.get_block(k).header.SerializeToString(),
                  n.chains[CHANNEL].blocks.get_block(k).data.SerializeToString())
                 for k in range(2)]
                for n in nodes
            ]
            assert hd[0] == hd[1] == hd[2]
            for n in nodes:
                blk = n.chains[CHANNEL].blocks.get_block(1)
                sets = pu.block_signed_data(blk)
                assert len(sets) == 1  # own signature present
                omd = _json.loads(
                    bytes(blk.metadata.metadata[
                        common_pb2.BlockMetadataIndex.ORDERER])
                )
                # quorum commit proof rides the consensus metadata
                assert len(omd["bft_proof"]) >= 3
            await bc.close()
        finally:
            for n in nodes:
                await n.stop()

    run(scenario(), timeout=90)


def test_bft_chain_restart_recovers_blocks(tmp_path):
    """An OrderingChain on the BFT consenter restarted mid-stream must
    not lose or duplicate blocks: the WAL replay re-fires apply_cb and
    the chain skips batches already materialized (the raft-recovery
    contract, shared by both consenters)."""
    from fabric_tpu.ordering.blockcutter import BatchConfig
    from fabric_tpu.ordering.chain import OrderingChain

    async def scenario():
        sent = []

        def send_cb(peer, msg):
            sent.append((peer, msg))

        def mk():
            return OrderingChain(
                "bftrestart", "solo", ["solo"],
                data_dir=str(tmp_path / "chain"), send_cb=send_cb,
                config=BatchConfig(max_message_count=1, batch_timeout_s=0.05),
                consensus="bft",
            )

        chain = mk()
        chain.start()
        for i in range(3):
            res = await chain.broadcast(b"env-%d" % i)
            assert res["status"] == 200, res
        assert chain.height == 3
        blocks_before = [
            chain.blocks.get_block(k).SerializeToString() for k in range(3)
        ]
        chain.stop()

        # restart from disk: WAL + block store agree, nothing re-cut
        chain2 = mk()
        chain2.start()
        assert chain2.height == 3
        for k in range(3):
            assert chain2.blocks.get_block(k).SerializeToString() == blocks_before[k]
        res = await chain2.broadcast(b"env-3")
        assert res["status"] == 200
        assert chain2.height == 4
        assert chain2.blocks.get_block(3).data.data[0] == b"env-3"
        chain2.stop()

    run(scenario())


def test_bft_new_view_requires_justification(tmp_path):
    """A NEW_VIEW without a 2f+1 signed VIEW-CHANGE justification must
    not install a view — a byzantine future leader can no longer
    unilaterally wipe prepared state (PBFT §4.4; ADVICE r3 high)."""
    async def scenario():
        nodes, applied, down, signers, _ = _mk_cluster(tmp_path)
        for n in nodes.values():
            n.start()
        try:
            o0, o1 = nodes["o0"], nodes["o1"]
            assert o0.view == 0
            # bare NEW_VIEW (vcs absent) properly signed by o1, the
            # legitimate leader of view 1
            forged = o1._sign({"type": "bft_new_view", "from": "o1",
                               "view": 1, "vcs": {}})
            o0.handle(json.loads(json.dumps(forged)))
            await asyncio.sleep(0.1)
            assert o0.view == 0  # refused

            # now a justified one: collect real VIEW-CHANGEs from the
            # other nodes (suppress o1's own auto-new-view by keeping
            # its inbox closed)
            down.add("o1")
            for oid in ("o0", "o2", "o3"):
                nodes[oid].request_view_change()
            assert await _wait(lambda: len(o0.view_changes.get(1, {})) >= 3)
            vcs = {k: json.loads(json.dumps(v))
                   for k, v in o0.view_changes[1].items()}
            nv = o1._sign({"type": "bft_new_view", "from": "o1",
                           "view": 1, "vcs": vcs})
            o0.handle(json.loads(json.dumps(nv)))
            await asyncio.sleep(0.05)
            assert o0.view == 1  # installed with proof
        finally:
            for n in nodes.values():
                n.stop()

    run(scenario())


def test_bft_byzantine_new_leader_cannot_drop_or_substitute(tmp_path):
    """A new leader whose NEW_VIEW is justified must still re-propose
    the certified prepared entries verbatim: replicas refuse a
    substitute payload at a reserved sequence (and a dropped entry
    shifts later payloads into reserved slots, which is the same
    refusal)."""
    async def scenario():
        nodes, applied, down, signers, _ = _mk_cluster(tmp_path)
        # suppress COMMIT delivery so seq 1 stays prepared-not-committed
        suppress = {"on": True}
        for oid, node in nodes.items():
            orig = node.send_cb

            def wrap(orig):
                def send(dst, msg):
                    if suppress["on"] and msg.get("type") == "bft_commit":
                        return
                    orig(dst, msg)
                return send
            node.send_cb = wrap(orig)
        for n in nodes.values():
            n.start()
        try:
            o0, o1 = nodes["o0"], nodes["o1"]
            payload_a = b"batch-A"
            o0.propose(payload_a)
            # all honest nodes reach prepared(seq 1, A)
            assert await _wait(lambda: all(
                nodes[o].slots.get(1) is not None
                and len([v for v in nodes[o].slots[1].prepares.values()]) >= 3
                for o in ("o0", "o2", "o3")
            ))
            assert all(nodes[o].last_applied == 0 for o in nodes)

            # view change towards o1 (byzantine: we drive it manually)
            down.add("o1")
            for oid in ("o0", "o2", "o3"):
                nodes[oid].request_view_change()
            assert await _wait(lambda: len(o0.view_changes.get(1, {})) >= 3)
            vcs = {k: json.loads(json.dumps(v))
                   for k, v in o0.view_changes[1].items()}
            nv = o1._sign({"type": "bft_new_view", "from": "o1",
                           "view": 1, "vcs": vcs})
            for oid in ("o0", "o2", "o3"):
                nodes[oid].handle(json.loads(json.dumps(nv)))
            await asyncio.sleep(0.05)
            assert o0.view == 1
            assert o0._expected_repro  # seq 1 reserved for payload A

            # SUBSTITUTE: o1 re-proposes B at the reserved seq
            sub = o1._sign({"type": "bft_pre_prepare", "from": "o1",
                            "view": 1, "seq": 1,
                            "payload": b"batch-EVIL".hex()})
            for oid in ("o0", "o2", "o3"):
                nodes[oid].handle(json.loads(json.dumps(sub)))
            await asyncio.sleep(0.1)
            for oid in ("o0", "o2", "o3"):
                s = nodes[oid].slots.get(1)
                assert s is None or s.payload is None  # refused
                assert nodes[oid]._expected_repro  # still owed A

            # DROP: o1 skips A and proposes a fresh payload at seq 1
            # (same reserved slot) — also refused
            drop = o1._sign({"type": "bft_pre_prepare", "from": "o1",
                             "view": 1, "seq": 1,
                             "payload": b"batch-C".hex()})
            o0.handle(json.loads(json.dumps(drop)))
            await asyncio.sleep(0.05)
            s = o0.slots.get(1)
            assert s is None or s.payload is None

            # honest re-proposal of A is accepted and, with commits
            # re-enabled, commits on every honest node
            suppress["on"] = False
            ok = o1._sign({"type": "bft_pre_prepare", "from": "o1",
                           "view": 1, "seq": 1, "payload": payload_a.hex()})
            for oid in ("o0", "o2", "o3"):
                nodes[oid].handle(json.loads(json.dumps(ok)))
            assert await _wait(lambda: all(
                nodes[o].last_applied == 1 for o in ("o0", "o2", "o3")
            ))
            for o in ("o0", "o2", "o3"):
                assert applied[o][0].data == payload_a
        finally:
            for n in nodes.values():
                n.stop()

    run(scenario())

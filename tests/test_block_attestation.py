"""Block attestation: orderers sign assembled blocks
(blockwriter.go addBlockSignature analog) and peers verify delivered
blocks against the channel's /Channel/Orderer/BlockValidation policy
before commit (common/deliverclient/block_verification.go:243) — a
forged, stripped, or impostor-signed block must never commit."""

import asyncio

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.node import PeerChannel
from fabric_tpu.protos import transaction_pb2
from fabric_tpu.tools import configtxgen as cg

C = transaction_pb2.TxValidationCode
CHANNEL = "attchan"
CC = "attcc"


@pytest.fixture(scope="module")
def material():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    oorg = cryptogen.generate_org(
        "OrdererMSP", "example.com", peers=0, orderers=2, users=0
    )
    profile = cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(org1.msp_id, org1.msp())],
        orderer_orgs=[cg.OrgProfile(oorg.msp_id, oorg.msp())],
    )
    return {
        "org1": org1,
        "genesis": cg.genesis_block(profile),
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "peer": cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        "orderer": cryptogen.signing_identity(oorg, "orderer0.example.com"),
        "orderer2": cryptogen.signing_identity(oorg, "orderer1.example.com"),
    }


def _block(material, num, prev, n_tx=1):
    envs = []
    for i in range(n_tx):
        _, _, prop = txa.create_signed_proposal(
            material["client"], CHANNEL, CC, [b"i"]
        )
        tx = TxRWSet()
        tx.ns_rwset(CC).writes[f"k{num}_{i}"] = b"v"
        rw = tx.to_proto().SerializeToString()
        resps = [txa.create_proposal_response(prop, rw, material["peer"], CC)]
        envs.append(txa.assemble_transaction(prop, resps, material["client"]))
    blk = pu.new_block(num, prev)
    for e in envs:
        blk.data.data.append(e.SerializeToString())
    return pu.finalize_block(blk)


def test_peer_rejects_unsigned_and_forged_blocks(material, tmp_path):
    ch = PeerChannel(
        CHANNEL, str(tmp_path / "peer"), genesis_block=material["genesis"]
    )
    prev = pu.block_header_hash(ch.ledger.blocks.get_block(0).header)

    # unsigned block → rejected before the commit pipeline runs
    blk = _block(material, 1, prev)
    with pytest.raises(ValueError, match="BlockValidation"):
        asyncio.run(ch.commit_block(blk))

    # signed by a NON-orderer identity (an app-org client) → rejected
    blk2 = _block(material, 1, prev)
    pu.sign_block(blk2, material["client"])
    with pytest.raises(ValueError, match="BlockValidation"):
        asyncio.run(ch.commit_block(blk2))

    # properly signed by the orderer org's node → commits
    blk3 = _block(material, 1, prev)
    pu.sign_block(blk3, material["orderer"])
    flt = asyncio.run(ch.commit_block(blk3))
    assert len(flt) == 1
    assert ch.height == 2

    # a signature from ANOTHER block must not transplant: take block
    # 3's valid signature metadata onto a different (forged) block
    prev2 = pu.block_header_hash(ch.ledger.blocks.get_block(1).header)
    forged = _block(material, 2, prev2, n_tx=2)
    idx = blk3.metadata.metadata[0]
    forged.metadata.metadata[0] = idx  # transplanted SIGNATURES entry
    with pytest.raises(ValueError, match="BlockValidation"):
        asyncio.run(ch.commit_block(forged))

    # tampering the header after signing invalidates the signature
    tampered = _block(material, 2, prev2)
    pu.sign_block(tampered, material["orderer"])
    tampered.header.previous_hash = b"\x00" * 32
    with pytest.raises(ValueError, match="BlockValidation"):
        asyncio.run(ch.commit_block(tampered))


def test_ordering_chain_signs_materialized_blocks(material, tmp_path):
    """The consenter's block assembly signs every cut block; the
    signature satisfies the channel policy the peers enforce."""
    from fabric_tpu.channelconfig import Bundle, SignedData
    from fabric_tpu.ordering.blockcutter import BatchConfig
    from fabric_tpu.ordering.chain import OrderingChain
    from fabric_tpu.protos import configtx_pb2, common_pb2

    async def drive():
        chain = OrderingChain(
            CHANNEL, "o0", ["o0"], str(tmp_path / "o0"),
            send_cb=lambda *_: None,
            config=BatchConfig(max_message_count=1, batch_timeout_s=0.05),
            genesis_block=material["genesis"],
            signer=material["orderer"],
        )
        chain.start()
        try:
            _, _, prop = txa.create_signed_proposal(
                material["client"], CHANNEL, CC, [b"i"]
            )
            env = txa.assemble_transaction(
                prop,
                [txa.create_proposal_response(
                    prop, TxRWSet().to_proto().SerializeToString(),
                    material["peer"], CC)],
                material["client"],
            )
            for _ in range(200):
                r = await chain.broadcast(env.SerializeToString())
                if r["status"] == 200:
                    break
                await asyncio.sleep(0.05)
            assert r["status"] == 200
            assert chain.height == 2
            return chain.blocks.get_block(1)
        finally:
            chain.stop()

    loop = asyncio.new_event_loop()
    try:
        blk = loop.run_until_complete(asyncio.wait_for(drive(), 30))
    finally:
        loop.close()

    sets = pu.block_signed_data(blk)
    assert len(sets) == 1
    # the signature satisfies the channel's BlockValidation policy
    env = pu.unmarshal(common_pb2.Envelope, material["genesis"].data.data[0])
    payload = pu.unmarshal(common_pb2.Payload, env.payload)
    cfg_env = pu.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
    bundle = Bundle(CHANNEL, cfg_env.config)
    signed = [
        SignedData(identity=c, data=d, signature=s) for c, d, s in sets
    ]
    assert bundle.policy_manager.evaluate(
        "/Channel/Orderer/BlockValidation", signed
    )


def test_peer_requires_bft_quorum_attestation(material, tmp_path):
    """On a BFT channel, one orderer signature is not enough: the block
    must carry 2f+1 signed COMMITs binding (seq, digest-of-batch), by
    distinct valid orderer identities, with monotone seq."""
    import hashlib
    import json

    from fabric_tpu.ordering.bft import _signable

    org1 = material["org1"]
    oorg = cryptogen.generate_org(
        "OrdererMSP", "bft.example.com", peers=0, orderers=7, users=0
    )
    signers = [
        cryptogen.signing_identity(oorg, f"orderer{i}.bft.example.com")
        for i in range(7)
    ]
    profile = cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(org1.msp_id, org1.msp())],
        orderer_orgs=[cg.OrgProfile(oorg.msp_id, oorg.msp())],
        consensus_type="bft",
        # consenter identities pinned: ONLY signers[0..3] may vote
        raft_consenters=[
            ("h", i + 1, signers[i].serialized) for i in range(4)
        ],
    )
    ch = PeerChannel(
        CHANNEL, str(tmp_path / "bftpeer"), genesis_block=cg.genesis_block(profile)
    )
    prev = pu.block_header_hash(ch.ledger.blocks.get_block(0).header)

    def mk_signed(num, seq, n_sigs=3, digest=None, with_proof=True,
                  sign_from=0):
        blk = _block(material, num, prev)
        payload = json.dumps(
            [bytes(e).hex() for e in blk.data.data]
        ).encode()
        d = digest or hashlib.sha256(payload).hexdigest()
        meta = {"term": 0, "index": seq}
        if with_proof:
            proof = []
            for i in range(sign_from, sign_from + n_sigs):
                m = {"type": "bft_commit", "from": f"o{i}", "view": 0,
                     "seq": seq, "digest": d}
                m["sig"] = signers[i].sign(_signable(m)).hex()
                m["from_cert"] = signers[i].serialized.hex()
                proof.append(m)
            meta["bft_proof"] = proof
        from fabric_tpu.protos import common_pb2 as cpb

        idx = cpb.BlockMetadataIndex.ORDERER
        while len(blk.metadata.metadata) <= idx:
            blk.metadata.metadata.append(b"")
        blk.metadata.metadata[idx] = json.dumps(meta).encode()
        pu.sign_block(blk, signers[0])
        return blk

    # signed but NO quorum proof → rejected
    with pytest.raises(ValueError, match="BFT"):
        asyncio.run(ch.commit_block(mk_signed(1, 1, with_proof=False)))
    # only 2 of quorum-3 commits → rejected
    with pytest.raises(ValueError, match="quorum"):
        asyncio.run(ch.commit_block(mk_signed(1, 1, n_sigs=2)))
    # digest not binding THIS block's batch → rejected
    with pytest.raises(ValueError, match="quorum"):
        asyncio.run(ch.commit_block(mk_signed(1, 1, digest="ab" * 32)))
    # valid orderer-ORG identities that are NOT consenters → rejected
    with pytest.raises(ValueError, match="quorum"):
        asyncio.run(ch.commit_block(mk_signed(1, 1, sign_from=4)))
    # proper 2f+1 attestation → commits
    flt = asyncio.run(ch.commit_block(mk_signed(1, 1)))
    assert len(flt) == 1
    assert ch.height == 2
    # a later block reusing an old (non-advancing) seq → rejected
    prev = pu.block_header_hash(ch.ledger.blocks.get_block(1).header)
    with pytest.raises(ValueError, match="advance"):
        asyncio.run(ch.commit_block(mk_signed(2, 1)))
    flt = asyncio.run(ch.commit_block(mk_signed(2, 2)))
    assert ch.height == 3


def test_single_identity_cannot_forge_bft_quorum(material, tmp_path):
    """One compromised orderer identity fabricating 2f+1 COMMITs under
    distinct invented sender names must NOT satisfy the attestation:
    votes are deduped by identity, not by the unauthenticated 'from'."""
    import hashlib
    import json

    from fabric_tpu.ordering.bft import _signable

    org1 = material["org1"]
    oorg = cryptogen.generate_org(
        "OrdererMSP", "forge.example.com", peers=0, orderers=4, users=0
    )
    evil = cryptogen.signing_identity(oorg, "orderer0.forge.example.com")
    profile = cg.Profile(
        CHANNEL,
        application_orgs=[cg.OrgProfile(org1.msp_id, org1.msp())],
        orderer_orgs=[cg.OrgProfile(oorg.msp_id, oorg.msp())],
        consensus_type="bft",
        raft_consenters=[("h", 1), ("h", 2), ("h", 3), ("h", 4)],
    )
    ch = PeerChannel(
        CHANNEL, str(tmp_path / "forgepeer"),
        genesis_block=cg.genesis_block(profile),
    )
    prev = pu.block_header_hash(ch.ledger.blocks.get_block(0).header)
    blk = _block(material, 1, prev)
    payload = json.dumps([bytes(e).hex() for e in blk.data.data]).encode()
    d = hashlib.sha256(payload).hexdigest()
    proof = []
    for i in range(3):  # distinct names, SAME identity
        m = {"type": "bft_commit", "from": f"fake{i}", "view": 0,
             "seq": 1, "digest": d}
        m["sig"] = evil.sign(_signable(m)).hex()
        m["from_cert"] = evil.serialized.hex()
        proof.append(m)
    # and an app-org member's votes must not count either
    for i in range(2):
        m = {"type": "bft_commit", "from": f"app{i}", "view": 0,
             "seq": 1, "digest": d}
        m["sig"] = material["client"].sign(_signable(m)).hex()
        m["from_cert"] = material["client"].serialized.hex()
        proof.append(m)
    from fabric_tpu.protos import common_pb2 as cpb

    idx = cpb.BlockMetadataIndex.ORDERER
    while len(blk.metadata.metadata) <= idx:
        blk.metadata.metadata.append(b"")
    blk.metadata.metadata[idx] = json.dumps(
        {"term": 0, "index": 1, "bft_proof": proof}
    ).encode()
    pu.sign_block(blk, evil)
    with pytest.raises(ValueError, match="quorum"):
        asyncio.run(ch.commit_block(blk))

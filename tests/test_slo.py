"""SLO burn-rate engine battery (fabric_tpu.observe.slo) — crypto-free:
spec parsing, rolling-window burn math under an injected clock
(burn-up under violations, decay back under recovery), the fast-burn
WARN with its cooldown, the tracer finished-block feed (latency +
busy kinds, channel scoping), and the /slo endpoint over a live
OperationsServer."""

import asyncio
import json
import logging
import urllib.request

import pytest

from fabric_tpu.observe import Tracer
from fabric_tpu.observe.slo import (
    SloEngine,
    SloError,
    parse_slos,
)
from fabric_tpu.ops_metrics import Registry


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


def _engine(spec, clock=None, registry=None):
    return SloEngine(
        parse_slos(spec), clock=clock or _Clock(),
        registry=registry or Registry(),
    )


# ---------------------------------------------------------------------------
# spec parsing


class TestParse:
    def test_latency_and_busy_round_trip(self):
        objs = parse_slos(
            "commit:latency:ms=250:target=0.95:windows=30,120:fast=6;"
            "busy:busy:pct=5"
        )
        commit, busy = objs
        assert commit.name == "commit" and commit.kind == "latency"
        assert commit.ms == 250.0 and commit.target == 0.95
        assert commit.windows == (30.0, 120.0) and commit.fast == 6.0
        assert abs(commit.budget - 0.05) < 1e-9
        assert busy.kind == "busy"
        assert abs(busy.target - 0.95) < 1e-9  # 1 - pct/100
        assert busy.windows == (60.0, 300.0)   # defaults

    def test_empty_spec_is_empty(self):
        assert parse_slos("") == []
        assert parse_slos(" ; ") == []

    def test_channel_filter(self):
        (o,) = parse_slos("t:latency:ms=10:channel=chanA")
        assert o.channel == "chanA"

    @pytest.mark.parametrize("bad", [
        "nokind",                        # no kind field
        "x:frobnicate:ms=5",             # unknown kind
        "x:latency",                     # latency without ms
        "x:latency:ms=0",                # non-positive threshold
        "x:busy",                        # busy without pct
        "x:busy:pct=0",                  # out-of-range budget
        "x:busy:pct=100",
        "x:latency:ms=5:target=1.5",     # target outside (0,1)
        "x:latency:ms=5:bogus=1",        # unknown key
        "x:latency:ms=five",             # unparsable value
        "x:latency:ms=5;x:busy:pct=1",   # duplicate objective name
        "x:latency:ms=5:windows=0",      # dead window: burn always None
        "x:latency:ms=5:windows=-5,60",  # negative window
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(SloError):
            parse_slos(bad)


# ---------------------------------------------------------------------------
# burn math


class TestBurn:
    def test_burn_rises_above_one_and_recovers(self):
        """The acceptance shape: clean traffic sits < 1, a violation
        storm drives burn ≥ 1, and after recovery (good traffic +
        window rolloff) it returns < 1."""
        clk = _Clock()
        eng = _engine("commit:latency:ms=100:target=0.9:windows=60",
                      clock=clk)
        (o,) = eng.objectives
        for _ in range(20):          # healthy baseline: all good
            eng.record(o, "chan", good=True)
            clk.advance(1.0)
        assert eng.burn("commit", "chan") == 0.0
        for _ in range(10):          # 5x-latency storm: all bad
            eng.record(o, "chan", good=False)
            clk.advance(1.0)
        burning = eng.burn("commit", "chan")
        # 10 bad / 30 events in window = 0.33 bad frac / 0.1 budget
        assert burning >= 1.0
        for _ in range(55):          # recovery: good traffic returns
            eng.record(o, "chan", good=True)
            clk.advance(1.0)
        # the storm has rolled out of the 60s window entirely
        assert eng.burn("commit", "chan") < 1.0

    def test_burn_decays_without_new_traffic(self):
        """Recovery must not require fresh events: burn() recomputes
        at call time, so a quiet channel's violations age out."""
        clk = _Clock()
        eng = _engine("q:latency:ms=1:windows=10:min_events=1",
                      clock=clk)
        (o,) = eng.objectives
        eng.record(o, "c", good=False)
        assert eng.burn("q", "c") > 1.0
        clk.advance(11.0)
        assert eng.burn("q", "c") is None  # window empty again

    def test_no_traffic_is_not_a_violation(self):
        eng = _engine("q:latency:ms=1")
        assert eng.burn("q", "nochan") is None
        rep = eng.report()
        assert rep["objectives"][0]["channels"] == {}

    def test_windows_are_independent(self):
        clk = _Clock()
        eng = _engine("q:latency:ms=1:target=0.9:windows=10,100",
                      clock=clk)
        (o,) = eng.objectives
        eng.record(o, "c", good=False)
        clk.advance(20.0)            # past the fast window only
        for _ in range(9):
            eng.record(o, "c", good=True)
        assert eng.burn("q", "c", window=10) == 0.0
        assert eng.burn("q", "c", window=100) == pytest.approx(1.0)

    def test_cold_start_floor_one_bad_block_is_no_burn(self):
        """The cold-start guard (default min_events=5): ONE bad block
        in a near-empty window reports burn None — a freshly started
        peer must not read as burn ≥ 1 (or page) off a single sample."""
        clk = _Clock()
        eng = _engine("q:latency:ms=1:windows=60", clock=clk)
        (o,) = eng.objectives
        assert o.min_events == 5  # the default floor
        eng.record(o, "c", good=False)
        assert eng.burn("q", "c") is None
        for _ in range(3):
            eng.record(o, "c", good=False)
        assert eng.burn("q", "c") is None       # 4 < 5: still no sample
        eng.record(o, "c", good=False)
        assert eng.burn("q", "c") >= 1.0        # 5th event: real signal

    def test_cold_start_floor_suppresses_fast_burn_warn(self, caplog):
        clk = _Clock()
        reg = Registry()
        eng = SloEngine(
            parse_slos("q:latency:ms=1:target=0.9:windows=30:fast=2"),
            clock=clk, registry=reg,
        )
        (o,) = eng.objectives
        with caplog.at_level(logging.WARNING,
                             logger="fabric_tpu.observe.slo"):
            eng.record(o, "c", good=False)  # the one cold-start bad block
        assert not [r for r in caplog.records
                    if "fast burn" in r.getMessage()]
        assert reg.counter("slo_fast_burn_total").value(
            slo="q", channel="c"
        ) == 0

    def test_min_events_one_restores_raw_behavior(self):
        clk = _Clock()
        eng = _engine("q:latency:ms=1:windows=60:min_events=1",
                      clock=clk)
        (o,) = eng.objectives
        eng.record(o, "c", good=False)
        assert eng.burn("q", "c") >= 1.0

    def test_min_events_spec_validation(self):
        with pytest.raises(SloError):
            parse_slos("q:latency:ms=1:min_events=0")
        (o,) = parse_slos("q:latency:ms=1:min_events=7")
        assert o.min_events == 7

    def test_burns_accessor_recomputes_all_series(self):
        """The autopilot's error-signal read: every (objective,
        channel) series on the fast window, floors respected."""
        clk = _Clock()
        eng = _engine(
            "q:latency:ms=1:target=0.9:windows=10:min_events=1",
            clock=clk,
        )
        (o,) = eng.objectives
        for _ in range(5):
            eng.record(o, "a", good=False)
        eng.record(o, "b", good=True)
        burns = eng.burns()
        assert burns[("q", "a")] >= 1.0
        assert burns[("q", "b")] == 0.0
        clk.advance(11.0)  # everything ages out; recomputed at read
        burns = eng.burns()
        assert burns[("q", "a")] is None and burns[("q", "b")] is None

    def test_burn_gauge_exported(self):
        reg = Registry()
        clk = _Clock()
        eng = SloEngine(
            parse_slos("q:latency:ms=1:windows=60:min_events=1"),
            clock=clk, registry=reg,
        )
        (o,) = eng.objectives
        eng.record(o, "c", good=False)
        g = reg.gauge("slo_burn_rate")
        assert g.value(slo="q", window="60s", channel="c") > 1.0

    def test_burn_gauge_decays_on_report_without_traffic(self):
        """The scrape path must not freeze a burning gauge after a
        channel's traffic stops — report() refreshes it as the window
        rolls."""
        reg = Registry()
        clk = _Clock()
        eng = SloEngine(
            parse_slos("q:latency:ms=1:windows=60:min_events=1"),
            clock=clk, registry=reg,
        )
        (o,) = eng.objectives
        eng.record(o, "c", good=False)
        g = reg.gauge("slo_burn_rate")
        assert g.value(slo="q", window="60s", channel="c") > 1.0
        clk.advance(120.0)  # the incident ages out; NO new events
        eng.report()        # what /slo (and a scraper hook) drives
        assert g.value(slo="q", window="60s", channel="c") == 0.0


# ---------------------------------------------------------------------------
# fast burn


class TestFastBurn:
    def test_warn_fires_once_per_window(self, caplog):
        clk = _Clock()
        reg = Registry()
        eng = SloEngine(
            parse_slos("q:latency:ms=1:target=0.9:windows=30:fast=2:"
                       "min_events=1"),
            clock=clk, registry=reg,
        )
        (o,) = eng.objectives
        with caplog.at_level(logging.WARNING,
                             logger="fabric_tpu.observe.slo"):
            for _ in range(10):
                eng.record(o, "c", good=False)
                clk.advance(0.5)
        warns = [r for r in caplog.records if "fast burn" in r.getMessage()]
        assert len(warns) == 1  # cooldown: one WARN per window
        assert "q" in warns[0].getMessage()
        assert reg.counter("slo_fast_burn_total").value(
            slo="q", channel="c"
        ) == 1
        # the cooldown expires with the window
        clk.advance(31.0)
        with caplog.at_level(logging.WARNING,
                             logger="fabric_tpu.observe.slo"):
            eng.record(o, "c", good=False)
        assert reg.counter("slo_fast_burn_total").value(
            slo="q", channel="c"
        ) == 2

    def test_fast_zero_disables_warn(self, caplog):
        clk = _Clock()
        eng = _engine("q:latency:ms=1:fast=0", clock=clk)
        (o,) = eng.objectives
        with caplog.at_level(logging.WARNING,
                             logger="fabric_tpu.observe.slo"):
            for _ in range(20):
                eng.record(o, "c", good=False)
        assert not [r for r in caplog.records
                    if "fast burn" in r.getMessage()]


# ---------------------------------------------------------------------------
# the tracer feed


def _finish(tr, number, dur_s, ns="", **attrs):
    root = tr.begin_block(number, ns=ns, **attrs)
    root.t1 = root.t0 + dur_s
    tr.finish_block(root)
    return root


class TestTracerFeed:
    def test_latency_kind_classifies_block_durations(self):
        tr = Tracer(ring_blocks=8, slow_factor=0)
        eng = _engine("commit:latency:ms=50:target=0.5:windows=60")
        tr.add_listener(eng.on_block)
        _finish(tr, 0, 0.010, channel="chanA")   # good
        _finish(tr, 1, 0.200, channel="chanA")   # bad
        _finish(tr, 2, 0.300, channel="chanB")   # bad, other channel
        rep = eng.report()
        chans = rep["objectives"][0]["channels"]
        assert chans["chanA"]["events"] == 2
        assert chans["chanA"]["bad"] == 1
        assert chans["chanB"]["events"] == 1
        assert chans["chanB"]["bad"] == 1

    def test_busy_kind_counts_only_sidecar_roots(self):
        tr = Tracer(ring_blocks=8, slow_factor=0)
        eng = _engine("busy:busy:pct=50:windows=60")
        tr.add_listener(eng.on_block)
        _finish(tr, 0, 0.01, channel="chanA")  # peer block: not counted
        _finish(tr, 1, 0.0, ns="sidecar", channel="sidecar:t0",
                busy=True)
        _finish(tr, 2, 0.01, ns="sidecar", channel="sidecar:t0")
        chans = eng.report()["objectives"][0]["channels"]
        assert list(chans) == ["sidecar:t0"]
        assert chans["sidecar:t0"]["events"] == 2
        assert chans["sidecar:t0"]["bad"] == 1

    def test_busy_roots_are_not_latency_samples(self):
        tr = Tracer(ring_blocks=8, slow_factor=0)
        eng = _engine("lat:latency:ms=1000:windows=60")
        tr.add_listener(eng.on_block)
        _finish(tr, 1, 0.0, ns="sidecar", channel="sidecar:t0",
                busy=True)
        assert eng.report()["objectives"][0]["channels"] == {}

    def test_channel_filter_scopes_the_objective(self):
        tr = Tracer(ring_blocks=8, slow_factor=0)
        eng = _engine("a_only:latency:ms=50:channel=chanA:windows=60")
        tr.add_listener(eng.on_block)
        _finish(tr, 0, 0.2, channel="chanA")
        _finish(tr, 1, 0.2, channel="chanB")
        chans = eng.report()["objectives"][0]["channels"]
        assert list(chans) == ["chanA"]

    def test_listener_failure_is_contained(self):
        tr = Tracer(ring_blocks=8, slow_factor=0)

        def broken(root):
            raise RuntimeError("listener bug")

        tr.add_listener(broken)
        _finish(tr, 0, 0.01)  # must not raise
        assert [b["block"] for b in tr.blocks()] == [0]
        tr.remove_listener(broken)
        tr.remove_listener(broken)  # idempotent


# ---------------------------------------------------------------------------
# /slo endpoint


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, json.loads(r.read())


def test_slo_endpoint_over_live_opsserver():
    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    clk = _Clock()
    eng = _engine("commit:latency:ms=100:target=0.9:windows=60",
                  clock=clk)
    (o,) = eng.objectives
    for i in range(10):
        eng.record(o, "chanA", good=i % 2 == 0)  # 50% bad → burn 5.0

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=Registry(), health=HealthRegistry(),
            tracer=Tracer(ring_blocks=4, slow_factor=0), slo=eng,
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, rep = await loop.run_in_executor(
                None, _get, srv.port, "/slo"
            )
            assert st == 200
            (obj,) = rep["objectives"]
            assert obj["name"] == "commit" and obj["ms"] == 100.0
            ch = obj["channels"]["chanA"]
            assert ch["events"] == 10 and ch["bad"] == 5
            assert ch["burn"]["60s"] == pytest.approx(5.0)
            assert ch["status"] == "burning"
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


def test_global_configure_attaches_once():
    from fabric_tpu.observe import slo as slo_mod
    from fabric_tpu.observe.tracer import global_tracer

    eng = slo_mod.configure("g:latency:ms=999999:windows=60")
    try:
        assert eng is slo_mod.global_engine()
        assert eng.objectives[0].name == "g"
        n = global_tracer()._listeners.count(eng.on_block)
        assert n == 1
        slo_mod.configure("g:latency:ms=999999:windows=60")
        assert global_tracer()._listeners.count(eng.on_block) == 1
    finally:
        slo_mod.configure("")  # disarm for other tests

# ---------------------------------------------------------------------------
# endorse-side objectives: the sign lane's SLO feed (ISSUE 14 satellite
# — the ROADMAP PR-13 follow-up)


class TestEndorseObjectives:
    def test_default_pair_parses(self):
        from fabric_tpu.observe.slo import DEFAULT_ENDORSE_SLOS

        objs = parse_slos(DEFAULT_ENDORSE_SLOS)
        assert [(o.name, o.kind, o.channel) for o in objs] == [
            ("endorse", "latency", "endorse"),
            ("endorse_busy", "busy", "endorse"),
        ]
        assert objs[0].ms > 0
        # the pair composes with a commit-path spec (distinct names)
        both = parse_slos(
            "commit:latency:ms=250;" + DEFAULT_ENDORSE_SLOS
        )
        assert len(both) == 3

    def test_observer_classifies_wait_and_busy(self):
        from fabric_tpu.observe.slo import (
            DEFAULT_ENDORSE_SLOS, endorse_observer,
        )

        clk = _Clock()
        eng = _engine(DEFAULT_ENDORSE_SLOS, clock=clk)
        obs = endorse_observer(eng)
        for _ in range(6):
            obs(2.0, False)       # fast waits: good latency samples
        obs(80.0, False)          # one slow wait: bad latency
        obs(None, True)           # one BUSY bounce: bad busy sample
        burns = eng.burns()
        lat = burns[("endorse", "endorse")]
        busy = burns[("endorse_busy", "endorse")]
        assert lat is not None and lat > 1.0       # 1/7 bad vs 1% budget
        assert busy is not None and busy > 1.0     # 1/7 bad vs 5% budget
        # a BUSY bounce is NOT a latency sample (7 latency events, not
        # 8) while the busy objective sees every admission edge (8)
        rep = eng.report()
        by_name = {o["name"]: o for o in rep["objectives"]}
        assert by_name["endorse"]["channels"]["endorse"]["events"] == 7
        assert by_name["endorse_busy"]["channels"]["endorse"][
            "events"] == 8
        # /slo surface: both objectives report on the endorse channel
        assert by_name["endorse"]["channels"]["endorse"]["status"] in (
            "burning", "fast_burn",
        )

    def test_observer_resolves_objectives_at_call_time(self):
        from fabric_tpu.observe.slo import (
            DEFAULT_ENDORSE_SLOS, endorse_observer,
        )

        clk = _Clock()
        eng = _engine("", clock=clk)
        obs = endorse_observer(eng)
        obs(1.0, False)  # no endorse objectives yet: nothing recorded
        assert eng.burns() == {}
        eng.set_objectives(parse_slos(DEFAULT_ENDORSE_SLOS))
        obs(1.0, False)  # same closure now feeds the rotated set
        assert ("endorse", "endorse") in eng.burns()

    def test_through_a_real_sign_batcher(self):
        """The wiring PeerNode.start() performs, minus the node: a
        real SignBatcher with the observer attached feeds the engine
        from its flush path (waits) and its admission path (BUSY)."""
        import threading

        from fabric_tpu.observe.slo import (
            DEFAULT_ENDORSE_SLOS, endorse_observer,
        )
        from fabric_tpu.peer.signlane import SignBatcher, SignBusy

        clk = _Clock()
        eng = _engine(DEFAULT_ENDORSE_SLOS, clock=clk)
        gate = threading.Event()

        def backend(digests):
            gate.wait(timeout=10.0)
            return [(1, 1)] * len(digests)

        b = SignBatcher(backend, batch_max=2, wait_ms=0.0)
        b.observer = endorse_observer(eng)
        b.start()
        busy = []
        try:
            # a request storm against the gated backend: the 2×cap
            # admission window fills and the overflow bounces BUSY
            # (the test_signlane overflow shape, observer attached)
            def worker():
                try:
                    b.sign_digest(7)
                except SignBusy as e:
                    busy.append(e)

            ts = [threading.Thread(target=worker) for _ in range(10)]
            for t in ts:
                t.start()
            import time as _t

            _t.sleep(0.3)
            gate.set()
            for t in ts:
                t.join(timeout=10.0)
            assert busy, "expected BUSY bounces"
        finally:
            gate.set()
            b.stop()
        burns = eng.burns()
        assert ("endorse", "endorse") in burns       # wait samples fed
        assert ("endorse_busy", "endorse") in burns  # BUSY event fed
        rep = eng.report()
        by_name = {o["name"]: o for o in rep["objectives"]}
        ch = by_name["endorse_busy"]["channels"]["endorse"]
        assert ch["bad"] >= 1  # at least the overflow bounce

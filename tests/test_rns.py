"""RNS (Cox-Rower) field-core tests: Montgomery multiplication, base
extension, add/sub bound discipline — all bit-exact against Python
ints via CRT reconstruction of every device result (the oracle the
module's docstring promises)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import rns

P = ec_ref.P
N = ec_ref.N


def _rv(ints, bound):
    return rns.RV(jnp.asarray(rns.ints_to_rns(ints)), bound)


def _ints(rv):
    return rns.rv_to_ints(rv.arr)


def test_base_construction():
    assert len(set(rns.BASE_A) | set(rns.BASE_B)) == 2 * rns.N_CH
    assert all(m < (1 << 12) for m in rns.BASE_A + rns.BASE_B)
    assert rns.M_A > (1 << 270) and rns.M_B > (1 << 270)
    # every prime odd and coprime to both moduli
    for m in rns.BASE_A + rns.BASE_B:
        assert P % m and N % m


def test_residue_roundtrip(rng):
    xs = [int.from_bytes(rng.bytes(32), "big") for _ in range(16)]
    xs += [0, 1, P - 1, P, rns.M_A - 1]
    arr = rns.ints_to_rns(xs)
    back = rns.rv_to_ints(arr)
    for x, b in zip(xs, back):
        assert b == x % (rns.M_A * rns.M_B)
        assert b == x  # all inputs < M_A·M_B


@pytest.mark.parametrize("mod", [P, N], ids=["p", "n"])
def test_mont_mul_chain_exact(mod, rng):
    """300 chained Montgomery muls, bit-exact vs Python ints; output
    bound invariants hold on every step."""
    ctx = rns.ctx_for(mod)
    Minv = pow(rns.M_A, -1, mod)
    B = 8
    a_int = [int.from_bytes(rng.bytes(32), "big") % mod for _ in range(B)]
    b_int = [mod - 1, 1, 0, 2] + [
        int.from_bytes(rng.bytes(32), "big") % mod for _ in range(B - 4)
    ]
    mul = jax.jit(lambda x, y: rns.mont_mul(
        rns.RV(x, 3 * mod), rns.RV(y, mod), ctx).arr)
    a = jnp.asarray(rns.ints_to_rns(a_int))
    b = jnp.asarray(rns.ints_to_rns(b_int))
    want = list(a_int)
    for it in range(300):
        a = mul(a, b)
        for lane in range(B):
            want[lane] = want[lane] * b_int[lane] * Minv % mod
        if it % 59 == 0 or it == 299:
            got = rns.rv_to_ints(a)
            for lane in range(B):
                assert got[lane] < 3 * mod, (it, lane)
                assert got[lane] % mod == want[lane], (it, lane)


def test_add_sub_exact(rng):
    ctx = rns.ctx_for(P)
    xs = [int.from_bytes(rng.bytes(32), "big") % P for _ in range(8)]
    ys = [int.from_bytes(rng.bytes(32), "big") % P for _ in range(8)]
    x, y = _rv(xs, P), _rv(ys, P)
    s = x + y
    for g, a, b in zip(_ints(s), xs, ys):
        assert g == a + b and g <= s.bound
    d = rns.rv_sub(x, y, ctx)
    for g, a, b in zip(_ints(d), xs, ys):
        assert g % P == (a - b) % P and g <= d.bound


def test_extension_rank_edges():
    """Exact-rank extension at the dangerous corners: v = 0, v = 1,
    v near the bound — the α = ⌊s + ¼⌋ path must never be off by one."""
    vals = [0, 1, 2, P - 1, P, 2 * P, 3 * P - 1]
    arrB = rns.ints_to_rns(vals)[:, rns.N_CH:]  # base-B residues
    out = rns._extend(jnp.asarray(arrB), rns.EXT_BA, rns.MOD_A, exact=True)
    primes = rns.BASE_A
    got = np.asarray(out)
    for row, v in zip(got, vals):
        for r, m in zip(row, primes):
            assert int(r) == v % m, (v, m)


def test_down_biased_extension_slack():
    """Inexact extension may add exactly one source-M — never more,
    never subtract."""
    vals = [0, 1, rns.M_A - 1, rns.M_A // 2, 12345678901234567890]
    arrA = rns.ints_to_rns(vals)[:, :rns.N_CH]
    out = rns._extend(jnp.asarray(arrA), rns.EXT_AB, rns.MOD_B, exact=False)
    got = np.asarray(out)
    primes = rns.BASE_B
    for row, v in zip(got, vals):
        ok0 = all(int(r) == v % m for r, m in zip(row, primes))
        ok1 = all(int(r) == (v + rns.M_A) % m for r, m in zip(row, primes))
        assert ok0 or ok1, v


def test_rem_helpers_exhaustive_edges(rng):
    """Float-reciprocal remainders at boundary magnitudes."""
    for mod_obj, primes in ((rns.MOD_A, rns.BASE_A), (rns.MOD_B, rns.BASE_B)):
        edge = []
        for m in primes:
            edge.append([0, m - 1, m, m + 1, (1 << 24) - 1,
                         ((1 << 24) - 1) // m * m])
        t = jnp.asarray(np.array(edge, np.int32).T)  # [6, n]
        out = np.asarray(mod_obj.rem24(t))
        for i, m in enumerate(primes):
            for j in range(t.shape[0]):
                assert int(out[j, i]) == int(t[j, i]) % m
        t30 = jnp.asarray(
            np.array([[(1 << 30) - 1] * len(primes),
                      [(1 << 30) - (1 << 20)] * len(primes),
                      [0] * len(primes)], np.int32)
        )
        out30 = np.asarray(mod_obj.rem30(t30))
        for i, m in enumerate(primes):
            for j in range(3):
                assert int(out30[j, i]) == int(t30[j, i]) % m


def test_mont_roundtrip(rng):
    ctx = rns.ctx_for(P)
    xs = [int.from_bytes(rng.bytes(32), "big") % P for _ in range(8)]
    x = _rv(xs, P)
    xm = rns.to_mont(x, ctx)
    for g, a in zip(_ints(xm), xs):
        assert g % P == a * rns.M_A % P
    back = rns.from_mont(xm, ctx)
    for g, a in zip(_ints(back), xs):
        assert g % P == a


def test_eq_const_mod_p(rng):
    ctx = rns.ctx_for(P)
    # values ≡ 0 mod p in Montgomery domain: 0, p·M, 2p·M …
    vals = [0, P * rns.M_A % (1 << 520), 7, P - 1, 2 * P]
    ints = [0, P, 2 * P, 7, P + 3]
    x = _rv(ints, 3 * P)
    hits = np.asarray(rns.eq_const_mod_p(rns.RV(x.arr, 3 * P), ctx))
    # from_mont multiplies by M⁻¹ — ≡0-ness mod p is preserved
    assert list(hits) == [True, True, True, False, False]

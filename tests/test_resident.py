"""Device-resident MVCC state (fabric_tpu/state): the resident ≡ host
differential battery.

Layers (all crypto-free — the full-BlockValidator differential lives
in tests/test_pipeline.py behind the ``cryptography`` gate):

1. ResidencyManager unit semantics — admission, hits, LRU range
   eviction, commit delta scatters, cached absence, disable latch,
   invalidation;
2. the fused stage-2 RESIDENT program variant
   (``DeviceBlockPipeline.run(resident=...)``) is bit-equal to the
   host ``ver_ok`` path on every output lane, across hit / miss /
   overlay-override lanes and on 2- and 8-device meshes (the resident
   table sharded axis-0 like every other stage-2 operand);
3. a resident toy validator through the REAL CommitPipeline at depths
   1/2/3 — hit/miss/eviction churn, barrier redos, degrade latch
   mid-stream, and a crash-replay rebuild — always verdict- and
   state-identical to the host-oracle toy;
4. the end-to-end run with REAL device signature verifies (the
   crypto-free analog of the production flow: ec_ref signatures
   through ``verify_launch`` + resident state + pipeline).
"""

import json
from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp
import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import ec_ref
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.ops import mvcc as mvcc_ops
from fabric_tpu.ops import p256v3 as v3
from fabric_tpu.parallel import mesh as pmesh
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.state import (
    ResidencyManager,
    build_launch_pack,
    resolve_residency,
)


def _seed_state(n=8, stale_every=3, absent_every=4):
    """Committed state over keys k0..k{n-1}: every ``absent_every``-th
    missing, every ``stale_every``-th at a version the readers below
    will not expect."""
    state = MemVersionedDB()
    b = UpdateBatch()
    for u in range(n):
        if absent_every and u % absent_every == absent_every - 1:
            continue
        ver = (9, 9) if (stale_every and u % stale_every == 0) else (1, u)
        b.put("ns", f"k{u}", b"v%d" % u, ver)
    state.apply_updates(b, (1, 0))
    return state


# ---------------------------------------------------------------------------
# 1. manager unit semantics


def test_manager_ctor_validation():
    with pytest.raises(ValueError):
        ResidencyManager(capacity_mb=0)
    with pytest.raises(ValueError):
        ResidencyManager(range_bits=0)
    with pytest.raises(ValueError):
        ResidencyManager(range_bits=25)
    with pytest.raises(ValueError):
        ResidencyManager(slots=2)
    assert resolve_residency(False, 64, 12) is None
    r = resolve_residency(True, 1, 8)
    assert r is not None and r.capacity >= 256
    # pow2 capacity: mesh shards must divide it
    assert ResidencyManager(slots=100).capacity == 64


def test_manager_admit_hit_and_values():
    state = _seed_state(8)
    res = ResidencyManager(slots=32, range_bits=6)
    pairs = [("ns", f"k{u}") for u in range(8)]
    _t, u1 = build_launch_pack(res, pairs, state)
    assert (u1[:8, 0] == -1).all()  # first sight: all miss
    # host lanes carried the committed values
    up, uv = state.get_versions_cols(pairs)
    assert np.array_equal(u1[:8, 1].astype(bool), up)
    table, u2 = build_launch_pack(res, pairs, state)
    assert (u2[:8, 0] >= 0).all()   # second sight: all hit
    arr = np.asarray(table)
    rows = arr[u2[:8, 0]]
    assert np.array_equal(rows[:, 0].astype(bool), up)
    assert np.array_equal(
        rows[:, 1:3][up], uv.view(np.int32)[up]
    )
    # cached ABSENCE: the absent key is resident with present=0
    absent = pairs.index(("ns", "k3"))
    assert rows[absent, 0] == 0
    st = res.stats()
    assert st["hits_total"] == 8 and st["misses_total"] == 8
    assert st["hit_rate"] == 0.5


def test_manager_commit_delta_scatter():
    state = _seed_state(8, stale_every=0, absent_every=0)
    # budget 0: the write path may never OPEN a range here, so the
    # brand-new-range-stays-a-miss contract below is exact
    res = ResidencyManager(slots=32, range_bits=6,
                           write_admit_budget=0)
    pairs = [("ns", f"k{u}") for u in range(8)]
    build_launch_pack(res, pairs, state)          # admit
    cb = UpdateBatch()
    cb.put("ns", "k0", b"new", (5, 2))
    cb.delete("ns", "k1", (5, 3))
    cb.put("ns", "brand_new", b"x", (5, 4))       # no resident range
    res.apply_batch(cb)
    table, u = build_launch_pack(res, pairs, state)
    arr = np.asarray(table)
    assert list(arr[u[0, 0]]) == [1, 5, 2]        # updated in place
    assert arr[u[1, 0]][0] == 0                   # delete → cached absence
    # a brand-new key in a non-resident range stays a miss (budget 0)
    slots, _t = res.lookup([("ns", "brand_new")])
    assert slots[0] == -1
    # ... but a write into an ALREADY-resident range is admitted free
    rid_key = None
    rid0 = res.range_of("ns", "k0")
    for cand in ("x%d" % i for i in range(200)):
        if res.range_of("ns", cand) == rid0:
            rid_key = cand
            break
    assert rid_key is not None
    cb2 = UpdateBatch()
    cb2.put("ns", rid_key, b"y", (6, 0))
    res.apply_batch(cb2)
    slots, table = res.lookup([("ns", rid_key)])
    assert slots[0] >= 0
    assert list(np.asarray(table)[slots[0]]) == [1, 6, 0]


def test_manager_write_admission_budget():
    with pytest.raises(ValueError):
        ResidencyManager(write_admit_budget=-1)
    res = ResidencyManager(slots=32, range_bits=6,
                           write_admit_budget=2)
    # writes into 4 DISTINCT brand-new ranges in one committed block:
    # only the per-block budget's worth of ranges may open
    picks, seen = [], set()
    i = 0
    while len(picks) < 4:
        pr = ("ns", "w%d" % i)
        rid = res.range_of(*pr)
        if rid not in seen:
            seen.add(rid)
            picks.append(pr)
        i += 1
    cb = UpdateBatch()
    for j, (ns, k) in enumerate(picks):
        cb.put(ns, k, b"v", (3, j))
    res.apply_batch(cb)
    slots, _t = res.lookup(picks)
    assert int((slots >= 0).sum()) == 2
    st = res.stats()
    assert st["write_admits_total"] == 2
    assert st["write_admit_budget"] == 2
    # the NEXT block's write-set gets a fresh budget — the two ranges
    # skipped above open now, and the already-resident keys update in
    # place without recharging it
    cb2 = UpdateBatch()
    for j, (ns, k) in enumerate(picks):
        cb2.put(ns, k, b"v2", (4, j))
    res.apply_batch(cb2)
    slots2, _t = res.lookup(picks)
    assert int((slots2 >= 0).sum()) == 4
    assert res.stats()["write_admits_total"] == 4


def test_manager_lru_eviction_pins_touched_ranges():
    res = ResidencyManager(slots=8, range_bits=4)
    ones = np.ones(1, bool)
    v = np.asarray([[1, 0]], np.uint32)
    hot = ("ns", "hot")
    hot_rid = res.range_of(*hot)
    # cold keys from 3 DISTINCT ranges, none of them the hot one: a
    # touched-every-iteration range then provably survives — eviction
    # always finds an older cold range to sacrifice first
    by_rid: dict[int, list] = {}
    for i in range(400):
        pr = ("ns", "c%d" % i)
        rid = res.range_of(*pr)
        if rid != hot_rid:
            by_rid.setdefault(rid, []).append(pr)
        if len([r for r in by_rid if len(by_rid[r]) >= 10]) >= 3:
            break
    groups = [by_rid[r] for r in sorted(by_rid) if len(by_rid[r]) >= 10][:3]
    assert len(groups) == 3
    res.admit([hot], ones, v)
    for i in range(30):
        res.admit([groups[i % 3][i // 3]], ones, v)
        assert res.lookup([hot])[0][0] >= 0, (
            "touched (MRU) range was evicted at step %d" % i
        )
    st = res.stats()
    assert st["evictions_total"] > 0
    assert st["resident_keys"] <= res.capacity


def test_manager_disable_latch_and_invalidate():
    state = _seed_state(4, stale_every=0, absent_every=0)
    res = ResidencyManager(slots=16, range_bits=4)
    pairs = [("ns", f"k{u}") for u in range(4)]
    build_launch_pack(res, pairs, state)
    # invalidation drops a key back to miss
    res.invalidate_keys([("ns", "k0")])
    slots, _ = res.lookup(pairs)
    assert slots[0] == -1 and (slots[1:] >= 0).all()
    # disable: everything misses, pack refuses, stats honest
    res.disable("test latch")
    assert not res.enabled
    assert build_launch_pack(res, pairs, state) is None
    assert (res.lookup(pairs)[0] == -1).all()
    assert res.stats()["enabled"] is False
    # apply_batch is a no-op while latched (no crash, no corruption)
    cb = UpdateBatch()
    cb.put("ns", "k0", b"z", (7, 0))
    assert res.apply_batch(cb) == 0


def test_pack_too_large_working_set_falls_back():
    state = _seed_state(4, stale_every=0, absent_every=0)
    res = ResidencyManager(slots=4, range_bits=3)
    pairs = [("ns", "big%d" % i) for i in range(10)]
    assert build_launch_pack(res, pairs, state) is None


# ---------------------------------------------------------------------------
# 2. stage-2 resident variant ≡ host ver_ok


def _stage2_fixture(rng):
    """The crypto-free fused-stage-2 harness (test_multidevice shape):
    a 2-of-3 policy group + a flat static block whose 12 txs read one
    unique key each and write the next tx's key (a conflict chain the
    fixpoint must walk)."""
    from fabric_tpu.crypto import policy as pol

    policy = pol.from_dsl("OutOf(2, 'O1.peer', 'O2.peer', 'O3.peer')")
    plan = pol.compile_plan(policy)
    P = len(plan.principals)
    S, Eb, T, n_sig = 4, 16, 16, 16
    handle = v3.VerifyHandle(jnp.asarray(rng.random(n_sig) < 0.75), n_sig)
    match = np.zeros((Eb, S, P), np.int32)
    endo_idx = np.full((Eb, S), -1, np.int32)
    tx_of = np.full(Eb, -1, np.int32)
    for e in range(12):
        tx_of[e] = e % T
        for s in range(3):
            endo_idx[e, s] = (e * 3 + s) % n_sig
            match[e, s, s % P] = 1
    gp = np.zeros((Eb, S * P + S + 1), np.int32)
    gp[:, :S * P] = match.reshape(Eb, -1)
    gp[:, S * P:S * P + S] = endo_idx
    gp[:, -1] = tx_of

    n_txs, U = 12, 12
    pairs = [("ns", f"k{u}") for u in range(U)]
    read_keys = np.full((T, 2), -1, np.int32)
    read_present = np.zeros((T, 2), bool)
    read_vers = np.zeros((T, 2, 2), np.uint32)
    write_keys = np.full((T, 2), -1, np.int32)
    rr, rc, ru = [], [], []
    for i in range(n_txs):
        read_keys[i, 0] = i
        read_present[i, 0] = i % 4 != 3    # expect-absent lanes too
        read_vers[i, 0] = (1, i)
        write_keys[i, 0] = (i + 1) % n_txs
        rr.append(i)
        rc.append(0)
        ru.append(i)
    static = mvcc_ops.VecStaticBlock(
        read_keys=read_keys, read_present=read_present,
        read_vers=read_vers, write_keys=write_keys,
        rq_lo=np.full((T, 1), -1, np.int32),
        rq_hi=np.full((T, 1), -1, np.int32),
        read_fill=[], read_key_set=set(pairs),
        r_rows=np.asarray(rr, np.intp), r_cols=np.asarray(rc, np.intp),
        r_uid=np.asarray(ru, np.int32), u_composite=pairs,
        u_pairs=pairs,
    )
    state = _seed_state(U)
    launch_vec = np.zeros((T, 3), np.int32)
    launch_vec[:, 0] = np.arange(T) % n_sig
    launch_vec[:n_txs, 1] = 1
    return (plan, gp, Eb, S, handle, static, pairs, state, launch_vec,
            T, n_txs)


def _run_host(pipe, fx, overlay=None):
    (plan, gp, Eb, S, handle, static, pairs, state, launch_vec, T,
     n_txs) = fx
    up, uv = state.get_versions_cols(pairs)
    if overlay is not None:
        for ui, pr in enumerate(pairs):
            vv = overlay.updates.get(pr)
            if vv is None:
                continue
            if vv.value is None:
                up[ui] = False
            else:
                up[ui] = True
                uv[ui] = vv.version
    lv = launch_vec.copy()
    lv[:n_txs, 2] = static.ver_ok_from_u(up, uv)[:n_txs]
    return pipe.run(handle, lv, [(plan, jnp.asarray(gp), Eb, S)],
                    static.packed_static(), static.dims, T)()


def _run_resident(pipe, fx, res, overlay=None, mesh=None):
    (plan, gp, Eb, S, handle, static, pairs, state, launch_vec, T,
     n_txs) = fx
    out = build_launch_pack(res, pairs, state, overlay=overlay)
    assert out is not None
    table, u_pack = out
    lv = launch_vec.copy()
    lv[:, 2] = 1  # inert: ver_ok computed on device
    return pipe.run(
        handle, lv, [(plan, pmesh.shard_batch(mesh, jnp.asarray(gp)),
                      Eb, S)],
        static.packed_static(), static.dims, T, mesh=mesh,
        resident=(table, u_pack, static.packed_read_pv()),
    )()


_KEYS = ("valid", "conflict", "phantom", "creator_ok", "policy_ok",
         "sig_valid")


def test_stage2_resident_bit_equal_hit_miss_overlay():
    """THE device acceptance gate: the resident stage-2 variant is
    bit-equal to the host ver_ok path on every output lane — on an
    all-miss pack (host lanes), an all-hit pack (table gathers), after
    a commit delta scatter, and under an in-flight overlay override —
    with the fixpoint's conflict chain load-bearing throughout."""
    from fabric_tpu.peer.device_block import DeviceBlockPipeline

    rng = np.random.default_rng(20260804)
    fx = _stage2_fixture(rng)
    state = fx[7]
    pipe = DeviceBlockPipeline()
    base = _run_host(pipe, fx)
    assert base["valid"][:12].any() and not base["valid"][:12].all()

    res = ResidencyManager(slots=64, range_bits=5)
    got_miss = _run_resident(pipe, fx, res)     # all host lanes
    for k in _KEYS:
        assert np.array_equal(base[k], got_miss[k]), ("miss", k)
    got_hit = _run_resident(pipe, fx, res)      # all table gathers
    for k in _KEYS:
        assert np.array_equal(base[k], got_hit[k]), ("hit", k)

    # commit a delta: k0 bumps, k1 deleted — BOTH paths see it
    cb = UpdateBatch()
    cb.put("ns", "k1", b"n", (4, 0))   # was stale-or-absent before
    cb.delete("ns", "k2", (4, 1))
    state.apply_updates(cb, (4, 0))
    res.apply_batch(cb)
    base2 = _run_host(pipe, fx)
    got2 = _run_resident(pipe, fx, res)
    for k in _KEYS:
        assert np.array_equal(base2[k], got2[k]), ("post-commit", k)
    assert not np.array_equal(base["valid"], base2["valid"]), (
        "the committed delta must actually change verdicts"
    )

    # in-flight overlay override: writes not yet committed anywhere —
    # targeting keys of currently-VALID txs so the seam is load-bearing
    ov = UpdateBatch()
    ov.put("ns", "k3", b"o", (6, 0))   # tx3 expected ABSENT
    ov.delete("ns", "k8", (6, 1))      # tx8 expected present (1,8)
    base3 = _run_host(pipe, fx, overlay=ov)
    got3 = _run_resident(pipe, fx, res, overlay=ov)
    for k in _KEYS:
        assert np.array_equal(base3[k], got3[k]), ("overlay", k)
    assert not np.array_equal(base2["valid"], base3["valid"]), (
        "the overlay override must actually change verdicts"
    )
    # attribution honesty: overlay-forced lanes are counted on their
    # own counter, never as resident hits (the bench A/B must not
    # credit the table for reads served from the overlay)
    st = res.stats()
    assert st["overlay_forced_total"] == 2
    assert st["hits_total"] + st["misses_total"] + \
        st["overlay_forced_total"] == 4 * 12


def test_stage2_resident_mesh_sharded_bit_equal():
    """The resident table shards axis-0 over the data mesh like every
    other stage-2 operand — 2- and 8-device meshes bit-equal to the
    unsharded resident run and to the host oracle."""
    from fabric_tpu.peer.device_block import DeviceBlockPipeline

    rng = np.random.default_rng(20260805)
    fx = _stage2_fixture(rng)
    pipe = DeviceBlockPipeline()
    base = _run_host(pipe, fx)
    for nd in (2, 8):
        mesh = pmesh.resolve_mesh(nd)
        res = ResidencyManager(slots=64, range_bits=5, mesh=mesh)
        _run_resident(pipe, fx, res, mesh=mesh)   # warm (admit)
        got = _run_resident(pipe, fx, res, mesh=mesh)
        for k in _KEYS:
            assert np.array_equal(base[k], got[k]), (nd, k)


# ---------------------------------------------------------------------------
# 3. the resident toy validator ≡ host oracle through CommitPipeline


@dataclass
class _Ptx:
    txid: str
    idx: int
    is_config: bool = False


@dataclass
class _Pend:
    block: object
    txs: list
    raw: list
    overlay: object
    extra: object
    fetch: object

    @property
    def txids(self):
        return {p.txid for p in self.txs if p.txid}


class ResidentToyValidator:
    """The crypto-free end-to-end shape: per-tx version checks resolve
    through the REAL ResidencyManager (hits off the device table
    snapshot, misses host-gathered + admitted, overlay keys forced
    onto overlay values) and each committed batch scatters back
    through the pipeline's ``resident_commit`` hook.  ``resident=None``
    is the host oracle — identical semantics, direct state reads.

    tx wire form: {"id", "reads": {k: [b, t] | None}, "writes":
    {k: v}, "deletes": [k], "cfg": bool, "sig": [...] (optional —
    with ``sign=True`` the REAL p256v3 device verify judges it)}."""

    VALID, BADSIG, DUP, MVCC = 0, 4, 2, 11

    def __init__(self, state, resident=None, sign=False):
        self.state = state
        self.resident = resident
        self.sign = sign

    def preprocess(self, block):
        raw = [json.loads(bytes(d)) for d in block.data.data]
        if self.sign:
            items = [tuple(int(x) for x in t["sig"]) for t in raw]
            fetch = v3.verify_launch(items)
        else:
            n = len(raw)

            def fetch():
                return [True] * n
        return raw, fetch

    def validate_launch(self, block, pre=None, overlay=None,
                        extra_txids=None):
        raw, fetch = pre if pre is not None else self.preprocess(block)
        txs = [
            _Ptx(t["id"], i, bool(t.get("cfg")))
            for i, t in enumerate(raw)
        ]
        return _Pend(block, txs, raw, overlay, extra_txids, fetch)

    def _versions(self, pairs, overlay):
        over = {}
        if overlay is not None:
            for pr, vv in overlay.updates.items():
                over[pr] = (
                    None if vv.value is None else tuple(vv.version)
                )
        res = self.resident
        out = []
        if res is not None and res.enabled:
            slots, table = res.lookup(
                pairs, forced_pairs=(set(over) if over else None)
            )
            miss_idx = [i for i, s in enumerate(slots)
                        if s < 0 and pairs[i] not in over]
            hostvals = {}
            if miss_idx:
                mp = [pairs[i] for i in miss_idx]
                up, uv = self.state.get_versions_cols(mp)
                res.admit(mp, up, uv)
                for j, i in enumerate(miss_idx):
                    hostvals[i] = (
                        tuple(int(x) for x in uv[j]) if up[j] else None
                    )
            arr = np.asarray(table) if table is not None else None
            for i, pr in enumerate(pairs):
                if pr in over:
                    out.append(over[pr])
                elif slots[i] >= 0:
                    row = arr[slots[i]]
                    out.append(
                        tuple(int(x) for x in
                              row[1:3].view(np.uint32))
                        if row[0] else None
                    )
                else:
                    out.append(hostvals.get(i))
            return out
        for pr in pairs:
            if pr in over:
                out.append(over[pr])
                continue
            vv = self.state.get_state(*pr)
            out.append(None if vv is None else tuple(vv.version))
        return out

    def validate_finish(self, pend):
        bits = pend.fetch()
        pairs, pidx = [], {}
        for t in pend.raw:
            for k in t.get("reads", {}):
                pr = ("ns", k)
                if pr not in pidx:
                    pidx[pr] = len(pairs)
                    pairs.append(pr)
        vers = self._versions(pairs, pend.overlay)
        codes = []
        batch = UpdateBatch()
        num = pend.block.header.number
        seen = set(pend.extra or ())
        for i, (ptx, t) in enumerate(zip(pend.txs, pend.raw)):
            if not bits[i]:
                codes.append(self.BADSIG)
                continue
            if ptx.txid in seen:
                codes.append(self.DUP)
                continue
            seen.add(ptx.txid)
            ok = True
            for k, want in t.get("reads", {}).items():
                got = vers[pidx[("ns", k)]]
                wt = None if want is None else tuple(want)
                if got != wt:
                    ok = False
                    break
            if not ok:
                codes.append(self.MVCC)
                continue
            codes.append(self.VALID)
            for k, val in t.get("writes", {}).items():
                batch.put("ns", k, val.encode(), (num, ptx.idx))
            for k in t.get("deletes", ()):
                batch.delete("ns", k, (num, ptx.idx))
        return bytes(codes), batch, []

    def resident_commit(self, batch):
        if self.resident is not None:
            self.resident.apply_batch(batch)


def _churn_stream(n_blocks=8, n_tx=6, barrier_at=None, sign_key=None):
    """Dependent block stream over a HOT working set plus per-block
    cold keys: hot reads re-hit every block (residency pays), k→k+1
    and k→k+2 reads cross the in-flight window (overlay coherence),
    per-block stale lanes and deletes churn the cache, and an optional
    mid-stream CONFIG barrier forces the redo path.  With ``sign_key``
    every tx carries a REAL signature, every third corrupted."""
    blocks, prev = [], b""
    for n in range(n_blocks):
        txs = []
        for i in range(n_tx):
            t = {"id": f"t{n}_{i}", "writes": {f"k{n}_{i}": f"v{n}"}}
            if sign_key is not None:
                e = ec_ref.digest_int(b"rt%d_%d" % (n, i))
                r, s = sign_key.sign_digest(e)
                if i % 3 == 2:
                    s = ec_ref.N - s  # high-S → device rejects
                t["sig"] = [str(x) for x in (e, r, s, *sign_key.public)]
            if i == 0:
                # HOT key: written by block 0, read by every block
                t["reads"] = {"hot": [0, 0] if n else None}
                if n == 0:
                    t["writes"]["hot"] = "h"
            if n > 0 and i == 1:
                t["reads"] = {f"k{n-1}_1": [n - 1, 1]}  # k→k+1 fresh
            if n > 1 and i == 3:
                t["reads"] = {f"k{n-2}_3": [n - 2, 3]}  # k→k+2 fresh
            if n > 1 and i == 4:
                t["reads"] = {f"k{n-2}_4": [0, 0]}      # stale → MVCC
            if n > 0 and i == 5:
                t["deletes"] = [f"k{n-1}_5"]
                t["reads"] = {f"k{n-1}_5": [n - 1, 5]}
            if barrier_at is not None and n == barrier_at and i == 2:
                t["cfg"] = True
            txs.append(t)
        blk = pu.new_block(n, prev)
        for t in txs:
            blk.data.data.append(json.dumps(t).encode())
        blk = pu.finalize_block(blk)
        prev = pu.block_header_hash(blk.header)
        blocks.append(blk)
    return blocks


def _run_toy(blocks, depth, resident=None, sign=False,
             disable_after=None, rebuild_after=None):
    state = MemVersionedDB()
    v = ResidentToyValidator(state, resident=resident, sign=sign)
    filters = []
    committed = [0]

    def commit_fn(res_blk):
        state.apply_updates(
            res_blk.batch, (res_blk.block.header.number, 0)
        )
        committed[0] += 1
        if (disable_after is not None
                and committed[0] == disable_after
                and resident is not None):
            resident.disable("mid-stream latch (test)")

    with CommitPipeline(v, commit_fn, depth=depth) as pipe:
        for bi, b in enumerate(blocks):
            if rebuild_after is not None and bi == rebuild_after:
                # crash-replay: residency is memory-only — a restart
                # rebuilds it COLD over the reopened ledger state
                r = pipe.flush()
                if r is not None:
                    filters.append(
                        (r.block.header.number, list(r.tx_filter))
                    )
                new_res = (
                    ResidencyManager(
                        slots=resident.capacity,
                        range_bits=resident.range_bits,
                    ) if resident is not None else None
                )
                v.resident = new_res
            r = pipe.submit(b)
            if r is not None:
                filters.append(
                    (r.block.header.number, list(r.tx_filter))
                )
        r = pipe.flush()
        if r is not None:
            filters.append((r.block.header.number, list(r.tx_filter)))
    filters.sort()
    return filters, dict(state._data), v


def test_toy_resident_depth2_matches_oracle_with_hits():
    blocks = _churn_stream()
    f1, s1, _ = _run_toy(blocks, depth=1)
    res = ResidencyManager(slots=256, range_bits=8)
    f2, s2, _ = _run_toy(blocks, depth=2, resident=res)
    assert f2 == f1
    assert s2 == s1
    st = res.stats()
    assert st["hits_total"] > 0, "the hot working set never hit"
    # stream shape sanity: fresh k→k+2, stale, delete lanes all fired
    for n, flt in f1:
        if n > 1:
            assert flt[3] == ResidentToyValidator.VALID
            assert flt[4] == ResidentToyValidator.MVCC


def test_toy_resident_depth3_barrier_redo_matches_oracle():
    blocks = _churn_stream(barrier_at=3)
    f1, s1, _ = _run_toy(blocks, depth=1)
    res = ResidencyManager(slots=256, range_bits=8)
    f3, s3, _ = _run_toy(blocks, depth=3, resident=res)
    assert f3 == f1
    assert s3 == s1
    assert res.stats()["hits_total"] > 0


def test_toy_resident_eviction_churn_matches_oracle():
    """A cache far smaller than the stream's key universe: constant
    admission/eviction churn, still bit-equal verdicts and state."""
    blocks = _churn_stream(n_blocks=10)
    f1, s1, _ = _run_toy(blocks, depth=1)
    res = ResidencyManager(slots=8, range_bits=2)
    f2, s2, _ = _run_toy(blocks, depth=2, resident=res)
    assert f2 == f1
    assert s2 == s1
    assert res.stats()["evictions_total"] > 0, (
        "an 8-slot cache over this stream must have churned"
    )


def test_toy_resident_degrade_latch_mid_stream():
    """The cache latches OFF mid-stream (the device-failure shape):
    later blocks ride the host oracle path, verdicts and state never
    fork."""
    blocks = _churn_stream()
    f1, s1, _ = _run_toy(blocks, depth=1)
    res = ResidencyManager(slots=256, range_bits=8)
    f2, s2, _ = _run_toy(blocks, depth=2, resident=res,
                         disable_after=3)
    assert f2 == f1
    assert s2 == s1
    assert not res.enabled


def test_toy_resident_crash_replay_rebuilds_cold():
    """Mid-stream 'crash': the manager is dropped and a COLD one
    continues over the same committed state — misses refill it and
    verdicts never fork (residency is memory-only by design)."""
    blocks = _churn_stream()
    f1, s1, _ = _run_toy(blocks, depth=1)
    res = ResidencyManager(slots=256, range_bits=8)
    f2, s2, v = _run_toy(blocks, depth=2, resident=res,
                         rebuild_after=4)
    assert f2 == f1
    assert s2 == s1
    st = v.resident.stats()  # the post-crash manager
    assert st["misses_total"] > 0 and st["hits_total"] > 0, (
        "the rebuilt cache must have gone cold → warm again"
    )


@pytest.fixture(scope="module")
def key():
    return ec_ref.SigningKey.generate()


def test_toy_resident_end_to_end_device_verify(key):
    """The crypto-free END-TO-END: real p256v3 device signature
    verifies (bad-sig lanes load-bearing) + resident version state +
    depth-2 CommitPipeline ≡ the host-oracle serial run."""
    blocks = _churn_stream(n_blocks=4, n_tx=8, sign_key=key)
    f1, s1, _ = _run_toy(blocks, depth=1, sign=True)
    res = ResidencyManager(slots=256, range_bits=8)
    f2, s2, _ = _run_toy(blocks, depth=2, resident=res, sign=True)
    assert f2 == f1
    assert s2 == s1
    assert res.stats()["hits_total"] > 0
    for _n, flt in f1:
        assert flt[2] == ResidentToyValidator.BADSIG
        assert ResidentToyValidator.VALID in flt

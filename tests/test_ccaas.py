"""Chaincode-as-a-service: a contract hosted in an external server
process, driven through the peer's endorser with state callbacks over
the duplex stream (reference: ccaas_builder/, handler.go:364)."""

import asyncio

from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.ccaas import CCaaSProxy, ChaincodeServer
from fabric_tpu.peer.chaincode import ChaincodeRuntime, KVContract
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.protos import proposal_pb2

CHANNEL, CC = "ccaaschan", "remotecc"


def test_ccaas_end_to_end(tmp_path):
    async def scenario():
        server = await ChaincodeServer().start()
        server.register(CC, KVContract())
        try:
            org = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
            mgr = MSPManager({"Org1MSP": org.msp()})
            signer = cryptogen.signing_identity(org, "peer0.org1.example.com")
            client = cryptogen.signing_identity(org, "User1@org1.example.com")

            state = MemVersionedDB()
            seed = UpdateBatch()
            seed.put(CC, "existing", b"42", (1, 0))
            state.apply_updates(seed, (1, 0))

            rt = ChaincodeRuntime()
            rt.register(CC, CCaaSProxy(CC, "127.0.0.1", server.port))
            endorser = Endorser(mgr, signer, state, rt)

            loop = asyncio.get_event_loop()

            async def endorse(args, transient=None):
                signed, tx_id, prop = txa.create_signed_proposal(
                    client, CHANNEL, CC, args, transient=transient
                )
                return await loop.run_in_executor(
                    None, endorser.process_proposal, signed
                )

            # read existing state through the remote contract
            res = await endorse([b"get", b"existing"])
            assert res.response.response.status == 200

            # write path: rwset is built peer-side
            res = await endorse([b"put", b"k1", b"v1"])
            assert res.response.response.status == 200
            from fabric_tpu.ledger.rwset import TxRWSet
            from fabric_tpu import protoutil
            prp = protoutil.unmarshal(
                proposal_pb2.ProposalResponsePayload, res.response.payload
            )
            cca = protoutil.unmarshal(proposal_pb2.ChaincodeAction, prp.extension)
            rw = TxRWSet.from_bytes(cca.results)
            assert rw.ns[CC].writes["k1"] == b"v1"

            # private data through the remote contract
            res = await endorse([b"put_private", b"collX", b"pk"],
                                transient={"value": b"pv"})
            assert res.response.response.status == 200
            assert res.pvt_cleartext[(CC, "collX")]["pk"] == b"pv"

            # error propagation
            res = await endorse([b"get", b"missing-key"])
            assert res.response.response.status == 404
        finally:
            await server.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 60))
    finally:
        loop.close()

"""Host-path vs device-path differential property test: randomized
adversarial blocks (invalid signatures, duplicate endorsers/txids,
consumption-unsafe policies, stale/phantom reads, range queries,
hashed-collection reads/writes, key-level endorsement (SBE) lanes —
committed policies, in-block policy updates/clears — config txs,
garbage envelopes) must produce identical TRANSACTIONS_FILTER, update
batches (values + metadata + versions), and history on
`_validate_host` and the fused device path — the fallback conditions
are exactly where a silent divergence would hide.  (Missing-pvtdata /
BTL-expiry / eligibility live at the peer coordinator layer and are
pinned by test_gossip_pvtdata.py instead.)"""

import random

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.validator import (
    BlockValidator, NamespaceInfo, PolicyProvider,
)

CHANNEL = "diffchan"
CC_SAFE = "diffcc"
CC_UNSAFE = "diffun"
N_BLOCKS = 200
TXS_PER_BLOCK = 8  # fixed-ish sizes keep the jit shape set small


@pytest.fixture(scope="module")
def net():
    orgs = [
        cryptogen.generate_org(f"Org{i}MSP", f"org{i}.diff.example.com",
                               peers=1, users=1)
        for i in (1, 2, 3)
    ]
    mgr = MSPManager({o.msp_id: o.msp() for o in orgs})
    peers = [
        cryptogen.signing_identity(o, f"peer0.org{i}.diff.example.com")
        for i, o in zip((1, 2, 3), orgs)
    ]
    rogue_org = cryptogen.generate_org("RogueMSP", "rogue.diff.example.com",
                                       peers=1)
    prov = PolicyProvider({
        CC_SAFE: NamespaceInfo(policy=pol.from_dsl(
            "OutOf(2, 'Org1MSP.peer', 'Org2MSP.peer', 'Org3MSP.peer')")),
        # one identity can match BOTH principals → consumption-unsafe
        # rows → the device path must fall back and still agree
        CC_UNSAFE: NamespaceInfo(policy=pol.from_dsl(
            "OutOf(1, 'Org1MSP.peer', 'Org1MSP.member')")),
    })
    return {
        "mgr": mgr, "prov": prov, "peers": peers,
        "client": cryptogen.signing_identity(orgs[0],
                                             "User1@org1.diff.example.com"),
        "rogue": cryptogen.signing_identity(rogue_org,
                                            "peer0.rogue.diff.example.com"),
    }


def _sbe_policy_bytes(msp_id: str) -> bytes:
    from fabric_tpu.crypto.msp import policy_to_proto

    return policy_to_proto(
        pol.from_dsl(f"OutOf(1, '{msp_id}.peer')")
    ).SerializeToString()


def _seed_state():
    from fabric_tpu.ledger.rwset import VALIDATION_PARAMETER, encode_metadata

    db = MemVersionedDB()
    seed = UpdateBatch()
    for i in range(8):
        seed.put(CC_SAFE, f"s{i}", b"v", (1, i))
        seed.put(CC_UNSAFE, f"u{i}", b"v", (1, i))
    # SBE lane: committed key-level policies (Org2-only / Org3-only)
    for i in range(4):
        seed.put(
            CC_SAFE, f"sbe{i}", b"locked", (1, 20 + i),
            metadata=encode_metadata({
                VALIDATION_PARAMETER:
                    _sbe_policy_bytes("Org2MSP" if i % 2 else "Org3MSP"),
            }),
        )
    # hashed private-collection lane
    import hashlib as _hl

    for i in range(4):
        kh = _hl.sha256(b"pk%d" % i).digest()
        seed.put(f"{CC_SAFE}$collA#hashed", kh.hex(),
                 _hl.sha256(b"pv%d" % i).digest(), (1, 30 + i))
    db.apply_updates(seed, (1, 0))
    return db


def _rand_tx(net, rng):
    ns = CC_UNSAFE if rng.random() < 0.15 else CC_SAFE
    tx = TxRWSet()
    n = tx.ns_rwset(ns)
    for _ in range(rng.randrange(0, 3)):
        i = rng.randrange(8)
        key = f"{'u' if ns == CC_UNSAFE else 's'}{i}"
        kind = rng.random()
        if kind < 0.6:
            n.reads[key] = (1, i)          # fresh
        elif kind < 0.8:
            n.reads[key] = (0, 99)         # stale → conflict
        else:
            n.reads[f"absent{i}"] = None   # absent, matches state
    for _ in range(rng.randrange(0, 3)):
        n.writes[f"w{rng.randrange(12)}"] = b"x"
    if ns == CC_SAFE:
        sb = rng.random()
        if sb < 0.12:
            # write an SBE-locked key (committed Org2/Org3-only
            # policy): validity depends on which endorsers land below
            n.writes[f"sbe{rng.randrange(4)}"] = b"y"
        elif sb < 0.2:
            # in-block policy update / clear on a random key
            from fabric_tpu.ledger.rwset import VALIDATION_PARAMETER

            key = rng.choice(
                [f"sbe{rng.randrange(4)}", f"s{rng.randrange(8)}"]
            )
            if rng.random() < 0.3:
                n.metadata_writes[key] = {}  # clear → ns policy
            else:
                n.metadata_writes[key] = {
                    VALIDATION_PARAMETER: _sbe_policy_bytes(
                        rng.choice(["Org1MSP", "Org2MSP", "Org3MSP"])
                    ),
                }
        if rng.random() < 0.12:
            # hashed private-collection reads/writes
            import hashlib as _hl

            coll = n.hashed.setdefault(
                "collA", {"reads": {}, "writes": {}}
            )
            i = rng.randrange(4)
            kh = _hl.sha256(b"pk%d" % i).digest()
            if rng.random() < 0.5:
                coll["reads"][kh] = (
                    (1, 30 + i) if rng.random() < 0.7 else (0, 9)
                )
            else:
                coll["writes"][kh] = (_hl.sha256(b"nv").digest(), False)
    if rng.random() < 0.15:
        # range query over seeded keys; sometimes missing a result
        lo, hi = "s0", "s4"
        results = [(f"s{i}", (1, i)) for i in range(4)
                   if not (rng.random() < 0.4 and i == 2)]
        n.range_queries.append((lo, hi, results))
    rw = tx.to_proto().SerializeToString()

    choice = rng.random()
    peers = net["peers"]
    if choice < 0.55:
        endorsers = rng.sample(peers, 2)          # satisfies 2-of-3
    elif choice < 0.7:
        endorsers = [rng.choice(peers)]           # under-endorsed
    elif choice < 0.8:
        p = rng.choice(peers)
        endorsers = [p, p]                        # duplicate endorser
    elif choice < 0.9:
        endorsers = [rng.choice(peers), net["rogue"]]  # foreign org
    else:
        endorsers = rng.sample(peers, 3)
    _, _, prop = txa.create_signed_proposal(net["client"], CHANNEL, ns, [b"i"])
    resps = [txa.create_proposal_response(prop, rw, e, ns) for e in endorsers]
    env = txa.assemble_transaction(prop, resps, net["client"])

    tamper = rng.random()
    if tamper < 0.08:
        env.signature = env.signature[:-4] + bytes(4)   # bad creator sig
    elif tamper < 0.16:
        raw = bytearray(env.SerializeToString())
        # flip one byte deep in the payload: often breaks an
        # endorsement or the structure — both paths must agree on HOW
        raw[len(raw) // 2] ^= 0x40
        return bytes(raw)
    return env.SerializeToString()


def _rand_block(net, rng, num):
    envs = []
    dup_pool = []
    for _ in range(TXS_PER_BLOCK):
        r = rng.random()
        if r < 0.04:
            envs.append(b"")                      # nil envelope
        elif r < 0.08:
            envs.append(b"\x13garbage-bytes")     # malformed
        elif r < 0.12 and dup_pool:
            envs.append(rng.choice(dup_pool))     # duplicate txid
        else:
            raw = _rand_tx(net, rng)
            envs.append(raw)
            dup_pool.append(raw)
    blk = pu.new_block(num, b"prev-%d" % num)
    for e in envs:
        blk.data.data.append(e)
    return pu.finalize_block(blk)


def test_host_device_differential(net):
    rng = random.Random(20260730)
    mismatches = []
    for bi in range(N_BLOCKS):
        blk = _rand_block(net, rng, num=2 + bi)

        v_dev = BlockValidator(net["mgr"], net["prov"], _seed_state())
        flt_d, batch_d, hist_d = v_dev.validate(blk)

        v_host = BlockValidator(net["mgr"], net["prov"], _seed_state())
        pre = v_host.preprocess(blk)
        flt_h, batch_h, hist_h = v_host._validate_host(
            blk, pre[0], pre[1], pre[2], fb=pre[5]
        )
        def rows(b):
            return sorted(
                (k, vv.value, vv.metadata, vv.version)
                for k, vv in b.updates.items()
            )

        if (bytes(flt_d) != bytes(flt_h)
                or rows(batch_d) != rows(batch_h)
                or hist_d != hist_h):
            mismatches.append((bi, list(flt_d), list(flt_h)))
    assert not mismatches, mismatches[:5]

"""Chaincode lifecycle tests: approve/commit state machine, the
state-backed policy provider, and the VERDICT gate — changing a
chaincode's policy via a committed transaction changes validation
behavior (reference: core/chaincode/lifecycle,
plugindispatcher/dispatcher.go:266 GetInfoForValidate)."""

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import lifecycle as lc
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.chaincode import ChaincodeRuntime
from fabric_tpu.peer.simulator import TxSimulator
from fabric_tpu.peer.validator import BlockValidator, NamespaceInfo
from fabric_tpu.protos import transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL = "lcchan"
CC = "mycc"
ORGS = ["Org1MSP", "Org2MSP", "Org3MSP"]


@pytest.fixture(scope="module")
def net():
    orgs = {
        f"Org{i}MSP": cryptogen.generate_org(
            f"Org{i}MSP", f"org{i}.example.com", peers=1, users=1
        )
        for i in (1, 2, 3)
    }
    mgr = MSPManager({k: o.msp() for k, o in orgs.items()})
    return {
        "orgs": orgs,
        "mgr": mgr,
        "client": cryptogen.signing_identity(
            orgs["Org1MSP"], "User1@org1.example.com"
        ),
        "peers": {
            k: cryptogen.signing_identity(o, f"peer0.org{i}.example.com")
            for i, (k, o) in enumerate(orgs.items(), start=1)
        },
    }


def _runtime():
    rt = ChaincodeRuntime()
    rt.register(lc.LIFECYCLE_NS, lc.LifecycleContract(org_lister=lambda: ORGS))
    return rt


def _invoke(rt, state, args, creator=b""):
    sim = TxSimulator(state)
    resp = rt.execute(sim, lc.LIFECYCLE_NS, args, creator=creator)
    return resp, sim


def _creator(net, org):
    return net["peers"][org].serialized


def _apply(state, sim, height):
    rw, _ = sim.done()
    tx = TxRWSet.from_bytes(rw)
    batch = UpdateBatch()
    for ns_name, n in tx.ns.items():
        for k, v in n.writes.items():
            batch.put(ns_name, k, v, (height, 0))
    state.apply_updates(batch, (height, 0))
    return batch


def test_approve_then_commit(net):
    state = MemVersionedDB()
    rt = _runtime()
    spec = b'{"policy": {"ref": "Endorsement"}}'

    # commit without approvals: fails
    resp, _ = _invoke(rt, state, [b"commit", CC.encode(), b"1", spec],
                      creator=_creator(net, "Org1MSP"))
    assert resp.status == 500 and "insufficient" in resp.message

    # two of three orgs approve → committable
    for h, org in enumerate(("Org1MSP", "Org2MSP"), start=1):
        resp, sim = _invoke(rt, state, [b"approve", CC.encode(), b"1", spec],
                            creator=_creator(net, org))
        assert resp.status == 200, resp.message
        _apply(state, sim, h)

    resp, _ = _invoke(rt, state, [b"checkcommitreadiness", CC.encode(), b"1", spec])
    import json
    ready = json.loads(resp.payload)
    assert ready == {"Org1MSP": True, "Org2MSP": True, "Org3MSP": False}

    resp, sim = _invoke(rt, state, [b"commit", CC.encode(), b"1", spec],
                        creator=_creator(net, "Org1MSP"))
    assert resp.status == 200, resp.message
    _apply(state, sim, 3)

    resp, _ = _invoke(rt, state, [b"querydef", CC.encode()])
    cd = lc.ChaincodeDefinition.from_bytes(resp.payload)
    assert cd.sequence == 1 and cd.policy == {"ref": "Endorsement"}

    # sequence discipline: re-commit of seq 1 and skip to 3 both fail
    for seq in (b"1", b"3"):
        resp, _ = _invoke(rt, state, [b"commit", CC.encode(), seq, spec],
                          creator=_creator(net, "Org1MSP"))
        assert resp.status == 500

    # approval at a mismatched spec does not count
    other = b'{"policy": {"ref": "Admins"}}'
    resp, sim = _invoke(rt, state, [b"approve", CC.encode(), b"2", other],
                        creator=_creator(net, "Org3MSP"))
    _apply(state, sim, 4)
    resp, _ = _invoke(rt, state, [b"commit", CC.encode(), b"2", spec],
                      creator=_creator(net, "Org1MSP"))
    assert resp.status == 500


def _committed_def_state(policy_ast, plugin="default", seq=1):
    """State DB holding one committed definition for CC."""
    state = MemVersionedDB()
    cd = lc.ChaincodeDefinition(
        name=CC, sequence=seq, plugin=plugin,
        policy=lc.policy_spec_from_ast(policy_ast),
    )
    b = UpdateBatch()
    b.put(lc.LIFECYCLE_NS, lc.definition_key(CC), cd.to_bytes(), (1, 0))
    state.apply_updates(b, (1, 0))
    return state


def test_provider_reads_committed_state(net):
    ast = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.peer')")
    state = _committed_def_state(ast)
    prov = lc.LifecyclePolicyProvider(state)
    info = prov.info(CC)
    assert info is not None and info.policy == ast
    assert prov.info("unknown-ns") is None

    # ref resolution through a channel-config-backed resolver
    refs = {"Endorsement": pol.from_dsl("OutOf(2,'Org1MSP.peer','Org2MSP.peer','Org3MSP.peer')")}
    prov2 = lc.LifecyclePolicyProvider(state, ref_resolver=refs.get)
    assert prov2.info(lc.LIFECYCLE_NS) is None  # no LifecycleEndorsement ref
    refs["LifecycleEndorsement"] = refs["Endorsement"]
    prov3 = lc.LifecyclePolicyProvider(state, ref_resolver=refs.get)
    assert prov3.info(lc.LIFECYCLE_NS).policy == refs["Endorsement"]


def _tx(net, endorsers, writes, ns=CC, signer=None):
    signer = signer or net["client"]
    signed, tx_id, prop = txa.create_signed_proposal(signer, CHANNEL, ns, [b"invoke"])
    tx = TxRWSet()
    n = tx.ns_rwset(ns)
    for k, v in writes:
        n.writes[k] = v
    rw = tx.to_proto().SerializeToString()
    responses = [txa.create_proposal_response(prop, rw, e, ns) for e in endorsers]
    return txa.assemble_transaction(prop, responses, signer)


def _block(envs, num):
    blk = pu.new_block(num, b"prev")
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    return pu.finalize_block(blk)


def test_committed_policy_change_changes_validation(net):
    """The VERDICT gate: rotating CC's policy via a committed
    ``_lifecycle`` write flips a previously-valid endorsement set to
    ENDORSEMENT_POLICY_FAILURE on the very next block."""
    org1_only = pol.from_dsl("AND('Org1MSP.peer')")
    both = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.peer')")
    lifecycle_pol = pol.from_dsl("OutOf(1,'Org1MSP.peer','Org2MSP.peer')")

    state = _committed_def_state(org1_only)
    prov = lc.LifecyclePolicyProvider(state, lifecycle_policy=lifecycle_pol)
    v = BlockValidator(net["mgr"], prov, state)

    p1, p2 = net["peers"]["Org1MSP"], net["peers"]["Org2MSP"]

    # block 2: Org1-only endorsement is VALID under the current policy
    env1 = _tx(net, [p1], [("k", b"v1")])
    flt, batch, _ = v.validate(_block([env1], 2))
    assert list(flt) == [C.VALID]
    state.apply_updates(batch, (2, 0))
    prov.on_block_committed(batch)

    # block 3: a _lifecycle tx rotates the policy to AND(Org1, Org2)
    cd = lc.ChaincodeDefinition(
        name=CC, sequence=2, policy=lc.policy_spec_from_ast(both)
    )
    env_lc = _tx(net, [p1], [(lc.definition_key(CC), cd.to_bytes())],
                 ns=lc.LIFECYCLE_NS)
    flt, batch, _ = v.validate(_block([env_lc], 3))
    assert list(flt) == [C.VALID]
    state.apply_updates(batch, (3, 0))
    prov.on_block_committed(batch)

    # block 4: the same Org1-only endorsement now FAILS policy
    env2 = _tx(net, [p1], [("k", b"v2")])
    env3 = _tx(net, [p1, p2], [("k2", b"v3")])
    flt, batch, _ = v.validate(_block([env2, env3], 4))
    assert list(flt) == [C.ENDORSEMENT_POLICY_FAILURE, C.VALID]

"""Device-time launch ledger battery (observe/ledger.py).

Crypto-free core (injected clock, private registry/tracer):

* attribution identities — compile + queue + execute + transfer sums
  to the row's wall (exactly on misses; within tolerance on hits,
  where the residue is the dispatch overhead the row also reports);
* queue attribution under depth-N overlap — a launch enqueued while
  its lane predecessor is still executing books the wait as QUEUE,
  not execute;
* program-cache accounting (exact verdicts and first-seen inference),
  enqueue-only rows (scatters), ring bounds, HBM owner bookkeeping;
* disabled ⇒ zero instruments registered and every dispatch hook is
  one module-global read + None check returning None;
* histogram trace exemplars (bounded last-K rings, surfaced by
  ``ops_metrics.exemplars_report``);
* device-lane child spans under the dispatch-time parent span
  (``device:<lane>`` thread rows; compile color-coded in the Chrome
  export);
* the ``/launches`` endpoint over a live OperationsServer;
* a REAL fused stage-2 dispatch (the crypto-free test_resident
  harness) recording miss-then-hit rows whose identity holds on a
  real device.

Crypto-gated acceptance: one real endorsed block through the full
BlockValidator — the stage-2 row's queue+execute covers the measured
``device_wait`` stage.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from fabric_tpu.observe import ledger
from fabric_tpu.observe.ledger import LaunchLedger
from fabric_tpu.observe.tracer import Tracer
from fabric_tpu.ops_metrics import Registry, exemplars_report


class Clock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _ledger(clk=None, **kw):
    clk = clk or Clock()
    reg = Registry()
    tr = Tracer(ring_blocks=8, slow_factor=0, clock=clk)
    return LaunchLedger(registry=reg, tracer=tr, clock=clk, **kw), \
        reg, tr, clk


# ---------------------------------------------------------------------------
# attribution identities


def test_identity_on_cache_miss_is_exact():
    led, reg, tr, clk = _ledger()
    rec = led.launch("stage2", compiled=True, lanes=64, h2d_bytes=4096)
    rec.note_h2d(0, seconds=0.010)          # timed staging upload
    clk.advance(0.5)                        # the compile
    rec.dispatched()
    clk.advance(0.1)                        # host gap before the sync
    rec.sync_begin()
    clk.advance(0.9)                        # blocked sync = execute
    rec.sync_end(d2h_bytes=64)
    row = led.rows()[-1]
    assert row["cache"] == "miss"
    assert row["compile_ms"] == 500.0
    assert row["queue_ms"] == 0.0
    assert row["execute_ms"] == 1000.0      # gap + blocked sync
    assert row["h2d_bytes"] == 4096 and row["d2h_bytes"] == 64
    attributed = (row["compile_ms"] + row["queue_ms"]
                  + row["execute_ms"] + row["h2d_ms"])
    assert attributed == pytest.approx(row["wall_ms"], rel=1e-9)


def test_identity_on_cache_hit_within_tolerance():
    led, reg, tr, clk = _ledger()
    # warm the lane so the hit row has a predecessor
    r0 = led.launch("k", compiled=True)
    clk.advance(0.01)
    r0.dispatched()
    r0.sync_begin()
    clk.advance(0.05)
    r0.sync_end()
    rec = led.launch("k", compiled=False, lanes=8)
    clk.advance(0.002)                      # dispatch overhead (hit)
    rec.dispatched()
    rec.sync_begin()
    clk.advance(0.2)
    rec.sync_end()
    row = led.rows()[-1]
    assert row["cache"] == "hit" and row["compile_ms"] == 0.0
    attributed = (row["compile_ms"] + row["queue_ms"]
                  + row["execute_ms"] + row["h2d_ms"])
    # the residue is exactly the dispatch overhead, reported honestly
    assert row["wall_ms"] - attributed == pytest.approx(
        row["dispatch_ms"], rel=1e-9)
    assert abs(row["wall_ms"] - attributed) <= 0.05 * row["wall_ms"]


def test_queue_attribution_under_overlap():
    """Depth-N shape: launch B enqueued while A still executes on the
    same lane — B's wait behind A books as QUEUE, the remainder as
    execute."""
    led, reg, tr, clk = _ledger()
    a = led.launch("stage2", compiled=False)
    clk.advance(0.001)
    a.dispatched()                           # A enqueued at t=100.001
    b = led.launch("stage2", compiled=False)
    clk.advance(0.001)
    b.dispatched()                           # B enqueued at t=100.002
    # A syncs: blocked until t=100.502 → lane busy until then
    a.sync_begin()
    clk.advance(0.5)
    a.sync_end()
    # B syncs: blocked until t=100.802
    b.sync_begin()
    clk.advance(0.3)
    b.sync_end()
    row = led.rows()[-1]
    assert row["queue_ms"] == pytest.approx(500.0, abs=1.5)
    assert row["execute_ms"] == pytest.approx(300.0, abs=1.5)
    # trailing signal reads the queueing
    assert led.queue_p99_ms() == pytest.approx(row["queue_ms"])


def test_nonblocking_sync_does_not_book_host_lag_as_execute():
    """The device finished long before the host looked: a sync that
    returns immediately bounds completion at its ENTRY, so the host's
    lag is not attributed to execute beyond that bound."""
    led, reg, tr, clk = _ledger()
    rec = led.launch("k", compiled=False)
    clk.advance(0.001)
    rec.dispatched()
    clk.advance(0.05)                        # device works ≤ 50 ms
    clk.advance(5.0)                         # host wanders off
    rec.sync_begin()
    rec.sync_end()                           # returns instantly
    row = led.rows()[-1]
    assert row["execute_ms"] == pytest.approx(5050.0, abs=1.5)
    # NOT 5050 + another blocked-sync interval: the bound is the entry
    assert row["wall_ms"] == pytest.approx(5051.0, abs=1.5)


def test_first_seen_key_infers_compile():
    led, reg, tr, clk = _ledger()
    r1 = led.launch("verify", key=(1024, False, 0))
    assert r1.compiled is True
    r2 = led.launch("verify", key=(1024, False, 0))
    assert r2.compiled is False
    r3 = led.launch("verify", key=(2048, False, 0))
    assert r3.compiled is True


def test_enqueue_only_rows_leave_lane_untouched():
    led, reg, tr, clk = _ledger()
    rec = led.launch("resident_scatter", compiled=True, h2d_bytes=192)
    clk.advance(0.02)
    rec.dispatched()
    rec.complete()
    rec.complete()                            # idempotent
    row = led.rows()[-1]
    assert row["queue_ms"] is None and row["execute_ms"] is None
    assert row["wall_ms"] is None
    assert row["compile_ms"] == 20.0 and row["h2d_bytes"] == 192
    # the lane's completion estimate is untouched: the next synced
    # launch sees no phantom predecessor
    nxt = led.launch("k", compiled=False)
    nxt.dispatched()
    nxt.sync_begin()
    clk.advance(0.1)
    nxt.sync_end()
    assert led.rows()[-1]["queue_ms"] == 0.0


def test_ring_bound_and_row_filters():
    led, reg, tr, clk = _ledger(ring=8)
    for i in range(20):
        rec = led.launch("a" if i % 2 else "b", compiled=False)
        rec.dispatched()
        rec.sync_begin()
        clk.advance(0.001)
        rec.sync_end()
    assert len(led.rows()) == 8
    assert len(led.rows(3)) == 3
    assert all(r["kernel"] == "a" for r in led.rows(kernel="a"))
    st = led.stats()
    assert st["rows_retained"] == 8
    assert set(st["kernels"]) == {"a", "b"}


def test_begin_dispatch_excludes_host_staging_from_compile():
    """The verify path stages the wire frame on the host BETWEEN
    opening the record and dispatching — begin_dispatch() re-anchors
    so staging is never booked as compile (miss) or dispatch overhead
    (hit)."""
    led, reg, tr, clk = _ledger()
    rec = led.launch("verify", compiled=True)
    clk.advance(2.0)                         # host wire-frame staging
    rec.begin_dispatch()
    clk.advance(0.3)                         # the actual compile
    rec.begin_dispatch()                     # later calls are no-ops
    rec.dispatched()
    rec.sync_begin()
    clk.advance(0.1)
    rec.sync_end()
    row = led.rows()[-1]
    assert row["compile_ms"] == pytest.approx(300.0)
    assert row["wall_ms"] == pytest.approx(400.0)


def test_transient_hbm_pins_sum_and_release():
    """Depth-N concurrent launches SUM their frame pins (the
    watermark records the true concurrent peak, not the largest
    single block) and release them at completion."""
    led, reg, tr, clk = _ledger()
    a = led.launch("stage2", compiled=False)
    a.pin_hbm("launch_frames", 10 << 20)
    a.dispatched()
    b = led.launch("stage2", compiled=False)
    b.pin_hbm("launch_frames", 10 << 20)     # both in flight
    b.dispatched()
    hbm = led.stats()["hbm"]["launch_frames"]
    assert hbm["current_bytes"] == 20 << 20
    assert hbm["watermark_bytes"] == 20 << 20
    a.sync_begin()
    clk.advance(0.1)
    a.sync_end()
    hbm = led.stats()["hbm"]["launch_frames"]
    assert hbm["current_bytes"] == 10 << 20  # A's frames released
    b.sync_begin()
    clk.advance(0.1)
    b.sync_end()
    hbm = led.stats()["hbm"]["launch_frames"]
    assert hbm["current_bytes"] == 0         # idle reports idle
    assert hbm["watermark_bytes"] == 20 << 20
    assert reg.gauge("device_ledger_hbm_bytes").value(
        owner="launch_frames") == 0


def test_rows_zero_bound_means_none():
    led, reg, tr, clk = _ledger()
    rec = led.launch("k", compiled=False)
    rec.dispatched()
    rec.sync_begin()
    rec.sync_end()
    assert led.rows(0) == []
    assert led.rows(-3) == []
    assert led.report(rows=0)["recent"] == []


def test_hbm_owner_bookkeeping():
    led, reg, tr, clk = _ledger()
    led.account_hbm("resident_table", 1 << 20)
    led.account_hbm("comb_table", 376832)
    led.account_hbm("resident_table", 512)    # level drops
    hbm = led.stats()["hbm"]
    assert hbm["resident_table"] == {
        "current_bytes": 512, "watermark_bytes": 1 << 20,
    }
    assert hbm["comb_table"]["watermark_bytes"] == 376832
    g = reg.gauge("device_ledger_hbm_bytes")
    assert g.value(owner="resident_table") == 512
    assert reg.gauge("device_ledger_hbm_watermark_bytes").value(
        owner="resident_table") == float(1 << 20)


# ---------------------------------------------------------------------------
# disabled ⇒ zero cost, zero instruments


def test_disabled_hooks_are_none_checks_and_register_nothing():
    assert ledger.global_ledger() is None     # the module default
    before = Registry()
    assert ledger.launch("stage2", compiled=True) is None
    ledger.note_h2d("state", 4096)
    ledger.account_hbm("resident_table", 1024)
    # nothing was created anywhere: a fresh registry stays empty and
    # the global one gained no device_launch_* instruments from these
    # disabled calls (instruments are built only in LaunchLedger.__init__)
    assert before.metrics() == []
    led, reg, tr, clk = _ledger()
    names = {n for n, _m in reg.metrics()}
    assert "device_launch_compile_seconds" in names
    assert "device_launches_total" in names


def test_acquire_release_refcount():
    reg = Registry()
    try:
        l1 = ledger.acquire(registry=reg)
        l2 = ledger.acquire()
        assert l1 is l2 and ledger.global_ledger() is l1
        ledger.release()
        assert ledger.global_ledger() is l1   # one holder left
        ledger.release()
        assert ledger.global_ledger() is None
    finally:
        ledger.configure(enabled=False)


# ---------------------------------------------------------------------------
# exemplars


def test_histogram_exemplar_ring_bounds():
    reg = Registry()
    h = reg.histogram("lat_seconds", "t", exemplars=3)
    for i in range(10):
        h.observe(float(i), exemplar=f"blk{i}", kernel="stage2")
    h.observe(0.5, kernel="stage2")           # no exemplar: not recorded
    snap = h.exemplar_snapshot()
    [(key, ring)] = snap.items()
    assert dict(key) == {"kernel": "stage2"}
    assert ring == [(7.0, "blk7"), (8.0, "blk8"), (9.0, "blk9")]
    rep = exemplars_report(reg)
    assert rep["lat_seconds"]["kernel=stage2"] == [
        [7.0, "blk7"], [8.0, "blk8"], [9.0, "blk9"],
    ]
    # unarmed histograms stay exemplar-free and out of the report
    h2 = reg.histogram("plain_seconds", "t")
    h2.observe(1.0, exemplar="x")
    assert h2.exemplar_snapshot() == {}
    assert "plain_seconds" not in exemplars_report(reg)


def test_ledger_rows_carry_trace_exemplars():
    led, reg, tr, clk = _ledger()
    root = tr.begin_block(42, channel="c")
    tok = tr.attach(root)
    try:
        rec = led.launch("stage2", compiled=True)
        clk.advance(0.1)
        rec.dispatched()
        rec.sync_begin()
        clk.advance(0.2)
        rec.sync_end()
    finally:
        tr.detach(tok)
        tr.finish_block(root)
    assert led.rows()[-1]["block"] == "42"
    rep = exemplars_report(reg)
    assert rep["device_launch_compile_seconds"]["kernel=stage2"] == [
        [pytest.approx(0.1), "42"],
    ]
    assert rep["device_launch_execute_seconds"]["kernel=stage2"][0][1] \
        == "42"


# ---------------------------------------------------------------------------
# device-lane trace spans


def test_device_lane_child_spans_and_chrome_colors():
    led, reg, tr, clk = _ledger()
    root = tr.begin_block(7, channel="c")
    tok = tr.attach(root)
    try:
        # predecessor occupies the lane so the second launch queues
        a = led.launch("stage2", compiled=True)
        clk.advance(0.3)
        a.dispatched()
        b = led.launch("stage2", compiled=False)
        clk.advance(0.001)
        b.dispatched()
        a.sync_begin()
        clk.advance(0.4)
        a.sync_end()
        b.sync_begin()
        clk.advance(0.2)
        b.sync_end()
    finally:
        tr.detach(tok)
        tr.finish_block(root)
    tree = tr.block(7)
    names = [c["name"] for c in tree["children"]]
    assert names.count("dev:compile") == 1
    assert names.count("dev:execute") == 2
    assert names.count("dev:queue") == 1
    for c in tree["children"]:
        assert c["thread"] == "device:dev"
        assert c["attrs"]["kernel"] == "stage2"
    qs = [c for c in tree["children"] if c["name"] == "dev:queue"]
    assert qs[0]["dur_ms"] == pytest.approx(400.0, abs=1.5)
    # Perfetto export: device spans ride their own thread row with
    # compile color-coded distinct from execute
    evs = tr.chrome_events()
    by_name = {}
    for e in evs:
        if e.get("ph") == "X":
            by_name.setdefault(e["name"], []).append(e)
    assert by_name["dev:compile"][0]["cname"] == "terrible"
    assert by_name["dev:execute"][0]["cname"] == "good"
    assert by_name["dev:queue"][0]["cname"] == "bad"
    dev_tids = {e["tid"] for e in by_name["dev:execute"]}
    blk_tids = {e["tid"] for e in by_name["block"]}
    assert dev_tids.isdisjoint(blk_tids)


# ---------------------------------------------------------------------------
# /launches endpoint


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, json.loads(r.read())


def test_launches_endpoint_roundtrip():
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    led, reg, tr, clk = _ledger()
    for i, kernel in enumerate(("stage2", "stage2", "sign")):
        rec = led.launch(kernel, compiled=(i != 1))
        clk.advance(0.05)
        rec.dispatched()
        rec.sync_begin()
        clk.advance(0.1)
        rec.sync_end()
    led.account_hbm("resident_table", 4096)

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=reg, health=HealthRegistry(), launches=led,
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, idx = await loop.run_in_executor(
                None, _get, srv.port, "/launches"
            )
            assert st == 200 and idx["enabled"]
            assert idx["kernels"]["stage2"]["launches"] == 2
            assert idx["kernels"]["stage2"]["cache_hit_rate"] == 0.5
            assert idx["kernels"]["sign"]["cache_misses"] == 1
            assert idx["hbm"]["resident_table"]["watermark_bytes"] == 4096
            assert len(idx["recent"]) == 3
            st, f = await loop.run_in_executor(
                None, _get, srv.port, "/launches?kernel=sign&n=2"
            )
            assert [r["kernel"] for r in f["recent"]] == ["sign"]
            try:
                await loop.run_in_executor(
                    None, _get, srv.port, "/launches?n=zap"
                )
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


def test_launches_endpoint_unarmed_is_honest():
    import asyncio

    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    assert ledger.global_ledger() is None

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=Registry(), health=HealthRegistry(),
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, idx = await loop.run_in_executor(
                None, _get, srv.port, "/launches"
            )
            assert st == 200 and idx == {"enabled": False}
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# black-box bundles carry the ledger


def test_blackbox_bundle_carries_launches_and_exemplars():
    from fabric_tpu.observe import blackbox

    led, reg, tr, clk = _ledger()
    root = tr.begin_block(3, channel="c")
    tok = tr.attach(root)
    rec = led.launch("stage2", compiled=True)
    clk.advance(0.2)
    rec.dispatched()
    rec.sync_begin()
    clk.advance(0.1)
    rec.sync_end()
    tr.detach(tok)
    tr.finish_block(root)
    bb = blackbox.BlackBox(sampler=None, tracer=tr, registry=reg,
                           clock=clk)
    try:
        # the recorder resolves the ledger from the process global
        ledger._global = led
        b = bb.record("degrade_latch", channel="c")
    finally:
        ledger._global = None
    assert b["launches"]["kernels"]["stage2"]["launches"] == 1
    assert b["launches"]["recent"][0]["block"] == "3"
    assert "device_launch_compile_seconds" in b["exemplars"]
    idx = bb.bundles()[0]
    assert "launches" in idx["sections"]
    assert "exemplars" in idx["sections"]


# ---------------------------------------------------------------------------
# autopilot signal


def test_autopilot_prefers_ledger_queue_signal():
    from fabric_tpu.control.autopilot import Autopilot, Signals

    clk = Clock(0.0)
    acts = []
    ap = Autopilot(
        None, lambda k, v: acts.append((k, v)),
        tracer=Tracer(ring_blocks=4, slow_factor=0, clock=clk),
        clock=clk, registry=Registry(),
        initial={"coalesce_blocks": 0, "verify_chunk": 0,
                 "pipeline_depth": 2},
    )
    # ledger signal present AND the legacy launch signal inside ITS
    # dead band: the ledger reading must drive the decision
    d = ap.tick(Signals(device_queue_p99_ms=80.0, launch_p99_ms=150.0,
                        clock_s=20.0))
    assert (d.knob, d.direction) == ("verify_chunk", "up")
    assert d.signal == "device_queue_p99_ms" and d.value == 80.0
    # quiet device lane → chunk recovers toward monolithic
    d = ap.tick(Signals(device_queue_p99_ms=0.5, clock_s=60.0))
    assert (d.knob, d.direction) == ("verify_chunk", "down")
    assert d.signal == "device_queue_p99_ms"
    # no ledger → the launch-span fallback still works
    d = ap.tick(Signals(launch_p99_ms=900.0, clock_s=120.0))
    assert d is not None and d.signal == "launch_p99_ms"


def test_autopilot_reads_global_ledger_signal():
    from fabric_tpu.control.autopilot import Autopilot

    clk = Clock(50.0)
    led, reg, tr, _clk = _ledger(clk)
    a = led.launch("stage2", compiled=False)
    clk.advance(0.001)
    a.dispatched()
    b = led.launch("stage2", compiled=False)
    clk.advance(0.001)
    b.dispatched()
    a.sync_begin()
    clk.advance(0.06)
    a.sync_end()
    b.sync_begin()
    clk.advance(0.01)
    b.sync_end()
    ap = Autopilot(
        None, lambda k, v: None,
        tracer=Tracer(ring_blocks=4, slow_factor=0, clock=clk),
        clock=clk, registry=Registry(),
    )
    try:
        ledger._global = led
        s = ap.read_signals()
    finally:
        ledger._global = None
    assert s.device_queue_p99_ms == pytest.approx(60.0, abs=1.5)
    s2 = ap.read_signals()                    # ledger gone → None
    assert s2.device_queue_p99_ms is None


# ---------------------------------------------------------------------------
# REAL fused stage-2 dispatch (crypto-free) — rows on a real device


def test_real_stage2_dispatch_records_attributed_rows():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax.numpy as jnp  # noqa: F401 — harness needs the device stack
    from test_resident import _run_host, _stage2_fixture

    from fabric_tpu.peer.device_block import DeviceBlockPipeline

    rng = np.random.default_rng(20260806)
    fx = _stage2_fixture(rng)
    pipe = DeviceBlockPipeline()
    reg = Registry()
    led = ledger.configure(registry=reg,
                           tracer=Tracer(ring_blocks=4, slow_factor=0))
    try:
        _run_host(pipe, fx)                   # compile or cache-load
        _run_host(pipe, fx)                   # guaranteed hit
    finally:
        ledger.configure(enabled=False)
    rows = led.rows(kernel="stage2")
    assert len(rows) == 2
    assert rows[-1]["cache"] == "hit"
    for row in rows:
        assert row["execute_ms"] is not None and row["execute_ms"] >= 0
        attributed = (row["compile_ms"] + row["queue_ms"]
                      + row["execute_ms"] + row["h2d_ms"])
        # the identity on a REAL dispatch: residue ≤ 5% + dispatch
        # overhead (hit rows book the dispatch call outside compile)
        assert abs(row["wall_ms"] - attributed) <= (
            0.05 * row["wall_ms"] + row["dispatch_ms"] + 0.01
        )
    st = led.stats()["kernels"]["stage2"]
    assert st["launches"] == 2 and st["cache_hit_rate"] == 0.5
    assert st["h2d_bytes"] > 0 and st["d2h_bytes"] > 0
    hbm = led.stats()["hbm"]
    assert hbm["launch_frames"]["watermark_bytes"] > 0
    assert hbm["outputs"]["watermark_bytes"] > 0


# ---------------------------------------------------------------------------
# crypto-gated acceptance: a real endorsed block's ledger rows cover
# the measured device_wait


def test_e2e_block_ledger_rows_cover_device_wait():
    pytest.importorskip("cryptography")
    from fabric_tpu.crypto import cryptogen
    from fabric_tpu.crypto import policy as pol
    from fabric_tpu.crypto.msp import MSPManager
    from fabric_tpu.ledger.rwset import TxRWSet
    from fabric_tpu.ledger.statedb import MemVersionedDB
    from fabric_tpu.peer import txassembly as txa
    from fabric_tpu.peer.validator import (
        BlockValidator,
        NamespaceInfo,
        PolicyProvider,
    )
    from fabric_tpu import protoutil as pu

    org = cryptogen.generate_org("Org1MSP", "org1.example.com",
                                 peers=1, users=1)
    mgr = MSPManager({"Org1MSP": org.msp()})
    client = cryptogen.signing_identity(org, "User1@org1.example.com")
    peer = cryptogen.signing_identity(org, "peer0.org1.example.com")

    def mk_env(i):
        signed, tx_id, prop = txa.create_signed_proposal(
            client, "ch", "cc", [b"invoke"]
        )
        tx = TxRWSet()
        tx.ns_rwset("cc").writes[f"k{i}"] = b"v"
        rw = tx.to_proto().SerializeToString()
        resp = txa.create_proposal_response(prop, rw, peer, "cc")
        return txa.assemble_transaction(prop, [resp], client)

    blk = pu.new_block(0, b"prev")
    for i in range(4):
        blk.data.data.append(mk_env(i).SerializeToString())
    blk = pu.finalize_block(blk)

    prov = PolicyProvider({
        "cc": NamespaceInfo(policy=pol.from_dsl("OutOf(1, 'Org1MSP.peer')")),
    })
    v = BlockValidator(mgr, prov, MemVersionedDB())
    v.timings = {}
    led = ledger.configure(registry=Registry())
    try:
        flt, batch, _hist = v.validate(blk)
    finally:
        ledger.configure(enabled=False)
    assert all(c == 0 for c in flt)           # VALID — the device path ran
    s2 = led.rows(kernel="stage2")
    assert len(s2) == 1
    row = s2[0]
    # the fused path closes the verify record enqueue-only
    vr = led.rows(kernel="verify")
    assert len(vr) == 1 and vr[0]["execute_ms"] is None
    device_wait_ms = v.timings.get("device_wait", 0.0) * 1000.0
    assert device_wait_ms > 0
    # the stage-2 row's device interval COVERS the measured sync wait
    # (it additionally includes the enqueue→sync-entry host gap), and
    # does not overshoot it by more than the dispatch-side wall
    got = row["queue_ms"] + row["execute_ms"]
    assert got >= device_wait_ms * 0.95
    assert got <= row["wall_ms"]
    attributed = (row["compile_ms"] + row["queue_ms"]
                  + row["execute_ms"] + row["h2d_ms"])
    assert abs(row["wall_ms"] - attributed) <= (
        0.05 * row["wall_ms"] + row["dispatch_ms"] + 0.01
    )

"""Typed node configuration (core/peer/config.go +
orderer/common/localconfig analog): schema validation naming the bad
key, defaults, and FABTPU_ env-var overrides."""

import pytest

from fabric_tpu.nodeconfig import (
    ConfigError, OrdererConfig, PeerConfig, TlsConfig,
    load_orderer_config, load_peer_config,
)


PEER_MIN = {"id": "p0", "data_dir": "/tmp/p0",
            "msp_id": "Org1MSP", "msp_dir": "/tmp/msp"}


def test_defaults_and_required():
    cfg = load_peer_config(dict(PEER_MIN))
    assert isinstance(cfg, PeerConfig)
    assert cfg.port == 0 and cfg.host == "127.0.0.1"
    assert cfg.group_commit == 8 and cfg.transient_retention == 100
    assert cfg.tls is None
    with pytest.raises(ConfigError, match="missing required"):
        load_peer_config({"id": "p0"})
    # a peer cannot start without its signing identity
    with pytest.raises(ConfigError, match="msp_dir"):
        load_peer_config({"id": "p0", "data_dir": "d", "msp_id": "O"})
    # the orderer can (unsigned dev channels)
    load_orderer_config({"id": "o0", "data_dir": "/tmp/o0"})


def test_optional_fields_validated():
    # int | None (PEP 604) fields must still be type-checked
    with pytest.raises(ConfigError, match="operations_port"):
        load_peer_config({**PEER_MIN, "operations_port": "not-a-port"})
    cfg = load_peer_config({**PEER_MIN, "operations_port": 9443})
    assert cfg.operations_port == 9443
    # ... and env-overridable
    cfg = load_peer_config(
        dict(PEER_MIN), environ={"FABTPU_OPERATIONS_PORT": "9444"}
    )
    assert cfg.operations_port == 9444


def test_partial_tls_rejected():
    with pytest.raises(ConfigError, match="cert, key, and ca.*missing"):
        load_peer_config({**PEER_MIN, "tls": {"cert": "c.pem"}})
    # an all-empty section means no TLS
    assert load_peer_config({**PEER_MIN, "tls": {}}).tls is None


def test_unknown_key_named_with_suggestion():
    with pytest.raises(ConfigError, match="unknown key 'prot'.*'port'"):
        load_peer_config({**PEER_MIN, "prot": 7051})
    with pytest.raises(ConfigError, match="tls.certt"):
        load_peer_config({**PEER_MIN, "tls": {"certt": "x"}})
    with pytest.raises(ConfigError, match="channels\\[\\]"):
        load_peer_config({**PEER_MIN, "channels": [{"nam": "ch"}]})


def test_type_errors_name_key_and_types():
    with pytest.raises(ConfigError, match="key 'port'.*int"):
        load_peer_config({**PEER_MIN, "port": "abc"})
    with pytest.raises(ConfigError, match="batch_timeout_s"):
        load_orderer_config({
            "id": "o", "data_dir": "d", "batch_timeout_s": [],
        })
    with pytest.raises(ConfigError, match="consensus.*raft.*bft"):
        load_orderer_config({
            "id": "o", "data_dir": "d", "consensus": "paxos",
        })


def test_orderer_knobs_and_nested_sections():
    cfg = load_orderer_config({
        "id": "o0", "data_dir": "/tmp/o0",
        "cluster": {"o0": ["127.0.0.1", 7050]},
        "max_message_count": 10, "batch_timeout_s": 0.5,
        "consensus": "bft", "view_timeout": 1.5, "wal_retention": 64,
        "tls": {"cert": "c.pem", "key": "k.pem", "ca": "ca.pem"},
        "channels": [{"name": "ch1", "genesis": "g.block"}, "devch"],
    })
    assert isinstance(cfg, OrdererConfig)
    assert cfg.cluster["o0"] == ("127.0.0.1", 7050)
    assert cfg.consensus == "bft" and cfg.wal_retention == 64
    assert isinstance(cfg.tls, TlsConfig) and cfg.tls.cert == "c.pem"
    assert cfg.channels[0].name == "ch1"
    assert cfg.channels[1] == "devch"


def test_env_overrides():
    env = {
        "FABTPU_PORT": "7051",
        "FABTPU_GROUP_COMMIT": "16",
        "FABTPU_DELIVER_CENSORSHIP_CHECK_S": "0.75",
        "FABTPU_TLS_CA": "/etc/ca.pem",
        "FABTPU_TLS_CERT": "/etc/cert.pem",
        "FABTPU_TLS_KEY": "/etc/key.pem",
        "IRRELEVANT": "x",
    }
    cfg = load_peer_config({**PEER_MIN, "port": 1}, environ=env)
    assert cfg.port == 7051               # env beats the file
    assert cfg.group_commit == 16
    assert cfg.deliver_censorship_check_s == 0.75
    assert cfg.tls is not None and cfg.tls.ca == "/etc/ca.pem"
    # bad env values are named by their variable
    with pytest.raises(ConfigError, match="FABTPU_PORT"):
        load_peer_config(
            dict(PEER_MIN), environ={"FABTPU_PORT": "not-a-port"}
        )
    with pytest.raises(ConfigError, match="unknown env override"):
        load_peer_config(
            dict(PEER_MIN), environ={"FABTPU_TLS_BOGUS": "x"}
        )


def test_sign_lane_knobs_flow_and_validate():
    """ISSUE 13 knobs: defaults OFF (the serial signer path), values
    flow like every prior knob, bad values are operator-grade
    ConfigErrors, env overrides work."""
    cfg = load_peer_config(dict(PEER_MIN))
    assert cfg.sign_device is False
    assert cfg.sign_batch_max == 256
    assert cfg.sign_batch_wait_ms == 2.0
    assert cfg.sign_self_check is False
    cfg = load_peer_config({
        **PEER_MIN, "sign_device": True, "sign_batch_max": 1024,
        "sign_batch_wait_ms": 0.5, "sign_self_check": True,
    })
    assert (cfg.sign_device, cfg.sign_batch_max,
            cfg.sign_batch_wait_ms, cfg.sign_self_check) == (
        True, 1024, 0.5, True)
    with pytest.raises(ConfigError, match="sign_batch_max"):
        load_peer_config({**PEER_MIN, "sign_batch_max": 0})
    with pytest.raises(ConfigError, match="sign_batch_wait_ms"):
        load_peer_config({**PEER_MIN, "sign_batch_wait_ms": -1})
    cfg = load_peer_config(
        dict(PEER_MIN), environ={"FABTPU_SIGN_DEVICE": "1",
                                 "FABTPU_SIGN_BATCH_MAX": "512"}
    )
    assert cfg.sign_device is True and cfg.sign_batch_max == 512

def test_state_resident_knobs_flow_and_validate():
    """ISSUE 14 knobs (device-resident MVCC state): default OFF (the
    exact host state_fill path), values flow like every prior knob,
    bad values are operator-grade ConfigErrors, env overrides work."""
    cfg = load_peer_config(dict(PEER_MIN))
    assert cfg.state_resident is False
    assert cfg.state_resident_mb == 64
    assert cfg.state_resident_range_bits == 12
    cfg = load_peer_config({
        **PEER_MIN, "state_resident": True, "state_resident_mb": 256,
        "state_resident_range_bits": 16,
    })
    assert (cfg.state_resident, cfg.state_resident_mb,
            cfg.state_resident_range_bits) == (True, 256, 16)
    with pytest.raises(ConfigError, match="state_resident_mb"):
        load_peer_config({**PEER_MIN, "state_resident_mb": 0})
    with pytest.raises(ConfigError, match="state_resident_range_bits"):
        load_peer_config({**PEER_MIN, "state_resident_range_bits": 0})
    with pytest.raises(ConfigError, match="state_resident_range_bits"):
        load_peer_config({**PEER_MIN, "state_resident_range_bits": 25})
    cfg = load_peer_config(
        dict(PEER_MIN), environ={"FABTPU_STATE_RESIDENT": "1",
                                 "FABTPU_STATE_RESIDENT_MB": "8"}
    )
    assert cfg.state_resident is True and cfg.state_resident_mb == 8

"""End-to-end block-validation pipeline tests.

Builds real signed transactions (cryptogen identities → proposals →
endorsements → envelopes → block) and runs them through the TPU
pipeline, asserting the exact TRANSACTIONS_FILTER codes the reference
would produce (scenarios modeled on txvalidator v20 + txmgr tests).
"""

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.validator import BlockValidator, NamespaceInfo, PolicyProvider
from fabric_tpu.protos import common_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL = "testchan"
CC = "mycc"


@pytest.fixture(scope="module")
def net():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    mgr = MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()})
    return {
        "mgr": mgr,
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "p1": cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        "p2": cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    }


def _rwset(reads=(), writes=(), ns=CC):
    tx = TxRWSet()
    n = tx.ns_rwset(ns)
    for k, ver in reads:
        n.reads[k] = ver
    for k, v in writes:
        n.writes[k] = v
    return tx.to_proto().SerializeToString()


def _tx(net, endorsers, reads=(), writes=(), signer=None, ns=CC):
    signer = signer or net["client"]
    signed, tx_id, prop = txa.create_signed_proposal(signer, CHANNEL, ns, [b"invoke"])
    rw = _rwset(reads, writes, ns)
    responses = [
        txa.create_proposal_response(prop, rw, e, ns) for e in endorsers
    ]
    return txa.assemble_transaction(prop, responses, signer), tx_id


def _block(envs, num=0):
    blk = pu.new_block(num, b"prev")
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    return pu.finalize_block(blk)


@pytest.fixture()
def validator(net):
    state = MemVersionedDB()
    b = UpdateBatch()
    b.put(CC, "existing", b"v", (1, 0))
    state.apply_updates(b, (1, 0))
    policy = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.peer')")
    prov = PolicyProvider({CC: NamespaceInfo(policy=policy)})
    return BlockValidator(net["mgr"], prov, state)


def test_valid_and_policy_failure(net, validator):
    env_ok, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k1", b"v1")])
    env_one, _ = _tx(net, [net["p1"]], writes=[("k2", b"v2")])  # missing Org2
    blk = _block([env_ok, env_one])
    flt, batch, history = validator.validate(blk)
    assert list(flt) == [C.VALID, C.ENDORSEMENT_POLICY_FAILURE]
    assert (CC, "k1") in batch.updates and (CC, "k2") not in batch.updates
    assert history == [(CC, "k1", 0)]


def test_tampered_endorsement_rejected(net, validator):
    env, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")])
    # corrupt one endorsement signature byte, then RE-SIGN the envelope
    # as the creator so only the endorsement check can fire (a stale
    # envelope signature would trip BAD_CREATOR_SIGNATURE first)
    payload = pu.unmarshal(common_pb2.Payload, env.payload)
    tx = pu.unmarshal(transaction_pb2.Transaction, payload.data)
    cap = pu.unmarshal(transaction_pb2.ChaincodeActionPayload, tx.actions[0].payload)
    sig = bytearray(cap.action.endorsements[1].signature)
    sig[-1] ^= 1
    cap.action.endorsements[1].signature = bytes(sig)
    tx.actions[0].payload = cap.SerializeToString()
    payload.data = tx.SerializeToString()
    env2 = pu.sign_envelope(payload, net["client"])
    flt, _, _ = validator.validate(_block([env2]))
    assert list(flt) == [C.ENDORSEMENT_POLICY_FAILURE]


def test_bad_creator_signature(net, validator):
    env, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")])
    env.signature = env.signature[:-2] + bytes(2)
    flt, _, _ = validator.validate(_block([env]))
    assert list(flt) == [C.BAD_CREATOR_SIGNATURE]


def test_mvcc_conflict_between_block_txs(net, validator):
    envA, _ = _tx(net, [net["p1"], net["p2"]],
                  reads=[("existing", (1, 0))], writes=[("existing", b"new")])
    envB, _ = _tx(net, [net["p1"], net["p2"]],
                  reads=[("existing", (1, 0))], writes=[("other", b"x")])
    flt, batch, _ = validator.validate(_block([envA, envB]))
    assert list(flt) == [C.VALID, C.MVCC_READ_CONFLICT]
    assert (CC, "other") not in batch.updates


def test_stale_version_and_absent_reads(net, validator):
    env_stale, _ = _tx(net, [net["p1"], net["p2"]], reads=[("existing", (0, 0))])
    env_absent_ok, _ = _tx(net, [net["p1"], net["p2"]], reads=[("ghost", None)])
    flt, _, _ = validator.validate(_block([env_stale, env_absent_ok]))
    assert list(flt) == [C.MVCC_READ_CONFLICT, C.VALID]


def test_duplicate_txid_in_block(net, validator):
    env, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")])
    flt, _, _ = validator.validate(_block([env, env]))
    assert list(flt) == [C.VALID, C.DUPLICATE_TXID]


def test_unknown_namespace_rejected(net, validator):
    env, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")], ns="nope")
    flt, _, _ = validator.validate(_block([env]))
    assert list(flt) == [C.INVALID_CHAINCODE]


def test_invalid_creator_msp(net, validator):
    outsider_org = cryptogen.generate_org("MarsMSP", "mars.example.com", users=1)
    outsider = cryptogen.signing_identity(outsider_org, "User1@mars.example.com")
    env, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")], signer=outsider)
    flt, _, _ = validator.validate(_block([env]))
    assert list(flt) == [C.BAD_CREATOR_SIGNATURE]


def test_config_tx_passes_through(net, validator):
    ch = pu.make_channel_header(common_pb2.HeaderType.CONFIG, CHANNEL)
    sh = pu.make_signature_header(net["client"].serialized, b"n")
    env = pu.sign_envelope(pu.make_payload(ch, sh, b""), net["client"])
    flt, _, _ = validator.validate(_block([env]))
    assert list(flt) == [C.VALID]


def test_garbage_envelope(net, validator):
    env = common_pb2.Envelope(payload=b"\x01\x02garbage")
    flt, _, _ = validator.validate(_block([env]))
    assert list(flt) == [C.BAD_PAYLOAD]


def _rwset_ranges(ranges, reads=(), writes=(), ns=CC):
    """rwset with range queries: ranges = [(start, end, [(key, ver)])]."""
    tx = TxRWSet()
    n = tx.ns_rwset(ns)
    for k, ver in reads:
        n.reads[k] = ver
    for k, v in writes:
        n.writes[k] = v
    for start, end, results in ranges:
        n.range_queries.append((start, end, list(results)))
    return tx.to_proto().SerializeToString()


def _tx_raw(net, endorsers, rwset_bytes, signer=None, ns=CC):
    signer = signer or net["client"]
    signed, tx_id, prop = txa.create_signed_proposal(signer, CHANNEL, ns, [b"invoke"])
    responses = [
        txa.create_proposal_response(prop, rwset_bytes, e, ns) for e in endorsers
    ]
    return txa.assemble_transaction(prop, responses, signer), tx_id


def test_repeated_endorsement_not_double_counted(net):
    """A client repeating one endorser's endorsement must not satisfy a
    2-of-same-org policy (round-1/2 bypass #2 regression)."""
    state = MemVersionedDB()
    policy = pol.from_dsl("OutOf(2, 'Org1MSP.member', 'Org1MSP.member')")
    prov = PolicyProvider({CC: NamespaceInfo(policy=policy)})
    v = BlockValidator(net["mgr"], prov, state)
    # same endorser twice → ONE signature toward the policy
    env_dup, _ = _tx(net, [net["p1"], net["p1"]], writes=[("k", b"v")])
    # two distinct Org1 members → satisfied
    env_ok, _ = _tx(net, [net["p1"], net["client"]], writes=[("k2", b"v")])
    flt, _, _ = v.validate(_block([env_dup, env_ok]))
    assert list(flt) == [C.ENDORSEMENT_POLICY_FAILURE, C.VALID]


def test_txid_binding(net, validator):
    """tx_id must equal sha256(nonce ‖ creator) — squatting rejected."""
    env, _ = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")])
    payload = pu.unmarshal(common_pb2.Payload, env.payload)
    ch = pu.unmarshal(common_pb2.ChannelHeader, payload.header.channel_header)
    ch.tx_id = "f" * 64  # squat someone else's id space
    payload.header.channel_header = ch.SerializeToString()
    env2 = pu.sign_envelope(payload, net["client"])
    flt, _, _ = validator.validate(_block([env2]))
    assert list(flt) == [C.BAD_PROPOSAL_TXID]


def test_committed_state_range_phantom(net, validator):
    """A key committed inside a recorded range but missing from its
    results is a phantom even with NO in-block writer (the reference
    merges committed state into the range re-check)."""
    # validator fixture state has CC/"existing"@(1,0)
    ok_results = [("existing", (1, 0))]
    env_ok, _ = _tx_raw(net, [net["p1"], net["p2"]],
                        _rwset_ranges([("a", "z", ok_results)]))
    env_phantom, _ = _tx_raw(net, [net["p1"], net["p2"]],
                             _rwset_ranges([("a", "z", [])]))  # missed it
    flt, _, _ = validator.validate(_block([env_ok, env_phantom]))
    assert list(flt) == [C.VALID, C.PHANTOM_READ_CONFLICT]


def test_unbounded_range_phantom_in_block(net, validator):
    """end_key == '' scans to the namespace end: an in-block write far
    beyond any bounded guess must still phantom the range."""
    env_w, _ = _tx(net, [net["p1"], net["p2"]], writes=[("zzzz", b"v")])
    env_rq, _ = _tx_raw(
        net, [net["p1"], net["p2"]],
        _rwset_ranges([("existing", "", [("existing", (1, 0))])]),
    )
    flt, _, _ = validator.validate(_block([env_w, env_rq]))
    assert list(flt) == [C.VALID, C.PHANTOM_READ_CONFLICT]


def test_range_results_stale_version(net, validator):
    """Recorded range results carry versions; staleness fails the tx."""
    env, _ = _tx_raw(net, [net["p1"], net["p2"]],
                     _rwset_ranges([("a", "z", [("existing", (0, 0))])]))
    flt, _, _ = validator.validate(_block([env]))
    assert list(flt) == [C.MVCC_READ_CONFLICT]


def test_config_tx_garbage_rejected(net, validator):
    """CONFIG envelopes are not rubber-stamped: unparseable config
    payloads and bad signatures are rejected."""
    ch = pu.make_channel_header(common_pb2.HeaderType.CONFIG, CHANNEL)
    sh = pu.make_signature_header(net["client"].serialized, b"n")
    # block 1, not 0: genesis blocks are the admin-verified trust
    # anchor and bypass config validation (kvledger bootstrap)
    payload = pu.make_payload(ch, sh, b"\x01\x02\x03garbage-not-a-config")
    env = pu.sign_envelope(payload, net["client"])
    flt, _, _ = validator.validate(_block([env], num=1))
    assert list(flt) == [C.BAD_PAYLOAD]

    env2 = pu.sign_envelope(pu.make_payload(ch, sh, b""), net["client"])
    env2.signature = bytes(len(env2.signature))
    flt, _, _ = validator.validate(_block([env2], num=1))
    assert list(flt) == [C.BAD_CREATOR_SIGNATURE]


def test_device_signed_endorsements_validate_on_device(net, validator):
    """ISSUE 13 acceptance: endorse-on-device, validate-on-device.

    Proposal responses ESCC-signed by the batched device sign lane
    (RFC 6979 nonces, fixed-base comb kernel, verify-after-sign armed)
    flow through the UNCHANGED BlockValidator commit path and produce
    the exact verdicts of the all-CPU OpenSSL signing path."""
    from fabric_tpu.peer import signlane

    batchers, providers = [], []
    for peer in (net["p1"], net["p2"]):
        d = signlane.private_scalar(peer)
        b = signlane.SignBatcher(
            signlane.device_sign_backend(d, verify_after=True),
            batch_max=16, wait_ms=5.0,
        ).start()
        batchers.append(b)
        providers.append(signlane.BatchedSigner(peer, b))
    try:
        # deterministic nonces: the SAME bytes sign to the SAME DER
        assert (providers[0].sign(b"replay") ==
                providers[0].sign(b"replay"))
        env_ok, _ = _tx(net, providers, writes=[("dk1", b"v1")])
        env_one, _ = _tx(net, [providers[0]], writes=[("dk2", b"v2")])
        flt, batch, history = validator.validate(
            _block([env_ok, env_one])
        )
        assert list(flt) == [C.VALID, C.ENDORSEMENT_POLICY_FAILURE]
        assert (CC, "dk1") in batch.updates
        # the all-CPU signing path agrees verdict for verdict
        env_ok_cpu, _ = _tx(
            net, [net["p1"], net["p2"]], writes=[("dk1", b"v1")]
        )
        env_one_cpu, _ = _tx(net, [net["p1"]], writes=[("dk2", b"v2")])
        flt_cpu, _, _ = validator.validate(
            _block([env_ok_cpu, env_one_cpu])
        )
        assert list(flt) == list(flt_cpu)
    finally:
        for b in batchers:
            b.stop()

"""Native C++ block pre-parser: bit-exact equivalence with the Python
parse path across a mixed adversarial block, and identical validator
verdicts with the fast path forced on and off."""

import hashlib

import numpy as np
import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.crypto import cryptogen
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.identity import sig_to_ints
from fabric_tpu.crypto.msp import MSPManager
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import MemVersionedDB, UpdateBatch
from fabric_tpu.native import blockparse as nbp
from fabric_tpu.peer import txassembly as txa
from fabric_tpu.peer.validator import BlockValidator, NamespaceInfo, PolicyProvider
from fabric_tpu.protos import common_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode
CHANNEL, CC = "natchan", "natcc"


@pytest.fixture(scope="module")
def net():
    org1 = cryptogen.generate_org("Org1MSP", "org1.example.com", peers=1, users=1)
    org2 = cryptogen.generate_org("Org2MSP", "org2.example.com", peers=1)
    return {
        "mgr": MSPManager({"Org1MSP": org1.msp(), "Org2MSP": org2.msp()}),
        "client": cryptogen.signing_identity(org1, "User1@org1.example.com"),
        "p1": cryptogen.signing_identity(org1, "peer0.org1.example.com"),
        "p2": cryptogen.signing_identity(org2, "peer0.org2.example.com"),
    }


def _tx(net, endorsers, writes=(), reads=(), tamper=None):
    signed, tx_id, prop = txa.create_signed_proposal(
        net["client"], CHANNEL, CC, [b"invoke"]
    )
    tx = TxRWSet()
    ns = tx.ns_rwset(CC)
    for k, ver in reads:
        ns.reads[k] = ver
    for k, v in writes:
        ns.writes[k] = v
    rw = tx.to_proto().SerializeToString()
    resps = [txa.create_proposal_response(prop, rw, e, CC) for e in endorsers]
    env = txa.assemble_transaction(prop, resps, net["client"])
    if tamper == "sig":
        env.signature = env.signature[:-3] + bytes(3)
    elif tamper == "endo":
        payload = pu.unmarshal(common_pb2.Payload, env.payload)
        t = pu.unmarshal(transaction_pb2.Transaction, payload.data)
        cap = pu.unmarshal(
            transaction_pb2.ChaincodeActionPayload, t.actions[0].payload
        )
        sig = bytearray(cap.action.endorsements[0].signature)
        sig[-2] ^= 0xFF
        cap.action.endorsements[0].signature = bytes(sig)
        t.actions[0].payload = cap.SerializeToString()
        payload.data = t.SerializeToString()
        env.payload = payload.SerializeToString()
        env.signature = net["client"].sign(env.payload)
    return env


def _mixed_block(net, num=2):
    envs = [
        _tx(net, [net["p1"], net["p2"]], writes=[("a", b"1")]),
        _tx(net, [net["p1"]], writes=[("b", b"2")]),           # under-endorsed
        _tx(net, [net["p1"], net["p2"]], tamper="sig"),        # bad creator sig
        _tx(net, [net["p1"], net["p2"]], tamper="endo"),       # bad endorsement
        _tx(net, [net["p1"], net["p2"]],
            reads=[("stale", (9, 9))], writes=[("c", b"3")]),  # mvcc conflict
        _tx(net, [net["p1"], net["p2"], net["p1"]],            # dup endorser
            writes=[("d", b"4")]),
    ]
    raw = [e.SerializeToString() for e in envs]
    raw.append(b"")                 # nil envelope
    raw.append(b"\x09garbage")      # malformed
    # pad with valid txs so the native fast path engages (>= 16)
    while len(raw) < 18:
        raw.append(_tx(net, [net["p1"], net["p2"]],
                       writes=[(f"p{len(raw)}", b"x")]).SerializeToString())
    blk = pu.new_block(num, b"prev")
    for r in raw:
        blk.data.data.append(r)
    return pu.finalize_block(blk)


def _validator(net):
    state = MemVersionedDB()
    seed = UpdateBatch()
    seed.put(CC, "stale", b"v", (1, 0))
    state.apply_updates(seed, (1, 0))
    policy = pol.from_dsl("AND('Org1MSP.peer', 'Org2MSP.peer')")
    return BlockValidator(
        net["mgr"], PolicyProvider({CC: NamespaceInfo(policy=policy)}), state
    )


def test_native_vs_python_identical_verdicts(net, monkeypatch):
    blk = _mixed_block(net)
    v1 = _validator(net)
    flt_fast, batch_fast, hist_fast = v1.validate(blk)

    # force the python path by disabling the native library
    import fabric_tpu.native as nat

    monkeypatch.setattr(nat, "_libs", {})
    monkeypatch.setattr(nat, "_lib_failed", {"blockparse"})
    v2 = _validator(net)
    flt_slow, batch_slow, hist_slow = v2.validate(blk)

    assert list(flt_fast) == list(flt_slow)
    assert flt_fast[0] == C.VALID
    assert flt_fast[1] == C.ENDORSEMENT_POLICY_FAILURE
    assert flt_fast[2] == C.BAD_CREATOR_SIGNATURE
    assert flt_fast[3] == C.ENDORSEMENT_POLICY_FAILURE
    assert flt_fast[4] == C.MVCC_READ_CONFLICT
    assert flt_fast[5] == C.VALID
    assert flt_fast[6] == C.NIL_ENVELOPE
    assert flt_fast[7] == C.BAD_PAYLOAD
    assert sorted(batch_fast.updates) == sorted(batch_slow.updates)
    assert hist_fast == hist_slow


def test_native_span_extraction(net):
    env = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")])
    raw = env.SerializeToString()
    out = nbp.parse_envelopes([raw])
    if out is None:
        pytest.skip("no native toolchain")
    assert out.ok[0] == 1
    e0 = pu.unmarshal(common_pb2.Envelope, raw)
    payload = pu.unmarshal(common_pb2.Payload, e0.payload)
    sh = pu.unmarshal(common_pb2.SignatureHeader, payload.header.signature_header)
    assert out.span(out.creator_span, 0) == sh.creator
    assert bytes(out.payload_digest[0]) == hashlib.sha256(e0.payload).digest()
    r, s = sig_to_ints(e0.signature)
    assert int.from_bytes(bytes(out.creator_r[0]), "big") == r
    assert int.from_bytes(bytes(out.creator_s[0]), "big") == s
    _, _, cap, prp, cca = pu.extract_action(e0)
    assert out.span(out.results_span, 0) == cca.results
    assert int(out.endo_count[0]) == 2


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def test_native_adversarial_lengths(net):
    """Crafted wire bytes with huge/overflowing varint lengths must not
    crash or mis-span — the `p + len > end` pointer form would wrap and
    accept them (ADVICE r3: overflow UB on attacker-controlled
    lengths).  Every case must come back ok=0 / harmless, byte-for-byte
    identical behavior to the Python decoder's rejection."""
    good = _tx(net, [net["p1"], net["p2"]], writes=[("k", b"v")])
    good_raw = good.SerializeToString()

    def fld(field: int, payload: bytes) -> bytes:
        return _varint(field << 3 | 2) + _varint(len(payload)) + payload

    huge = (1 << 64) - 9  # wraps p + len back below end
    cases = [
        # envelope payload-field length far beyond the buffer
        _varint(1 << 3 | 2) + _varint(huge) + b"x" * 32,
        # plausible envelope whose nested header length overflows
        fld(1, _varint(1 << 3 | 2) + _varint(huge) + b"y" * 8) + fld(2, b"sig"),
        # fixed32/fixed64 fields truncated at the buffer edge
        _varint(5 << 3 | 5) + b"\x01",
        _varint(5 << 3 | 1) + b"\x01\x02",
        # DER signature with a huge inner INTEGER length
        fld(1, fld(1, fld(1, b"\x08\x03") + fld(2, b"\x0a\x02hi")))
        + fld(2, b"\x30\x84\xff\xff\xff\xff\x02\x01\x01"),
        # truncated varint at end of buffer
        b"\xff\xff\xff",
        b"",
    ]
    out = nbp.parse_envelopes(cases + [good_raw])
    if out is None:
        pytest.skip("no native toolchain")
    for i in range(len(cases)):
        assert out.ok[i] == 0
    assert out.ok[len(cases)] == 1  # sane envelope still parses


def test_native_sha256_length_boundaries():
    """The native SHA-256 (SHA-NI fast path where available) must match
    hashlib across every padding boundary and multi-block length."""
    import ctypes
    import os

    import fabric_tpu.native as nat

    lib = nat.blockparse_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    out = (ctypes.c_uint8 * 32)()
    for n in [0, 1, 3, 54, 55, 56, 57, 63, 64, 65, 118, 119, 120, 121,
              127, 128, 129, 1000, 4096]:
        data = os.urandom(n)
        lib.sha256_test(ctypes.c_char_p(data), ctypes.c_int64(n), out)
        assert bytes(out) == hashlib.sha256(data).digest(), n

"""Bit-exactness tests for the TPU ECDSA P-256 kernel vs host oracles.

Oracles: fabric_tpu.crypto.ec_ref (pure-Python ints) and the
`cryptography` package (OpenSSL) for signature generation cross-checks.
"""

import hashlib

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from fabric_tpu.crypto import ec_ref
from fabric_tpu.ops import p256


def _rand_ints(rng, n, bound):
    return [int.from_bytes(rng.bytes(40), "big") % bound for _ in range(n)]


def test_limb_roundtrip(rng):
    xs = _rand_ints(rng, 8, 1 << 256)
    arr = p256.ints_to_limbs(xs)
    assert p256.limbs_to_ints(arr) == xs


@pytest.mark.parametrize("mod", [p256.MODP, p256.MODN])
def test_mont_mul_matches_int(rng, mod):
    n = 16
    a = _rand_ints(rng, n, mod.m)
    b = _rand_ints(rng, n, mod.m)
    am = [mod.to_mont_int(x) for x in a]
    bm = [mod.to_mont_int(x) for x in b]
    out = p256._mont_mul(
        jnp.asarray(p256.ints_to_limbs(am)), jnp.asarray(p256.ints_to_limbs(bm)), mod
    )
    got = p256.limbs_to_ints(out)
    want = [mod.to_mont_int(x * y % mod.m) for x, y in zip(a, b)]
    assert got == want


@pytest.mark.parametrize("mod", [p256.MODP, p256.MODN])
def test_add_sub_mod(rng, mod):
    n = 16
    a = _rand_ints(rng, n, mod.m)
    b = _rand_ints(rng, n, mod.m)
    da, db = jnp.asarray(p256.ints_to_limbs(a)), jnp.asarray(p256.ints_to_limbs(b))
    assert p256.limbs_to_ints(p256._add_mod(da, db, mod)) == [
        (x + y) % mod.m for x, y in zip(a, b)
    ]
    assert p256.limbs_to_ints(p256._sub_mod(da, db, mod)) == [
        (x - y) % mod.m for x, y in zip(a, b)
    ]


def test_mont_pow_inverse(rng):
    mod = p256.MODN
    a = _rand_ints(rng, 8, mod.m - 1)
    a = [x + 1 for x in a]
    am = jnp.asarray(p256.ints_to_limbs([mod.to_mont_int(x) for x in a]))
    inv = p256._mont_pow_const(am, p256.N - 2, mod)
    got = p256.limbs_to_ints(p256._from_mont(inv, mod))
    want = [pow(x, -1, mod.m) for x in a]
    assert got == want


def _to_affine(X, Y, Z):
    """Host-side Jacobian→affine for test comparison."""
    xs, ys, zs = (p256.limbs_to_ints(p256._from_mont(v, p256.MODP)) for v in (X, Y, Z))
    out = []
    for x, y, z in zip(xs, ys, zs):
        if z == 0:
            out.append(None)
        else:
            zi = pow(z, -1, p256.P)
            out.append(((x * zi * zi) % p256.P, (y * zi * zi * zi) % p256.P))
    return out


def _jacobian(points):
    """affine points (or None=∞) → Montgomery Jacobian device arrays."""
    mp = p256.MODP
    xs = [mp.to_mont_int(pt[0]) if pt else 0 for pt in points]
    ys = [mp.to_mont_int(pt[1]) if pt else 0 for pt in points]
    zs = [(1 << 256) % p256.P if pt else 0 for pt in points]
    return (
        jnp.asarray(p256.ints_to_limbs(xs)),
        jnp.asarray(p256.ints_to_limbs(ys)),
        jnp.asarray(p256.ints_to_limbs(zs)),
    )


def test_point_double_matches_ref(rng):
    pts = [ec_ref.pt_mul(k + 1, ec_ref.G) for k in _rand_ints(rng, 8, p256.N - 1)]
    pts.append(None)  # ∞
    X, Y, Z = _jacobian(pts)
    got = _to_affine(*p256._pt_double(X, Y, Z))
    want = [ec_ref.pt_double(pt) for pt in pts]
    assert got == want


def test_point_add_matches_ref(rng):
    ks = _rand_ints(rng, 6, p256.N - 1)
    p1 = [ec_ref.pt_mul(k + 1, ec_ref.G) for k in ks]
    p2 = [ec_ref.pt_mul(3 * k + 7, ec_ref.G) for k in ks]
    # edge cases: ∞+P, P+∞, P+P (doubling), P+(-P) (→∞)
    q = ec_ref.pt_mul(12345, ec_ref.G)
    qneg = (q[0], p256.P - q[1])
    p1 += [None, q, q, q]
    p2 += [q, None, q, qneg]
    X1, Y1, Z1 = _jacobian(p1)
    X2, Y2, Z2 = _jacobian(p2)
    got = _to_affine(*p256._pt_add(X1, Y1, Z1, X2, Y2, Z2))
    want = [ec_ref.pt_add(a, b) for a, b in zip(p1, p2)]
    assert got == want


def test_verify_batch_valid_and_corrupted(rng):
    keys = [ec_ref.SigningKey(d=_rand_ints(rng, 1, p256.N - 1)[0] + 1) for _ in range(4)]
    items, want = [], []
    for i in range(16):
        sk = keys[i % len(keys)]
        msg = b"payload-%d" % i
        e = ec_ref.digest_int(msg)
        r, s = sk.sign_digest(e)
        qx, qy = sk.public
        kind = i % 4
        if kind == 0:  # valid
            items.append((e, r, s, qx, qy))
            want.append(True)
        elif kind == 1:  # corrupted digest
            items.append((e ^ 1, r, s, qx, qy))
            want.append(False)
        elif kind == 2:  # corrupted s
            items.append((e, r, (s + 1) % p256.N, qx, qy))
            want.append(False)
        else:  # wrong key
            ox, oy = keys[(i + 1) % len(keys)].public
            items.append((e, r, s, ox, oy))
            want.append(False)
    got = p256.verify_host(items)
    assert got == want
    # agree with the pure-python oracle on every case
    for (e, r, s, qx, qy), g in zip(items, got):
        assert ec_ref.verify_digest((qx, qy), e, r, s) == g


def test_verify_rejects_high_s_and_degenerate(rng):
    sk = ec_ref.SigningKey.generate()
    e = ec_ref.digest_int(b"low-s test")
    r, s = sk.sign_digest(e)
    qx, qy = sk.public
    high_s = p256.N - s  # valid ECDSA but high-S: must be rejected
    items = [
        (e, r, s, qx, qy),
        (e, r, high_s, qx, qy),
        (e, 0, s, qx, qy),
        (e, r, 0, qx, qy),
        (e, p256.N, s, qx, qy),
        (e, r, s, qx, (qy + 1) % p256.P),  # off-curve key
    ]
    want = [True, False, False, False, False, False]
    # pad to the shared 16-wide bucket so the suite compiles one kernel
    items += [(e, r, s, qx, qy)] * (16 - len(items))
    want += [True] * (16 - len(want))
    assert p256.verify_host(items) == want


def test_verify_against_openssl_generated():
    """Cross-check with OpenSSL-generated (non-low-S-normalized) sigs."""
    from cryptography.hazmat.primitives.asymmetric import ec as cec
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.utils import decode_dss_signature

    items, want = [], []
    for i in range(16):
        key = cec.generate_private_key(cec.SECP256R1())
        pub = key.public_key().public_numbers()
        msg = b"openssl-%d" % i
        sig = key.sign(msg, cec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(sig)
        if s > p256.HALF_N:
            s = p256.N - s  # normalize as the reference signer does
        e = int.from_bytes(hashlib.sha256(msg).digest(), "big")
        items.append((e, r, s, pub.x, pub.y))
        want.append(True)
    assert p256.verify_host(items) == want

"""Block-commit span tracer tests (fabric_tpu.observe): span-tree
shape through a real depth-2 CommitPipeline run over the crypto-free
DeviceToyValidator, ring-buffer eviction, slow-block watchdog, Chrome
trace-event schema, cross-thread span adoption (host pool workers),
the /trace operations-server endpoint, the locked ops_metrics read
accessors, and the traceview text waterfall."""

import asyncio
import json
import logging
import os
import sys
import threading
import urllib.error
import urllib.request

import pytest

# scripts/ is not a package: make traceview importable for its tests
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                    "scripts")
)

from fabric_tpu import observe
from fabric_tpu.observe import Span, Tracer  # noqa: F401
from fabric_tpu.ledger.statedb import MemVersionedDB
from fabric_tpu.peer.pipeline import CommitPipeline


class _Clock:
    """Deterministic perf_counter stand-in for watchdog tests."""

    def __init__(self):
        self.t = 0.0

    def advance(self, dt: float) -> None:
        self.t += dt

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# core span mechanics


def test_span_nesting_and_thread_local_current():
    tr = Tracer(ring_blocks=4, slow_factor=0)
    root = tr.begin_block(1, channel="c")
    with tr.span("launch", parent=root) as sp:
        # the launch span became this thread's current: a parentless
        # retro add() lands under it (how validator._t plugs in)
        assert tr.current() is sp
        tr.add("state_fill", 0.0, 0.001)
        tr.event("note", detail="x")
    assert tr.current() is None
    tr.finish_block(root)
    (tree,) = tr.blocks()
    assert tree["block"] == 1
    (launch,) = tree["children"]
    assert launch["name"] == "launch"
    assert [c["name"] for c in launch["children"]] == ["state_fill"]
    assert launch["events"][0]["name"] == "note"


def test_disabled_tracer_is_noop():
    tr = Tracer(ring_blocks=0)
    assert not tr.enabled
    root = tr.begin_block(5)
    assert root is None
    with tr.span("x", parent=root) as sp:
        assert sp is None
        tr.add("y", 0.0, 1.0)  # parentless: dropped
        tr.event("z")
    tr.finish_block(root)
    assert tr.blocks() == [] and tr.slow_blocks() == []


def test_explicit_handle_crosses_executor_threads():
    """contextvars don't follow ThreadPoolExecutor tasks — the span
    handle passed + attach() is the supported crossing."""
    from concurrent.futures import ThreadPoolExecutor

    tr = Tracer(ring_blocks=4, slow_factor=0)
    root = tr.begin_block(2)

    def task():
        assert tr.current() is None  # nothing followed implicitly
        tok = tr.attach(root)
        try:
            with tr.span("worker-stage"):
                pass
        finally:
            tr.detach(tok)
        return threading.current_thread().name

    with ThreadPoolExecutor(1, thread_name_prefix="tw") as ex:
        worker_name = ex.submit(task).result()
    tr.finish_block(root)
    (child,) = root.children
    assert child.name == "worker-stage" and child.thread == worker_name


def test_ring_eviction():
    tr = Tracer(ring_blocks=2, slow_factor=0)
    for n in range(3):
        tr.finish_block(tr.begin_block(n))
    assert [b["block"] for b in tr.blocks()] == [1, 2]
    assert tr.block(0) is None
    assert tr.block(2)["block"] == 2


def test_watchdog_flags_slow_block(caplog):
    clk = _Clock()
    tr = Tracer(ring_blocks=32, slow_factor=3.0, clock=clk)
    for n in range(9):  # arm the median (8+ samples) at 10 ms/block
        root = tr.begin_block(n)
        clk.advance(0.010)
        tr.finish_block(root)
    assert tr.slow_blocks() == []
    with caplog.at_level(logging.WARNING, logger="fabric_tpu.observe"):
        root = tr.begin_block(9)
        with tr.span("finish", parent=root):
            clk.advance(0.500)  # 50x the trailing median
        tr.finish_block(root)
    (slow,) = tr.slow_blocks()
    assert slow["block"] == 9 and slow["attrs"]["slow"] is True
    assert any("slow block 9" in r.getMessage()
               and "finish" in r.getMessage()
               for r in caplog.records)
    # a watchdog of 0 never flags
    clk2 = _Clock()
    tr2 = Tracer(ring_blocks=32, slow_factor=0, clock=clk2)
    for n in range(12):
        root = tr2.begin_block(n)
        clk2.advance(10.0 if n == 11 else 0.01)
        tr2.finish_block(root)
    assert tr2.slow_blocks() == []


def test_configure_resize_keeps_recent_trees():
    tr = Tracer(ring_blocks=8, slow_factor=0)
    for n in range(5):
        tr.finish_block(tr.begin_block(n))
    tr.configure(ring_blocks=2)
    assert [b["block"] for b in tr.blocks()] == [3, 4]
    tr.configure(ring_blocks=0)
    assert not tr.enabled and tr.begin_block(9) is None


# ---------------------------------------------------------------------------
# the real thing: a depth-2 pipelined run over the device toy validator


@pytest.fixture(scope="module")
def toy_run():
    """One depth-2 CommitPipeline run (5 blocks, real device verifies,
    bad-sig lanes) captured by a fresh tracer."""
    from test_multidevice import DeviceToyValidator, _device_stream
    from fabric_tpu.crypto import ec_ref

    tr = Tracer(ring_blocks=16, slow_factor=0)
    key = ec_ref.SigningKey.generate()
    blocks = _device_stream(key, n_blocks=5, n_tx=8)
    state = MemVersionedDB()
    v = DeviceToyValidator(state)
    filters = []

    def commit_fn(res):
        state.apply_updates(res.batch, (res.block.header.number, 0))
        filters.append((res.block.header.number, list(res.tx_filter)))

    with CommitPipeline(v, commit_fn, depth=2, tracer=tr) as pipe:
        for b in blocks:
            pipe.submit(b)
    return tr, sorted(filters)


def test_pipeline_span_tree_shape(toy_run):
    """Every committed block leaves one finalized tree whose
    prefetch/launch/finish/commit children are complete, nested inside
    the root's window, and placed on the right threads."""
    tr, filters = toy_run
    assert len(filters) == 5  # nothing lost to tracing
    roots = list(tr._ring)
    assert [r.attrs["block"] for r in roots] == [0, 1, 2, 3, 4]
    for r in roots:
        names = [c.name for c in r.children]
        for want in ("prefetch", "prefetch_wait", "launch", "finish",
                     "commit_wait", "commit"):
            assert names.count(want) == 1, (r.attrs, want, names)
        for c in r.children:
            assert c.t1 is not None, (r.attrs, c.name)
            assert c.t0 >= r.t0 - 1e-6 and c.t1 <= r.t1 + 1e-6
        by = {c.name: c for c in r.children}
        # prefetch ran on the prefetch thread; pipelined commits on the
        # committer thread (the tail flushes inline on the caller)
        assert by["prefetch"].thread.startswith("fabtpu-prefetch")
        if "tail" not in r.attrs:
            assert by["commit"].thread.startswith("fabtpu-committer")
        # stage order within the block: launch → finish → commit
        assert by["launch"].t0 <= by["finish"].t0 <= by["commit"].t0
    # the tail block is annotated as such
    assert roots[-1].attrs.get("tail") is True


def test_pipeline_overlap_visible(toy_run):
    """The depth-2 win on the timeline: block k+1's prefetch begins
    while block k is still in flight (strictly before k's commit
    completes) — impossible under depth-1, where root k finalizes
    before submit(k+1) runs."""
    tr, _ = toy_run
    roots = list(tr._ring)
    for prev, cur in zip(roots, roots[1:]):
        prefetch = next(c for c in cur.children if c.name == "prefetch")
        commit = next(c for c in prev.children if c.name == "commit")
        assert prefetch.t0 < commit.t1, (prev.attrs, cur.attrs)
        assert prefetch.t0 < prev.t1


def test_chrome_export_schema_and_overlap(toy_run, tmp_path):
    """The export is Chrome-trace-event JSON Perfetto can load: X/i
    events with ts/dur/pid/tid + thread_name metadata rows, block
    numbers in args — and the prefetch(k+1)-before-commit(k)-ends
    overlap is readable straight off the event timestamps."""
    tr, _ = toy_run
    path = tmp_path / "trace.json"
    tr.export_chrome(str(path))
    data = json.loads(path.read_text())
    events = data["traceEvents"]
    meta = [e for e in events if e.get("ph") == "M"]
    names = {e["args"]["name"] for e in meta}
    assert any(n.startswith("fabtpu-prefetch") for n in names)
    assert any(n.startswith("fabtpu-committer") for n in names)
    xs = [e for e in events if e["ph"] == "X"]
    assert xs, "no complete events"
    for e in xs:
        for k in ("name", "ts", "dur", "pid", "tid", "args"):
            assert k in e, (k, e)
    by_block: dict = {}
    for e in xs:
        by_block.setdefault(e["args"]["block"], []).append(e)
    assert sorted(by_block) == [0, 1, 2, 3, 4]
    for k in range(4):
        commit_k = next(e for e in by_block[k] if e["name"] == "commit")
        pre_k1 = next(e for e in by_block[k + 1]
                      if e["name"] == "prefetch")
        assert pre_k1["ts"] < commit_k["ts"] + commit_k["dur"]

    # the text waterfall renders the same file without a browser
    import traceview

    text = traceview.render(data)
    assert "block 3" in text and "prefetch" in text and "#" in text
    one = traceview.render(data, block=2)
    assert "block 2" in one and "block 3" not in one


def test_traceview_renders_trace_dump(toy_run):
    import traceview

    tr, _ = toy_run
    dump = {
        "slow_blocks": tr.slow_blocks(),
        "recent_blocks": tr.blocks(4),
    }
    text = traceview.render(dump)
    assert "block 4" in text and "commit" in text
    single = traceview.render(tr.block(3))
    assert single.startswith("block 3") and "finish" in single


# ---------------------------------------------------------------------------
# host pool workers adopt the submitting thread's span


def test_hostpool_worker_spans_cross_thread():
    from fabric_tpu.parallel.hostpool import HostStagePool

    tr = observe.global_tracer()
    root = tr.begin_block(991)
    assert root is not None  # global default is always-on
    tok = tr.attach(root)
    try:
        with HostStagePool(2) as pool:
            assert pool.map(lambda x: x * 2, [1, 2, 3],
                            stage="unit") == [2, 4, 6]
    finally:
        tr.detach(tok)
    tasks = [c for c in root.children if c.name == "unit"]
    assert len(tasks) == 3
    assert all(c.thread.startswith("fabtpu-hoststage") for c in tasks)
    assert all("worker" in c.attrs for c in tasks)


# ---------------------------------------------------------------------------
# /trace endpoint round-trip


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, json.loads(r.read())


def test_trace_endpoint_roundtrip(toy_run):
    from fabric_tpu.ops_metrics import Registry
    from fabric_tpu.opsserver import HealthRegistry, OperationsServer

    tr, _ = toy_run
    reg = Registry()
    reg.histogram("validator_stage_seconds").observe(0.01, stage="finish")

    async def scenario():
        srv = await OperationsServer(
            port=0, registry=reg, health=HealthRegistry(), tracer=tr
        ).start()
        try:
            loop = asyncio.get_event_loop()
            st, idx = await loop.run_in_executor(
                None, _get, srv.port, "/trace"
            )
            assert st == 200 and idx["enabled"]
            assert idx["blocks_in_ring"] == [0, 1, 2, 3, 4]
            assert [b["block"] for b in idx["recent_blocks"]] == [1, 2, 3, 4]
            # the summary reads histograms through the LOCKED snapshot
            summ = idx["summary"]["validator_stage_seconds"]
            assert summ["stage=finish"]["count"] == 1
            # the deep-pipelining acceptance number rides the index
            cov = idx["pipeline_overlap_coverage"]
            assert cov["window"] == 2
            assert set(cov) >= {"blocks_measured", "mean", "p50", "min"}
            # and ?overlap_window= adjusts the neighbor window
            st, idx1 = await loop.run_in_executor(
                None, _get, srv.port, "/trace?overlap_window=1"
            )
            assert idx1["pipeline_overlap_coverage"]["window"] == 1
            st, tree = await loop.run_in_executor(
                None, _get, srv.port, "/trace?block=3"
            )
            assert st == 200 and tree["block"] == 3
            assert {c["name"] for c in tree["children"]} >= {
                "prefetch", "launch", "finish", "commit"
            }
            try:
                await loop.run_in_executor(
                    None, _get, srv.port, "/trace?block=77"
                )
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            await srv.stop()

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(scenario(), 30))
    finally:
        loop.close()


# ---------------------------------------------------------------------------
# ops_metrics locked read accessors


def test_metrics_locked_accessors():
    from fabric_tpu.ops_metrics import Registry

    reg = Registry()
    c = reg.counter("c_total")
    c.add(2, channel="a")
    c.add(3, channel="a")
    assert c.value(channel="a") == 5.0
    assert c.snapshot() == {(("channel", "a"),): 5.0}
    g = reg.gauge("g")
    g.set(7, channel="a")
    assert g.value(channel="a") == 7.0
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0, float("inf")))
    assert h.value(stage="x") is None
    h.observe(0.05, stage="x")
    h.observe(0.5, stage="x")
    snap = h.value(stage="x")
    assert snap["count"] == 2 and snap["counts"] == [1, 2, 2]
    assert abs(snap["sum"] - 0.55) < 1e-9
    assert reg.metric("h_seconds") is h and reg.metric("nope") is None

    # render still emits the same exposition format off the snapshots
    text = reg.render()
    assert 'c_total{channel="a"} 5.0' in text
    assert 'h_seconds_bucket{stage="x",le="0.1"} 1' in text
    assert 'h_seconds_count{stage="x"} 2' in text


def test_metrics_concurrent_read_write_smoke():
    """Readers (render / value / snapshot) race writers without
    torn/failed reads — the bug was unlocked reads of ``_values``."""
    from fabric_tpu.ops_metrics import Registry

    reg = Registry()
    c = reg.counter("rw_total")
    h = reg.histogram("rw_seconds")
    stop = threading.Event()
    errors = []

    def writer(i):
        try:
            n = 0
            while not stop.is_set():
                # fresh label keys force dict growth mid-read
                c.add(1, worker=str(i), n=str(n % 97))
                h.observe(0.001, worker=str(i), n=str(n % 97))
                n += 1
        except Exception as e:  # surface, don't swallow
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            reg.render()
            c.value(worker="0", n="1")
            h.snapshot()
    except Exception as e:
        errors.append(e)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors


# ---------------------------------------------------------------------------
# namespaced rings + multi-process export (ISSUE 9)


def test_namespaced_rings_are_independent():
    tr = Tracer(ring_blocks=2, slow_factor=0)
    for n in range(3):
        tr.finish_block(tr.begin_block(n, channel="c"))
    for n in range(5):
        tr.finish_block(tr.begin_block(n, ns="sidecar", channel="s"))
    # the sidecar storm evicted only its own ring
    assert [b["block"] for b in tr.blocks()] == [1, 2]
    assert [b["block"] for b in tr.blocks(ns="sidecar")] == [3, 4]
    assert tr.block(1)["attrs"]["channel"] == "c"
    assert tr.block(4, ns="sidecar")["attrs"]["ns"] == "sidecar"
    assert tr.block(4) is None  # no cross-namespace shadowing
    assert tr.namespaces() == {"": 2, "sidecar": 2}
    # a resize keeps both rings (truncated)
    tr.configure(ring_blocks=1)
    assert tr.namespaces() == {"": 1, "sidecar": 1}


def test_watchdog_medians_are_per_namespace(caplog):
    """Sub-ms sidecar requests must not drag the block-commit median
    down (which would flag every normal block as slow), and vice
    versa."""
    clk = _Clock()
    tr = Tracer(ring_blocks=64, slow_factor=3.0, clock=clk)
    for n in range(10):  # blocks at a steady 100 ms
        root = tr.begin_block(n)
        clk.advance(0.100)
        tr.finish_block(root)
    for n in range(20):  # requests at a steady 1 ms, separate ns
        root = tr.begin_block(n, ns="sidecar")
        clk.advance(0.001)
        tr.finish_block(root)
    with caplog.at_level(logging.WARNING, logger="fabric_tpu.observe"):
        root = tr.begin_block(99)  # another normal 100 ms block
        clk.advance(0.100)
        tr.finish_block(root)
    # against the BLOCK median (100 ms) this is not slow; against a
    # polluted mixed median (~1 ms) it would have been 100x
    assert tr.slow_blocks() == []


def test_root_propagates_to_leaf_spans():
    tr = Tracer(ring_blocks=4, slow_factor=0)
    root = tr.begin_block(5)
    assert root.root is root
    with tr.span("launch", parent=root) as launch:
        assert launch.root is root
        with tr.span("inner") as inner:
            assert inner.root is root
        tr.add("retro", 0.0, 0.001)
    assert root.children[0].children[0].root is root
    tr.finish_block(root)


def test_span_from_dict_roundtrip_with_offset():
    from fabric_tpu.observe import span_from_dict

    tr = Tracer(ring_blocks=4, slow_factor=0)
    root = tr.begin_block(3, channel="x")
    with tr.span("dispatch", parent=root, n=2):
        pass
    tr.event("note", parent=root)
    tr.finish_block(root)
    d = root.to_dict(0.0)  # absolute times, the wire form
    sp = span_from_dict(d, offset_s=10.0, proc="sidecar")
    assert sp.proc == "sidecar" and sp.children[0].proc == "sidecar"
    assert sp.t0 == pytest.approx(root.t0 - 10.0, abs=1e-3)
    assert sp.children[0].name == "dispatch"
    assert sp.children[0].attrs == {"n": 2}
    assert sp.events[0][0] == "note"
    assert sp.children[0].t0 == pytest.approx(
        root.children[0].t0 - 10.0, abs=1e-3
    )


def test_traceview_renders_multiprocess_dump():
    """Satellite: merged peer+sidecar trees render with per-process
    labels and the clock-offset annotation, both input forms."""
    import traceview
    from fabric_tpu.observe import span_from_dict

    tr = Tracer(ring_blocks=4, slow_factor=0)
    root = tr.begin_block(11, channel="chanA")
    with tr.span("sig_prepare_launch", parent=root):
        pass
    # a stitched remote subtree, the client shape
    remote_src = Tracer(ring_blocks=4, slow_factor=0)
    rroot = remote_src.begin_block(1, ns="sidecar",
                                   channel="sidecar:chanA")
    remote_src.add("queue_wait", rroot.t0, rroot.t0 + 0.001,
                   parent=rroot)
    remote_src.add("dispatch", rroot.t0 + 0.001, rroot.t0 + 0.003,
                   parent=rroot)
    remote_src.end(rroot)
    sp = span_from_dict(rroot.to_dict(0.0), offset_s=-0.002,
                        proc="sidecar")
    sp.name = "sidecar_request"
    sp.attrs["clock_offset_ms"] = -2.0
    sp.attrs["rtt_ms"] = 0.4
    root.children.append(sp)
    tr.finish_block(root)

    # /trace-dump form
    text = traceview.render(tr.block(11))
    assert "sidecar:" in text            # per-process row label
    assert "clock offset -2.000 ms" in text
    assert "queue_wait" in text and "dispatch" in text

    # Chrome form: distinct pid + process_name metadata
    data = {"traceEvents": tr.chrome_events()}
    text = traceview.render(data, block=11)
    assert "sidecar:" in text
    assert "clock offset -2.000 ms" in text
    assert "sig_prepare_launch" in text


# ---------------------------------------------------------------------------
# overlap-coverage analyzer (observe/overlap.py)


def _cov_rows():
    """Hand-built timeline: block 1's device_wait [10.00, 10.10);
    block 0's commit covers [10.00, 10.05), block 3's prefetch (a
    DISTANCE-2 neighbor) [10.05, 10.08) — union coverage 0.8 at
    window 2, 0.5 at window 1.  Block 6 sits outside every window.
    Non-host spans (commit_wait) and SAME-block host work must not
    count."""
    return [
        (0, "commit", 10.00, 10.05),
        (1, "device_wait", 10.00, 10.10),
        (1, "host_parse", 10.00, 10.10),    # own block: never counts
        (3, "prefetch", 10.05, 10.08),
        (3, "commit_wait", 10.00, 10.20),   # pure wait: never counts
        (6, "device_wait", 20.00, 20.10),   # no in-window neighbor
    ]


def test_overlap_coverage_math():
    from fabric_tpu.observe import overlap

    cov = overlap.coverage_from_spans(_cov_rows(), window=2)
    assert cov["window"] == 2
    per = {b["block"]: b for b in cov["per_block"]}
    assert per[1]["coverage"] == pytest.approx(0.8)
    assert per[1]["device_wait_ms"] == pytest.approx(100.0)
    assert per[1]["covered_ms"] == pytest.approx(80.0)
    # block 6 has NO in-window neighbor at all → skipped entirely
    assert 6 not in per
    assert cov["blocks_measured"] == 1
    assert cov["min"] == pytest.approx(0.8)

    # window 1: block 0's commit is the only neighbor of block 1 —
    # block 3's prefetch falls out of the window
    cov1 = overlap.coverage_from_spans(_cov_rows(), window=1)
    per1 = {b["block"]: b for b in cov1["per_block"]}
    assert per1[1]["coverage"] == pytest.approx(0.5)
    assert cov1["blocks_measured"] == 1


def test_overlap_coverage_union_no_double_count():
    """Nested/overlapping host spans union — a container span plus
    its children must not count twice."""
    from fabric_tpu.observe import overlap

    rows = [
        (1, "device_wait", 0.0, 1.0),
        (0, "commit", 0.0, 0.6),
        (0, "ledger_commit", 0.0, 0.5),   # nested inside commit
        (0, "fsync", 0.5, 0.6),           # ditto
    ]
    cov = overlap.coverage_from_spans(rows, window=1)
    assert cov["per_block"][0]["coverage"] == pytest.approx(0.6)


def _device_wait_tracer():
    """A tracer whose trees carry device_wait spans with a known
    overlap shape — 3 blocks, each block's device_wait half-covered by
    its predecessor's commit."""
    clk = _Clock()
    tr = Tracer(ring_blocks=8, slow_factor=0, clock=clk)
    for n in range(3):
        base = 10.0 * n
        root = tr.begin_block(n)
        root.t0 = base
        tr.add("launch", base, base + 1.0, parent=root)
        tr.add("device_wait", base + 1.0, base + 5.0, parent=root)
        if n + 1 < 3:
            # predecessor's commit overlaps HALF the successor's wait
            tr.add("commit", base + 11.0, base + 13.0, parent=root)
        root.t1 = base + 9.0
        tr.finish_block(root)
    return tr


def test_overlap_coverage_all_three_input_forms():
    """The live-roots, /trace-dump (t0_s anchored), and Chrome-event
    forms of the SAME flight recorder must agree."""
    from fabric_tpu.observe import overlap

    tr = _device_wait_tracer()
    live = overlap.coverage_from_roots(tr.recent_roots(), window=2)
    dump = overlap.coverage_from_trace_dump(
        {"recent_blocks": tr.blocks(), "slow_blocks": []}, window=2
    )
    chrome = overlap.coverage_from_spans(
        overlap.spans_from_chrome(tr.chrome_events()), window=2
    )
    assert live["blocks_measured"] == dump["blocks_measured"] \
        == chrome["blocks_measured"] > 0
    # block 1's wait [11, 15] is covered by block 0's commit [11, 13]
    per = {b["block"]: b for b in live["per_block"]}
    assert per[1]["coverage"] == pytest.approx(0.5)
    for a, b in ((live, dump), (live, chrome)):
        for x, y in zip(a["per_block"], b["per_block"]):
            assert x["block"] == y["block"]
            assert x["coverage"] == pytest.approx(y["coverage"],
                                                  abs=1e-3)

    # a dump with no t0_s anchors (pre-upgrade capture) returns None
    old = [{k: v for k, v in b.items() if k != "t0_s"}
           for b in tr.blocks()]
    assert overlap.coverage_from_trace_dump(
        {"recent_blocks": old, "slow_blocks": []}
    ) is None


def test_traceview_coverage_table():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "traceview", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "traceview.py",
        ),
    )
    traceview = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(traceview)

    tr = _device_wait_tracer()
    dump = {"recent_blocks": tr.blocks(), "slow_blocks": [],
            "blocks_in_ring": [b["block"] for b in tr.blocks()]}
    text = traceview.render_coverage(dump, window=2)
    assert "pipeline overlap coverage" in text
    assert "device_wait" in text
    chrome = {"traceEvents": tr.chrome_events()}
    text2 = traceview.render_coverage(chrome, window=2)
    assert "pipeline overlap coverage" in text2
